"""Array-contract abstract interpretation (the OSL18xx engine).

An abstract interpreter over :mod:`analysis.dataflow`'s per-function CFGs
computing a **(dtype, rank, symbolic-axis)** lattice for numpy/jax values,
checked against the contract registry in ``encoding/dtypes.py``
(``ARENA_CONTRACTS``/``STATE_CONTRACTS``/``KERNEL_ARG_CONTRACTS``).

Abstract value
    ``ArrayVal(dtype, axes, creations, widenings)``. ``dtype`` is one of
    the ABI width tags (``bool/u8/i32/i64/f32/f64``) or ``None`` =
    unknown; ``axes`` is a tuple of canonical axis names (``"?"`` =
    unknown axis) or ``None`` = unknown rank. ``creations`` records
    array-creation sites (``np.zeros`` without a policy dtype, explicit
    non-policy dtypes); ``widenings`` records promotion events (a binop /
    ``np.where`` / int-division producing a wider dtype than an operand).
    Both event sets are capped at :data:`_EVENT_CAP` entries, keeping the
    lattice finite.

Lattice / termination
    Join is pointwise: dtypes and axes join to themselves when equal and
    to unknown otherwise (a two-level lattice over a finite tag set);
    event sets join by capped union over the finite universe of source
    sites in one function. Every chain therefore stabilizes and the
    generic ``forward_analyze`` worklist terminates. Interprocedural
    summaries (joined return value + parameter-to-boundary flows) are
    iterated to a fixpoint exactly like ``TaintEngine`` — a bounded
    number of rounds, then one collect pass that emits findings.

Promotion rules
    NumPy NEP-50 semantics by default: python scalars are weak (an int
    scalar never widens an array; a float scalar widens integer arrays to
    f64), ``i32 × f32 → f64``, int true-division → f64, integer
    ``sum``/``prod`` accumulate at i64. Files that import ``jax.numpy``
    use JAX's lattice instead (int × float → the float's width, no
    value-free f64 jumps) so jit kernels are not flagged with numpy-only
    promotions. The tables are verified against ``np.result_type`` /
    ``jnp.promote_types`` by tests/test_analysis_arrays.py.

Checked boundaries
    ``EncodedCluster(...)``/``ScanState(...)``/``NodeArenas(...)``
    constructor bindings (keyword, positional, and ``**dict``),
    ``._replace(...)`` on struct-typed values, and calls into the kernel
    entries declared in ``KERNEL_ARG_CONTRACTS`` (trailing-axis match, so
    batched/vmapped leading axes are allowed). Findings:

    - **OSL1801** off-policy creation: an array created without (or with
      a non-policy) dtype reaches a contract boundary of a different
      width — anchored at the creation site.
    - **OSL1802** silent upcast: a promotion event on a path reaching a
      boundary whose contract is narrower than the promoted dtype —
      anchored at the promotion site, interprocedural.
    - **OSL1803** shape-contract violation: rank or named-axis-order
      mismatch against the declared contract — anchored at the binding.

The checker only acts on *known* facts — unknown dtypes/axes never fire —
so precision is favored over recall (zero-suppression sweep).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field, replace
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple, Union

from .core import FileContext, ProjectContext
from .dataflow import Atom, DataflowEngine, FnUnit, forward_analyze, get_engine

# width tags, narrowest-first within each kind
_INT_LADDER = ("bool", "u8", "i32", "i64")
_FLOAT_LADDER = ("f32", "f64")
TAGS = _INT_LADDER + _FLOAT_LADDER

_EVENT_CAP = 4
_MAX_ROUNDS = 4

#: modules analyzed / reported on — the arena pipeline
_SCOPE = ("encoding/", "engine/", "parallel/", "native/", "ops/")

_NP_NAME_TO_TAG = {
    "bool": "bool", "bool_": "bool", "uint8": "u8", "int32": "i32",
    "int64": "i64", "float32": "f32", "float64": "f64", "double": "f64",
    # non-policy widths that a mutation / drift may introduce: keep them
    # distinguishable so the mismatch message names the real width
    "int8": "i8", "int16": "i16", "uint16": "u16", "uint32": "u32",
    "uint64": "u64", "float16": "f16", "bfloat16": "bf16",
}

_CREATORS = {
    "zeros", "ones", "empty", "full", "arange", "array", "asarray",
    "ascontiguousarray", "frombuffer", "fromiter", "linspace",
}
_LIKE_CREATORS = {"zeros_like", "ones_like", "empty_like", "full_like"}
_ARRAY_BASES = {"np", "numpy", "jnp"}
_BIN_FUNCS = {"maximum", "minimum", "fmax", "fmin", "add", "subtract",
              "multiply", "divide", "true_divide", "power", "hypot"}
_FLOAT_UFUNCS = {"log", "log2", "log10", "log1p", "exp", "expm1", "sqrt",
                 "sin", "cos", "tan", "tanh", "arctan", "arcsin", "arccos"}
_INT_ACCUM_REDUCERS = {"sum", "prod", "cumsum", "cumprod"}
_KEEP_REDUCERS = {"max", "min", "amax", "amin"}
_PASSTHROUGH_CALLS = {"copy", "device_put", "to_device", "block_until_ready",
                      "broadcast_to"}
_STRUCT_NAMES = ("EncodedCluster", "ScanState", "NodeArenas")


def npname_to_tag(name: str) -> Optional[str]:
    if name in _NP_NAME_TO_TAG:
        return _NP_NAME_TO_TAG[name]
    short = (name.replace("float", "f").replace("uint", "u")
             .replace("int", "i"))
    return short if short != name or name.startswith(("f", "u", "i")) else None


def _is_float(tag: str) -> bool:
    return tag in ("f16", "bf16", "f32", "f64")


def _rank_of(tag: str, ladder: Sequence[str]) -> int:
    try:
        return ladder.index(tag)
    except ValueError:
        return len(ladder)  # unknown exotic width: treat as widest


def promote(a: str, b: str, jax_sem: bool) -> str:
    """Promotion of two known *array* dtype tags."""
    if a == b:
        return a
    fa, fb = _is_float(a), _is_float(b)
    if fa and fb:
        return a if _rank_of(a, _FLOAT_LADDER) >= _rank_of(b, _FLOAT_LADDER) else b
    if not fa and not fb:
        return a if _rank_of(a, _INT_LADDER) >= _rank_of(b, _INT_LADDER) else b
    flt, other = (a, b) if fa else (b, a)
    if jax_sem:
        return flt  # JAX: int x float -> the float's width
    # NumPy: i32/i64 x f32 -> f64; bool/u8 x f32 -> f32
    if flt == "f32" and other in ("i32", "i64", "u32", "u64", "i16", "u16"):
        return "f64"
    return flt


def promote_weak(tag: str, scalar_kind: str, jax_sem: bool) -> str:
    """Array tag x python scalar (NEP-50 weak promotion)."""
    if scalar_kind == "float" and not _is_float(tag):
        return "f32" if jax_sem else "f64"
    return tag


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------

Event = Tuple[str, int, int, str]  # (path, line, col, description)


def _cap(events: Iterable[Event]) -> Tuple[Event, ...]:
    return tuple(sorted(set(events))[:_EVENT_CAP])


@dataclass(frozen=True)
class ArrayVal:
    """One abstract numpy/jax value."""

    dtype: Optional[str] = None
    axes: Optional[Tuple[str, ...]] = None
    creations: Tuple[Event, ...] = ()
    widenings: Tuple[Event, ...] = ()
    param_src: int = -1  # parameter index when the raw param, else -1


@dataclass(frozen=True)
class StructVal:
    """A value known to be one of the contract-carrying NamedTuples."""

    struct: str  # EncodedCluster | ScanState | NodeArenas


@dataclass(frozen=True)
class DictVal:
    """A dict literal with constant string keys and array-ish values."""

    items: Tuple[Tuple[str, ArrayVal], ...]


@dataclass(frozen=True)
class Scalar:
    """A weak python scalar ('int' | 'float' | 'bool')."""

    kind: str


Val = Union[ArrayVal, StructVal, DictVal, Scalar]


def join_vals(a: Optional[Val], b: Optional[Val]) -> Optional[Val]:
    if a == b:
        return a
    if isinstance(a, ArrayVal) and isinstance(b, ArrayVal):
        return ArrayVal(
            dtype=a.dtype if a.dtype == b.dtype else None,
            axes=a.axes if a.axes == b.axes else None,
            creations=_cap(a.creations + b.creations),
            widenings=_cap(a.widenings + b.widenings),
            param_src=a.param_src if a.param_src == b.param_src else -1,
        )
    if isinstance(a, DictVal) and isinstance(b, DictVal):
        da, db = dict(a.items), dict(b.items)
        keys = sorted(set(da) & set(db))
        joined = []
        for k in keys:
            j = join_vals(da[k], db[k])
            if isinstance(j, ArrayVal):
                joined.append((k, j))
        return DictVal(tuple(joined))
    return None


State = Dict[str, Val]


def _join_states(a: State, b: State) -> State:
    if a == b:
        return a
    out: State = {}
    for k in set(a) | set(b):
        j = join_vals(a.get(k), b.get(k)) if (k in a and k in b) else None
        if j is not None:
            out[k] = j
    return out


# ---------------------------------------------------------------------------
# contract source
# ---------------------------------------------------------------------------

_CONTRACT_BLOCKS = ("ARENA_CONTRACTS", "STATE_CONTRACTS")


@dataclass
class Contracts:
    """The registry from ``encoding/dtypes.py`` — parsed from the linted
    source when the file is in the project (so corpus fixtures and policy
    edits are honored), imported live otherwise."""

    policies: Dict[str, str] = field(default_factory=dict)  # name -> tag
    arena: Dict[str, Tuple[str, Tuple[str, ...]]] = field(default_factory=dict)
    state: Dict[str, Tuple[str, Tuple[str, ...]]] = field(default_factory=dict)
    kernel_args: Dict[str, Dict[str, Tuple[str, Tuple[str, ...]]]] = field(
        default_factory=dict
    )
    struct_params: Dict[str, str] = field(default_factory=dict)
    axis_aliases: Dict[str, str] = field(default_factory=dict)
    buffer_aliases: Dict[str, str] = field(default_factory=dict)
    entry_lines: Dict[str, int] = field(default_factory=dict)  # field -> line
    source_path: Optional[str] = None
    problems: List[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        self._vocab: Dict[str, str] = {}

    def _build_vocab(self) -> None:
        for table in (self.arena, self.state, *self.kernel_args.values()):
            for _tag, axes in table.values():
                for ax in axes:
                    self._vocab[ax.lower()] = ax
        for alias, canon in self.axis_aliases.items():
            self._vocab[alias.lower()] = self._vocab.get(canon.lower(), canon)

    def norm_axis(self, name: str) -> str:
        """Canonical axis for a rendered shape symbol, '?' when unknown."""
        return self._vocab.get(name.lower(), "?")

    def struct_fields(self, struct: str) -> Dict[str, Tuple[str, Tuple[str, ...]]]:
        if struct == "EncodedCluster":
            return self.arena
        if struct == "ScanState":
            return self.state
        if struct == "NodeArenas":
            # raw arenas share names (and contracts) with the assembled
            # cluster; plus the host-side gpu device-count column
            sub = {k: v for k, v in self.arena.items() if k.startswith("node_")
                   or k in ("alloc", "unschedulable", "taint_key", "taint_val",
                            "taint_effect", "label_val", "label_num")}
            sub["node_gpu_count"] = ("INT_DTYPE", ("N",))
            return sub
        return {}

    def resolve(self, entry: Tuple[str, Tuple[str, ...]]) -> Tuple[Optional[str], Tuple[str, ...], str]:
        """(tag, axes, policy-name); tag None when the policy is unknown."""
        policy, axes = entry
        return self.policies.get(policy), axes, policy


def _parse_dtypes_module(tree: ast.Module, path: str) -> Contracts:
    out = Contracts(source_path=path)
    for node in tree.body:
        target: Optional[str] = None
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            target, value = node.target.id, node.value
        if target is None or value is None:
            continue
        if target.endswith("_DTYPE"):
            leaf = value.attr if isinstance(value, ast.Attribute) else (
                value.id if isinstance(value, ast.Name) else None
            )
            tag = npname_to_tag(leaf) if leaf else None
            if tag is None:
                out.problems.append(
                    f"policy constant {target} does not resolve to a numpy dtype"
                )
            else:
                out.policies[target] = tag
            continue
        if target in _CONTRACT_BLOCKS + (
            "KERNEL_ARG_CONTRACTS", "AXIS_ALIASES", "BUFFER_FIELD_ALIASES",
            "STRUCT_PARAM_NAMES",
        ):
            try:
                lit = ast.literal_eval(value)
            except (ValueError, SyntaxError):
                out.problems.append(f"{target} is not a literal dict")
                continue
            if target == "ARENA_CONTRACTS":
                out.arena = lit
            elif target == "STATE_CONTRACTS":
                out.state = lit
            elif target == "KERNEL_ARG_CONTRACTS":
                out.kernel_args = lit
            elif target == "AXIS_ALIASES":
                out.axis_aliases = lit
            elif target == "BUFFER_FIELD_ALIASES":
                out.buffer_aliases = lit
            else:
                out.struct_params = lit
            if target in _CONTRACT_BLOCKS and isinstance(value, ast.Dict):
                for key in value.keys:
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        out.entry_lines[key.value] = key.lineno
    out._build_vocab()
    return out


def _live_contracts() -> Contracts:
    out = Contracts()
    try:
        from ..encoding import dtypes as D
    except ImportError as e:  # numpy-free environment: no contracts, no findings
        out.problems.append(f"cannot import encoding.dtypes: {e}")
        return out
    import numpy as np

    for name in dir(D):
        if name.endswith("_DTYPE"):
            out.policies[name] = npname_to_tag(np.dtype(getattr(D, name)).name) or "?"
    out.arena = dict(D.ARENA_CONTRACTS)
    out.state = dict(D.STATE_CONTRACTS)
    out.kernel_args = {k: dict(v) for k, v in D.KERNEL_ARG_CONTRACTS.items()}
    out.axis_aliases = dict(D.AXIS_ALIASES)
    out.buffer_aliases = dict(D.BUFFER_FIELD_ALIASES)
    out.struct_params = dict(D.STRUCT_PARAM_NAMES)
    out._build_vocab()
    return out


def load_contracts(project: ProjectContext) -> Contracts:
    for ctx in project.contexts:
        p = "/" + ctx.path.replace(os.sep, "/")
        if p.endswith("/encoding/dtypes.py"):
            return _parse_dtypes_module(ctx.tree, ctx.path)
    return _live_contracts()


# ---------------------------------------------------------------------------
# the interprocedural engine
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ArrayFinding:
    code: str  # OSL1801 | OSL1802 | OSL1803
    path: str
    line: int
    col: int
    message: str


@dataclass
class ArraySummary:
    ret: Optional[ArrayVal] = None
    # (param index, struct name, field) boundaries the raw param reaches
    param_checks: Tuple[Tuple[int, str, str], ...] = ()

    def key(self) -> Tuple:
        return (self.ret, self.param_checks)


def _in_scope(path: str) -> bool:
    p = "/" + path.replace(os.sep, "/")
    return any(f"/{frag}" in p for frag in _SCOPE) and "/tests/" not in p


class ArrayEngine:
    """Summary-fixpoint driver over every in-scope function unit."""

    def __init__(self, project: ProjectContext) -> None:
        self.df: DataflowEngine = get_engine(project)
        self.contracts = load_contracts(project)
        self.summaries: Dict[str, ArraySummary] = {}
        self.quals = [
            q for q, u in self.df.units.items() if _in_scope(u.ctx.path)
        ]

    def run(self) -> List[ArrayFinding]:
        if not self.contracts.arena and not self.contracts.state:
            return []  # no registry in sight: nothing to check against
        for _round in range(_MAX_ROUNDS):
            changed = False
            for qual in self.quals:
                new = self._analyze(qual, collect=False)
                old = self.summaries.get(qual)
                if old is None or old.key() != new.key():
                    self.summaries[qual] = new
                    changed = True
            if not changed:
                break
        seen: Set[Tuple] = set()
        findings: List[ArrayFinding] = []
        for qual in self.quals:
            self._analyze(qual, collect=True, findings=findings, seen=seen)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.code, f.message))
        return findings

    def _analyze(
        self,
        qual: str,
        collect: bool,
        findings: Optional[List[ArrayFinding]] = None,
        seen: Optional[Set[Tuple]] = None,
    ) -> ArraySummary:
        unit = self.df.units[qual]
        cfg = self.df.cfg(qual)
        summary = ArraySummary()
        pass_ = _ArrayPass(self, unit, summary, collect, findings, seen)
        forward_analyze(cfg, pass_.init_state(), pass_.transfer, _join_states)
        return summary


class _ArrayPass:
    def __init__(
        self,
        engine: ArrayEngine,
        unit: FnUnit,
        summary: ArraySummary,
        collect: bool,
        findings: Optional[List[ArrayFinding]],
        seen: Optional[Set[Tuple]],
    ) -> None:
        self.eng = engine
        self.df = engine.df
        self.con = engine.contracts
        self.unit = unit
        self.summary = summary
        self.collect = collect
        self.findings = findings
        self.seen = seen
        self.jax_sem = "jax.numpy" in unit.ctx.source or "jax import numpy" in unit.ctx.source
        self._param_checks: Set[Tuple[int, str, str]] = set(summary.param_checks)

    # -- init ----------------------------------------------------------------

    def _annotations(self) -> Dict[str, Optional[str]]:
        node = self.unit.node
        out: Dict[str, Optional[str]] = {}
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return out
        args = node.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            ann = a.annotation
            leaf = None
            if isinstance(ann, ast.Name):
                leaf = ann.id
            elif isinstance(ann, ast.Attribute):
                leaf = ann.attr
            elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                leaf = ann.value.rsplit(".", 1)[-1]
            out[a.arg] = leaf
        return out

    def init_state(self) -> State:
        state: State = {}
        ann = self._annotations()
        leaf = self.unit.qual.rsplit(".", 1)[-1]
        karg = self.con.kernel_args.get(leaf, {})
        for i, p in enumerate(self.unit.params):
            a = ann.get(p)
            if a in _STRUCT_NAMES:
                state[p] = StructVal(a)
            elif p in karg:
                tag, axes, _name = self.con.resolve(karg[p])
                state[p] = ArrayVal(dtype=tag, axes=axes or None, param_src=i)
            elif a is None and p in self.con.struct_params:
                state[p] = StructVal(self.con.struct_params[p])
            else:
                state[p] = ArrayVal(param_src=i)
        return state

    # -- transfer ------------------------------------------------------------

    def transfer(self, atom: Atom, state: State) -> State:
        node = atom.node
        new = state
        if atom.role == "test":
            self.eval(node.test if hasattr(node, "test") else node, state)
            return new
        if atom.role == "iter" and isinstance(node, (ast.For, ast.AsyncFor)):
            self.eval(node.iter, state)
            return self._bind(node.target, None, new)
        if atom.role == "withitem" and isinstance(node, ast.withitem):
            self.eval(node.context_expr, state)
            if node.optional_vars is not None:
                return self._bind(node.optional_vars, None, new)
            return new
        if atom.role in ("except",):
            return new
        if atom.role == "return" and isinstance(node, ast.Return):
            if node.value is not None:
                val = self.eval(node.value, state)
                if isinstance(val, ArrayVal):
                    joined = join_vals(self.summary.ret, val) if self.summary.ret else val
                    if isinstance(joined, ArrayVal):
                        self.summary.ret = joined
            return new
        if isinstance(node, ast.Assign):
            val = self.eval(node.value, state)
            for t in node.targets:
                new = self._bind(t, val, new)
            return new
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            return self._bind(node.target, self.eval(node.value, state), new)
        if isinstance(node, ast.AugAssign):
            val = self._binop(node.target, node.op, node.value, state, node)
            if isinstance(node.target, ast.Name):
                new = dict(new)
                if val is None:
                    new.pop(node.target.id, None)
                else:
                    new[node.target.id] = val
            return new
        if isinstance(node, ast.Expr):
            self.eval(node.value, state)
            return new
        if isinstance(node, (ast.Assert, ast.Raise, ast.Delete)):
            for child in ast.walk(node):
                if isinstance(child, ast.Call):
                    self.eval(child, state)
            return new
        return new

    def _bind(self, target: ast.AST, val: Optional[Val], state: State) -> State:
        if isinstance(target, ast.Name):
            state = dict(state)
            if val is None:
                state.pop(target.id, None)
            else:
                # plain aliasing keeps the raw-parameter identity: a param
                # renamed and then bound to a contract field is still the
                # caller's value (interprocedural param_checks)
                state[target.id] = val
            return state
        if isinstance(target, (ast.Tuple, ast.List)):
            out = state
            for elt in target.elts:
                out = self._bind(elt, None, out)
            return out
        return state

    # -- eval ----------------------------------------------------------------

    def eval(self, expr: ast.AST, state: State) -> Optional[Val]:
        if isinstance(expr, ast.Constant):
            v = expr.value
            if isinstance(v, bool):
                return Scalar("bool")
            if isinstance(v, int):
                return Scalar("int")
            if isinstance(v, float):
                return Scalar("float")
            return None
        if isinstance(expr, ast.Name):
            return state.get(expr.id)
        if isinstance(expr, ast.Attribute):
            return self._eval_attr(expr, state)
        if isinstance(expr, ast.Subscript):
            return self._eval_subscript(expr, state)
        if isinstance(expr, ast.BinOp):
            return self._binop(expr.left, expr.op, expr.right, state, expr)
        if isinstance(expr, ast.UnaryOp):
            inner = self.eval(expr.operand, state)
            if isinstance(expr.op, ast.Not):
                return Scalar("bool") if isinstance(inner, Scalar) else (
                    replace(inner, dtype="bool", param_src=-1)
                    if isinstance(inner, ArrayVal) else None
                )
            return inner
        if isinstance(expr, ast.BoolOp):
            vals = [self.eval(v, state) for v in expr.values]
            out = vals[0]
            for v in vals[1:]:
                out = join_vals(out, v)
            return out
        if isinstance(expr, ast.IfExp):
            self.eval(expr.test, state)
            return join_vals(self.eval(expr.body, state), self.eval(expr.orelse, state))
        if isinstance(expr, ast.Compare):
            operands = [self.eval(o, state) for o in [expr.left] + expr.comparators]
            arrays = [o for o in operands if isinstance(o, ArrayVal)]
            if arrays:
                best = max(arrays, key=lambda a: len(a.axes) if a.axes else -1)
                return ArrayVal(dtype="bool", axes=best.axes)
            return Scalar("bool")
        if isinstance(expr, ast.Call):
            return self._eval_call(expr, state)
        if isinstance(expr, ast.Dict):
            items = []
            for k, v in zip(expr.keys, expr.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    av = self.eval(v, state)
                    if isinstance(av, ArrayVal):
                        items.append((k.value, av))
                else:
                    self.eval(v, state) if v is not None else None
            return DictVal(tuple(items))
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            for elt in expr.elts:
                self.eval(elt, state)
            return None
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            return None
        if isinstance(expr, ast.Starred):
            return self.eval(expr.value, state)
        return None

    def _eval_attr(self, expr: ast.Attribute, state: State) -> Optional[Val]:
        base = self.eval(expr.value, state)
        if isinstance(base, StructVal):
            fields = self.con.struct_fields(base.struct)
            entry = fields.get(expr.attr)
            if entry is not None:
                tag, axes, _name = self.con.resolve(entry)
                return ArrayVal(dtype=tag, axes=self._norm_axes(axes))
            return None
        if isinstance(base, ArrayVal):
            if expr.attr == "T":
                return replace(
                    base,
                    axes=tuple(reversed(base.axes)) if base.axes else None,
                    param_src=-1,
                )
            if expr.attr in ("real", "imag"):
                return base
            return None
        return None

    def _eval_subscript(self, expr: ast.Subscript, state: State) -> Optional[Val]:
        base = self.eval(expr.value, state)
        if isinstance(base, DictVal):
            sl = expr.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                return dict(base.items).get(sl.value)
            return None
        if not isinstance(base, ArrayVal):
            return None
        elts = expr.slice.elts if isinstance(expr.slice, ast.Tuple) else [expr.slice]
        axes = base.axes
        if axes is not None:
            new_axes: Optional[List[str]] = []
            pos = 0
            for elt in elts:
                if isinstance(elt, ast.Slice):
                    if pos < len(axes):
                        new_axes.append("?")  # sliced extent: name no longer exact
                        pos += 1
                    else:
                        new_axes = None
                        break
                elif isinstance(elt, ast.Constant) and elt.value is None:
                    new_axes = None  # newaxis
                    break
                elif isinstance(elt, ast.Constant) and elt.value is Ellipsis:
                    new_axes = None
                    break
                else:
                    idx = self.eval(elt, state)
                    if isinstance(idx, ArrayVal):
                        new_axes = None  # fancy/mask indexing
                        break
                    if pos < len(axes):
                        pos += 1  # integer index drops the axis
                    else:
                        new_axes = None
                        break
            if new_axes is not None:
                new_axes.extend(axes[pos:])
            return replace(
                base, axes=tuple(new_axes) if new_axes is not None else None,
                param_src=-1,
            )
        return replace(base, axes=None, param_src=-1)

    # -- arithmetic ----------------------------------------------------------

    def _binop(
        self, left: ast.AST, op: ast.operator, right: ast.AST,
        state: State, site: ast.AST,
    ) -> Optional[Val]:
        l = self.eval(left, state)
        r = self.eval(right, state)
        if isinstance(l, Scalar) and isinstance(r, Scalar):
            if isinstance(op, ast.Div):
                return Scalar("float")
            kinds = {l.kind, r.kind}
            return Scalar("float" if "float" in kinds else "int")
        lav = l if isinstance(l, ArrayVal) else None
        rav = r if isinstance(r, ArrayVal) else None
        if lav is None and rav is None:
            return None
        axes = self._broadcast_axes(lav, rav)
        creations = (lav.creations if lav else ()) + (rav.creations if rav else ())
        widenings = (lav.widenings if lav else ()) + (rav.widenings if rav else ())
        dtype: Optional[str] = None
        if lav is not None and rav is not None:
            if lav.dtype and rav.dtype:
                dtype = promote(lav.dtype, rav.dtype, self.jax_sem)
                if isinstance(op, ast.Div) and not _is_float(dtype):
                    dtype = "f32" if self.jax_sem else "f64"
                if dtype not in (lav.dtype, rav.dtype) or (
                    isinstance(op, ast.Div) and dtype not in (lav.dtype, rav.dtype)
                ):
                    widenings += (self._event(site, f"{lav.dtype} x {rav.dtype} -> {dtype}"),)
        else:
            av = lav or rav
            other = r if lav is not None else l
            if isinstance(other, Scalar) and av.dtype:
                dtype = promote_weak(av.dtype, other.kind, self.jax_sem)
                if isinstance(op, ast.Div) and not _is_float(dtype):
                    dtype = "f32" if self.jax_sem else "f64"
                if dtype != av.dtype:
                    widenings += (
                        self._event(site, f"{av.dtype} x py-{other.kind} -> {dtype}"),
                    )
            # unknown operand: dtype unknown, keep the known side's axes
        return ArrayVal(
            dtype=dtype, axes=axes, creations=_cap(creations),
            widenings=_cap(widenings),
        )

    def _broadcast_axes(
        self, l: Optional[ArrayVal], r: Optional[ArrayVal]
    ) -> Optional[Tuple[str, ...]]:
        la = l.axes if l is not None else None
        ra = r.axes if r is not None else None
        if la is None:
            return ra
        if ra is None:
            return la
        if la == ra:
            return la
        if len(la) != len(ra):
            return la if len(la) > len(ra) else ra
        return None

    # -- calls ---------------------------------------------------------------

    @staticmethod
    def _dotted(expr: ast.AST) -> Optional[str]:
        parts: List[str] = []
        cur = expr
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            parts.append(cur.id)
            return ".".join(reversed(parts))
        return None

    def _event(self, node: ast.AST, desc: str) -> Event:
        return (
            self.unit.ctx.path,
            getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0),
            desc,
        )

    def _resolve_dtype_arg(self, expr: ast.AST) -> Tuple[Optional[str], bool]:
        """(tag, is_policy_or_known). tag None + True = explicit-but-opaque
        (e.g. ``x.dtype``): no default-creation event, nothing to check."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return npname_to_tag(expr.value), True
        if isinstance(expr, ast.Name):
            if expr.id == "bool":
                return "bool", True
            if expr.id in self.con.policies:
                return self.con.policies[expr.id], True
            tag = npname_to_tag(expr.id)
            if tag and expr.id in _NP_NAME_TO_TAG:
                return tag, True
            return None, True
        if isinstance(expr, ast.Attribute):
            leaf = expr.attr
            if leaf in self.con.policies:
                return self.con.policies[leaf], True
            if leaf in _NP_NAME_TO_TAG:
                return _NP_NAME_TO_TAG[leaf], True
            return None, True  # x.dtype and friends: opaque
        if isinstance(expr, ast.Call):
            # np.dtype(np.float32)
            inner = expr.args[0] if expr.args else None
            if inner is not None:
                return self._resolve_dtype_arg(inner)
        return None, True

    def _axes_from_shape(self, expr: ast.AST) -> Optional[Tuple[str, ...]]:
        elts = expr.elts if isinstance(expr, (ast.Tuple, ast.List)) else [expr]
        axes = []
        for e in elts:
            axes.append(self._render_axis(e))
        return tuple(axes)

    def _render_axis(self, e: ast.AST) -> str:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            return self.con.norm_axis(str(e.value)) if self.con.norm_axis(
                str(e.value)) != "?" else str(e.value)
        name: Optional[str] = None
        if isinstance(e, ast.Name):
            name = e.id
        elif isinstance(e, ast.Attribute):
            name = e.attr
        elif (
            isinstance(e, ast.BinOp)
            and isinstance(e.op, ast.Add)
            and isinstance(e.right, ast.Constant)
            and isinstance(e.right.value, int)
        ):
            base = self._render_axis(e.left)
            if base != "?":
                name = f"{base}+{e.right.value}"
        if name is None:
            return "?"
        return self.con.norm_axis(name)

    def _norm_axes(self, axes: Tuple[str, ...]) -> Tuple[str, ...]:
        return tuple(self.con.norm_axis(a) if self.con.norm_axis(a) != "?" else a
                     for a in axes)

    def _scalar_tag(self, val: Optional[Val], jaxish: bool) -> Optional[str]:
        if isinstance(val, Scalar):
            if val.kind == "float":
                return "f32" if jaxish else "f64"
            if val.kind == "int":
                return "i32" if jaxish else "i64"
            return "bool"
        if isinstance(val, ArrayVal):
            return val.dtype
        return None

    def _eval_call(self, call: ast.Call, state: State) -> Optional[Val]:
        dotted = self._dotted(call.func)
        leaf = dotted.rsplit(".", 1)[-1] if dotted else None
        base = dotted.rsplit(".", 2)[-2] if dotted and "." in dotted else None
        if leaf is None and isinstance(call.func, ast.Attribute):
            # method chained on a call/subscript receiver, e.g.
            # np.frombuffer(b).reshape(s): _dotted can't root it at a Name,
            # but _eval_method only needs the attr + an evaluable receiver
            leaf = call.func.attr

        # struct constructors / _replace are contract boundaries
        if leaf in _STRUCT_NAMES:
            self._check_constructor(leaf, call, state)
            return StructVal(leaf)
        if leaf == "_replace" and isinstance(call.func, ast.Attribute):
            recv = self.eval(call.func.value, state)
            if isinstance(recv, StructVal):
                self._check_kwargs(recv.struct, call, state)
                return recv
            for kw in call.keywords:
                if kw.value is not None:
                    self.eval(kw.value, state)
            return None

    # kernel entry boundaries
        if leaf in self.con.kernel_args:
            self._check_kernel_call(leaf, call, state)

        # numpy/jax creators & transforms
        if base in _ARRAY_BASES and leaf is not None:
            out = self._eval_np_call(base, leaf, call, state)
            if out is not None or leaf in _CREATORS or leaf in _LIKE_CREATORS:
                return out
        if leaf is not None and isinstance(call.func, ast.Attribute):
            out = self._eval_method(leaf, call, state)
            if out is not None:
                return out

        # known helpers
        if leaf == "_grown" and len(call.args) >= 2:
            src = self.eval(call.args[0], state)
            axes = self._axes_from_shape(call.args[1])
            if isinstance(src, ArrayVal):
                return ArrayVal(dtype=src.dtype, axes=axes,
                                creations=src.creations, widenings=src.widenings)
            return ArrayVal(axes=axes)
        if leaf in _PASSTHROUGH_CALLS and call.args:
            inner = self.eval(call.args[0], state)
            if isinstance(inner, ArrayVal):
                return replace(inner, param_src=-1)
            for a in call.args[1:]:
                self.eval(a, state)
            return None

        # interprocedural: resolved project call -> summary
        target = self.df.resolve_call(self.unit, call)
        for a in call.args:
            self.eval(a, state)
        for kw in call.keywords:
            if kw.value is not None:
                self.eval(kw.value, state)
        if target is not None:
            summ = self.eng.summaries.get(target)
            if summ is not None:
                self._apply_param_checks(target, summ, call, state)
                return summ.ret
        return None

    def _eval_np_call(
        self, module_base: str, leaf: str, call: ast.Call, state: State
    ) -> Optional[Val]:
        jaxish = module_base == "jnp" or (self.jax_sem and module_base != "np")
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        if leaf in _CREATORS:
            return self._eval_creator(jaxish, leaf, call, kw, state)
        if leaf in _LIKE_CREATORS:
            src = self.eval(call.args[0], state) if call.args else None
            axes = src.axes if isinstance(src, ArrayVal) else None
            if "dtype" in kw:
                tag, _known = self._resolve_dtype_arg(kw["dtype"])
                return ArrayVal(dtype=tag, axes=axes)
            if isinstance(src, ArrayVal):
                return ArrayVal(dtype=src.dtype, axes=axes)
            return ArrayVal()
        if leaf == "where" and len(call.args) == 3:
            self.eval(call.args[0], state)
            a = self.eval(call.args[1], state)
            b = self.eval(call.args[2], state)
            return self._promote_vals(a, b, call, jaxish)
        if leaf in ("concatenate", "stack", "vstack", "hstack", "column_stack"):
            parts: List[Optional[Val]] = []
            if call.args and isinstance(call.args[0], (ast.Tuple, ast.List)):
                parts = [self.eval(e, state) for e in call.args[0].elts]
            out: Optional[Val] = parts[0] if parts else None
            for p in parts[1:]:
                out = self._promote_vals(out, p, call, jaxish)
            if isinstance(out, ArrayVal):
                return replace(out, axes=None, param_src=-1)
            return ArrayVal()
        if leaf in _BIN_FUNCS and len(call.args) >= 2:
            a = self.eval(call.args[0], state)
            b = self.eval(call.args[1], state)
            return self._promote_vals(a, b, call, jaxish)
        if leaf == "clip" and call.args:
            out = self.eval(call.args[0], state)
            for bound in call.args[1:3]:
                out = self._promote_vals(out, self.eval(bound, state), call, jaxish)
            return out if isinstance(out, ArrayVal) else None
        if leaf in _FLOAT_UFUNCS and call.args:
            src = self.eval(call.args[0], state)
            if isinstance(src, ArrayVal):
                if src.dtype and not _is_float(src.dtype):
                    dtype = "f32" if jaxish else "f64"
                    wid = src.widenings + (
                        self._event(call, f"{leaf}({src.dtype}) -> {dtype}"),
                    )
                    return replace(src, dtype=dtype, widenings=_cap(wid), param_src=-1)
                return replace(src, param_src=-1)
            return None
        if leaf in _INT_ACCUM_REDUCERS and call.args:
            return self._reduce(self.eval(call.args[0], state), leaf, call, jaxish)
        if leaf in _KEEP_REDUCERS and call.args:
            src = self.eval(call.args[0], state)
            if isinstance(src, ArrayVal):
                return replace(src, axes=None, param_src=-1)
            return None
        if leaf == "transpose" and call.args:
            src = self.eval(call.args[0], state)
            if isinstance(src, ArrayVal):
                axes = tuple(reversed(src.axes)) if src.axes and len(call.args) == 1 else None
                return replace(src, axes=axes, param_src=-1)
            return None
        if leaf in _NP_NAME_TO_TAG:  # np.float64(x) style strong scalar
            for a in call.args:
                self.eval(a, state)
            return ArrayVal(dtype=_NP_NAME_TO_TAG[leaf], axes=())
        return None

    def _eval_creator(
        self, jaxish: bool, leaf: str, call: ast.Call,
        kw: Dict[str, ast.expr], state: State,
    ) -> ArrayVal:
        fname = ("jnp." if jaxish else "np.") + leaf
        axes: Optional[Tuple[str, ...]] = None
        if leaf in ("zeros", "ones", "empty", "full") and call.args:
            axes = self._axes_from_shape(call.args[0])
        elif leaf == "arange" and call.args:
            axes = (self._render_axis(call.args[0]),) if len(call.args) == 1 else ("?",)
        elif leaf == "linspace":
            axes = ("?",)
        dtype_expr = kw.get("dtype")
        if dtype_expr is None:
            for pos, name in self._dtype_positions(leaf, call):
                dtype_expr = pos
                break
        if dtype_expr is not None:
            tag, _known = self._resolve_dtype_arg(dtype_expr)
            return ArrayVal(dtype=tag, axes=axes)
        # no dtype: default-width creation
        default: Optional[str] = None
        event_needed = True
        if leaf in ("zeros", "ones", "empty", "linspace"):
            default = None if jaxish else "f64"
        elif leaf == "frombuffer":
            default = None if jaxish else "f64"
        elif leaf == "full" and len(call.args) >= 2:
            default = self._scalar_tag(self.eval(call.args[1], state), jaxish)
        elif leaf == "arange" and call.args:
            kinds = [self.eval(a, state) for a in call.args]
            if any(isinstance(k, Scalar) and k.kind == "float" for k in kinds):
                default = "f32" if jaxish else "f64"
            else:
                # extents are ints in practice: numpy defaults to i64,
                # jax to i32 (which IS the policy width — stays clean)
                default = "i32" if jaxish else "i64"
        elif leaf in ("array", "asarray", "ascontiguousarray", "fromiter"):
            src = self.eval(call.args[0], state) if call.args else None
            if isinstance(src, ArrayVal):
                # dtype-preserving view/copy: not a creation
                return replace(src, param_src=-1)
            if isinstance(src, Scalar):
                default = self._scalar_tag(src, jaxish)
            elif call.args and isinstance(call.args[0], (ast.Tuple, ast.List)):
                default = self._literal_seq_tag(call.args[0], jaxish)
            else:
                event_needed = False  # unknown payload: don't guess
        ev: Tuple[Event, ...] = ()
        if event_needed:
            ev = (self._event(call, f"{fname} (dtype {default or 'default'})"),)
        return ArrayVal(dtype=default, axes=axes, creations=ev)

    @staticmethod
    def _dtype_positions(leaf: str, call: ast.Call):
        # positional dtype args: zeros/ones/empty(shape, dtype),
        # full(shape, fill, dtype), arange(..., dtype) is kw-only in practice
        if leaf in ("zeros", "ones", "empty") and len(call.args) >= 2:
            yield call.args[1], "dtype"
        if leaf == "full" and len(call.args) >= 3:
            yield call.args[2], "dtype"
        if leaf in ("array", "asarray", "ascontiguousarray", "frombuffer") and len(call.args) >= 2:
            yield call.args[1], "dtype"

    def _literal_seq_tag(self, seq: ast.expr, jaxish: bool) -> Optional[str]:
        has_float = False
        all_scalar = True
        for node in ast.walk(seq):
            if isinstance(node, ast.Constant):
                if isinstance(node.value, float):
                    has_float = True
                elif not isinstance(node.value, (int, bool)):
                    all_scalar = False
            elif not isinstance(node, (ast.Tuple, ast.List, ast.UnaryOp,
                                       ast.USub, ast.UAdd, ast.Load)):
                all_scalar = False
        if not all_scalar:
            return None
        if has_float:
            return "f32" if jaxish else "f64"
        return "i32" if jaxish else "i64"

    def _eval_method(self, leaf: str, call: ast.Call, state: State) -> Optional[Val]:
        assert isinstance(call.func, ast.Attribute)
        recv = self.eval(call.func.value, state)
        if not isinstance(recv, ArrayVal):
            return None
        jaxish = self.jax_sem
        if leaf == "astype" and call.args:
            tag, _known = self._resolve_dtype_arg(call.args[0])
            # an explicit cast sanctions the value: prior events cleared
            return ArrayVal(dtype=tag, axes=recv.axes)
        if leaf == "copy":
            return replace(recv, param_src=-1)
        if leaf == "reshape":
            args = call.args
            if len(args) == 1 and isinstance(args[0], (ast.Tuple, ast.List)):
                axes = self._axes_from_shape(args[0])
            elif args:
                axes = tuple(self._render_axis(a) for a in args)
            else:
                axes = None
            if axes and any(
                isinstance(a, ast.Constant) and a.value == -1
                for a in (args[0].elts if len(args) == 1 and isinstance(args[0], (ast.Tuple, ast.List)) else args)
            ):
                axes = None
            return replace(recv, axes=axes, param_src=-1)
        if leaf in ("ravel", "flatten"):
            return replace(recv, axes=None, param_src=-1)
        if leaf == "transpose":
            axes = tuple(reversed(recv.axes)) if recv.axes and not call.args else None
            return replace(recv, axes=axes, param_src=-1)
        if leaf in _INT_ACCUM_REDUCERS:
            return self._reduce(recv, leaf, call, jaxish)
        if leaf in _KEEP_REDUCERS or leaf == "mean":
            if leaf == "mean" and recv.dtype and not _is_float(recv.dtype):
                dtype = "f32" if jaxish else "f64"
                return ArrayVal(dtype=dtype, axes=None,
                                creations=recv.creations,
                                widenings=_cap(recv.widenings + (
                                    self._event(call, f"mean({recv.dtype}) -> {dtype}"),)))
            return replace(recv, axes=None, param_src=-1)
        if leaf in _PASSTHROUGH_CALLS:
            return replace(recv, param_src=-1)
        return None

    def _reduce(
        self, src: Optional[Val], leaf: str, call: ast.Call, jaxish: bool
    ) -> Optional[Val]:
        if not isinstance(src, ArrayVal):
            return None
        if src.dtype and not _is_float(src.dtype) and not jaxish and src.dtype != "i64":
            wid = src.widenings + (
                self._event(call, f"{leaf}({src.dtype}) -> i64"),
            )
            return ArrayVal(dtype="i64", axes=None, creations=src.creations,
                            widenings=_cap(wid))
        return replace(src, axes=None, param_src=-1)

    def _promote_vals(
        self, a: Optional[Val], b: Optional[Val], site: ast.AST, jaxish: bool
    ) -> Optional[Val]:
        aav = a if isinstance(a, ArrayVal) else None
        bav = b if isinstance(b, ArrayVal) else None
        if aav is None and bav is None:
            return None
        axes = self._broadcast_axes(aav, bav)
        creations = (aav.creations if aav else ()) + (bav.creations if bav else ())
        widenings = (aav.widenings if aav else ()) + (bav.widenings if bav else ())
        dtype: Optional[str] = None
        if aav is not None and bav is not None and aav.dtype and bav.dtype:
            dtype = promote(aav.dtype, bav.dtype, jaxish)
            if dtype not in (aav.dtype, bav.dtype):
                widenings += (self._event(site, f"{aav.dtype} x {bav.dtype} -> {dtype}"),)
        elif (aav is None) != (bav is None):
            av = aav or bav
            other = b if aav is not None else a
            if isinstance(other, Scalar) and av.dtype:
                dtype = promote_weak(av.dtype, other.kind, jaxish)
                if dtype != av.dtype:
                    widenings += (
                        self._event(site, f"{av.dtype} x py-{other.kind} -> {dtype}"),
                    )
        return ArrayVal(dtype=dtype, axes=axes, creations=_cap(creations),
                        widenings=_cap(widenings))

    # -- boundaries ----------------------------------------------------------

    def _check_constructor(self, struct: str, call: ast.Call, state: State) -> None:
        fields = self.con.struct_fields(struct)
        order = list(fields)
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                self.eval(arg.value, state)
                return  # positional mapping lost after *args
            if i < len(order):
                self._check_bind(struct, order[i], self.eval(arg, state), arg)
        self._check_kwargs(struct, call, state)

    def _check_kwargs(self, struct: str, call: ast.Call, state: State) -> None:
        fields = self.con.struct_fields(struct)
        for kw in call.keywords:
            if kw.arg is None:  # **mapping
                mapping = self.eval(kw.value, state)
                if isinstance(mapping, DictVal):
                    for name, av in mapping.items:
                        if name in fields:
                            self._check_bind(struct, name, av, kw.value)
                continue
            val = self.eval(kw.value, state)
            if kw.arg in fields:
                self._check_bind(struct, kw.arg, val, kw.value)

    def _check_kernel_call(self, leaf: str, call: ast.Call, state: State) -> None:
        contracts = self.con.kernel_args.get(leaf, {})
        target = self.df.resolve_call(self.unit, call)
        params: List[str] = []
        offset = 0
        if target is not None:
            callee = self.df.units[target]
            params = callee.params
            if params and params[0] in ("self", "cls") and isinstance(
                call.func, ast.Attribute
            ):
                offset = 1
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                return
            idx = i + offset
            if idx < len(params) and params[idx] in contracts:
                self._check_kernel_bind(leaf, params[idx], contracts[params[idx]],
                                        self.eval(arg, state), arg)
        for kw in call.keywords:
            if kw.arg in contracts:
                self._check_kernel_bind(leaf, kw.arg, contracts[kw.arg],
                                        self.eval(kw.value, state), kw.value)

    def _check_kernel_bind(
        self, fn: str, param: str, entry: Tuple[str, Tuple[str, ...]],
        val: Optional[Val], site: ast.AST,
    ) -> None:
        tag, axes, policy = self.con.resolve(entry)
        self._check_value(
            f"{fn}(…, {param}=…)", tag, self._norm_axes(axes), policy, val, site,
            trailing_axes=True,
        )

    def _check_bind(
        self, struct: str, fname: str, val: Optional[Val], site: ast.AST
    ) -> None:
        entry = self.con.struct_fields(struct).get(fname)
        if entry is None:
            return
        tag, axes, policy = self.con.resolve(entry)
        self._check_value(
            f"{struct}.{fname}", tag, self._norm_axes(axes), policy, val, site,
            trailing_axes=False,
        )
        if isinstance(val, ArrayVal) and val.param_src >= 0 and not self.collect:
            self._param_checks.add((val.param_src, struct, fname))
            self.summary.param_checks = tuple(sorted(self._param_checks))

    def _check_value(
        self, what: str, tag: Optional[str], want_axes: Tuple[str, ...],
        policy: str, val: Optional[Val], site: ast.AST, trailing_axes: bool,
    ) -> None:
        if not isinstance(val, ArrayVal):
            return
        if tag is not None and val.dtype is not None and val.dtype != tag:
            if val.widenings:
                for path, line, col, desc in val.widenings:
                    self._emit(
                        "OSL1802", path, line, col,
                        f"silent upcast ({desc}) reaches `{what}` "
                        f"(contract {policy}={tag}, value is {val.dtype})",
                    )
            elif val.creations:
                for path, line, col, desc in val.creations:
                    self._emit(
                        "OSL1801", path, line, col,
                        f"off-policy array creation ({desc}) reaches `{what}` "
                        f"(contract {policy}={tag}, value is {val.dtype})",
                    )
            else:
                self._emit(
                    "OSL1801", self.unit.ctx.path,
                    getattr(site, "lineno", 0), getattr(site, "col_offset", 0),
                    f"`{what}` receives a {val.dtype} value "
                    f"(contract {policy}={tag}) built without a policy dtype",
                )
        if want_axes and val.axes is not None:
            got = val.axes
            want = want_axes
            if trailing_axes and len(got) > len(want):
                got = got[len(got) - len(want):]
            if len(got) != len(want):
                self._emit(
                    "OSL1803", self.unit.ctx.path,
                    getattr(site, "lineno", 0), getattr(site, "col_offset", 0),
                    f"shape contract violation: `{what}` expects rank "
                    f"{len(want)} axes [{', '.join(want)}], got rank "
                    f"{len(val.axes)}",
                )
            elif any(
                g != "?" and w != "?" and g.lower() != w.lower()
                for g, w in zip(got, want)
            ):
                self._emit(
                    "OSL1803", self.unit.ctx.path,
                    getattr(site, "lineno", 0), getattr(site, "col_offset", 0),
                    f"shape contract violation: `{what}` expects axes "
                    f"[{', '.join(want)}], got [{', '.join(got)}]",
                )

    def _apply_param_checks(
        self, target: str, summ: ArraySummary, call: ast.Call, state: State
    ) -> None:
        if not summ.param_checks:
            return
        callee = self.df.units[target]
        offset = 0
        if callee.params and callee.params[0] in ("self", "cls") and isinstance(
            call.func, ast.Attribute
        ):
            offset = 1
        by_index = {i: a for i, a in enumerate(call.args)
                    if not isinstance(a, ast.Starred)}
        by_name = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        for pidx, struct, fname in summ.param_checks:
            arg: Optional[ast.expr] = None
            pos = pidx - offset
            if pos in by_index:
                arg = by_index[pos]
            elif pidx < len(callee.params) and callee.params[pidx] in by_name:
                arg = by_name[callee.params[pidx]]
            if arg is not None:
                self._check_bind(struct, fname, self.eval(arg, state), arg)

    def _emit(self, code: str, path: str, line: int, col: int, message: str) -> None:
        if not self.collect or self.findings is None or self.seen is None:
            return
        key = (code, path, line, col, message)
        if key in self.seen:
            return
        self.seen.add(key)
        self.findings.append(ArrayFinding(code, path, line, col, message))


def get_array_findings(project: ProjectContext) -> List[ArrayFinding]:
    cached = getattr(project, "_array_findings", None)
    if cached is None:
        cached = ArrayEngine(project).run()
        project._array_findings = cached
    return cached
