"""shm-discipline (OSL1701): shared-memory segments are created, attached
and unlinked ONLY in ``server/fleet.py``.

The fleet's whole-of-/dev/shm hygiene story (ISSUE 15, docs/serving.md
"Scaling past one process") rests on one module owning every segment
lifecycle: the publisher's close/atexit/resource-tracker chain unlinks
exactly the set it created, readers unregister their attachments so an
exiting worker never destroys the owner's live segments, and the seqlock
retry bounds the attach path. One ``SharedMemory(...)`` constructed
anywhere else and a segment exists that no owner unlinks, no reader
unregisters, and no retry loop protects — the classic leaked-/dev/shm
failure mode the tests pin down.

The rule flags, in any module other than ``server/fleet.py``:

- imports of ``multiprocessing.shared_memory`` (``import`` or
  ``from ... import``), including ``from multiprocessing import
  shared_memory``;
- any call whose callee is spelled ``SharedMemory(...)`` (dotted or
  bare) — construction IS both create and attach;
- ``.unlink()`` calls on a receiver whose name mentions ``shm`` or
  ``segment`` (destroying a segment from outside the owner).

Fix by routing through ``server/fleet.py``'s publisher/reader API
(``TwinPublisher`` / ``FleetReader``); see docs/static-analysis.md.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import FileContext, Finding, Rule, dotted_name, register

_FIX = (
    "shared-memory create/attach/unlink lives in server/fleet.py "
    "(TwinPublisher/FleetReader own the segment lifecycle)"
)


@register
class ShmDisciplineRule(Rule):
    name = "shm-discipline"
    code = "OSL1701"
    description = "shared-memory segment lifecycle outside server/fleet.py"
    # tests exercise leak/crash scenarios on purpose
    exclude_paths = ("server/fleet.py", "tests/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("multiprocessing.shared_memory"):
                        yield self.finding(
                            ctx, node,
                            f"import of {alias.name} outside server/fleet.py; {_FIX}",
                        )
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.startswith("multiprocessing.shared_memory"):
                    yield self.finding(
                        ctx, node,
                        f"import from {mod} outside server/fleet.py; {_FIX}",
                    )
                elif mod == "multiprocessing" and any(
                    a.name == "shared_memory" for a in node.names
                ):
                    yield self.finding(
                        ctx, node,
                        "from multiprocessing import shared_memory outside "
                        f"server/fleet.py; {_FIX}",
                    )
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                leaf = name.rsplit(".", 1)[-1]
                if leaf == "SharedMemory":
                    yield self.finding(
                        ctx, node,
                        "SharedMemory construction (create/attach) outside "
                        f"server/fleet.py; {_FIX}",
                    )
                elif (
                    leaf == "unlink"
                    and isinstance(node.func, ast.Attribute)
                    and any(
                        tag in (dotted_name(node.func.value) or "").lower()
                        for tag in ("shm", "segment")
                    )
                ):
                    yield self.finding(
                        ctx, node,
                        "shared-memory unlink outside server/fleet.py "
                        f"(only the owner destroys segments); {_FIX}",
                    )
