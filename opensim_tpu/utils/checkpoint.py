"""Checkpoint / resume of simulation state.

The reference has no checkpointing (SURVEY.md §5 — a run is stateless
end-to-end); here the encoded cluster + scan carry are plain tensors, so
snapshotting mid-plan is a single ``np.savez``. This enables resuming a
long capacity sweep, sharing an encoded 50k-pod cluster between processes,
or diffing two planning runs.
"""

from __future__ import annotations

import json
from typing import Tuple

import numpy as np

from ..encoding.state import EncodedCluster, ScanState

_FORMAT_VERSION = 1


def _npz_path(path: str) -> str:
    return path if path.endswith(".npz") else path + ".npz"


def save_state(path: str, ec: EncodedCluster, st: ScanState, extra: dict | None = None) -> None:
    arrays = {}
    for name, arr in ec._asdict().items():
        arrays[f"ec_{name}"] = np.asarray(arr)
    for name, arr in st._asdict().items():
        arrays[f"st_{name}"] = np.asarray(arr)
    arrays["__meta__"] = np.frombuffer(
        json.dumps({"version": _FORMAT_VERSION, "extra": extra or {}}).encode(), dtype=np.uint8
    )
    np.savez_compressed(_npz_path(path), **arrays)


def load_state(path: str) -> Tuple[EncodedCluster, ScanState, dict]:
    with np.load(_npz_path(path)) as data:
        meta = json.loads(bytes(data["__meta__"]).decode())
        if meta.get("version") != _FORMAT_VERSION:
            raise ValueError(f"{path}: unsupported checkpoint version {meta.get('version')}")
        fields = {k[3:]: data[k] for k in data.files if k.startswith("ec_")}
        # additive-field compatibility: version-1 checkpoints written before
        # gc_mask existed load with the conservative default (all-static
        # allocatable — exactly their behavior when saved)
        if "gc_mask" not in fields:
            fields["gc_mask"] = np.zeros((fields["alloc"].shape[1],), dtype=bool)
        if "log_sizes" not in fields:
            from ..encoding.dtypes import log_size_table

            fields["log_sizes"] = log_size_table(fields["alloc"].shape[0])
        ec = EncodedCluster(**fields)
        st = ScanState(**{k[3:]: data[k] for k in data.files if k.startswith("st_")})
    return ec, st, meta.get("extra", {})
