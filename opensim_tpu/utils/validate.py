"""Registered input validators — the sanitizer convention for OSL1603.

The untrusted-input-taint rule (``analysis/rules_dataflow.py``) tracks
HTTP query/body params, CLI args, YAML documents, and stdin through the
call graph and flags any flow into ``open()``/path joins/``subprocess``
that has not passed a **registered validator**. A validator is any
function carrying the :func:`sanitizer` decorator — the decorator is the
registration; the analyzer treats the function's return value as clean.

That makes this module the audit surface: every place untrusted input
crosses into the filesystem is either one of these functions or a
``@sanitizer``-decorated validator next to the code it guards (e.g. the
campaign planner's ``_resolve_path``). Keep validators small, raising
``ValueError`` on rejection so the CLI/REST surfaces render the usual
one-liner.
"""

from __future__ import annotations

import os

__all__ = ["sanitizer", "user_path", "child_path"]


def sanitizer(fn):
    """Register ``fn`` as a taint validator (OSL1603). The analyzer keys
    on the decorator name; the attribute makes registration introspectable
    at runtime too."""
    fn.__taint_sanitizer__ = True
    return fn


@sanitizer
def user_path(p, *, label: str = "path", allow_empty: bool = False) -> str:
    """Validate a user-supplied filesystem path (CLI flags, config
    references). Rejects control characters — the class of input that
    turns log lines, shell handoffs, and error messages into injection
    vectors — and empty strings unless the flag is optional."""
    s = os.fspath(p)
    if not s:
        if allow_empty:
            return s
        raise ValueError(f"empty {label}")
    if any(ord(c) < 32 for c in s):
        raise ValueError(f"invalid {label}: control character in {s!r}")
    return s


@sanitizer
def child_path(base: str, rel, *, label: str = "path") -> str:
    """Resolve a spec-relative path against its document's directory.
    Absolute paths pass through (the CLI trust domain allows them — the
    operator already has file access); relative paths are joined,
    normalized, and must stay UNDER ``base`` — a ``..`` escape out of the
    spec's directory is rejected. Control characters are rejected either
    way."""
    s = user_path(rel, label=label)
    if os.path.isabs(s) or not base:
        return s
    resolved = os.path.normpath(os.path.join(base, s))
    root = os.path.normpath(base)
    if resolved != root and not resolved.startswith(root + os.sep):
        raise ValueError(
            f"invalid {label}: {s!r} escapes the spec directory {base!r}"
        )
    return resolved
