"""Terminal progress — the pterm parity layer.

The reference animates a per-pod progress bar while its scheduler goroutine
works through the queue (``pkg/simulator/simulator.go:311-321``) and shows
spinners around cluster snapshots (``:506-509``). Here the whole bind scan is
ONE fused device op, so per-pod increments don't exist; instead each host
phase gets a live spinner with an elapsed-time readout and a final tally
(``✓ schedule 50000 pods (2.4s)``). Output is TTY-gated (the ``DisablePTerm`` equivalent) and goes to
stderr so piped reports stay clean; ``OPENSIM_NO_PROGRESS=1`` force-disables.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Optional, TextIO

from . import envknobs

_FRAMES = "⠋⠙⠹⠸⠼⠴⠦⠧⠇⠏"


def enabled_by_default(stream: TextIO) -> bool:
    if envknobs.raw("OPENSIM_NO_PROGRESS"):
        return False
    try:
        return bool(stream.isatty())
    except (AttributeError, ValueError):
        return False


class Spinner:
    """Context manager: ``with Spinner("schedule 50000 pods"): ...`` animates
    while the body runs and leaves one ``✓ label (1.2s)`` line behind."""

    def __init__(self, label: str, stream: Optional[TextIO] = None, enabled: Optional[bool] = None):
        self.label = label
        self.stream = stream if stream is not None else sys.stderr
        self.enabled = enabled_by_default(self.stream) if enabled is None else enabled
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0

    def __enter__(self) -> "Spinner":
        self._t0 = time.monotonic()
        if self.enabled:
            self._thread = threading.Thread(target=self._spin, daemon=True)
            self._thread.start()
        return self

    def _spin(self) -> None:
        i = 0
        while not self._stop.wait(0.1):
            dt = time.monotonic() - self._t0
            self.stream.write(f"\r{_FRAMES[i % len(_FRAMES)]} {self.label}… {dt:.1f}s ")
            self.stream.flush()
            i += 1

    def __exit__(self, exc_type, *_exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
        if self.enabled:
            dt = time.monotonic() - self._t0
            mark = "✓" if exc_type is None else "✗"
            self.stream.write(f"\r{mark} {self.label} ({dt:.1f}s)\n")
            self.stream.flush()

