"""Accelerator reachability probe.

The axon tunnel that fronts the TPU can die in a total-hang mode where ANY
jax device op — even ``jax.devices()`` — blocks forever. Every entry point
that would otherwise touch the device on the user's behalf (the CLI's
``--backend auto``, ``bench.py``) first runs a trivial device op in a
*subprocess* with a timeout; on failure the caller forces the CPU platform
in-process (``jax.config.update("jax_platforms", "cpu")``) instead of
hanging. The verdict is cached on disk briefly so a batch of invocations
pays the timeout once.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time

from . import envknobs


def _default_cache_path() -> str:
    """Per-user verdict cache. A world-shared fixed path would let another
    user's file pin a stale verdict (or hold the name so os.replace fails
    forever); scoping by uid inside XDG_RUNTIME_DIR (itself per-user) or the
    tmpdir avoids both."""
    uid = os.getuid() if hasattr(os, "getuid") else 0
    base = os.environ.get("XDG_RUNTIME_DIR") or tempfile.gettempdir()
    return os.path.join(base, f"opensim-tpu-probe-{uid}")


_PROBE_CACHE = envknobs.raw("OPENSIM_PROBE_CACHE") or _default_cache_path()
_PROBE_TTL_S = 600


def accelerator_reachable(timeout_s: int = 90, fresh: bool = False) -> bool:
    """True when a trivial jax device op completes in a subprocess.

    Note the semantic: "a device op completes", not "a TPU exists" — on a
    CPU-only host the probe succeeds quickly and auto mode proceeds to the
    platform jax picks (where the C++ engine is the default anyway).
    ``fresh=True`` skips the cached verdict (an explicit --backend tpu
    request must not trust a pre-outage "ok" for up to the TTL) but still
    records the new one.
    """
    if not fresh:
        try:
            st = os.stat(_PROBE_CACHE)
            owned = not hasattr(os, "getuid") or st.st_uid == os.getuid()
            if owned and time.time() - st.st_mtime < _PROBE_TTL_S:
                with open(_PROBE_CACHE) as f:
                    return f.read().strip() == "ok"
        except OSError:
            pass
    verdict = False
    try:
        r = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax, jax.numpy as jnp; import numpy; "
                "numpy.asarray(jnp.ones((8,8)).sum()); print('ok')",
            ],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        verdict = r.returncode == 0 and "ok" in r.stdout
    except (OSError, subprocess.TimeoutExpired):
        verdict = False
    try:
        tmp = f"{_PROBE_CACHE}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write("ok" if verdict else "dead")
        os.replace(tmp, _PROBE_CACHE)  # atomic: concurrent readers never see a torn write
    except OSError:
        pass
    return verdict


def ensure_accelerator_or_cpu(timeout_s: int = 90) -> str | None:
    """Probe, and force the host-CPU platform in-process when the
    accelerator is unreachable. Returns a human-readable note on fallback,
    None when the device path is healthy. Call BEFORE any jax device op."""
    if accelerator_reachable(timeout_s):
        return None
    import jax

    jax.config.update("jax_platforms", "cpu")
    return "cpu fallback: accelerator unreachable (axon tunnel down)"
