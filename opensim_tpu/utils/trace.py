"""Latency tracing — parity with the ``k8s.io/utils/trace`` spans the
reference sprinkles through the hot paths: ``Simulate`` traced at a 1 s
threshold (``pkg/simulator/core.go:72-73``), the cluster snapshot at 100 ms
(``simulator.go:511-512``), per-pod scheduling at 100 ms
(``generic_scheduler.go:132-133``). Spans log only when they exceed their
threshold, with step breakdowns.

For device-side profiling the reference exposes pprof on its HTTP server
(``pkg/server/server.go:152``); the analogue here is the JAX profiler —
``start_profiler()`` serves the TensorBoard-compatible trace endpoint.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

log = logging.getLogger("opensim_tpu.trace")


class Trace:
    """Threshold-gated span with sub-steps.

    with Trace("Simulate", threshold_s=1.0) as tr:
        ...
        tr.step("expand workloads")
        ...
    """

    def __init__(self, name: str, threshold_s: float = 1.0) -> None:
        self.name = name
        self.threshold_s = threshold_s
        self.start = 0.0
        self.steps: List[Tuple[str, float]] = []

    def __enter__(self) -> "Trace":
        self.start = time.monotonic()
        return self

    def step(self, msg: str) -> None:
        self.steps.append((msg, time.monotonic()))

    def __exit__(self, *exc) -> None:
        total = time.monotonic() - self.start
        if total < self.threshold_s:
            return
        lines = [f'Trace "{self.name}": total {total * 1000:.0f}ms (threshold {self.threshold_s * 1000:.0f}ms):']
        prev = self.start
        for msg, ts in self.steps:
            lines.append(f"  step {msg}: {(ts - prev) * 1000:.0f}ms")
            prev = ts
        log.warning("\n".join(lines))


_profiler_active = False


def start_profiler(port: int = 9999) -> Optional[int]:
    """Start the JAX profiler server (TensorBoard trace viewer endpoint) —
    the pprof analogue for the XLA side."""
    global _profiler_active
    if _profiler_active:
        return port
    import jax

    jax.profiler.start_server(port)
    _profiler_active = True
    return port
