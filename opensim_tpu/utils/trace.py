"""Latency tracing — parity with the ``k8s.io/utils/trace`` spans the
reference sprinkles through the hot paths: ``Simulate`` traced at a 1 s
threshold (``pkg/simulator/core.go:72-73``), the cluster snapshot at 100 ms
(``simulator.go:511-512``), per-pod scheduling at 100 ms
(``generic_scheduler.go:132-133``). Spans log only when they exceed their
threshold, with step breakdowns.

For device-side profiling the reference exposes pprof on its HTTP server
(``pkg/server/server.go:152``); the analogue here is the JAX profiler —
``start_profiler()`` serves the TensorBoard-compatible trace endpoint.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

log = logging.getLogger("opensim_tpu.trace")


class Trace:
    """Threshold-gated span with sub-steps.

    with Trace("Simulate", threshold_s=1.0) as tr:
        ...
        tr.step("expand workloads")
        ...
    """

    def __init__(self, name: str, threshold_s: float = 1.0) -> None:
        self.name = name
        self.threshold_s = threshold_s
        self.start = 0.0
        self.steps: List[Tuple[str, float]] = []

    def __enter__(self) -> "Trace":
        self.start = time.monotonic()
        return self

    def step(self, msg: str) -> None:
        self.steps.append((msg, time.monotonic()))

    def __exit__(self, *exc) -> None:
        total = time.monotonic() - self.start
        if total < self.threshold_s:
            return
        lines = [f'Trace "{self.name}": total {total * 1000:.0f}ms (threshold {self.threshold_s * 1000:.0f}ms):']
        prev = self.start
        for msg, ts in self.steps:
            lines.append(f"  step {msg}: {(ts - prev) * 1000:.0f}ms")
            prev = ts
        log.warning("\n".join(lines))


class PrepStats:
    """Host-side prepare attribution (incremental-prepare observability).

    Every way a simulation can obtain its ``Prepared`` records here:
      ``full``        — a cold expand+encode of the whole cluster
      ``delta_apps``  — delta re-encode: pods appended to a cached base
      ``delta_nodes`` — delta re-encode: nodes added to a cached base
      ``twin_delta``  — live-twin watch events folded into the warm base
                        (pod insert / drop-mask flip, server/watch.py)
      ``hit``         — encode-cache hit (fingerprint + bind-state restore)

    ``bench.py`` emits these as ``host_prep_s``; the REST server exports
    them as ``simon_prepare_seconds_total``; tests use ``last`` to assert a
    request skipped re-encoding."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        self.seconds: dict = {}
        self.counts: dict = {}
        self.last: Optional[Tuple[str, float]] = None

    def record(self, kind: str, seconds: float) -> None:
        with self._lock:
            self.seconds[kind] = self.seconds.get(kind, 0.0) + seconds
            self.counts[kind] = self.counts.get(kind, 0) + 1
            self.last = (kind, seconds)
        # host-prepare attribution as trace spans (ISSUE 5): every way a
        # simulation obtained its Prepared appears in the request's span
        # tree. No-op (one contextvar read) without an ambient trace.
        from ..obs import trace as _obs

        _obs.record_span(f"prep.{kind}", seconds, kind=kind)

    def total_seconds(self) -> float:
        with self._lock:
            return sum(self.seconds.values())

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "seconds": dict(self.seconds),
                "counts": dict(self.counts),
                "last": self.last,
            }


PREP_STATS = PrepStats()


_profiler_active = False


def start_profiler(port: int = 9999) -> Optional[int]:
    """Start the JAX profiler server (TensorBoard trace viewer endpoint) —
    the pprof analogue for the XLA side."""
    global _profiler_active
    if _profiler_active:
        return port
    import jax

    jax.profiler.start_server(port)
    _profiler_active = True
    return port
