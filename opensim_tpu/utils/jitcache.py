"""JAX persistent compilation cache wiring.

The headline 50k/5k plan spends ~1 s of cold start compiling the scan/
megakernel pipelines; the persistent cache amortizes that across processes
(CI runs, repeated `simon apply` invocations, server restarts).

Opt-in via environment:
  OPENSIM_JIT_CACHE=1        enable at the default dir (~/.cache/opensim-tpu/jit)
  OPENSIM_JIT_CACHE=<path>   enable at <path>
  OPENSIM_JIT_CACHE=0        force-disable (even for callers that default on)

``bench.py`` and test conftest enable it by default (JAX_COMPILATION_CACHE_DIR
wins if already set so existing workflows keep their cache location).
Call ``maybe_enable`` BEFORE the first jax import when possible — the env
var route is the most portable across jax versions; the config.update calls
cover an already-imported jax.
"""

from __future__ import annotations

import os
from typing import Optional

from . import envknobs

DEFAULT_DIR = os.path.join(
    os.path.expanduser(os.environ.get("XDG_CACHE_HOME", "~/.cache")),
    "opensim-tpu",
    "jit",
)

#: the directory maybe_enable() actually activated (None = disabled) —
#: cache_stats() reports it to the compile-telemetry surface (obs/profile)
_ACTIVE_DIR: Optional[str] = None


def cache_stats() -> Optional[dict]:
    """Footprint of the persistent compilation cache directory, or None
    when disabled. O(entries) directory scan — called from debug/metrics
    reads, never the serving hot path."""
    cache_dir = _ACTIVE_DIR or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache_dir or not os.path.isdir(cache_dir):
        return None
    files = total = 0
    try:
        with os.scandir(cache_dir) as it:
            for entry in it:
                try:
                    if entry.is_file():
                        files += 1
                        total += entry.stat().st_size
                except OSError:
                    continue  # entry raced away mid-scan
    except OSError:
        return None
    return {"dir": cache_dir, "files": files, "bytes": total}


def maybe_enable(default: bool = False, path: Optional[str] = None) -> Optional[str]:
    """Enable the persistent compilation cache if opted in.

    Returns the cache directory in effect, or None when disabled. `default`
    is the behavior with OPENSIM_JIT_CACHE unset: benches/CLIs that always
    benefited from a warm cache pass True."""
    raw = envknobs.raw("OPENSIM_JIT_CACHE")
    if raw == "0":
        return None
    if not raw and not default and not path:
        return None
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or (
        raw if raw not in ("", "1") else None
    ) or path or DEFAULT_DIR
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError as e:
        # an unwritable cache dir degrades to cold compiles, it must never
        # fail the caller — but silently eating it hid real misconfiguration
        # (a wrong OPENSIM_JIT_CACHE path looked identical to disabled)
        import logging

        logging.getLogger("opensim_tpu").warning(
            "persistent jit cache disabled: cannot create %s (%s)", cache_dir, e
        )
        return None
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
    global _ACTIVE_DIR
    _ACTIVE_DIR = cache_dir
    try:  # jax may already be imported: set the config knobs directly too
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every compilation, not only the slow ones: the scan pipeline
        # recompiles per (P, N, feature) signature and each one matters
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except (ImportError, AttributeError, ValueError, KeyError):
        pass  # pre-import usage / older jax without the knob: the env var alone is enough
    return cache_dir
