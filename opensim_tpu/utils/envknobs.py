"""Typed registry of every ``OPENSIM_*`` environment knob (ISSUE 12).

The knob surface grew organically to ~45 variables scattered across ~25
modules, each with its own ad-hoc ``os.environ.get`` + parse + default.
That made three things impossible:

- an operator could not discover the surface (``docs/env.md`` is now
  GENERATED from this registry — ``make docs`` / ``python -m
  opensim_tpu.utils.envknobs``);
- a typo'd knob name silently read as unset (every read now routes through
  :func:`raw`, which fails loudly on an UNREGISTERED name — the analogue of
  the metric-family registry in ``obs/metrics.py``);
- nothing type-checked the documented default against the parser (every
  registered validator is exercised against its default by
  tests/test_envknobs.py).

Contract (lint rule OSL1401, ``analysis/rules_env.py``): no module outside
this one reads an ``OPENSIM_*`` variable from ``os.environ`` directly.
Reads go through :func:`raw` (the registered passthrough — call sites keep
their site-specific parse/degrade semantics) or :func:`value` (parse with
the registered validator). Writes (``os.environ["OPENSIM_X"] = ...``) stay
legal — the CLI's ``--backend`` plumbing and tests set knobs for child
code; governance is about undeclared READS.

Error-handling conventions carried by ``on_error`` (and enforced at the
call sites that own the parse):

- ``"raise"`` — an operator typo must surface at startup, not during an
  incident (watch/journal policy, headroom profiles, scan unroll);
- ``"default"`` — debug/observability knobs degrade to the default with a
  warning, never taking down library use (flight recorder, capacity topk).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

__all__ = ["Knob", "KNOBS", "register", "raw", "value", "is_set", "render_markdown"]


@dataclass(frozen=True)
class Knob:
    """One registered environment knob: the name, a human type tag, the
    documented default (raw string form, ``""`` = unset), the doc line that
    becomes its ``docs/env.md`` row, and an optional validator mapping the
    raw string to a parsed value (raising ``ValueError`` on garbage)."""

    name: str
    type: str  # int | float | flag | enum | str | path | spec
    default: str
    doc: str
    validator: Optional[Callable[[str], object]] = None
    choices: Tuple[str, ...] = ()
    on_error: str = "default"  # "default" (warn + fall back) or "raise"
    section: str = "general"


KNOBS: Dict[str, Knob] = {}


def register(knob: Knob) -> Knob:
    if not knob.name.startswith("OPENSIM_"):
        raise ValueError(f"env knob {knob.name!r} must be OPENSIM_-prefixed")
    if knob.name in KNOBS:
        raise ValueError(f"env knob {knob.name!r} registered twice")
    KNOBS[knob.name] = knob
    return knob


def _registered(name: str) -> Knob:
    knob = KNOBS.get(name)
    if knob is None:
        raise KeyError(
            f"env knob {name!r} is not registered in utils/envknobs.py; "
            "register it there (name, type, default, doc) so docs/env.md "
            "and the OSL1401 governance cover it"
        )
    return knob


def raw(name: str, default: str = "") -> str:
    """The ONE read path for ``OPENSIM_*`` variables: ``os.environ.get``
    for a REGISTERED knob. An unregistered name is a programming error —
    the knob ships undocumented and invisible to ``docs/env.md`` — and
    fails loudly here instead. A caller-supplied ``default`` must MATCH
    the registered one (tests/test_envknobs.py sweeps call sites for
    drift) — it exists so sites keep their unset-vs-empty semantics,
    not to fork the documented default."""
    _registered(name)
    return os.environ.get(name, default)


def is_set(name: str) -> bool:
    """Registered-knob presence check (``name in os.environ``)."""
    _registered(name)
    return name in os.environ


def value(name: str):
    """Parse the knob through its registered validator. Unset → the
    default is parsed instead. ``on_error="raise"`` knobs propagate the
    ``ValueError``; ``"default"`` knobs warn and return the parsed
    default (the degrade-don't-crash contract debug knobs follow)."""
    knob = _registered(name)
    if knob.validator is None:
        return raw(name, knob.default)
    text = os.environ.get(name, "")
    if text == "":
        text = knob.default
    try:
        return knob.validator(text)
    except ValueError:
        if knob.on_error == "raise":
            raise
        import logging

        logging.getLogger("opensim_tpu").warning(
            "ignoring unparseable %s=%r (using %r)", name, text, knob.default
        )
        return knob.validator(knob.default)


# ---------------------------------------------------------------------------
# validator combinators
# ---------------------------------------------------------------------------


def _int(lo: Optional[int] = None) -> Callable[[str], int]:
    def parse(text: str) -> int:
        v = int(text)
        if lo is not None and v < lo:
            raise ValueError(f"must be >= {lo}, got {v}")
        return v

    return parse


def _float(lo: Optional[float] = None, exclusive: bool = False) -> Callable[[str], float]:
    def parse(text: str) -> float:
        v = float(text)
        if lo is not None and (v <= lo if exclusive else v < lo):
            raise ValueError(f"must be {'>' if exclusive else '>='} {lo}, got {v}")
        return v

    return parse


def _flag(text: str) -> bool:
    return text.strip().lower() in ("1", "on", "true", "yes")


def _enum(*choices: str) -> Callable[[str], str]:
    def parse(text: str) -> str:
        v = text.strip().lower()
        if v not in choices:
            raise ValueError(f"must be one of {'|'.join(choices)}, got {text!r}")
        return v

    return parse


def _str(text: str) -> str:
    return text


# ---------------------------------------------------------------------------
# the registry — grouped the way docs/env.md renders it
# ---------------------------------------------------------------------------

_ENGINE = [
    Knob("OPENSIM_NATIVE", "flag", "", "`1` forces the C++ scan engine (exact value; `--backend native` sets it).", None, section="engine"),
    Knob("OPENSIM_DISABLE_NATIVE", "flag", "", "Any non-empty value disables the C++ scan engine (pure XLA/Pallas paths only).", None, section="engine"),
    Knob("OPENSIM_DISABLE_FASTPATH", "flag", "", "Any non-empty value disables the Pallas megakernel fast path (`--backend xla` sets it).", None, section="engine"),
    Knob("OPENSIM_FASTPATH", "enum", "", "Megakernel mode override; `interpret` runs the Pallas kernels in interpret mode (CI parity without a TPU).", None, choices=("", "interpret"), section="engine"),
    Knob("OPENSIM_REQUIRE_TPU", "flag", "", "`1` fails hard instead of falling back when the TPU engine cannot run (exact value; `--backend tpu` sets it).", None, section="engine"),
    Knob("OPENSIM_NATIVE_PROFILE", "flag", "", "Any non-empty value enables C++ engine per-stage profiling; populates `native_profile` in bench rows and engine traces.", None, section="engine"),
    Knob("OPENSIM_NATIVE_FORCE_GENERIC", "flag", "", "Disable the C++ engine's incremental cache (read inside scan_engine.cc; parity harness).", _flag, section="engine"),
    Knob("OPENSIM_SCAN_UNROLL", "int", "1", "XLA scan unroll factor (accelerator tuning; resolved outside jit so it keys the jit cache).", _int(lo=1), on_error="raise", section="engine"),
    Knob("OPENSIM_BATCH_ENGINE", "enum", "auto", "Request-axis batch engine: `auto` (C++ scans on accelerator-less hosts, vmapped XLA otherwise), `xla`, or `native`.", _enum("auto", "xla", "native"), on_error="raise", section="engine"),
    Knob("OPENSIM_JIT_CACHE", "spec", "", "Persistent XLA compile cache: `1` = default dir (~/.cache/opensim-tpu/jit), `0` = force off, a path = enable there. bench/CLI default it on.", None, section="engine"),
]

_RESILIENCE = [
    Knob("OPENSIM_REQUEST_TIMEOUT_S", "float", "", "Default per-request deadline in seconds (the `X-Simon-Timeout-S` header wins; unset/0 = unbounded).", None, section="resilience"),
    Knob("OPENSIM_BREAKER_THRESHOLD", "int", "3", "Consecutive engine failures before that engine's circuit breaker opens.", _int(lo=1), on_error="raise", section="resilience"),
    Knob("OPENSIM_BREAKER_COOLDOWN_S", "float", "30", "Seconds an open engine breaker waits before a half-open probe.", _float(lo=0.0), on_error="raise", section="resilience"),
    Knob("OPENSIM_FAULTS", "spec", "", "Deterministic fault injection: `point:count:exc[,point:count:exc...]` (docs/resilience.md fault table).", None, section="resilience"),
    Knob("OPENSIM_SNAPSHOT_TIMEOUT_S", "float", "60", "Per-endpoint timeout for cluster snapshot list calls.", _float(lo=0.0, exclusive=True), on_error="raise", section="resilience"),
    Knob("OPENSIM_SNAPSHOT_RETRIES", "int", "3", "Snapshot fetch attempts before degrading to a stale snapshot / typed 503.", _int(lo=1), on_error="raise", section="resilience"),
    Knob("OPENSIM_SNAPSHOT_BACKOFF_S", "float", "0.1", "Full-jitter backoff base between snapshot fetch retries.", _float(lo=0.0), on_error="raise", section="resilience"),
]

_SERVER = [
    Knob("OPENSIM_ADMISSION", "enum", "on", "`on` routes requests through the admission queue + batcher; `off` restores the single-flight TryLock path.", None, choices=("on", "off"), section="server"),
    Knob("OPENSIM_PREP_CACHE", "flag", "1", "`0` disables the encode cache (per-request full prepare).", None, section="server"),
    Knob("OPENSIM_QUEUE_BOUND", "int", "64", "Admission queue bound; past it requests shed typed 503 + Retry-After.", _int(lo=1), section="server"),
    Knob("OPENSIM_BATCH_WINDOW_MS", "float", "5", "Admission coalescing window in ms, measured from the first waiter.", _float(lo=0.0), section="server"),
    Knob("OPENSIM_BATCH_MAX", "int", "16", "Max requests folded into one batched schedule dispatch.", _int(lo=1), section="server"),
    Knob("OPENSIM_WORKERS", "int", "", "Worker-pool size for unbatchable requests (default: a small CPU-derived bound).", None, section="server"),
    Knob("OPENSIM_WORKERS_MODE", "enum", "auto", "Worker pool mode: `auto`/`thread` (default) or `process` (opt-in fork+probe).", _enum("auto", "thread", "process"), section="server"),
    Knob("OPENSIM_ACCESS_LOG", "flag", "", "`1` emits one JSON access-log line per request on the `opensim_tpu.access` logger (exact value; `--access-log` sets it).", None, section="server"),
    Knob("OPENSIM_WATCH_STALE_S", "float", "30", "No watch event/bookmark for this long → the stream is stale and the twin degrades.", _float(lo=0.0, exclusive=True), on_error="raise", section="server"),
    Knob("OPENSIM_WATCH_RESYNC_S", "float", "300", "Anti-entropy relist-and-diff interval (0 disables).", _float(lo=0.0), on_error="raise", section="server"),
    Knob("OPENSIM_WATCH_RECONNECTS", "int", "5", "Bounded watch reconnect attempts per incident.", _int(lo=1), on_error="raise", section="server"),
    Knob("OPENSIM_WATCH_BACKOFF_S", "float", "0.2", "Full-jitter backoff base between watch reconnects.", _float(lo=0.0), on_error="raise", section="server"),
    Knob("OPENSIM_JOURNAL_FSYNC", "enum", "interval", "Journal fsync policy: `always`, `interval`, or `off`.", _enum("always", "interval", "off"), on_error="raise", section="server"),
    Knob("OPENSIM_JOURNAL_FSYNC_S", "float", "1.0", "Journal `interval` fsync cadence in seconds.", _float(lo=0.0, exclusive=True), on_error="raise", section="server"),
    Knob("OPENSIM_JOURNAL_SEGMENT_MB", "float", "64", "Journal segment rotation size bound in MB.", _float(lo=0.0, exclusive=True), on_error="raise", section="server"),
    Knob("OPENSIM_JOURNAL_CHECKPOINT_EVERY", "int", "4096", "Event records between journal cadence checkpoints.", _int(lo=1), on_error="raise", section="server"),
    Knob("OPENSIM_JOURNAL_KEEP", "int", "2", "Checkpoint segments retained by journal pruning.", _int(lo=1), on_error="raise", section="server"),
    Knob("OPENSIM_JOURNAL_QUEUE", "int", "65536", "Journal writer queue bound; past it records drop (counted) and the next checkpoint re-anchors.", _int(lo=1), on_error="raise", section="server"),
    # multi-process serving fleet (server/fleet.py, docs/serving.md
    # "Scaling past one process")
    Knob("OPENSIM_WORKERS_FLEET", "int", "", "Fleet worker processes for `simon server` (the `--workers` flag wins; unset/0/1 = single process).", None, section="server"),
    Knob("OPENSIM_FLEET_PUBLISH_MS", "float", "50", "Twin-owner publish cadence: how often the owner checks the twin generation and republishes arena deltas over shared memory.", _float(lo=1.0), section="server"),
    Knob("OPENSIM_FLEET_ATTACH_RETRIES", "int", "16", "Seqlock attach retries before a worker declares the publication torn (counted in simon_fleet_attach_retries_exhausted_total).", _int(lo=1), section="server"),
    Knob("OPENSIM_FLEET_ADMIN_PORT", "int", "", "Fleet admin port (aggregated /metrics, /healthz, /api/fleet/status). Default: public port + 1.", None, section="server"),
    Knob("OPENSIM_FLEET_ATTACH", "str", "", "INTERNAL: shared-memory control-block name a fleet worker attaches to (set by the fleet supervisor, never by operators).", None, section="server"),
    Knob("OPENSIM_FLEET_INTERNAL_PORT", "int", "", "INTERNAL: per-worker loopback listener port the fleet supervisor scrapes for /metrics aggregation (set by the supervisor).", None, section="server"),
    # HA control plane (server/fleet.py, docs/serving.md "Surviving owner
    # loss & rolling upgrades")
    Knob("OPENSIM_HA", "flag", "", "`1` enables the HA control plane: the fleet owner holds a fenced lease next to the journal and a `simon server --standby` process tails the journal, ready to take over.", None, section="server"),
    Knob("OPENSIM_HA_LEASE_S", "float", "5", "HA lease duration in seconds: an owner that has not renewed within this window is considered dead and the standby takes over (renewal cadence is a third of it).", _float(lo=0.0, exclusive=True), on_error="raise", section="server"),
    Knob("OPENSIM_HA_TAIL_POLL_MS", "float", "50", "Standby journal tail-follow poll cadence in ms (also the lease-expiry check cadence).", _float(lo=1.0), on_error="raise", section="server"),
    Knob("OPENSIM_HA_HANDOVER_TIMEOUT_S", "float", "30", "Bound on an explicit handover drain (rolling upgrade): past it the requesting standby falls back to lease-expiry takeover.", _float(lo=0.0, exclusive=True), on_error="raise", section="server"),
    Knob("OPENSIM_FLEET_LEASE", "str", "", "INTERNAL: HA lease file path a fleet worker follows to re-resolve the owner's control block after a failover (set by the fleet supervisor, never by operators).", None, section="server"),
    # pipelined admission + priority lanes (server/admission.py,
    # docs/serving.md "Continuous batching & priority lanes")
    Knob("OPENSIM_PIPELINE", "enum", "on", "`on` overlaps batch k+1 host prep with batch k engine dispatch (staged pipeline); `off` restores the serial single-batch-in-flight loop.", None, choices=("on", "off"), section="server"),
    Knob("OPENSIM_PRIORITY_LANES", "enum", "on", "`on` splits the admission queue into interactive/bulk lanes with weighted pickup; `off` restores strict FIFO.", None, choices=("on", "off"), section="server"),
    Knob("OPENSIM_LANE_INTERACTIVE_PODS", "int", "8", "Requests expanding to at most this many pods ride the interactive lane (explain requests always do).", _int(lo=0), section="server"),
    Knob("OPENSIM_LANE_WEIGHT", "int", "4", "Interactive-lane pickups per bulk pickup when both lanes are non-empty (weighted round-robin ratio).", _int(lo=1), section="server"),
    Knob("OPENSIM_LANE_STARVATION_S", "float", "0.5", "Starvation bound: a bulk request waiting longer than this is picked next regardless of lane weight.", _float(lo=0.0), section="server"),
    Knob("OPENSIM_EXPAND_CACHE", "flag", "1", "`0` disables the workload-expansion template cache (per-request full template clone + validation).", None, section="server"),
]

_OBSERVABILITY = [
    Knob("OPENSIM_TRACE", "flag", "1", "`0` disables request tracing (dormant cost: one contextvar read per instrumentation point).", None, section="observability"),
    Knob("OPENSIM_FLIGHT_RECORDER_N", "int", "64", "Flight-recorder ring capacity (last N request traces).", _int(lo=1), section="observability"),
    Knob("OPENSIM_EXPLAIN_STORE_N", "int", "512", "Per-trace cap on stored placement explanations (`?explain=1` audits).", _int(lo=1), section="observability"),
    Knob("OPENSIM_CAPACITY_TOPK", "int", "10", "Per-node series cap for `simon_cluster_node_utilization` (cardinality governor).", _int(lo=0), section="observability"),
    Knob("OPENSIM_CAPACITY_TIMELINE_N", "int", "512", "Capacity timeline ring capacity (generation-keyed samples).", _int(lo=1), section="observability"),
    Knob("OPENSIM_HEADROOM_PROFILES", "spec", "small=500m:1Gi,large=4:8Gi", "Registered headroom probe profiles: `name=cpu:mem[:max_replicas],...` (validated loudly).", None, on_error="raise", section="observability"),
    Knob("OPENSIM_MEM_TICKER_S", "float", "10", "Low-rate memory watermark sampling cadence in seconds (0 disables the ticker).", _float(lo=0.0), section="observability"),
    # time-series ring + SLO engine (obs/timeseries.py, obs/slo.py,
    # docs/observability.md "Watching the fleet")
    Knob("OPENSIM_TS_INTERVAL_S", "float", "5", "Time-series ring sampling cadence: every registered metric family is sampled into the on-disk ring at this interval.", _float(lo=0.0, exclusive=True), on_error="raise", section="observability"),
    Knob("OPENSIM_TS_WINDOWS", "int", "48", "Time-series ring bound: sealed delta-encoded windows kept on disk (oldest evicted first).", _int(lo=2), on_error="raise", section="observability"),
    Knob("OPENSIM_TS_WINDOW_SAMPLES", "int", "60", "Samples per time-series window before it seals to disk (windows × window_samples × interval = retention).", _int(lo=2), on_error="raise", section="observability"),
    Knob("OPENSIM_TS_DIR", "path", "", "Time-series ring directory (persists across restarts and is re-adopted on boot). Default: a private tempdir removed on shutdown.", None, section="observability"),
    Knob("OPENSIM_SLO", "spec", "availability:99.9,latency_p99:99:2.5,freshness:99:30", "Declarative SLOs: `name:target_pct[:threshold_s],...` with kinds availability/latency_p99/freshness (validated loudly).", None, on_error="raise", section="observability"),
    Knob("OPENSIM_SLO_WINDOWS", "spec", "5m,1h", "SLO burn-rate evaluation windows: `<number><s|m|h|d>,...` (multi-window burn-rate alerting).", None, on_error="raise", section="observability"),
]

_PLANNER = [
    Knob("OPENSIM_CAMPAIGN_EXEC", "enum", "warm", "Campaign execution mode (docs/campaigns.md): `warm` = one full prepare + prepcache deltas; `cold` = per-step full prepare (the verification mode the delta-equality gate compares against).", _enum("warm", "cold"), on_error="raise", section="planner"),
    Knob("OPENSIM_CAMPAIGN_MAX_STEPS", "int", "256", "Campaign spec safety bound: specs with more steps are rejected at parse time.", _int(lo=1), on_error="raise", section="planner"),
    Knob("OPENSIM_CAMPAIGN_MAX_WAVES", "int", "64", "Drain-wave runaway bound: cordon/evict/reschedule passes per drain step (blocked-eviction retries included).", _int(lo=1), on_error="raise", section="planner"),
]

_DEBUG = [
    Knob("OPENSIM_LOCKWATCH", "flag", "", "`1`/`on`/`true` enables the runtime lock-order sanitizer (`make tsan` arms it in-process).", _flag, section="debug"),
    Knob("OPENSIM_LOCKWATCH_HOLD_MS", "float", "500", "Lockwatch hold-time outlier threshold in ms (floor 1; a typo degrades to the default with a warning).", _float(lo=1.0), section="debug"),
    Knob("OPENSIM_LOCKWATCH_HOLD_EXEMPT", "spec", "", "Comma-separated site substrings exempt from lockwatch hold-time checks (inversions are never exempt).", None, section="debug"),
    Knob("OPENSIM_NO_PROGRESS", "flag", "", "Any non-empty value suppresses interactive progress spinners.", None, section="debug"),
    Knob("OPENSIM_PROBE_CACHE", "path", "", "Accelerator-probe verdict cache file (default: under XDG_RUNTIME_DIR/tmp).", None, section="debug"),
]

for _knob in _ENGINE + _RESILIENCE + _SERVER + _OBSERVABILITY + _PLANNER + _DEBUG:
    register(_knob)


# ---------------------------------------------------------------------------
# docs generation (docs/env.md)
# ---------------------------------------------------------------------------

_SECTIONS = (
    ("engine", "Engine selection & tuning"),
    ("resilience", "Resilience (deadlines, breakers, faults, snapshot retry)"),
    ("server", "Serving (admission, workers, live twin, journal)"),
    ("observability", "Observability (tracing, capacity, memory)"),
    ("planner", "Planner (campaigns)"),
    ("debug", "Debug & development"),
)


def render_markdown() -> str:
    """The generated ``docs/env.md`` body — one table row per registered
    knob, grouped by section. Regenerate with ``make docs`` (sync is gated
    by tests/test_envknobs.py)."""
    lines = [
        "# Environment knobs",
        "",
        "Every `OPENSIM_*` variable the system reads, generated from the",
        "typed registry in `opensim_tpu/utils/envknobs.py` (`make docs`).",
        "Do not edit by hand. Raw `os.environ` reads of `OPENSIM_*` outside",
        "the registry are banned by lint rule OSL1401",
        "(docs/static-analysis.md).",
        "",
    ]
    for section, title in _SECTIONS:
        knobs = sorted((k for k in KNOBS.values() if k.section == section), key=lambda k: k.name)
        if not knobs:
            continue
        lines += [f"## {title}", "", "| Knob | Type | Default | Description |", "|---|---|---|---|"]
        for k in knobs:
            default = f"`{k.default}`" if k.default != "" else "unset"
            kind = k.type if not k.choices else "enum"
            lines.append(f"| `{k.name}` | {kind} | {default} | {k.doc} |")
        lines.append("")
    return "\n".join(lines)


def main() -> int:
    import sys

    sys.stdout.write(render_markdown())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
