"""Pause the cyclic GC across bulk allocate-and-retain phases.

Workload expansion materializes ~10 container objects per pod and RETAINS
them all, so the generational collector re-scans a monotonically growing
heap several times per plan — and jax registers a gc callback that makes
every collection pricier still. Measured at the 50k-pod headline shape:
expansion drops 0.94 s → 0.22 s with collection paused (the objects are
acyclic; nothing is freed mid-phase anyway, so pausing loses nothing —
CPython's refcounting still reclaims all non-cyclic garbage immediately).
"""

from __future__ import annotations

import gc
from contextlib import contextmanager


@contextmanager
def gc_paused():
    """Disable cyclic collection for the duration; nestable and exception
    safe. No-op when collection is already disabled (outer pause wins)."""
    if not gc.isenabled():
        yield
        return
    gc.disable()
    try:
        yield
    finally:
        gc.enable()
