"""Workload → pod expansion and pod sanitization (controller emulation).

Reference parity: ``pkg/utils/utils.go`` —
``MakeValidPodsByDeployment``/``ByReplicaSet`` (:132-171),
``MakeValidPodByCronJob``/``ByJob`` (:173-217), ``MakeValidPodsByStatefulSet``
(:219-292), ``MakeValidPodsByDaemonset`` (:325-351 via daemon predicates),
``MakeValidPod`` sanitization (:378-463), ``NewFakeNodes`` (:885-901), and
``GenerateValidPodsFromAppResources`` (``pkg/simulator/utils.go:37``).
"""

from __future__ import annotations

import collections
import copy
import json
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

import yaml

from . import selectors
from .quantity import parse_quantity
from .objects import (
    ANNO_NODE_LOCAL_STORAGE,
    ANNO_POD_LOCAL_STORAGE,
    ANNO_WORKLOAD_KIND,
    ANNO_WORKLOAD_NAME,
    ANNO_WORKLOAD_NAMESPACE,
    DEFAULT_SCHEDULER_NAME,
    LABEL_HOSTNAME,
    LABEL_NEW_NODE,
    Node,
    ObjectMeta,
    OwnerReference,
    Pod,
    ResourceTypes,
    Workload,
    _rand_suffix,
    new_uid,
    object_from_dict,
)

# Storage-class names recognized for local storage — pkg/utils/const.go:4-16.
SC_LVM = {"open-local-lvm", "yoda-lvm-default"}
SC_DEVICE_SSD = {"open-local-device-ssd", "open-local-mountpoint-ssd", "yoda-mountpoint-ssd", "yoda-device-ssd"}
SC_DEVICE_HDD = {"open-local-device-hdd", "open-local-mountpoint-hdd", "yoda-mountpoint-hdd", "yoda-device-hdd"}
LOCAL_SC_NAMES = SC_LVM | SC_DEVICE_SSD | SC_DEVICE_HDD


class InvalidPodError(ValueError):
    pass


def _clone_jsonish(x):
    """Deep copy for yaml-shaped data without copy.deepcopy's memo
    machinery (3-5× faster on pod dicts); unknown types (e.g. yaml
    datetimes) fall back to deepcopy."""
    t = type(x)
    if t is dict:
        return {k: _clone_jsonish(v) for k, v in x.items()}
    if t is list:
        return [_clone_jsonish(v) for v in x]
    if t in (str, int, float, bool, type(None)):
        return x
    return copy.deepcopy(x)


def make_valid_pod(pod: Pod) -> Pod:
    """Sanitize a pod the way ``MakeValidPod`` (pkg/utils/utils.go:378-463)
    does: default namespace / DNS policy / restart policy / scheduler name,
    strip env/mounts/probes, PVC volumes → hostPath, reset status; then run
    basic validation.

    The copy is structured, not copy.deepcopy (a live-cluster replay
    sanitizes tens of thousands of snapshot pods per plan): fresh
    metadata, shallow spec — spec internals are treated as immutable after
    sanitization, the same invariant ``_fast_clone`` relies on (the PVC
    rewrite below replaces ``spec.volumes`` wholesale rather than mutating
    it) — and a json-ish clone of ``raw``."""
    pm = pod.metadata
    meta = object.__new__(ObjectMeta)
    meta.__dict__ = {
        "name": pm.name,
        "namespace": pm.namespace,
        "labels": dict(pm.labels) if pm.labels else {},
        "annotations": dict(pm.annotations) if pm.annotations else {},
        "uid": pm.uid,
        "generate_name": pm.generate_name,
        "owner_references": list(pm.owner_references),
    }
    spec = object.__new__(type(pod.spec))
    spec.__dict__ = pod.spec.__dict__.copy()
    p = object.__new__(type(pod))
    p.__dict__ = {
        "metadata": meta,
        "spec": spec,
        "phase": pod.phase,
        "raw": _clone_jsonish(pod.raw),
    }
    if p.metadata.namespace == "":
        p.metadata.namespace = "default"
        if p.raw:
            p.raw.setdefault("metadata", {})["namespace"] = "default"
    if p.metadata.labels is None:
        p.metadata.labels = {}
    if p.metadata.annotations is None:
        p.metadata.annotations = {}
    if p.spec.scheduler_name == "":
        p.spec.scheduler_name = DEFAULT_SCHEDULER_NAME
    # Raw-dict sanitization for round-tripping (p.raw was already deep-copied
    # with the pod above; mutate in place).
    if p.raw:
        raw = p.raw
        spec = raw.setdefault("spec", {})
        spec.setdefault("dnsPolicy", "ClusterFirst")
        spec.setdefault("restartPolicy", "Always")
        spec.setdefault("schedulerName", DEFAULT_SCHEDULER_NAME)
        spec.pop("imagePullSecrets", None)
        for clist in ("containers", "initContainers"):
            for c in spec.get(clist) or []:
                c.setdefault("terminationMessagePolicy", "FallbackToLogsOnError")
                c.setdefault("imagePullPolicy", "IfNotPresent")
                if (c.get("securityContext") or {}).get("privileged") is not None:
                    c["securityContext"]["privileged"] = False
                c.pop("volumeMounts", None)
                c.pop("env", None)
                c.pop("livenessProbe", None)
                c.pop("readinessProbe", None)
                c.pop("startupProbe", None)
        for v in spec.get("volumes") or []:
            if "persistentVolumeClaim" in v:
                v["hostPath"] = {"path": "/tmp"}
                v.pop("persistentVolumeClaim", None)
        raw["status"] = {}
        # PVC volumes were rewritten; keep the parsed view in sync.
        p.spec.volumes = copy.deepcopy(spec.get("volumes") or [])
    _validate_pod(p)
    return p


def _validate_pod(pod: Pod) -> None:
    """Small subset of ValidatePodCreate (pkg/utils/utils.go:495-508): the
    checks that can actually fire on simulator inputs."""
    if not pod.metadata.name and not pod.metadata.generate_name:
        raise InvalidPodError("pod has no name")
    if not pod.spec.containers:
        raise InvalidPodError(f"pod {pod.metadata.name} has no containers")
    for t in pod.spec.tolerations:
        if t.operator == "Exists" and t.value:
            raise InvalidPodError(
                f"pod {pod.metadata.name}: toleration value must be empty when operator is Exists"
            )
    for res, v in pod.resource_requests().items():
        if v < 0:
            raise InvalidPodError(f"pod {pod.metadata.name}: negative request {res}")


def _pod_from_template(owner: Workload, controller_kind: str) -> Pod:
    """Build a pod from a workload's template with owner metadata — parity
    with SetObjectMetaFromObject (pkg/utils/utils.go:297-323)."""
    if not owner.metadata.uid:
        owner.metadata.uid = new_uid()
    raw = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {},
        "spec": copy.deepcopy(owner.template_raw.get("spec") or {}),
    }
    pod = Pod.from_dict(raw)
    pod.spec = copy.deepcopy(owner.template_spec)
    pod.metadata = ObjectMeta(
        name=f"{owner.metadata.name}-{_rand_suffix()}",
        namespace=owner.metadata.namespace,
        labels=dict(owner.template_metadata.labels),
        annotations=dict(owner.template_metadata.annotations),
        uid=new_uid(),
        generate_name=owner.metadata.name,
        owner_references=[
            OwnerReference(
                kind=controller_kind,
                name=owner.metadata.name,
                uid=owner.metadata.uid,
                api_version="apps/v1" if controller_kind in ("ReplicaSet", "StatefulSet", "DaemonSet") else "batch/v1",
            )
        ],
    )
    raw["metadata"] = pod.metadata.to_dict()
    pod.raw = raw
    return pod


def _add_workload_info(pod: Pod, kind: str, name: str, namespace: str) -> Pod:
    pod.metadata.annotations[ANNO_WORKLOAD_KIND] = kind
    pod.metadata.annotations[ANNO_WORKLOAD_NAME] = name
    pod.metadata.annotations[ANNO_WORKLOAD_NAMESPACE] = namespace
    return pod


def _fast_clone(proto: Pod, name: str) -> Pod:
    """Cheap replica of a sanitized prototype pod: fresh metadata, shared
    (immutable after sanitization) spec internals. Replica expansion is the
    host-side hot path at 50k-pod scale — one deepcopy per workload, not
    per pod, and the per-clone objects are built via ``object.__new__`` to
    skip dataclass default processing (measured ~2× on this path)."""
    from .objects import ObjectMeta, Pod as PodCls

    pm = proto.metadata
    uid = new_uid()
    # direct __dict__ assignment from literals: ~30% faster than
    # update(**kwargs) on this 50k-calls/plan path (no kwargs dict, no
    # per-key update loop)
    meta = object.__new__(ObjectMeta)
    meta.__dict__ = {
        "name": name,
        "namespace": pm.namespace,
        "labels": dict(pm.labels),
        "annotations": dict(pm.annotations),
        "uid": uid,
        "generate_name": pm.generate_name,
        "owner_references": list(pm.owner_references),
    }
    # cheap shallow spec copy (node_name is set per pod at bind decode;
    # nested lists stay shared and immutable post-sanitization)
    spec = object.__new__(type(proto.spec))
    spec.__dict__ = proto.spec.__dict__.copy()
    pod = object.__new__(PodCls)
    pod.__dict__ = {
        "metadata": meta,
        "spec": spec,
        "phase": proto.phase,
        "raw": {**proto.raw, "metadata": {"name": name, "namespace": pm.namespace, "uid": uid}}
        if proto.raw
        else {},
    }
    return pod


# ---------------------------------------------------------------------------
# Workload-expansion proto cache (ISSUE 16): repeated workload SHAPES skip
# the template deepcopy + sanitization + validation entirely.
# ---------------------------------------------------------------------------

#: sentinel substituted for the workload's own name inside the content key
_NAME_PH = "\x00workload-name\x00"
_PROTO_CACHE_CAP = 256

_cache_lock = threading.Lock()
_proto_cache: "collections.OrderedDict[str, dict]" = collections.OrderedDict()  # guarded-by: _cache_lock
_cache_stats = {"hits": 0, "misses": 0}  # guarded-by: _cache_lock


def _expand_cache_on() -> bool:
    from ..utils import envknobs

    return envknobs.raw("OPENSIM_EXPAND_CACHE", "1").strip().lower() not in (
        "0", "off", "false",
    )


def expand_cache_stats() -> Dict[str, int]:
    with _cache_lock:
        return dict(_cache_stats, entries=len(_proto_cache))


def expand_cache_clear() -> None:
    with _cache_lock:
        _proto_cache.clear()
        _cache_stats["hits"] = 0
        _cache_stats["misses"] = 0


def _content_key(kind: str, w: Workload) -> Optional[str]:
    """Canonical content key for a workload's expansion, with the
    workload's OWN NAME normalized to a placeholder exactly where
    materialization knows how to rewrite it (template metadata values and
    the owner-name chain). The raw template spec is keyed UNNORMALIZED: a
    name embedded inside the spec simply keys a distinct entry — never a
    false share — so hits are guaranteed rewrite-complete.

    The PARSED ``template_spec`` is keyed alongside the raw dict because
    the two can diverge: callers may mutate the parsed object after
    ``from_dict`` (``w.template_spec.scheduler_name = "packer"`` is how
    tests select a profile), and the proto pod is built from the parsed
    object — keying raw alone would hand such a workload another
    workload's unmutated expansion."""
    raw_spec = (w.template_raw or {}).get("spec")
    if not raw_spec:
        # a hand-built Workload without raw provenance cannot be keyed on
        # content (template_spec may not round-trip) — bypass the cache
        return None
    nm = w.metadata.name

    def norm(d: Dict[str, str]) -> Dict[str, str]:
        return {
            k: _NAME_PH if isinstance(v, str) and v == nm else v
            for k, v in (d or {}).items()
        }

    try:
        return json.dumps(
            {
                "kind": kind,
                "ns": w.metadata.namespace,
                "labels": norm(w.template_metadata.labels),
                "annotations": norm(w.template_metadata.annotations),
                "spec": raw_spec,
                "pspec": repr(w.template_spec),
                "vct": w.volume_claim_templates if kind == "StatefulSet" else None,
            },
            sort_keys=True,
            default=str,
        )
    except (TypeError, ValueError):
        return None


def _name_chain(kind: str, nm: str) -> List[str]:
    """The fresh owner-name chain a cache hit regenerates — the same
    shapes the uncached expansions build (rand suffixes per expansion, so
    names never repeat across requests; STS ordinals stay deterministic)."""
    if kind == "Deployment":
        rs = f"{nm}-{_rand_suffix()}"
        return [nm, rs, f"{rs}-{_rand_suffix()}"]
    if kind == "CronJob":
        job = f"{nm}-{_rand_suffix()}"
        return [nm, job, f"{job}-{_rand_suffix()}"]
    if kind == "StatefulSet":
        return [nm, f"{nm}-0"]
    return [nm, f"{nm}-{_rand_suffix()}"]


def _chain_from_proto(kind: str, w: Workload, proto: Pod) -> List[str]:
    """Recover the built proto's owner-name chain (the strings a hit must
    substitute): the intermediate owner's name IS the proto's
    generate_name for every chained kind."""
    if kind in ("Deployment", "CronJob"):
        return [w.metadata.name, proto.metadata.generate_name, proto.metadata.name]
    return [w.metadata.name, proto.metadata.name]


def _materialize(entry: dict, w: Workload, n: int) -> List[Pod]:
    """Copy-on-write expansion from a cached proto: fresh metadata with the
    old name chain substituted (exact string matches only — the key
    guarantees nothing else differs), fresh uids and rand suffixes, shared
    immutable spec internals (the ``_fast_clone`` invariant)."""
    proto: Pod = entry["proto"]
    kind: str = entry["kind"]
    old_chain: List[str] = entry["chain"]
    new_chain = _name_chain(kind, w.metadata.name)
    sub = {o: nw for o, nw in zip(old_chain, new_chain) if o != nw}

    def s(v):
        return sub.get(v, v) if isinstance(v, str) else v

    pm = proto.metadata
    # unchained kinds: the head's owner IS the workload (real uid); chained
    # kinds own the head via a synthesized intermediate whose uid is fresh
    # on the uncached path too
    owner_uid = (w.metadata.uid or new_uid()) if len(new_chain) == 2 else new_uid()
    meta = object.__new__(ObjectMeta)
    meta.__dict__ = {
        "name": new_chain[-1],
        "namespace": pm.namespace,
        "labels": {k: s(v) for k, v in pm.labels.items()},
        "annotations": {k: s(v) for k, v in pm.annotations.items()},
        "uid": new_uid(),
        "generate_name": s(pm.generate_name),
        "owner_references": [
            OwnerReference(
                kind=r.kind, name=s(r.name), uid=owner_uid,
                api_version=r.api_version, controller=r.controller,
            )
            for r in pm.owner_references
        ],
    }
    spec = object.__new__(type(proto.spec))
    spec.__dict__ = proto.spec.__dict__.copy()
    head = object.__new__(type(proto))
    head.__dict__ = {
        "metadata": meta,
        "spec": spec,
        "phase": proto.phase,
        "raw": {**proto.raw, "metadata": meta.to_dict()} if proto.raw else {},
    }
    pods = [head]
    if kind == "StatefulSet":
        for ordinal in range(1, n):
            pods.append(_fast_clone(head, f"{w.metadata.name}-{ordinal}"))
    else:
        clone_base = new_chain[-2]
        for _ in range(n - 1):
            pods.append(_fast_clone(head, f"{clone_base}-{_rand_suffix()}"))
    return pods


def _expand_cached(
    kind: str, w: Workload, n: int, build: Callable[[], List[Pod]]
) -> List[Pod]:
    """Content-keyed expansion: a hit materializes from the cached proto;
    a miss builds normally and caches a CLEAN copy of the proto (the
    returned pods get bind-mutated by decode — the cached copy must stay
    pristine)."""
    if not _expand_cache_on():
        return build()
    key = _content_key(kind, w)
    if key is None:
        return build()
    with _cache_lock:
        entry = _proto_cache.get(key)
        if entry is not None:
            _proto_cache.move_to_end(key)
            _cache_stats["hits"] += 1
    if entry is not None:
        return _materialize(entry, w, n)
    pods = build()
    if pods:
        proto = pods[0]
        entry = {
            "kind": kind,
            "chain": _chain_from_proto(kind, w, proto),
            # _fast_clone gives the pristine copy: fresh metadata dicts +
            # shallow spec (scalar bind fields live in the fresh __dict__,
            # nested internals immutable post-sanitization); generate_name
            # and owner names carry the chain for substitution
            "proto": _fast_clone(proto, proto.metadata.name),
        }
        with _cache_lock:
            _cache_stats["misses"] += 1
            _proto_cache[key] = entry
            _proto_cache.move_to_end(key)
            while len(_proto_cache) > _PROTO_CACHE_CAP:
                _proto_cache.popitem(last=False)
    return pods


def pods_from_replica_set(rs: Workload, _cache: bool = True) -> List[Pod]:
    n = max(rs.replicas, 0)
    if n == 0:
        return []

    def build() -> List[Pod]:
        proto = make_valid_pod(_pod_from_template(rs, "ReplicaSet"))
        proto = _add_workload_info(proto, "ReplicaSet", rs.metadata.name, rs.metadata.namespace)
        pods = [proto]
        for _ in range(n - 1):
            pods.append(_fast_clone(proto, f"{rs.metadata.name}-{_rand_suffix()}"))
        return pods

    if not _cache:
        return build()
    return _expand_cached("ReplicaSet", rs, n, build)


def pods_from_deployment(deploy: Workload) -> List[Pod]:
    """Deployment → generated ReplicaSet → pods. The generated RS keeps the
    deployment's name (reference: generateReplicaSetFromDeployment names the
    RS via SetObjectMetaFromObject → '<deploy>-<rand>'). Cached at THIS
    level (not the synthesized RS): the RS name embeds a fresh rand suffix
    per expansion, so only the deployment's own content is a stable key."""
    n = max(deploy.replicas, 0)
    if n == 0:
        return []

    def build() -> List[Pod]:
        rs = Workload(
            kind="ReplicaSet",
            metadata=ObjectMeta(
                name=f"{deploy.metadata.name}-{_rand_suffix()}",
                namespace=deploy.metadata.namespace,
                labels=dict(deploy.template_metadata.labels),
                annotations=dict(deploy.template_metadata.annotations),
                uid=new_uid(),
                generate_name=deploy.metadata.name,
                owner_references=[
                    OwnerReference(kind="Deployment", name=deploy.metadata.name, uid=deploy.metadata.uid or new_uid(), api_version="apps/v1")
                ],
            ),
            replicas=deploy.replicas,
            selector=deploy.selector,
            template_metadata=deploy.template_metadata,
            template_spec=deploy.template_spec,
            template_raw=deploy.template_raw,
        )
        return pods_from_replica_set(rs, _cache=False)

    return _expand_cached("Deployment", deploy, n, build)


def pods_from_job(job: Workload, _cache: bool = True) -> List[Pod]:
    n = max(job.replicas, 0)
    if n == 0:
        return []

    def build() -> List[Pod]:
        proto = make_valid_pod(_pod_from_template(job, "Job"))
        proto = _add_workload_info(proto, "Job", job.metadata.name, job.metadata.namespace)
        pods = [proto]
        for _ in range(n - 1):
            pods.append(_fast_clone(proto, f"{job.metadata.name}-{_rand_suffix()}"))
        return pods

    if not _cache:
        return build()
    return _expand_cached("Job", job, n, build)


def pods_from_cron_job(cj: Workload) -> List[Pod]:
    """CronJob → one manual Job instantiation → pods (reference:
    generateJobFromCronJob, pkg/utils/utils.go:204-217)."""
    n = max(cj.replicas, 0)
    if n == 0:
        return []

    def build() -> List[Pod]:
        job = Workload(
            kind="Job",
            metadata=ObjectMeta(
                name=f"{cj.metadata.name}-{_rand_suffix()}",
                namespace=cj.metadata.namespace,
                annotations={"cronjob.kubernetes.io/instantiate": "manual", **cj.template_metadata.annotations},
                labels=dict(cj.template_metadata.labels),
                uid=new_uid(),
                generate_name=cj.metadata.name,
            ),
            replicas=cj.replicas,
            template_metadata=cj.template_metadata,
            template_spec=cj.template_spec,
            template_raw=cj.template_raw,
        )
        return pods_from_job(job, _cache=False)

    return _expand_cached("CronJob", cj, n, build)


def pods_from_stateful_set(sts: Workload) -> List[Pod]:
    """StatefulSet → ordinal-named pods + local-storage volume annotation
    (pkg/utils/utils.go:219-292)."""
    n = max(sts.replicas, 0)
    if n == 0:
        return []

    def build() -> List[Pod]:
        proto = _pod_from_template(sts, "StatefulSet")
        proto.metadata.name = f"{sts.metadata.name}-0"
        if proto.raw:
            proto.raw["metadata"]["name"] = proto.metadata.name
        proto = make_valid_pod(proto)
        proto = _add_workload_info(proto, "StatefulSet", sts.metadata.name, sts.metadata.namespace)
        pods = [proto]
        for ordinal in range(1, n):
            pods.append(_fast_clone(proto, f"{sts.metadata.name}-{ordinal}"))
        _set_storage_annotation(pods, sts.volume_claim_templates)
        return pods

    return _expand_cached("StatefulSet", sts, n, build)


def _set_storage_annotation(pods: List[Pod], volume_claim_templates: List[dict]) -> None:
    """simon/pod-local-storage annotation from volumeClaimTemplates —
    SetStorageAnnotationOnPods (pkg/utils/utils.go:247-292)."""
    volumes = []
    for pvc in volume_claim_templates:
        sc = (pvc.get("spec") or {}).get("storageClassName")
        if sc is None:
            continue
        resources = (pvc.get("spec") or {}).get("resources") or {}
        # GetPVCRequested falls back to limits when requests.storage is absent
        size = (resources.get("requests") or {}).get("storage")
        if size is None:
            size = (resources.get("limits") or {}).get("storage", 0)
        size_b = int(parse_quantity(size))
        if sc in SC_LVM:
            kind = "LVM"
        elif sc in SC_DEVICE_SSD:
            kind = "SSD"
        elif sc in SC_DEVICE_HDD:
            kind = "HDD"
        else:
            continue  # unsupported storage class (reference logs an error)
        volumes.append({"size": str(size_b), "kind": kind, "scName": sc})
    if not volumes:
        return
    payload = json.dumps({"volumes": volumes})
    for pod in pods:
        pod.metadata.annotations[ANNO_POD_LOCAL_STORAGE] = payload


def _daemon_pod_for_node(ds: Workload, node_name: str) -> Pod:
    """DaemonSet pod pinned to a node via required node affinity on
    metadata.name — SetDaemonSetPodNodeNameByNodeAffinity semantics."""
    pod = _pod_from_template(ds, "DaemonSet")
    aff = copy.deepcopy(pod.spec.affinity) or {}
    node_aff = aff.setdefault("nodeAffinity", {})
    required = node_aff.setdefault("requiredDuringSchedulingIgnoredDuringExecution", {})
    pin_field = {"key": "metadata.name", "operator": "In", "values": [node_name]}
    terms = required.get("nodeSelectorTerms") or []
    if terms:
        for t in terms:
            t.setdefault("matchFields", []).append(copy.deepcopy(pin_field))
    else:
        terms = [{"matchFields": [pin_field]}]
    required["nodeSelectorTerms"] = terms
    pod.spec.affinity = aff
    if pod.raw is not None:
        pod.raw.setdefault("spec", {})["affinity"] = copy.deepcopy(aff)
    return pod


def pods_from_daemon_set(ds: Workload, nodes: List[Node]) -> List[Pod]:
    """One pod per eligible node (MakeValidPodsByDaemonset,
    pkg/utils/utils.go:337-351)."""
    pods = []
    for node in nodes:
        pod = _daemon_pod_for_node(ds, node.metadata.name)
        if not selectors.node_should_run_pod(node, pod):
            continue
        pod = make_valid_pod(pod)
        pods.append(_add_workload_info(pod, "DaemonSet", ds.metadata.name, ds.metadata.namespace))
    return pods


def generate_pods_from_resources(
    resources: ResourceTypes, nodes: Optional[List[Node]] = None, include_daemon_sets: bool = True
) -> List[Pod]:
    """Expand every workload in a ResourceTypes into schedulable pods —
    GenerateValidPodsFromAppResources / GetValidPodExcludeDaemonSet
    (pkg/simulator/utils.go:37-230). Bare pods are sanitized too. DaemonSet
    pods are expanded per eligible node when `nodes` is given."""
    pods: List[Pod] = []
    for p in resources.pods:
        pods.append(make_valid_pod(p))
    for d in resources.deployments:
        pods.extend(pods_from_deployment(d))
    deploy_keys = {(d.metadata.namespace, d.metadata.name) for d in resources.deployments}
    for rs in resources.replica_sets:
        # Skip replica sets whose owning deployment is in the input (the
        # deployment expands them); orphan RS snapshots still expand.
        if any(
            r.kind == "Deployment" and (rs.metadata.namespace, r.name) in deploy_keys
            for r in rs.metadata.owner_references
        ):
            continue
        pods.extend(pods_from_replica_set(rs))
    for sts in resources.stateful_sets:
        pods.extend(pods_from_stateful_set(sts))
    cron_keys = {(c.metadata.namespace, c.metadata.name) for c in resources.cron_jobs}
    for job in resources.jobs:
        if any(
            r.kind == "CronJob" and (job.metadata.namespace, r.name) in cron_keys
            for r in job.metadata.owner_references
        ):
            continue
        pods.extend(pods_from_job(job))
    for cj in resources.cron_jobs:
        pods.extend(pods_from_cron_job(cj))
    if include_daemon_sets:
        for ds in resources.daemon_sets:
            pods.extend(pods_from_daemon_set(ds, nodes if nodes is not None else resources.nodes))
    return pods


# ---------------------------------------------------------------------------
# YAML ingestion.
# ---------------------------------------------------------------------------

def yaml_files_in_dir(path: str) -> List[str]:
    """File paths under a dir (or the file itself), sorted — ParseFilePath
    (pkg/utils/utils.go:43-79)."""
    if os.path.isfile(path):
        return [path]
    out = []
    for root, _dirs, files in os.walk(path):
        for f in files:
            out.append(os.path.join(root, f))
    return sorted(out)


def load_yaml_objects(path: str) -> List[dict]:
    """All YAML documents in a file or directory (ignores non-YAML)."""
    docs: List[dict] = []
    for fp in yaml_files_in_dir(path):
        if not fp.endswith((".yaml", ".yml")):
            continue
        with open(fp) as f:
            for doc in yaml.safe_load_all(f):
                if isinstance(doc, dict):
                    docs.append(doc)
    return docs


def decode_yaml_strings(contents: List[str]) -> List[dict]:
    docs: List[dict] = []
    for s in contents:
        for doc in yaml.safe_load_all(s):
            if isinstance(doc, dict):
                docs.append(doc)
    return docs


def resources_from_dicts(docs: List[dict]) -> Tuple[ResourceTypes, List[str]]:
    """Typed decode of YAML docs into ResourceTypes; returns the list of
    skipped kinds (reference errors on unsupported kinds; we record them)."""
    rt = ResourceTypes()
    skipped = []
    for d in docs:
        obj = object_from_dict(d)
        if obj is None or not rt.add(obj):
            skipped.append(str(d.get("kind", "?")))
    return rt, skipped


def load_cluster_from_dir(path: str) -> ResourceTypes:
    """CreateClusterResourceFromClusterConfig (pkg/simulator/simulator.go:604-619):
    read a cluster yaml dir, and attach node-local-storage JSON annotations from
    sibling .json files named after nodes (MatchAndSetLocalStorageAnnotationOnNode,
    pkg/simulator/utils.go:385-401)."""
    rt, _ = resources_from_dicts(load_yaml_objects(path))
    storage_info: Dict[str, str] = {}
    for fp in yaml_files_in_dir(path):
        if fp.endswith(".json"):
            name = os.path.splitext(os.path.basename(fp))[0]
            with open(fp) as f:
                storage_info[name] = f.read()
    for node in rt.nodes:
        if node.metadata.name in storage_info:
            node.metadata.annotations[ANNO_NODE_LOCAL_STORAGE] = storage_info[node.metadata.name]
    return rt


# ---------------------------------------------------------------------------
# Fake node fabrication — NewFakeNodes (pkg/utils/utils.go:885-901).
# ---------------------------------------------------------------------------

def new_fake_nodes(template: Node, count: int) -> List[Node]:
    nodes = []
    for _ in range(count):
        node = copy.deepcopy(template)
        name = f"simon-{_rand_suffix(8)}"
        node.metadata.name = name
        node.metadata.uid = new_uid()
        node.metadata.labels = dict(node.metadata.labels)
        node.metadata.labels[LABEL_HOSTNAME] = name
        node.metadata.labels[LABEL_NEW_NODE] = ""
        if node.raw:
            node.raw.setdefault("metadata", {})["name"] = name
            node.raw["metadata"].setdefault("labels", {}).update(node.metadata.labels)
        nodes.append(node)
    return nodes
