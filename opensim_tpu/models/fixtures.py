"""Test fixture builders — parity with ``pkg/test`` (MakeFakePod/Node/... with
functional ``With*`` options, e.g. ``pkg/test/node.go:15-40``,
``pkg/test/pod.go:13-47``)."""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional

from .objects import (
    ANNO_NODE_LOCAL_STORAGE,
    ANNO_POD_LOCAL_STORAGE,
    Node,
    Pod,
    Workload,
    object_from_dict,
)

Option = Callable[[dict], None]


# -- pod/template options ----------------------------------------------------

def with_labels(labels: Dict[str, str]) -> Option:
    def apply(d: dict) -> None:
        d.setdefault("metadata", {}).setdefault("labels", {}).update(labels)

    return apply


def with_annotations(annotations: Dict[str, str]) -> Option:
    def apply(d: dict) -> None:
        d.setdefault("metadata", {}).setdefault("annotations", {}).update(annotations)

    return apply


def with_namespace(ns: str) -> Option:
    def apply(d: dict) -> None:
        d.setdefault("metadata", {})["namespace"] = ns

    return apply


def _pod_template(d: dict) -> dict:
    if d.get("kind") == "CronJob":
        return d["spec"]["jobTemplate"]["spec"].setdefault("template", {})
    return d["spec"].setdefault("template", {})


def _pod_spec(d: dict) -> dict:
    # For workloads, options target the pod template.
    if d.get("kind") in ("Deployment", "ReplicaSet", "StatefulSet", "DaemonSet", "Job", "CronJob"):
        return _pod_template(d).setdefault("spec", {})
    return d.setdefault("spec", {})


def _pod_meta(d: dict) -> dict:
    if d.get("kind") in ("Deployment", "ReplicaSet", "StatefulSet", "DaemonSet", "Job", "CronJob"):
        return _pod_template(d).setdefault("metadata", {})
    return d.setdefault("metadata", {})


def with_pod_labels(labels: Dict[str, str]) -> Option:
    def apply(d: dict) -> None:
        _pod_meta(d).setdefault("labels", {}).update(labels)

    return apply


def with_node_name(name: str) -> Option:
    def apply(d: dict) -> None:
        _pod_spec(d)["nodeName"] = name

    return apply


def with_node_selector(sel: Dict[str, str]) -> Option:
    def apply(d: dict) -> None:
        _pod_spec(d).setdefault("nodeSelector", {}).update(sel)

    return apply


def with_tolerations(tolerations: List[dict]) -> Option:
    def apply(d: dict) -> None:
        _pod_spec(d).setdefault("tolerations", []).extend(tolerations)

    return apply


def with_affinity(affinity: dict) -> Option:
    def apply(d: dict) -> None:
        # merge at the top level so nodeAffinity and podAffinity options
        # compose instead of the last call replacing the whole dict
        _pod_spec(d).setdefault("affinity", {}).update(affinity)

    return apply


def with_requests(requests: Dict[str, str]) -> Option:
    def apply(d: dict) -> None:
        spec = _pod_spec(d)
        for c in spec.setdefault("containers", []):
            c.setdefault("resources", {}).setdefault("requests", {}).update(requests)

    return apply


def with_host_ports(ports: List[int]) -> Option:
    def apply(d: dict) -> None:
        spec = _pod_spec(d)
        for c in spec.setdefault("containers", []):
            c.setdefault("ports", []).extend(
                {"hostPort": p, "containerPort": p, "protocol": "TCP"} for p in ports
            )

    return apply


def with_priority(priority: int) -> Option:
    def apply(d: dict) -> None:
        _pod_spec(d)["priority"] = int(priority)

    return apply


def with_host_port_specs(specs: List[dict]) -> Option:
    """Full container-port dicts (hostPort/protocol/hostIP)."""

    def apply(d: dict) -> None:
        spec = _pod_spec(d)
        for c in spec.setdefault("containers", []):
            c.setdefault("ports", []).extend(dict(p) for p in specs)

    return apply


def with_topology_spread(constraints: List[dict]) -> Option:
    def apply(d: dict) -> None:
        _pod_spec(d)["topologySpreadConstraints"] = constraints

    return apply


def with_pod_local_storage(volumes_json: str) -> Option:
    return with_annotations({ANNO_POD_LOCAL_STORAGE: volumes_json})


# -- node options ------------------------------------------------------------

def with_taints(taints: List[dict]) -> Option:
    def apply(d: dict) -> None:
        d.setdefault("spec", {}).setdefault("taints", []).extend(taints)

    return apply


def with_node_local_storage(vgs: Optional[List[dict]] = None, devices: Optional[List[dict]] = None) -> Option:
    """WithNodeLocalStorage (pkg/test/node.go:64-69): the
    simon/node-local-storage annotation JSON."""
    payload = json.dumps({"vgs": vgs or [], "devices": devices or []})
    return with_annotations({ANNO_NODE_LOCAL_STORAGE: payload})


def with_allocatable(alloc: Dict[str, str]) -> Option:
    def apply(d: dict) -> None:
        d.setdefault("status", {}).setdefault("allocatable", {}).update(alloc)
        d.setdefault("status", {}).setdefault("capacity", {}).update(alloc)

    return apply


# -- builders ----------------------------------------------------------------

def make_fake_pod(name: str, cpu: str = "100m", memory: str = "128Mi", *options: Option) -> Pod:
    """MakeFakePod (pkg/test/pod.go:13-47): defaults an nginx container."""
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {
                    "name": "nginx",
                    "image": "nginx:latest",
                    "resources": {"requests": {"cpu": cpu, "memory": memory}},
                }
            ]
        },
    }
    for opt in options:
        opt(d)
    return Pod.from_dict(d)


def make_fake_node(name: str, cpu: str = "32", memory: str = "64Gi", pods: str = "110", *options: Option) -> Node:
    """MakeFakeNode (pkg/test/node.go:15-40): default 110-pod capacity."""
    d = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": {"kubernetes.io/hostname": name}},
        "status": {
            "allocatable": {"cpu": cpu, "memory": memory, "pods": pods},
            "capacity": {"cpu": cpu, "memory": memory, "pods": pods},
        },
    }
    for opt in options:
        opt(d)
    return Node.from_dict(d)


def _make_workload(kind: str, name: str, replicas: int, cpu: str, memory: str, *options: Option) -> Workload:
    labels = {"app": name}
    d = {
        "apiVersion": "apps/v1" if kind in ("Deployment", "ReplicaSet", "StatefulSet", "DaemonSet") else "batch/v1",
        "kind": kind,
        "metadata": {"name": name, "namespace": "default", "labels": dict(labels)},
        "spec": {
            "selector": {"matchLabels": dict(labels)},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    "containers": [
                        {
                            "name": "nginx",
                            "image": "nginx:latest",
                            "resources": {"requests": {"cpu": cpu, "memory": memory}},
                        }
                    ]
                },
            },
        },
    }
    if kind in ("Deployment", "ReplicaSet", "StatefulSet"):
        d["spec"]["replicas"] = replicas
    elif kind == "Job":
        d["spec"]["completions"] = replicas
        d["spec"].pop("selector")
    for opt in options:
        opt(d)
    return Workload.from_dict(d)


def make_fake_deployment(name: str, replicas: int = 1, cpu: str = "100m", memory: str = "128Mi", *options: Option) -> Workload:
    return _make_workload("Deployment", name, replicas, cpu, memory, *options)


def make_fake_replica_set(name: str, replicas: int = 1, cpu: str = "100m", memory: str = "128Mi", *options: Option) -> Workload:
    return _make_workload("ReplicaSet", name, replicas, cpu, memory, *options)


def make_fake_stateful_set(name: str, replicas: int = 1, cpu: str = "100m", memory: str = "128Mi", *options: Option) -> Workload:
    return _make_workload("StatefulSet", name, replicas, cpu, memory, *options)


def make_fake_daemon_set(name: str, cpu: str = "100m", memory: str = "128Mi", *options: Option) -> Workload:
    return _make_workload("DaemonSet", name, 1, cpu, memory, *options)


def make_fake_job(name: str, completions: int = 1, cpu: str = "100m", memory: str = "128Mi", *options: Option) -> Workload:
    return _make_workload("Job", name, completions, cpu, memory, *options)


def make_fake_cron_job(name: str, completions: int = 1, cpu: str = "100m", memory: str = "128Mi", *options: Option) -> Workload:
    job = _make_workload("Job", name, completions, cpu, memory)
    d = {
        "apiVersion": "batch/v1beta1",
        "kind": "CronJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"schedule": "* * * * *", "jobTemplate": {"spec": job.raw["spec"]}},
    }
    for opt in options:
        opt(d)
    return Workload.from_dict(d)
