"""Kubernetes object model (host layer).

Plain-Python dataclasses standing in for the ``corev1``/``appsv1`` typed
objects the reference manipulates. Each object keeps its source dict in
``raw`` so unmodelled fields round-trip. The set of modelled kinds mirrors
``ResourceTypes`` in the reference (``pkg/simulator/core.go:38-52``): Pods,
Nodes, Deployments, ReplicaSets, StatefulSets, DaemonSets, Jobs, CronJobs,
Services, PodDisruptionBudgets, StorageClasses, PersistentVolumeClaims,
ConfigMaps.
"""

from __future__ import annotations

import copy
import threading as _threading
import uuid as _uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .quantity import parse_quantity

# Annotation / label protocol — parity with pkg/type/const.go:19-31.
ANNO_WORKLOAD_KIND = "simon/workload-kind"
ANNO_WORKLOAD_NAME = "simon/workload-name"
ANNO_WORKLOAD_NAMESPACE = "simon/workload-namespace"
ANNO_NODE_LOCAL_STORAGE = "simon/node-local-storage"
ANNO_POD_LOCAL_STORAGE = "simon/pod-local-storage"
ANNO_NODE_GPU_SHARE = "simon/node-gpu-share"
ANNO_POD_PROVISIONER = "simon/pod-provisioner"
LABEL_NEW_NODE = "simon/new-node"
LABEL_APP_NAME = "simon/app-name"
ENV_MAX_CPU = "MaxCPU"
ENV_MAX_MEMORY = "MaxMemory"
ENV_MAX_VG = "MaxVG"
SEPARATE_SYMBOL = "-"
# simontype.DefaultSchedulerName = corev1.DefaultSchedulerName
# (pkg/type/const.go:12): the reference schedules with the DEFAULT
# kube scheduler name, and MakeValidPod defaults pods to it
DEFAULT_SCHEDULER_NAME = "default-scheduler"
LABEL_HOSTNAME = "kubernetes.io/hostname"

# GPU-share annotation protocol — pkg/type/open-gpu-share/utils/const.go:4-8.
RES_GPU_MEM = "alibabacloud.com/gpu-mem"
RES_GPU_COUNT = "alibabacloud.com/gpu-count"
ANNO_GPU_INDEX = "alibabacloud.com/gpu-index"
ANNO_GPU_ASSUME_TIME = "alibabacloud.com/assume-time"
LABEL_GPU_CARD_MODEL = "alibabacloud.com/gpu-card-model"

_counter = [0]


class VersionedObject:
    """Local mutation counter for the prepare-cache coherence protocol.

    ``PrepareCache`` fingerprints hash object identity + version, NOT deep
    content — so in-place edits of an already-fingerprinted object are
    invisible to the cache (the NOTES.md envelope). The protocol:

    1. mutate the object, then call ``obj.touch()`` — a cheap marker that
       the content behind the fingerprint changed;
    2. drop the stale entries with ``cache.invalidate(obj)``.

    A cache hit on an entry whose watched object was touched without
    invalidation raises ``StaleFingerprintError`` (engine/prepcache.py).
    The static side of the same contract is opensim-lint's cache-mutation
    rule (OSL401)."""

    _local_version = 0  # class default: instances allocate on first touch
    # process-global epoch: bumped on EVERY touch so cache freshness checks
    # are one integer compare in the steady state (no touches anywhere)
    # instead of an O(watched objects) version scan per cache hit. Lock-
    # guarded: a lost increment would let an entry re-arm its fast path
    # past a concurrent touch and silently serve a stale prepare.
    _touch_epoch = [0]
    _touch_lock = _threading.Lock()

    def touch(self) -> None:
        with VersionedObject._touch_lock:
            self._local_version = self._local_version + 1
            VersionedObject._touch_epoch[0] += 1

    @property
    def local_version(self) -> int:
        return self._local_version


def touch_epoch() -> int:
    """Current global touch epoch (see VersionedObject.touch)."""
    return VersionedObject._touch_epoch[0]


def _rand_suffix(n: int = 10) -> str:
    """Deterministic unique suffix standing in for k8s rand.String(10)
    (``pkg/utils/utils.go:313``). Deterministic so runs are reproducible."""
    _counter[0] += 1
    return f"{_counter[0]:0{n}x}"[-n:]


def new_uid() -> str:
    """Unique id in UUID shape without the UUID-object cost (this is on the
    50k-pod expansion hot path)."""
    _counter[0] += 1
    return f"00000000-0000-0000-0000-{_counter[0]:012x}"


@dataclass
class OwnerReference:
    kind: str = ""
    name: str = ""
    uid: str = ""
    api_version: str = ""
    controller: bool = True

    def to_dict(self) -> dict:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "name": self.name,
            "uid": self.uid,
            "controller": self.controller,
            "blockOwnerDeletion": True,
        }


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    uid: str = ""
    generate_name: str = ""
    owner_references: List[OwnerReference] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ObjectMeta":
        d = d or {}
        refs = [
            OwnerReference(
                kind=r.get("kind", ""),
                name=r.get("name", ""),
                uid=r.get("uid", ""),
                api_version=r.get("apiVersion", ""),
                controller=bool(r.get("controller", False)),
            )
            for r in d.get("ownerReferences") or []
        ]
        return cls(
            name=d.get("name", "") or "",
            namespace=d.get("namespace", "") or "",
            labels={k: str(v) for k, v in (d.get("labels") or {}).items()},
            annotations={k: str(v) for k, v in (d.get("annotations") or {}).items()},
            uid=str(d.get("uid", "") or ""),
            generate_name=d.get("generateName", "") or "",
            owner_references=refs,
        )

    def to_dict(self) -> dict:
        out: Dict[str, Any] = {"name": self.name}
        if self.namespace:
            out["namespace"] = self.namespace
        if self.labels:
            out["labels"] = dict(self.labels)
        if self.annotations:
            out["annotations"] = dict(self.annotations)
        if self.uid:
            out["uid"] = self.uid
        if self.generate_name:
            out["generateName"] = self.generate_name
        if self.owner_references:
            out["ownerReferences"] = [r.to_dict() for r in self.owner_references]
        return out


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" matches all effects
    toleration_seconds: Optional[int] = None

    @classmethod
    def from_dict(cls, d: dict) -> "Toleration":
        return cls(
            key=d.get("key", "") or "",
            operator=d.get("operator") or "Equal",  # k8s default operator is Equal
            value=str(d.get("value", "") or ""),
            effect=d.get("effect", "") or "",
            toleration_seconds=d.get("tolerationSeconds"),
        )


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute

    @classmethod
    def from_dict(cls, d: dict) -> "Taint":
        return cls(
            key=d.get("key", "") or "",
            value=str(d.get("value", "") or ""),
            # k8s requires an effect on taints; default missing ones to
            # NoSchedule so parsed and programmatic taints behave alike
            effect=d.get("effect", "") or "NoSchedule",
        )


@dataclass
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Container:
    name: str = ""
    image: str = ""
    requests: Dict[str, float] = field(default_factory=dict)
    limits: Dict[str, float] = field(default_factory=dict)
    ports: List[ContainerPort] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "Container":
        res = d.get("resources") or {}
        requests = {k: parse_quantity(v) for k, v in (res.get("requests") or {}).items()}
        limits = {k: parse_quantity(v) for k, v in (res.get("limits") or {}).items()}
        ports = [
            ContainerPort(
                host_port=int(p.get("hostPort", 0) or 0),
                container_port=int(p.get("containerPort", 0) or 0),
                protocol=p.get("protocol", "TCP") or "TCP",
                host_ip=p.get("hostIP", "") or "",
            )
            for p in d.get("ports") or []
        ]
        return cls(
            name=d.get("name", "") or "",
            image=d.get("image", "") or "",
            requests=requests,
            limits=limits,
            ports=ports,
        )


@dataclass
class PodSpec:
    node_name: str = ""
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Dict[str, float] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[dict] = None  # raw affinity dict (nodeAffinity/podAffinity/podAntiAffinity)
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread_constraints: List[dict] = field(default_factory=list)
    host_network: bool = False
    scheduler_name: str = ""
    priority: int = 0
    volumes: List[dict] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodSpec":
        d = d or {}
        return cls(
            node_name=d.get("nodeName", "") or "",
            containers=[Container.from_dict(c) for c in d.get("containers") or []],
            init_containers=[Container.from_dict(c) for c in d.get("initContainers") or []],
            overhead={k: parse_quantity(v) for k, v in (d.get("overhead") or {}).items()},
            node_selector={k: str(v) for k, v in (d.get("nodeSelector") or {}).items()},
            affinity=copy.deepcopy(d.get("affinity")) if d.get("affinity") else None,
            tolerations=[Toleration.from_dict(t) for t in d.get("tolerations") or []],
            topology_spread_constraints=copy.deepcopy(d.get("topologySpreadConstraints") or []),
            host_network=bool(d.get("hostNetwork", False)),
            scheduler_name=d.get("schedulerName", "") or "",
            priority=int(d.get("priority", 0) or 0),
            volumes=copy.deepcopy(d.get("volumes") or []),
        )


@dataclass
class Pod(VersionedObject):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    phase: str = ""
    raw: dict = field(default_factory=dict)

    kind = "Pod"

    @classmethod
    def from_dict(cls, d: dict) -> "Pod":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            spec=PodSpec.from_dict(d.get("spec")),
            phase=(d.get("status") or {}).get("phase", "") or "",
            raw=d,
        )

    # -- effective resource requests, k8s semantics:
    # max(sum(containers), max(initContainers)) + overhead
    # (mirrors resourcehelper.PodRequestsAndLimits used at plugin/simon.go:46)
    def resource_requests(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for c in self.spec.containers:
            for k, v in c.requests.items():
                total[k] = total.get(k, 0.0) + v
        for c in self.spec.init_containers:
            for k, v in c.requests.items():
                if v > total.get(k, 0.0):
                    total[k] = v
        for k, v in self.spec.overhead.items():
            total[k] = total.get(k, 0.0) + v
        return total

    def resource_limits(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for c in self.spec.containers:
            for k, v in c.limits.items():
                total[k] = total.get(k, 0.0) + v
        for c in self.spec.init_containers:
            for k, v in c.limits.items():
                if v > total.get(k, 0.0):
                    total[k] = v
        for k, v in self.spec.overhead.items():
            total[k] = total.get(k, 0.0) + v
        return total

    def host_ports(self) -> List[ContainerPort]:
        out = []
        for c in list(self.spec.containers) + list(self.spec.init_containers):
            for p in c.ports:
                if p.host_port > 0:
                    out.append(p)
        return out

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    # GPU-share request, parity with GetGpuMemoryAndCountFromPodAnnotation
    # (pkg/type/open-gpu-share/utils/pod.go:83-100): gpu-mem (memory PER GPU)
    # and gpu-count both come from pod *annotations*; absent count → 0.
    def gpu_mem_request(self) -> float:
        val = self.metadata.annotations.get(RES_GPU_MEM)
        if not val:
            return 0.0
        try:
            return parse_quantity(val)
        except ValueError:
            return 0.0

    def local_volumes(self) -> list:
        """Decode the simon/pod-local-storage annotation (volume dicts with
        kind/size/scName); the single parser shared by encoding and reports."""
        import json

        raw = self.metadata.annotations.get(ANNO_POD_LOCAL_STORAGE)
        if not raw:
            return []
        try:
            data = json.loads(raw)
            vols = data.get("volumes") if isinstance(data, dict) else None
        except ValueError:
            return []
        return [v for v in (vols or []) if isinstance(v, dict)]

    def gpu_count_request(self) -> int:
        try:
            cnt = int(self.metadata.annotations.get(RES_GPU_COUNT, "0"))
        except ValueError:
            return 0
        return max(cnt, 0)


@dataclass
class Node(VersionedObject):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    allocatable: Dict[str, float] = field(default_factory=dict)
    capacity: Dict[str, float] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False
    raw: dict = field(default_factory=dict)

    kind = "Node"

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        status = d.get("status") or {}
        spec = d.get("spec") or {}
        alloc = {k: parse_quantity(v) for k, v in (status.get("allocatable") or {}).items()}
        cap = {k: parse_quantity(v) for k, v in (status.get("capacity") or {}).items()}
        if not alloc:
            alloc = dict(cap)
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            allocatable=alloc,
            capacity=cap,
            taints=[Taint.from_dict(t) for t in spec.get("taints") or []],
            unschedulable=bool(spec.get("unschedulable", False)),
            raw=d,
        )

    @property
    def name(self) -> str:
        return self.metadata.name

    def to_dict(self) -> dict:
        d = copy.deepcopy(self.raw) if self.raw else {"apiVersion": "v1", "kind": "Node"}
        d["metadata"] = self.metadata.to_dict()
        return d


@dataclass
class Workload(VersionedObject):
    """Common shape for Deployment / ReplicaSet / StatefulSet / DaemonSet /
    Job / CronJob: metadata + pod template (+ replicas/completions)."""

    kind: str = ""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    replicas: int = 1
    selector: Optional[dict] = None
    template_metadata: ObjectMeta = field(default_factory=ObjectMeta)
    template_spec: PodSpec = field(default_factory=PodSpec)
    template_raw: dict = field(default_factory=dict)
    volume_claim_templates: List[dict] = field(default_factory=list)
    raw: dict = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "Workload":
        kind = d.get("kind", "")
        spec = d.get("spec") or {}
        if kind == "CronJob":
            job_spec = (spec.get("jobTemplate") or {}).get("spec") or {}
            template = job_spec.get("template") or {}
            completions = job_spec.get("completions")
            replicas = 1 if completions is None else int(completions)
            selector = job_spec.get("selector")
            vct = []
        elif kind == "Job":
            template = spec.get("template") or {}
            completions = spec.get("completions")
            replicas = 1 if completions is None else int(completions)
            selector = spec.get("selector")
            vct = []
        else:
            template = spec.get("template") or {}
            replicas = int(spec.get("replicas", 1) if spec.get("replicas") is not None else 1)
            selector = spec.get("selector")
            vct = copy.deepcopy(spec.get("volumeClaimTemplates") or [])
        return cls(
            kind=kind,
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            replicas=replicas,
            selector=copy.deepcopy(selector),
            template_metadata=ObjectMeta.from_dict(template.get("metadata")),
            template_spec=PodSpec.from_dict(template.get("spec")),
            template_raw=copy.deepcopy(template),
            volume_claim_templates=vct,
            raw=d,
        )

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class RawObject(VersionedObject):
    """Kinds carried through but not interpreted beyond a few fields:
    Service, StorageClass, PersistentVolumeClaim, ConfigMap."""

    kind: str = ""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    raw: dict = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "RawObject":
        return cls(kind=d.get("kind", ""), metadata=ObjectMeta.from_dict(d.get("metadata")), raw=d)


@dataclass
class PodDisruptionBudget(VersionedObject):
    """Typed ``policy/v1`` PodDisruptionBudget (ISSUE 13): the campaign
    engine tracks per-step disruption budgets, so the spec fields the
    disruption controller reads — ``minAvailable`` / ``maxUnavailable``
    (absolute or percentage) and the pod ``selector`` — are parsed once
    here instead of being re-dug out of ``raw`` at every eviction check.
    ``raw`` still round-trips the full object (the preemption pass and the
    twin keep reading it like any other resource)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    #: int, ``"N%"`` string, or None — exactly one of the two is normally set
    min_available: Optional[object] = None
    max_unavailable: Optional[object] = None
    selector: Optional[dict] = None
    raw: dict = field(default_factory=dict)

    kind = "PodDisruptionBudget"

    @classmethod
    def from_dict(cls, d: dict) -> "PodDisruptionBudget":
        spec = d.get("spec") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            min_available=spec.get("minAvailable"),
            max_unavailable=spec.get("maxUnavailable"),
            selector=copy.deepcopy(spec.get("selector")),
            raw=d,
        )

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return f"{self.metadata.namespace or 'default'}/{self.metadata.name}"

    @staticmethod
    def _resolve(value, basis: int) -> int:
        """An absolute count, or ``ceil(pct · basis)`` for ``"N%"`` — the
        disruption controller's ``GetScaledValueFromIntOrPercent`` with
        round-up semantics."""
        import math

        if isinstance(value, str) and value.strip().endswith("%"):
            return int(math.ceil(float(value.strip()[:-1]) / 100.0 * basis))
        return int(value)

    def selects(self) -> bool:
        """Nil/empty selectors match nothing (``filterPodsWithPDBViolation``
        semantics — same rule the preemption pass applies)."""
        sel = self.selector or {}
        return bool(sel.get("matchLabels") or sel.get("matchExpressions"))

    def matches(self, pod: "Pod") -> bool:
        from . import selectors

        return (
            self.selects()
            and pod.metadata.namespace == (self.metadata.namespace or "default")
            and bool(pod.metadata.labels)
            and selectors.match_label_selector(self.selector, pod.metadata.labels)
        )

    def disruptions_allowed(self, healthy: int, expected: int) -> int:
        """``status.disruptionsAllowed`` from the current healthy matching
        count and the expected count (the owning workloads' declared
        replicas) — the disruption controller's arithmetic, clamped at 0.
        A PDB with neither field set allows unlimited disruptions."""
        if self.min_available is not None:
            allowed = healthy - self._resolve(self.min_available, expected)
        elif self.max_unavailable is not None:
            allowed = healthy - (expected - self._resolve(self.max_unavailable, expected))
        else:
            return 1 << 30
        return max(int(allowed), 0)


@dataclass
class ResourceTypes:
    """Parity with pkg/simulator/core.go:38-52."""

    pods: List[Pod] = field(default_factory=list)
    nodes: List[Node] = field(default_factory=list)
    deployments: List[Workload] = field(default_factory=list)
    replica_sets: List[Workload] = field(default_factory=list)
    stateful_sets: List[Workload] = field(default_factory=list)
    daemon_sets: List[Workload] = field(default_factory=list)
    jobs: List[Workload] = field(default_factory=list)
    cron_jobs: List[Workload] = field(default_factory=list)
    services: List[RawObject] = field(default_factory=list)
    pdbs: List[PodDisruptionBudget] = field(default_factory=list)
    storage_classes: List[RawObject] = field(default_factory=list)
    pvcs: List[RawObject] = field(default_factory=list)
    config_maps: List[RawObject] = field(default_factory=list)

    def add(self, obj) -> bool:
        kind = obj.kind
        dest = {
            "Pod": self.pods,
            "Node": self.nodes,
            "Deployment": self.deployments,
            "ReplicaSet": self.replica_sets,
            "StatefulSet": self.stateful_sets,
            "DaemonSet": self.daemon_sets,
            "Job": self.jobs,
            "CronJob": self.cron_jobs,
            "Service": self.services,
            "PodDisruptionBudget": self.pdbs,
            "StorageClass": self.storage_classes,
            "PersistentVolumeClaim": self.pvcs,
            "ConfigMap": self.config_maps,
        }.get(kind)
        if dest is None:
            return False
        dest.append(obj)
        return True


WORKLOAD_KINDS = {"Deployment", "ReplicaSet", "StatefulSet", "DaemonSet", "Job", "CronJob"}
RAW_KINDS = {"Service", "StorageClass", "PersistentVolumeClaim", "ConfigMap"}


def object_from_dict(d: dict):
    """Typed decode switch — parity with GetObjectFromYamlContent
    (``pkg/simulator/utils.go:233-275``). Returns None for unsupported kinds."""
    if not isinstance(d, dict):
        return None
    kind = d.get("kind", "")
    if kind == "Pod":
        return Pod.from_dict(d)
    if kind == "Node":
        return Node.from_dict(d)
    if kind in WORKLOAD_KINDS:
        return Workload.from_dict(d)
    if kind == "PodDisruptionBudget":
        return PodDisruptionBudget.from_dict(d)
    if kind in RAW_KINDS:
        return RawObject.from_dict(d)
    return None
