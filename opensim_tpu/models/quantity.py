"""Kubernetes resource-quantity parsing.

The reference relies on ``k8s.io/apimachinery``'s ``resource.Quantity``
(used throughout e.g. ``pkg/simulator/plugin/simon.go:57-66``). This module
implements the subset of quantity semantics the simulator needs: parsing
decimal/binary-SI suffixed strings to numeric base units and formatting them
back for reports.
"""

from __future__ import annotations

from fractions import Fraction
from typing import Dict, Union

# Binary SI (power-of-two) suffixes.
_BINARY: Dict[str, int] = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
# Decimal SI suffixes (note lowercase k, as in upstream).
_DECIMAL: Dict[str, Union[int, Fraction]] = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 1000),
    "": Fraction(1),
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}


def parse_quantity(value: object) -> float:
    """Parse a Kubernetes quantity (e.g. ``"1500m"``, ``"16Gi"``, ``2``) to a
    float in base units."""
    if value is None:
        return 0.0
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    if not s:
        return 0.0
    # Scientific notation like "1e3" is legal in k8s quantities.
    for suffix in _BINARY:
        if s.endswith(suffix):
            return float(Fraction(s[: -len(suffix)]) * _BINARY[suffix])
    # Longest decimal suffixes are single-char; check exponent form first.
    try:
        return float(s)
    except ValueError:
        pass
    suffix = s[-1]
    if suffix in _DECIMAL:
        num = s[:-1]
        return float(Fraction(num) * _DECIMAL[suffix])
    raise ValueError(f"unparseable quantity: {value!r}")


def parse_quantity_milli(value: object) -> int:
    """Parse to integer milli-units (the natural unit for CPU)."""
    return int(round(parse_quantity(value) * 1000))


def format_quantity(value: float, binary: bool = True) -> str:
    """Human-readable rendering for reports (mirrors how pterm tables in
    ``pkg/apply/apply.go:309-687`` show Gi/Mi quantities)."""
    if value == 0:
        return "0"
    if binary:
        for suffix in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
            unit = _BINARY[suffix]
            if abs(value) >= unit:
                v = value / unit
                if abs(v - round(v)) < 1e-9:
                    return f"{int(round(v))}{suffix}"
                return f"{v:.2f}{suffix}"
    if abs(value - round(value)) < 1e-9:
        return str(int(round(value)))
    return f"{value:.3f}"


def format_milli(value_milli: int) -> str:
    """Render a milli quantity (CPU) like kubectl does."""
    if value_milli % 1000 == 0:
        return str(value_milli // 1000)
    return f"{value_milli}m"
