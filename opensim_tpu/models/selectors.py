"""Host-side label-selector / node-affinity / taint matching.

Reference-parity with the ``k8s.io/apimachinery`` label machinery and the
scheduler helpers the reference calls (e.g. daemon predicates used by
``NodeShouldRunPod``, ``pkg/utils/utils.go:325-351``). These functions serve
two roles: (1) host-side workload expansion (DaemonSet eligibility), and
(2) golden references for the vectorized device kernels in
``opensim_tpu/ops`` — the unit tests assert kernel output equals these.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .objects import Node, Pod, Taint, Toleration


# ---------------------------------------------------------------------------
# Label selectors (metav1.LabelSelector): matchLabels + matchExpressions.
# ---------------------------------------------------------------------------

def match_label_selector(selector: Optional[dict], labels: Dict[str, str]) -> bool:
    """Does a metav1.LabelSelector match a label set?  A nil selector matches
    nothing (k8s semantics for e.g. affinity term selectors); an empty
    selector matches everything."""
    if selector is None:
        return False
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != str(v):
            return False
    for expr in selector.get("matchExpressions") or []:
        if not _match_expression(expr, labels):
            return False
    return True


def _match_expression(expr: dict, labels: Dict[str, str]) -> bool:
    key = expr.get("key", "")
    op = expr.get("operator", "")
    values = [str(v) for v in (expr.get("values") or [])]
    present = key in labels
    val = labels.get(key)
    if op == "In":
        return present and val in values
    if op == "NotIn":
        return not present or val not in values
    if op == "Exists":
        return present
    if op == "DoesNotExist":
        return not present
    raise ValueError(f"unknown label selector operator: {op}")


# ---------------------------------------------------------------------------
# Node selectors / node affinity (corev1.NodeSelector).
# ---------------------------------------------------------------------------

def _match_node_expression(expr: dict, labels: Dict[str, str]) -> bool:
    key = expr.get("key", "")
    op = expr.get("operator", "")
    values = [str(v) for v in (expr.get("values") or [])]
    present = key in labels
    val = labels.get(key)
    if op == "In":
        return present and val in values
    if op == "NotIn":
        return not present or val not in values
    if op == "Exists":
        return present
    if op == "DoesNotExist":
        return not present
    if op in ("Gt", "Lt"):
        if not present or len(values) != 1:
            return False
        try:
            node_val = int(val)  # type: ignore[arg-type]
            sel_val = int(values[0])
        except (TypeError, ValueError):
            return False
        return node_val > sel_val if op == "Gt" else node_val < sel_val
    raise ValueError(f"unknown node selector operator: {op}")


def match_node_selector_term(term: dict, node: Node) -> bool:
    """One NodeSelectorTerm: AND of matchExpressions (on labels) and
    matchFields (on metadata.name)."""
    exprs = term.get("matchExpressions") or []
    fields = term.get("matchFields") or []
    if not exprs and not fields:
        return False  # empty term matches no objects (k8s semantics)
    for expr in exprs:
        if not _match_node_expression(expr, node.metadata.labels):
            return False
    for expr in fields:
        if expr.get("key") != "metadata.name":
            return False
        if not _match_node_expression(expr, {"metadata.name": node.metadata.name}):
            return False
    return True


def match_node_selector_terms(terms: List[dict], node: Node) -> bool:
    """NodeSelector = OR over terms."""
    return any(match_node_selector_term(t, node) for t in terms)


def pod_matches_node_selector_and_affinity(pod: Pod, node: Node) -> bool:
    """RequiredDuringSchedulingIgnoredDuringExecution node affinity plus the
    plain nodeSelector map — the predicate behind the NodeAffinity filter
    plugin and daemon.Predicates' fitsNodeAffinity."""
    for k, v in pod.spec.node_selector.items():
        if node.metadata.labels.get(k) != str(v):
            return False
    aff = (pod.spec.affinity or {}).get("nodeAffinity") or {}
    required = aff.get("requiredDuringSchedulingIgnoredDuringExecution")
    if required is not None:
        # k8s MatchNodeSelectorTerms: an empty terms list matches no nodes.
        if not match_node_selector_terms(required.get("nodeSelectorTerms") or [], node):
            return False
    return True


def node_affinity_preferred_score(pod: Pod, node: Node) -> int:
    """Sum of matching preferred term weights (NodeAffinity score plugin)."""
    aff = (pod.spec.affinity or {}).get("nodeAffinity") or {}
    total = 0
    for pref in aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        term = pref.get("preference") or {}
        if match_node_selector_term(term, node):
            total += int(pref.get("weight", 0))
    return total


# ---------------------------------------------------------------------------
# Taints / tolerations.
# ---------------------------------------------------------------------------

def toleration_tolerates_taint(tol: Toleration, taint: Taint) -> bool:
    if tol.effect and tol.effect != taint.effect:
        return False
    if tol.key and tol.key != taint.key:
        return False
    # empty key with Exists matches all taints
    if not tol.key and tol.operator != "Exists":
        return False
    if tol.operator == "Exists":
        return True
    if tol.operator in ("Equal", ""):
        return tol.value == taint.value
    return False


def find_untolerated_taint(
    taints: List[Taint], tolerations: List[Toleration], effects: Optional[List[str]] = None
) -> Optional[Taint]:
    """First taint (with effect in `effects`, default NoSchedule+NoExecute)
    not tolerated by any toleration. Mirrors v1helper.FindMatchingUntoleratedTaint."""
    if effects is None:
        effects = ["NoSchedule", "NoExecute"]
    for taint in taints:
        if taint.effect not in effects:
            continue
        if not any(toleration_tolerates_taint(t, taint) for t in tolerations):
            return taint
    return None


def count_intolerable_prefer_no_schedule(pod: Pod, node: Node) -> int:
    """TaintToleration score plugin input: number of PreferNoSchedule taints
    the pod does not tolerate."""
    count = 0
    for taint in node.taints:
        if taint.effect != "PreferNoSchedule":
            continue
        if not any(toleration_tolerates_taint(t, taint) for t in pod.spec.tolerations):
            count += 1
    return count


# ---------------------------------------------------------------------------
# DaemonSet eligibility — parity with NodeShouldRunPod
# (pkg/utils/utils.go:325-351 → k8s.io/kubernetes/pkg/controller/daemon
# Predicates: fitsNodeName, fitsNodeAffinity, fitsTaints).
# ---------------------------------------------------------------------------

def node_should_run_pod(node: Optional[Node], pod: Pod) -> bool:
    if node is None:
        return False
    if pod.spec.node_name and pod.spec.node_name != node.metadata.name:
        return False
    if not pod_matches_node_selector_and_affinity(pod, node):
        return False
    if find_untolerated_taint(node.taints, pod.spec.tolerations, ["NoSchedule", "NoExecute"]):
        return False
    return True


# ---------------------------------------------------------------------------
# Inter-pod affinity helpers (host-side golden reference).
# ---------------------------------------------------------------------------

def affinity_term_matches_pod(term: dict, term_pod_namespace: str, candidate: Pod) -> bool:
    """Does an affinity term (labelSelector + namespaces) match a candidate
    pod?  Empty `namespaces` means the term-owner pod's own namespace."""
    namespaces = [str(n) for n in (term.get("namespaces") or [])] or [term_pod_namespace]
    if candidate.metadata.namespace not in namespaces:
        return False
    return match_label_selector(term.get("labelSelector"), candidate.metadata.labels)
