"""Per-engine circuit breakers for the scheduling-engine fallback ladder.

The ladder (megakernel → C++ native → XLA scan) already had *selection*
pre-checks (``fastpath.why_not`` / ``nativepath.why_not``); this module adds
the *runtime*-failure half: when an engine that passed its pre-checks fails
while running (Mosaic compile error, ``ScanArgs`` ABI drift, device loss),
``engine/simulator.simulate()`` records the failure here and demotes the
request one rung. After ``threshold`` consecutive failures the breaker opens
— later requests skip the doomed attempt outright (the skip reason lands in
``EngineDecision.skipped``, the trip in ``/metrics``) — and after
``cooldown_s`` it goes half-open: one probe request is allowed through; a
success closes the breaker, a failure re-opens it for another cooldown.

States: ``closed`` (normal), ``open`` (skip), ``half-open`` (probe).
``OPENSIM_REQUIRE_TPU=1`` bypasses breaker gating entirely — "fail hard,
never demote" means a broken megakernel must raise, not be skipped.

Knobs: ``OPENSIM_BREAKER_THRESHOLD`` (default 3 consecutive failures),
``OPENSIM_BREAKER_COOLDOWN_S`` (default 30). The clock is injectable
(``breaker.clock = fake``) so half-open transitions are testable without
sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..utils import envknobs

__all__ = ["CircuitBreaker", "engine_breaker", "all_breakers", "reset_breakers"]


def _env_int(name: str, default: int) -> int:
    raw = envknobs.raw(name)
    try:
        return int(raw) if raw else default
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}") from None


def _env_float(name: str, default: float) -> float:
    raw = envknobs.raw(name)
    try:
        return float(raw) if raw else default
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}") from None


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing. Thread-safe."""

    def __init__(
        self,
        name: str,
        threshold: Optional[int] = None,
        cooldown_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.name = name
        self.threshold = threshold if threshold is not None else _env_int("OPENSIM_BREAKER_THRESHOLD", 3)
        self.cooldown_s = cooldown_s if cooldown_s is not None else _env_float("OPENSIM_BREAKER_COOLDOWN_S", 30.0)
        self.clock = clock
        self._lock = threading.Lock()
        self.consecutive_failures = 0
        self.failures_total = 0
        self.trips_total = 0
        self.last_error: str = ""
        self._opened_at: Optional[float] = None
        self._probing = False

    # -- state --------------------------------------------------------------

    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self.clock() - self._opened_at >= self.cooldown_s:
            return "half-open"
        return "open"

    def allow(self) -> bool:
        """May the engine be attempted? ``closed`` → yes; ``open`` → no;
        ``half-open`` → yes, once (the probe) — concurrent requests during
        the probe are still skipped so one broken engine can't stall a
        whole burst."""
        with self._lock:
            state = self._state_locked()
            if state == "closed":
                return True
            if state == "open":
                return False
            if self._probing:
                return False
            self._probing = True
            return True

    def describe_block(self) -> str:
        """One-line skip reason for ``EngineDecision.skipped``."""
        with self._lock:
            remaining = 0.0
            if self._opened_at is not None:
                remaining = max(0.0, self.cooldown_s - (self.clock() - self._opened_at))
            return (
                f"circuit breaker {self._state_locked()} after "
                f"{self.consecutive_failures} consecutive failure(s) "
                f"(last: {self.last_error}; retry in {remaining:.1f}s)"
            )

    # -- outcomes -----------------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            self._opened_at = None
            self._probing = False
            self.last_error = ""

    def record_failure(self, exc: BaseException) -> None:
        trip_info = None
        with self._lock:
            self.consecutive_failures += 1
            self.failures_total += 1
            self.last_error = f"{type(exc).__name__}: {exc}"
            was_probe = self._probing
            was_closed = self._opened_at is None
            if was_probe or self.consecutive_failures >= self.threshold:
                # a failed half-open probe re-opens for a fresh cooldown;
                # each closed→open and half-open→open transition is one trip
                self._opened_at = self.clock()
                self._probing = False
                if was_closed or was_probe:
                    self.trips_total += 1
                    # snapshot the state that tripped THIS request while
                    # still locked — a concurrent record_failure/reset must
                    # not rewrite the event's attribution
                    trip_info = (self.consecutive_failures, self.last_error)
        if trip_info is not None:
            # trace event OUTSIDE the breaker lock (the span sink shares one
            # recorder lock with /metrics; never nest the two)
            from ..obs import trace as _obs

            _obs.event(
                "breaker.trip", status="error", engine=self.name,
                failures=trip_info[0], error=trip_info[1],
            )

    def reset(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            self.failures_total = 0
            self.trips_total = 0
            self.last_error = ""
            self._opened_at = None
            self._probing = False


_BREAKERS: Dict[str, CircuitBreaker] = {}
_REGISTRY_LOCK = threading.Lock()


def engine_breaker(name: str) -> CircuitBreaker:
    """Process-global breaker for engine ``name`` (megakernel/native/xla —
    the XLA scan is the floor of the ladder and never consults its breaker,
    but keeping it registered makes /metrics uniform)."""
    with _REGISTRY_LOCK:
        br = _BREAKERS.get(name)
        if br is None:
            br = _BREAKERS[name] = CircuitBreaker(name)
        return br


def all_breakers() -> Dict[str, CircuitBreaker]:
    with _REGISTRY_LOCK:
        return dict(_BREAKERS)


def reset_breakers() -> None:
    """Test hook: forget all breaker state (and cached env-derived config)."""
    with _REGISTRY_LOCK:
        _BREAKERS.clear()
