"""Request-scoped deadlines, enforced at phase boundaries.

A :class:`Deadline` is created once per request (``server/rest.py`` reads the
``X-Simon-Timeout-S`` header, falling back to ``OPENSIM_REQUEST_TIMEOUT_S``)
and carried through the serving path in a :mod:`contextvars` variable — the
HTTP server handles each request on its own thread, so scopes never bleed
between concurrent requests. Deep layers call :func:`check_deadline` at the
points where work can be abandoned cleanly:

    snapshot → prepare → encode → schedule → decode

The scan itself is a single compiled dispatch and cannot be interrupted
mid-flight; the contract is *phase-boundary* enforcement — an exhausted
deadline raises :class:`DeadlineExceeded` naming the phase it was caught at,
which the REST layer maps to a typed 504 JSON error.

``check_deadline`` with no ambient deadline is a no-op (one contextvar read),
so library callers that never set a scope pay nothing.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Callable, Iterator, Optional

__all__ = [
    "Deadline",
    "DeadlineExceeded",
    "check_deadline",
    "current_deadline",
    "deadline_scope",
]


class DeadlineExceeded(RuntimeError):
    """The request's time budget ran out. ``phase`` names the boundary the
    exhaustion was caught at (snapshot/prepare/encode/schedule/decode)."""

    def __init__(self, message: str, phase: str = "") -> None:
        super().__init__(message)
        self.phase = phase


class Deadline:
    """A monotonic-clock expiry point. ``clock`` is injectable so tests can
    drive expiry deterministically instead of sleeping."""

    def __init__(
        self,
        expires_at: float,
        budget_s: float,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.expires_at = expires_at
        self.budget_s = budget_s
        self.clock = clock

    @classmethod
    def after(cls, seconds: float, clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(clock() + seconds, seconds, clock=clock)

    def remaining(self) -> float:
        return self.expires_at - self.clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, phase: str) -> None:
        rem = self.remaining()
        if rem <= 0.0:
            # observability (ISSUE 5): the exhaustion lands in the request's
            # span tree as an instant event naming the phase; the enclosing
            # phase span is marked deadline-exceeded by its own __exit__
            from ..obs import trace as _obs

            _obs.event(
                "deadline.exceeded", status="deadline-exceeded",
                phase=phase, budget_s=round(self.budget_s, 6),
                over_by_s=round(-rem, 6),
            )
            raise DeadlineExceeded(
                f"request deadline exceeded at the {phase!r} phase "
                f"(budget {self.budget_s:.3f}s, over by {-rem:.3f}s)",
                phase=phase,
            )

    def __repr__(self) -> str:  # debugging / log lines
        return f"Deadline(budget={self.budget_s:.3f}s, remaining={self.remaining():.3f}s)"


_CURRENT: contextvars.ContextVar[Optional[Deadline]] = contextvars.ContextVar(
    "opensim_deadline", default=None
)


def current_deadline() -> Optional[Deadline]:
    return _CURRENT.get()


@contextlib.contextmanager
def deadline_scope(deadline: Optional[Deadline]) -> Iterator[Optional[Deadline]]:
    """Install ``deadline`` as the ambient request deadline for the body.
    ``deadline_scope(None)`` keeps whatever scope is already ambient (so
    ``simulate(deadline=None)`` composes with a server-installed scope)."""
    if deadline is None:
        yield current_deadline()
        return
    token = _CURRENT.set(deadline)
    try:
        yield deadline
    finally:
        _CURRENT.reset(token)


def check_deadline(phase: str) -> None:
    """Raise :class:`DeadlineExceeded` if the ambient deadline (if any) is
    exhausted. The per-phase hook the engine layers call."""
    dl = _CURRENT.get()
    if dl is not None:
        dl.check(phase)
