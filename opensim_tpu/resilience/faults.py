"""Deterministic fault injection at named points in the serving path.

TPU-only failure modes (device loss, Mosaic compile errors) cannot be
reproduced naturally on the CPU hosts that run CI — and transient network
failures cannot be reproduced *deterministically* anywhere. This module puts
a named hook at each place the resilience layer defends, so the chaos suite
(tests/test_chaos.py, ``make chaos``) can prove every recovery path with an
exact failure schedule:

=====================  ======================================================
point                  fires inside
=====================  ======================================================
``snapshot.http``      ``SimonServer._refresh_snapshot``'s apiserver fetch
                       (inside the retry loop — N injections consume N
                       attempts)
``prep.encode``        ``engine/simulator._prepare_inner`` before the encoder
                       build
``engine.compile``     ``fastpath.schedule`` / ``nativepath.schedule`` entry
                       (a runtime engine failure → fallback ladder)
``engine.device_put``  ``engine/scheduler.to_device``
``cache.stale``        ``PrepareCache.check_fresh`` (raises
                       ``StaleFingerprintError`` like a mid-flight touch)
``watch.disconnect``   the watch event read loop (``server/watch.py``) — the
                       stream drops mid-flight and must reconnect
``watch.gone``         the watch event read loop — the apiserver expires the
                       resourceVersion (``410 Gone``) and the consumer must
                       relist-and-rebase
``watch.drop_event``   watch event dispatch — the event is LOST (not an
                       exception: the consumer silently skips it), so only
                       the anti-entropy pass can notice the drift
``watch.reorder``      watch event dispatch — the event is delivered AFTER
                       its successor (out-of-order stream)
``journal.write``      the journal writer thread's record write
                       (``server/journal.py``) — the disk fails mid-append;
                       the twin keeps serving, recording degrades loudly
``journal.fsync``      the journal writer's fsync — same degradation
                       contract as ``journal.write``
``journal.corrupt``    ``Journal.recover`` — recovery from a poisoned
                       journal must degrade to a full relist with a typed
                       warning, never crash the server
``fleet.lease_steal``  ``FleetLease.check`` (``server/fleet.py``) — the HA
                       lease is observed held by ANOTHER epoch: the owner
                       must fence itself (stop publishing, demote) instead
                       of split-braining
``journal.tail_gap``   ``JournalTailer.poll`` (``server/journal.py``) — a
                       drained batch is lost (the tailer fell off pruned
                       history); the standby's twin diverges until the next
                       checkpoint record rebases it back to truth
``shm.republish``      ``TwinPublisher.publish`` between the segment writes
                       and the seqlock control swap — a publish dies
                       mid-flight; readers must keep serving the previous
                       stable generation, never a torn one
=====================  ======================================================

Activation, either route:

- environment: ``OPENSIM_FAULTS=point:count:exc[,point:count:exc...]`` —
  re-read whenever the variable's raw value changes, so subprocess tests can
  set it without an import-order dance;
- test API: ``inject(point, count, exc)`` / ``clear_faults()``.

``count`` is the number of times the point fires before going inert (the
chaos tests' recovery schedules: ``snapshot.http:2:oserror`` with 3 retry
attempts must recover; ``:5`` must fail closed). ``exc`` names the exception
class per ``_EXCEPTIONS`` below. Unknown points or exception names fail
loudly at parse time — a typo'd fault spec silently injecting nothing would
invalidate the whole chaos suite.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..utils import envknobs

__all__ = [
    "FAULT_POINTS",
    "FaultError",
    "clear_faults",
    "fault_point",
    "fault_stats",
    "inject",
]

FAULT_POINTS = (
    "snapshot.http",
    "prep.encode",
    "engine.compile",
    "engine.device_put",
    "cache.stale",
    "watch.disconnect",
    "watch.gone",
    "watch.drop_event",
    "watch.reorder",
    "journal.write",
    "journal.fsync",
    "journal.corrupt",
    "fleet.lease_steal",
    "journal.tail_gap",
    "shm.republish",
)


class FaultError(RuntimeError):
    """Default injected exception (``exc`` name ``fault``/``runtime``)."""


def _stale_exc(message: str) -> BaseException:
    # lazy: faults must stay import-light (it is imported by the engine hot
    # path) and prepcache imports the simulator stack
    from ..engine.prepcache import StaleFingerprintError

    return StaleFingerprintError(message)


def _fetch_exc(message: str) -> BaseException:
    from ..server.snapshot import SnapshotFetchError

    return SnapshotFetchError(message)


def _url_exc(message: str) -> BaseException:
    import urllib.error

    return urllib.error.URLError(message)


_EXCEPTIONS: Dict[str, Callable[[str], BaseException]] = {
    "fault": FaultError,
    "runtime": RuntimeError,
    "oserror": OSError,
    "timeout": TimeoutError,
    "urlerror": _url_exc,
    "fetch": _fetch_exc,
    "stale": _stale_exc,
}


class _FaultSpec:
    def __init__(self, point: str, count: int, exc: str) -> None:
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r}; known: {FAULT_POINTS}")
        if exc not in _EXCEPTIONS:
            raise ValueError(f"unknown fault exception {exc!r}; known: {sorted(_EXCEPTIONS)}")
        if count < 1:
            raise ValueError(f"fault count must be >= 1, got {count}")
        self.point = point
        self.remaining = count
        self.exc = exc


_LOCK = threading.RLock()
_ACTIVE: Dict[str, _FaultSpec] = {}
_FIRED: Dict[str, int] = {}
_ENV_RAW: Optional[str] = None  # last OPENSIM_FAULTS value parsed


def parse_spec(raw: str) -> Dict[str, _FaultSpec]:
    """``point:count:exc,...`` → specs. Count and exc are optional
    (``point`` alone means fire once with FaultError)."""
    specs: Dict[str, _FaultSpec] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) > 3:
            raise ValueError(f"bad fault spec {part!r}: want point[:count[:exc]]")
        point = bits[0].strip()
        try:
            count = int(bits[1]) if len(bits) > 1 and bits[1].strip() else 1
        except ValueError:
            raise ValueError(f"bad fault count in {part!r}") from None
        exc = bits[2].strip().lower() if len(bits) > 2 and bits[2].strip() else "fault"
        specs[point] = _FaultSpec(point, count, exc)
    return specs


def _sync_env_locked() -> None:
    global _ENV_RAW
    raw = envknobs.raw("OPENSIM_FAULTS")
    if raw == _ENV_RAW:
        return
    _ENV_RAW = raw
    _ACTIVE.clear()
    _ACTIVE.update(parse_spec(raw))


def inject(point: str, count: int = 1, exc: str = "fault") -> None:
    """Test API: arm ``point`` to fire ``count`` times raising ``exc``."""
    with _LOCK:
        _sync_env_locked()
        _ACTIVE[point] = _FaultSpec(point, count, exc)


def clear_faults() -> None:
    """Disarm every injection (env-armed ones stay cleared until the env
    value changes) and zero the fired counters."""
    global _ENV_RAW
    with _LOCK:
        _ACTIVE.clear()
        _FIRED.clear()
        _ENV_RAW = envknobs.raw("OPENSIM_FAULTS")


def fault_stats() -> Dict[str, int]:
    """{point: times fired} — exported at /metrics so a chaos run can assert
    its faults actually landed."""
    with _LOCK:
        return dict(_FIRED)


def fault_point(name: str) -> None:
    """The per-site hook. Inert (one env read + dict lookup) unless armed."""
    if _ENV_RAW == "" and not _ACTIVE and not envknobs.raw("OPENSIM_FAULTS"):
        return  # fast path: nothing armed, nothing in the environment
    with _LOCK:
        _sync_env_locked()
        spec = _ACTIVE.get(name)
        if spec is None or spec.remaining <= 0:
            return
        spec.remaining -= 1
        if spec.remaining == 0:
            del _ACTIVE[name]
        _FIRED[name] = _FIRED.get(name, 0) + 1
        factory = _EXCEPTIONS[spec.exc]
    # injections are trace events too (ISSUE 5): a chaos run's span trees
    # show exactly where each fault landed, next to the recovery it forced
    from ..obs import trace as _obs

    _obs.event("fault.injected", status="error", point=name, exc=spec.exc)
    raise factory(f"injected fault at {name}")
