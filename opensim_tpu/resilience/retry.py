"""Bounded retry with jittered exponential backoff.

One function, :func:`retry_call`, used wherever the repo talks to something
that can transiently fail (the apiserver snapshot fetch). Policy follows the
standard full-jitter scheme: attempt ``k`` (0-based) sleeps a uniform sample
from ``[0, min(max_delay, base_delay * 2**k)]``, which decorrelates retry
storms across clients while keeping the expected backoff exponential.

Everything nondeterministic is injectable — ``sleep``, ``rng`` — so tests
assert exact schedules without wall-clock time. The attempt bound is a hard
parameter, never unlimited: opensim-lint rule OSL601 (unbounded-retry) flags
hand-rolled ``while True`` retry loops and constant-sleep backoff; this is
the sanctioned replacement.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

T = TypeVar("T")

__all__ = ["retry_call", "backoff_delay"]


def backoff_delay(
    attempt: int,
    base_delay: float = 0.5,
    max_delay: float = 10.0,
    rng: Optional[random.Random] = None,
) -> float:
    """The :func:`retry_call` full-jitter schedule as a bare delay, for
    loops that respawn rather than re-call (the fleet supervisor's worker
    respawn backoff, server/fleet.py): attempt ``k`` (0-based) sleeps a
    uniform sample from ``[0, min(max_delay, base_delay * 2**k)]``."""
    rng = rng if rng is not None else random.Random()
    return rng.uniform(0.0, min(max_delay, base_delay * (2.0 ** max(0, attempt))))


def retry_call(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    trace_name: str = "retry.backoff",
) -> T:
    """Call ``fn`` up to ``attempts`` times; re-raise the last failure.

    Only exceptions matching ``retry_on`` are retried — anything else
    propagates immediately (an auth misconfiguration must not be hammered
    three times). ``on_retry(attempt_index, exc, delay_s)`` fires before each
    backoff sleep (metrics/log hook). Each retried failure also lands in the
    ambient request trace as one instant event named ``trace_name`` — pass a
    site-specific name (the snapshot fetch uses ``snapshot.retry``) so the
    span tree attributes the backoff; callers must NOT emit their own event
    from ``on_retry`` on top of it."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    rng = rng if rng is not None else random.Random()
    for k in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if k == attempts - 1:
                raise
            delay = rng.uniform(0.0, min(max_delay, base_delay * (2.0**k)))
            # retries land in the ambient request trace (ISSUE 5): each
            # backed-off attempt is ONE instant event naming the failure
            from ..obs import trace as _obs

            _obs.event(
                trace_name, status="error", attempt=k + 1,
                error=f"{type(e).__name__}: {e}", delay_s=round(delay, 6),
            )
            if on_retry is not None:
                on_retry(k, e, delay)
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
