"""Resilience layer: request deadlines, engine circuit breakers, retry with
jittered backoff, and deterministic fault injection.

The REST server is the production surface (ROADMAP north star: heavy traffic
from millions of users); before this package a mid-request failure — snapshot
fetch error, engine compile failure, device loss, stale prepare-cache entry —
either crashed the request with a raw 500 or hung it indefinitely. The four
modules here make the serving path survive faults:

- ``deadline``  — request-scoped :class:`Deadline` propagated via a context
  variable from ``server/rest.py`` into ``engine/simulator.simulate()``,
  enforced at phase boundaries (snapshot, prepare, encode, schedule, decode)
  so an exhausted budget becomes a typed 504, not a hang;
- ``breaker``   — per-engine :class:`CircuitBreaker` behind the megakernel →
  C++ native → XLA scan fallback ladder: a *runtime* engine failure demotes
  the request and counts against the engine; repeated failures open the
  breaker (skip the doomed attempt), with half-open probing after a cooldown;
- ``retry``     — :func:`retry_call`, bounded attempts with jittered
  exponential backoff (the snapshot fetch path);
- ``faults``    — deterministic fault injection at named points
  (``OPENSIM_FAULTS=point:count:exc`` or the test API), so every failure
  mode above is provable on CPU (docs/resilience.md).
"""

from .breaker import (  # noqa: F401
    CircuitBreaker,
    all_breakers,
    engine_breaker,
    reset_breakers,
)
from .deadline import (  # noqa: F401
    Deadline,
    DeadlineExceeded,
    check_deadline,
    current_deadline,
    deadline_scope,
)
from .faults import FaultError, clear_faults, fault_point, fault_stats, inject  # noqa: F401
from .retry import retry_call  # noqa: F401
