#!/usr/bin/env python
"""Headline benchmark: the 50k-pod / 5k-node capacity plan.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
`value` = wall-clock seconds for the full plan (workload expansion →
encoding → 50k-step scheduling scan → decode), measured on the available
accelerator. `vs_baseline` = the <10 s target from BASELINE.md divided by
the measured time (>1 means the target is beaten). The reference publishes
no numbers (SURVEY.md §6), so the driver-set target is the yardstick.

Usage: python bench.py [--pods N] [--nodes N] [--profile small|full]
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/opensim-jit-cache")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from opensim_tpu.engine.simulator import AppResource, simulate  # noqa: E402
from opensim_tpu.models import ResourceTypes, fixtures as fx  # noqa: E402


def synthetic_cluster(n_nodes: int) -> ResourceTypes:
    rt = ResourceTypes()
    zones = [f"zone-{z}" for z in range(4)]
    for i in range(n_nodes):
        rt.nodes.append(
            fx.make_fake_node(
                f"node-{i:05d}",
                "64",
                "256Gi",
                "256",
                fx.with_labels(
                    {
                        "topology.kubernetes.io/zone": zones[i % len(zones)],
                        "node-role.kubernetes.io/worker": "",
                        "disk": "ssd" if i % 3 else "hdd",
                    }
                ),
            )
        )
    return rt


def synthetic_apps(n_pods: int) -> ResourceTypes:
    """~20 workload templates covering the kernel surface: resource fit,
    tolerations, node selectors, spread, anti-affinity."""
    rt = ResourceTypes()
    n_workloads = 20
    per = n_pods // n_workloads
    for w in range(n_workloads):
        opts = []
        if w % 4 == 0:
            opts.append(fx.with_node_selector({"disk": "ssd"}))
        if w % 5 == 0:
            opts.append(
                fx.with_topology_spread(
                    [
                        {
                            "maxSkew": 5,
                            "topologyKey": "topology.kubernetes.io/zone",
                            "whenUnsatisfiable": "ScheduleAnyway",
                            "labelSelector": {"matchLabels": {"app": f"bench-{w}"}},
                        }
                    ]
                )
            )
        rt.deployments.append(
            fx.make_fake_deployment(
                f"bench-{w}", per, f"{100 + 20 * (w % 8)}m", f"{256 + 64 * (w % 6)}Mi", *opts
            )
        )
    return rt


def bench_defrag(n_scenarios: int, n_nodes: int, n_pods: int, warmup: bool) -> int:
    """BASELINE.md config 5: parallel what-if node-drain scenarios.
    Metric: scenarios/sec/chip."""
    from opensim_tpu.planner.defrag import plan_drains

    cluster = synthetic_cluster(n_nodes)
    apps = [AppResource("bench", synthetic_apps(n_pods))]
    candidates = [n.metadata.name for n in cluster.nodes[:n_scenarios]]
    if warmup:
        plan_drains(cluster, apps, candidates=candidates[:8])
    t0 = time.time()
    result = plan_drains(cluster, apps, candidates=candidates)
    dt = time.time() - t0
    print(
        json.dumps(
            {
                "metric": f"defrag sweep ({len(candidates)} drain scenarios, {n_pods} pods/{n_nodes} nodes)",
                "value": round(len(candidates) / dt, 2),
                "unit": "scenarios/s/chip",
                "vs_baseline": round(len(candidates) / dt, 2),  # no reference number exists
                "drainable": len(result.drainable()),
                "wall_s": round(dt, 2),
            }
        )
    )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=50000)
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--warmup", action="store_true", help="run once first to populate the jit cache")
    ap.add_argument(
        "--config",
        default="plan",
        choices=["plan", "defrag"],
        help="plan = capacity-plan wall-clock (headline); defrag = drain-scenario sweep",
    )
    ap.add_argument("--scenarios", type=int, default=1000, help="defrag: number of drain scenarios")
    args = ap.parse_args()

    if args.config == "defrag":
        return bench_defrag(args.scenarios, args.nodes, args.pods, args.warmup)

    cluster = synthetic_cluster(args.nodes)
    apps = [AppResource("bench", synthetic_apps(args.pods))]

    if args.warmup:
        simulate(cluster, apps, node_pad=128)

    t0 = time.time()
    result = simulate(cluster, apps, node_pad=128)
    dt = time.time() - t0

    scheduled = sum(len(ns.pods) for ns in result.node_status)
    target_s = 10.0
    print(
        json.dumps(
            {
                "metric": f"{args.pods // 1000}k-pod/{args.nodes // 1000}k-node capacity plan wall-clock",
                "value": round(dt, 3),
                "unit": "s",
                "vs_baseline": round(target_s / dt, 2) if dt > 0 else 0.0,
                "scheduled": scheduled,
                "unscheduled": len(result.unscheduled_pods),
                "pods_per_sec": round((scheduled + len(result.unscheduled_pods)) / dt, 1),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
