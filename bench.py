#!/usr/bin/env python
"""Headline benchmark: the 50k-pod / 5k-node capacity plan.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
`value` = wall-clock seconds for the full plan (workload expansion →
encoding → 50k-step scheduling scan → decode), measured on the available
accelerator. `vs_baseline` = the <10 s target from BASELINE.md divided by
the measured time (>1 means the target is beaten). The reference publishes
no numbers (SURVEY.md §6), so the driver-set target is the yardstick.

Usage: python bench.py [--pods N] [--nodes N] [--config NAME] [--scenarios N]
"""

import argparse
import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# persistent XLA compilation cache (utils/jitcache.py): default dir under
# ~/.cache/opensim-tpu so the cold_s trajectory is comparable across runs;
# OPENSIM_JIT_CACHE=0 opts out, JAX_COMPILATION_CACHE_DIR still wins
from opensim_tpu.utils.jitcache import maybe_enable  # noqa: E402

maybe_enable(default=True)

from opensim_tpu.utils.probe import ensure_accelerator_or_cpu  # noqa: E402

BACKEND_NOTE = ensure_accelerator_or_cpu()

import numpy as np  # noqa: E402

from opensim_tpu.engine.simulator import AppResource, simulate  # noqa: E402
from opensim_tpu.models import ResourceTypes, fixtures as fx  # noqa: E402


# failure contract (NOTES invariant: the driver parses exactly ONE JSON
# line from stdout): every failure path must emit a single-line JSON error
# object and exit nonzero — never a bare traceback. _STAGE tracks how far
# the run got so the error line says which phase died.
_STAGE = ["startup"]


def _stage(name: str) -> None:
    _STAGE[0] = name


def _fmt(n: int) -> str:
    return f"{n // 1000}k" if n >= 1000 and n % 1000 == 0 else str(n)


def _serial_floors(config: str, pods: int, nodes: int):
    """Measured serial baselines (tools/serial_baseline.py) for the same
    workload at the same shape, if recorded. Returns (python_rec, cxx_rec),
    either None. The python-serial floor UNDERSTATES the Go reference's
    speed; the c++-serial row (native/serial_engine.cc) is the measured
    stand-in for the Go constant factor. bench's `plan` config and the
    baseline tool's `synthetic` use the same generators, so either key
    matches by shape."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BASELINE_MEASURED.json")
    try:
        with open(path) as f:
            measured = json.load(f)
    except (OSError, ValueError):
        return None, None
    cfgs = {"plan": ("plan", "synthetic")}.get(config, (config,))

    def find(cxx):
        for rec in measured.values():
            if not isinstance(rec, dict) or rec.get("config") not in cfgs:
                continue
            # classify by the record's own impl field, not key naming
            if str(rec.get("impl", "")).startswith("c++") != cxx:
                continue
            if rec.get("pods") == pods and rec.get("nodes") == nodes:
                return rec
        return None

    return find(False), find(True)


def synthetic_cluster(n_nodes: int) -> ResourceTypes:
    rt = ResourceTypes()
    zones = [f"zone-{z}" for z in range(4)]
    for i in range(n_nodes):
        rt.nodes.append(
            fx.make_fake_node(
                f"node-{i:05d}",
                "64",
                "256Gi",
                "256",
                fx.with_labels(
                    {
                        "topology.kubernetes.io/zone": zones[i % len(zones)],
                        "node-role.kubernetes.io/worker": "",
                        "disk": "ssd" if i % 3 else "hdd",
                    }
                ),
            )
        )
    return rt


def synthetic_apps(n_pods: int) -> ResourceTypes:
    """~20 workload templates covering the kernel surface: resource fit,
    tolerations, node selectors, spread, anti-affinity."""
    rt = ResourceTypes()
    n_workloads = 20
    per = n_pods // n_workloads
    for w in range(n_workloads):
        opts = []
        if w % 4 == 0:
            opts.append(fx.with_node_selector({"disk": "ssd"}))
        if w % 5 == 0:
            opts.append(
                fx.with_topology_spread(
                    [
                        {
                            "maxSkew": 5,
                            "topologyKey": "topology.kubernetes.io/zone",
                            "whenUnsatisfiable": "ScheduleAnyway",
                            "labelSelector": {"matchLabels": {"app": f"bench-{w}"}},
                        }
                    ]
                )
            )
        rt.deployments.append(
            fx.make_fake_deployment(
                f"bench-{w}", per, f"{100 + 20 * (w % 8)}m", f"{256 + 64 * (w % 6)}Mi", *opts
            )
        )
    return rt


def bigu_apps(n_pods: int, n_templates: int = 1000) -> ResourceTypes:
    """Template-heavy workload (verdict envelope target: 1000 distinct pod
    specs): exercises the megakernel's big-U mode (HBM template tables)."""
    rt = ResourceTypes()
    per = max(n_pods // n_templates, 1)
    for w in range(n_templates):
        rt.deployments.append(
            fx.make_fake_deployment(
                f"t{w:04d}", per, f"{100 + (w % 400)}m", f"{128 + (w % 97)}Mi"
            )
        )
    return rt


def forced_cluster(n_nodes: int, n_bound: int) -> ResourceTypes:
    """Live-cluster replay shape: a snapshot full of pre-bound pods (the
    server re-binds them as forced pods every request)."""
    rt = synthetic_cluster(n_nodes)
    for i in range(n_bound):
        rt.pods.append(
            fx.make_fake_pod(
                f"bound-{i:05d}", "500m", "1Gi", fx.with_node_name(f"node-{i % n_nodes:05d}")
            )
        )
    return rt


def _tmpl_annotate(deploy, anno: dict) -> None:
    """Pod-TEMPLATE annotations on a workload (gpu-share / open-local pod
    requests live on the pod template, not the controller metadata)."""
    deploy.template_metadata.annotations.update(anno)
    deploy.template_raw.setdefault("metadata", {}).setdefault(
        "annotations", {}
    ).update(anno)


def gpu_cluster(n_nodes: int) -> ResourceTypes:
    """All-GPU fleet (ISSUE-19 envelope target): every node advertises
    gpu-share devices — 8 × 8Gi per the reference NewGpuNodeInfo semantics
    (per-device memory = total gpu-mem / gpu-count)."""
    rt = ResourceTypes()
    zones = [f"zone-{z}" for z in range(4)]
    for i in range(n_nodes):
        rt.nodes.append(
            fx.make_fake_node(
                f"node-{i:05d}", "64", "256Gi", "256",
                fx.with_labels({"topology.kubernetes.io/zone": zones[i % len(zones)]}),
                fx.with_allocatable({
                    "alibabacloud.com/gpu-mem": "64Gi",
                    "alibabacloud.com/gpu-count": "8",
                }),
            )
        )
    return rt


def gpu_apps(n_pods: int) -> ResourceTypes:
    """All-GPU workload mix: gpu-share templates (pod-template gpu-mem
    annotations → the per-GPU-index headroom carry) plus whole-GPU
    templates (spec gpu-count requests → the gc_dyn dynamic-allocatable
    filter/score, Reserve-rewritten at every bind)."""
    rt = ResourceTypes()
    n_workloads = 10
    per = n_pods // n_workloads
    for w in range(n_workloads):
        if w % 5 == 4:
            rt.deployments.append(
                fx.make_fake_deployment(
                    f"gpu-{w}", per, "250m", "512Mi",
                    fx.with_requests({"alibabacloud.com/gpu-count": "1"}),
                )
            )
            continue
        d = fx.make_fake_deployment(f"gpu-{w}", per, "250m", "512Mi")
        _tmpl_annotate(d, {
            "alibabacloud.com/gpu-mem": f"{2 + 2 * (w % 3)}Gi",
            "alibabacloud.com/gpu-count": "1",
        })
        rt.deployments.append(d)
    return rt


def local_pv_cluster(n_nodes: int) -> ResourceTypes:
    """All-local-PV fleet (ISSUE-19 envelope target): every node carries an
    open-local LVM volume group plus exclusive devices."""
    rt = ResourceTypes()
    zones = [f"zone-{z}" for z in range(4)]
    for i in range(n_nodes):
        rt.nodes.append(
            fx.make_fake_node(
                f"node-{i:05d}", "64", "256Gi", "256",
                fx.with_labels({"topology.kubernetes.io/zone": zones[i % len(zones)]}),
                fx.with_node_local_storage(
                    vgs=[{"name": "pool0", "capacity": 600 * 1024**3}],
                    devices=[
                        {"device": "/dev/vdb", "capacity": 100 * 1024**3, "mediaType": "ssd"},
                        {"device": "/dev/vdc", "capacity": 100 * 1024**3, "mediaType": "ssd"},
                    ],
                ),
            )
        )
    return rt


def local_pv_apps(n_pods: int) -> ResourceTypes:
    """All-local-PV workload mix: every template requests an open-local LVM
    volume (per-disk allocation carry + the w_local score term); one
    template in ten adds an exclusive SSD device volume."""
    rt = ResourceTypes()
    n_workloads = 10
    per = n_pods // n_workloads
    for w in range(n_workloads):
        vols = [{
            "size": str((5 + 5 * (w % 3)) * 1024**3),
            "kind": "LVM", "scName": "open-local-lvm",
        }]
        if w == 4:
            vols.append({
                "size": str(20 * 1024**3),
                "kind": "SSD", "scName": "open-local-device",
            })
        d = fx.make_fake_deployment(f"loc-{w}", per, "250m", "512Mi")
        _tmpl_annotate(d, {"simon/pod-local-storage": json.dumps({"volumes": vols})})
        rt.deployments.append(d)
    return rt


def _verify_envelope(cluster, apps) -> dict:
    """ISSUE 19 in-row bit-equality gates (gpu / local-pv configs): one
    shared Prepared encoding driven through the incremental C++ path, the
    forced-generic C++ path, and the XLA scan — placements, failure
    attribution, and final state must agree element-for-element."""
    from opensim_tpu.engine import nativepath
    from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
    from opensim_tpu.engine.simulator import prepare

    prep = prepare(cluster, apps, node_pad=128)
    P = len(prep.ordered)
    pv = np.ones(P, bool)
    inc = nativepath.schedule(prep, pv)
    prior = os.environ.get("OPENSIM_NATIVE_FORCE_GENERIC")
    os.environ["OPENSIM_NATIVE_FORCE_GENERIC"] = "1"
    try:
        gen = nativepath.schedule(prep, pv)
    finally:
        if prior is None:
            del os.environ["OPENSIM_NATIVE_FORCE_GENERIC"]
        else:
            os.environ["OPENSIM_NATIVE_FORCE_GENERIC"] = prior
    t, v, f = pad_pod_stream(prep.tmpl_ids, pv, prep.forced)
    xout = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    inc_stats = inc.native_stats or {}
    gen_stats = gen.native_stats or {}
    return {
        "verify_native_path": inc_stats.get("path"),
        "verify_classes": (inc_stats.get("steps") or {}).get("classes") or {},
        "placements_identical_generic": int(
            gen_stats.get("path") == "generic"
            and np.array_equal(inc.chosen, gen.chosen)
            and np.array_equal(inc.fail_counts, gen.fail_counts)
            and np.array_equal(inc.final_state.used, gen.final_state.used)
        ),
        "placements_identical_xla": int(
            np.array_equal(np.asarray(xout.chosen)[:P], inc.chosen)
            and np.array_equal(np.asarray(xout.fail_counts)[:P], inc.fail_counts)
            and np.array_equal(np.asarray(xout.final_state.used), inc.final_state.used)
        ),
    }


def bench_defrag(n_scenarios: int, n_nodes: int, n_pods: int, warmup: bool) -> int:
    """BASELINE.md config 5: parallel what-if node-drain scenarios.
    Metric: scenarios/sec/chip."""
    from opensim_tpu.planner.defrag import plan_drains

    cluster = synthetic_cluster(n_nodes)
    apps = [AppResource("bench", synthetic_apps(n_pods))]
    candidates = [n.metadata.name for n in cluster.nodes[:n_scenarios]]
    if warmup:
        plan_drains(cluster, apps, candidates=candidates[:8])
    t0 = time.time()
    result = plan_drains(cluster, apps, candidates=candidates)
    dt = time.time() - t0
    record = {
        "metric": f"defrag sweep ({len(candidates)} drain scenarios, {n_pods} pods/{n_nodes} nodes)",
        "value": round(len(candidates) / dt, 2),
        "unit": "scenarios/s/chip",
        "vs_baseline": round(len(candidates) / dt, 2),  # no reference number exists
        "drainable": len(result.drainable()),
        "wall_s": round(dt, 2),
    }
    serial, cxx = _serial_floors("defrag", n_pods, n_nodes)
    if serial and serial.get("scenarios_per_sec"):
        record["vs_serial"] = round(record["value"] / serial["scenarios_per_sec"], 1)
    if cxx and cxx.get("scenarios_per_sec"):
        record["vs_serial_cxx"] = round(record["value"] / cxx["scenarios_per_sec"], 1)
    print(json.dumps(record))
    return 0


def _campaign_inputs(n_nodes: int, n_pods: int):
    """Synthetic lifecycle-campaign scenario (ISSUE 13): the bench cluster
    owns the workloads (campaigns drain/reschedule cluster pods), a quarter
    of them guarded by PDBs, and the campaign mixes the four acceptance
    step shapes: PDB-aware drain wave, reclaim storm, deploy, scale-down
    check."""
    from opensim_tpu.models.objects import PodDisruptionBudget

    cluster = synthetic_cluster(n_nodes)
    cluster.deployments.extend(synthetic_apps(n_pods).deployments)
    for w in cluster.deployments[:5]:
        cluster.pdbs.append(
            PodDisruptionBudget.from_dict(
                {
                    "apiVersion": "policy/v1",
                    "kind": "PodDisruptionBudget",
                    "metadata": {"name": f"{w.metadata.name}-pdb", "namespace": "default"},
                    "spec": {
                        "maxUnavailable": "25%",
                        "selector": {"matchLabels": {"app": w.metadata.name}},
                    },
                }
            )
        )
    drain_n = max(2, n_nodes // 10)
    storm_n = max(1, n_nodes // 20)
    steps = [
        {"name": "upgrade", "type": "drain-wave", "count": drain_n, "wave": max(1, drain_n // 4)},
        {"name": "spot-storm", "type": "reclaim-storm", "count": storm_n},
        {
            "name": "push",
            "type": "deploy",
            "app": {"name": "push"},
            "resources": [
                {
                    "apiVersion": "apps/v1",
                    "kind": "Deployment",
                    "metadata": {"name": "push", "namespace": "default"},
                    "spec": {
                        "replicas": max(4, n_pods // 20),
                        "selector": {"matchLabels": {"app": "push"}},
                        "template": {
                            "metadata": {"labels": {"app": "push"}},
                            "spec": {
                                "containers": [
                                    {
                                        "name": "c",
                                        "resources": {
                                            "requests": {"cpu": "250m", "memory": "512Mi"}
                                        },
                                    }
                                ]
                            },
                        },
                    },
                }
            ],
        },
        {"name": "shrink-check", "type": "scale-down-check", "count": 8},
    ]
    return cluster, steps


def bench_campaign(n_nodes: int, n_pods: int, warmup: bool) -> int:
    """Campaign-engine throughput (ISSUE 13): a 4-step mixed lifecycle
    campaign (drain wave w/ PDBs + reclaim storm + deploy + scale-down
    check) on the warm delta path. Metrics: steps/s and pods rescheduled/s;
    at small sizes the row also gates warm-vs-cold fingerprint equality
    in-row (the delta-execution proof)."""
    from opensim_tpu.planner import campaign as campaign_mod

    cluster, steps_raw = _campaign_inputs(n_nodes, n_pods)
    if warmup:
        campaign_mod.run_campaign(cluster, campaign_mod.parse_steps(steps_raw), mode="warm")
    t0 = time.time()
    res = campaign_mod.run_campaign(
        cluster, campaign_mod.parse_steps(steps_raw), mode="warm", name="bench"
    )
    dt = time.time() - t0
    n_steps = len(res.steps)
    rescheduled = sum(s.rescheduled for s in res.steps)
    record = {
        "metric": f"campaign ({n_steps} scored steps, {_fmt(n_pods)} pods/{_fmt(n_nodes)} nodes)",
        "value": round(dt, 3),
        "unit": "s",
        "vs_baseline": round(10.0 / dt, 2) if dt > 0 else 0.0,
        "config": "campaign",
        "steps": n_steps,
        "steps_per_s": round(n_steps / dt, 2) if dt > 0 else 0.0,
        "rescheduled": rescheduled,
        "rescheduled_per_s": round(rescheduled / dt, 1) if dt > 0 else 0.0,
        "evicted": sum(s.evicted for s in res.steps),
        "blocked": sum(len(s.blocked) for s in res.steps),
        # pods still pending at campaign end (the capacity sample, not the
        # last step's scan report — a what-if final step never scans)
        "unschedulable": int((res.steps[-1].capacity or {}).get("pods_pending", 0)),
        "full_prepares": res.full_prepares,
        "fingerprint": res.fingerprint,
    }
    if n_pods <= 5000:
        # the delta-execution gate, in-row: the warm campaign's per-step
        # fingerprints must be bit-identical to cold per-step prepares
        cold = campaign_mod.run_campaign(
            cluster, campaign_mod.parse_steps(steps_raw), mode="cold", name="bench"
        )
        record["verified_vs_cold"] = bool(
            [s.fingerprint for s in res.steps] == [s.fingerprint for s in cold.steps]
        )
        if not record["verified_vs_cold"]:
            raise RuntimeError("campaign warm-delta fingerprints diverged from cold per-step prepares")
    if BACKEND_NOTE:
        record["backend"] = BACKEND_NOTE
    print(json.dumps(record))
    return 0


def affinity_apps(n_pods: int) -> ResourceTypes:
    """BASELINE.md config 4: InterPodAffinity + PodTopologySpread heavy."""
    rt = ResourceTypes()
    n_workloads = 10
    per = n_pods // n_workloads
    for w in range(n_workloads):
        opts = [
            fx.with_topology_spread(
                [
                    {
                        "maxSkew": 3,
                        "topologyKey": "topology.kubernetes.io/zone",
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": f"aff-{w}"}},
                    }
                ]
            )
        ]
        if w % 2 == 0:
            opts.append(
                fx.with_affinity(
                    {
                        "podAntiAffinity": {
                            "preferredDuringSchedulingIgnoredDuringExecution": [
                                {
                                    "weight": 100,
                                    "podAffinityTerm": {
                                        "labelSelector": {"matchLabels": {"app": f"aff-{w}"}},
                                        "topologyKey": "kubernetes.io/hostname",
                                    },
                                }
                            ]
                        }
                    }
                )
            )
        else:
            opts.append(
                fx.with_affinity(
                    {
                        "podAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": [
                                {
                                    "labelSelector": {"matchLabels": {"app": f"aff-{w - 1}"}},
                                    "topologyKey": "topology.kubernetes.io/zone",
                                }
                            ]
                        }
                    }
                )
            )
        rt.deployments.append(fx.make_fake_deployment(f"aff-{w}", per, "100m", "256Mi", *opts))
    return rt


def bench_reference_example(config_path: str, extended: str, warmup: bool, label: str) -> int:
    """BASELINE.md configs 1-2: the reference repo's example simon configs,
    run through the full `simon apply` pipeline."""
    from opensim_tpu.planner.apply import Applier, Options

    def run() -> float:
        t0 = time.time()
        rc = Applier(
            Options(
                simon_config=config_path,
                output_file="/dev/null",
                extended_resources=[r for r in extended.split(",") if r],
            )
        ).run()
        if rc != 0:
            raise RuntimeError(f"simon apply failed with rc={rc}")
        return time.time() - t0

    if warmup:
        run()
    dt = run()
    print(
        json.dumps(
            {
                "metric": f"simon apply {label} wall-clock",
                "value": round(dt, 3),
                "unit": "s",
                "vs_baseline": round(1.0 / dt, 2) if dt > 0 else 0.0,  # reference trace threshold: 1 s
            }
        )
    )
    return 0


def _core_guard_note(config: str, host_cores: int):
    """Serving QPS is core-count-bound: comparing a fresh row against a
    baseline recorded on a different core count measures the boxes, not
    the code. Every serving row records host_cores; when the committed
    baseline for this config was measured on a different count, the row
    carries an explicit refusal note (and tools/perf_guard.py refuses to
    compute ratios at all). Returns None when comparable or unknown."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    try:
        with open(path) as f:
            baselines = json.load(f).get("baselines", {})
    except (OSError, ValueError):
        return None
    for entry in baselines.values():
        row = entry.get("row", {})
        if row.get("config") != config or "host_cores" not in row:
            continue
        if int(row["host_cores"]) != host_cores:
            return (
                f"refused: baseline measured on {row['host_cores']} core(s), "
                f"this box has {host_cores} — re-baseline on a same-core box"
            )
    return None


def bench_serving(concurrency: int, duration_s: float) -> int:
    """ISSUE 8 + 16 acceptance run: the closed loop against live
    stub-backed twin servers — single-flight vs admission queue +
    request-axis batching (ISSUE 8 pair), then serial-batch vs the staged
    admission pipeline with the placement-parity gate and the measured
    prep-under-dispatch overlap (ISSUE 16 pair) — ALL numbers in the one
    JSON line. The bars: qps ≥ 4× qps_single_flight at bounded p99, and
    pipelined ≥ 2× non-pipelined (the multiple needs ≥4 host cores; the
    row records host_cores so cross-box readers can tell)."""
    from opensim_tpu.server.loadgen import run_pipeline_benchmark, run_stub_benchmark

    _stage("serving")
    # hundreds of clients need sharded client processes or the loadgen's
    # own GIL throttles the offered load (docs/serving.md)
    client_procs = 4 if concurrency >= 128 else 0
    report = run_stub_benchmark(
        concurrency=concurrency, duration_s=duration_s, base_port=18980,
        client_procs=client_procs,
    )
    _stage("serving-pipeline")
    pipe = run_pipeline_benchmark(
        concurrency=concurrency, duration_s=duration_s, base_port=19080,
        client_procs=client_procs,
    )
    record = {
        "metric": (
            f"serving closed loop ({concurrency} clients, "
            f"{duration_s:.0f}s, stub-apiserver twin)"
        ),
        "value": pipe["qps"],
        "unit": "req/s",
        "config": "serving",
        # the ISSUE 8 acceptance pair: batched QPS vs the seed's single-flight
        "qps_single_flight": report["qps_single_flight"],
        "qps_admission": report["qps"],
        "vs_single_flight": report["speedup"],
        "p50_s": pipe["p50_s"],
        "p99_s": pipe["p99_s"],
        "p99_single_flight_s": report["p99_single_flight_s"],
        "batches": pipe["batches"],
        "mean_batch_size": pipe["mean_batch_size"],
        "shed": pipe["shed"],
        "shed_single_flight": report["shed_single_flight"],
        "errors": pipe["errors"],
        "queue_wait_p99_s": report["admission"]["queue_wait_p99_s"],
        # the ISSUE 16 acceptance pair: staged pipeline vs serial batches,
        # same box, same stub cluster, plus the in-row parity gate
        "qps_non_pipelined": pipe["qps_non_pipelined"],
        "vs_non_pipelined": pipe["vs_non_pipelined"],
        "p99_non_pipelined_s": pipe["p99_non_pipelined_s"],
        "overlapped_batches": pipe["overlapped_batches"],
        "prep_overlap_s": pipe["prep_overlap_s"],
        "placements_identical": pipe["placements_identical"],
        "client_procs": client_procs,
        "host_cores": os.cpu_count() or 0,
    }
    note = _core_guard_note("serving", record["host_cores"])
    if note:
        record["baseline_comparison"] = note
    if BACKEND_NOTE:
        record["backend_note"] = BACKEND_NOTE
    print(json.dumps(record))
    return 0


def bench_serving_fleet(workers: int, concurrency: int, duration_s: float) -> int:
    """ISSUE 15 acceptance run: the closed loop against a multi-process
    fleet (`--workers N`: twin owner + shm publication + SO_REUSEPORT
    workers) vs ONE single-process admission server, same stub cluster,
    same concurrency. The bar is fleet qps above single-process at p99 no
    worse, placements bit-identical (the in-row ``placements_identical``
    gate), and zero torn-generation attach abandonments."""
    from opensim_tpu.server.loadgen import run_fleet_benchmark

    _stage("serving-fleet")
    report = run_fleet_benchmark(
        workers=workers, concurrency=concurrency, duration_s=duration_s,
        base_port=19480,
        # hundreds of clients need sharded client processes or the
        # loadgen's own GIL throttles the offered load (docs/serving.md)
        client_procs=4 if concurrency >= 128 else 0,
    )
    record = {
        "metric": (
            f"fleet serving closed loop ({concurrency} clients, "
            f"{duration_s:.0f}s, {workers}-worker shm fleet vs single process)"
        ),
        "value": report["qps"],
        "unit": "req/s",
        "config": "serving-fleet",
        "workers": workers,
        # the acceptance pair: fleet QPS vs one admission-batched process
        "qps_single_process": report["qps_single_process"],
        "vs_single_process": report["vs_single_process"],
        "p50_s": report["p50_s"],
        "p99_s": report["p99_s"],
        "p99_single_process_s": report["p99_single_process_s"],
        "batches": report["batches"],
        "mean_batch_size": report["mean_batch_size"],
        "shed": report["shed"],
        "errors": report["errors"],
        # in-row gates: bit-identical placements across the process
        # boundary, zero seqlock-retry exhaustion, no crash-respawns
        "placements_identical": report["placements_identical"],
        "torn_generation_exhausted": report["torn_generation_exhausted"],
        "respawns": report["respawns"],
        "fleet_generation": report["fleet_generation"],
        "fleet_publishes": report["fleet_publishes"],
        # context for cross-box comparison: on a 2-core box the workers
        # and the sharded clients contend for the same cores, so the
        # fleet's headroom shows as p99 first, absolute QPS second
        "host_cores": os.cpu_count() or 0,
    }
    note = _core_guard_note("serving-fleet", record["host_cores"])
    if note:
        record["baseline_comparison"] = note
    if BACKEND_NOTE:
        record["backend_note"] = BACKEND_NOTE
    print(json.dumps(record))
    return 0


def _synth_storm_journal(path: str, n_events: int, n_nodes: int) -> None:
    """Record a synthetic event storm into a fresh journal: one checkpoint
    anchoring a node fleet, then a pod churn stream (adds, node-bound adds,
    and deletes — tombstones included) with monotonic resourceVersions, the
    same wire shapes the live twin journals."""
    from opensim_tpu.server.journal import Journal

    cluster = synthetic_cluster(n_nodes)
    journal = Journal(path, policy={"fsync": "off"})
    try:
        rv = 1000
        journal.record_checkpoint(
            {"nodes": [n.raw for n in cluster.nodes]},
            generation=1,
            resume_rvs={"nodes": str(rv), "pods": str(rv)},
            why="bench",
        )
        gen = 1
        for i in range(n_events):
            rv += 1
            gen += 1
            if i % 10 == 9:
                # a delete of an earlier add: replay must tombstone it
                victim = i - 9 + (i % 3)
                journal.record_event(
                    "pods", "DELETED",
                    {"metadata": {"name": f"storm-{victim:06d}", "namespace": "bench",
                                  "resourceVersion": str(rv)}},
                    gen,
                )
                continue
            pod = {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"storm-{i:06d}", "namespace": "bench",
                             "resourceVersion": str(rv)},
                "spec": {"containers": [
                    {"name": "c", "resources": {"requests": {
                        "cpu": "100m", "memory": "256Mi"}}}
                ]},
                "status": {"phase": "Pending"},
            }
            if i % 3:
                pod["spec"]["nodeName"] = f"node-{i % n_nodes:05d}"
                pod["status"]["phase"] = "Running"
            journal.record_event("pods", "ADDED", pod, gen)
    finally:
        journal.close()


def bench_replay(journal_path: str, n_events: int, n_nodes: int, speed: float) -> int:
    """ISSUE 11 benchmark row: stream a recorded (or synthesized) watch-event
    journal through the twin's apply path + the capacity observatory at
    ``speed``× (0 = as fast as possible) and report event throughput. The
    random-access ``rebuild_twin`` view must land bit-equal to the streamed
    replay — the determinism gate that makes recorded production traces a
    repeatable scenario corpus (docs/live-twin.md 'Durability & replay')."""
    import tempfile

    _stage("replay")
    label = journal_path
    tmp = None
    if not journal_path:
        tmp = tempfile.mkdtemp(prefix="bench-replay-")
        journal_path = os.path.join(tmp, "journal")
        label = f"synthetic storm ({_fmt(n_events)} events, {_fmt(n_nodes)} nodes)"
        _synth_storm_journal(journal_path, n_events, n_nodes)
    try:
        return _bench_replay_run(journal_path, label, speed)
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


def _bench_replay_run(journal_path: str, label: str, speed: float) -> int:
    from opensim_tpu.obs.capacity import CapacityEngine
    from opensim_tpu.server.journal import rebuild_twin, replay_events

    capacity = CapacityEngine()
    counts = {}
    twin = None
    t0 = time.time()
    for rec, twin, change in replay_events(journal_path, speed=speed):
        counts[rec["t"]] = counts.get(rec["t"], 0) + 1
        capacity.on_replay(rec, twin, change)
    wall = time.time() - t0
    if twin is None:
        raise RuntimeError(f"{journal_path}: no replayable records")
    fp = twin.fingerprint()
    rebuilt, meta = rebuild_twin(journal_path)
    if rebuilt.fingerprint() != fp:
        raise RuntimeError(
            "rebuild_twin fingerprint diverged from the streamed replay "
            f"({rebuilt.fingerprint()} != {fp})"
        )
    events = counts.get("ev", 0)
    sample = capacity.sample()
    record = {
        "metric": f"journal replay event storm ({label})",
        "value": round(wall, 3),
        "unit": "s",
        "config": "replay",
        "events": events,
        "rebases": counts.get("rb", 0),
        "checkpoints": counts.get("ck", 0),
        "events_per_s": round(events / wall, 1) if wall > 0 else 0.0,
        "generation": twin.generation,
        "fingerprint": fp,
        "rebuild_bit_equal": True,
        "speed": speed,
    }
    if sample is not None:
        record["nodes"] = sample.nodes
        record["pods_bound"] = sample.pods_bound
        record["pods_pending"] = sample.pods_pending
        record["cpu_utilization"] = round(sample.utilization.get("cpu", 0.0), 4)
    if BACKEND_NOTE:
        record["backend_note"] = BACKEND_NOTE
    print(json.dumps(record))
    return 0


def bench_steady(n_pods: int, n_nodes: int, repeats: int) -> int:
    """Steady-state re-simulation: N repeated simulates against ONE cluster
    through the encode cache (opensim_tpu/engine/prepcache.py). The metric
    pair that matters is host_prep_s (warm, cache-hit prepare) vs
    cold_host_prep_s (the one full expand+encode) — the incremental-prepare
    acceptance bar is warm ≥ 5× faster than cold."""
    import statistics

    from opensim_tpu.engine import prepcache
    from opensim_tpu.utils.trace import PREP_STATS

    cluster = synthetic_cluster(n_nodes)
    apps = [AppResource("bench", synthetic_apps(n_pods))]
    cache = prepcache.PrepareCache()
    PREP_STATS.reset()

    t0 = time.time()
    r0 = prepcache.simulate_cached(cluster, apps, cache, node_pad=128)
    cold_s = time.time() - t0
    cold_prep_s = PREP_STATS.snapshot()["seconds"].get("full", 0.0)
    scheduled0 = sum(len(ns.pods) for ns in r0.node_status)

    warm_wall, warm_prep = [], []
    for _ in range(repeats):
        t0 = time.time()
        r = prepcache.simulate_cached(cluster, apps, cache, node_pad=128)
        warm_wall.append(time.time() - t0)
        kind, secs = PREP_STATS.snapshot()["last"]
        if kind != "hit":
            raise RuntimeError(f"steady-state iteration re-prepared (kind={kind})")
        warm_prep.append(secs)
        scheduled = sum(len(ns.pods) for ns in r.node_status)
        if scheduled != scheduled0 or len(r.unscheduled_pods) != len(r0.unscheduled_pods):
            raise RuntimeError("cached re-simulation diverged from the cold run")

    host_prep_s = statistics.median(warm_prep)
    record = {
        "metric": f"steady-state re-simulation ({_fmt(n_pods)} pods/{_fmt(n_nodes)} nodes, {repeats} warm runs)",
        "value": round(statistics.median(warm_wall), 3),
        "unit": "s",
        "vs_baseline": round(cold_s / statistics.median(warm_wall), 2),
        "host_prep_s": round(host_prep_s, 4),
        "cold_host_prep_s": round(cold_prep_s, 3),
        "prep_speedup": round(cold_prep_s / host_prep_s, 1) if host_prep_s > 0 else float("inf"),
        "cold_s": round(cold_s, 3),
        "prep_cache": cache.stats.as_dict(),
        "scheduled": scheduled0,
        "unscheduled": len(r0.unscheduled_pods),
    }
    if BACKEND_NOTE:
        record["backend"] = BACKEND_NOTE
    print(json.dumps(record))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=50000)
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument(
        "--warmup",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="run once first to populate the jit cache (--no-warmup to measure cold)",
    )
    ap.add_argument(
        "--config",
        default="plan",
        choices=["plan", "defrag", "affinity", "gpu", "local-pv", "example", "gpushare", "bigu", "forced", "steady", "serving", "replay", "campaign"],
        help=(
            "plan = capacity-plan wall-clock (headline); defrag = drain-scenario "
            "sweep; affinity = interpod+spread heavy; gpu = all-GPU-share + "
            "whole-GPU (gc_dyn) envelope row; local-pv = all-open-local "
            "LVM/device envelope row (both carry in-row bit-equality gates "
            "vs the generic C++ path and the XLA scan); example/gpushare = the "
            "shipped example simon configs; bigu = 1000 distinct templates "
            "(big-U megakernel mode); forced = live-cluster replay (90%% "
            "pre-bound pods); steady = repeated re-simulation of one cluster "
            "through the encode cache (host-side prepare trajectory); serving "
            "= closed-loop QPS of the live server, admission-batched vs "
            "single-flight (docs/serving.md); replay = stream a recorded "
            "watch-event journal (--journal, or a synthesized storm) through "
            "the twin + capacity observatory (docs/live-twin.md)"
        ),
    )
    ap.add_argument(
        "--journal", default="",
        help="replay: journal directory recorded by `simon server --journal` "
        "(default: synthesize an event storm of --events events)",
    )
    ap.add_argument("--events", type=int, default=20000, help="replay: synthesized storm size")
    ap.add_argument(
        "--speed", type=float, default=0.0,
        help="replay: pace at N× recorded gaps (0 = as fast as possible)",
    )
    ap.add_argument("--concurrency", type=int, default=48, help="serving: closed-loop clients")
    ap.add_argument("--duration", type=float, default=10.0, help="serving: measured seconds per mode")
    ap.add_argument(
        "--workers", type=int, default=0,
        help="serving: ≥2 measures the multi-process fleet (`simon server "
        "--workers N`, docs/serving.md 'Scaling past one process') against "
        "a single-process admission server instead of admission vs "
        "single-flight",
    )
    ap.add_argument("--scenarios", type=int, default=1000, help="defrag: number of drain scenarios")
    ap.add_argument("--repeats", type=int, default=10, help="steady: number of warm re-simulations")
    ap.add_argument(
        "--explain",
        action="store_true",
        help=(
            "run the measured simulation with the decision audit enabled "
            "(plan-family configs): the JSON line gains filter_rejects (nodes "
            "rejected per filter across all steps) and unschedulable_reasons. "
            "Forces the C++ generic path / XLA count_all scan, so the wall "
            "time measures the audited path, not the headline"
        ),
    )
    ap.add_argument(
        "--trace",
        default="",
        metavar="FILE",
        help=(
            "write a Chrome-trace/Perfetto JSON of the measured run (plan-"
            "family configs): every phase span — prepare, encode, engine "
            "attempts, decode — with the C++ engine's profile attached. "
            "Load at chrome://tracing or ui.perfetto.dev"
        ),
    )
    args = ap.parse_args()
    _stage("measure")

    repo = os.path.dirname(os.path.abspath(__file__))
    if args.config == "serving":
        if args.workers >= 2:
            return bench_serving_fleet(args.workers, args.concurrency, args.duration)
        return bench_serving(args.concurrency, args.duration)
    if args.config == "replay":
        return bench_replay(args.journal, args.events, args.nodes, args.speed)
    if args.config == "steady":
        return bench_steady(args.pods, args.nodes, args.repeats)
    if args.config == "campaign":
        return bench_campaign(args.nodes, args.pods, args.warmup)
    if args.config == "defrag":
        return bench_defrag(args.scenarios, args.nodes, args.pods, args.warmup)
    if args.config == "example":
        return bench_reference_example(
            os.path.join(repo, "example/simon-config.yaml"), "", args.warmup, "example/simon-config"
        )
    if args.config == "gpushare":
        return bench_reference_example(
            os.path.join(repo, "example/simon-gpushare-config.yaml"),
            "gpu",
            args.warmup,
            "example/simon-gpushare-config",
        )

    if args.config == "forced":
        # 90% of the pod stream is pre-bound snapshot pods
        cluster = forced_cluster(args.nodes, int(args.pods * 0.9))
        apps = [AppResource("bench", synthetic_apps(args.pods - int(args.pods * 0.9)))]
    elif args.config == "gpu":
        cluster = gpu_cluster(args.nodes)
    elif args.config == "local-pv":
        cluster = local_pv_cluster(args.nodes)
    else:
        cluster = synthetic_cluster(args.nodes)
    if args.config == "affinity":
        apps = [AppResource("bench", affinity_apps(args.pods))]
    elif args.config == "gpu":
        apps = [AppResource("bench", gpu_apps(args.pods))]
    elif args.config == "local-pv":
        apps = [AppResource("bench", local_pv_apps(args.pods))]
    elif args.config == "bigu":
        rt = bigu_apps(args.pods)
        # per-template replica rounding changes the real pod count: keep the
        # reported label honest (the driver parses the metric line)
        args.pods = sum(w.replicas for w in rt.deployments)
        apps = [AppResource("bench", rt)]
    elif args.config != "forced":
        apps = [AppResource("bench", synthetic_apps(args.pods))]

    from opensim_tpu.utils.trace import PREP_STATS

    cold_s = None
    if args.warmup:
        _stage("warmup")
        t0 = time.time()
        simulate(cluster, apps, node_pad=128)
        cold_s = round(time.time() - t0, 3)

    _stage("measure")
    PREP_STATS.reset()
    # --trace: span-trace the measured run (the explicit flag wins over
    # OPENSIM_TRACE=0); the root span brackets exactly the timed region, so
    # the exported trace's total time matches the reported wall time
    from opensim_tpu.obs import trace as tracing

    tr = tracing.start_trace("bench", force=True) if args.trace else None
    t0 = time.time()
    with tracing.trace_scope(tr):
        result = simulate(cluster, apps, node_pad=128, explain=args.explain)
    dt = time.time() - t0
    if tr is not None:
        tr.finish()
    prep_last = PREP_STATS.snapshot()["last"]  # the measured run's prepare

    scheduled = sum(len(ns.pods) for ns in result.node_status)
    target_s = 10.0
    record = {
        "metric": f"{_fmt(args.pods)}-pod/{_fmt(args.nodes)}-node "
        + {
            "affinity": "affinity-heavy ", "bigu": "1000-template ",
            "forced": "forced-replay ", "gpu": "all-GPU-share ",
            "local-pv": "all-local-PV ",
        }.get(args.config, "")
        + "capacity plan wall-clock",
        "value": round(dt, 3),
        "unit": "s",
        "vs_baseline": round(target_s / dt, 2) if dt > 0 else 0.0,
        "scheduled": scheduled,
        "unscheduled": len(result.unscheduled_pods),
        "pods_per_sec": round((scheduled + len(result.unscheduled_pods)) / dt, 1),
    }
    if cold_s is not None:
        record["cold_s"] = cold_s  # includes first-compile (cached across runs)
    if prep_last is not None:
        # host-side expand+encode seconds of the measured run (the cold full
        # prepare; --config steady reports the warm/cached trajectory)
        record["host_prep_s"] = round(prep_last[1], 3)
    if result.engine is not None:
        # engine attribution (VERDICT r4 #3): which engine produced this
        # number, and why the faster ones (if any) were skipped
        record["engine"] = result.engine.name
        if result.engine.skipped:
            record["engine_skipped"] = result.engine.skipped
        # C++ engine path attribution (ISSUE 4): incremental vs generic —
        # a cache disengage must be visible in the record, never inferred
        if result.engine.native_path is not None:
            record["native_path"] = result.engine.native_path
            record["native_steps"] = result.engine.native_steps
        # decision audit (--explain): per-filter reject totals + pods by
        # primary unschedulable reason, straight off the EngineDecision
        if args.explain and result.engine.filter_rejects is not None:
            record["filter_rejects"] = result.engine.filter_rejects
            reason_hist = {}
            for e in result.engine.explanations or []:
                if e.status != "scheduled":
                    from opensim_tpu.engine.reasons import primary_code

                    code = primary_code(e.reasons)
                    key = code.name.lower() if code is not None else e.status
                    reason_hist[key] = reason_hist.get(key, 0) + 1
            record["unschedulable_reasons"] = reason_hist
    if args.config in ("gpu", "local-pv"):
        # ISSUE 19 in-row gates: the measured (incremental) placements must
        # be bit-identical to the generic C++ path AND the XLA scan, and the
        # incremental envelope must actually have engaged — a row that went
        # generic measures the wrong thing even when it is fast enough
        _stage("verify")
        gates = _verify_envelope(cluster, apps)
        record["native_engaged"] = int(
            result.engine is not None
            and result.engine.native_path == "incremental"
            and gates.pop("verify_native_path") == "incremental"
            and bool(gates.pop("verify_classes"))
        )
        record.update(gates)
    if os.environ.get("OPENSIM_NATIVE_PROFILE"):
        # per-stage engine timings as structured data (still ONE JSON line);
        # populated by the C++ engine when profiling is enabled
        from opensim_tpu.engine import nativepath as _np_path

        prof = _np_path.last_profile()
        if prof is not None:
            record["native_profile"] = prof
    serial, cxx = _serial_floors(
        args.config, scheduled + len(result.unscheduled_pods), args.nodes
    )
    if serial and serial.get("schedule_s") and dt > 0:
        record["vs_serial"] = round(serial["schedule_s"] / dt, 1)
        record["serial_schedule_s"] = serial["schedule_s"]
    if cxx and cxx.get("schedule_s") and dt > 0:
        # the headline honest ratio: vectorized wall-clock vs the measured
        # compiled-serial (Go-cost stand-in) schedule time
        record["vs_serial_cxx"] = round(cxx["schedule_s"] / dt, 1)
        record["cxx_serial_schedule_s"] = cxx["schedule_s"]
    if tr is not None:
        tracing.write_chrome(tr, args.trace)
        # the measured wall time and the trace's root span, side by side —
        # the two must agree (acceptance: within 10%)
        record["trace_file"] = args.trace
        record["trace_span_s"] = round(tr.root.duration_s, 3)
    if BACKEND_NOTE:
        record["backend"] = BACKEND_NOTE
    print(json.dumps(record))
    return 0


def _guarded_main() -> int:
    """Top-level failure contract: one JSON line on stdout, nonzero exit.
    argparse's own exits (usage errors print to stderr) are translated into
    the same one-line shape so the driver never sees an empty stdout."""
    try:
        return main()
    except SystemExit as e:
        if e.code in (0, None):
            return 0
        print(json.dumps({"error": f"exited with status {e.code}", "stage": _STAGE[0]}))
        return e.code if isinstance(e.code, int) else 1
    except KeyboardInterrupt:
        print(json.dumps({"error": "interrupted", "stage": _STAGE[0]}))
        return 130
    except BaseException as e:
        print(json.dumps({"error": f"{type(e).__name__}: {e}", "stage": _STAGE[0]}))
        return 1


if __name__ == "__main__":
    sys.exit(_guarded_main())
