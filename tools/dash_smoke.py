#!/usr/bin/env python
"""Fleet-observability smoke gate (``make dash-smoke``, part of
``make verify``).

The ISSUE 20 acceptance run, end to end over a real 2-worker fleet:

1. start the stub apiserver, boot ``simon server --workers 2`` against it
   with a fast time-series cadence (``OPENSIM_TS_INTERVAL_S=0.2``), and
   feed watch events so publications carry stamped event ids;
2. drive a closed-loop load burst, then assert the ring answered:
   ``GET /api/debug/timeseries`` non-empty, family + range filters
   honored, ``GET /api/fleet/slo`` shape-conformant with burn rates for
   every default objective and window;
3. ``simon dash``: rendering one fetched payload twice is byte-stable
   (the contract behind ``--once --json``), and the CLI subprocess
   prints valid JSON and exits 0;
4. the aggregated admin ``/metrics`` has zero duplicate series, one
   header per family, and the per-worker ``{worker="i"}`` breakdowns
   riding next to the summed families;
5. cross-process stitching: a request traced on a worker carries the
   owner's publication span + event ids, and ``/api/debug/requests/<id>``
   grafts the ``fleet.publication`` subtree under the worker's own
   admission/engine spans;
6. reboot the fleet with ``OPENSIM_TRACE=0``: no traces are recorded and
   the sustained QPS keeps a generous floor of the traced run's — the
   dormant observability path must cost nothing measurable.

Exit 0 on success; 1 with a one-line reason per failed check.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> int:
    print(f"dash-smoke: FAIL: {msg}")
    return 1


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http_json(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _http_text(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def _log_tail(path: str, n: int = 3000) -> str:
    try:
        with open(path) as f:
            return f.read()[-n:]
    except OSError:
        return "<no log>"


def _wait(pred, timeout: float, msg: str) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError(f"timed out waiting for {msg}")


def _spawn(argv, env, logfile):
    return subprocess.Popen(
        argv, stdout=open(logfile, "w"), stderr=subprocess.STDOUT,
        env=env, cwd=REPO, text=True,
    )


def _pod(name, rv):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default",
                     "resourceVersion": str(rv)},
        "spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "50m"}}}
        ]},
        "status": {"phase": "Pending"},
    }


def _boot_fleet(stub_kc, tmp, tag, extra_env):
    port = _free_port()
    env = dict(
        os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
        OPENSIM_FLEET_PUBLISH_MS="50",
        OPENSIM_TS_INTERVAL_S="0.2",
        OPENSIM_TS_WINDOWS="6", OPENSIM_TS_WINDOW_SAMPLES="32",
        **extra_env,
    )
    logfile = os.path.join(tmp, f"owner-{tag}.log")
    proc = _spawn(
        [sys.executable, "-m", "opensim_tpu", "server",
         "--kubeconfig", stub_kc, "--watch", "on",
         "--port", str(port), "--workers", "2", "--backend", "cpu"],
        env, logfile,
    )

    def up():
        if proc.poll() is not None:
            raise RuntimeError(f"fleet[{tag}] died at boot: {_log_tail(logfile)}")
        try:
            body = _http_json(f"http://127.0.0.1:{port + 1}/healthz", timeout=2.0)
            if body.get("workers", 0) < 2:
                return False
            _http_text(f"http://127.0.0.1:{port}/healthz", timeout=2.0)
            return True
        except OSError:
            return False

    _wait(up, timeout=120.0, msg=f"fleet[{tag}] up")
    return proc, port, logfile


def _shutdown(proc):
    if proc is not None and proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def _check_timeseries(admin: str):
    doc = _http_json(f"{admin}/api/debug/timeseries?range=5m")
    samples = doc.get("samples") or []
    if len(samples) < 2:
        return f"ring has {len(samples)} samples after the burst (want >= 2)"
    stats = doc.get("stats") or {}
    if stats.get("window_capacity") != 6:
        return f"ring stats missing/wrong capacity: {stats}"
    fam = _http_json(
        f"{admin}/api/debug/timeseries?family=simon_request_seconds&range=5m"
    )
    for _ts, series in fam.get("samples") or []:
        for key in series:
            name = key.split("{", 1)[0]
            if not name.startswith("simon_request_seconds"):
                return f"family filter leaked series {key!r}"
    try:
        _http_json(f"{admin}/api/debug/timeseries?range=bogus")
        return "a garbage ?range= was accepted (want HTTP 400)"
    except urllib.error.HTTPError as e:
        if e.code != 400:
            return f"garbage ?range= returned HTTP {e.code} (want 400)"
    return None


def _check_slo(admin: str):
    doc = _http_json(f"{admin}/api/fleet/slo")
    names = {row.get("name") for row in doc.get("objectives") or []}
    if names != {"availability", "latency_p99", "freshness"}:
        return f"SLO objectives {sorted(names)} != default trio"
    for row in doc["objectives"]:
        windows = row.get("windows") or {}
        if set(windows) != {"5m", "1h"}:
            return f"SLO windows {sorted(windows)} != default 5m/1h"
        for label, win in windows.items():
            if not isinstance(win.get("burn_rate"), (int, float)):
                return f"{row['name']}/{label} has no numeric burn_rate: {win}"
            if "no_data" not in win and win.get("samples", 99) < 2:
                return f"{row['name']}/{label} underpopulated without no_data"
    return None


def _check_dash(admin: str):
    from opensim_tpu.cli.dash import dash_rows, fetch_dash

    payload = fetch_dash(admin, range_spec="5m", timeout_s=5.0)
    if "timeseries" not in payload or "slo" not in payload:
        return f"dash payload incomplete: {sorted(payload)}"
    a = json.dumps(dash_rows(payload), sort_keys=True)
    b = json.dumps(dash_rows(json.loads(json.dumps(payload))), sort_keys=True)
    if a != b:
        return "dash rows are not byte-stable for one payload"
    rows = dash_rows(payload)
    if rows.get("samples", 0) < 2 or "qps" not in rows:
        return f"dash rows missing traffic section: {sorted(rows)}"
    cli = subprocess.run(
        [sys.executable, "-m", "opensim_tpu", "dash", "--url", admin,
         "--once", "--json"],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"), cwd=REPO,
    )
    if cli.returncode != 0:
        return f"simon dash --once --json exited {cli.returncode}: {cli.stderr[-500:]}"
    try:
        cli_rows = json.loads(cli.stdout)
    except ValueError:
        return f"simon dash --once --json printed non-JSON: {cli.stdout[:200]!r}"
    if "ring" not in cli_rows:
        return f"simon dash JSON missing ring stats: {sorted(cli_rows)}"
    return None


def _check_aggregated_metrics(admin: str):
    text = _http_text(f"{admin}/metrics")
    seen, helped, typed = set(), set(), set()
    worker_labeled = summed = False
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            if name in helped:
                return f"duplicate HELP header for {name}"
            helped.add(name)
            continue
        if line.startswith("# TYPE "):
            name = line.split()[2]
            if name in typed:
                return f"duplicate TYPE header for {name}"
            typed.add(name)
            continue
        key = line.rsplit(" ", 1)[0]
        if key in seen:
            return f"duplicate series at the aggregated endpoint: {key!r}"
        seen.add(key)
        if key.startswith("simon_request_seconds_count"):
            if 'worker="' in key:
                worker_labeled = True
            else:
                summed = True
    if not (worker_labeled and summed):
        return (
            "aggregated endpoint missing "
            + ("worker-labeled " if not worker_labeled else "summed ")
            + "request series"
        )
    for needle in ("simon_ts_samples_total", "simon_slo_burn_rate",
                   "simon_fleet_freshness_seconds"):
        if needle not in text:
            return f"{needle} missing from the aggregated endpoint"
    return None


def _check_stitched_trace(url: str, stub):
    from opensim_tpu.models import fixtures as fx

    payload = json.dumps(
        {"deployments": [fx.make_fake_deployment("stitch", 3, "500m", "1Gi").raw]}
    ).encode()
    deadline = time.monotonic() + 60.0
    last = "no attempt completed"
    attempt = 0
    while time.monotonic() < deadline:
        attempt += 1
        # fresh watch events, so the next publication carries stamped ids
        stub.upsert("/api/v1/pods", _pod(f"stitch-{attempt}", rv=5000 + attempt))
        time.sleep(0.3)
        rid = f"stitch-{attempt:04d}"
        req = urllib.request.Request(
            f"{url}/api/deploy-apps", data=payload, method="POST",
            headers={"X-Simon-Request-Id": rid},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                if resp.status != 200:
                    last = f"deploy returned HTTP {resp.status}"
                    continue
                resp.read()
        except OSError as e:
            last = f"deploy failed: {e}"
            continue
        # SO_REUSEPORT: the debug read must land on the SAME worker that
        # served the request — retry new connections until it does
        tree = None
        for _ in range(24):
            try:
                tree = _http_json(f"{url}/api/debug/requests/{rid}", timeout=5.0)
                break
            except urllib.error.HTTPError as e:
                if e.code != 404:
                    return f"debug endpoint returned HTTP {e.code}"
                time.sleep(0.05)
            except OSError as e:
                last = f"debug read failed: {e}"
                time.sleep(0.05)
        if tree is None:
            last = "could not reach the serving worker's flight recorder"
            continue
        attrs = (tree.get("spans") or {}).get("attrs") or {}
        fleet = tree.get("fleet") or {}
        child_names = {
            c.get("name") for c in (tree.get("spans") or {}).get("children") or []
        }
        if not {"schedule", "decode"} & child_names:
            return f"worker trace has no engine spans: {sorted(child_names)}"
        if "serving_generation" not in attrs:
            last = "request trace not stamped with serving_generation"
            continue
        if fleet.get("name") != "fleet.publication" or not fleet.get("span"):
            last = f"no fleet.publication graft on the trace: {sorted(fleet)}"
            continue
        if attrs.get("fleet_publication") != fleet["span"]:
            last = "trace and graft disagree on the publication span"
            continue
        carried = [e.get("event_id") for e in fleet.get("events") or []]
        if not carried:
            last = "publication carried no event ids (timing); retrying"
            continue
        stamped = set(str(attrs.get("fleet_events") or "").split(",")) - {""}
        if stamped != set(carried):
            return (
                f"owner event ids {carried} != worker trace stamp "
                f"{sorted(stamped)}"
            )
        print(
            f"dash-smoke: stitched trace OK (gen {attrs['serving_generation']}, "
            f"{len(carried)} carried event id(s), publication span {fleet['span']})"
        )
        return None
    return f"stitched trace never materialized: {last}"


def main() -> int:  # noqa: C901 - one linear scenario, early-exit checks
    import tempfile

    from opensim_tpu.server.loadgen import _seed_stub, run_loadgen

    tmp = tempfile.mkdtemp(prefix="dash-smoke-")
    stub = _seed_stub(n_nodes=8, n_pods=16)
    kc = stub.kubeconfig(tmp)
    owner = None
    try:
        owner, port, _logfile = _boot_fleet(kc, tmp, "traced", {})
        url = f"http://127.0.0.1:{port}"
        admin = f"http://127.0.0.1:{port + 1}"

        report = run_loadgen(
            url, mode="closed", concurrency=4, duration_s=3.0,
            warmup_requests=2, metrics_url=admin,
        )
        if report.get("errors", 1) != 0:
            return fail(f"traced burst saw errors: {report}")
        qps_traced = report.get("qps", 0.0)
        print(f"dash-smoke: traced burst {qps_traced} qps")

        # the sampler needs a couple of ticks spanning the burst
        def sampled():
            try:
                doc = _http_json(f"{admin}/api/debug/timeseries?range=5m")
                return len(doc.get("samples") or []) >= 2
            except OSError:
                return False

        _wait(sampled, timeout=20.0, msg="ring samples after the burst")

        for check, label in (
            (_check_timeseries, "timeseries"),
            (_check_slo, "slo"),
            (_check_dash, "dash"),
            (_check_aggregated_metrics, "aggregated metrics"),
        ):
            err = check(admin)
            if err:
                return fail(f"[{label}] {err}")
            print(f"dash-smoke: {label} OK")

        err = _check_stitched_trace(url, stub)
        if err:
            return fail(f"[stitching] {err}")

        _shutdown(owner)

        # dormant mode: OPENSIM_TRACE=0 must record nothing and keep QPS
        owner, port, _logfile = _boot_fleet(
            kc, tmp, "untraced", {"OPENSIM_TRACE": "0"}
        )
        url = f"http://127.0.0.1:{port}"
        admin = f"http://127.0.0.1:{port + 1}"
        report = run_loadgen(
            url, mode="closed", concurrency=4, duration_s=3.0,
            warmup_requests=2, metrics_url=admin,
        )
        if report.get("errors", 1) != 0:
            return fail(f"untraced burst saw errors: {report}")
        qps_off = report.get("qps", 0.0)
        print(f"dash-smoke: untraced burst {qps_off} qps")
        recorded = _http_json(f"{url}/api/debug/requests").get("requests")
        if recorded:
            return fail(
                f"OPENSIM_TRACE=0 still recorded {len(recorded)} trace(s)"
            )
        # generous floor: the dormant path must not collapse throughput
        # (tight ratios flake in CI; a real regression is far below 0.5x)
        if qps_off < 0.5 * qps_traced:
            return fail(
                f"untraced qps {qps_off} < 0.5x traced {qps_traced} — "
                "the dormant tracing path is not free"
            )
        print("dash-smoke: PASS")
        return 0
    finally:
        _shutdown(owner)
        stub.stop()


if __name__ == "__main__":
    sys.exit(main())
