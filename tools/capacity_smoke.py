#!/usr/bin/env python
"""Capacity-observatory smoke gate (``make capacity-smoke``, part of
``make verify``) — the ISSUE 9 acceptance, end to end in one process:

1. start the canned stub apiserver and a watch-mode REST server against it
   (live twin + capacity engine attached);
2. pull ``GET /api/cluster/report`` once — this bootstraps the warm base
   prep (the ONLY full prepare the observatory is allowed) and probes
   headroom through it;
3. drive an event storm (pod binds, deletes, a node add) through the watch
   stream and assert the utilization/pressure gauges move, the twin
   generation advances, and the watch-apply histogram fills — with the
   full-prepare count still at its post-bootstrap value (capacity refresh
   is O(changes), never a rescan);
4. re-probe headroom through the warm twin base and prove it bit-consistent
   with a fresh cold ``simulate``-backed probe of the same cluster;
5. sanity-check ``/metrics`` exposition (no duplicate series, per-node
   series capped at OPENSIM_CAPACITY_TOPK) and the timeline export.

Exit 0 on success; 1 with a one-line reason per failed check.
"""

import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("OPENSIM_CAPACITY_TOPK", "3")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> int:
    print(f"capacity-smoke: FAIL: {msg}")
    return 1


def _pod(name, node="", cpu="500m", mem="1Gi", phase="Running"):
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": mem}}}
            ]
        },
        "status": {"phase": phase},
    }
    if node:
        d["spec"]["nodeName"] = node
    return d


def _wait(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def _metric_value(text: str, needle: str):
    for line in text.splitlines():
        if line.startswith(needle):
            return float(line.rsplit(None, 1)[1])
    return None


def main() -> int:
    from http.server import ThreadingHTTPServer

    from opensim_tpu.models import fixtures as fx
    from opensim_tpu.obs import capacity as capacity_mod
    from opensim_tpu.server import rest
    from opensim_tpu.server.stubapi import StubApiServer
    from opensim_tpu.server.watch import RestWatchSource, WatchSupervisor
    from opensim_tpu.utils.trace import PREP_STATS

    n_nodes = 6
    stub = StubApiServer(bookmark_interval_s=0.1).start()
    stub.seed(
        "/api/v1/nodes",
        [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(n_nodes)],
    )
    stub.seed("/api/v1/pods", [_pod("seed-0", node="n0"), _pod("seed-1", node="n1")])
    for p in (
        "/apis/apps/v1/daemonsets", "/apis/policy/v1/poddisruptionbudgets",
        "/api/v1/services", "/apis/storage.k8s.io/v1/storageclasses",
        "/api/v1/persistentvolumeclaims", "/api/v1/configmaps",
    ):
        stub.seed(p, [])
    tmp = tempfile.mkdtemp(prefix="capacity-smoke-")
    kc = stub.kubeconfig(tmp)

    policy = {"stale_s": 5.0, "resync_s": 0.0, "reconnects": 3, "backoff_s": 0.02}
    sup = WatchSupervisor(RestWatchSource(kc, read_timeout_s=5.0), policy=policy)
    server = rest.SimonServer(kubeconfig=kc, watch=sup)
    sup.prep_cache = server.prep_cache
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), rest.make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    def get(path):
        with urllib.request.urlopen(f"{base}{path}", timeout=60) as resp:
            raw = resp.read().decode()
        return json.loads(raw) if path.startswith("/api") else raw

    try:
        if not sup.start(wait_s=15.0):
            return fail("twin did not sync against the stub apiserver")

        # --- bootstrap: the first report builds the warm base + probes ----
        report0 = get("/api/cluster/report")
        if report0["capacity"]["nodes"] != n_nodes:
            return fail(f"report nodes {report0['capacity']['nodes']} != {n_nodes}")
        if not report0["capacity"]["headroom"]:
            return fail("bootstrap report carries no headroom probes")
        metrics0 = get("/metrics")
        util0 = _metric_value(metrics0, 'simon_cluster_utilization_ratio{resource="cpu"}')
        bound0 = _metric_value(metrics0, "simon_cluster_pods_bound")
        if util0 is None or bound0 != 2:
            return fail(f"bootstrap gauges wrong (util={util0}, bound={bound0})")
        full_after_bootstrap = PREP_STATS.counts.get("full", 0)
        gen0 = sup.twin.generation

        # --- event storm ---------------------------------------------------
        # two delta-expressible waves (pod adds/deletes ride twin_pod_delta,
        # the node add rides extend_with_nodes; a MIXED batch is the one
        # shape that legitimately drops the warm lineage, so the storm
        # flushes between waves exactly like the supervisor's tick would)
        for i in range(12):
            stub.upsert("/api/v1/pods", _pod(f"storm-{i}", node=f"n{i % n_nodes}", cpu="1"))
        stub.delete("/api/v1/pods", "seed-0")
        stub.upsert("/api/v1/pods", _pod("pending-0", cpu="250m"))
        if not _wait(lambda: sup.twin.generation >= gen0 + 14):
            return fail("pod storm never fully reached the twin")
        sup.flush_pending()
        gen1 = sup.twin.generation
        stub.upsert("/api/v1/nodes", fx.make_fake_node(f"n{n_nodes}", "8", "16Gi").raw)
        if not _wait(lambda: sup.twin.generation >= gen1 + 1):
            return fail("node ADDED never reached the twin")
        sup.flush_pending()

        metrics1 = get("/metrics")
        util1 = _metric_value(metrics1, 'simon_cluster_utilization_ratio{resource="cpu"}')
        bound1 = _metric_value(metrics1, "simon_cluster_pods_bound")
        pending1 = _metric_value(metrics1, "simon_cluster_pods_pending")
        gen_gauge = _metric_value(metrics1, "simon_twin_generation")
        applies = _metric_value(metrics1, "simon_watch_apply_seconds_count")
        if bound1 != 13:  # 2 seed - 1 deleted + 12 storm
            return fail(f"pods_bound gauge did not track the storm (got {bound1})")
        if pending1 != 1:
            return fail(f"pending gauge did not track the unbound pod (got {pending1})")
        if util1 is None or util1 <= util0:
            return fail(f"cpu utilization ratio did not rise ({util0} -> {util1})")
        if gen_gauge != sup.twin.generation:
            return fail(f"simon_twin_generation {gen_gauge} != twin {sup.twin.generation}")
        if not applies or applies < 15:
            return fail(f"simon_watch_apply_seconds saw only {applies} events")
        if PREP_STATS.counts.get("full", 0) != full_after_bootstrap:
            return fail(
                "the event storm paid a full O(cluster) prepare "
                f"({PREP_STATS.counts.get('full', 0)} != {full_after_bootstrap})"
            )

        # --- headroom: warm twin probe == fresh cold probe -----------------
        report1 = get("/api/cluster/report")
        warm = report1["capacity"]["headroom"]
        if PREP_STATS.counts.get("full", 0) != full_after_bootstrap:
            return fail("the post-storm report paid a full O(cluster) prepare")
        # the cold verification probe below legitimately pays its own
        # prepare — the serving-path accounting window is already closed
        cluster = sup.twin.materialize()
        for profile in capacity_mod.headroom_profiles():
            cold = capacity_mod.headroom_probe(cluster, profile)
            if warm.get(profile.name) != cold:
                return fail(
                    f"headroom[{profile.name}] warm={warm.get(profile.name)} "
                    f"!= fresh simulate probe {cold}"
                )

        # --- exposition sanity + cardinality cap ---------------------------
        sample_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})?\s\S+$")
        seen = set()
        for line in metrics1.splitlines():
            if not line or line.startswith("#"):
                continue
            if not sample_re.match(line):
                return fail(f"/metrics line fails the exposition grammar: {line!r}")
            key = line.rsplit(None, 1)[0]
            if key in seen:
                return fail(f"duplicate series in /metrics: {key!r}")
            seen.add(key)
        node_series = [
            k for k in seen if k.startswith("simon_cluster_node_utilization{")
        ]
        cap = int(os.environ["OPENSIM_CAPACITY_TOPK"]) * len(capacity_mod.RESOURCES)
        if len(node_series) != cap:
            return fail(
                f"per-node series cap broken: {len(node_series)} series "
                f"(expected {cap} for topk={os.environ['OPENSIM_CAPACITY_TOPK']})"
            )

        # --- timeline export ----------------------------------------------
        tl = get("/api/debug/capacity")
        if not tl["samples"]:
            return fail("timeline export is empty")
        if tl["samples"][-1]["generation"] != sup.twin.generation:
            return fail("timeline newest sample is not the current generation")

        print(
            "capacity-smoke: ok — storm of "
            f"{int(applies)} events tracked at O(changes) "
            f"(full prepares stayed at {full_after_bootstrap}), cpu utilization "
            f"{util0:.3f} -> {util1:.3f}, headroom {warm} bit-consistent with "
            f"fresh probes, {len(node_series)} capped node series, "
            f"{len(tl['samples'])} timeline sample(s)"
        )
        return 0
    finally:
        sup.stop()
        httpd.shutdown()
        stub.stop()


if __name__ == "__main__":
    sys.exit(main())
