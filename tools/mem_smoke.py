#!/usr/bin/env python
"""Memory-observatory gate (ISSUE 12, `make mem-smoke`).

Drives a request storm against an in-process server plus a twin-delta
churn against its warm base entry, then asserts the contracts the memory
surface ships under (docs/observability.md "Memory & profiles"):

1. the gauges MOVE: prep-cache bytes/entries climb from the storm, RSS is
   nonzero, ring occupancy reflects the recorded traces;
2. the totals RECONCILE: `simon mem`'s prep-cache total equals the sum of
   per-entry unique-bytes attributions exactly, and stays within 1% of an
   independent distinct-leaf walk (the ISSUE 12 acceptance criterion);
3. the scrape stays CONFORMANT: every simon_mem_*/simon_compile_*/
   simon_phase_profile_* family renders # HELP/# TYPE once, with zero
   duplicate series;
4. the delta lineage is visible: a twin pod churn produces an entry with
   lineage_depth > 0 and a nonzero drop density.

Run directly (used by `make verify`); exits nonzero with a reason on any
violation.
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def fail(msg: str) -> "NoReturn":  # noqa: F821
    print(f"mem-smoke: FAIL: {msg}")
    raise SystemExit(1)


def main() -> int:
    from opensim_tpu.engine import prepcache
    from opensim_tpu.models import ResourceTypes, fixtures as fx
    from opensim_tpu.obs.footprint import prepcache_footprint
    from opensim_tpu.server import rest

    # -- a cluster with bound pods so the base prep has a real stream ------
    rt = ResourceTypes()
    for i in range(12):
        rt.nodes.append(fx.make_fake_node(f"n{i:02d}", "32", "128Gi"))
    for i in range(40):
        rt.pods.append(
            fx.make_fake_pod(f"bound-{i:03d}", "500m", "1Gi",
                             fx.with_node_name(f"n{i % 12:02d}"))
        )
    server = rest.SimonServer(base_cluster=rt)

    empty = prepcache_footprint(server.prep_cache)
    if empty["total_bytes"] != 0 or empty["entries"]:
        fail("prep cache not empty before the storm")

    # -- storm: distinct deploy payloads populate base + derived entries ---
    for k in range(4):
        payload = {
            "deployments": [
                fx.make_fake_deployment(f"storm-{k}", 3 + k, "250m", "512Mi").raw
            ]
        }
        code, _body = server.deploy_apps(payload)
        if code != 200:
            fail(f"deploy {k} returned {code}")

    mem = server.memory.debug_payload()
    cache = mem["prepcache"]
    if not cache["entries"]:
        fail("storm produced no cache entries")
    if cache["total_bytes"] <= 0:
        fail("prep-cache bytes did not move under the storm")
    if mem["process"]["rss_bytes"] <= 0:
        fail("process RSS reads zero")
    rings = mem["rings"]
    if rings["flight_recorder"]["entries"] < 4:
        fail(f"flight recorder did not record the storm: {rings}")

    # -- reconciliation: totals == Σ per-entry unique bytes (±1% vs an
    #    independent distinct-leaf walk) ------------------------------------
    total = cache["total_bytes"]
    entry_sum = sum(e["unique_bytes"] for e in cache["entries"])
    if total != entry_sum:
        fail(f"total_bytes {total} != Σ unique_bytes {entry_sum}")
    seen, independent = set(), 0
    from opensim_tpu.obs.footprint import entry_host_leaves

    for entry in server.prep_cache.entries_snapshot():
        with entry.lock:
            for _name, arr in entry_host_leaves(entry):
                if id(arr) not in seen:
                    seen.add(id(arr))
                    independent += int(arr.nbytes)
    if abs(independent - total) > 0.01 * max(1, independent):
        fail(f"independent walk {independent} vs reported total {total} off by >1%")
    dtype_sum = sum(cache["dtypes"].values())
    if abs(dtype_sum - total) > 0.01 * max(1, total):
        fail(f"dtype breakdown {dtype_sum} does not reconcile with total {total}")

    # -- twin-delta lineage: churn the base entry, depth + drop density ----
    base_key = [
        e["key"] for e in cache["entries"] if e["key"].endswith("|base")
    ]
    if not base_key:
        fail("no base entry in the cache after the storm")
    base = server.prep_cache.get(base_key[0])
    added = [fx.make_fake_pod("twin-new-0", "250m", "512Mi")]
    removed = {("default", "bound-000"), ("default", "bound-001")}
    with base.lock:
        base.restore()
        derived = prepcache.twin_pod_delta(base, base.key + "|churn", added, removed)
    if derived is None:
        fail("twin_pod_delta declined a small churn")
    server.prep_cache.put(derived.key, derived)
    churn = prepcache_footprint(server.prep_cache)
    churn_entry = next(e for e in churn["entries"] if e["key"].endswith("|churn"))
    if churn_entry["lineage_depth"] < 1:
        fail(f"churn entry lineage_depth {churn_entry['lineage_depth']} < 1")
    if churn_entry["drop_density"] <= 0:
        fail("churn entry drop density is zero despite deletions")
    if churn["total_bytes"] != sum(e["unique_bytes"] for e in churn["entries"]):
        fail("reconciliation broke after the twin delta")

    # -- exposition conformance over the whole scrape ----------------------
    text = rest.METRICS.render(
        prep_cache=server.prep_cache, admission=server.admission,
        capacity=server.capacity, memory=server.memory,
    )
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s(-?[0-9.eE+-]+|NaN|[+-]?Inf)$"
    )
    helped, typed, series = set(), set(), set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            if name in helped:
                fail(f"duplicate HELP for {name}")
            helped.add(name)
            continue
        if line.startswith("# TYPE "):
            name = line.split()[2]
            if name in typed:
                fail(f"duplicate TYPE for {name}")
            typed.add(name)
            continue
        m = sample_re.match(line)
        if not m:
            fail(f"non-conformant sample line: {line!r}")
        key = (m.group(1), m.group(2) or "")
        if key in series:
            fail(f"duplicate series: {line!r}")
        series.add(key)
    for family in (
        "simon_mem_rss_bytes", "simon_mem_prepcache_bytes",
        "simon_mem_prepcache_entries", "simon_mem_arena_bytes",
        "simon_mem_ring_entries", "simon_mem_ring_capacity",
        "simon_backend_compile_total", "simon_phase_profile_calls_total",
    ):
        if family not in helped:
            fail(f"{family} missing from the scrape")
    reported = int(
        next(l for l in text.splitlines()
             if l.startswith("simon_mem_prepcache_bytes ")).split()[-1]
    )
    if reported != churn["total_bytes"]:
        fail(
            f"scrape gauge {reported} disagrees with the debug payload "
            f"{churn['total_bytes']}"
        )

    server.close()
    print(
        "mem-smoke: OK — "
        f"{len(churn['entries'])} entries, {churn['total_bytes']} arena bytes "
        f"({churn['shared_bytes']} shared), totals reconcile, "
        f"{len(series)} series conformant"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
