#!/usr/bin/env python
"""HA control-plane smoke gate (``make ha-smoke``, part of ``make verify``).

The ISSUE 18 acceptance run, end to end over real subprocesses:

1. start the stub apiserver seeded with a small live cluster;
2. boot an HA owner fleet (``OPENSIM_HA=1``, ``--workers 2 --journal``):
   fenced lease + journal + shared-memory twin publication;
3. boot a hot standby (``simon server --standby``) and wait until its
   journal tail reaches parity with the owner;
4. record placement probes, then drive the public port with the
   closed-loop load generator and **SIGKILL the owner mid-run**;
5. the standby must take the lease, adopt the surviving workers and
   republish — while the loadgen sees ZERO errors (the SO_REUSEPORT
   workers keep answering from their last attached generation
   throughout the failover window);
6. assert the post-takeover placements are bit-identical to the
   pre-kill probes, ``simon_fleet_takeovers_total{reason="expired"}``
   is exactly 1, and — after everything is torn down — no orphaned
   ``simon-fleet-*`` segment is left in ``/dev/shm`` (the resource
   tracker outlives even a SIGKILLed owner).

The assertion-grade versions of these gates (fingerprint vs a fresh
relist, zero relists, adoption identity) live in ``tests/test_ha.py``;
this gate is the fast always-on end-to-end check with load applied.

Exit 0 on success; 1 with a one-line reason per failed check.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> int:
    print(f"ha-smoke: FAIL: {msg}")
    return 1


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http_json(url: str, timeout: float = 3.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _http_text(url: str, timeout: float = 3.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def _log_tail(path: str, n: int = 3000) -> str:
    try:
        with open(path) as f:
            return f.read()[-n:]
    except OSError:
        return "<no log>"


def _wait(pred, timeout: float, msg: str) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError(f"timed out waiting for {msg}")


def _metric_value(text: str, needle: str):
    for line in text.splitlines():
        if line.startswith(needle):
            return float(line.rsplit(" ", 1)[-1])
    return None


def _spawn(argv, env, logfile):
    # stdout goes to a FILE, never a pipe: the fleet workers inherit the
    # fd and outlive the owner on takeover, so a pipe would never EOF
    return subprocess.Popen(
        argv, stdout=open(logfile, "w"), stderr=subprocess.STDOUT,
        env=env, cwd=REPO, text=True,
    )


def main() -> int:  # noqa: C901 - one linear scenario, early-exit checks
    import tempfile

    from opensim_tpu.server.loadgen import (
        _canon_response,
        _payload,
        _post_deploy,
        _seed_stub,
        run_loadgen,
    )

    tmp = tempfile.mkdtemp(prefix="ha-smoke-")
    shm_before = set(glob.glob("/dev/shm/simon-fleet-*"))
    stub = _seed_stub(n_nodes=8, n_pods=16)
    kc = stub.kubeconfig(tmp)
    jd = os.path.join(tmp, "journal")
    port = _free_port()
    url = f"http://127.0.0.1:{port}"
    owner_admin = f"http://127.0.0.1:{port + 1}"
    sb_admin = f"http://127.0.0.1:{port + 16}"
    env = dict(
        os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
        OPENSIM_HA="1", OPENSIM_HA_LEASE_S="2",
        OPENSIM_HA_TAIL_POLL_MS="25", OPENSIM_FLEET_PUBLISH_MS="50",
        OPENSIM_JOURNAL_FSYNC="always", OPENSIM_JOURNAL_CHECKPOINT_EVERY="64",
    )
    owner_log = os.path.join(tmp, "owner.log")
    sb_log = os.path.join(tmp, "standby.log")
    owner = standby = None
    adopted_pids: set = set()
    try:
        owner = _spawn(
            [sys.executable, "-m", "opensim_tpu", "server",
             "--kubeconfig", kc, "--watch", "on", "--journal", jd,
             "--port", str(port), "--workers", "2", "--backend", "cpu"],
            env, owner_log,
        )

        def owner_up():
            if owner.poll() is not None:
                raise RuntimeError(
                    f"owner died at boot: {_log_tail(owner_log)}"
                )
            try:
                body = _http_json(f"{owner_admin}/healthz", timeout=2.0)
                if body.get("workers", 0) < 2 or body.get("generation", -1) < 0:
                    return False
                # every worker is alive AND the shared public port answers
                _http_text(f"{url}/healthz", timeout=2.0)
                return True
            except OSError:
                return False

        _wait(owner_up, timeout=120.0, msg="HA owner fleet up")
        status = _http_json(f"{owner_admin}/api/fleet/status")
        if status.get("role") != "owner" or status.get("epoch") != 1:
            return fail(f"owner booted in unexpected state: {status}")
        worker_pids = {w["pid"] for w in status["workers"] if w["alive"]}

        standby = _spawn(
            [sys.executable, "-m", "opensim_tpu", "server", "--standby",
             "--kubeconfig", kc, "--watch", "auto", "--journal", jd,
             "--port", str(port), "--workers", "2", "--backend", "cpu"],
            env, sb_log,
        )

        def standby_at_parity():
            if standby.poll() is not None:
                raise RuntimeError(
                    f"standby died at boot: {_log_tail(sb_log)}"
                )
            try:
                body = _http_json(f"{sb_admin}/api/fleet/status", timeout=2.0)
                return body.get("role") == "standby" and body.get("at_parity")
            except OSError:
                return False

        _wait(standby_at_parity, timeout=60.0, msg="standby tail parity")
        print("ha-smoke: owner + standby up, standby at tail parity")

        # placement identity probes, recorded BEFORE any failover
        probes = [
            _canon_response(
                _post_deploy(url, _payload(777, i, 3, "500m", "1Gi"))
            )
            for i in range(4)
        ]

        # closed-loop load through the failover window
        report_box: dict = {}

        def drive():
            try:
                report_box["report"] = run_loadgen(
                    url, mode="closed", concurrency=8, duration_s=12.0,
                    warmup_requests=2, metrics_url=sb_admin,
                )
            except Exception as e:  # surfaced as a gate failure below
                report_box["error"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=drive, daemon=True)
        t.start()
        time.sleep(3.0)
        owner.kill()  # SIGKILL: no flush, no lease release, no goodbye
        owner.wait(timeout=10)
        print("ha-smoke: owner SIGKILLed mid-run")

        def promoted():
            if standby.poll() is not None:
                raise RuntimeError(
                    f"standby died during takeover: {_log_tail(sb_log)}"
                )
            try:
                body = _http_json(f"{sb_admin}/api/fleet/status", timeout=2.0)
                return body.get("role") == "owner"
            except OSError:
                return False

        _wait(promoted, timeout=60.0, msg="standby takeover")
        status = _http_json(f"{sb_admin}/api/fleet/status")
        if status.get("epoch") != 2:
            return fail(f"takeover epoch != 2: {status.get('epoch')}")
        adopted_pids = {w["pid"] for w in status["workers"] if w.get("adopted")}
        if adopted_pids != worker_pids:
            return fail(
                f"takeover respawned workers: adopted {sorted(adopted_pids)} "
                f"!= original {sorted(worker_pids)}"
            )
        print(f"ha-smoke: standby took over at epoch 2, "
              f"adopted workers {sorted(adopted_pids)}")

        t.join(timeout=120.0)
        if t.is_alive():
            return fail("loadgen never finished")
        if "error" in report_box:
            return fail(f"loadgen crashed: {report_box['error']}")
        report = report_box["report"]
        print(f"ha-smoke: loadgen through the kill: "
              f"qps={report['qps']} ok={report['ok']} "
              f"shed={report['shed']} errors={report['errors']}")
        if report["errors"] != 0:
            return fail(
                f"loadgen saw {report['errors']} errors across the failover"
            )
        if report["ok"] <= 0:
            return fail("loadgen completed zero requests")

        # bit-identical placements: the same payloads against the new
        # owner's fleet must place exactly as before the kill
        for i, want in enumerate(probes):
            got = _canon_response(
                _post_deploy(url, _payload(777, i, 3, "500m", "1Gi"))
            )
            if got != want:
                return fail(
                    f"placement diverged after takeover (probe {i}): "
                    f"{got} != {want}"
                )

        metrics = _http_text(f"{sb_admin}/metrics")
        takeovers = _metric_value(
            metrics, 'simon_fleet_takeovers_total{reason="expired"}'
        )
        if takeovers != 1.0:
            return fail(
                f'simon_fleet_takeovers_total{{reason="expired"}} == '
                f"{takeovers}, want 1"
            )
    except (RuntimeError, TimeoutError, OSError) as e:
        if owner is not None:
            print(f"ha-smoke: owner log tail:\n{_log_tail(owner_log)}")
        if standby is not None:
            print(f"ha-smoke: standby log tail:\n{_log_tail(sb_log)}")
        return fail(str(e))
    finally:
        # the standby-turned-owner owns the adopted workers: SIGTERM it
        # first so it reaps them, then sweep whatever is left
        if standby is not None and standby.poll() is None:
            standby.send_signal(signal.SIGTERM)
            try:
                standby.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
        for p in (owner, standby):
            if p is not None and p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        for pid in adopted_pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass
        stub.stop()

    # /dev/shm hygiene: the SIGKILLed owner's segments must be reaped by
    # its surviving resource tracker, the new owner's by its own shutdown
    deadline = time.monotonic() + 15.0
    leftovers = set(glob.glob("/dev/shm/simon-fleet-*")) - shm_before
    while leftovers and time.monotonic() < deadline:
        time.sleep(0.5)
        leftovers = set(glob.glob("/dev/shm/simon-fleet-*")) - shm_before
    if leftovers:
        return fail(f"orphaned /dev/shm segments: {sorted(leftovers)}")

    print("ha-smoke: OK (zero-error failover, bit-identical placements, "
          "one takeover, clean /dev/shm)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
