#!/usr/bin/env python
"""Decision-audit smoke gate (``make explain-smoke``, part of ``make verify``).

Generates a throwaway simon config (6 nodes; one schedulable and one
infeasible workload), then drives the REAL ``simon explain`` CLI against it
on both CPU engines and asserts the ISSUE 7 acceptance bar end to end:

1. ``simon explain`` renders a kube-style ``0/N nodes are available: …``
   breakdown for the unschedulable workload;
2. the per-filter rejection counts (and the whole explanation set) are
   identical between the C++ generic engine and the XLA scan;
3. the deep single-pod audit resolves a workload-name query, and its
   per-plugin score breakdown sums to the reported total on the winner;
4. the aggregate per-filter reject totals agree between engines.

Exit 0 on success; 1 with a one-line reason per failed check.
"""

import contextlib
import io
import json
import os
import re
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> int:
    print(f"explain-smoke: FAIL: {msg}")
    return 1


NODE_TMPL = """apiVersion: v1
kind: Node
metadata:
  name: {name}
  labels:
    kubernetes.io/hostname: {name}
    topology.kubernetes.io/zone: {zone}
status:
  allocatable:
    cpu: "4"
    memory: 8Gi
    pods: "110"
  capacity:
    cpu: "4"
    memory: 8Gi
    pods: "110"
"""

DEPLOY_TMPL = """apiVersion: apps/v1
kind: Deployment
metadata:
  name: {name}
spec:
  replicas: {replicas}
  selector:
    matchLabels:
      app: {name}
  template:
    metadata:
      labels:
        app: {name}
    spec:
      containers:
      - name: c
        resources:
          requests:
            cpu: {cpu}
            memory: {memory}
"""

CONFIG_TMPL = """apiVersion: simon/v1alpha1
kind: Config
metadata:
  name: explain-smoke
spec:
  cluster:
    customConfig: cluster
  appList:
  - name: smoke
    path: apps
"""


def write_config(root: str) -> str:
    nodes_dir = os.path.join(root, "cluster", "nodes")
    apps_dir = os.path.join(root, "apps")
    os.makedirs(nodes_dir)
    os.makedirs(apps_dir)
    for i in range(6):
        with open(os.path.join(nodes_dir, f"n{i:02d}.yaml"), "w") as f:
            f.write(NODE_TMPL.format(name=f"n{i:02d}", zone=f"z{i % 2}"))
    with open(os.path.join(apps_dir, "web.yaml"), "w") as f:
        f.write(DEPLOY_TMPL.format(name="web", replicas=4, cpu="500m", memory="1Gi"))
    with open(os.path.join(apps_dir, "nofit.yaml"), "w") as f:
        f.write(DEPLOY_TMPL.format(name="nofit", replicas=2, cpu="64", memory="1Gi"))
    cfg = os.path.join(root, "config.yaml")
    with open(cfg, "w") as f:
        f.write(CONFIG_TMPL)
    return cfg


_BACKEND_ENV = ("OPENSIM_NATIVE", "OPENSIM_DISABLE_NATIVE", "OPENSIM_DISABLE_FASTPATH")


def run_cli(argv) -> str:
    """One in-process ``simon`` invocation with captured stdout; backend
    env selections are reset afterwards so runs stay independent."""
    from opensim_tpu.cli.main import main

    saved = {k: os.environ.get(k) for k in _BACKEND_ENV}
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            rc = main(argv)
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
    if rc != 0:
        raise RuntimeError(f"simon {' '.join(argv)} exited {rc}:\n{buf.getvalue()}")
    return buf.getvalue()


def canon(obj):
    """Strip expansion-time uid suffixes from pod names so runs compare."""
    s = json.dumps(obj, sort_keys=True)
    return json.loads(re.sub(r"-[0-9a-f]{10}", "", s))


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="explain-smoke-") as root:
        cfg = write_config(root)

        # 1+2: summary audit, both engines, must agree byte-for-byte
        out_native = json.loads(run_cli(["explain", "-f", cfg, "--json", "--backend", "native"]))
        out_xla = json.loads(run_cli(["explain", "-f", cfg, "--json", "--backend", "xla"]))
        if not out_native["unschedulable"]:
            return fail("the infeasible workload was reported schedulable")
        msg = out_native["unschedulable"][0]["message"]
        if not re.match(r"^0/6 nodes are available: .*Insufficient cpu", msg):
            return fail(f"not a kube-style breakdown: {msg!r}")
        if canon(out_native["unschedulable"]) != canon(out_xla["unschedulable"]):
            return fail(
                "engines disagree on the unschedulable explanations:\n"
                f"  native: {canon(out_native['unschedulable'])}\n"
                f"  xla:    {canon(out_xla['unschedulable'])}"
            )
        if out_native["filter_rejects"] != out_xla["filter_rejects"]:
            return fail(
                f"filter-reject totals differ: {out_native['filter_rejects']} "
                f"vs {out_xla['filter_rejects']}"
            )
        if out_native["filter_rejects"].get("fit", 0) < 1:
            return fail(f"no fit rejects recorded: {out_native['filter_rejects']}")

        # 3: deep audit of one scheduled pod by workload name
        deep = json.loads(run_cli(["explain", "-f", cfg, "--json", "--backend", "native", "web"]))
        if deep["status"] != "scheduled" or not deep.get("scores"):
            return fail(f"deep audit lacks a score breakdown: {deep}")
        if abs(sum(deep["scores"].values()) - deep["score"]) > 0.01:
            return fail(
                f"score parts {deep['scores']} do not sum to total {deep['score']}"
            )
        # and of the unschedulable workload
        deep_bad = json.loads(
            run_cli(["explain", "-f", cfg, "--json", "--backend", "xla", "nofit"])
        )
        if deep_bad["status"] != "unschedulable" or not deep_bad.get("reasons"):
            return fail(f"deep audit of the infeasible pod is wrong: {deep_bad}")

    print(
        "explain-smoke: ok — kube-style breakdowns engine-identical "
        f"({msg!r}), rejects {out_native['filter_rejects']}, deep audit "
        f"scored {deep['node']} at {deep['score']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
