#!/bin/sh
# Round-4 TPU tunnel watcher: probe every 5 minutes; on success write a
# sentinel so the build loop knows silicon is reachable (VERDICT r3 #1).
OUT=/tmp/opensim-tpu-watch
rm -f "$OUT.up"
while true; do
  if timeout 90 python -c "
import jax, numpy as np
d = jax.devices()
assert d and d[0].platform == 'tpu', d
x = np.asarray(jax.numpy.ones((8, 8)) * 2)
assert float(x.sum()) == 128.0
print('TPU OK:', d)
" >"$OUT.last" 2>&1; then
    date > "$OUT.up"
    cat "$OUT.last" >> "$OUT.up"
    exit 0
  fi
  date >> "$OUT.log"
  sleep 300
done
