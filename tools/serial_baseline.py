#!/usr/bin/env python
"""Measured serial baseline: a faithful object-at-a-time re-implementation
of the reference's scheduling loop, timed on the BASELINE.md configs.

The Go reference has no published numbers and no Go toolchain exists in
this environment, so BENCH.md carries a modeled Go cost bracket
(tools/go_baseline_proxy.py). This tool adds a MEASURED floor: the exact
serial pipeline the reference runs —

    for each pod:                      # simulator.go:309-348
        filter all nodes               # generic_scheduler.go:131-180
        score the feasible set         # framework.RunScorePlugins
        bind the best                  # lowest index on ties (see below)

— implemented object-at-a-time over Pod/Node objects with kube's own
incremental NodeInfo/PreFilter design (scheduler framework types.go
NodeInfo; interpodaffinity/filtering.go PreFilter maps), never touching
the tensor encodings or JAX. Semantics match the independent kube oracle
(tests/test_k8s_oracle.py) and the engines: the default plugin set with
registry.go:119-132 weights plus Simon/Open-Local/Open-Gpu-Share, the
Reserve-updated gpu-count allocatable, and the deterministic lowest-index
tie-break (the engines' documented divergence from reservoir sampling).

Honesty note, stated plainly: this floor is measured in *Python*, which is
slower than the reference's Go per operation — so the speedups computed
against it OVERSTATE nothing: the vectorized engines' advantage vs real Go
is smaller than vs this floor by roughly the Go-vs-Python constant, which
the modeled brackets in BENCH.md estimate. Conversely kube's 16-goroutine
parallelism is absent here, as it is in the serial loop timed above.

Usage:
  python tools/serial_baseline.py --config all            # the 5 configs
  python tools/serial_baseline.py --config plan           # 50k/5k headline
  python tools/serial_baseline.py --config synthetic --pods 1000 --nodes 100

Each run prints one JSON line per config and (with --out, default
BASELINE_MEASURED.json) merges results into a file bench.py reads to
report `vs_serial`.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from opensim_tpu.models import selectors  # noqa: E402
from opensim_tpu.models.objects import Node, Pod  # noqa: E402
from opensim_tpu.models.quantity import parse_quantity  # noqa: E402

HOSTNAME = "kubernetes.io/hostname"
GPU_MEM = "alibabacloud.com/gpu-mem"
GPU_COUNT = "alibabacloud.com/gpu-count"
NONZERO_CPU = 0.1
NONZERO_MEM = 200.0 * 1024 * 1024

W_BALANCED = 1.0
W_LEAST = 1.0
W_NODE_AFFINITY = 1.0
W_TAINT = 1.0
W_INTERPOD = 1.0
W_SPREAD = 2.0
W_SHARE = 2.0  # Simon (1) + Open-Gpu-Share (1): same formula and norm
W_LOCAL = 1.0
W_AVOID = 10000.0


def _sel_key(sel) -> str:
    return json.dumps(sel, sort_keys=True) if sel is not None else "null"


def _term_sig(term: dict, owner_ns: str):
    ns = tuple(sorted(term.get("namespaces") or [owner_ns]))
    return (ns, _sel_key(term.get("labelSelector")), term.get("topologyKey", ""))


def _sig_matches(sig, pod: Pod) -> bool:
    ns, sel_key, _key = sig
    if pod.metadata.namespace not in ns:
        return False
    sel = json.loads(sel_key)
    if sel is None:
        return False
    return selectors.match_label_selector(sel, pod.metadata.labels)


def _terms(pod: Pod, kind: str, mode: str):
    aff = (pod.spec.affinity or {}).get(kind) or {}
    return aff.get(f"{mode}DuringSchedulingIgnoredDuringExecution") or []


def _pod_gpu(pod: Pod):
    return pod.gpu_mem_request(), (
        pod.gpu_count_request() if pod.gpu_mem_request() > 0 else 0
    )


def _pod_local(pod: Pod):
    lvm, devs = 0.0, []
    for v in pod.local_volumes():
        kind = str(v.get("kind", ""))
        try:
            size = float(parse_quantity(v.get("size", 0)))
        except ValueError:
            continue
        if kind == "LVM":
            lvm += size
        elif kind in ("SSD", "HDD"):
            devs.append((size, kind))
    return lvm, devs


class CarrierCounts:
    """Per-(term signature) domain tallies contributed by BOUND pods that
    CARRY the term — kube's topologyToMatchedExistingAntiAffinityTerms and
    the symmetric preferred/hard-affinity weight maps (scoring.go
    processExistingPod), memoized by signature so one workload's identical
    pods share an entry."""

    def __init__(self):
        self.entries = {}  # sig -> {val: weight}

    def add(self, sig, node_val, w: float):
        if node_val is None:
            return
        m = self.entries.get(sig)
        if m is None:
            m = self.entries[sig] = {}
        m[node_val] = m.get(node_val, 0.0) + w

    def matching(self, pod: Pod):
        """[(topology key, {val: weight})] for sigs whose term matches."""
        out = []
        for sig, m in self.entries.items():
            if m and _sig_matches(sig, pod):
                out.append((sig[2], m))
        return out


class MatchCounts:
    """Per-(term-set signature) counts of bound pods MATCHING the terms,
    per topology value — kube's PreFilter count maps
    (interpodaffinity/filtering.go:113-127 podsMatchingAllTerms;
    podtopologyspread calPreFilterState). Registered lazily on first
    sight (one backfill scan over bound pods), then maintained
    incrementally at every bind."""

    def __init__(self, scheduler: "SerialScheduler"):
        self.sched = scheduler
        self.entries = {}  # sigset -> {"maps": [dict], "total": float}

    def get(self, terms, owner_ns):
        sigset = tuple(_term_sig(t, owner_ns) for t in terms)
        e = self.entries.get(sigset)
        if e is None:
            maps = [{} for _ in sigset]
            total = 0.0
            for q, ni in self.sched.bound:
                if all(_sig_matches(s, q) for s in sigset):
                    for s, m in zip(sigset, maps):
                        val = ni.labels.get(s[2])
                        if val is not None:
                            m[val] = m.get(val, 0.0) + 1.0
                            total += 1.0
            e = self.entries[sigset] = {"maps": maps, "total": total}
        return e

    def on_bind(self, pod: Pod, ni: "NodeInfo"):
        for sigset, e in self.entries.items():
            if all(_sig_matches(s, pod) for s in sigset):
                for s, m in zip(sigset, e["maps"]):
                    val = ni.labels.get(s[2])
                    if val is not None:
                        m[val] = m.get(val, 0.0) + 1.0
                        e["total"] += 1.0


class NodeInfo:
    """Cached per-node aggregates — framework.NodeInfo (types.go): the
    serial loop's answer to not rescanning every bound pod per decision."""

    __slots__ = (
        "node", "idx", "name", "labels", "alloc", "taints", "unschedulable",
        "used", "nz_cpu", "nz_mem", "ports", "n_pods", "gpu_free", "has_dev",
        "vgs", "devs", "avoid", "prefer_taints",
    )

    def __init__(self, node: Node, idx: int):
        self.node = node
        self.idx = idx
        self.name = node.metadata.name
        self.labels = node.metadata.labels
        self.alloc = dict(node.allocatable)
        self.taints = node.taints
        self.unschedulable = node.unschedulable
        self.used = {}
        self.nz_cpu = 0.0
        self.nz_mem = 0.0
        self.ports = []  # ContainerPort of bound pods
        self.n_pods = 0
        total = node.allocatable.get(GPU_MEM, 0.0)
        cnt = int(node.allocatable.get(GPU_COUNT, 0))
        self.gpu_free = [total / cnt] * cnt if cnt > 0 and total > 0 else []
        self.has_dev = bool(self.gpu_free)
        self.vgs, self.devs = [], []
        raw = node.metadata.annotations.get("simon/node-local-storage")
        if raw:
            try:
                data = json.loads(raw)
            except ValueError:
                data = {}
            for vg in data.get("vgs") or []:
                cap = float(parse_quantity(vg.get("capacity", 0)))
                self.vgs.append([cap, cap])  # [free, cap]
            for d in data.get("devices") or []:
                cap = float(parse_quantity(d.get("capacity", 0)))
                media = "SSD" if str(d.get("mediaType", "")).lower() == "ssd" else "HDD"
                self.devs.append([cap, media, cap])  # [free, media, cap]
        self.avoid = set()
        anno = node.metadata.annotations.get(
            "scheduler.alpha.kubernetes.io/preferAvoidPods"
        )
        if anno:
            try:
                entries = json.loads(anno).get("preferAvoidPods") or []
            except (ValueError, AttributeError):
                entries = []
            for e in entries:
                pc = ((e.get("podSignature") or {}).get("podController") or {})
                self.avoid.add((str(pc.get("kind", "")), str(pc.get("uid", ""))))
        self.prefer_taints = any(t.effect == "PreferNoSchedule" for t in node.taints)

    def alloc_view(self) -> dict:
        """Reserve-updated allocatable (open-gpu-share.go:147-188): on
        device-bearing nodes gpu-count = count of not-fully-used devices."""
        if not self.has_dev:
            return self.alloc
        a = dict(self.alloc)
        a[GPU_COUNT] = float(sum(1 for f in self.gpu_free if f > 0))
        return a


class SerialScheduler:
    def __init__(self, nodes):
        self.nodes = [NodeInfo(n, i) for i, n in enumerate(nodes)]
        self.by_name = {ni.name: ni for ni in self.nodes}
        self.bound = []  # (pod, NodeInfo)
        self.exist_anti = CarrierCounts()
        self.sym_pref = CarrierCounts()
        self.match_counts = MatchCounts(self)
        # static topology facts
        self.key_vals = {}  # key -> set of values over all nodes
        for ni in self.nodes:
            for k, v in ni.labels.items():
                self.key_vals.setdefault(k, set()).add(v)
        self.any_prefer_taints = any(ni.prefer_taints for ni in self.nodes)
        self.any_avoid = any(ni.avoid for ni in self.nodes)
        self._eligible_cache = {}

    # -- filters -------------------------------------------------------------

    def _static_ok(self, pod: Pod, ni: NodeInfo) -> bool:
        if ni.unschedulable:
            return False
        if pod.spec.node_name and pod.spec.node_name != ni.name:
            return False
        if not selectors.pod_matches_node_selector_and_affinity(pod, ni.node):
            return False
        if ni.taints and selectors.find_untolerated_taint(
            ni.taints, pod.spec.tolerations
        ):
            return False
        return True

    def _fit_ok(self, req: dict, ni: NodeInfo) -> bool:
        alloc = ni.alloc_view()
        used = ni.used
        for k, v in req.items():
            if v > 0 and used.get(k, 0.0) + v > alloc.get(k, 0.0):
                return False
        return True

    def _ports_ok(self, mine, ni: NodeInfo) -> bool:
        for theirs in ni.ports:
            for m in mine:
                if m.protocol != theirs.protocol or m.host_port != theirs.host_port:
                    continue
                ia = "" if m.host_ip in ("", "0.0.0.0") else m.host_ip
                ib = "" if theirs.host_ip in ("", "0.0.0.0") else theirs.host_ip
                if ia == ib or ia == "" or ib == "":
                    return False
        return True

    def _gpu_ok(self, mem, cnt, ni: NodeInfo) -> bool:
        if mem <= 0:
            return True
        return cnt > 0 and sum(int(f // mem) for f in ni.gpu_free) >= cnt

    def _local_ok(self, lvm, devs, ni: NodeInfo) -> bool:
        if lvm > 0 and not any(free >= lvm for free, _cap in ni.vgs):
            return False
        taken = set()
        for media in ("SSD", "HDD"):
            for size, _m in sorted(v for v in devs if v[1] == media):
                pick, pick_cap = None, None
                for idx, (free, m, cap) in enumerate(ni.devs):
                    if idx in taken or m != media or free < size or free <= 0:
                        continue
                    if pick is None or cap < pick_cap:
                        pick, pick_cap = idx, cap
                if pick is None:
                    return False
                taken.add(pick)
        return True

    def _eligible_vals(self, pod: Pod, key: str):
        """Values of `key` over nodes passing the pod's node affinity —
        the PreFilter's eligible-domain set, cached by the pod's static
        node-affinity signature (pods of one workload share it)."""
        sig = (
            tuple(sorted(pod.spec.node_selector.items())),
            _sel_key((pod.spec.affinity or {}).get("nodeAffinity")),
            key,
        )
        vals = self._eligible_cache.get(sig)
        if vals is None:
            vals = {
                ni.labels[key]
                for ni in self.nodes
                if key in ni.labels
                and selectors.pod_matches_node_selector_and_affinity(pod, ni.node)
            }
            self._eligible_cache[sig] = vals
        return vals

    # -- one pod through the pipeline ----------------------------------------

    def schedule_one(self, pod: Pod):
        """Filter -> Score -> select (generic_scheduler.go:131-180 with
        PercentageOfNodesToScore=100). Returns the chosen NodeInfo or None."""
        ns = pod.metadata.namespace
        req = dict(pod.resource_requests())
        req["pods"] = req.get("pods", 0.0) + 1
        mine_ports = pod.host_ports()
        gpu_mem, gpu_cnt = _pod_gpu(pod)
        lvm, dev_vols = _pod_local(pod)

        # PreFilter: incoming interpod terms and spread constraints
        anti_terms = _terms(pod, "podAntiAffinity", "required")
        aff_terms = _terms(pod, "podAffinity", "required")
        anti_entries = [
            (t.get("topologyKey", ""), self.match_counts.get([t], ns))
            for t in anti_terms
        ]
        aff_entry = self.match_counts.get(aff_terms, ns) if aff_terms else None
        exist_anti_hits = self.exist_anti.matching(pod)

        hard_spread, soft_spread = [], []
        explicit = pod.spec.topology_spread_constraints
        if explicit:
            for c in explicit:
                lst = (
                    hard_spread
                    if c.get("whenUnsatisfiable", "DoNotSchedule") == "DoNotSchedule"
                    else soft_spread
                )
                lst.append(c)
        else:
            owner = self._owner_selector(pod)
            if owner is not None:
                soft_spread = [
                    {"topologyKey": HOSTNAME, "maxSkew": 3, "labelSelector": owner},
                    {"topologyKey": "topology.kubernetes.io/zone", "maxSkew": 5,
                     "labelSelector": owner},
                ]
        spread_pre = []
        for c in hard_spread:
            key = c.get("topologyKey", "")
            entry = self.match_counts.get(
                [{"labelSelector": c.get("labelSelector"), "topologyKey": key,
                  "namespaces": [ns]}], ns)
            elig = self._eligible_vals(pod, key)
            cnts = entry["maps"][0]
            min_cnt = min((cnts.get(v, 0.0) for v in elig), default=None)
            self_match = (
                1.0
                if c.get("labelSelector") is not None
                and selectors.match_label_selector(
                    c.get("labelSelector"), pod.metadata.labels)
                else 0.0
            )
            spread_pre.append((key, cnts, min_cnt, float(c.get("maxSkew", 1)),
                               self_match))

        # -- Filter over all nodes
        feasible = []
        for ni in self.nodes:
            if not self._static_ok(pod, ni):
                continue
            if not self._fit_ok(req, ni):
                continue
            if mine_ports and not self._ports_ok(mine_ports, ni):
                continue
            # spread hard (filtering.go:276)
            ok = True
            for key, cnts, min_cnt, skew, self_match in spread_pre:
                val = ni.labels.get(key)
                if val is None or min_cnt is None:
                    ok = False
                    break
                if cnts.get(val, 0.0) + self_match - min_cnt > skew:
                    ok = False
                    break
            if not ok:
                continue
            # existing pods' required anti-affinity vs this pod
            for key, m in exist_anti_hits:
                val = ni.labels.get(key)
                if val is not None and m.get(val, 0.0) > 0:
                    ok = False
                    break
            if not ok:
                continue
            # incoming required anti terms (node missing key: vacuous)
            for t, (key, entry) in zip(anti_terms, anti_entries):
                val = ni.labels.get(key)
                if val is not None and entry["maps"][0].get(val, 0.0) > 0:
                    ok = False
                    break
            if not ok:
                continue
            # incoming required affinity (satisfyPodAffinity + bootstrap)
            if aff_terms:
                labels_ok = all(
                    ni.labels.get(t.get("topologyKey", "")) is not None
                    for t in aff_terms
                )
                per_term = labels_ok and all(
                    m.get(ni.labels.get(s[2]), 0.0) > 0
                    for s, m in zip(
                        (tuple(_term_sig(t, ns) for t in aff_terms)),
                        aff_entry["maps"],
                    )
                )
                if not per_term:
                    bootstrap = (
                        labels_ok
                        and aff_entry["total"] == 0.0
                        and all(
                            selectors.affinity_term_matches_pod(t, ns, pod)
                            for t in aff_terms
                        )
                    )
                    if not bootstrap:
                        continue
            if gpu_mem > 0 and not self._gpu_ok(gpu_mem, gpu_cnt, ni):
                continue
            if (lvm > 0 or dev_vols) and not self._local_ok(lvm, dev_vols, ni):
                continue
            feasible.append(ni)

        if not feasible:
            return None

        # -- Score (per-plugin normalization over the feasible list)
        scores = [0.0] * len(feasible)
        cpu_req = req.get("cpu") or NONZERO_CPU
        mem_req = req.get("memory") or NONZERO_MEM
        for i, ni in enumerate(feasible):
            ac = ni.alloc.get("cpu", 0.0)
            am = ni.alloc.get("memory", 0.0)
            rc = ni.nz_cpu + cpu_req
            rm = ni.nz_mem + mem_req
            ls = 0.0 if (ac == 0 or rc > ac) else (ac - rc) * 100.0 / ac
            ms = 0.0 if (am == 0 or rm > am) else (am - rm) * 100.0 / am
            scores[i] += W_LEAST * (ls + ms) / 2.0
            cf = rc / ac if ac else 0.0
            mf = rm / am if am else 0.0
            bal = 0.0 if (cf >= 1 or mf >= 1) else (1.0 - abs(cf - mf)) * 100.0
            scores[i] += W_BALANCED * bal

        pna = (pod.spec.affinity or {}).get("nodeAffinity") or {}
        if pna.get("preferredDuringSchedulingIgnoredDuringExecution"):
            raw = [float(selectors.node_affinity_preferred_score(pod, ni.node))
                   for ni in feasible]
            mx = max(raw, default=0.0)
            for i, v in enumerate(raw):
                scores[i] += W_NODE_AFFINITY * (v * 100.0 / mx if mx > 0 else v)

        if self.any_prefer_taints:
            raw = [
                float(selectors.count_intolerable_prefer_no_schedule(pod, ni.node))
                if ni.prefer_taints else 0.0
                for ni in feasible
            ]
            mx = max(raw, default=0.0)
            for i, v in enumerate(raw):
                scores[i] += W_TAINT * (100.0 - v * 100.0 / mx if mx > 0 else 100.0)

        self._interpod_score(pod, ns, feasible, scores)
        self._spread_score(pod, ns, soft_spread, feasible, scores)
        self._share_score(pod, feasible, scores)
        if lvm > 0 or dev_vols:
            self._local_score(lvm, dev_vols, feasible, scores)
        if self.any_avoid:
            ctrl = None
            for ref in pod.metadata.owner_references:
                if ref.controller and ref.kind in ("ReplicaSet",
                                                   "ReplicationController"):
                    ctrl = (ref.kind, ref.uid)
                    break
            for i, ni in enumerate(feasible):
                avoided = ctrl is not None and ctrl in ni.avoid
                scores[i] += W_AVOID * (0.0 if avoided else 100.0)

        best_i = 0
        for i in range(1, len(feasible)):
            if scores[i] > scores[best_i]:
                best_i = i
        return feasible[best_i]

    def _interpod_score(self, pod, ns, feasible, scores):
        # incoming preferred terms + symmetric carried terms (scoring.go)
        parts = []
        for tw in _terms(pod, "podAffinity", "preferred"):
            t = tw.get("podAffinityTerm") or {}
            e = self.match_counts.get([t], ns)
            parts.append((float(tw.get("weight", 0)), t.get("topologyKey", ""),
                          e["maps"][0]))
        for tw in _terms(pod, "podAntiAffinity", "preferred"):
            t = tw.get("podAffinityTerm") or {}
            e = self.match_counts.get([t], ns)
            parts.append((-float(tw.get("weight", 0)), t.get("topologyKey", ""),
                          e["maps"][0]))
        sym = self.sym_pref.matching(pod)
        if not parts and not sym:
            return
        raw = []
        for ni in feasible:
            s = 0.0
            for w, key, m in parts:
                val = ni.labels.get(key)
                if val is not None:
                    s += w * m.get(val, 0.0)
            for key, m in sym:
                val = ni.labels.get(key)
                if val is not None:
                    s += m.get(val, 0.0)
            raw.append(s)
        hi = max(max(raw), 0.0)
        lo = min(min(raw), 0.0)
        rng = hi - lo
        if rng > 0:
            for i, v in enumerate(raw):
                scores[i] += W_INTERPOD * 100.0 * (v - lo) / rng

    def _spread_score(self, pod, ns, soft, feasible, scores):
        if not soft:
            return
        pre = []
        for c in soft:
            key = c.get("topologyKey", "")
            e = self.match_counts.get(
                [{"labelSelector": c.get("labelSelector"), "topologyKey": key,
                  "namespaces": [ns]}], ns)
            size = len(self.key_vals.get(key, ()))
            pre.append((key, e["maps"][0], math.log(size + 2.0),
                        float(c.get("maxSkew", 1))))
        raw, ignored = [], []
        for ni in feasible:
            s, ig = 0.0, False
            for key, cnts, w, skew in pre:
                val = ni.labels.get(key)
                if val is None:
                    ig = True
                    continue
                s += cnts.get(val, 0.0) * w + (skew - 1.0)
            raw.append(s)
            ignored.append(ig)
        scored = [v for v, ig in zip(raw, ignored) if not ig]
        mx = max(scored, default=0.0)
        mn = min(scored, default=0.0)
        for i, (v, ig) in enumerate(zip(raw, ignored)):
            if ig:
                continue
            scores[i] += W_SPREAD * (100.0 if mx <= 0 else 100.0 * (mx + mn - v) / mx)

    def _share_score(self, pod, feasible, scores):
        req = pod.resource_requests()
        raw = []
        for ni in feasible:
            if not req:
                raw.append(100.0)
                continue
            best = 0.0
            for r, alloc in ni.alloc_view().items():
                pr = req.get(r, 0.0)
                avail = alloc - pr
                share = (1.0 if pr else 0.0) if avail == 0 else pr / avail
                if share > best:
                    best = share
            raw.append(best * 100.0)
        hi, lo = max(raw), min(raw)
        rng = hi - lo
        if rng > 0:
            for i, v in enumerate(raw):
                scores[i] += W_SHARE * (v - lo) * 100.0 / rng

    def _local_score(self, lvm, devs, feasible, scores):
        raw = []
        for ni in feasible:
            parts, count = 0.0, 0
            if lvm > 0:
                cands = [v for v in ni.vgs if v[0] >= lvm]
                if cands:
                    choice = min(cands, key=lambda v: v[0])
                    parts += lvm / choice[1]
                count += 1
            for media in ("SSD", "HDD"):
                sizes = [s for s, m in devs if m == media]
                if not sizes:
                    continue
                size = max(sizes)
                fitting = [d for d in ni.devs
                           if d[1] == media and d[0] >= size and d[0] > 0]
                if fitting:
                    parts += len(sizes) * size / min(d[2] for d in fitting)
                count += len(sizes)
            raw.append(parts / count * 10.0 if count else 0.0)
        hi, lo = max(raw), min(raw)
        rng = hi - lo
        if rng > 0:
            for i, v in enumerate(raw):
                scores[i] += W_LOCAL * (v - lo) * 100.0 / rng

    @staticmethod
    def _owner_selector(pod: Pod):
        if pod.metadata.annotations.get("simon/workload-kind") and pod.metadata.labels:
            return {"matchLabels": dict(pod.metadata.labels)}
        return None

    # -- bind ----------------------------------------------------------------

    def bind(self, pod: Pod, ni: NodeInfo):
        self.bound.append((pod, ni))
        used = ni.used
        for k, v in pod.resource_requests().items():
            used[k] = used.get(k, 0.0) + v
        used["pods"] = used.get("pods", 0.0) + 1
        req = pod.resource_requests()
        ni.nz_cpu += req.get("cpu") or NONZERO_CPU
        ni.nz_mem += req.get("memory") or NONZERO_MEM
        ni.ports.extend(pod.host_ports())
        ni.n_pods += 1

        ns = pod.metadata.namespace
        for t in _terms(pod, "podAntiAffinity", "required"):
            key = t.get("topologyKey", "")
            self.exist_anti.add(_term_sig(t, ns), ni.labels.get(key), 1.0)
        for tw in _terms(pod, "podAffinity", "preferred"):
            t = tw.get("podAffinityTerm") or {}
            self.sym_pref.add(_term_sig(t, ns), ni.labels.get(t.get("topologyKey", "")),
                              float(tw.get("weight", 0)))
        for tw in _terms(pod, "podAntiAffinity", "preferred"):
            t = tw.get("podAffinityTerm") or {}
            self.sym_pref.add(_term_sig(t, ns), ni.labels.get(t.get("topologyKey", "")),
                              -float(tw.get("weight", 0)))
        for t in _terms(pod, "podAffinity", "required"):
            # HardPodAffinityWeight = 1 symmetric score contribution
            self.sym_pref.add(_term_sig(t, ns), ni.labels.get(t.get("topologyKey", "")),
                              1.0)
        self.match_counts.on_bind(pod, ni)

        mem, cnt = _pod_gpu(pod)
        if mem > 0 and cnt > 0 and ni.gpu_free:
            free = ni.gpu_free
            if cnt == 1:
                fitting = [i for i, f in enumerate(free) if f >= mem]
                if fitting:
                    tight = min(fitting, key=lambda i: (free[i], i))
                    free[tight] -= mem
            else:
                left = cnt
                for i, f in enumerate(free):
                    take = min(int(f // mem), left)
                    free[i] -= take * mem
                    left -= take
                    if left == 0:
                        break
        lvm, devs = _pod_local(pod)
        if lvm > 0:
            cands = [v for v in ni.vgs if v[0] >= lvm]
            if cands:
                min(cands, key=lambda v: v[0])[0] -= lvm
        if devs:
            taken = set()
            for media in ("SSD", "HDD"):
                for size, _m in sorted(v for v in devs if v[1] == media):
                    pick, pick_cap = None, None
                    for idx, (free, m, cap) in enumerate(ni.devs):
                        if idx in taken or m != media or free < size or free <= 0:
                            continue
                        if pick is None or cap < pick_cap:
                            pick, pick_cap = idx, cap
                    if pick is not None:
                        taken.add(pick)
                        ni.devs[pick][0] = 0.0


def run_serial(cluster, apps, progress=False):
    """Expand (reusing the package's expansion + ordering) then schedule
    the whole stream serially. Returns (n_scheduled, n_unscheduled,
    expand_s, schedule_s, chosen_names)."""
    from opensim_tpu.engine import queues
    from opensim_tpu.engine.simulator import _cluster_pods
    from opensim_tpu.models import expand
    from opensim_tpu.models.objects import LABEL_APP_NAME

    t0 = time.time()
    stream = []
    cluster_pods, _n_bare, _ds_sizes = _cluster_pods(cluster)
    for p in cluster_pods:
        stream.append((p, bool(p.spec.node_name)))
    for app in apps:
        pods = expand.generate_pods_from_resources(app.resources, cluster.nodes)
        for p in pods:
            p.metadata.labels.setdefault(LABEL_APP_NAME, app.name)
        pods = queues.toleration_sort(queues.affinity_sort(pods))
        stream.extend((p, bool(p.spec.node_name)) for p in pods)
    expand_s = time.time() - t0

    sched = SerialScheduler(cluster.nodes)
    scheduled = unscheduled = 0
    chosen = []
    t0 = time.time()
    for i, (pod, forced) in enumerate(stream):
        if progress and i and i % 5000 == 0:
            print(f"  ... {i}/{len(stream)} pods, {time.time() - t0:.1f}s",
                  file=sys.stderr)
        if forced:
            ni = sched.by_name.get(pod.spec.node_name)
            if ni is not None:
                sched.bind(pod, ni)
                scheduled += 1
                chosen.append(ni.name)
            else:
                unscheduled += 1
                chosen.append(None)
            continue
        ni = sched.schedule_one(pod)
        if ni is None:
            unscheduled += 1
            chosen.append(None)
        else:
            sched.bind(pod, ni)
            scheduled += 1
            chosen.append(ni.name)
    schedule_s = time.time() - t0
    return scheduled, unscheduled, expand_s, schedule_s, chosen


# ---------------------------------------------------------------------------
# the BASELINE.md configs
# ---------------------------------------------------------------------------

def _bench():
    import bench

    return bench


def _example(config_path: str):
    from opensim_tpu.planner.apply import Applier, Options

    a = Applier(Options(simon_config=config_path))
    return a.load_cluster(), a.load_apps()


def _runner(args):
    """--impl python → this module's run_serial; --impl c++ → the compiled
    serial engine (opensim_tpu/native/serial_engine.cc), the same pipeline
    in C++ — the measured stand-in for the Go reference's constant factor
    (placement parity asserted by tests/test_serial_baseline.py)."""
    if getattr(args, "impl", "python") == "c++":
        from opensim_tpu.native.serial import run_serial_native

        return run_serial_native, "c++-serial (same NodeInfo/PreFilter pipeline compiled -O3; see native/serial_engine.cc)"
    return run_serial, "python-serial (kube NodeInfo/PreFilter design; see module docstring)"


def run_config(name: str, args):
    from opensim_tpu.engine.simulator import AppResource

    bench = _bench()
    if name in ("example", "gpushare"):
        path = os.path.join(
            _REPO,
            "example/simon-config.yaml" if name == "example"
            else "example/simon-gpushare-config.yaml",
        )
        cluster, apps = _example(path)
        pods_n, nodes_n = None, len(cluster.nodes)
    elif name == "synthetic":
        pods_n, nodes_n = args.pods or 10000, args.nodes or 1000
        cluster = bench.synthetic_cluster(nodes_n)
        apps = [AppResource("bench", bench.synthetic_apps(pods_n))]
    elif name == "affinity":
        pods_n, nodes_n = args.pods or 5000, args.nodes or 500
        cluster = bench.synthetic_cluster(nodes_n)
        apps = [AppResource("bench", bench.affinity_apps(pods_n))]
    elif name == "plan":
        pods_n, nodes_n = args.pods or 50000, args.nodes or 5000
        cluster = bench.synthetic_cluster(nodes_n)
        apps = [AppResource("bench", bench.synthetic_apps(pods_n))]
    elif name == "defrag":
        return run_defrag(args)
    else:
        raise SystemExit(f"unknown config {name}")

    run, impl = _runner(args)
    scheduled, unscheduled, expand_s, schedule_s, _ = run(
        cluster, apps, progress=True
    )
    total = scheduled + unscheduled
    rec = {
        "config": name,
        "pods": total,
        "nodes": len(cluster.nodes),
        "expand_s": round(expand_s, 3),
        "schedule_s": round(schedule_s, 3),
        "pods_per_sec": round(total / schedule_s, 1) if schedule_s else None,
        "scheduled": scheduled,
        "unscheduled": unscheduled,
        "impl": impl,
    }
    print(json.dumps(rec))
    return rec


def run_defrag(args):
    """BASELINE config 5 floor: K drain what-ifs, each a full serial
    re-simulation with the candidate node removed (the vectorized sweep
    runs these as scenarios in one dispatch)."""
    from opensim_tpu.engine.simulator import AppResource

    bench = _bench()
    run, impl = _runner(args)
    pods_n, nodes_n = args.pods or 10000, args.nodes or 1000
    k = args.scenarios or 3
    cluster = bench.synthetic_cluster(nodes_n)
    apps = [AppResource("bench", bench.synthetic_apps(pods_n))]
    t0 = time.time()
    for c in range(k):
        import copy

        sub = copy.copy(cluster)
        sub.nodes = [n for i, n in enumerate(cluster.nodes) if i != c]
        run(sub, apps)
    dt = time.time() - t0
    rec = {
        "config": "defrag",
        "pods": pods_n,
        "nodes": nodes_n,
        "scenarios": k,
        "wall_s": round(dt, 3),
        "scenarios_per_sec": round(k / dt, 4),
        "impl": f"{impl.split(' ')[0]}, one full re-simulation per drain scenario",
    }
    print(json.dumps(rec))
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--config", default="all",
        choices=["all", "example", "gpushare", "synthetic", "affinity",
                 "defrag", "plan"],
    )
    ap.add_argument("--pods", type=int, default=None)
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--scenarios", type=int, default=None)
    ap.add_argument(
        "--impl", default="python", choices=["python", "c++"],
        help="c++ runs the compiled serial engine (the measured Go-cost "
        "stand-in); results are stored under '<config>-cxx' keys",
    )
    ap.add_argument(
        "--out", default=os.path.join(_REPO, "BASELINE_MEASURED.json"),
        help="merge results into this JSON file ('' disables)",
    )
    args = ap.parse_args()

    names = (
        ["example", "gpushare", "synthetic", "affinity", "defrag"]
        if args.config == "all" else [args.config]
    )
    suffix = "-cxx" if args.impl == "c++" else ""
    existing = {}
    if args.out:
        try:
            with open(args.out) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            pass
    results = {}
    for name in names:
        rec = run_config(name, args)
        # the canonical key updates in place when the shape matches (or no
        # canonical record exists yet); only a genuinely different shape
        # gets its own suffixed key, so canonical rows never go stale
        key = name + suffix
        canon = existing.get(key)
        if (
            isinstance(canon, dict)
            and (canon.get("pods"), canon.get("nodes")) != (rec.get("pods"), rec.get("nodes"))
        ):
            key = f"{name}-{rec.get('pods')}p-{rec.get('nodes')}n{suffix}"
        results[key] = rec

    if args.out:
        merged = {}
        try:
            with open(args.out) as f:
                merged = json.load(f)
        except (OSError, ValueError):
            pass
        merged.update(results)
        with open(args.out, "w") as f:
            json.dump(merged, f, indent=1, sort_keys=True)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
