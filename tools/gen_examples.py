#!/usr/bin/env python
"""Generate the repo's self-contained example tree under example/.

The reference ships demo inputs under its own example/ dir; this repo authors
an ORIGINAL equivalent set (different clusters, workloads, sizes and names)
covering the same feature surface: tainted control-plane nodes, a local-storage
worker (simon/node-local-storage sibling JSON), GPU-share nodes, an
anti-affinity StatefulSet that cannot fully fit, daemonsets with and without
tolerations, storage-class-driven PVC synthesis, a Helm chart, and newnode
capacity templates.  Run `python tools/gen_examples.py` from the repo root to
regenerate; the output is checked in so users (and tests) never need the
reference checkout.
"""

import json
import os
import sys

import yaml

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "example")

GiB = 1024 ** 3

CP_TAINT = {"key": "node-role.kubernetes.io/control-plane", "effect": "NoSchedule"}
CP_TOLERATION = {"key": "node-role.kubernetes.io/control-plane", "operator": "Exists", "effect": "NoSchedule"}


def write(relpath, content):
    path = os.path.join(ROOT, relpath)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        if isinstance(content, str):
            f.write(content)
        else:
            yaml.safe_dump(content, f, sort_keys=False)


def node(name, cpu, memory, labels=None, taints=None, zone=None):
    lab = {
        "kubernetes.io/arch": "amd64",
        "kubernetes.io/os": "linux",
        "kubernetes.io/hostname": name,
    }
    if zone:
        lab["topology.kubernetes.io/zone"] = zone
    lab.update(labels or {})
    alloc = {"cpu": str(cpu), "memory": memory, "pods": "110",
             "ephemeral-storage": "100Gi"}
    d = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": lab},
        "status": {
            "allocatable": dict(alloc),
            "capacity": dict(alloc),
            "conditions": [{"type": "Ready", "status": "True",
                            "reason": "KubeletReady",
                            "message": "kubelet is posting ready status"}],
        },
    }
    if taints:
        d["spec"] = {"taints": taints}
    return d


def container(name="app", image="registry.example.com/app:1.0", cpu="100m", memory="128Mi",
              gpu_mem=None, ports=None):
    c = {"name": name, "image": image,
         "resources": {"requests": {"cpu": cpu, "memory": memory},
                       "limits": {"cpu": cpu, "memory": memory}}}
    if ports:
        c["ports"] = [{"containerPort": p, "hostPort": p} for p in ports]
    return c


def workload(kind, name, namespace, replicas, pod_labels, containers, *,
             tolerations=None, affinity=None, node_selector=None,
             volume_claims=None, spread=None, api="apps/v1"):
    tmpl = {"metadata": {"labels": dict(pod_labels)},
            "spec": {"containers": containers}}
    if tolerations:
        tmpl["spec"]["tolerations"] = tolerations
    if affinity:
        tmpl["spec"]["affinity"] = affinity
    if node_selector:
        tmpl["spec"]["nodeSelector"] = node_selector
    if spread:
        tmpl["spec"]["topologySpreadConstraints"] = spread
    spec = {"selector": {"matchLabels": dict(pod_labels)}, "template": tmpl}
    if kind not in ("DaemonSet",):
        spec["replicas"] = replicas
    if kind == "StatefulSet":
        spec["serviceName"] = name
        spec["podManagementPolicy"] = "Parallel"
        if volume_claims:
            spec["volumeClaimTemplates"] = volume_claims
    if kind == "Job":
        spec = {"completions": replicas, "parallelism": replicas, "template": tmpl}
        tmpl["spec"]["restartPolicy"] = "Never"
    return {"apiVersion": api, "kind": kind,
            "metadata": {"name": name, "namespace": namespace}, "spec": spec}


def anti_affinity(label_key, label_value, namespace, topology="kubernetes.io/hostname"):
    return {"podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
        {"labelSelector": {"matchExpressions": [
            {"key": label_key, "operator": "In", "values": [label_value]}]},
         "topologyKey": topology, "namespaces": [namespace]}]}}


def vct(name, sc, size):
    return {"metadata": {"name": name},
            "spec": {"accessModes": ["ReadWriteOnce"], "storageClassName": sc,
                     "resources": {"requests": {"storage": size}}}}


# ---------------------------------------------------------------------------
# cluster/demo — 2 tainted control-plane nodes + 2 workers (one with storage)
# ---------------------------------------------------------------------------

def gen_cluster_demo():
    cp_labels = {"node-role.kubernetes.io/control-plane": ""}
    wk_labels = {"node-role.kubernetes.io/worker": ""}
    write("cluster/demo/nodes/cp-1.yaml", node("cp-1", 8, "16Gi", cp_labels, [CP_TAINT], zone="zone-a"))
    write("cluster/demo/nodes/cp-2.yaml", node("cp-2", 8, "16Gi", cp_labels, [CP_TAINT], zone="zone-b"))
    write("cluster/demo/nodes/worker-1.yaml", node("worker-1", 16, "32Gi", wk_labels, zone="zone-a"))
    write("cluster/demo/nodes/worker-2.yaml", node("worker-2", 16, "32Gi", wk_labels, zone="zone-b"))
    # open-local storage sidecar for worker-1 (simon/node-local-storage JSON)
    write("cluster/demo/nodes/worker-1.json", json.dumps({
        "vgs": [
            {"name": "pool-a", "capacity": str(200 * GiB), "requested": "0"},
            {"name": "pool-b", "capacity": str(100 * GiB), "requested": "0"},
        ],
        "devices": [
            {"name": "/dev/sdb", "device": "/dev/sdb", "capacity": str(128 * GiB),
             "mediaType": "ssd", "isAllocated": "false"},
            {"name": "/dev/sdc", "device": "/dev/sdc", "capacity": str(256 * GiB),
             "mediaType": "hdd", "isAllocated": "false"},
            {"name": "/dev/sdd", "device": "/dev/sdd", "capacity": str(256 * GiB),
             "mediaType": "hdd", "isAllocated": "false"},
        ],
    }, indent=2) + "\n")

    # base cluster workloads
    write("cluster/demo/deploy-cluster-dns.yaml", workload(
        "Deployment", "cluster-dns", "kube-system", 2, {"k8s-app": "cluster-dns"},
        [container("dns", "registry.example.com/dns:1.9", "250m", "128Mi")]))
    write("cluster/demo/ds-node-agent.yaml", workload(
        "DaemonSet", "node-agent", "kube-system", 0, {"k8s-app": "node-agent"},
        [container("agent", "registry.example.com/agent:0.4", "100m", "64Mi")],
        tolerations=[{"operator": "Exists"}]))
    write("cluster/demo/ds-ingress.yaml", workload(
        "DaemonSet", "ingress-edge", "kube-system", 0, {"k8s-app": "ingress-edge"},
        [container("envoy", "registry.example.com/edge:2.1", "200m", "256Mi")],
        node_selector={"node-role.kubernetes.io/worker": ""}))
    for sc, prov in [("open-local-lvm", "local.csi.aliyun.com"),
                     ("open-local-device-ssd", "local.csi.aliyun.com"),
                     ("open-local-device-hdd", "local.csi.aliyun.com")]:
        write(f"cluster/demo/sc-{sc.replace('open-local-', '')}.yaml", {
            "apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
            "metadata": {"name": sc}, "provisioner": prov,
            "volumeBindingMode": "WaitForFirstConsumer",
        })


# ---------------------------------------------------------------------------
# cluster/gpushare — two 4-GPU nodes
# ---------------------------------------------------------------------------

def gen_cluster_gpushare():
    for i in (1, 2):
        n = node(f"gpu-a-{i}", 48, "192000Mi",
                 {"alibabacloud.com/gpu-card-model": "A10",
                  "node-role.kubernetes.io/worker": ""})
        for sec in ("allocatable", "capacity"):
            n["status"][sec]["alibabacloud.com/gpu-count"] = "4"
            n["status"][sec]["alibabacloud.com/gpu-mem"] = "61440Mi"  # 4 x 15360Mi
        write(f"cluster/gpushare/nodes/gpu-a-{i}.yaml", n)


# ---------------------------------------------------------------------------
# applications
# ---------------------------------------------------------------------------

def gen_app_simple():
    ns = "demo-app"
    write("application/simple/deploy-web.yaml", workload(
        "Deployment", "web", ns, 3, {"app": "web"},
        [container("web", "registry.example.com/web:3.2", "500m", "512Mi")]))
    write("application/simple/rs-cache.yaml", workload(
        "ReplicaSet", "cache", ns, 2, {"app": "cache"},
        [container("cache", "registry.example.com/cache:7", "250m", "1Gi")]))
    write("application/simple/job-migrate.yaml", workload(
        "Job", "schema-migrate", ns, 2, {"app": "schema-migrate"},
        [container("migrate", "registry.example.com/migrate:1.0", "200m", "256Mi")],
        api="batch/v1"))
    write("application/simple/pod-probe.yaml", {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "probe", "namespace": ns, "labels": {"app": "probe"}},
        "spec": {"containers": [container("probe", "registry.example.com/probe:0.1", "50m", "64Mi")]}})
    write("application/simple/ds-exporter.yaml", workload(
        "DaemonSet", "metrics-exporter", ns, 0, {"app": "metrics-exporter"},
        [container("exporter", "registry.example.com/exporter:1.5", "100m", "96Mi")]))
    # 6 replicas, hostname anti-affinity, tolerates the CP taint: exactly one
    # replica lands per node (4 nodes) and 2 stay unschedulable.
    write("application/simple/sts-kv.yaml", workload(
        "StatefulSet", "kv-store", ns, 6, {"app": "kv-store"},
        [container("kv", "registry.example.com/kv:5.4", "500m", "1Gi")],
        tolerations=[CP_TOLERATION],
        affinity=anti_affinity("app", "kv-store", ns)))


def gen_app_local():
    # only worker-1 has VGs/devices; the hdd claim needs an exclusive device,
    # so replicas beyond the device count stay pending.
    write("application/local/sts-db.yaml", workload(
        "StatefulSet", "db", "data", 4, {"app": "db"},
        [container("db", "registry.example.com/db:14", "1", "2Gi")],
        volume_claims=[
            vct("wal", "open-local-lvm", "20Gi"),
            vct("data", "open-local-lvm", "50Gi"),
            vct("cold", "open-local-device-hdd", "150Gi"),
        ]))


def gen_app_gpushare():
    ns = "ml"

    def gpu_pod(name, mem, count, cpu="4", memory="8192Mi"):
        return {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         "annotations": {"alibabacloud.com/gpu-mem": mem,
                                         "alibabacloud.com/gpu-count": str(count)}},
            "spec": {"containers": [container("cuda", "registry.example.com/cuda:12", cpu, memory)]}}

    write("application/gpushare/pod-infer-small.yaml", gpu_pod("infer-small", "4096Mi", 1))
    write("application/gpushare/pod-infer-full.yaml", gpu_pod("infer-full", "15360Mi", 1))
    write("application/gpushare/pod-train.yaml", gpu_pod("train-dual", "12288Mi", 2, cpu="8", memory="32768Mi"))
    rs = workload("ReplicaSet", "serving", ns, 4, {"app": "serving"},
                  [container("srv", "registry.example.com/serving:2", "2", "4096Mi")])
    rs["spec"]["template"]["metadata"]["annotations"] = {
        "alibabacloud.com/gpu-mem": "2048Mi", "alibabacloud.com/gpu-count": "1"}
    write("application/gpushare/rs-serving.yaml", rs)


def gen_app_scale():
    ns = "load"
    write("application/scale/deploy-api.yaml", workload(
        "Deployment", "api", ns, 40, {"app": "api"},
        [container("api", "registry.example.com/api:9", "250m", "512Mi")]))
    write("application/scale/deploy-frontend.yaml", workload(
        "Deployment", "frontend", ns, 60, {"app": "frontend"},
        [container("fe", "registry.example.com/fe:9", "100m", "256Mi")]))
    write("application/scale/sts-queue.yaml", workload(
        "StatefulSet", "queue", ns, 30, {"app": "queue"},
        [container("mq", "registry.example.com/mq:3", "200m", "512Mi")]))
    write("application/scale/rs-worker.yaml", workload(
        "ReplicaSet", "worker", ns, 20, {"app": "worker"},
        [container("wk", "registry.example.com/worker:9", "150m", "256Mi")]))
    write("application/scale/job-batch.yaml", workload(
        "Job", "batch", ns, 10, {"app": "batch"},
        [container("batch", "registry.example.com/batch:9", "500m", "1Gi")],
        api="batch/v1"))


def gen_app_mixed():
    """Kernel-stress app: node affinity, zone spread, pod affinity, host ports."""
    ns = "mixed"
    write("application/mixed/deploy-zonal.yaml", workload(
        "Deployment", "zonal", ns, 4, {"app": "zonal"},
        [container("z", "registry.example.com/zonal:1", "200m", "256Mi")],
        spread=[{"maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
                 "whenUnsatisfiable": "DoNotSchedule",
                 "labelSelector": {"matchLabels": {"app": "zonal"}}}]))
    write("application/mixed/deploy-pinned.yaml", workload(
        "Deployment", "pinned", ns, 2, {"app": "pinned"},
        [container("p", "registry.example.com/pinned:1", "100m", "128Mi")],
        affinity={"nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": {
            "nodeSelectorTerms": [{"matchExpressions": [
                {"key": "node-role.kubernetes.io/worker", "operator": "Exists"}]}]}}}))
    write("application/mixed/deploy-sidecar.yaml", workload(
        "Deployment", "sidecar", ns, 2, {"app": "sidecar"},
        [container("s", "registry.example.com/sidecar:1", "100m", "128Mi")],
        affinity={"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
            {"labelSelector": {"matchLabels": {"app": "pinned"}},
             "topologyKey": "kubernetes.io/hostname", "namespaces": [ns]}]}}))
    write("application/mixed/sts-gateway.yaml", workload(
        "StatefulSet", "gateway", ns, 2, {"app": "gateway"},
        [container("gw", "registry.example.com/gw:1", "250m", "256Mi", ports=[30443])],
        affinity=anti_affinity("app", "gateway", ns)))
    write("application/mixed/pod-edge.yaml", {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "edge-probe", "namespace": ns, "labels": {"app": "edge-probe"}},
        "spec": {"nodeSelector": {"node-role.kubernetes.io/worker": ""},
                 "containers": [container("e", "registry.example.com/edge:1", "50m", "64Mi")]}})


# ---------------------------------------------------------------------------
# chart: obs-stack — exercises the renderer's Go-template subset
# ---------------------------------------------------------------------------

CHART_FILES = {
    "Chart.yaml": """\
apiVersion: v2
name: obs-stack
description: Observability stack demo chart (agent + server + retention jobs)
version: 0.2.0
appVersion: "1.8"
""",
    "values.yaml": """\
namespace: obs
images:
  agent: registry.example.com/obs-agent:1.8
  server: registry.example.com/obs-server:1.8
  tools: registry.example.com/obs-tools:1.8
server:
  replicas: 2
  cpu: 500m
  memory: 1Gi
agent:
  cpu: 100m
  memory: 128Mi
retention:
  enabled: true
  schedule: "0 3 * * *"
storage:
  className: open-local-lvm
  size: 30Gi
scrape:
  interval: 30s
  timeout: 10s
""",
    "templates/_helpers.tpl": """\
{{- define "obs-stack.fullname" -}}
{{ printf "%s-%s" .Release.Name .Chart.Name | trunc 63 | trimSuffix "-" }}
{{- end -}}
{{- define "obs-stack.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
{{- end }}
""",
    "templates/configmap.yaml": """\
apiVersion: v1
kind: ConfigMap
metadata:
  name: {{ .Release.Name }}-config
  namespace: {{ .Values.namespace }}
  labels:
    {{- include "obs-stack.labels" . | nindent 4 }}
data:
  chart: {{ .Chart.Name | quote }}
  fullname: {{ include "obs-stack.fullname" . | quote }}
  version: {{ .Chart.Version | quote }}
  retention: {{ .Values.retention.enabled | toString | quote }}
{{- range $k, $v := .Values.scrape }}
  scrape.{{ $k }}: {{ $v | quote }}
{{- end }}
""",
    "templates/service.yaml": """\
apiVersion: v1
kind: Service
metadata:
  name: {{ .Release.Name }}-server
  namespace: {{ .Values.namespace }}
spec:
  selector:
    app: {{ .Release.Name }}-server
  ports:
    - port: 9090
      targetPort: 9090
""",
    "templates/storage-class.yaml": """\
apiVersion: storage.k8s.io/v1
kind: StorageClass
metadata:
  name: {{ .Values.storage.className }}
provisioner: local.csi.aliyun.com
volumeBindingMode: WaitForFirstConsumer
""",
    "templates/agent-daemonset.yaml": """\
apiVersion: apps/v1
kind: DaemonSet
metadata:
  name: {{ .Release.Name }}-agent
  namespace: {{ .Values.namespace }}
spec:
  selector:
    matchLabels:
      app: {{ .Release.Name }}-agent
  template:
    metadata:
      labels:
        app: {{ .Release.Name }}-agent
    spec:
      tolerations:
        - operator: Exists
      containers:
        - name: agent
          image: {{ .Values.images.agent }}
          resources:
            requests:
              cpu: {{ .Values.agent.cpu }}
              memory: {{ .Values.agent.memory }}
""",
    "templates/server-deployment.yaml": """\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: {{ .Release.Name }}-server
  namespace: {{ .Values.namespace }}
  labels:
    {{- include "obs-stack.labels" . | nindent 4 }}
spec:
  replicas: {{ .Values.server.replicas | int }}
  selector:
    matchLabels:
      app: {{ .Release.Name }}-server
  template:
    metadata:
      labels:
        app: {{ .Release.Name }}-server
    spec:
      containers:
        - name: server
          image: {{ .Values.images.server }}
          resources:
            requests:
              cpu: {{ .Values.server.cpu }}
              memory: {{ .Values.server.memory }}
          volumeMounts:
            - name: tsdb
              mountPath: /var/lib/obs
      volumes:
        - name: tsdb
          persistentVolumeClaim:
            claimName: {{ .Release.Name }}-tsdb
""",
    "templates/pvc.yaml": """\
apiVersion: v1
kind: PersistentVolumeClaim
metadata:
  name: {{ .Release.Name }}-tsdb
  namespace: {{ .Values.namespace }}
spec:
  accessModes:
    - ReadWriteOnce
  storageClassName: {{ .Values.storage.className }}
  resources:
    requests:
      storage: {{ .Values.storage.size | default "10Gi" }}
""",
    "templates/retention-cronjob.yaml": """\
{{- if .Values.retention.enabled }}
apiVersion: batch/v1
kind: CronJob
metadata:
  name: {{ .Release.Name }}-retention
  namespace: {{ .Values.namespace }}
spec:
  schedule: {{ .Values.retention.schedule | quote }}
  jobTemplate:
    spec:
      template:
        spec:
          restartPolicy: Never
          containers:
            - name: prune
              image: {{ .Values.images.tools }}
              resources:
                requests:
                  cpu: 100m
                  memory: 128Mi
{{- end }}
""",
    "templates/init-job.yaml": """\
apiVersion: batch/v1
kind: Job
metadata:
  name: {{ .Release.Name }}-init
  namespace: {{ .Values.namespace }}
spec:
  completions: 1
  template:
    spec:
      restartPolicy: Never
      containers:
        - name: init
          image: {{ .Values.images.tools }}
          resources:
            requests:
              cpu: 100m
              memory: 128Mi
""",
    "templates/namespace.yaml": """\
apiVersion: v1
kind: Namespace
metadata:
  name: {{ .Values.namespace }}
""",
    "templates/serviceaccount.yaml": """\
apiVersion: v1
kind: ServiceAccount
metadata:
  name: {{ .Release.Name }}-agent
  namespace: {{ .Values.namespace }}
""",
}


def gen_chart():
    for rel, content in CHART_FILES.items():
        write(f"application/charts/obs-stack/{rel}", content)


# ---------------------------------------------------------------------------
# newnode templates + configs
# ---------------------------------------------------------------------------

def gen_newnode():
    write("newnode/demo/extra-worker.yaml", node(
        "extra-worker", 32, "64Gi", {"node-role.kubernetes.io/worker": ""}, zone="zone-a"))
    write("newnode/demo/extra-worker.json", json.dumps({
        "vgs": [{"name": "pool-a", "capacity": str(500 * GiB), "requested": "0"}],
        "devices": [
            {"name": "/dev/sdb", "device": "/dev/sdb", "capacity": str(256 * GiB),
             "mediaType": "hdd", "isAllocated": "false"},
        ],
    }, indent=2) + "\n")
    gpu = node("extra-gpu", 48, "192000Mi",
               {"alibabacloud.com/gpu-card-model": "A10",
                "node-role.kubernetes.io/worker": ""})
    for sec in ("allocatable", "capacity"):
        gpu["status"][sec]["alibabacloud.com/gpu-count"] = "4"
        gpu["status"][sec]["alibabacloud.com/gpu-mem"] = "61440Mi"
    write("newnode/gpushare/extra-gpu.yaml", gpu)


def gen_configs():
    write("simon-config.yaml", {
        "apiVersion": "simon/v1alpha1", "kind": "Config",
        "metadata": {"name": "simon-config"},
        "spec": {
            "cluster": {"customConfig": "cluster/demo"},
            "appList": [
                {"name": "obs", "path": "application/charts/obs-stack", "chart": True},
                {"name": "simple", "path": "application/simple"},
            ],
            "newNode": "newnode/demo",
        },
    })
    write("simon-gpushare-config.yaml", {
        "apiVersion": "simon/v1alpha1", "kind": "Config",
        "metadata": {"name": "simon-gpushare-config"},
        "spec": {
            "cluster": {"customConfig": "cluster/gpushare"},
            "appList": [{"name": "ml", "path": "application/gpushare"}],
            "newNode": "newnode/gpushare",
        },
    })
    write("simon-local-config.yaml", {
        "apiVersion": "simon/v1alpha1", "kind": "Config",
        "metadata": {"name": "simon-local-config"},
        "spec": {
            "cluster": {"customConfig": "cluster/demo"},
            "appList": [{"name": "data", "path": "application/local"}],
            "newNode": "newnode/demo",
        },
    })


def gen_campaign():
    """A demo lifecycle campaign (docs/campaigns.md): deploy a PDB-guarded
    canary, drain a worker one wave at a time, lose the spot pool at once,
    regrow from the newnode template, then ask whether the cluster could
    shrink back down safely."""
    write("campaign.yaml", {
        "apiVersion": "simon/v1alpha1", "kind": "Campaign",
        "metadata": {"name": "demo-lifecycle"},
        "spec": {
            "cluster": {"customConfig": "cluster/demo"},
            "steps": [
                {
                    "name": "canary", "type": "deploy",
                    "app": {"name": "canary"},
                    "resources": [
                        {
                            "apiVersion": "apps/v1", "kind": "Deployment",
                            "metadata": {"name": "canary", "namespace": "default"},
                            "spec": {
                                "replicas": 4,
                                "selector": {"matchLabels": {"app": "canary"}},
                                "template": {
                                    "metadata": {"labels": {"app": "canary"}},
                                    "spec": {"containers": [{
                                        "name": "web",
                                        "image": "registry.example.com/canary:1.0",
                                        "resources": {"requests": {"cpu": "500m", "memory": "1Gi"}},
                                    }]},
                                },
                            },
                        },
                        {
                            "apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
                            "metadata": {"name": "canary-pdb", "namespace": "default"},
                            "spec": {
                                "minAvailable": 3,
                                "selector": {"matchLabels": {"app": "canary"}},
                            },
                        },
                    ],
                },
                {"name": "upgrade-workers", "type": "drain-wave", "nodes": ["worker-1"], "wave": 1},
                {"name": "spot-storm", "type": "reclaim-storm", "nodes": ["worker-2"]},
                {"name": "regrow", "type": "add-nodes", "count": 2, "template": {"path": "newnode/demo"}},
                {"name": "shrink-check", "type": "scale-down-check"},
            ],
        },
    })


def main():
    gen_cluster_demo()
    gen_cluster_gpushare()
    gen_app_simple()
    gen_app_local()
    gen_app_gpushare()
    gen_app_scale()
    gen_app_mixed()
    gen_chart()
    gen_newnode()
    gen_configs()
    gen_campaign()
    print(f"example tree regenerated under {os.path.abspath(ROOT)}", file=sys.stderr)


if __name__ == "__main__":
    main()
