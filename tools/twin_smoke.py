#!/usr/bin/env python
"""Live-twin smoke gate (``make twin-smoke``, part of ``make verify``).

The ISSUE 6 chaos proof, end to end and in one process:

1. start the canned stub apiserver (``server/stubapi.py``) and a watch-mode
   REST server against it (stdlib ``?watch=1`` source, no kubernetes
   package needed);
2. serve one deploy-apps request (builds the warm base prep), then mutate
   the cluster through watch events while injecting ``watch.disconnect``,
   ``watch.gone`` and a ``watch.drop_event`` mid-stream;
3. run an anti-entropy pass (repairs the dropped event, counts drift);
4. assert the twin's content fingerprint equals a fresh full relist, the
   watch server's next response is placement-shape-equal to a polling-mode
   server's answer after that relist, the delta path (not a second full
   prepare) carried the events, and ``/metrics`` shows the state machine,
   drift and fault counters.

Exit 0 on success; 1 with a one-line reason per failed check.
"""

import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> int:
    print(f"twin-smoke: FAIL: {msg}")
    return 1


def _pod(name, phase="Pending", node="", cpu="100m"):
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": cpu}}}]},
        "status": {"phase": phase},
    }
    if node:
        d["spec"]["nodeName"] = node
    return d


def _shape(resp):
    return (
        sorted((e["node"], len(e["pods"])) for e in resp["nodeStatus"]),
        sorted(u["reason"] for u in resp["unscheduledPods"]),
    )


def _wait(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def main() -> int:
    from http.server import ThreadingHTTPServer

    from opensim_tpu.engine.prepcache import fingerprint_cluster
    from opensim_tpu.models import fixtures as fx
    from opensim_tpu.resilience import faults
    from opensim_tpu.server import rest
    from opensim_tpu.server.snapshot import _cluster_via_rest
    from opensim_tpu.server.stubapi import StubApiServer
    from opensim_tpu.server.watch import RestWatchSource, WatchSupervisor
    from opensim_tpu.utils.trace import PREP_STATS

    stub = StubApiServer(bookmark_interval_s=0.1).start()
    stub.seed("/api/v1/nodes", [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(4)])
    stub.seed("/api/v1/pods", [_pod("seed", phase="Running", node="n0")])
    for p in (
        "/apis/apps/v1/daemonsets", "/apis/policy/v1/poddisruptionbudgets",
        "/api/v1/services", "/apis/storage.k8s.io/v1/storageclasses",
        "/api/v1/persistentvolumeclaims", "/api/v1/configmaps",
    ):
        stub.seed(p, [])
    tmp = tempfile.mkdtemp(prefix="twin-smoke-")
    kc = stub.kubeconfig(tmp)

    policy = {"stale_s": 5.0, "resync_s": 0.0, "reconnects": 3, "backoff_s": 0.02}
    sup = WatchSupervisor(RestWatchSource(kc, read_timeout_s=5.0), policy=policy)
    server = rest.SimonServer(kubeconfig=kc, watch=sup)
    sup.prep_cache = server.prep_cache
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), rest.make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    try:
        if not sup.start(wait_s=15.0):
            return fail("twin did not sync against the stub apiserver")

        payload = json.dumps(
            {"deployments": [fx.make_fake_deployment("smoke", 5, "500m", "1Gi").raw]}
        ).encode()

        def post():
            req = urllib.request.Request(f"{base}/api/deploy-apps", data=payload, method="POST")
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.status, json.load(resp)

        status, _first = post()
        if status != 200:
            return fail(f"warmup deploy-apps returned HTTP {status}")
        full_prepares = PREP_STATS.counts.get("full", 0)

        # --- fault storm while the cluster mutates --------------------------
        faults.inject("watch.disconnect", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod("storm-a"))
        if not _wait(lambda: faults.fault_stats().get("watch.disconnect") == 1):
            return fail("watch.disconnect fault never fired")

        faults.inject("watch.gone", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod("storm-b", cpu="250m"))
        if not _wait(lambda: faults.fault_stats().get("watch.gone") == 1):
            return fail("watch.gone fault never fired")
        if not _wait(lambda: sup.relists_total >= 1):
            return fail("410 Gone did not trigger a relist-and-rebase")

        faults.inject("watch.drop_event", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod("storm-c", cpu="150m"))
        if not _wait(lambda: faults.fault_stats().get("watch.drop_event") == 1):
            return fail("watch.drop_event fault never fired")

        drift = sup.anti_entropy()
        if drift < 0:
            return fail("anti-entropy relist failed")
        if sup.drift_total < 1:
            return fail("dropped event was not detected as drift")

        names = {"storm-a", "storm-b", "storm-c"}
        if not _wait(lambda: names <= {p.metadata.name for p in sup.twin.materialize().pods}):
            return fail("twin did not reconverge on the full mutation set")

        fresh, _rvs = _cluster_via_rest(kc, None)
        if sup.twin.fingerprint() != fingerprint_cluster(fresh):
            return fail("twin fingerprint != fresh full relist after the fault storm")

        # --- convergence proof: watch server vs polling server --------------
        status, twin_body = post()
        if status != 200:
            return fail(f"post-storm deploy-apps returned HTTP {status}")
        polling = rest.SimonServer(kubeconfig=kc)
        code, relist_body = polling.deploy_apps(
            {"deployments": [fx.make_fake_deployment("smoke", 5, "500m", "1Gi").raw]}
        )
        if code != 200:
            return fail(f"polling-mode server returned HTTP {code}")
        if _shape(twin_body) != _shape(relist_body):
            return fail(
                f"placements diverged: twin {_shape(twin_body)} vs relist {_shape(relist_body)}"
            )

        # --- warm path: post-storm, a single event rides the delta
        # re-encoder and the next request pays no full prepare (the storm
        # itself legitimately drops the lineage: a rebase is a content jump)
        full_before = PREP_STATS.counts.get("full", 0)
        delta_before = PREP_STATS.counts.get("twin_delta", 0)
        gen_before = sup.twin.generation
        stub.upsert("/api/v1/pods", _pod("calm"))
        if not _wait(lambda: sup.twin.generation > gen_before):
            return fail("calm-phase ADDED event never reached the twin")
        sup.flush_pending()
        if PREP_STATS.counts.get("twin_delta", 0) != delta_before + 1:
            return fail("calm-phase ADDED did not ride the twin_delta re-encoder")
        status, _calm = post()
        if status != 200:
            return fail(f"calm-phase deploy-apps returned HTTP {status}")
        if PREP_STATS.counts.get("full", 0) != full_before:
            return fail("calm-phase request paid a full O(cluster) prepare")

        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            metrics = resp.read().decode()
        for needle in (
            'simon_watch_state{state="live"} 1',
            "simon_watch_events_total",
            "simon_watch_reconnects_total",
            "simon_twin_drift_total{resource=",
            'simon_faults_injected_total{point="watch.disconnect"} 1',
            'simon_faults_injected_total{point="watch.gone"} 1',
            'simon_faults_injected_total{point="watch.drop_event"} 1',
        ):
            if needle not in metrics:
                return fail(f"/metrics missing {needle!r}")

        print(
            "twin-smoke: ok — disconnect/410/lost-event storm absorbed "
            f"(drift {sup.drift_total}, reconnects {sup.reconnects_total}, "
            f"relists {sup.relists_total}), placements shape-equal to a full "
            f"relist, {PREP_STATS.counts.get('twin_delta', 0)} delta re-encode(s), "
            f"{PREP_STATS.counts.get('full', 0) - full_prepares} extra full prepare(s)"
        )
        return 0
    finally:
        sup.stop()
        httpd.shutdown()
        stub.stop()


if __name__ == "__main__":
    sys.exit(main())
