"""`make tsan` — runtime lock-order sanitizer gate (docs/static-analysis.md).

Three phases, any failure exits non-zero:

1. **Detector self-test**: a seeded A→B/B→A inversion MUST be caught by a
   private LockWatch instance — a green gate means "no inversions
   observed by a proven-awake detector", never "detector asleep".
2. **Instrumented run**: installs the lockwatch wrapper (every
   ``threading.Lock``/``RLock`` created from repo code afterwards is
   traced), then runs the threaded test modules — ``test_watch.py``,
   ``test_admission.py``, ``test_capacity.py``, ``test_journal.py`` (the
   journal's bounded writer must never convoy reflector dispatch; its
   dispatch-side hold times are gated here) — in-process under it.
3. **Verdict**: any lock-order inversion, any non-exempt hold-time
   outlier (> ``OPENSIM_LOCKWATCH_HOLD_MS``, default 500), or a test
   failure fails the gate. Both acquisition stacks are printed for
   inversions.

Graceful skip (exit 0 with a notice): the threaded test modules are
absent, or pytest collects nothing from them (e.g. a build that excludes
threading-dependent tests) — there is nothing for a lock sanitizer to
watch then.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

THREADED_TESTS = ("test_watch.py", "test_admission.py", "test_capacity.py", "test_journal.py")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["OPENSIM_LOCKWATCH"] = "1"

    from opensim_tpu.analysis import lockwatch

    # phase 1: the detector must demonstrably catch a seeded inversion
    if not lockwatch.self_test():
        print("tsan: FAIL — lockwatch self-test did not catch the seeded "
              "A->B/B->A inversion (detector broken)")
        return 1
    print("tsan: self-test ok (seeded lock-order inversion caught)")

    present = [
        os.path.join(REPO, "tests", t)
        for t in THREADED_TESTS
        if os.path.isfile(os.path.join(REPO, "tests", t))
    ]
    if not present:
        print("tsan: SKIP — threaded test modules not present; nothing to watch")
        return 0

    # phase 2: install BEFORE importing opensim_tpu so module-level
    # singletons (RECORDER, FLIGHT_RECORDER, ...) get instrumented locks
    watch = lockwatch.install()
    import pytest  # noqa: E402

    rc = pytest.main(
        present
        + ["-q", "-m", "not slow", "-p", "no:cacheprovider", "-p", "no:randomly"]
    )
    rep = watch.report()
    print(lockwatch.format_report(rep))

    if rc == 5:  # no tests collected: threading-dependent tests excluded
        print("tsan: SKIP — pytest collected nothing from the threaded modules")
        return 0
    failed = False
    if rc != 0:
        print(f"tsan: FAIL — pytest exited {rc} under the sanitizer")
        failed = True
    if rep["inversions"]:
        print(f"tsan: FAIL — {len(rep['inversions'])} lock-order inversion(s)")
        failed = True
    if rep["hold_outliers"]:
        print(
            f"tsan: FAIL — {len(rep['hold_outliers'])} hold-time outlier(s) "
            f"over {rep['hold_threshold_ms']:.0f} ms"
        )
        failed = True
    if not failed:
        print(
            f"tsan: ok — {rep['edges']} lock-order edge(s) observed across "
            f"{rep['acquisitions']} acquisition(s), no inversions, no hold "
            "outliers"
        )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
