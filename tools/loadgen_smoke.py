#!/usr/bin/env python
"""Serving-core smoke gate (``make loadgen-smoke``, part of ``make verify``).

Two phases, both closed loops against the canned stub apiserver:

**Phase 1 — the ISSUE 8 core** (admission queue + batching vs the seed's
single-flight TryLock):

1. start the stub apiserver seeded with a small live cluster;
2. boot TWO live-twin simon servers as subprocesses against it — one with
   ``OPENSIM_ADMISSION=off``, one with the admission queue (the default);
3. drive each with the closed-loop load generator at the same concurrency;
4. assert the admission server sustains MORE QPS than the single-flight
   baseline with zero errors, a bounded p99, and a non-empty
   ``simon_batch_size`` histogram.

**Phase 2 — the ISSUE 15 fleet** (multi-process serving):

5. boot a ``--workers 2`` fleet (twin owner publishing arena deltas over
   shared memory + 2 SO_REUSEPORT workers) and a single-process admission
   server, drive both with the same closed loop;
6. assert fleet QPS ≥ the single-process run, zero errors, ZERO
   torn-generation attach abandonments, and the end-to-end placement
   parity gate (same payloads → same placements on both servers).

**Phase 3 — the ISSUE 16 pipeline** (staged continuous batching):

7. boot the admission server twice — ``OPENSIM_PIPELINE=off`` (serial
   inline batches) vs ``on`` (prep/dispatch/decode stages) — and drive
   both with the same closed loop;
8. assert the pipelined mode measured REAL overlap (prep-under-dispatch
   seconds > 0 on the server's own counter), sustains QPS no worse than
   the serial-batch floor, zero errors, and zero placement divergence
   (the end-to-end parity gate between the two modes).

The full-length run (the acceptance numbers) is
``python bench.py --config serving [--workers N]``; this gate uses shorter
windows and conservative margins so a loaded CI box never flakes.

Exit 0 on success; 1 with a one-line reason per failed check.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> int:
    print(f"loadgen-smoke: FAIL: {msg}")
    return 1


def main() -> int:
    from opensim_tpu.server.loadgen import (
        run_fleet_benchmark,
        run_pipeline_benchmark,
        run_stub_benchmark,
    )

    report = run_stub_benchmark(
        concurrency=16, duration_s=4.0, n_nodes=6, n_pods=12,
        base_port=18850,
    )
    print(
        "loadgen-smoke: single-flight "
        f"{report['qps_single_flight']:.1f} qps vs admission "
        f"{report['qps']:.1f} qps ({report['speedup']:.2f}x), "
        f"{report['batches']} batches (mean size "
        f"{report['mean_batch_size']:.1f}), p99 {report['p99_s'] or -1:.3f}s"
    )
    if report["admission"]["errors"]:
        return fail(f"admission run had {report['admission']['errors']} errors")
    if report["qps_single_flight"] <= 0:
        return fail("single-flight baseline measured 0 qps")
    # CI-safe margin: the acceptance-grade ≥4x number comes from the longer
    # bench run; a loaded CI box still must show batching WINNING
    if report["qps"] <= report["qps_single_flight"] * 1.1:
        return fail(
            f"admission qps {report['qps']} not above single-flight "
            f"baseline {report['qps_single_flight']} (x1.1 margin)"
        )
    if report["batches"] < 1 or report["mean_batch_size"] < 2:
        return fail(
            "batch-size histogram empty or degenerate "
            f"(batches={report['batches']}, mean={report['mean_batch_size']})"
        )
    if report["p99_s"] is None or report["p99_s"] > 5.0:
        return fail(f"admission p99 unbounded: {report['p99_s']}")
    print("loadgen-smoke: ok — " + json.dumps(
        {k: report[k] for k in (
            "qps_single_flight", "qps", "speedup", "mean_batch_size", "p99_s"
        )}
    ))

    # ---- phase 2: the multi-process fleet (ISSUE 15) ----------------------
    # sharded clients + enough concurrency to engage both workers: below
    # that the comparison is box noise (one admission process already
    # keeps a small closed loop fed), not the fleet
    fleet = run_fleet_benchmark(
        workers=2, concurrency=48, duration_s=6.0, n_nodes=6, n_pods=12,
        base_port=18860, client_procs=2,
    )
    print(
        "loadgen-smoke: fleet(2w) "
        f"{fleet['qps']:.1f} qps vs single-process "
        f"{fleet['qps_single_process']:.1f} qps "
        f"({fleet['vs_single_process']:.2f}x), p99 {fleet['p99_s'] or -1:.3f}s "
        f"(single {fleet['p99_single_process_s'] or -1:.3f}s), "
        f"gen {fleet['fleet_generation']}, respawns {fleet['respawns']}"
    )
    if fleet["errors"]:
        return fail(f"fleet run had {fleet['errors']} errors")
    if not fleet["placements_identical"]:
        return fail("fleet placements diverged from the single-process server")
    if fleet["torn_generation_exhausted"]:
        return fail(
            "workers exhausted seqlock retries "
            f"({fleet['torn_generation_exhausted']} torn-generation abandonments)"
        )
    # the fleet must at least match one process (the acceptance multiple
    # comes from the longer bench run); the 0.95 floor absorbs CI noise on
    # a box where 2 workers already saturate the cores. Below 2 cores the
    # fleet CANNOT match one process — two worker processes on one core
    # are pure context-switch overhead (measured ~0.75x) — so the floor
    # drops and the correctness gates above carry the phase.
    cores = os.cpu_count() or 1
    fleet_floor = 0.95 if cores >= 2 else 0.6
    if fleet["qps"] < fleet["qps_single_process"] * fleet_floor:
        return fail(
            f"fleet qps {fleet['qps']} below single-process "
            f"{fleet['qps_single_process']} (x{fleet_floor} floor, {cores} core(s))"
        )
    if fleet["fleet_generation"] < 0 or fleet["fleet_publishes"] < 1:
        return fail("owner never published a generation over shared memory")
    print("loadgen-smoke: ok — " + json.dumps(
        {k: fleet[k] for k in (
            "qps_single_process", "qps", "vs_single_process", "p99_s",
            "placements_identical", "torn_generation_exhausted",
        )}
    ))

    # ---- phase 3: the staged pipeline (ISSUE 16) --------------------------
    pipe = run_pipeline_benchmark(
        concurrency=16, duration_s=4.0, n_nodes=6, n_pods=12,
        base_port=18880,
    )
    print(
        "loadgen-smoke: pipelined "
        f"{pipe['qps']:.1f} qps vs serial-batch "
        f"{pipe['qps_non_pipelined']:.1f} qps "
        f"({pipe['vs_non_pipelined']:.2f}x on {pipe['host_cores']} core(s)), "
        f"{pipe['overlapped_batches']}/{pipe['batches']} batches overlapped "
        f"({pipe['prep_overlap_s']:.3f}s prep under dispatch), "
        f"p99 {pipe['p99_s'] or -1:.3f}s"
    )
    if pipe["errors"]:
        return fail(f"pipelined run had {pipe['errors']} errors")
    if not pipe["placements_identical"]:
        return fail("pipelined placements diverged from the serial-batch mode")
    if pipe["prep_overlap_s"] <= 0 or pipe["overlapped_batches"] < 1:
        return fail(
            "pipeline measured no prep-under-dispatch overlap "
            f"(overlap={pipe['prep_overlap_s']}s, "
            f"overlapped_batches={pipe['overlapped_batches']})"
        )
    # QPS floor, not a speedup gate: the acceptance multiple needs spare
    # cores (bench.py refuses cross-core-count comparisons for the same
    # reason). Below 4 cores the stages all contend for the same core —
    # overlap exists but cannot pay — so the floor only screens for a
    # pathological slowdown there
    floor = 0.9 if pipe["host_cores"] >= 4 else 0.7
    if pipe["qps"] < pipe["qps_non_pipelined"] * floor:
        return fail(
            f"pipelined qps {pipe['qps']} below the serial-batch floor "
            f"{pipe['qps_non_pipelined']} (x{floor}, {pipe['host_cores']} core(s))"
        )
    print("loadgen-smoke: ok — " + json.dumps(
        {k: pipe[k] for k in (
            "qps_non_pipelined", "qps", "vs_non_pipelined", "host_cores",
            "overlapped_batches", "prep_overlap_s", "placements_identical",
        )}
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
