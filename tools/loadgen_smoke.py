#!/usr/bin/env python
"""Serving-core smoke gate (``make loadgen-smoke``, part of ``make verify``).

The ISSUE 8 closed loop, shortened for CI:

1. start the canned stub apiserver seeded with a small live cluster;
2. boot TWO live-twin simon servers as subprocesses against it — one with
   ``OPENSIM_ADMISSION=off`` (the seed's single-flight TryLock behavior),
   one with the admission queue + cross-request batching (the default);
3. drive each with the closed-loop load generator
   (``opensim_tpu/server/loadgen.py``) at the same concurrency;
4. assert the admission server sustains MORE QPS than the single-flight
   baseline with zero errors, a bounded p99, and a non-empty
   ``simon_batch_size`` histogram (batching actually engaged — a smoke
   that passes with batching silently dead would gate nothing).

The full-length run (the ≥4× acceptance number) is
``python bench.py --config serving``; this gate uses shorter windows and a
conservative margin so a loaded CI box never flakes.

Exit 0 on success; 1 with a one-line reason per failed check.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> int:
    print(f"loadgen-smoke: FAIL: {msg}")
    return 1


def main() -> int:
    from opensim_tpu.server.loadgen import run_stub_benchmark

    report = run_stub_benchmark(
        concurrency=16, duration_s=4.0, n_nodes=6, n_pods=12,
        base_port=18850,
    )
    print(
        "loadgen-smoke: single-flight "
        f"{report['qps_single_flight']:.1f} qps vs admission "
        f"{report['qps']:.1f} qps ({report['speedup']:.2f}x), "
        f"{report['batches']} batches (mean size "
        f"{report['mean_batch_size']:.1f}), p99 {report['p99_s'] or -1:.3f}s"
    )
    if report["admission"]["errors"]:
        return fail(f"admission run had {report['admission']['errors']} errors")
    if report["qps_single_flight"] <= 0:
        return fail("single-flight baseline measured 0 qps")
    # CI-safe margin: the acceptance-grade ≥4x number comes from the longer
    # bench run; a loaded CI box still must show batching WINNING
    if report["qps"] <= report["qps_single_flight"] * 1.1:
        return fail(
            f"admission qps {report['qps']} not above single-flight "
            f"baseline {report['qps_single_flight']} (x1.1 margin)"
        )
    if report["batches"] < 1 or report["mean_batch_size"] < 2:
        return fail(
            "batch-size histogram empty or degenerate "
            f"(batches={report['batches']}, mean={report['mean_batch_size']})"
        )
    if report["p99_s"] is None or report["p99_s"] > 5.0:
        return fail(f"admission p99 unbounded: {report['p99_s']}")
    print("loadgen-smoke: ok — " + json.dumps(
        {k: report[k] for k in (
            "qps_single_flight", "qps", "speedup", "mean_batch_size", "p99_s"
        )}
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
