#!/usr/bin/env python
"""Best-effort Go-baseline proxy — used while no Go toolchain exists in this
environment (BASELINE.md requires the reference be *measured*; this stays an
estimate and is labeled as such everywhere it is quoted).

Model
-----
The reference schedules strictly serially: one pod in flight at a time
(`pkg/simulator/simulator.go:309-348` blocks on a channel per pod), each pod
running the vendored kube-scheduler pipeline over EVERY node
(`PercentageOfNodesToScore=100`, `pkg/simulator/utils.go:370`) with
16-goroutine fan-out (`vendor/.../parallelize/parallelism.go:26-41`).

    t_pod(N) = t_fixed + N * (c_filter * n_filter + c_score * n_score) / W

- n_filter = 10 filter plugins, n_score = 8 score plugins in the active
  profile (`algorithmprovider/registry.go:71-149`)
- W = 16 workers
- t_fixed = per-pod driver overhead: pod Create through the fake client,
  informer dispatch, scheduling-queue pop, bind Update, rendezvous channel
  round-trip (`simulator.go:323-346`, `scheduler.go:441-614`)

Three cost models bracket the plausible range:

  optimistic   c = 100 ns/plugin·node, t_fixed = 50 µs   (branch-predictable
               predicates, warm caches — a floor, not an expectation)
  realistic    c = 500 ns/plugin·node, t_fixed = 200 µs  (label-map lookups,
               selector matching, string ops dominate the Go plugins)
  SLO-anchored derived from the kube-scheduler scalability SLO of
               100 pods/s on a 5k-node cluster (k8s sig-scalability SLO;
               note that figure is measured WITH 50 % node sampling —
               simon forces 100 %, so this still flatters the baseline):
               t_pod(5000) = 10 ms, split per the formula above.

Run: python tools/go_baseline_proxy.py
"""

N_FILTER = 10
N_SCORE = 8
WORKERS = 16

MODELS = {
    "optimistic": dict(c=100e-9, fixed=50e-6),
    "realistic": dict(c=500e-9, fixed=200e-6),
    # solve c for t_pod(5000) = 10 ms with the realistic fixed cost
    "slo-anchored": dict(
        c=(10e-3 - 200e-6) * WORKERS / (5000 * (N_FILTER + N_SCORE)), fixed=200e-6
    ),
}

# (name, pods, nodes, measured TPU seconds from BENCH.md)
CONFIGS = [
    ("50k/5k headline", 50_000, 5_000, 2.4),
    ("10k/1k (config 3)", 10_000, 1_000, 1.0),
    ("affinity 5k/500 (config 4)", 5_000, 500, 1.4),
]


def t_pod(n_nodes: int, c: float, fixed: float) -> float:
    return fixed + n_nodes * c * (N_FILTER + N_SCORE) / WORKERS


def main() -> None:
    print(f"{'config':28s} {'model':14s} {'est. Go wall':>12s} {'TPU':>6s} {'est. speedup':>12s}")
    for name, pods, nodes, tpu_s in CONFIGS:
        for model, p in MODELS.items():
            go_s = pods * t_pod(nodes, p["c"], p["fixed"])
            print(f"{name:28s} {model:14s} {go_s:10.1f} s {tpu_s:5.1f}s {go_s / tpu_s:11.0f}×")
    print(
        "\nAll figures are MODELED, not measured — the environment ships no Go\n"
        "toolchain. The SLO-anchored model is the most defensible: it starts\n"
        "from the kube-scheduler's own 100 pods/s scalability SLO at 5k nodes\n"
        "and still understates simon's cost (simon scores 100% of nodes and\n"
        "adds a serial channel rendezvous per pod)."
    )


if __name__ == "__main__":
    main()
