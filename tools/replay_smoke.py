#!/usr/bin/env python
"""Durability & replay smoke gate (``make replay-smoke``, part of
``make verify``).

The ISSUE 11 crash-recovery proof, end to end and in one process:

1. start the canned stub apiserver and a journaled watch-mode server
   (``simon server --journal`` wiring, in-process); serve one deploy-apps
   request (builds the warm base prep), then mutate the cluster through an
   event storm;
2. "crash": abandon the supervisor WITHOUT a clean stop and scribble a torn
   frame onto the newest segment (the on-disk shape a SIGKILL mid-write
   leaves behind);
3. recover: a fresh supervisor on the same journal must restore the twin
   from checkpoint + suffix replay — fingerprint bit-equal to a fresh full
   relist, ZERO relists spent, the torn tail truncated loudly, and
   ``simon_journal_recoveries_total{outcome="restored"}`` counted;
4. prove the restored lineage is warm: post-restore deploys pay exactly ONE
   full prepare and a calm-phase event rides the twin_delta re-encoder;
5. replay: ``simon replay <journal> --speed 10`` must reproduce the final
   twin fingerprint, and ``bench.py --config replay --journal <journal>``
   must emit a benchmark row with ``rebuild_bit_equal``.

Exit 0 on success; 1 with a one-line reason per failed check.
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> int:
    print(f"replay-smoke: FAIL: {msg}")
    return 1


def _pod(name, phase="Pending", node="", cpu="100m"):
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": cpu}}}]},
        "status": {"phase": phase},
    }
    if node:
        d["spec"]["nodeName"] = node
    return d


def _wait(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def main() -> int:
    from http.server import ThreadingHTTPServer

    from opensim_tpu.engine.prepcache import fingerprint_cluster
    from opensim_tpu.models import fixtures as fx
    from opensim_tpu.server import rest
    from opensim_tpu.server.journal import Journal
    from opensim_tpu.server.snapshot import _cluster_via_rest
    from opensim_tpu.server.stubapi import StubApiServer
    from opensim_tpu.server.watch import RestWatchSource, WatchSupervisor
    from opensim_tpu.utils.trace import PREP_STATS

    stub = StubApiServer(bookmark_interval_s=0.1).start()
    stub.seed("/api/v1/nodes", [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(4)])
    stub.seed("/api/v1/pods", [_pod("seed", phase="Running", node="n0")])
    for p in (
        "/apis/apps/v1/daemonsets", "/apis/policy/v1/poddisruptionbudgets",
        "/api/v1/services", "/apis/storage.k8s.io/v1/storageclasses",
        "/api/v1/persistentvolumeclaims", "/api/v1/configmaps",
    ):
        stub.seed(p, [])
    tmp = tempfile.mkdtemp(prefix="replay-smoke-")
    kc = stub.kubeconfig(tmp)
    jdir = os.path.join(tmp, "journal")

    policy = {"stale_s": 5.0, "resync_s": 0.0, "reconnects": 3, "backoff_s": 0.02}
    # fsync=always: the crash-test setting — every accepted event is on disk
    # before the "crash" below
    sup1 = WatchSupervisor(
        RestWatchSource(kc, read_timeout_s=5.0), policy=policy,
        journal=Journal(jdir, policy={"fsync": "always"}),
    )
    fp_crash = None
    try:
        if not sup1.start(wait_s=15.0):
            return fail("recording twin did not sync against the stub apiserver")

        for i in range(25):
            stub.upsert("/api/v1/pods", _pod(f"storm-{i}", cpu="150m"))
        stub.delete("/api/v1/pods", "storm-3")
        want = {f"storm-{i}" for i in range(25)} - {"storm-3"} | {"seed"}
        if not _wait(lambda: {p.metadata.name for p in sup1.twin.materialize().pods} == want):
            return fail("recording twin did not converge on the storm")
        if not sup1.journal.flush(timeout=10.0):
            return fail("journal flush before the crash timed out")
        fp_crash = sup1.twin.fingerprint()
    finally:
        # a failed recording phase ends the run; success "crashes" instead:
        # no sup1.stop(), no journal.close() — the writer just stops being
        # scheduled, exactly like a SIGKILL
        if fp_crash is None:
            stub.stop()
    # --- the crash: halt sup1's threads (a SIGKILL would take them too —
    # the true-subprocess version lives in tests/test_journal.py) but never
    # close the journal, then scribble a torn half-frame onto the newest
    # segment: the on-disk shape of dying mid-write
    sup1.stop()
    segs = sorted(f for f in os.listdir(jdir) if f.endswith(".seg"))
    if not segs:
        stub.stop()
        return fail("no journal segments were written")
    with open(os.path.join(jdir, segs[-1]), "ab") as f:
        f.write(b"\x94\x00\x00\x00TORN")  # length says 148, bytes say crash

    # --- recovery ----------------------------------------------------------
    jr2 = Journal(jdir, policy={"fsync": "always"})
    sup2 = WatchSupervisor(RestWatchSource(kc, read_timeout_s=5.0), policy=policy, journal=jr2)
    server = rest.SimonServer(kubeconfig=kc, watch=sup2, journal=jr2)
    sup2.prep_cache = server.prep_cache
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), rest.make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        PREP_STATS.reset()
        if not sup2.start(wait_s=15.0):
            return fail("recovery twin did not come up from the journal")
        if sup2.relists_total != 0:
            return fail(
                f"recovery spent {sup2.relists_total} relist(s); the journal "
                "restore path must resume the reflectors without one"
            )
        fresh, _rvs = _cluster_via_rest(kc, None)
        if sup2.twin.fingerprint() != fingerprint_cluster(fresh):
            return fail("restored fingerprint != fresh full relist")
        if sup2.twin.fingerprint() != fp_crash:
            return fail("restored fingerprint != the twin at crash time")
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            metrics = resp.read().decode()
        if 'simon_journal_recoveries_total{outcome="restored"} 1' not in metrics:
            return fail("/metrics missing the restored-recovery counter")

        # --- warm lineage: exactly ONE full prepare after recovery ---------
        payload = json.dumps(
            {"deployments": [fx.make_fake_deployment("smoke", 5, "500m", "1Gi").raw]}
        ).encode()

        def post():
            req = urllib.request.Request(f"{base}/api/deploy-apps", data=payload, method="POST")
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.status, json.load(resp)

        status, _ = post()
        if status != 200:
            return fail(f"post-recovery deploy-apps returned HTTP {status}")
        if PREP_STATS.counts.get("full", 0) != 1:
            return fail(
                f"post-recovery deploy paid {PREP_STATS.counts.get('full', 0)} "
                "full prepares (want the restored lineage's one)"
            )
        gen_before = sup2.twin.generation
        stub.upsert("/api/v1/pods", _pod("calm"))
        if not _wait(lambda: sup2.twin.generation > gen_before):
            return fail("calm-phase event never reached the restored twin")
        sup2.flush_pending()
        status, _ = post()
        if status != 200:
            return fail(f"calm-phase deploy-apps returned HTTP {status}")
        if PREP_STATS.counts.get("full", 0) != 1:
            return fail("calm-phase request paid a second full prepare on the restored lineage")

        # --- drift against the journal-restored twin is journaled as a
        # rebase record, keeping the file a faithful history (the replay
        # below must land on the post-repair state)
        from opensim_tpu.resilience import faults

        faults.inject("watch.drop_event", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod("dropped"))
        if not _wait(lambda: faults.fault_stats().get("watch.drop_event") == 1):
            return fail("watch.drop_event fault never fired")
        if sup2.anti_entropy() < 0:
            return fail("anti-entropy relist failed")
        if sup2.drift_total < 1:
            return fail("dropped event was not detected as drift")
        fp_final = sup2.twin.fingerprint()
    finally:
        sup2.stop()
        httpd.shutdown()
        server.close()
        stub.stop()

    # --- replay at 10x reproduces the final twin ---------------------------
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    out = subprocess.run(
        [sys.executable, "-m", "opensim_tpu", "replay", jdir, "--speed", "10"],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo,
    )
    if out.returncode != 0:
        return fail(f"simon replay failed: {out.stderr.strip()[-300:]}")
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    if summary["fingerprint"] != fp_final:
        return fail(
            f"replayed fingerprint {summary['fingerprint']} != live final {fp_final}"
        )
    if summary["rebases"] < 1:
        return fail("the crash-time anti-entropy rebase was not journaled")

    bench = subprocess.run(
        [sys.executable, "bench.py", "--config", "replay", "--journal", jdir],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo,
    )
    if bench.returncode != 0:
        return fail(f"bench.py --config replay failed: {bench.stderr.strip()[-300:]}")
    row = json.loads(bench.stdout.strip().splitlines()[-1])
    if row.get("config") != "replay" or not row.get("rebuild_bit_equal"):
        return fail(f"bench replay row malformed: {row}")

    print(
        "replay-smoke: ok — crash with torn tail restored bit-equal to a "
        f"fresh relist with 0 relists and 1 full prepare; 10x replay of "
        f"{summary['events']} event(s) + {summary['rebases']} rebase(s) "
        f"reproduced fingerprint {fp_final}; bench row "
        f"{row['events_per_s']} events/s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
