#!/bin/sh
# One-command TPU revalidation (VERDICT r4 #1): run the moment the axon
# tunnel opens (`/tmp/opensim-tpu-watch.up` appears, or `make tpu-probe`
# succeeds). Everything is timeout-wrapped because a dying tunnel hangs
# any device op forever.
#
#   make tpu-revalidate          # = sh tools/tpu_revalidate.sh
#
# Produces TPU_REVALIDATION.log (full output) and prints a summary. Steps:
#  1. probe the accelerator (fail fast if the tunnel is down)
#  2. compiled-Mosaic test pass: every megakernel/sweep parity test that
#     round 3-5 added on top of the last silicon-validated commit c4ea5bd
#  3. bench.py on every BASELINE config + the 100k/10k double-scale point
#  4. the batched-sweep scenarios/s/chip number (target >=50)
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
cd "$REPO" || exit 1
LOG="$REPO/TPU_REVALIDATION.log"
: > "$LOG"
say() { echo "== $*" | tee -a "$LOG"; }

say "probe"
if ! timeout 120 python -c "
import jax, numpy as np
d = jax.devices()
assert d and d[0].platform == 'tpu', d
x = np.asarray(jax.numpy.ones((8, 8)) * 2)
assert float(x.sum()) == 128.0
print('TPU OK:', d)
" >> "$LOG" 2>&1; then
  say "FAIL: accelerator unreachable (tunnel down) — see $LOG"
  exit 1
fi

say "compiled-Mosaic test pass (fastpath + sweeps + kernel parity)"
timeout 3000 env OPENSIM_TEST_BACKEND=tpu python -m pytest \
  tests/test_fastpath.py tests/test_fastpath_fuzz.py tests/test_parallel.py \
  tests/test_kernel_parity.py -q >> "$LOG" 2>&1
TESTS_RC=$?
say "tests rc=$TESTS_RC (0 = all compiled-Mosaic parity tests green)"

say "bench: headline + all configs"
for ARGS in "" "--config bigu" "--config forced" "--config affinity --pods 5000 --nodes 500" \
            "--config example" "--config gpushare" "--pods 100000 --nodes 10000"; do
  say "bench.py $ARGS"
  timeout 1200 python bench.py $ARGS >> "$LOG" 2>&1 || say "  (rc=$? for '$ARGS')"
done

say "batched sweep scenarios/s/chip (target >=50)"
timeout 1200 python bench.py --config defrag --scenarios 64 --nodes 200 --pods 2000 >> "$LOG" 2>&1 || say "  (rc=$? for small sweep)"
timeout 1800 python bench.py --config defrag --scenarios 1000 --nodes 1000 --pods 10000 >> "$LOG" 2>&1 || say "  (rc=$? for 1000-scenario sweep)"

say "summary (JSON lines measured above)"
grep -h '^{' "$LOG" | tee -a /dev/null
say "done — paste the JSON lines into BENCH.md (round-5 TPU table), update README headline, and commit"
[ "$TESTS_RC" -eq 0 ] || exit 1
