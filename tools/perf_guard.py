#!/usr/bin/env python
"""Perf-regression sentinel (ISSUE 12): guard bench rows against the
committed baseline manifest.

The bench trajectory (BENCH_r01..r06) was append-only JSON no gate ever
read — a perf or memory regression shipped silently. This tool closes the
loop against ``BENCH_BASELINE.json``:

- every baseline entry carries the committed row plus per-metric
  tolerances (``kind: time`` → measured/baseline must stay under
  ``max_ratio``; ``kind: rate`` → must stay above ``min_ratio``;
  ``kind: exact`` → bit-stable counts — placement drift is a correctness
  bug, never noise);
- the default run is the SELF-CHECK: each committed baseline row must
  pass against its own tolerances, and a synthetically slowed copy must
  FAIL — the detector-awake proof (`make tsan` phase 1's pattern), so a
  manifest edit can never silently disarm the guard;
- ``--row FILE --baseline KEY`` guards an externally produced row (a
  fresh bench run on a dev box);
- ``--fresh KEY`` runs the entry's recorded bench command and guards the
  row it prints;
- ``--tolerance-only`` (what ``make verify`` runs): time/rate verdicts
  are REPORTED but only ``exact`` metrics fail the gate — wall-clock on a
  slow shared CI box must not flake the build, while a placement-count
  drift still does. Full enforcement is the default everywhere else.

Output: one human verdict table on stderr, one JSON summary line on
stdout (the repo's bench contract), nonzero exit on failure. See BENCH.md
"Guarding the trajectory" for the manifest format and the re-baselining
workflow.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(REPO, "BENCH_BASELINE.json")


@dataclass
class MetricVerdict:
    """One metric's comparison: typed, so the report is machine-usable."""

    metric: str
    kind: str  # time | rate | exact
    baseline: float
    measured: Optional[float]
    ratio: Optional[float]  # measured/baseline (None when unmeasurable)
    limit: Optional[float]  # max_ratio (time) / min_ratio (rate)
    ok: bool
    enforced: bool
    note: str = ""


@dataclass
class GuardReport:
    baseline: str
    source: str
    verdicts: List[MetricVerdict]

    @property
    def failed(self) -> List[MetricVerdict]:
        return [v for v in self.verdicts if not v.ok and v.enforced]

    @property
    def warned(self) -> List[MetricVerdict]:
        return [v for v in self.verdicts if not v.ok and not v.enforced]

    @property
    def ok(self) -> bool:
        return not self.failed


class GuardError(RuntimeError):
    """Typed failure: a malformed manifest/row — distinct from a tolerance
    violation (which is a report, not an exception)."""


def load_manifest(path: str = MANIFEST) -> dict:
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise GuardError(f"cannot load baseline manifest {path}: {e}") from None
    baselines = manifest.get("baselines")
    if not isinstance(baselines, dict) or not baselines:
        raise GuardError(f"{path}: no baselines")
    for key, entry in baselines.items():
        for field in ("row", "metrics", "source"):
            if field not in entry:
                raise GuardError(f"{path}: baseline {key!r} lacks {field!r}")
        for name, spec in entry["metrics"].items():
            kind = spec.get("kind")
            if kind not in ("time", "rate", "exact"):
                raise GuardError(
                    f"{path}: baseline {key!r} metric {name!r} has unknown "
                    f"kind {kind!r} (time|rate|exact)"
                )
            if kind == "time" and not spec.get("max_ratio"):
                raise GuardError(f"{path}: time metric {name!r} needs max_ratio")
            if kind == "rate" and not spec.get("min_ratio"):
                raise GuardError(f"{path}: rate metric {name!r} needs min_ratio")
            if name not in entry["row"]:
                raise GuardError(
                    f"{path}: baseline {key!r} row lacks guarded metric {name!r}"
                )
    return manifest


def compare(row: dict, key: str, entry: dict, tolerance_only: bool = False) -> GuardReport:
    """Compare one fresh bench row against one baseline entry.

    When both rows record ``host_cores`` and they differ, the comparison
    is REFUSED outright (one verdict, no ratios): serving QPS is
    core-count-bound, so a cross-core ratio measures the boxes, not the
    code — re-baseline on a same-core box instead (BENCH.md)."""
    base_cores = entry["row"].get("host_cores")
    row_cores = row.get("host_cores")
    if (
        base_cores is not None
        and row_cores is not None
        and int(base_cores) != int(row_cores)
    ):
        return GuardReport(
            baseline=key,
            source=entry["source"],
            verdicts=[
                MetricVerdict(
                    metric="host_cores", kind="exact",
                    baseline=float(base_cores), measured=float(row_cores),
                    ratio=None, limit=None, ok=False, enforced=True,
                    note=(
                        f"comparison refused: baseline measured on "
                        f"{base_cores} core(s), this row on {row_cores} — "
                        "time/rate ratios are not comparable across core "
                        "counts; re-baseline on a same-core box"
                    ),
                )
            ],
        )
    verdicts: List[MetricVerdict] = []
    for name, spec in entry["metrics"].items():
        kind = spec["kind"]
        base = float(entry["row"][name])
        enforced = (kind == "exact") or not tolerance_only
        if name not in row:
            verdicts.append(
                MetricVerdict(
                    metric=name, kind=kind, baseline=base, measured=None,
                    ratio=None, limit=spec.get("max_ratio") or spec.get("min_ratio"),
                    ok=False, enforced=True,  # a missing metric is never tolerable
                    note="metric missing from the measured row",
                )
            )
            continue
        measured = float(row[name])
        if kind == "exact":
            ok = measured == base
            verdicts.append(
                MetricVerdict(
                    metric=name, kind=kind, baseline=base, measured=measured,
                    ratio=None, limit=None, ok=ok, enforced=True,
                    note="" if ok else "exact metric drifted",
                )
            )
            continue
        ratio = measured / base if base else None
        if kind == "time":
            limit = float(spec["max_ratio"])
            ok = ratio is not None and ratio <= limit
            note = "" if ok else f"slower than {limit}x baseline"
        else:  # rate
            limit = float(spec["min_ratio"])
            ok = ratio is not None and ratio >= limit
            note = "" if ok else f"below {limit}x baseline"
        verdicts.append(
            MetricVerdict(
                metric=name, kind=kind, baseline=base, measured=measured,
                ratio=round(ratio, 4) if ratio is not None else None,
                limit=limit, ok=ok, enforced=enforced, note=note,
            )
        )
    return GuardReport(baseline=key, source=entry["source"], verdicts=verdicts)


def slowed_row(entry: dict, factor: float = 8.0) -> dict:
    """A synthetically degraded copy of the committed row: every time
    metric multiplied, every rate metric divided — the self-check input
    that MUST fail (proves the tolerances actually bite)."""
    row = dict(entry["row"])
    for name, spec in entry["metrics"].items():
        if spec["kind"] == "time":
            row[name] = float(row[name]) * factor
        elif spec["kind"] == "rate":
            row[name] = float(row[name]) / factor
    return row


def render_report(report: GuardReport, out) -> None:
    status = "PASS" if report.ok else "FAIL"
    print(f"[perf-guard] {report.baseline} ({report.source}): {status}", file=out)
    for v in report.verdicts:
        mark = "ok " if v.ok else ("WARN" if not v.enforced else "FAIL")
        ratio = f" ratio={v.ratio}" if v.ratio is not None else ""
        limit = ""
        if v.limit is not None:
            limit = f" limit={'<=' if v.kind == 'time' else '>='}{v.limit}"
        note = f" ({v.note})" if v.note else ""
        print(
            f"  {mark} {v.metric} [{v.kind}] baseline={v.baseline} "
            f"measured={v.measured}{ratio}{limit}{note}",
            file=out,
        )


def self_check(manifest: dict) -> List[GuardReport]:
    """Every committed baseline row passes; every slowed copy fails. Runs
    with enforcement ON regardless of --tolerance-only: the flag only
    relaxes FRESH-row timing (--row/--fresh on a slow box); the detector
    itself must always be provably awake."""
    reports: List[GuardReport] = []
    for key, entry in manifest["baselines"].items():
        clean = compare(entry["row"], key, entry, tolerance_only=False)
        reports.append(clean)
        if not clean.ok:
            continue  # already failing; the report says why
        slow = compare(slowed_row(entry), key, entry, tolerance_only=False)
        if slow.ok:
            # a manifest whose tolerances cannot catch an 8x slowdown is
            # disarmed — fail the self-check loudly
            reports.append(
                GuardReport(
                    baseline=f"{key} (slowed-copy self-test)",
                    source=entry["source"],
                    verdicts=[
                        MetricVerdict(
                            metric="detector-awake", kind="exact", baseline=1.0,
                            measured=0.0, ratio=None, limit=None, ok=False,
                            enforced=True,
                            note="an 8x-degraded row PASSED; tolerances are disarmed",
                        )
                    ],
                )
            )
        else:
            reports.append(
                GuardReport(
                    baseline=f"{key} (slowed-copy self-test)",
                    source=entry["source"],
                    verdicts=[
                        MetricVerdict(
                            metric="detector-awake", kind="exact", baseline=1.0,
                            measured=1.0, ratio=None, limit=None, ok=True,
                            enforced=True,
                            note=f"{len(slow.failed)} metric(s) correctly failed",
                        )
                    ],
                )
            )
    return reports


def run_fresh(entry: dict) -> dict:
    """Run the entry's recorded bench command and parse its one-line JSON
    row (the repo's bench stdout contract)."""
    cmd = entry.get("bench_cmd")
    if not cmd:
        raise GuardError("baseline entry has no bench_cmd; use --row instead")
    proc = subprocess.run(
        cmd, cwd=REPO, capture_output=True, text=True, timeout=1800
    )
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    if proc.returncode != 0 or not lines:
        raise GuardError(
            f"bench command {' '.join(cmd)} failed rc={proc.returncode}: "
            f"{(lines[-1] if lines else proc.stderr.strip()[-400:])!r}"
        )
    try:
        row = json.loads(lines[-1])
    except ValueError as e:
        raise GuardError(f"bench output is not a JSON row: {e}") from None
    if "error" in row:
        raise GuardError(f"bench failed: {row}")
    return row


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--manifest", default=MANIFEST, help="baseline manifest path")
    ap.add_argument("--baseline", default="", help="baseline key for --row/--fresh")
    ap.add_argument("--row", default="", metavar="FILE", help="guard a bench row from FILE (or - for stdin)")
    ap.add_argument("--fresh", action="store_true", help="run the baseline's bench command and guard its row")
    ap.add_argument(
        "--tolerance-only", action="store_true",
        help="time/rate violations are reported but only exact metrics fail "
        "(the make verify mode: slow CI boxes must not flake the build)",
    )
    args = ap.parse_args()

    try:
        manifest = load_manifest(args.manifest)
    except GuardError as e:
        print(json.dumps({"error": str(e), "stage": "manifest"}))
        print(f"perf-guard: {e}", file=sys.stderr)
        return 2

    reports: List[GuardReport] = []
    try:
        if args.row or args.fresh:
            key = args.baseline
            if not key:
                if len(manifest["baselines"]) == 1:
                    key = next(iter(manifest["baselines"]))
                else:
                    raise GuardError(
                        "--baseline KEY required (known: "
                        + ", ".join(sorted(manifest["baselines"])) + ")"
                    )
            if key not in manifest["baselines"]:
                raise GuardError(f"unknown baseline {key!r}")
            entry = manifest["baselines"][key]
            if args.fresh:
                row = run_fresh(entry)
            else:
                raw = sys.stdin.read() if args.row == "-" else open(args.row).read()
                row = json.loads(raw)
            reports.append(compare(row, key, entry, tolerance_only=args.tolerance_only))
        else:
            reports = self_check(manifest)
    except (GuardError, OSError, ValueError) as e:
        print(json.dumps({"error": str(e), "stage": "guard"}))
        print(f"perf-guard: {e}", file=sys.stderr)
        return 2

    for report in reports:
        render_report(report, sys.stderr)
    failed = [r for r in reports if not r.ok]
    warned = sum(len(r.warned) for r in reports)
    print(
        json.dumps(
            {
                "metric": "perf-guard",
                "baselines": len(reports),
                "failed": [r.baseline for r in failed],
                "warnings": warned,
                "tolerance_only": args.tolerance_only,
                "ok": not failed,
                "reports": [
                    {"baseline": r.baseline, "verdicts": [asdict(v) for v in r.verdicts]}
                    for r in reports
                ],
            },
            sort_keys=True,
        )
    )
    return 0 if not failed else 1


if __name__ == "__main__":
    sys.exit(main())
