#!/usr/bin/env python
"""Perf smoke gate (`make perf-smoke`, wired into `make verify`).

Runs a small affinity-heavy workload (the ISSUE-4 shape: required + preferred
interpod terms plus hard topology spread) through the C++ scan engine twice:

1. normally — asserting the INCREMENTAL same-template cache actually served
   the scheduled steps (a silent disengage back to the generic path is the
   failure mode this gate exists to catch, long before a 10 s bench run);
2. with OPENSIM_NATIVE_FORCE_GENERIC=1 — asserting placements, failure
   attribution and the final count tensors are bit-identical, so the cache
   can never trade correctness for the speed it reports.

Prints one JSON line and exits nonzero on any violation.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from opensim_tpu import native
    from opensim_tpu.engine import nativepath
    from opensim_tpu.engine.simulator import AppResource, prepare

    import bench

    if not native.available():
        # match the test suites' behavior: environments without a C++
        # toolchain skip native-dependent gates instead of failing verify
        print(json.dumps({"skipped": f"native engine unavailable: {native.load_error()}"}))
        return 0

    # the knob under test must not leak in from (or stomp) the caller's env
    prior_fg = os.environ.pop("OPENSIM_NATIVE_FORCE_GENERIC", None)

    cluster = bench.synthetic_cluster(200)
    apps = [AppResource("smoke", bench.affinity_apps(2000))]
    prep = prepare(cluster, apps, node_pad=128)
    pv = np.ones(len(prep.ordered), bool)

    t0 = time.time()
    out_inc = nativepath.schedule(prep, pv)
    t_inc = time.time() - t0
    stats = out_inc.native_stats or {}
    steps = stats.get("steps", {})

    os.environ["OPENSIM_NATIVE_FORCE_GENERIC"] = "1"
    try:
        t0 = time.time()
        out_gen = nativepath.schedule(prep, pv)
        t_gen = time.time() - t0
    finally:
        if prior_fg is None:
            del os.environ["OPENSIM_NATIVE_FORCE_GENERIC"]
        else:
            os.environ["OPENSIM_NATIVE_FORCE_GENERIC"] = prior_fg

    record = {
        "metric": "perf-smoke (2k-pod/200-node affinity, incremental vs generic)",
        "native_path": stats.get("path"),
        "native_steps": steps,
        "incremental_s": round(t_inc, 3),
        "generic_s": round(t_gen, 3),
        "forced_path": (out_gen.native_stats or {}).get("path"),
    }

    if stats.get("path") != "incremental":
        record["error"] = (
            "incremental cache did not engage on the affinity workload "
            f"(path={stats.get('path')!r}, steps={steps})"
        )
    elif (out_gen.native_stats or {}).get("path") != "generic":
        record["error"] = "OPENSIM_NATIVE_FORCE_GENERIC=1 did not force the generic path"
    elif not np.array_equal(out_inc.chosen, out_gen.chosen):
        mism = int((out_inc.chosen != out_gen.chosen).sum())
        record["error"] = f"{mism} placement mismatches incremental vs generic"
    elif not np.array_equal(out_inc.fail_counts, out_gen.fail_counts):
        record["error"] = "failure attribution differs incremental vs generic"
    elif not np.array_equal(out_inc.final_state.used, out_gen.final_state.used) or not np.array_equal(
        out_inc.final_state.dom_sel, out_gen.final_state.dom_sel
    ):
        record["error"] = "final state differs incremental vs generic"

    print(json.dumps(record))
    return 1 if "error" in record else 0


if __name__ == "__main__":
    sys.exit(main())
