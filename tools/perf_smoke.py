#!/usr/bin/env python
"""Perf smoke gate (`make perf-smoke`, wired into `make verify`).

Runs small feature-heavy workloads through the C++ scan engine twice each:

1. normally — asserting the INCREMENTAL same-template cache actually served
   the scheduled steps (a silent disengage back to the generic path is the
   failure mode this gate exists to catch, long before a 10 s bench run);
2. with OPENSIM_NATIVE_FORCE_GENERIC=1 — asserting placements, failure
   attribution and the final count tensors are bit-identical, so the cache
   can never trade correctness for the speed it reports.

Three scenarios cover the envelope's load-bearing carry classes (ISSUE 19):

- ``affinity`` — the ISSUE-4 shape: required + preferred interpod terms
  plus hard topology spread;
- ``ports`` — every template carries host ports (per-node port-bitmap
  carry; classes attribution must show ``ports``);
- ``gpu`` — gpu-share + whole-GPU templates (per-GPU-index headroom carry
  and the gc_dyn dynamic share score; classes must show ``gpu``).

Prints one JSON line and exits nonzero on any violation.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _ports_apps(n_pods):
    """All templates carry host ports: each incremental step exercises the
    per-node port-bitmap carry."""
    from opensim_tpu.models import ResourceTypes, fixtures as fx

    rt = ResourceTypes()
    n_workloads = 8
    per = n_pods // n_workloads
    port_sets = ([8080], [9090], [8080, 9443], [5000], [9443], [5000, 9090], [8443], [7070])
    for w in range(n_workloads):
        rt.deployments.append(
            fx.make_fake_deployment(
                f"ports-{w}", per, "250m", "512Mi",
                fx.with_host_ports(port_sets[w]),
            )
        )
    return rt


def _run_scenario(name, cluster, apps, required_class, nativepath, prepare):
    """One engagement + bit-equality pass; returns (record, error|None)."""
    from opensim_tpu.engine.simulator import AppResource

    prep = prepare(cluster, [AppResource(name, apps)], node_pad=128)
    pv = np.ones(len(prep.ordered), bool)

    t0 = time.time()
    out_inc = nativepath.schedule(prep, pv)
    t_inc = time.time() - t0
    stats = out_inc.native_stats or {}
    steps = stats.get("steps", {})

    os.environ["OPENSIM_NATIVE_FORCE_GENERIC"] = "1"
    try:
        t0 = time.time()
        out_gen = nativepath.schedule(prep, pv)
        t_gen = time.time() - t0
    finally:
        del os.environ["OPENSIM_NATIVE_FORCE_GENERIC"]

    record = {
        "native_path": stats.get("path"),
        "native_steps": steps,
        "incremental_s": round(t_inc, 3),
        "generic_s": round(t_gen, 3),
        "forced_path": (out_gen.native_stats or {}).get("path"),
    }

    error = None
    if stats.get("path") != "incremental" or int(steps.get("incremental", 0)) <= 0:
        error = (
            f"{name}: incremental cache did not engage "
            f"(path={stats.get('path')!r}, steps={steps})"
        )
    elif required_class and int((steps.get("classes") or {}).get(required_class, 0)) <= 0:
        error = (
            f"{name}: incremental path never exercised the {required_class!r} "
            f"carry class (classes={steps.get('classes')})"
        )
    elif (out_gen.native_stats or {}).get("path") != "generic":
        error = f"{name}: OPENSIM_NATIVE_FORCE_GENERIC=1 did not force the generic path"
    elif not np.array_equal(out_inc.chosen, out_gen.chosen):
        mism = int((out_inc.chosen != out_gen.chosen).sum())
        error = f"{name}: {mism} placement mismatches incremental vs generic"
    elif not np.array_equal(out_inc.fail_counts, out_gen.fail_counts):
        error = f"{name}: failure attribution differs incremental vs generic"
    elif not np.array_equal(out_inc.final_state.used, out_gen.final_state.used) or not np.array_equal(
        out_inc.final_state.dom_sel, out_gen.final_state.dom_sel
    ):
        error = f"{name}: final state differs incremental vs generic"
    return record, error


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from opensim_tpu import native
    from opensim_tpu.engine import nativepath
    from opensim_tpu.engine.simulator import prepare

    import bench

    if not native.available():
        # match the test suites' behavior: environments without a C++
        # toolchain skip native-dependent gates instead of failing verify
        print(json.dumps({"skipped": f"native engine unavailable: {native.load_error()}"}))
        return 0

    # the knob under test must not leak in from (or stomp) the caller's env
    prior_fg = os.environ.pop("OPENSIM_NATIVE_FORCE_GENERIC", None)

    scenarios = (
        ("affinity", bench.synthetic_cluster(200), bench.affinity_apps(2000), "interpod"),
        ("ports", bench.synthetic_cluster(200), _ports_apps(2000), "ports"),
        ("gpu", bench.gpu_cluster(200), bench.gpu_apps(2000), "gpu"),
    )

    record = {
        "metric": "perf-smoke (2k-pod/200-node affinity+ports+gpu, incremental vs generic)",
    }
    try:
        for name, cluster, apps, klass in scenarios:
            # the affinity scenario predates the classes attribution split
            # and is gated on engagement + equality only
            required = klass if klass in ("ports", "gpu", "local", "score") else None
            scen, error = _run_scenario(name, cluster, apps, required, nativepath, prepare)
            record[name] = scen
            if error:
                record["error"] = error
                break
    finally:
        if prior_fg is not None:
            os.environ["OPENSIM_NATIVE_FORCE_GENERIC"] = prior_fg

    print(json.dumps(record))
    return 1 if "error" in record else 0


if __name__ == "__main__":
    sys.exit(main())
