#!/usr/bin/env python
"""Observability smoke gate (``make obs-smoke``, part of ``make verify``).

Starts the REST server in-process, drives one deploy-apps request with a
propagated ``X-Simon-Request-Id``, and asserts the whole observability
contract end to end (ISSUE 5 acceptance):

1. the response echoes the request id;
2. the flight recorder serves the request's trace — a span tree covering
   prepare→encode→schedule→decode with engine child spans — at
   ``/api/debug/requests`` and ``/api/debug/requests/<id>``;
3. ``/metrics`` renders ``simon_phase_seconds_bucket`` latency histograms
   (cumulative, ``+Inf``-terminated) for the served phases.

Exit 0 on success; 1 with a one-line reason per failed check.
"""

import json
import os
import sys
import threading
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUEST_ID = "obs-smoke-0001"


def fail(msg: str) -> int:
    print(f"obs-smoke: FAIL: {msg}")
    return 1


def main() -> int:
    from http.server import ThreadingHTTPServer

    from opensim_tpu.models import ResourceTypes, fixtures as fx
    from opensim_tpu.server.rest import SimonServer, make_handler

    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(
            fx.make_fake_node(
                f"n{i:02d}", "16", "64Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 2}"}),
            )
        )
    server = SimonServer(base_cluster=cluster)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"

    try:
        payload = json.dumps(
            {"deployments": [fx.make_fake_deployment("smoke", 6, "100m", "128Mi").raw]}
        ).encode()
        req = urllib.request.Request(
            f"{base}/api/deploy-apps",
            data=payload,
            method="POST",
            headers={"X-Simon-Request-Id": REQUEST_ID},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            if resp.status != 200:
                return fail(f"deploy-apps returned HTTP {resp.status}")
            echoed = resp.headers.get("X-Simon-Request-Id")
            body = json.load(resp)
        if echoed != REQUEST_ID:
            return fail(f"request id not echoed (got {echoed!r})")
        if not body.get("nodeStatus"):
            return fail("deploy-apps scheduled nothing")

        with urllib.request.urlopen(f"{base}/api/debug/requests", timeout=30) as resp:
            summaries = json.load(resp)["requests"]
        if not any(s["request_id"] == REQUEST_ID for s in summaries):
            return fail("flight recorder summary list is missing the request")

        with urllib.request.urlopen(
            f"{base}/api/debug/requests/{REQUEST_ID}", timeout=30
        ) as resp:
            tree = json.load(resp)
        if tree["status"] != "ok" or tree["endpoint"] != "deploy-apps":
            return fail(f"unexpected trace summary: {tree['status']}/{tree['endpoint']}")

        names = set()

        def walk(node):
            names.add(node["name"])
            for c in node.get("children", ()):
                walk(c)

        walk(tree["spans"])
        needed = {"prepare", "encode", "schedule", "decode"}
        if not needed <= names:
            return fail(f"span tree missing phases {sorted(needed - names)} (got {sorted(names)})")
        # an engine-LADDER rung span specifically — engine.device_put (a
        # child of encode) must not satisfy the attribution check
        rungs = {"engine.megakernel", "engine.native", "engine.xla"}
        if not rungs & names:
            return fail(f"span tree has no engine-ladder rung span (got {sorted(names)})")

        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            metrics = resp.read().decode()
        for needle in (
            "# TYPE simon_phase_seconds histogram",
            'simon_phase_seconds_bucket{phase="schedule",endpoint="deploy-apps",le="+Inf"} ',
            'simon_request_seconds_bucket{endpoint="deploy-apps",status="ok",le="+Inf"} ',
            "simon_phase_seconds_count",
        ):
            if needle not in metrics:
                return fail(f"/metrics missing {needle!r}")

        print(
            "obs-smoke: ok — request id echoed, flight-recorder span tree "
            f"({len(names)} distinct spans), phase histograms rendered"
        )
        return 0
    finally:
        httpd.shutdown()


if __name__ == "__main__":
    sys.exit(main())
