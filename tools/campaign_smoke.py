#!/usr/bin/env python
"""Campaign-engine smoke gate (``make campaign-smoke``, part of
``make verify``) — the ISSUE 13 acceptance, end to end in one process:

1. start the canned stub apiserver and a watch-mode REST server against it
   (live twin + capacity engine + a real PodDisruptionBudget);
2. POST a 3-step campaign (PDB-aware drain wave + reclaim storm +
   scale-down check) to ``/api/campaign`` and assert it runs against the
   twin with EXACTLY ONE full prepare (the campaign's own; the event
   stream and scoring stay O(changes)/host-side);
3. assert the capacity scores move across steps (nodes drop, utilization
   rises), the PDB ledger charged the drain's evictions, and the
   scale-down verdicts carry PDB blocking;
4. assert report text/JSON parity: the response's ``table`` section is
   byte-equal to the shared ``planner/report.campaign_step_rows`` builder
   re-run over the serialized steps;
5. run ``bench.py --config campaign`` at a small size and assert the row
   parses with ``verified_vs_cold`` true (the warm-delta vs cold-prepare
   fingerprint gate, computed in-row).

Exit 0 on success; 1 with a one-line reason per failed check.
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> int:
    print(f"campaign-smoke: FAIL: {msg}")
    return 1


def _pod(name, node="", cpu="1", mem="2Gi", labels=None):
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default", "labels": labels or {}},
        "spec": {
            "containers": [
                {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": mem}}}
            ]
        },
        "status": {"phase": "Running"},
    }
    if node:
        d["spec"]["nodeName"] = node
    return d


def main() -> int:
    from http.server import ThreadingHTTPServer

    from opensim_tpu.models import fixtures as fx
    from opensim_tpu.planner import report as report_mod
    from opensim_tpu.server import rest
    from opensim_tpu.server.stubapi import StubApiServer
    from opensim_tpu.server.watch import RestWatchSource, WatchSupervisor
    from opensim_tpu.utils.trace import PREP_STATS

    stub = StubApiServer(bookmark_interval_s=0.1).start()
    stub.seed(
        "/api/v1/nodes",
        [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(4)],
    )
    # web pods guarded by a PDB (minAvailable 2 of 3 -> one disruption at a
    # time), plus unguarded fillers
    stub.seed(
        "/api/v1/pods",
        [
            _pod("web-0", node="n0", labels={"app": "web"}),
            _pod("web-1", node="n0", labels={"app": "web"}),
            _pod("web-2", node="n1", labels={"app": "web"}),
            _pod("fill-0", node="n1", cpu="500m"),
            _pod("fill-1", node="n2", cpu="500m"),
        ],
    )
    stub.seed(
        "/apis/policy/v1/poddisruptionbudgets",
        [
            {
                "apiVersion": "policy/v1",
                "kind": "PodDisruptionBudget",
                "metadata": {"name": "web-pdb", "namespace": "default"},
                "spec": {"minAvailable": 2, "selector": {"matchLabels": {"app": "web"}}},
            }
        ],
    )
    for p in (
        "/apis/apps/v1/daemonsets", "/api/v1/services",
        "/apis/storage.k8s.io/v1/storageclasses",
        "/api/v1/persistentvolumeclaims", "/api/v1/configmaps",
    ):
        stub.seed(p, [])
    tmp = tempfile.mkdtemp(prefix="campaign-smoke-")
    kc = stub.kubeconfig(tmp)

    policy = {"stale_s": 5.0, "resync_s": 0.0, "reconnects": 3, "backoff_s": 0.02}
    sup = WatchSupervisor(RestWatchSource(kc, read_timeout_s=5.0), policy=policy)
    server = rest.SimonServer(kubeconfig=kc, watch=sup)
    sup.prep_cache = server.prep_cache
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), rest.make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    def post(path, body):
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            return json.load(resp)

    try:
        if not sup.start(wait_s=15.0):
            return fail("twin did not sync against the stub apiserver")
        # settle the serving-path bootstrap prepares before accounting
        with urllib.request.urlopen(f"{base}/api/cluster/report", timeout=120) as resp:
            json.load(resp)

        full0 = PREP_STATS.counts.get("full", 0)
        t0 = time.monotonic()
        result = post(
            "/api/campaign",
            {
                "name": "smoke",
                "steps": [
                    {"name": "upgrade-n0", "type": "drain-wave", "nodes": ["n0"], "wave": 1},
                    {"name": "spot", "type": "reclaim-storm", "nodes": ["n2"]},
                    {"name": "shrink?", "type": "scale-down-check"},
                ],
            },
        )
        wall = time.monotonic() - t0
        full_delta = PREP_STATS.counts.get("full", 0) - full0

        # --- exactly one full prepare for the whole campaign ---------------
        if full_delta != 1:
            return fail(f"campaign paid {full_delta} full prepares (contract: exactly 1)")
        if result.get("fullPrepares") != 1:
            return fail(f"result reports fullPrepares={result.get('fullPrepares')} != 1")

        steps = result.get("steps") or []
        if len(steps) != 4:  # baseline + 3 spec steps
            return fail(f"expected 4 scored steps, got {len(steps)}")

        # --- capacity gauges move across steps ------------------------------
        caps = [s.get("capacity") or {} for s in steps]
        if caps[0].get("nodes") != 4 or caps[-1].get("nodes") != 2:
            return fail(
                f"node trajectory wrong: {[c.get('nodes') for c in caps]} "
                "(expected 4 -> ... -> 2 after drain + storm)"
            )
        u0 = (caps[0].get("utilization") or {}).get("cpu", 0.0)
        u2 = (caps[2].get("utilization") or {}).get("cpu", 0.0)
        if not u2 > u0:
            return fail(f"cpu utilization did not rise across the drain+storm ({u0} -> {u2})")
        if any("fragmentation" not in c or "spread" not in c for c in caps):
            return fail("per-step capacity samples missing fragmentation/spread scores")
        if any(not s.get("headroomFit") for s in steps):
            return fail("per-step headroom scores missing")

        # --- PDB ledger charged the drain -----------------------------------
        drain = steps[1]
        if drain.get("pdbSpent", {}).get("default/web-pdb", 0) < 1:
            return fail(f"drain wave consumed no PDB budget: {drain.get('pdbSpent')}")
        if drain.get("evicted", 0) < 2:
            return fail(f"drain wave evicted {drain.get('evicted')} pods (expected >= 2)")
        if drain.get("blocked"):
            return fail(f"drain left blocked evictions unexpectedly: {drain['blocked']}")
        checks = steps[3].get("checks") or []
        if not checks:
            return fail("scale-down-check produced no verdicts")
        if not any(c.get("pdbBlocked") for c in checks) and not all(
            c.get("removable") is not None for c in checks
        ):
            return fail(f"scale-down verdicts malformed: {checks}")

        # --- text/JSON parity ------------------------------------------------
        rows = report_mod.campaign_step_rows(steps)
        table = result.get("table") or {}
        if [table.get("header")] + list(table.get("rows") or []) != rows:
            return fail("response table is not byte-equal to campaign_step_rows over the steps")

        # --- bench row -------------------------------------------------------
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        bench = subprocess.run(
            [sys.executable, "bench.py", "--config", "campaign",
             "--pods", "300", "--nodes", "24", "--no-warmup"],
            capture_output=True, text=True, timeout=560, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        if bench.returncode != 0:
            return fail(f"bench.py --config campaign failed: {bench.stderr[-500:]}")
        row = json.loads(bench.stdout.strip().splitlines()[-1])
        for key in ("steps_per_s", "rescheduled_per_s", "full_prepares", "fingerprint"):
            if key not in row:
                return fail(f"bench campaign row missing {key!r}: {row}")
        if row.get("full_prepares") != 1:
            return fail(f"bench campaign paid {row.get('full_prepares')} full prepares")
        if row.get("verified_vs_cold") is not True:
            return fail("bench campaign row did not verify warm-delta vs cold fingerprints")

        print(
            "campaign-smoke: ok — 3-step campaign on the live twin in "
            f"{wall:.2f}s with exactly 1 full prepare, nodes "
            f"{[c.get('nodes') for c in caps]}, cpu util {u0:.3f} -> {u2:.3f}, "
            f"PDB spend {drain.get('pdbSpent')}, {len(checks)} scale-down "
            f"verdict(s), table parity ok, bench row "
            f"{row['steps_per_s']} steps/s verified vs cold"
        )
        return 0
    finally:
        sup.stop()
        httpd.shutdown()
        stub.stop()


if __name__ == "__main__":
    sys.exit(main())
