# Container parity with the reference's Dockerfile (build + test in-image).
# Base image must provide jax for the target accelerator; for CPU-only use:
FROM python:3.12-slim

WORKDIR /opensim-tpu
COPY . .
RUN pip install --no-cache-dir setuptools jax numpy PyYAML pytest \
    && pip install --no-build-isolation --no-deps -e . \
    && python -m pytest tests/ -q

ENTRYPOINT ["simon"]
CMD ["--help"]
