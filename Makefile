# parity with the reference's Makefile targets (build/test), TPU edition
.PHONY: test test-quick test-slow tpu-revalidate bench bench-all bench-serial docs native all lint mypy verify chaos perf-smoke obs-smoke twin-smoke explain-smoke loadgen-smoke capacity-smoke replay-smoke tsan mem-smoke perf-guard campaign-smoke ha-smoke dash-smoke

all: test

test:
	python -m pytest tests/ -q

# opensim-lint: repo-specific static analyzer (docs/static-analysis.md) —
# 27 rules incl. the interprocedural dataflow pack (OSL16xx) and the
# array-contract engine (OSL18xx), result-cached
# by content hash (.lint/cache.json: unchanged files skip their rules), a
# SARIF artifact at a stable path for CI upload, and the detector-awake
# corpus gate (every rule must fire on its fixture, stay quiet on the
# clean twin). `simon lint` is the same engine without make.
lint:
	python -m opensim_tpu.analysis opensim_tpu --cache .lint/cache.json --sarif-out .lint/opensim-lint.sarif --corpus tests/lint_corpus

# strict on the typed core (engine/prepcache, encoding/state, models/quantity);
# skipped with a notice when mypy is not in the image — the CI gate still
# runs the AST signature check below, which needs only the stdlib
mypy:
	@if python -c "import mypy" 2>/dev/null; then \
		python -m mypy opensim_tpu; \
	else \
		echo "mypy not installed: falling back to stdlib signature check"; \
		python -m opensim_tpu.analysis --check-typed-core; \
	fi

# fault-injection suite (docs/resilience.md): every OPENSIM_FAULTS point
# must either recover (retry/fallback, placements identical to an
# uninjected run) or fail closed with a typed error and intact /metrics.
# test_watch.py drives the live twin's watch faults (disconnect/410/lost
# event) against the canned stub apiserver mid-stream (docs/live-twin.md)
chaos:
	python -m pytest tests/test_chaos.py tests/test_resilience.py tests/test_watch.py tests/test_journal.py tests/test_ha.py -q

# perf gate (ISSUE 4, widened by ISSUE 19): small affinity/ports/gpu
# workloads must engage the C++ engine's incremental cache (with per-carry-
# class attribution) AND match the forced-generic path bit-for-bit
perf-smoke:
	python tools/perf_smoke.py

# observability gate (ISSUE 5, docs/observability.md): a live server must
# echo X-Simon-Request-Id, serve the request's span tree from the flight
# recorder, and render phase latency histograms at /metrics
obs-smoke:
	python tools/obs_smoke.py

# live-twin gate (ISSUE 6, docs/live-twin.md): stub apiserver + watch-mode
# server + injected disconnect/410/lost-event storm; the twin must
# reconverge with placements shape-equal to a fresh full relist, drift
# detected, and events carried by delta re-encodes (no full prepare)
twin-smoke:
	python tools/twin_smoke.py

# decision-audit gate (ISSUE 7, docs/observability.md): `simon explain` on
# an unschedulable pod must render a kube-style "0/N nodes are available"
# breakdown whose per-filter counts are identical between the XLA and C++
# generic engines, and the deep per-pod score breakdown must sum to the
# winner's total
explain-smoke:
	python tools/explain_smoke.py

# serving-core gate (ISSUE 8, docs/serving.md): closed-loop loadgen against
# two live stub-backed servers — the admission-queue server must sustain
# more QPS than the single-flight baseline with a non-empty batch-size
# histogram and bounded p99 (the full ≥4x number: bench.py --config serving)
loadgen-smoke:
	python tools/loadgen_smoke.py

# capacity-observatory gate (ISSUE 9, docs/observability.md): an event
# storm against the stub apiserver must move the utilization/headroom
# gauges with full-prepare count == bootstrap only (O(changes) refresh),
# headroom bit-consistent with a fresh simulate probe, and the per-node
# /metrics series capped at OPENSIM_CAPACITY_TOPK
capacity-smoke:
	python tools/capacity_smoke.py

# durability gate (ISSUE 11, docs/live-twin.md "Durability & replay"):
# record a stub storm into a journal, crash with a torn tail, recover —
# fingerprint bit-equal to a fresh relist with ZERO relists and exactly the
# restored lineage's one full prepare — then `simon replay --speed 10` and
# `bench.py --config replay` must reproduce the final twin fingerprint
replay-smoke:
	python tools/replay_smoke.py

# memory-observatory gate (ISSUE 12, docs/observability.md "Memory &
# profiles"): a request storm + twin-delta churn must move the simon_mem_*
# gauges, prep-cache totals must reconcile exactly with the per-entry
# arena attributions, delta lineage/drop density must be visible, and the
# whole scrape must stay exposition-conformant with zero duplicate series
mem-smoke:
	python tools/mem_smoke.py

# perf-regression sentinel (ISSUE 12, BENCH.md "Guarding the trajectory"):
# every committed BENCH_BASELINE.json row must pass its own tolerances AND
# a synthetically slowed copy must fail (detector-awake proof). Run in
# tolerance-only mode under verify so wall-clock on a slow CI box cannot
# flake the build while exact metrics (placement counts, error counts)
# still gate. Fresh-row runs: tools/perf_guard.py --fresh --baseline KEY
perf-guard:
	python tools/perf_guard.py --tolerance-only

# campaign-engine gate (ISSUE 13, docs/campaigns.md): a 3-step lifecycle
# campaign (PDB-aware drain wave + reclaim storm + scale-down check) POSTed
# to /api/campaign on the stub-apiserver twin must run with EXACTLY ONE
# full prepare, move the capacity scores, charge the PDB ledger, keep
# text/JSON table parity, and a small `bench.py --config campaign` row must
# parse with its in-row warm-vs-cold fingerprint gate green
campaign-smoke:
	python tools/campaign_smoke.py

# HA control-plane gate (ISSUE 18, docs/serving.md#surviving-owner-loss):
# loadgen driven straight through an owner SIGKILL — the tailing standby
# takes the fenced lease and adopts the surviving workers with ZERO client
# errors, bit-identical placements, exactly one takeover, and no orphaned
# /dev/shm segment after teardown
ha-smoke:
	python tools/ha_smoke.py

# fleet-observability gate (ISSUE 20, docs/observability.md "Watching
# the fleet"): a live 2-worker fleet under load must serve a non-empty
# time-series ring and a conformant SLO endpoint, render byte-stable
# `simon dash --once --json` rows, expose zero duplicate series at the
# aggregated admin /metrics, stitch the owner's publication span into
# worker request traces, and lose no measurable QPS with OPENSIM_TRACE=0
dash-smoke:
	python tools/dash_smoke.py

# runtime lock-order sanitizer (docs/static-analysis.md#make-tsan): a
# seeded A->B/B->A inversion must be caught (detector self-test), then the
# threaded test modules run under instrumented locks — any observed
# lock-order inversion or non-exempt >OPENSIM_LOCKWATCH_HOLD_MS hold fails;
# skips gracefully when the threaded tests are excluded from the build
tsan:
	python tools/tsan.py

# the CI gate: static analysis + types + tier-1 tests + chaos + perf + obs + twin + explain + loadgen + capacity + replay + lock sanitizer + memory + perf trajectory + campaigns + HA failover + fleet observability
verify: lint mypy test-quick chaos perf-smoke obs-smoke twin-smoke explain-smoke loadgen-smoke capacity-smoke replay-smoke tsan mem-smoke perf-guard campaign-smoke ha-smoke dash-smoke

# run the moment the TPU tunnel opens (tools/tpu_probe_loop.sh writes
# /tmp/opensim-tpu-watch.up): compiled-Mosaic parity suite + full bench
# sweep + scenarios/s/chip, logged to TPU_REVALIDATION.log
tpu-revalidate:
	sh tools/tpu_revalidate.sh

# inner-loop tier (<90 s): skips the nightly oracle/fuzz/multihost/parity
# matrix suites — run `make test` (both tiers) before shipping
test-quick:
	python -m pytest tests/ -q -m "not slow"

test-slow:
	python -m pytest tests/ -q -m slow

bench:
	python bench.py

bench-all: bench
	python bench.py --config example
	python bench.py --config gpushare
	python bench.py --pods 10000 --nodes 1000
	python bench.py --config affinity --pods 5000 --nodes 500
	python bench.py --config affinity
	python bench.py --config defrag --scenarios 64 --nodes 200 --pods 2000
	python bench.py --config bigu --pods 50000 --nodes 5000
	python bench.py --config forced --pods 50000 --nodes 5000

# measured serial floor on the 5 BASELINE configs (hours at full scale;
# see tools/serial_baseline.py --help for per-config runs)
bench-serial:
	python tools/serial_baseline.py --config all

docs:
	python -m opensim_tpu gen-doc --output-dir docs/commandline
	python -m opensim_tpu.utils.envknobs > docs/env.md

native:
	python -c "from opensim_tpu import native; p = native.ensure_built(); print(p or native.load_error())"
