"""Randomized differential testing: megakernel vs XLA scan on generated
workloads mixing every supported feature. Any placement mismatch is a bug in
one of the two pipelines (they implement the same semantics twice)."""

import os
import random

import numpy as np
import pytest

from opensim_tpu.engine import fastpath
from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
from opensim_tpu.engine.simulator import AppResource, prepare
from opensim_tpu.models import ResourceTypes, fixtures as fx

pytestmark = pytest.mark.slow  # nightly tier (README: test tiering)

_INTERPRET = os.environ.get("OPENSIM_TEST_BACKEND") != "tpu"


@pytest.fixture(autouse=True)
def _enable_interpret_fastpath(monkeypatch):
    monkeypatch.setenv("OPENSIM_FASTPATH", "interpret")


def random_cluster(rng: random.Random, n_nodes: int) -> ResourceTypes:
    import json

    rt = ResourceTypes()
    for i in range(n_nodes):
        opts = []
        labels = {}
        if rng.random() < 0.8:
            labels["topology.kubernetes.io/zone"] = f"z{rng.randrange(3)}"
        if rng.random() < 0.5:
            labels["topology.kubernetes.io/region"] = f"r{rng.randrange(2)}"
        if rng.random() < 0.25:
            labels["topology.rack"] = f"k{rng.randrange(4)}"
        if rng.random() < 0.15:
            labels["topology.row"] = f"w{rng.randrange(2)}"
        if rng.random() < 0.5:
            labels["disk"] = rng.choice(["ssd", "hdd"])
        opts.append(fx.with_labels(labels))
        if rng.random() < 0.15:
            # NodePreferAvoidPods: repel one of the fuzz RS controllers
            opts.append(
                fx.with_annotations(
                    {
                        "scheduler.alpha.kubernetes.io/preferAvoidPods": json.dumps(
                            {"preferAvoidPods": [
                                {"podSignature": {"podController": {
                                    "kind": "ReplicaSet",
                                    "uid": f"rs-fuzz-{rng.randrange(3)}",
                                }}}
                            ]}
                        )
                    }
                )
            )
        if rng.random() < 0.25:
            effect = rng.choice(["NoSchedule", "PreferNoSchedule"])
            opts.append(fx.with_taints([{"key": "dedicated", "value": "x", "effect": effect}]))
        if rng.random() < 0.3:
            opts.append(
                fx.with_allocatable(
                    {"alibabacloud.com/gpu-mem": "16Gi", "alibabacloud.com/gpu-count": "2"}
                )
            )
        if rng.random() < 0.25:
            opts.append(
                fx.with_node_local_storage(
                    vgs=[{"name": "pool0", "capacity": rng.choice([50, 100]) * 1024**3}],
                    devices=[
                        {
                            "device": "/dev/vdb",
                            "capacity": 100 * 1024**3,
                            "mediaType": rng.choice(["ssd", "hdd"]),
                        }
                    ],
                )
            )
        rt.nodes.append(
            fx.make_fake_node(f"n{i:03d}", str(rng.choice([8, 16, 32])), "64Gi", "110", *opts)
        )
    return rt


def random_app(rng: random.Random, n_workloads: int) -> ResourceTypes:
    rt = ResourceTypes()
    for w in range(n_workloads):
        opts = []
        if rng.random() < 0.3:
            opts.append(fx.with_node_selector({"disk": rng.choice(["ssd", "hdd"])}))
        if rng.random() < 0.3:
            opts.append(
                fx.with_tolerations(
                    [{"key": "dedicated", "operator": "Equal", "value": "x", "effect": "NoSchedule"}]
                )
            )
        if rng.random() < 0.3:
            opts.append(
                fx.with_topology_spread(
                    [
                        {
                            "maxSkew": rng.choice([1, 2, 5]),
                            "topologyKey": rng.choice(
                                ["kubernetes.io/hostname", "topology.kubernetes.io/zone",
                                 "topology.kubernetes.io/region", "topology.rack",
                                 "topology.row"]
                            ),
                            "whenUnsatisfiable": rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                            "labelSelector": {"matchLabels": {"app": f"w{w}"}},
                        }
                    ]
                )
            )
        if rng.random() < 0.25:
            kind = rng.choice(["podAffinity", "podAntiAffinity"])
            mode = rng.choice(["required", "preferred"])
            term = {
                "labelSelector": {"matchLabels": {"app": f"w{max(w - 1, 0)}"}},
                "topologyKey": rng.choice(
                    ["kubernetes.io/hostname", "topology.kubernetes.io/zone",
                     "topology.kubernetes.io/region", "topology.rack"]
                ),
            }
            if rng.random() < 0.3:
                term["namespaces"] = rng.sample(["ns-a", "ns-b", "default"], rng.randrange(1, 3))
            if mode == "required":
                aff = {kind: {"requiredDuringSchedulingIgnoredDuringExecution": [term]}}
            else:
                aff = {
                    kind: {
                        "preferredDuringSchedulingIgnoredDuringExecution": [
                            {"weight": rng.choice([10, 50, 100]), "podAffinityTerm": term}
                        ]
                    }
                }
            opts.append(fx.with_affinity(aff))
        if rng.random() < 0.2:
            opts.append(fx.with_host_ports([rng.choice([8080, 9090, 9443])]))
        if rng.random() < 0.15:
            # whole-GPU pods: gpu-count as a SPEC resource exercises the
            # dynamic allocatable (gpushare Reserve rewrite) fit/share path
            opts.append(fx.with_requests(
                {"alibabacloud.com/gpu-count": rng.choice(["1", "2"])}))
        if rng.random() < 0.4:
            opts.append(fx.with_namespace(rng.choice(["ns-a", "ns-b"])))
        deploy = fx.make_fake_deployment(
            f"w{w}",
            rng.randrange(2, 10),
            f"{rng.choice([100, 250, 500, 1000])}m",
            f"{rng.choice([128, 512, 1024])}Mi",
            *opts,
        )
        if rng.random() < 0.2:
            deploy.template_metadata.annotations.update(
                {"alibabacloud.com/gpu-mem": "2Gi", "alibabacloud.com/gpu-count": "1"}
            )
            deploy.template_raw.setdefault("metadata", {}).setdefault("annotations", {}).update(
                {"alibabacloud.com/gpu-mem": "2Gi", "alibabacloud.com/gpu-count": "1"}
            )
        rt.deployments.append(deploy)
    # occasionally: a stateful set with local storage + anti-affinity, and a
    # bare pre-bound pod (forced-bind path)
    if rng.random() < 0.4:
        sts = fx.make_fake_stateful_set(
            "db", rng.randrange(2, 5), "250m", "512Mi",
            fx.with_affinity(
                {
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"labelSelector": {"matchLabels": {"app": "db"}}, "topologyKey": "kubernetes.io/hostname"}
                        ]
                    }
                }
            ),
        )
        if rng.random() < 0.5:
            sts.volume_claim_templates = [
                {
                    "metadata": {"name": "data"},
                    "spec": {"storageClassName": "open-local-lvm", "resources": {"requests": {"storage": "10Gi"}}},
                }
            ]
        rt.stateful_sets.append(sts)
    if rng.random() < 0.3:
        rt.pods.append(fx.make_fake_pod("pinned", "100m", "128Mi", fx.with_node_name("n000")))
    if rng.random() < 0.3:
        # bare pods owned by the RS controllers the avoid annotations name
        from opensim_tpu.models.objects import OwnerReference

        rs = rng.randrange(3)
        for k in range(rng.randrange(1, 5)):
            p = fx.make_fake_pod(f"avoided-{rs}-{k}", "200m", "256Mi")
            p.metadata.owner_references = [
                OwnerReference(kind="ReplicaSet", name=f"rs-fuzz-{rs}",
                               uid=f"rs-fuzz-{rs}", controller=True)
            ]
            rt.pods.append(p)
    return rt


def _seeds():
    """Default CI seeds; OPENSIM_FUZZ_SEEDS=<n> widens the sweep (e.g. a
    nightly run with hundreds of seeds)."""
    import os

    extra = int(os.environ.get("OPENSIM_FUZZ_SEEDS", "0"))
    base = [1, 7, 23, 99]
    return base + list(range(1000, 1000 + extra))


@pytest.mark.parametrize("seed", _seeds())
def test_fuzz_fastpath_vs_xla(seed):
    rng = random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(8, 20))
    app = random_app(rng, rng.randrange(3, 8))
    # node_pad=8 leaves N off the 128-lane grid; build_inputs pads it
    prep = prepare(cluster, [AppResource("fuzz", app)], node_pad=rng.choice([8, 128]))
    if prep is None or not fastpath.applicable(prep):
        pytest.skip("generated workload outside fast-path bounds")
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    want = np.asarray(out.chosen)[:P]
    got, got_used, *_rest = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    mism = np.nonzero(want != got)[0]
    assert mism.size == 0, (
        f"seed={seed}: {mism.size}/{P} mismatches at {mism[:10]}; "
        f"xla={want[mism[:10]]} fast={got[mism[:10]]}"
    )
    np.testing.assert_allclose(got_used, np.asarray(out.final_state.used), rtol=1e-5)


@pytest.mark.parametrize("seed", [5, 42])
def test_fuzz_big_u_fastpath_vs_xla(seed):
    """Same differential check with the template space inflated past the
    VMEM-resident cap, forcing the kernel's big-U (HBM tables + per-step
    DMA) mode."""
    rng = random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(6, 12))
    app = random_app(rng, rng.randrange(2, 5))
    for i in range(520):
        app.pods.append(fx.make_fake_pod(f"u{i:04d}", f"{50 + i}m", f"{64 + (i % 7)}Mi"))
    prep = prepare(cluster, [AppResource("fuzz", app)], node_pad=128)
    if prep is None or not fastpath.applicable(prep):
        pytest.skip("generated workload outside fast-path bounds")
    assert int(prep.ec_np.req.shape[0]) > 512
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    want = np.asarray(out.chosen)[:P]
    # big_u forced: the heuristic keeps small-N resident, but the fuzz must
    # cover the HBM template-table DMA path
    got, got_used, *_rest = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET, big_u=True
    )
    mism = np.nonzero(want != got)[0]
    assert mism.size == 0, (
        f"seed={seed}: {mism.size}/{P} mismatches at {mism[:10]}; "
        f"xla={want[mism[:10]]} fast={got[mism[:10]]}"
    )
    np.testing.assert_allclose(got_used, np.asarray(out.final_state.used), rtol=1e-5)
