"""The detector-awake corpus gate (analysis/corpus.py): every registered
rule has a firing fixture and a paired clean variant, the gate passes on
the shipped corpus, and the gate itself catches asleep detectors, stale
fixtures, and precision regressions."""

import os
import textwrap

from opensim_tpu.analysis import RULES
from opensim_tpu.analysis.corpus import check_corpus, corpus_inventory, run_fixture

CORPUS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "lint_corpus")


def test_shipped_corpus_passes():
    assert check_corpus(CORPUS) == []


def test_every_registered_rule_has_fire_and_clean_fixtures():
    inv = corpus_inventory(CORPUS)
    for rule in RULES.values():
        entry = inv.get(rule.code, {})
        assert entry.get("fire"), f"{rule.code} has no fire fixture"
        assert entry.get("clean"), f"{rule.code} has no clean fixture"


def test_gate_catches_missing_fixture(tmp_path):
    problems = check_corpus(str(tmp_path))
    # an empty corpus dir: every rule reports both missing fixtures
    assert len(problems) == 2 * len(RULES)
    assert any("OSL101" in p and "no firing fixture" in p for p in problems)


def test_gate_catches_asleep_detector_and_stale_code(tmp_path):
    (tmp_path / "OSL501_fire.py").write_text("x = 1\n")  # does not fire
    (tmp_path / "OSL9999_fire.py").write_text("x = 1\n")  # no such rule
    problems = check_corpus(str(tmp_path))
    assert any("detector asleep" in p and "OSL501" in p for p in problems)
    assert any("OSL9999" in p and "no such rule" in p for p in problems)


def test_gate_catches_precision_regression(tmp_path):
    (tmp_path / "OSL501_clean.py").write_text(
        textwrap.dedent(
            """
            def swallow(risky):
                try:
                    risky()
                except Exception:
                    pass
            """
        )
    )
    problems = check_corpus(str(tmp_path))
    assert any("precision regression" in p for p in problems)


def test_run_fixture_honors_virtual_path():
    # OSL201 is scoped to encoding/: without the virtual-path header the
    # fixture would lint under tests/ and never fire
    codes, err = run_fixture(os.path.join(CORPUS, "OSL201_fire.py"), "OSL201")
    assert err is None and codes == ["OSL201"]
