"""Chaos suite (`make chaos`): deterministic fault injection through the
REST serving path. Acceptance bar (ISSUE 3): every injection point —
snapshot.http, prep.encode, engine.compile, engine.device_put, cache.stale —
either RECOVERS (retry/fallback, placements identical to an uninjected run)
or FAILS CLOSED with a typed JSON error (504/503/500 — never a hang, never a
raw traceback), with /metrics still served afterwards. Engine demotions are
visible in EngineDecision.skipped, breaker trips in /metrics, and
OPENSIM_REQUIRE_TPU=1 still fails hard with no silent demotion."""

import json
import threading
import urllib.error
import urllib.request
from contextlib import contextmanager

import pytest

from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.resilience import breaker as breaker_mod
from opensim_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_resilience_state(monkeypatch):
    monkeypatch.delenv("OPENSIM_FAULTS", raising=False)
    monkeypatch.setenv("OPENSIM_SNAPSHOT_BACKOFF_S", "0.001")
    faults.clear_faults()
    breaker_mod.reset_breakers()
    yield
    faults.clear_faults()
    breaker_mod.reset_breakers()


def _cluster(n_nodes=6):
    rt = ResourceTypes()
    for i in range(n_nodes):
        rt.nodes.append(
            fx.make_fake_node(
                f"n{i:03d}", "16", "64Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 3}"}),
            )
        )
    # a bound snapshot pod: the REST base-entry cache only engages when the
    # snapshot has schedulable pods (an empty prepare is never cached), and
    # the cache.stale chaos tests need the check_fresh path exercised
    rt.pods.append(fx.make_fake_pod("pinned", "100m", "128Mi", fx.with_node_name("n000")))
    return rt


def _payload():
    return {"deployments": [fx.make_fake_deployment("web", 6, "500m", "1Gi").raw]}


def _apps():
    rt = ResourceTypes()
    rt.deployments.append(fx.make_fake_deployment("web", 6, "500m", "1Gi"))
    return [AppResource("web", rt)]


def _shape(resp):
    """Comparable placement shape of a REST response: pod names embed a
    process-global expansion counter, so recovery equality is asserted on
    (node, pod count) plus unscheduled reasons — the same shape the
    prepcache parity tests use."""
    return (
        sorted((e["node"], len(e["pods"])) for e in resp["nodeStatus"]),
        sorted(u["reason"] for u in resp["unscheduledPods"]),
    )


def _result_shape(res):
    return (
        sorted((ns.node.metadata.name, len(ns.pods)) for ns in res.node_status),
        sorted(u.reason for u in res.unscheduled_pods),
    )


@contextmanager
def _serve(server):
    from http.server import ThreadingHTTPServer

    from opensim_tpu.server.rest import make_handler

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield httpd.server_address[1]
    finally:
        httpd.shutdown()


def _metrics_ok(server) -> str:
    """/metrics must render after every fault class (acceptance bar)."""
    from opensim_tpu.server.rest import METRICS

    text = METRICS.render(prep_cache=server.prep_cache)
    assert "simon_requests_total" in text
    return text


def _baseline_response(kind="deploy"):
    """The uninjected answer for (_cluster(), _payload()) — recovery tests
    assert byte-identical placements against this."""
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster())
    code, body = (server.deploy_apps if kind == "deploy" else server.scale_apps)(_payload())
    assert code == 200
    return body


# ---------------------------------------------------------------------------
# snapshot.http — retry, stale-serve degradation, fail-closed 503
# ---------------------------------------------------------------------------


def _kubeconfig_server(monkeypatch, ttl=3600.0):
    from opensim_tpu.server import rest

    fetches = []

    def fake_fetch(kubeconfig, master=None):
        fetches.append(kubeconfig)
        return _cluster()

    monkeypatch.setattr(rest, "cluster_from_kubeconfig", fake_fetch)
    return rest.SimonServer(kubeconfig="/tmp/kc", snapshot_ttl_s=ttl), fetches


def test_snapshot_http_transient_fault_recovers_via_retry(monkeypatch):
    from opensim_tpu.server.rest import METRICS

    server, fetches = _kubeconfig_server(monkeypatch)
    retries0 = METRICS.snapshot_retries
    # 2 injected failures, 3 attempts (OPENSIM_SNAPSHOT_RETRIES default):
    # the third attempt lands and the request must not notice
    faults.inject("snapshot.http", count=2, exc="fetch")
    code, body = server.deploy_apps(_payload())
    assert code == 200, body
    assert _shape(body) == _shape(_baseline_response())
    assert faults.fault_stats()["snapshot.http"] == 2
    assert METRICS.snapshot_retries - retries0 == 2
    assert not server.snapshot_stale
    _metrics_ok(server)


def test_snapshot_down_serves_stale_with_header(monkeypatch):
    from opensim_tpu.server.rest import METRICS

    server, fetches = _kubeconfig_server(monkeypatch)
    stale0 = METRICS.snapshot_stale_served
    with _serve(server) as port:
        body = json.dumps(_payload()).encode()

        def post():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST"
            )
            return urllib.request.urlopen(req)

        with post() as r:
            fresh = json.load(r)
        # apiserver goes down past the TTL: every retry fails, the last
        # good snapshot serves the request, tagged stale
        server._snapshot_at -= 7200.0
        faults.inject("snapshot.http", count=99, exc="fetch")
        with post() as r:
            assert r.headers.get("X-Simon-Snapshot") == "stale"
            degraded = json.load(r)
    assert _shape(degraded) == _shape(fresh)
    assert server.snapshot_stale
    assert METRICS.snapshot_stale_served - stale0 == 1
    assert len(fetches) == 1  # the down apiserver was probed once per TTL
    text = _metrics_ok(server)
    assert "simon_snapshot_stale_served_total" in text


def test_snapshot_down_cold_fails_closed_503(monkeypatch):
    server, _ = _kubeconfig_server(monkeypatch)
    faults.inject("snapshot.http", count=99, exc="fetch")
    code, body = server.deploy_apps(_payload())
    assert code == 503
    assert body["retryable"] is True
    assert "snapshot unavailable" in body["error"]
    assert faults.fault_stats()["snapshot.http"] == 3  # bounded attempts
    _metrics_ok(server)


# ---------------------------------------------------------------------------
# prep.encode / engine.device_put — fail closed typed, then recover
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("point", ["prep.encode", "engine.device_put"])
def test_prepare_stage_fault_fails_closed_then_recovers(point):
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster())
    faults.inject(point, count=1, exc="runtime")
    code, body = server.deploy_apps(_payload())
    assert code == 500
    assert f"injected fault at {point}" in body["error"]
    assert body["type"] == "RuntimeError"
    _metrics_ok(server)
    # the fault burned out: the very next request recovers fully
    code, body = server.deploy_apps(_payload())
    assert code == 200
    assert _shape(body) == _shape(_baseline_response())


# ---------------------------------------------------------------------------
# engine.compile — fallback ladder demotion + circuit breaker
# ---------------------------------------------------------------------------


def _require_native():
    from opensim_tpu import native

    if not native.available():
        pytest.skip("C++ native engine not built on this host")


def test_engine_compile_fault_demotes_to_xla_with_identical_placements():
    _require_native()
    cluster, apps = _cluster(), _apps()
    res0 = simulate(cluster, apps)
    assert res0.engine.name == "native"

    faults.inject("engine.compile", count=1, exc="runtime")
    res1 = simulate(_cluster(), _apps())
    # demoted one rung, visibly, with identical placements
    assert res1.engine.name == "xla"
    assert "injected fault at engine.compile" in res1.engine.skipped["native"]

    assert _result_shape(res1) == _result_shape(res0)
    # one failure does not open the breaker (threshold 3): next run is native
    br = breaker_mod.engine_breaker("native")
    assert br.failures_total == 1 and br.state() == "closed"
    assert simulate(_cluster(), _apps()).engine.name == "native"


def test_breaker_trips_after_threshold_then_half_open_recovers(monkeypatch):
    _require_native()
    from opensim_tpu.server.rest import METRICS

    monkeypatch.setenv("OPENSIM_BREAKER_THRESHOLD", "2")
    breaker_mod.reset_breakers()

    faults.inject("engine.compile", count=2, exc="runtime")
    for _ in range(2):
        assert simulate(_cluster(), _apps()).engine.name == "xla"
    br = breaker_mod.engine_breaker("native")
    assert br.state() == "open" and br.trips_total == 1

    # breaker open: the native attempt is skipped outright (no fault armed,
    # yet the engine still demotes — the skip reason says breaker)
    res = simulate(_cluster(), _apps())
    assert res.engine.name == "xla"
    assert "circuit breaker open" in res.engine.skipped["native"]

    # the trip is visible at /metrics
    text = METRICS.render()
    assert 'simon_engine_breaker_trips_total{engine="native"} 1' in text
    assert 'simon_engine_breaker_open{engine="native"} 1' in text

    # cooldown elapses → half-open probe runs the real engine and closes
    br.clock = lambda: br._opened_at + br.cooldown_s + 1.0
    res = simulate(_cluster(), _apps())
    assert res.engine.name == "native"
    assert br.state() == "closed"


def test_require_tpu_fails_hard_never_demotes(monkeypatch):
    """OPENSIM_REQUIRE_TPU=1: an injected megakernel compile failure must
    raise, not demote — even with healthy fallback engines below."""
    import jax

    from opensim_tpu.engine import fastpath

    monkeypatch.setenv("OPENSIM_REQUIRE_TPU", "1")
    monkeypatch.delenv("OPENSIM_FASTPATH", raising=False)
    monkeypatch.setattr(fastpath, "why_not", lambda prep, config=None: None)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    faults.inject("engine.compile", count=1, exc="runtime")
    with pytest.raises(RuntimeError, match="refusing to silently fall back"):
        simulate(_cluster(), _apps())
    assert faults.fault_stats()["engine.compile"] == 1  # the kernel WAS tried


# ---------------------------------------------------------------------------
# cache.stale — transparent single retry, fail closed on repeat
# ---------------------------------------------------------------------------


def test_cache_stale_fault_recovers_transparently():
    from opensim_tpu.server.rest import METRICS, SimonServer

    server = SimonServer(base_cluster=_cluster())
    retries0 = METRICS.stale_prep_retries
    code, first = server.deploy_apps(_payload())
    assert code == 200

    # one stale hit: check_fresh evicts, the internal retry re-prepares
    faults.inject("cache.stale", count=1, exc="stale")
    code, body = server.deploy_apps(_payload())
    assert code == 200
    assert _shape(body) == _shape(first)
    assert METRICS.stale_prep_retries - retries0 == 1
    _metrics_ok(server)


def test_cache_stale_repeat_fails_closed_then_recovers():
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster())
    code, first = server.deploy_apps(_payload())
    assert code == 200

    # stale on the original attempt AND on the internal retry: typed 500,
    # never a loop
    faults.inject("cache.stale", count=2, exc="stale")
    code, body = server.deploy_apps(_payload())
    assert code == 500
    assert body["type"] == "StaleFingerprintError"
    assert "injected fault at cache.stale" in body["error"]
    _metrics_ok(server)

    code, body = server.deploy_apps(_payload())
    assert code == 200 and _shape(body) == _shape(first)


# ---------------------------------------------------------------------------
# request deadlines — typed 504, server stays healthy
# ---------------------------------------------------------------------------


def test_deadline_exhaustion_returns_504_with_phase():
    from opensim_tpu.server.rest import METRICS, SimonServer

    server = SimonServer(base_cluster=_cluster())
    timeouts0 = METRICS.request_timeouts
    with _serve(server) as port:
        body = json.dumps(_payload()).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST",
            headers={"X-Simon-Timeout-S": "0.000001"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 504
        resp = json.load(ei.value)
        assert resp["phase"] in ("snapshot", "prepare", "encode", "schedule", "decode")
        assert "deadline exceeded" in resp["error"]

        # the timed-out request left the server fully healthy
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST"
        )
        with urllib.request.urlopen(req2) as r:
            assert _shape(json.load(r)) == _shape(_baseline_response())
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
            text = r.read().decode()
    assert METRICS.request_timeouts - timeouts0 == 1
    assert "simon_request_timeouts_total" in text


def test_env_default_deadline_applies_without_header(monkeypatch):
    from opensim_tpu.server.rest import SimonServer, request_deadline

    monkeypatch.setenv("OPENSIM_REQUEST_TIMEOUT_S", "0.000001")
    dl = request_deadline({})
    assert dl is not None and dl.budget_s == pytest.approx(1e-6)
    server = SimonServer(base_cluster=_cluster())
    code, body = server.deploy_apps(_payload(), deadline=dl)
    assert code == 504 and "phase" in body
    # unset/0 disables
    monkeypatch.setenv("OPENSIM_REQUEST_TIMEOUT_S", "0")
    assert request_deadline({}) is None
