"""OSL1804 three-way-sync regression matrix (detector-awake for the
contract-ABI parity pass): copies of the REAL registry + native sources
are mutated one axis at a time — contract width, policy constant value,
both native sides at once, dropped/stale registry entries — and the rule
must fire naming the exact field; the unmutated copies must stay green.

The both-native-sides mutation is the axis OSL1604 is blind to by
construction (the ctypes mirror and the C++ struct still agree with each
other); this matrix proves OSL1804 covers it."""

import os
import shutil

from opensim_tpu.analysis import lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "opensim_tpu")


def _stage(tmp_path, mutate=None):
    """Copy the real registry, arena structs and native sources into tmp
    preserving the path suffixes the rule locates them by."""
    root = os.path.join(str(tmp_path), "staged")
    os.makedirs(os.path.join(root, "encoding"))
    os.makedirs(os.path.join(root, "native"))
    for rel in ("encoding/dtypes.py", "encoding/state.py",
                "native/__init__.py", "native/scan_engine.cc"):
        shutil.copy(os.path.join(PKG, rel), os.path.join(root, rel))
    if mutate is not None:
        mutate(root)
    return root


def _findings(root, rules=("contract-abi-parity",)):
    return lint_paths([root], rules=list(rules))


def _edit(root, rel, old, new, count=1):
    path = os.path.join(root, rel)
    with open(path) as fh:
        src = fh.read()
    assert old in src, f"mutation anchor {old!r} missing from {rel}"
    with open(path, "w") as fh:
        fh.write(src.replace(old, new, count))


def test_real_sources_are_green(tmp_path):
    assert _findings(_stage(tmp_path)) == []


def test_contract_widened_against_native_fires_on_both_sides(tmp_path):
    # registry says i64, mirror and C++ still pack i32: one finding per
    # native side, each naming the field
    root = _stage(tmp_path)
    _edit(root, "encoding/dtypes.py",
          '"node_domain": ("INT_DTYPE", ("N", "Tk")),',
          '"node_domain": ("INT64_DTYPE", ("N", "Tk")),')
    findings = _findings(root)
    assert [f.code for f in findings] == ["OSL1804", "OSL1804"]
    for f in findings:
        assert "width drift" in f.message and "`node_domain`" in f.message


def test_policy_constant_narrowed_fires_for_every_contracted_field(tmp_path):
    # np.int32 -> np.int16 re-types EVERY INT_DTYPE contract at once; the
    # native sides still pack i32
    root = _stage(tmp_path)
    _edit(root, "encoding/dtypes.py", "INT_DTYPE = np.int32",
          "INT_DTYPE = np.int16")
    findings = _findings(root)
    assert findings and all(f.code == "OSL1804" for f in findings)
    assert len(findings) > 10  # every i32 buffer in the mirror + ScanArgs
    assert any("`node_domain`" in f.message for f in findings)


def test_both_native_sides_narrowed_fires_while_abi_parity_stays_green(tmp_path):
    # the OSL1604 blind spot: mirror AND C++ both flip to u8, consistent
    # with each other, while the contract stays INT_DTYPE (i32)
    root = _stage(tmp_path)
    _edit(root, "native/__init__.py", '("node_domain", _I32, "i32")',
          '("node_domain", _U8, "u8")')
    _edit(root, "native/scan_engine.cc", "const int32_t* node_domain;",
          "const uint8_t* node_domain;")
    assert _findings(root, rules=("abi-parity",)) == []  # 1604 cannot see it
    findings = _findings(root)
    assert [f.code for f in findings] == ["OSL1804", "OSL1804"]
    msgs = " | ".join(f.message for f in findings)
    assert "`node_domain`" in msgs and "u8" in msgs


def test_dropped_contract_entry_fires_naming_the_field(tmp_path):
    root = _stage(tmp_path)
    _edit(root, "encoding/dtypes.py",
          '    "node_domain": ("INT_DTYPE", ("N", "Tk")),\n', "")
    findings = _findings(root)
    assert findings and all(f.code == "OSL1804" for f in findings)
    assert any("`node_domain`" in f.message
               and "no ARENA_CONTRACTS entry" in f.message for f in findings)


def test_stale_contract_entry_fires(tmp_path):
    root = _stage(tmp_path)
    _edit(root, "encoding/dtypes.py",
          '    "node_domain": ("INT_DTYPE", ("N", "Tk")),',
          '    "node_domain": ("INT_DTYPE", ("N", "Tk")),\n'
          '    "ghost_field": ("INT_DTYPE", ("N",)),')
    findings = _findings(root)
    assert findings and all(f.code == "OSL1804" for f in findings)
    assert any("`ghost_field`" in f.message and "names no EncodedCluster"
               in f.message for f in findings)


def test_unresolvable_policy_name_fires(tmp_path):
    root = _stage(tmp_path)
    _edit(root, "encoding/dtypes.py",
          '"node_domain": ("INT_DTYPE", ("N", "Tk")),',
          '"node_domain": ("MYSTERY_DTYPE", ("N", "Tk")),')
    findings = _findings(root)
    assert findings and all(f.code == "OSL1804" for f in findings)
    assert any("MYSTERY_DTYPE" in f.message for f in findings)
