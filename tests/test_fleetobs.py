"""Fleet-wide observability (ISSUE 20, docs/observability.md "Watching
the fleet"): cross-process trace stitching, the event-to-servable
freshness pipeline, the bounded on-disk time-series ring, and the SLO
burn-rate engine.

The load-bearing gates:

- reader edge cases: merged histogram quantiles survive an empty worker,
  a +Inf-only tail, and a counter reset (the PromQL ``rate()`` rules);
- stitching: an owner-stamped watch event id rides the journal record
  and the shm publication, and lands on a worker request trace plus the
  grafted ``fleet.publication`` subtree — across a REAL publisher/client
  pair with two attached workers;
- freshness: every pipeline stage histogram moves under a twin storm;
- ring: the on-disk footprint stays bounded by construction and the
  delta encoding round-trips EXACTLY (equality, not tolerance);
- SLO: burn rates match hand-computed windows, short windows without
  data say ``no_data`` instead of lying with 0.0;
- takeover: the marker is visible in ``simon dash`` rows both from
  crafted samples (unit) and through a real owner SIGKILL (e2e, riding
  the HA harness from test_ha.py).
"""

import json
import math
import os
import time

import pytest

from opensim_tpu.engine import prepcache
from opensim_tpu.engine.simulator import prepare
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.obs.fleetobs import (
    FRESHNESS,
    PUB_EVENTS_MAX,
    new_event_id,
    publication_tree,
)
from opensim_tpu.obs.metrics import (
    RECORDER,
    bucket_deltas,
    counter_delta,
    histogram_quantile,
    parse_metrics,
)
from opensim_tpu.obs.recorder import FLIGHT_RECORDER
from opensim_tpu.obs.slo import Objective, SLOEngine, parse_objectives, parse_windows
from opensim_tpu.obs.timeseries import (
    TimeSeriesRing,
    parse_duration_s,
    render_series_key,
)
from opensim_tpu.server.fleet import FleetTwinClient, TwinPublisher


@pytest.fixture(autouse=True)
def _clean_obs_state():
    RECORDER.reset()
    FRESHNESS.reset()
    FLIGHT_RECORDER.clear()
    yield
    RECORDER.reset()
    FRESHNESS.reset()
    FLIGHT_RECORDER.clear()


def _cluster(n_nodes=6):
    rt = ResourceTypes()
    for i in range(n_nodes):
        rt.nodes.append(
            fx.make_fake_node(
                f"n{i:03d}", "16", "64Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 3}"}),
            )
        )
    rt.pods.append(fx.make_fake_pod("pinned", "100m", "128Mi", fx.with_node_name("n000")))
    return rt


def _publication_parts(cluster):
    base = prepcache.CacheEntry("t|base", prepare(cluster, []))
    with base.lock:
        base.restore()
        return prepcache.publication_parts(base)


# ---------------------------------------------------------------------------
# bucket-merge edge cases (ISSUE 20 satellite: the shared reader in
# obs/metrics.py that loadgen, dash and the SLO engine all consume)
# ---------------------------------------------------------------------------

_LADDER = """\
simon_request_seconds_bucket{{le="0.1",worker="{w}"}} {a}
simon_request_seconds_bucket{{le="1",worker="{w}"}} {b}
simon_request_seconds_bucket{{le="+Inf",worker="{w}"}} {c}
simon_request_seconds_count{{worker="{w}"}} {c}
"""


def test_bucket_merge_empty_worker_contributes_full_after_value():
    """A worker that joined mid-measurement (absent from the ``before``
    scrape) contributes its full ``after`` value — not a crash, not a
    silent drop."""
    before = parse_metrics(_LADDER.format(w="0", a=10, b=20, c=20))
    after = parse_metrics(
        _LADDER.format(w="0", a=30, b=60, c=60)
        + _LADDER.format(w="1", a=5, b=40, c=40)
    )
    deltas = dict(bucket_deltas(before, after, "simon_request_seconds", {}))
    assert deltas[0.1] == (30 - 10) + 5
    assert deltas[1.0] == (60 - 20) + 40
    assert deltas[math.inf] == (60 - 20) + 40
    assert counter_delta(before, after, "simon_request_seconds_count") == 40 + 40


def test_bucket_merge_counter_reset_uses_post_reset_value():
    """A decreased cumulative series means the worker restarted: the
    post-reset value IS the delta (the PromQL convention) — without it a
    restart mid-run poisons every merged quantile with negatives."""
    before = parse_metrics(_LADDER.format(w="0", a=100, b=200, c=200))
    after = parse_metrics(_LADDER.format(w="0", a=3, b=7, c=7))
    deltas = dict(bucket_deltas(before, after, "simon_request_seconds", {}))
    assert deltas[0.1] == 3 and deltas[1.0] == 7 and deltas[math.inf] == 7
    assert counter_delta(before, after, "simon_request_seconds_count") == 7
    q = histogram_quantile(before, after, "simon_request_seconds", 0.5)
    assert q is not None and 0.0 <= q <= 1.0


def test_quantile_in_inf_tail_returns_last_finite_bound():
    """Mass landing past the last finite bucket: the honest answer for a
    quantile in the +Inf bucket is the last finite bound, never inf."""
    before: dict = {}
    after = parse_metrics(_LADDER.format(w="0", a=0, b=1, c=100))
    assert histogram_quantile(before, after, "simon_request_seconds", 0.99) == 1.0


def test_quantile_none_on_empty_delta_and_superset_match():
    text = _LADDER.format(w="0", a=4, b=8, c=8)
    scrape = parse_metrics(text)
    # zero traffic between scrapes → None, not 0.0
    assert histogram_quantile(scrape, scrape, "simon_request_seconds", 0.5) is None
    # match is a label SUPERSET filter: an unmatched label selects nothing
    assert (
        histogram_quantile({}, scrape, "simon_request_seconds", 0.5,
                           match={"worker": "7"})
        is None
    )
    assert histogram_quantile(
        {}, scrape, "simon_request_seconds", 0.5, match={"worker": "0"}
    ) is not None


def test_parse_duration_grammar():
    assert parse_duration_s("300") == 300.0
    assert parse_duration_s("5m") == 300.0
    assert parse_duration_s("1h") == 3600.0
    assert parse_duration_s("2d") == 172800.0
    assert parse_duration_s("") is None
    assert parse_duration_s(None) is None
    with pytest.raises(ValueError):
        parse_duration_s("five minutes")


# ---------------------------------------------------------------------------
# cross-process trace stitching (the tentpole): one stitched tree per
# request, across a real publisher/client pair
# ---------------------------------------------------------------------------


def test_stitched_trace_across_two_worker_fleet():
    """Owner accepts an event → publishes generation 1 → two workers
    attach → a request served from the twin carries the owner's event id
    and publication span, and the flight-recorder tree grafts the
    owner-side ``fleet.publication`` subtree under the request."""
    from opensim_tpu.server import rest

    cluster = _cluster()
    parts = _publication_parts(cluster)
    eid = new_event_id()
    FRESHNESS.event_accepted(eid, 1, time.time())
    pub = TwinPublisher()
    clients = []
    server = None
    try:
        pub.publish(1, cluster, parts, state="live", stale=False)
        info = FRESHNESS.pub_info(1)
        assert info is not None and [e for e, _ in info["events"]] == [eid]
        for _ in range(2):  # a two-worker fleet: both attach the same publication
            c = FleetTwinClient(pub.control.name, prep_cache=prepcache.PrepareCache())
            assert c.start(wait_s=10.0)
            clients.append(c)
        server = rest.SimonServer(watch=clients[0])
        clients[0].prep_cache = server.prep_cache
        rid = "stitch-e2e-000001"
        code, _body = server.deploy_apps(
            {"deployments": [fx.make_fake_deployment("web", 3, "500m", "1Gi").raw]},
            request_id=rid,
        )
        assert code == 200
        tr = FLIGHT_RECORDER.get(rid)
        assert tr is not None
        # worker-side stamps on the request root
        assert tr.serving_generation == 1
        assert tr.root.attrs["fleet_publication"] == info["span"]
        assert eid in tr.root.attrs["fleet_events"].split(",")
        # worker-side engine spans coexist with the fleet stamps — one tree
        span_names = {sp.name for sp in tr.walk()}
        assert "snapshot" in span_names
        # the grafted owner-side subtree (what /api/debug/requests/<id>
        # returns as the "fleet" section)
        node = publication_tree(tr.serving_generation)
        assert node is not None and node["name"] == "fleet.publication"
        assert node["span"] == info["span"]
        (ev,) = node["events"]
        assert ev["event_id"] == eid
        assert ev["accept_to_publish_s"] >= 0.0
        assert ev["accept_to_attach_s"] >= ev["accept_to_publish_s"] - 1e-6
        assert ev["accept_to_serve_s"] >= ev["accept_to_attach_s"] - 1e-6
        assert node["first_served_unix"] >= node["published_unix"] - 1e-6
    finally:
        if server is not None:
            server.close()
        for c in clients:
            c.stop()
        pub.close()


def test_freshness_histogram_moves_under_twin_storm():
    """A publish storm (events accepted, generation published, five times
    over) moves the owner-side ``published`` stage once per accepted
    event; an attaching worker then moves ``attached`` and ``served`` for
    the carried ids. FRESHNESS is per-process in a real fleet — the reset
    between the two halves recreates that split in-process."""
    cluster = _cluster(3)
    pub = TwinPublisher()
    client = None
    try:
        accepted = 0
        for gen in range(1, 6):
            for _ in range(3):
                FRESHNESS.event_accepted(new_event_id(), gen, time.time())
                accepted += 1
            pub.publish(gen, cluster, None)
        scrape = parse_metrics("\n".join(FRESHNESS.metrics_lines()))
        assert counter_delta(
            {}, scrape, "simon_fleet_freshness_seconds_count", {"stage": "published"}
        ) == accepted

        FRESHNESS.reset()  # now play the worker process
        client = FleetTwinClient(pub.control.name, prep_cache=prepcache.PrepareCache())
        assert client.start(wait_s=10.0)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            _cl, key, _stale = client.serving_snapshot()
            if key == "fleet|5":
                break
            time.sleep(0.01)
        gen, info = client.stitch_info()  # first service closes the pipeline
        assert gen == 5 and isinstance(info, dict)
        scrape = parse_metrics("\n".join(FRESHNESS.metrics_lines()))
        counts = {
            stage: counter_delta(
                {}, scrape, "simon_fleet_freshness_seconds_count", {"stage": stage}
            )
            for stage in ("attached", "served")
        }
        # the worker attached generation 5, whose payload carries that
        # publication's folded events (3 here, well under the cap)
        assert 0 < counts["attached"] <= PUB_EVENTS_MAX
        assert 0 < counts["served"] <= counts["attached"]
    finally:
        if client is not None:
            client.stop()
        pub.close()


def test_publication_caps_carried_event_ids():
    for i in range(PUB_EVENTS_MAX * 3):
        FRESHNESS.event_accepted(new_event_id(), 1, time.time())
    info = FRESHNESS.publication(1)
    assert len(info["events"]) == PUB_EVENTS_MAX
    scrape = parse_metrics("\n".join(FRESHNESS.metrics_lines()))
    # every folded event was still OBSERVED, only the carried ids are capped
    assert counter_delta(
        {}, scrape, "simon_fleet_freshness_seconds_count", {"stage": "published"}
    ) == PUB_EVENTS_MAX * 3


def test_journal_record_carries_event_id_and_journaled_stage(tmp_path):
    from opensim_tpu.server.journal import Journal

    journal = Journal(str(tmp_path / "journal"), policy={"fsync": "always"})
    try:
        ts = time.time()
        journal.record_event(
            "pods", "ADDED",
            {"metadata": {"name": "p", "namespace": "default", "resourceVersion": "2"}},
            2, eid="abc123def456", ts=ts,
        )
        assert journal.flush(timeout=10.0)
    finally:
        journal.close()
    raw = ""
    for root, _dirs, files in os.walk(str(tmp_path / "journal")):
        for name in files:
            with open(os.path.join(root, name), errors="ignore") as f:
                raw += f.read()
    assert '"eid": "abc123def456"' in raw or '"eid":"abc123def456"' in raw
    scrape = parse_metrics("\n".join(FRESHNESS.metrics_lines()))
    assert counter_delta(
        {}, scrape, "simon_fleet_freshness_seconds_count", {"stage": "journaled"}
    ) == 1


# ---------------------------------------------------------------------------
# the time-series ring: bounded by construction, exact round-trip
# ---------------------------------------------------------------------------


def _sample(i: int):
    """A scrape with delta-unfriendly floats (0.1 steps do NOT invert
    exactly in IEEE754 — the encoder must fall back to absolutes)."""
    return {
        ("simon_requests_total", ()): float(i * 7),
        ("simon_request_seconds_sum", ()): i * 0.1,
        ("simon_request_seconds_bucket", (("le", "+Inf"),)): float(i),
        ("simon_lane_depth", (("lane", "interactive"),)): float(i % 3),
    }


def test_ring_is_bounded_and_roundtrips_exactly(tmp_path):
    d = str(tmp_path / "ring")
    ring = TimeSeriesRing(directory=d, windows=3, window_samples=4)
    appended = []
    for i in range(20):  # 5 full windows through a 3-window ring
        ts = 1000.0 + i
        series = _sample(i)
        ring.append(ts, series)
        appended.append((ts, {render_series_key(k): v for k, v in series.items()}))
    files = [n for n in os.listdir(d) if n.startswith("win-") and n.endswith(".json")]
    assert len(files) <= 2  # windows-1 sealed files + the in-memory open window
    st = ring.stats()
    assert st["windows"] <= 3 and st["bytes"] > 0
    got = ring.query()
    # the ring kept the NEWEST samples and every surviving value is
    # bit-for-bit equal to what was appended — equality, not tolerance
    assert 4 <= len(got) <= 12
    assert got == appended[-len(got):]
    ring.close()
    # explicit directory: close() keeps the files for post-mortems
    assert sorted(os.listdir(d)) == sorted(files)


def test_ring_adopts_existing_directory_and_keeps_bound(tmp_path):
    d = str(tmp_path / "ring")
    ring = TimeSeriesRing(directory=d, windows=3, window_samples=2)
    for i in range(8):
        ring.append(1000.0 + i, _sample(i))
    ring.close()
    reborn = TimeSeriesRing(directory=d, windows=3, window_samples=2)
    assert reborn.stats()["windows"] == 2  # previous run's sealed files adopted
    tail = reborn.query()[-1]
    assert tail[0] == 1007.0
    for i in range(8, 12):
        reborn.append(1000.0 + i, _sample(i))
    files = [n for n in os.listdir(d) if n.startswith("win-")]
    assert len(files) <= 2
    reborn.close()


def test_ring_query_family_and_range_filters(tmp_path):
    ring = TimeSeriesRing(directory=str(tmp_path / "r"), windows=4, window_samples=3)
    for i in range(7):
        ring.append(1000.0 + i * 10, _sample(i))
    fam = ring.query(family="simon_request_seconds")
    assert fam and all(
        k.split("{", 1)[0] in
        ("simon_request_seconds_sum", "simon_request_seconds_bucket")
        for _ts, s in fam for k in s
    )
    recent = ring.query(range_s=25.0, now=1060.0)
    assert [ts for ts, _ in recent] == [1040.0, 1050.0, 1060.0]
    with pytest.raises(ValueError):
        parse_duration_s("1w")  # the HTTP layer rejects, never silently ignores
    ring.close()


# ---------------------------------------------------------------------------
# the SLO engine: burn rates vs hand-computed windows
# ---------------------------------------------------------------------------


def _slo_scrape(ok, err, under_100ms, fresh_under_30, fresh_total):
    total = ok + err
    return {
        ("simon_request_seconds_count", (("endpoint", "deploy-apps"), ("status", "ok"))): float(ok),
        ("simon_request_seconds_count", (("endpoint", "deploy-apps"), ("status", "error"))): float(err),
        ("simon_request_seconds_bucket", (("endpoint", "deploy-apps"), ("le", "0.1"), ("status", "ok"))): float(under_100ms),
        ("simon_request_seconds_bucket", (("endpoint", "deploy-apps"), ("le", "+Inf"), ("status", "ok"))): float(total),
        ("simon_fleet_freshness_seconds_bucket", (("le", "30"), ("stage", "served"))): float(fresh_under_30),
        ("simon_fleet_freshness_seconds_bucket", (("le", "+Inf"), ("stage", "served"))): float(fresh_total),
        ("simon_fleet_freshness_seconds_count", (("stage", "served"),)): float(fresh_total),
    }


def test_slo_burn_rates_match_hand_computed_windows(tmp_path):
    ring = TimeSeriesRing(directory=str(tmp_path / "r"), windows=4, window_samples=64)
    # t=900: 100 requests, all good;  t=1000: +100 requests of which 10
    # errored and 10 (of the ok ones… by bucket: 90 stayed under 100ms)
    ring.append(900.0, _slo_scrape(ok=100, err=0, under_100ms=100,
                                   fresh_under_30=0, fresh_total=0))
    ring.append(1000.0, _slo_scrape(ok=190, err=10, under_100ms=190,
                                    fresh_under_30=95, fresh_total=100))
    objectives = [
        Objective("availability", 99.0),
        Objective("latency_p99", 99.0, 0.1),
        Objective("freshness", 99.0, 30.0),
    ]
    engine = SLOEngine(ring, objectives=objectives,
                       windows=[("5m", 300.0), ("30s", 30.0)])
    payload = engine.evaluate(now=1000.0)
    rows = {r["name"]: r for r in payload["objectives"]}
    # availability: bad=10 of total=100 new requests; budget=1% → burn 10×
    win = rows["availability"]["windows"]["5m"]
    assert (win["bad"], win["total"], win["burn_rate"]) == (10.0, 100.0, 10.0)
    # latency: 90 of 100 new under the 0.1 bound → 10 bad → burn 10×
    win = rows["latency_p99"]["windows"]["5m"]
    assert (win["bad"], win["total"], win["burn_rate"]) == (10.0, 100.0, 10.0)
    assert win["bucket_bound_s"] == 0.1
    # freshness: 95 of 100 served under 30s → 5 bad → burn 5×
    win = rows["freshness"]["windows"]["5m"]
    assert (win["bad"], win["total"], win["burn_rate"]) == (5.0, 100.0, 5.0)
    assert win["bucket_bound_s"] == 30.0
    # the 30s window holds ONE sample → no_data, burn pinned to 0.0:
    # an SLO must say "I don't know" rather than "all is well"
    for name in rows:
        short = rows[name]["windows"]["30s"]
        assert short["no_data"] is True and short["burn_rate"] == 0.0
    lines = engine.metrics_lines(now=1000.0)
    assert 'simon_slo_burn_rate{slo="availability",window="5m"} 10' in lines
    assert 'simon_slo_burn_rate{slo="freshness",window="30s"} 0' in lines
    ring.close()


def test_slo_and_window_parsers_fail_loudly():
    objs = parse_objectives("availability:99.9,latency_p99:99:2.5,freshness:99:30")
    assert [(o.kind, o.target_pct, o.threshold_s) for o in objs] == [
        ("availability", 99.9, None), ("latency_p99", 99.0, 2.5),
        ("freshness", 99.0, 30.0),
    ]
    assert abs(objs[0].budget - 0.001) < 1e-12
    with pytest.raises(ValueError):
        parse_objectives("latency_p99:99")  # threshold required
    with pytest.raises(ValueError):
        parse_objectives("uptime:99")  # unknown kind
    with pytest.raises(ValueError):
        parse_objectives("availability:100")  # target must be in (0, 100)
    assert parse_windows("5m,1h") == [("5m", 300.0), ("1h", 3600.0)]
    with pytest.raises(ValueError):
        parse_windows("5x")


# ---------------------------------------------------------------------------
# simon dash rows: pure, byte-stable, takeover markers visible
# ---------------------------------------------------------------------------


def _dash_payload():
    def enc(series):
        return {render_series_key(k): v for k, v in series.items()}

    s0 = dict(_slo_scrape(ok=100, err=0, under_100ms=100,
                          fresh_under_30=0, fresh_total=0))
    s0[("simon_requests_total", ())] = 100.0
    s0[("simon_fleet_takeovers_total", (("reason", "expired"),))] = 0.0
    s1 = dict(_slo_scrape(ok=190, err=10, under_100ms=190,
                          fresh_under_30=95, fresh_total=100))
    s1[("simon_requests_total", ())] = 200.0
    s1[("simon_fleet_takeovers_total", (("reason", "expired"),))] = 1.0
    s1[("simon_lane_depth", (("lane", "interactive"),))] = 2.0
    # a worker-labeled copy of the summed counter: dash must NOT double-count
    s1[("simon_requests_total", (("worker", "0"),))] = 200.0
    return {
        "timeseries": {
            "stats": {"windows": 1, "window_capacity": 4},
            "samples": [[900.0, enc(s0)], [950.0, enc(s1)]],
        },
        "slo": {
            "objectives": [{
                "name": "availability", "target_pct": 99.0,
                "windows": {"5m": {"burn_rate": 10.0, "no_data": False}},
            }],
        },
    }


def test_dash_rows_takeover_marker_and_single_counting():
    from opensim_tpu.cli.dash import dash_rows, format_dash

    rows = dash_rows(_dash_payload())
    assert rows["qps"] == pytest.approx(100.0 / 50.0)  # 100 new requests / 50 s
    assert rows["takeovers"] == [{"unix": 950.0, "reason": "expired", "count": 1.0}]
    assert rows["lanes"] == {"interactive": 2.0}
    assert rows["slo"][0]["windows"]["5m"]["burn_rate"] == 10.0
    text = format_dash(_dash_payload())
    assert "takeover  reason=expired" in text
    assert "slo       availability" in text


def test_dash_rows_are_byte_stable():
    from opensim_tpu.cli.dash import dash_rows

    payload = _dash_payload()
    a = json.dumps(dash_rows(payload), sort_keys=True)
    b = json.dumps(dash_rows(json.loads(json.dumps(payload))), sort_keys=True)
    assert a == b


def test_dash_degrades_per_surface():
    from opensim_tpu.cli.dash import dash_rows, format_dash

    payload = {"timeseries_error": "503: standby", "slo_error": "503: standby"}
    rows = dash_rows(payload)
    assert rows["samples"] == 0 and "qps" not in rows
    assert "timeseries unavailable" in format_dash(payload)


# ---------------------------------------------------------------------------
# end to end: the takeover marker survives an owner SIGKILL
# ---------------------------------------------------------------------------


def test_takeover_marker_recorded_through_owner_sigkill(tmp_path):
    """SIGKILL the HA owner: the promoted standby boots its OWN ring
    (``serve_fleet`` wires ``start_timeseries`` on promotion), the ring
    samples ``simon_fleet_takeovers_total{reason="expired"}``, and the
    dash rows render the takeover as a timeline marker — the operator
    sees the failover next to the latency it caused. Rides the HA
    harness from test_ha.py (same topology, observability assertions)."""
    import urllib.error

    from opensim_tpu.cli.dash import dash_rows, fetch_dash
    from opensim_tpu.server.stubapi import StubApiServer
    from test_ha import (
        _drain_kill, _ha_env, _http_json, _free_port, _owner_up,
        _pod_dict, _seed, _spawn_owner, _spawn_standby, _wait,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    stub = StubApiServer(bookmark_interval_s=0.1).start()
    _seed(stub)
    kc = stub.kubeconfig(tmp_path)
    jd = str(tmp_path / "journal")
    port = _free_port()
    env = dict(
        _ha_env(repo),
        OPENSIM_TS_INTERVAL_S="0.2",  # sample fast so markers appear in seconds
        OPENSIM_TS_WINDOWS="4", OPENSIM_TS_WINDOW_SAMPLES="16",
    )
    owner_log = str(tmp_path / "owner.log")
    sb_log = str(tmp_path / "standby.log")
    owner = standby = None
    sb_admin = port + 16
    try:
        owner = _spawn_owner(repo, kc, jd, port, env, owner_log)
        _wait(
            _owner_up(port + 1, owner, owner_log),
            timeout=120.0, msg="HA owner fleet up",
        )

        def owner_ring_sampling():
            try:
                doc = _http_json(f"http://127.0.0.1:{port + 1}/api/debug/timeseries")
                return len(doc.get("samples") or []) >= 2
            except (OSError, urllib.error.HTTPError):
                return False

        _wait(owner_ring_sampling, timeout=30.0, msg="owner ring to sample")

        standby = _spawn_standby(repo, kc, jd, port, env, sb_log)

        def standby_tailing():
            if standby.poll() is not None:
                raise AssertionError("standby died early")
            try:
                body = _http_json(f"http://127.0.0.1:{sb_admin}/api/fleet/status")
                return body["role"] == "standby" and body["at_parity"]
            except OSError:
                return False

        _wait(standby_tailing, timeout=60.0, msg="standby to tail to parity")
        # a standby has no ring: the endpoint says 503, and dash degrades
        # to an error field instead of dying
        with pytest.raises(urllib.error.HTTPError) as err:
            _http_json(f"http://127.0.0.1:{sb_admin}/api/debug/timeseries")
        assert err.value.code == 503
        payload = fetch_dash(f"http://127.0.0.1:{sb_admin}", timeout_s=3.0)
        assert "timeseries_error" in payload

        for i in range(10):
            stub.upsert("/api/v1/pods", _pod_dict(f"storm-{i}", rv=1000 + i))
        owner.kill()  # SIGKILL: no flush, no release, no goodbye
        owner.wait(timeout=10)

        def marker_visible():
            try:
                rows = dash_rows(
                    fetch_dash(f"http://127.0.0.1:{sb_admin}", timeout_s=3.0)
                )
            except (OSError, ValueError):
                return False
            return any(
                m["reason"] == "expired" for m in rows.get("takeovers") or []
            )

        _wait(marker_visible, timeout=90.0, msg="takeover marker in dash rows")
        # the promoted owner's SLO engine answers over the same ring
        slo = _http_json(f"http://127.0.0.1:{sb_admin}/api/fleet/slo")
        assert {row["name"] for row in slo["objectives"]} == {
            "availability", "latency_p99", "freshness",
        }
    finally:
        _drain_kill(owner, standby)
        stub.stop()
