"""OSL1604 ABI-drift regression matrix (detector-awake for the parity
pass): copies of the REAL abi-v5 native sources are mutated one axis at a
time — field order, pointer width, abi version, serial wire tag — and the
rule must fire naming the exact drifted field; the unmutated copies must
stay green."""

import os
import re
import shutil

from opensim_tpu.analysis import lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "opensim_tpu", "native")


def _stage(tmp_path, mutate=None):
    """Copy the real native sources into tmp; ``mutate(path)->None`` edits
    them. Returns the staged native/ dir."""
    dst = os.path.join(str(tmp_path), "native")
    os.makedirs(dst)
    for name in ("__init__.py", "serial.py", "scan_engine.cc", "serial_engine.cc"):
        shutil.copy(os.path.join(NATIVE, name), os.path.join(dst, name))
    if mutate is not None:
        mutate(dst)
    return dst


def _findings(dst):
    return lint_paths([dst], rules=["abi-parity"])


def _edit(dst, name, old, new, count=1):
    path = os.path.join(dst, name)
    with open(path) as fh:
        src = fh.read()
    assert old in src, f"mutation anchor {old!r} missing from {name}"
    with open(path, "w") as fh:
        fh.write(src.replace(old, new, count))


def test_real_abi_v5_sources_are_green(tmp_path):
    assert _findings(_stage(tmp_path)) == []


def test_field_order_swap_fires_naming_the_field(tmp_path):
    # swap Hp and Hports in the C++ dims declaration
    dst = _stage(tmp_path)
    _edit(dst, "scan_engine.cc", "Hp, Hports,", "Hports, Hp,")
    findings = _findings(dst)
    assert [f.code for f in findings] == ["OSL1604"]
    msg = findings[0].message
    assert "order drift" in msg and "`Hports`" in msg and "`Hp`" in msg


def test_python_packing_order_swap_fires(tmp_path):
    dst = _stage(tmp_path)
    _edit(dst, "__init__.py", '"node_valid", _U8, "u8"), ("alloc", _F32, "f32"',
          '"alloc", _F32, "f32"), ("node_valid", _U8, "u8"')
    findings = _findings(dst)
    assert [f.code for f in findings] == ["OSL1604"]
    assert "alloc" in findings[0].message


def test_pointer_width_drift_fires_naming_the_field(tmp_path):
    dst = _stage(tmp_path)
    _edit(dst, "scan_engine.cc", "int32_t* chosen;", "int64_t* chosen;")
    findings = _findings(dst)
    assert [f.code for f in findings] == ["OSL1604"]
    msg = findings[0].message
    assert "width drift" in msg and "`chosen`" in msg
    assert "ptr:i64" in msg and "ptr:i32" in msg


def test_dropped_field_fires_with_count(tmp_path):
    dst = _stage(tmp_path)
    _edit(dst, "scan_engine.cc", "  const float* avoid_score;", "")
    findings = _findings(dst)
    assert findings and all(f.code == "OSL1604" for f in findings)
    assert any("count drift" in f.message for f in findings)


def test_abi_version_drift_fires(tmp_path):
    dst = _stage(tmp_path)
    _edit(dst, "scan_engine.cc", "opensim_abi_version() { return 5; }",
          "opensim_abi_version() { return 6; }")
    findings = _findings(dst)
    assert [f.code for f in findings] == ["OSL1604"]
    assert "version drift" in findings[0].message


def test_v5_carry_field_dropped_fires_naming_it(tmp_path):
    # abi v5: dropping the bail_out carry buffer from the C++ struct must
    # fail the gate, not silently narrow the attribution surface
    dst = _stage(tmp_path)
    _edit(dst, "scan_engine.cc", "  int64_t* bail_out;     // [11]\n", "")
    findings = _findings(dst)
    assert findings and all(f.code == "OSL1604" for f in findings)
    assert any("count drift" in f.message for f in findings)
    assert any("bail_out" in f.message for f in findings)


def test_v5_carry_field_width_drift_fires_naming_it(tmp_path):
    dst = _stage(tmp_path)
    _edit(dst, "scan_engine.cc", "int64_t* class_steps;", "int32_t* class_steps;")
    findings = _findings(dst)
    assert [f.code for f in findings] == ["OSL1604"]
    msg = findings[0].message
    assert "width drift" in msg and "`class_steps`" in msg
    assert "ptr:i32" in msg and "ptr:i64" in msg


def test_v5_carry_field_order_swap_fires_naming_them(tmp_path):
    dst = _stage(tmp_path)
    _edit(dst, "scan_engine.cc",
          "  int64_t* bail_out;     // [11]\n  int64_t* class_steps;  // [4]",
          "  int64_t* class_steps;  // [4]\n  int64_t* bail_out;     // [11]")
    findings = _findings(dst)
    assert [f.code for f in findings] == ["OSL1604"]
    msg = findings[0].message
    assert "order drift" in msg and "`bail_out`" in msg and "`class_steps`" in msg


def test_serial_wire_version_drift_fires(tmp_path):
    dst = _stage(tmp_path)
    _edit(dst, "serial.py", "WIRE_VERSION = 1", "WIRE_VERSION = 2")
    findings = _findings(dst)
    assert [f.code for f in findings] == ["OSL1604"]
    assert "serial wire version drift" in findings[0].message


def test_missing_anchor_constant_fires(tmp_path):
    dst = _stage(tmp_path)
    _edit(dst, "__init__.py", "ABI_VERSION = 5", "_NOT_THE_ANCHOR = 5")
    findings = _findings(dst)
    assert [f.code for f in findings] == ["OSL1604"]
    assert "ABI_VERSION constant missing" in findings[0].message


def test_unparsable_packing_list_fails_loud_not_quiet(tmp_path):
    # review regression: a mirror whose packing list stops being resolvable
    # must FAIL the gate (parse problem finding), never silently skip it
    dst = _stage(tmp_path)
    _edit(dst, "__init__.py", "_DIMS = [", "_DIMS_RENAMED = [")
    findings = _findings(dst)
    assert findings and all(f.code == "OSL1604" for f in findings)
    assert any("_DIMS" in f.message and "parse problem" in f.message for f in findings)


def test_cc_anchors_present_in_real_source():
    src = open(os.path.join(NATIVE, "scan_engine.cc")).read()
    assert re.search(r"//\s*abi-begin:\s*ScanArgs", src)
    assert re.search(r"//\s*abi-end:\s*ScanArgs", src)
