"""Coverage for the remaining small components: greed queue, chart
tarballs, the scale-apps endpoint, report pod table, CLI doc generation."""

import pytest
import json
import os
import tarfile
import threading
import urllib.request

from opensim_tpu.engine.queues import greed_sort
from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx


from contextlib import contextmanager


@contextmanager
def _serve(server):
    """Boot a SimonServer on an ephemeral port; yields the port."""
    from http.server import ThreadingHTTPServer

    from opensim_tpu.server.rest import make_handler

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield httpd.server_address[1]
    finally:
        httpd.shutdown()


def test_greed_sort_order():
    nodes = [fx.make_fake_node("n0", "10", "100Gi")]
    pods = [
        fx.make_fake_pod("small", "100m", "1Gi"),
        fx.make_fake_pod("big", "8", "10Gi"),
        fx.make_fake_pod("pinned", "50m", "1Gi", fx.with_node_name("n0")),
        fx.make_fake_pod("mid", "2", "2Gi"),
    ]
    ordered = [p.metadata.name for p in greed_sort(nodes, pods)]
    # nodeName-pinned first, then descending dominant share (greed.go:37-67)
    assert ordered == ["pinned", "big", "mid", "small"]


def test_chart_tarball(tmp_path):
    from opensim_tpu.chart.render import process_chart

    src = "example/application/charts/obs-stack"
    tgz = tmp_path / "obs.tgz"
    with tarfile.open(tgz, "w:gz") as tf:
        tf.add(src, arcname="obs-stack")
    docs = process_chart("obs", str(tgz))
    assert len(docs) >= 10
    assert "{{" not in "\n".join(docs)


def test_scale_apps_endpoint():
    from http.server import ThreadingHTTPServer

    from opensim_tpu.server.rest import SimonServer, make_handler

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "8", "16Gi"))
    # an existing deployment's pods are bound in the snapshot
    existing = fx.make_fake_deployment("web", 2, "1", "1Gi")
    res = simulate(cluster, [AppResource("seed", ResourceTypes(deployments=[existing]))])
    for ns in res.node_status:
        cluster.pods.extend(ns.pods)

    with _serve(SimonServer(base_cluster=cluster)) as port:
        scaled = fx.make_fake_deployment("web", 5, "1", "1Gi")
        body = json.dumps({"deployments": [scaled.raw]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/scale-apps", data=body, method="POST"
        )
        with urllib.request.urlopen(req) as r:
            resp = json.load(r)
        assert resp["unscheduledPods"] == []
        # old replicas removed, 5 new ones placed
        assert sum(len(ns["pods"]) for ns in resp["nodeStatus"]) == 5


def test_report_pod_table(tmp_path):
    import io

    from opensim_tpu.planner import report as report_mod

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p1", "500m", "1Gi"))
    res = simulate(cluster, [AppResource("a", app)])
    buf = io.StringIO()
    report_mod.report(res, [], ["a"], out=buf, pod_nodes=[])
    text = buf.getvalue()
    assert "Pod Info" in text and "p1" in text and "500m" in text


def test_gen_doc(tmp_path):
    from opensim_tpu.cli.main import build_parser, gen_doc

    out_dir = tmp_path / "docs"
    assert gen_doc(build_parser(), str(out_dir)) == 0
    text = (out_dir / "simon.md").read_text()
    # one markdown per subcommand, like cobra/doc's GenMarkdownTree
    # (cmd/doc/generate_markdown.go:33)
    for cmd in ("apply", "defrag", "server", "version", "gen-doc"):
        assert f"simon {cmd}" in text
        per_cmd = (out_dir / f"simon_{cmd.replace('-', '_')}.md").read_text()
        assert f"# simon {cmd}" in per_cmd
    assert "--use-greed" in (out_dir / "simon_apply.md").read_text()


@pytest.mark.slow
def test_defrag_cli(tmp_path):
    import yaml as _yaml

    from opensim_tpu.cli.main import main

    cluster_dir = tmp_path / "cluster"
    app_dir = tmp_path / "app"
    cluster_dir.mkdir()
    app_dir.mkdir()
    for i in range(3):
        (cluster_dir / f"n{i}.yaml").write_text(_yaml.safe_dump(fx.make_fake_node(f"n{i}", "8", "16Gi").raw))
    (app_dir / "d.yaml").write_text(_yaml.safe_dump(fx.make_fake_deployment("d", 3, "1", "1Gi").raw))
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        f"apiVersion: simon/v1alpha1\nkind: Config\nmetadata: {{name: t}}\n"
        f"spec:\n  cluster: {{customConfig: {cluster_dir}}}\n  appList:\n    - name: a\n      path: {app_dir}\n"
    )
    out = tmp_path / "out.txt"
    assert main(["defrag", "-f", str(cfg), "-o", str(out)]) == 0
    text = out.read_text()
    assert "Drain Plan" in text and "3/3 node(s) drainable" in text
    # candidates filter
    assert main(["defrag", "-f", str(cfg), "--candidates", "n0, n1", "-o", str(out)]) == 0
    assert "2/2 node(s) drainable" in out.read_text()
    # unknown candidate -> explicit error, nonzero exit
    assert main(["defrag", "-f", str(cfg), "--candidates", "n99"]) == 1


def test_metrics_endpoint():
    from http.server import ThreadingHTTPServer

    from opensim_tpu.server.rest import SimonServer, make_handler

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("m1", "8", "16Gi"))
    with _serve(SimonServer(base_cluster=cluster)) as port:
        body = json.dumps({"deployments": [fx.make_fake_deployment("m", 2, "100m", "128Mi").raw]}).encode()
        req = urllib.request.Request(f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST")
        urllib.request.urlopen(req).read()
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
            text = r.read().decode()
        assert 'simon_requests_total{endpoint="deploy-apps"}' in text
        assert "simon_pods_scheduled_total" in text
        assert "simon_simulate_seconds_total" in text


def test_interactive_apply_scripted(tmp_path, monkeypatch):
    """The reference's interactive loop, driven with scripted answers."""
    import yaml as _yaml

    from opensim_tpu.planner.apply import Applier, Options

    cluster_dir = tmp_path / "cluster"
    app_dir = tmp_path / "app"
    nn_dir = tmp_path / "newnode"
    for d in (cluster_dir, app_dir, nn_dir):
        d.mkdir()
    (cluster_dir / "n.yaml").write_text(_yaml.safe_dump(fx.make_fake_node("n1", "2", "4Gi").raw))
    (app_dir / "d.yaml").write_text(_yaml.safe_dump(fx.make_fake_deployment("d", 4, "1", "1Gi").raw))
    (nn_dir / "n.yaml").write_text(_yaml.safe_dump(fx.make_fake_node("tmpl", "8", "16Gi").raw))
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        f"apiVersion: simon/v1alpha1\nkind: Config\nmetadata: {{name: t}}\n"
        f"spec:\n  cluster: {{customConfig: {cluster_dir}}}\n"
        f"  appList:\n    - name: a\n      path: {app_dir}\n  newNode: {nn_dir}\n"
    )
    # survey-style: Show results, Add nodes, "1" into the number prompt,
    # '-' declines the pod-table node selection. Legacy 'show'/'add' words
    # and numeric selections both resolve.
    answers = iter(["show", "2", "1", "-"])
    monkeypatch.setattr("builtins.input", lambda *a: next(answers))
    out = tmp_path / "out.txt"
    rc = Applier(Options(simon_config=str(cfg), interactive=True, output_file=str(out))).run()
    assert rc == 0
    assert "Simulation success!" in out.read_text()


def test_patch_pods_fn_hook():
    """WithPatchPodsFuncMap parity: the hook can mutate app pods before
    scheduling (simulator.go:243-249)."""
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))
    seen = []

    def patch(app_name, pods):
        seen.append(app_name)
        for p in pods:
            p.metadata.annotations["patched"] = "yes"

    res = simulate(cluster, [AppResource("a", app)], patch_pods_fn=patch)
    assert seen == ["a"]
    assert all(p.metadata.annotations.get("patched") == "yes" for ns in res.node_status for p in ns.pods)


def test_patch_pods_fn_per_pod_mutation_is_honored():
    """A hook that mutates ONE pod of a workload must change that pod's
    scheduling: workload-identity template hints are bypassed for patched
    app pods (the hint cannot see per-pod spec edits)."""
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "4", "8Gi"))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("w", 5, "1", "1Gi"))

    def patch(app_name, pods):
        # pod 3 alone demands more cpu than the node has. Clones share
        # nested spec lists, so a per-pod edit replaces the container list.
        import copy

        containers = copy.deepcopy(pods[3].spec.containers)
        containers[0].requests["cpu"] = 100.0
        pods[3].spec.containers = containers

    res = simulate(cluster, [AppResource("a", app)], patch_pods_fn=patch)
    assert len(res.unscheduled_pods) == 1
    assert "Insufficient cpu" in res.unscheduled_pods[0].reason
    assert sum(len(ns.pods) for ns in res.node_status) == 4


def test_server_newnodes_become_fake_nodes():
    from http.server import ThreadingHTTPServer

    from opensim_tpu.server.rest import SimonServer, make_handler

    with _serve(SimonServer(base_cluster=ResourceTypes())) as port:
        body = json.dumps(
            {
                "newnodes": [fx.make_fake_node("template", "8", "16Gi").raw],
                "deployments": [fx.make_fake_deployment("w", 2, "100m", "128Mi").raw],
            }
        ).encode()
        req = urllib.request.Request(f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST")
        with urllib.request.urlopen(req) as r:
            resp = json.load(r)
        assert resp["unscheduledPods"] == []
        # the requested node was renamed to a fake simon-<rand> node
        assert resp["nodeStatus"][0]["node"].startswith("simon-")


def test_server_busy_rejection():
    """TryLock 503 parity (server.go:167,:234): concurrent deploy requests
    are rejected while one is in flight (rejection happens before the
    payload is read, so a minimal body suffices)."""
    from http.server import ThreadingHTTPServer

    from opensim_tpu.server import rest as rest_mod
    from opensim_tpu.server.rest import SimonServer, make_handler

    # admission=False: the TryLock busy path is the OPENSIM_ADMISSION=off
    # mode (the default routes through the admission queue, ISSUE 8)
    with _serve(SimonServer(base_cluster=ResourceTypes(), admission=False)) as port:
        # hold the deploy lock like an in-flight simulation would
        assert rest_mod._deploy_lock.acquire(blocking=False)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/deploy-apps", data=b"{}", method="POST"
            )
            try:
                urllib.request.urlopen(req, timeout=5)
                assert False, "expected 503"
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert "busy" in json.load(e).get("error", "")
        finally:
            rest_mod._deploy_lock.release()


def test_package_root_api():
    """The lazy top-level API re-exports work and importing opensim_tpu
    alone must not be what initializes jax elsewhere."""
    import opensim_tpu as ot

    assert ot.__version__
    assert callable(ot.simulate) and callable(ot.plan_drains)
    assert ot.ResourceTypes is ResourceTypes
    import pytest as _pytest

    with _pytest.raises(AttributeError):
        ot.nonexistent_symbol


def test_chart_values_schema_validation(tmp_path):
    """Charts carrying values.schema.json are schema-validated before
    rendering (chartutil.ValidateAgainstSchema parity, pkg/chart/chart.go:
    18-41): good values render, violating values fail with the helm
    wording, and the error names the offending path."""
    import shutil

    import yaml

    from opensim_tpu.chart.render import ChartError, process_chart

    src = "example/application/charts/obs-stack"
    chart = tmp_path / "obs-stack"
    shutil.copytree(src, chart)
    schema = {
        "$schema": "http://json-schema.org/draft-07/schema#",
        "type": "object",
        "properties": {
            "replicas": {"type": "integer", "minimum": 1},
        },
        "required": ["replicas"],
    }
    (chart / "values.schema.json").write_text(json.dumps(schema))

    values = yaml.safe_load((chart / "values.yaml").read_text()) or {}
    values["replicas"] = 2
    (chart / "values.yaml").write_text(yaml.safe_dump(values))
    docs = process_chart("obs", str(chart))
    assert len(docs) >= 10  # valid values render normally

    values["replicas"] = 0  # violates minimum: 1
    (chart / "values.yaml").write_text(yaml.safe_dump(values))
    try:
        process_chart("obs", str(chart))
        raise AssertionError("schema violation must fail the chart")
    except ChartError as e:
        msg = str(e)
        assert "values don't meet the specifications" in msg
        assert "replicas" in msg

    (chart / "values.schema.json").write_text("{not json")
    try:
        process_chart("obs", str(chart))
        raise AssertionError("unparseable schema must fail the chart")
    except ChartError as e:
        assert "invalid values.schema.json" in str(e)


def test_chart_schema_invalid_schema_document(tmp_path):
    """A parseable-JSON but invalid schema raises ChartError (not a raw
    jsonschema.SchemaError), and a bad-draft keyword is caught by
    check_schema."""
    import shutil

    from opensim_tpu.chart.render import ChartError, process_chart

    chart = tmp_path / "obs-stack"
    shutil.copytree("example/application/charts/obs-stack", chart)
    (chart / "values.schema.json").write_text(json.dumps({"type": 123}))
    try:
        process_chart("obs", str(chart))
        raise AssertionError("invalid schema document must fail the chart")
    except ChartError as e:
        assert "invalid values.schema.json" in str(e)
