"""OSL16xx rule pack (analysis/rules_dataflow.py): each rule fires on its
known-bad fixture, stays quiet on the disciplined twin, honors
suppressions, and the repo itself stays clean — plus the incremental lint
cache's hit/miss/invalidenation behavior."""

import os
import textwrap

from opensim_tpu.analysis import RULES, lint_paths, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = "opensim_tpu/server/fixture.py"


def _codes(src, path=FIX, rules=None):
    return [f.code for f in lint_source(textwrap.dedent(src), path=path, rules=rules)]


def test_osl16xx_registered():
    by_code = {r.code for r in RULES.values()}
    assert {"OSL1601", "OSL1602", "OSL1603", "OSL1604"} <= by_code
    assert {"OSL1801", "OSL1802", "OSL1803", "OSL1804"} <= by_code
    assert "OSL1901" in by_code
    assert len(RULES) == 28


# ---------------------------------------------------------------------------
# OSL1601 jit-impurity
# ---------------------------------------------------------------------------


def test_jit_impurity_fires_across_call_graph_depth():
    src = """
    import time

    import jax

    def helper(c):
        return c * time.time()

    def body(carry, x):
        return helper(carry), x

    def outer(xs):
        return jax.lax.scan(body, 0, xs)
    """
    findings = lint_source(textwrap.dedent(src), path=FIX, rules=["jit-impurity"])
    assert [f.code for f in findings] == ["OSL1601"]
    # the message names the effect, the root, and the call chain
    assert "time.time" in findings[0].message
    assert "body" in findings[0].message and "helper" in findings[0].message


def test_jit_impurity_quiet_on_host_code_and_pure_traced_code():
    src = """
    import time

    import jax

    @jax.jit
    def step(x):
        return x + 1

    def host(xs):
        t0 = time.time()
        return step(xs), time.time() - t0
    """
    assert _codes(src, rules=["jit-impurity"]) == []


def test_jit_impurity_suppression():
    src = """
    import time

    import jax

    @jax.jit
    def step(x):
        return x + time.time()  # opensim-lint: disable=jit-impurity
    """
    assert _codes(src, rules=["jit-impurity"]) == []


def test_jit_impurity_repo_is_clean():
    root = os.path.join(REPO, "opensim_tpu")
    findings = [f for f in lint_paths([root]) if f.code == "OSL1601"]
    assert findings == [], [f"{f.path}:{f.line}: {f.message}" for f in findings]


# ---------------------------------------------------------------------------
# OSL1602 tracer-leak
# ---------------------------------------------------------------------------


def test_tracer_leak_fires_on_outliving_stores():
    src = """
    import jax
    import jax.numpy as jnp

    _HISTORY = []

    class Rec:
        @jax.jit
        def step(self, x):
            y = jnp.sum(x)
            self.last = y
            _HISTORY.append(x)
            return y
    """
    assert _codes(src, rules=["tracer-leak"]) == ["OSL1602", "OSL1602"]


def test_tracer_leak_quiet_on_concrete_and_local_state():
    src = """
    import jax
    import jax.numpy as jnp

    class Rec:
        @jax.jit
        def step(self, x):
            y = jnp.sum(x)
            self.calls = int(3)   # concrete host value: fine
            scratch = [y]
            scratch.append(y)     # local container: fine
            return y
    """
    assert _codes(src, rules=["tracer-leak"]) == []


def test_tracer_leak_repo_is_clean():
    root = os.path.join(REPO, "opensim_tpu")
    findings = [f for f in lint_paths([root]) if f.code == "OSL1602"]
    assert findings == [], [f"{f.path}:{f.line}: {f.message}" for f in findings]


# ---------------------------------------------------------------------------
# OSL1603 input-taint
# ---------------------------------------------------------------------------


def test_input_taint_fires_and_names_the_source():
    src = """
    from urllib.parse import parse_qs

    def handler(q):
        name = parse_qs(q).get("f", [""])[-1]
        return open(name)
    """
    findings = lint_source(textwrap.dedent(src), path=FIX, rules=["input-taint"])
    assert [f.code for f in findings] == ["OSL1603"]
    assert "http-query" in findings[0].message


def test_input_taint_quiet_through_registered_sanitizer():
    src = """
    from urllib.parse import parse_qs

    def sanitizer(fn):
        return fn

    @sanitizer
    def safe_name(raw):
        if not raw.isidentifier():
            raise ValueError(raw)
        return raw

    def handler(q):
        return open(safe_name(parse_qs(q).get("f", [""])[-1]))
    """
    assert _codes(src, rules=["input-taint"]) == []


def test_input_taint_interprocedural_and_cli_sources():
    src = """
    import sys

    def save(path, data):
        with open(path, "w") as fh:
            fh.write(data)

    def main():
        save(sys.argv[1], "hello")
    """
    findings = lint_source(textwrap.dedent(src), path=FIX, rules=["input-taint"])
    assert [f.code for f in findings] == ["OSL1603"]
    assert "cli-arg" in findings[0].message


def test_input_taint_repo_is_clean():
    root = os.path.join(REPO, "opensim_tpu")
    findings = [f for f in lint_paths([root]) if f.code == "OSL1603"]
    assert findings == [], [f"{f.path}:{f.line}: {f.message}" for f in findings]


# ---------------------------------------------------------------------------
# OSL1604 abi-parity (the full drift matrix lives in test_abi_parity.py)
# ---------------------------------------------------------------------------


def test_abi_parity_green_on_real_abi_v4_sources():
    root = os.path.join(REPO, "opensim_tpu")
    findings = [f for f in lint_paths([root], rules=["abi-parity"])]
    assert findings == [], [f"{f.path}:{f.line}: {f.message}" for f in findings]


# ---------------------------------------------------------------------------
# incremental lint cache
# ---------------------------------------------------------------------------


def _write_tree(root, files):
    for rel, src in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fh:
            fh.write(textwrap.dedent(src))


def test_cache_cold_then_warm_then_invalidate(tmp_path):
    tree = str(tmp_path / "proj")
    cache = str(tmp_path / "cache.json")
    _write_tree(
        tree,
        {
            "a.py": """
            def swallow(risky):
                try:
                    risky()
                except Exception:
                    pass
            """,
            "b.py": "x = 1\n",
        },
    )
    stats1: dict = {}
    f1 = lint_paths([tree], stats=stats1, cache_path=cache)
    assert stats1["cache_misses"] == 2 and stats1["cache_hits"] == 0
    assert stats1["project_pass"] == "rebuilt"
    assert [f.code for f in f1] == ["OSL501"]

    stats2: dict = {}
    f2 = lint_paths([tree], stats=stats2, cache_path=cache)
    assert stats2["cache_hits"] == 2 and stats2["cache_misses"] == 0
    assert stats2["project_pass"] == "reused"
    assert [f.as_dict() for f in f2] == [f.as_dict() for f in f1]

    # edit ONE file: that file misses, the other still hits, project rebuilds
    with open(os.path.join(tree, "b.py"), "w") as fh:
        fh.write("y = 2\n")
    stats3: dict = {}
    f3 = lint_paths([tree], stats=stats3, cache_path=cache)
    assert stats3["cache_hits"] == 1 and stats3["cache_misses"] == 1
    assert stats3["project_pass"] == "rebuilt"
    assert [f.code for f in f3] == ["OSL501"]


def test_cache_results_match_uncached_run(tmp_path):
    cache = str(tmp_path / "cache.json")
    root = os.path.join(REPO, "opensim_tpu", "utils")
    plain = [f.as_dict() for f in lint_paths([root])]
    cached_cold = [f.as_dict() for f in lint_paths([root], cache_path=cache)]
    cached_warm = [f.as_dict() for f in lint_paths([root], cache_path=cache)]
    assert plain == cached_cold == cached_warm


def test_cache_invalidates_on_cc_companion_edit(tmp_path):
    # review regression (verified live by the reviewer): a C++-only ABI
    # edit must invalidate the cached project pass — the warm cache must
    # never report a drifted ScanArgs as clean
    import shutil

    tree = str(tmp_path / "native")
    os.makedirs(tree)
    native = os.path.join(REPO, "opensim_tpu", "native")
    for name in ("__init__.py", "serial.py", "scan_engine.cc", "serial_engine.cc"):
        shutil.copy(os.path.join(native, name), os.path.join(tree, name))
    cache = str(tmp_path / "cache.json")
    assert lint_paths([tree], rules=["abi-parity"], cache_path=cache) == []
    # warm reuse first
    stats: dict = {}
    assert lint_paths([tree], rules=["abi-parity"], stats=stats, cache_path=cache) == []
    assert stats["project_pass"] == "reused"
    # now drift the C++ side ONLY
    cc = os.path.join(tree, "scan_engine.cc")
    src = open(cc).read()
    open(cc, "w").write(src.replace("Hp, Hports,", "Hports, Hp,"))
    findings = lint_paths([tree], rules=["abi-parity"], cache_path=cache)
    assert [f.code for f in findings] == ["OSL1604"], "warm cache hid the C++ drift"


def test_cache_scoped_run_does_not_evict_other_entries(tmp_path):
    # review regression: `simon lint <subdir> --cache shared.json` must not
    # wipe the full-run cache (prune only drops entries for DELETED files)
    tree = str(tmp_path / "proj")
    cache = str(tmp_path / "cache.json")
    _write_tree(tree, {"a/x.py": "x = 1\n", "b/y.py": "y = 2\n"})
    stats: dict = {}
    lint_paths([tree], stats=stats, cache_path=cache)
    # scoped run over a/ only
    lint_paths([os.path.join(tree, "a")], cache_path=cache)
    stats2: dict = {}
    lint_paths([tree], stats=stats2, cache_path=cache)
    assert stats2["cache_hits"] == 2, "scoped run evicted the sibling's entry"
    assert stats2["project_pass"] == "reused", "scoped run clobbered the project slot"
