"""Campaign engine tests (ISSUE 13): spec validation, PDB carry semantics,
warm-delta vs cold-prepare fingerprint equality, determinism across runs,
step behavior, report parity, the REST surface, and lint rule OSL1501."""

import json
import os
import textwrap

import pytest

from opensim_tpu.models import fixtures as fx
from opensim_tpu.models.objects import (
    PodDisruptionBudget,
    ResourceTypes,
    object_from_dict,
)
from opensim_tpu.planner import campaign as cp
from opensim_tpu.planner import report as report_mod


def make_cluster(n_nodes=5, web=6, api=3, pdb_min_available=None, pdb_selector=None):
    rt = ResourceTypes()
    for i in range(n_nodes):
        rt.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    if web:
        rt.deployments.append(fx.make_fake_deployment("web", web, "1", "2Gi"))
    if api:
        rt.deployments.append(fx.make_fake_deployment("api", api, "500m", "1Gi"))
    if pdb_min_available is not None:
        rt.pdbs.append(
            PodDisruptionBudget.from_dict(
                {
                    "apiVersion": "policy/v1",
                    "kind": "PodDisruptionBudget",
                    "metadata": {"name": "web-pdb", "namespace": "default"},
                    "spec": {
                        "minAvailable": pdb_min_available,
                        "selector": pdb_selector or {"matchLabels": {"app": "web"}},
                    },
                }
            )
        )
    return rt


MIXED_STEPS = [
    {"name": "upgrade", "type": "drain-wave", "nodes": ["n0", "n1"], "wave": 1},
    {"name": "storm", "type": "reclaim-storm", "nodes": ["n2"]},
    {
        "name": "push",
        "type": "deploy",
        "app": {"name": "canary"},
        "resources": [
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "canary", "namespace": "default"},
                "spec": {
                    "replicas": 3,
                    "selector": {"matchLabels": {"app": "canary"}},
                    "template": {
                        "metadata": {"labels": {"app": "canary"}},
                        "spec": {
                            "containers": [
                                {
                                    "name": "c",
                                    "resources": {
                                        "requests": {"cpu": "250m", "memory": "512Mi"}
                                    },
                                }
                            ]
                        },
                    },
                },
            }
        ],
    },
    {"name": "shrink", "type": "scale-down-check"},
]


# ---------------------------------------------------------------------------
# PodDisruptionBudget model
# ---------------------------------------------------------------------------


def test_pdb_model_parses_and_computes_budgets():
    pdb = PodDisruptionBudget.from_dict(
        {
            "kind": "PodDisruptionBudget",
            "metadata": {"name": "p", "namespace": "ns"},
            "spec": {"minAvailable": "50%", "selector": {"matchLabels": {"a": "b"}}},
        }
    )
    assert pdb.key() == "ns/p"
    assert pdb.selects()
    assert pdb.disruptions_allowed(healthy=4, expected=4) == 2
    assert pdb.disruptions_allowed(healthy=2, expected=4) == 0  # never negative
    assert pdb.matches(
        fx.make_fake_pod("x", "100m", "128Mi", fx.with_namespace("ns"), fx.with_labels({"a": "b"}))
    )
    assert not pdb.matches(
        fx.make_fake_pod("x", "100m", "128Mi", fx.with_labels({"a": "b"}))
    )  # wrong namespace

    mu = PodDisruptionBudget.from_dict(
        {"kind": "PodDisruptionBudget", "metadata": {"name": "m"},
         "spec": {"maxUnavailable": 1, "selector": {"matchLabels": {"a": "b"}}}}
    )
    assert mu.disruptions_allowed(healthy=4, expected=4) == 1
    # empty selector matches nothing; no spec fields = unlimited
    empty = PodDisruptionBudget.from_dict(
        {"kind": "PodDisruptionBudget", "metadata": {"name": "e"}, "spec": {}}
    )
    assert not empty.selects()
    assert empty.disruptions_allowed(0, 0) > 1_000_000


def test_pdb_typed_decode_everywhere():
    # object_from_dict routes the kind to the typed model
    obj = object_from_dict({"kind": "PodDisruptionBudget", "metadata": {"name": "x"}})
    assert isinstance(obj, PodDisruptionBudget)
    # the snapshot table decodes PDBs typed (live-twin campaigns see real
    # budgets), still optional (403-tolerant) like services/config_maps
    from opensim_tpu.server.snapshot import RESOURCE_BY_FIELD

    spec = RESOURCE_BY_FIELD["pdbs"]
    assert spec.optional
    assert isinstance(spec.wrap({"kind": "PodDisruptionBudget"}), PodDisruptionBudget)


# ---------------------------------------------------------------------------
# spec validation: typed errors naming the step and field
# ---------------------------------------------------------------------------


def test_spec_validation_unknown_type():
    with pytest.raises(cp.CampaignError) as ei:
        cp.parse_steps([{"type": "explode"}])
    # 1-based, matching the executed report's indices (baseline = 0)
    assert ei.value.step == "1"
    assert ei.value.field == "type"
    assert "drain-wave" in str(ei.value)  # names the known types


def test_spec_validation_unknown_field_names_step_and_field():
    with pytest.raises(cp.CampaignError) as ei:
        cp.parse_steps([{"name": "d", "type": "drain-wave", "nodes": ["n0"], "wavee": 2}])
    assert ei.value.step == "1 (d)"
    assert ei.value.field == "wavee"


def test_step_numbers_match_report_indices():
    """Spec step N's validation errors and its report row agree on N."""
    steps = cp.parse_steps(MIXED_STEPS)
    res = cp.run_campaign(make_cluster(), steps, mode="warm")
    for step, rep in zip(steps, res.steps[1:]):
        assert step.index == rep.index


def test_drain_wave_cap_is_typed_error(monkeypatch):
    """More planned waves than OPENSIM_CAMPAIGN_MAX_WAVES is a loud typed
    error up front — never a silently-abandoned target tail."""
    monkeypatch.setenv("OPENSIM_CAMPAIGN_MAX_WAVES", "2")
    steps = cp.parse_steps(
        [{"name": "big", "type": "drain-wave", "nodes": ["n0", "n1", "n2"], "wave": 1}]
    )
    with pytest.raises(cp.CampaignError) as ei:
        cp.run_campaign(make_cluster(), steps, mode="warm")
    assert ei.value.field == "wave"
    assert "MAX_WAVES" in str(ei.value)


def test_spec_validation_field_shapes():
    with pytest.raises(cp.CampaignError) as ei:
        cp.parse_steps([{"type": "drain-wave", "nodes": ["n0"], "wave": 0}])
    assert ei.value.field == "wave"
    with pytest.raises(cp.CampaignError) as ei:
        cp.parse_steps([{"type": "drain-wave"}])
    assert ei.value.field == "nodes"
    with pytest.raises(cp.CampaignError) as ei:
        cp.parse_steps([{"type": "scale", "workload": {"name": "w"}, "replicas": "many"}])
    assert ei.value.field == "replicas"
    with pytest.raises(cp.CampaignError) as ei:
        cp.parse_steps([{"type": "add-nodes", "count": 2}])
    assert ei.value.field == "template"
    with pytest.raises(cp.CampaignError) as ei:
        cp.parse_steps("not-a-list")
    assert ei.value.field == "steps"


def test_spec_validation_unknown_node_at_run_time():
    steps = cp.parse_steps([{"type": "drain-wave", "nodes": ["ghost"]}])
    with pytest.raises(cp.CampaignError) as ei:
        cp.run_campaign(make_cluster(), steps, mode="warm")
    assert ei.value.field == "nodes"
    assert "ghost" in str(ei.value)


def test_spec_max_steps_bound(monkeypatch):
    monkeypatch.setenv("OPENSIM_CAMPAIGN_MAX_STEPS", "2")
    with pytest.raises(cp.CampaignError) as ei:
        cp.parse_steps([{"type": "scale-down-check"}] * 3)
    assert ei.value.field == "steps"


# ---------------------------------------------------------------------------
# determinism + warm-vs-cold delta gate
# ---------------------------------------------------------------------------


def test_campaign_deterministic_across_runs():
    steps = cp.parse_steps(MIXED_STEPS)
    r1 = cp.run_campaign(make_cluster(pdb_min_available=4), steps, mode="warm")
    r2 = cp.run_campaign(make_cluster(pdb_min_available=4), cp.parse_steps(MIXED_STEPS), mode="warm")
    assert [s.fingerprint for s in r1.steps] == [s.fingerprint for s in r2.steps]
    assert r1.fingerprint == r2.fingerprint


def test_campaign_warm_delta_equals_cold_prepare():
    """The delta-execution acceptance gate: a mixed 4-step campaign's step
    fingerprints are bit-identical between warm (one full prepare +
    prepcache deltas) and cold (per-step full prepare) execution."""
    steps = cp.parse_steps(MIXED_STEPS)
    warm = cp.run_campaign(make_cluster(pdb_min_available=4), steps, mode="warm")
    cold = cp.run_campaign(
        make_cluster(pdb_min_available=4), cp.parse_steps(MIXED_STEPS), mode="cold"
    )
    assert warm.full_prepares == 1  # the contract: ONE full prepare per campaign
    assert cold.full_prepares > 1
    assert [s.fingerprint for s in warm.steps] == [s.fingerprint for s in cold.steps]
    assert warm.fingerprint == cold.fingerprint
    # the campaign actually did lifecycle work
    assert warm.steps[1].evicted > 0
    assert warm.steps[3].pods_added == 3
    assert len(warm.steps) == 5


def test_campaign_warm_cold_with_daemonsets_and_add_nodes():
    """DaemonSet splice order (warm extend_with_nodes) must match the cold
    expansion order, and added nodes get run-stable ids."""
    def cluster():
        rt = make_cluster(n_nodes=4, web=4, api=0)
        rt.daemon_sets.append(fx.make_fake_daemon_set("agent", "100m", "128Mi"))
        return rt

    raw = [
        {"type": "reclaim-storm", "nodes": ["n1"]},
        {"type": "add-nodes", "count": 2, "template": {"node": "n0"}},
    ]
    warm = cp.run_campaign(cluster(), cp.parse_steps(raw), mode="warm")
    cold = cp.run_campaign(cluster(), cp.parse_steps(raw), mode="cold")
    assert [s.fingerprint for s in warm.steps] == [s.fingerprint for s in cold.steps]
    add = warm.steps[2]
    assert add.nodes_added == ["added#0", "added#1"]  # run-stable ids
    # the new nodes' DaemonSet pods landed (one per added node)
    assert add.rescheduled >= 2


# ---------------------------------------------------------------------------
# PDB carry semantics
# ---------------------------------------------------------------------------


def test_pdb_blocked_eviction_never_dropped():
    """minAvailable == replicas: zero disruptions allowed, ever. The drain
    must report the blocked eviction loudly and leave the node cordoned —
    never silently drop the eviction or the pod."""
    cluster = make_cluster(n_nodes=3, web=3, api=0, pdb_min_available=3)
    steps = cp.parse_steps([{"type": "drain-wave", "nodes": ["n0"], "wave": 1}])
    res = cp.run_campaign(cluster, steps, mode="warm")
    s = res.steps[1]
    assert s.evicted == 0
    assert s.blocked, "blocked eviction must be reported"
    assert s.blocked[0]["pdb"] == "default/web-pdb"
    assert s.nodes_cordoned == ["n0"]
    assert s.nodes_drained == []  # the node never emptied
    assert s.pdb_allowed["default/web-pdb"] == 0
    # the pod is still alive and still placed (phase never lost)
    cap = s.capacity
    assert cap["pods_bound"] == 3 and cap["pods_pending"] == 0


def test_pdb_budget_recovers_across_waves():
    """minAvailable N-1: one disruption at a time. Draining two nodes must
    proceed wave by wave, deferring blocked evictions to the next wave as
    the budget recovers (the rescheduled pod turns healthy again)."""
    cluster = make_cluster(n_nodes=4, web=4, api=0, pdb_min_available=3)
    steps = cp.parse_steps([{"type": "drain-wave", "nodes": ["n0", "n1"], "wave": 1}])
    res = cp.run_campaign(cluster, steps, mode="warm")
    s = res.steps[1]
    assert not s.blocked  # everything eventually evicted
    assert sorted(s.nodes_drained) == ["n0", "n1"]
    assert s.pdb_spent["default/web-pdb"] == s.evicted
    assert s.waves >= 2  # the carry forced extra passes
    cold = cp.run_campaign(
        make_cluster(n_nodes=4, web=4, api=0, pdb_min_available=3),
        cp.parse_steps([{"type": "drain-wave", "nodes": ["n0", "n1"], "wave": 1}]),
        mode="cold",
    )
    assert res.fingerprint == cold.fingerprint


def test_reclaim_storm_ignores_pdbs():
    """Budgets guard voluntary evictions, not node failure: a reclaim storm
    displaces PDB-guarded pods regardless."""
    cluster = make_cluster(n_nodes=3, web=3, api=0, pdb_min_available=3)
    steps = cp.parse_steps([{"type": "reclaim-storm", "nodes": ["n0"]}])
    res = cp.run_campaign(cluster, steps, mode="warm")
    s = res.steps[1]
    assert s.evicted >= 1 and not s.blocked
    assert s.nodes_removed == ["n0"]


# ---------------------------------------------------------------------------
# step behavior
# ---------------------------------------------------------------------------


def test_scale_step_down_and_up():
    cluster = make_cluster(n_nodes=4, web=6, api=0)
    raw = [
        {"type": "scale", "workload": {"kind": "Deployment", "name": "web"}, "replicas": 2},
        {"type": "scale", "workload": {"kind": "Deployment", "name": "web"}, "replicas": 5},
    ]
    res = cp.run_campaign(cluster, cp.parse_steps(raw), mode="warm")
    down, up = res.steps[1], res.steps[2]
    assert down.deleted == 4 and down.capacity["pods_bound"] == 2
    assert up.pods_added == 3 and up.capacity["pods_bound"] == 5
    cold = cp.run_campaign(
        make_cluster(n_nodes=4, web=6, api=0), cp.parse_steps(raw), mode="cold"
    )
    assert res.fingerprint == cold.fingerprint


def test_scale_up_workload_deployed_in_campaign():
    """A later scale step can grow an app a deploy step introduced (the
    deployed workloads join the scale lookup book)."""
    raw = list(MIXED_STEPS[2:3]) + [  # the canary deploy (3 replicas)
        {"type": "scale", "workload": {"kind": "Deployment", "name": "canary"}, "replicas": 6}
    ]
    res = cp.run_campaign(make_cluster(n_nodes=4, web=2, api=0), cp.parse_steps(raw), mode="warm")
    assert res.steps[1].pods_added == 3
    assert res.steps[2].pods_added == 3  # scale 3 -> 6
    assert res.steps[2].capacity["pods_bound"] == 2 + 6


def test_scale_unknown_workload_is_typed_error():
    steps = cp.parse_steps(
        [{"type": "scale", "workload": {"kind": "Deployment", "name": "ghost"}, "replicas": 9}]
    )
    with pytest.raises(cp.CampaignError) as ei:
        cp.run_campaign(make_cluster(), steps, mode="warm")
    assert ei.value.field == "workload"


def test_add_nodes_recovers_pending_pods():
    """Storm shrinks the cluster below fit; add-nodes must re-place the
    pending pods (the autoscaler-response scenario)."""
    cluster = make_cluster(n_nodes=3, web=9, api=0)  # ~3 per node at 1 cpu... fits
    raw = [
        {"type": "reclaim-storm", "nodes": ["n1", "n2"]},
        {"type": "add-nodes", "count": 2, "template": {"node": "n0"}},
    ]
    res = cp.run_campaign(cluster, cp.parse_steps(raw), mode="warm")
    storm, grow = res.steps[1], res.steps[2]
    assert storm.unschedulable, "the storm must overflow the remaining node"
    assert grow.capacity["pods_pending"] == 0, "add-nodes must re-place the pending pods"
    assert not grow.unschedulable


def test_scale_down_check_is_pure():
    cluster = make_cluster(n_nodes=4, web=4, api=2, pdb_min_available=4)
    raw = [{"type": "scale-down-check"}, {"type": "scale-down-check"}]
    res = cp.run_campaign(cluster, cp.parse_steps(raw), mode="warm")
    s1, s2 = res.steps[1], res.steps[2]
    assert s1.fingerprint == res.steps[0].fingerprint == s2.fingerprint  # no mutation
    assert s1.checks and [c["node"] for c in s1.checks] == [c["node"] for c in s2.checks]
    assert all(set(c) >= {"node", "removable", "pods", "unschedulable", "pdbBlocked"} for c in s1.checks)
    # web pods are pinned at minAvailable: their nodes must be pdb-blocked
    assert any(c["pdbBlocked"] for c in s1.checks)


def test_defrag_step_executes_removable_plan():
    # half-empty cluster: defrag should find and drain at least one node
    cluster = make_cluster(n_nodes=5, web=3, api=0)
    res = cp.run_campaign(
        cluster, cp.parse_steps([{"type": "defrag", "maxNodes": 2, "wave": 1}]), mode="warm"
    )
    s = res.steps[1]
    assert s.checks  # the plan's verdicts are reported
    assert s.nodes_drained, "an underloaded cluster must yield at least one drain"
    assert s.capacity["nodes"] == 5 - len(s.nodes_drained)
    assert not s.unschedulable


def test_from_journal_step(tmp_path):
    """A recorded generation range replays through the campaign apply path:
    bound adds force-bind, unbound adds schedule, deletes free capacity."""
    from opensim_tpu.server.journal import Journal

    jdir = str(tmp_path / "journal")
    j = Journal(jdir, policy={"fsync": "off"})
    try:
        rv = 100
        gen = 10
        for i in range(4):
            rv += 1
            gen += 1
            pod = {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"j-{i}", "namespace": "default",
                             "resourceVersion": str(rv)},
                "spec": {"containers": [
                    {"name": "c", "resources": {"requests": {"cpu": "500m", "memory": "1Gi"}}}
                ]},
                "status": {"phase": "Pending"},
            }
            if i < 2:
                pod["spec"]["nodeName"] = "n1"
                pod["status"]["phase"] = "Running"
            j.record_event("pods", "ADDED", pod, gen)
        rv += 1
        gen += 1
        j.record_event(
            "pods", "DELETED",
            {"metadata": {"name": "j-0", "namespace": "default", "resourceVersion": str(rv)}},
            gen,
        )
    finally:
        j.close()

    cluster = make_cluster(n_nodes=3, web=2, api=0)
    raw = [{"type": "from-journal", "journal": jdir, "fromGeneration": 10, "toGeneration": 15}]
    res = cp.run_campaign(cluster, cp.parse_steps(raw), mode="warm")
    s = res.steps[1]
    assert s.journal_events == 5
    # NET effect of the range: j-0 was added then deleted inside the
    # window, so it never materializes (3 admissions, no deletion of a
    # pre-existing pod)
    assert s.pods_added == 3 and s.deleted == 0
    # 3 journal pods survive: j-1 bound to its recorded node, j-2/j-3 scheduled
    assert s.capacity["pods_bound"] == 2 + 3 and not s.unschedulable
    cold = cp.run_campaign(
        make_cluster(n_nodes=3, web=2, api=0), cp.parse_steps(raw), mode="cold"
    )
    assert res.fingerprint == cold.fingerprint


def test_from_journal_node_modify_reported_not_silent(tmp_path):
    """A MODIFIED event for a node the campaign already tracks is outside
    the delta envelope (in-place capacity change): it must be reported
    loudly in the step output, never silently replayed with stale alloc."""
    from opensim_tpu.server.journal import Journal

    jdir = str(tmp_path / "journal")
    j = Journal(jdir, policy={"fsync": "off"})
    try:
        j.record_event(
            "nodes", "MODIFIED",
            fx.make_fake_node("n0", "4", "8Gi").raw | {"metadata": {"name": "n0", "resourceVersion": "7"}},
            11,
        )
    finally:
        j.close()
    steps = cp.parse_steps([{"type": "from-journal", "journal": jdir, "fromGeneration": 10}])
    res = cp.run_campaign(make_cluster(n_nodes=2, web=1, api=0), steps, mode="warm")
    s = res.steps[1]
    assert s.journal_events == 1
    assert any("MODIFIED skipped" in u["reason"] for u in s.unschedulable)


def test_from_journal_generation_window():
    steps = cp.parse_steps(
        [{"type": "from-journal", "journal": "/nonexistent", "fromGeneration": 1}]
    )
    with pytest.raises(cp.CampaignError) as ei:
        cp.run_campaign(make_cluster(n_nodes=2, web=1, api=0), steps, mode="warm")
    assert ei.value.field == "journal"


# ---------------------------------------------------------------------------
# report parity + surfaces
# ---------------------------------------------------------------------------


def test_campaign_report_parity():
    """The JSON ``table`` section and the text renderer serialize the SAME
    rows (the byte-parity contract every report table follows)."""
    import io

    res = cp.run_campaign(
        make_cluster(pdb_min_available=4), cp.parse_steps(MIXED_STEPS), mode="warm"
    )
    d = res.to_dict()
    rows = report_mod.campaign_step_rows(d["steps"])
    assert [d["table"]["header"]] + d["table"]["rows"] == rows
    out = io.StringIO()
    report_mod.render_campaign(d, out)
    text = out.getvalue()
    # every cell of every row appears verbatim in the rendered table
    for row in rows:
        for cell in row:
            assert cell == "" or cell in text
    assert d["fingerprint"] in text
    # round-trips as JSON
    json.loads(json.dumps(d))


def test_drain_plan_rows_parity():
    from opensim_tpu.planner.defrag import DrainPlan

    plans = [
        DrainPlan(node="n0", feasible=True, unscheduled=0, freed_cpu_milli=8000, freed_memory=2**34),
        DrainPlan(node="n1", feasible=False, unscheduled=3, freed_cpu_milli=4000, freed_memory=2**33),
    ]
    rows = report_mod.drain_plan_rows(plans)
    assert rows[0] == ["Node", "Drainable", "Unscheduled", "Freed CPU", "Freed Memory"]
    assert rows[1][0] == "n0" and rows[1][1] == "√"
    assert rows[2][1] == "" and rows[2][2] == "3"


def test_rest_campaign_endpoint():
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=make_cluster(pdb_min_available=4))
    code, body = server.run_campaign({"name": "t", "steps": MIXED_STEPS})
    assert code == 200
    assert body["fullPrepares"] == 1
    assert len(body["steps"]) == 5
    assert body["table"]["rows"]
    # typed validation errors surface as 400 naming the step/field
    code, body = server.run_campaign({"steps": [{"type": "explode"}]})
    assert code == 400 and body["field"] == "type" and body["step"] == "1"
    code, body = server.run_campaign({"steps": MIXED_STEPS, "mode": "tepid"})
    assert code == 400 and body["field"] == "mode"


def test_campaign_env_knobs_registered():
    from opensim_tpu.utils import envknobs

    for name in (
        "OPENSIM_CAMPAIGN_EXEC",
        "OPENSIM_CAMPAIGN_MAX_STEPS",
        "OPENSIM_CAMPAIGN_MAX_WAVES",
    ):
        assert name in envknobs.KNOBS
        envknobs.value(name)  # default parses through its validator


# ---------------------------------------------------------------------------
# OSL1501 campaign-step-registry
# ---------------------------------------------------------------------------


def _codes(src, path="opensim_tpu/server/rest.py"):
    from opensim_tpu.analysis import lint_source

    return [f.code for f in lint_source(textwrap.dedent(src), path=path, rules=["campaign-step-registry"])]


def test_osl1501_fires_on_adhoc_dispatch():
    assert _codes('if step == "drain-wave":\n    go()\n') == ["OSL1501"]
    assert _codes('if kind in ("reclaim-storm", "scale-down-check"):\n    go()\n') == [
        "OSL1501",
        "OSL1501",
    ]
    assert _codes("register_step('mine')(cls)\n") == ["OSL1501"]


def test_osl1501_quiet_on_legit_uses():
    # dict literals (specs under test, bench scenarios) are not dispatch
    assert _codes('spec = {"type": "drain-wave", "wave": 1}\n') == []
    # the generic short names stay usable for REST kinds / CLI commands
    assert _codes('if kind == "deploy" or cmd == "defrag":\n    go()\n') == []
    # the registry module itself is excluded
    assert (
        _codes('if t == "drain-wave":\n    pass\n', path="opensim_tpu/planner/campaign.py") == []
    )


def test_osl1501_suppression_and_sync():
    assert _codes('if s == "from-journal":  # opensim-lint: disable=campaign-step-registry\n    go()\n') == []
    from opensim_tpu.analysis.rules_campaign import DISPATCH_LITERALS

    # the rule's literal set tracks the live registry (subset: the short
    # generic names are deliberately excluded from literal matching)
    assert DISPATCH_LITERALS <= set(cp.STEP_TYPES)


def test_repo_swept_clean_for_osl1501():
    from opensim_tpu.analysis import lint_paths

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = lint_paths([os.path.join(repo, "opensim_tpu")], rules=["campaign-step-registry"])
    assert findings == []


def test_resolve_path_rejects_control_characters_as_typed_error():
    # PR 14: _resolve_path is the campaign's registered taint validator
    # (OSL1603); rejections must stay CampaignError so the REST surface
    # renders the typed 400, never a generic 500
    with pytest.raises(cp.CampaignError):
        cp._resolve_path("bad\tpath")
    with pytest.raises(cp.CampaignError):
        cp.load_campaign_cluster(
            cp.CampaignSpec(name="x", steps=[], cluster={"customConfig": "a\nb"})
        )
    assert cp._resolve_path("plain/relative.yaml") == "plain/relative.yaml"


def test_remote_campaigns_reject_server_side_paths():
    # review hardening: a REST campaign naming a filesystem path must get a
    # typed 400-shaped CampaignError, never a server-side open(). Deploy
    # steps resolve their path at RUN time, so the gate guards the whole
    # evaluation (rest.py wraps parse AND run in remote_spec_context).
    with cp.remote_spec_context():
        with pytest.raises(cp.CampaignError) as ei:
            cp._resolve_path("/etc/passwd")
    assert "REST" in str(ei.value)
    # file-mode (trusted CLI) resolution still works
    assert cp._resolve_path("apps/app.yaml") == "apps/app.yaml"


def test_child_path_rejects_spec_dir_escape():
    from opensim_tpu.utils import validate

    with pytest.raises(ValueError):
        validate.child_path("/specs/dir", "../../etc/passwd")
    assert validate.child_path("/specs/dir", "sub/app.yaml") == "/specs/dir/sub/app.yaml"
    assert validate.child_path("/specs/dir", "/abs/path.yaml") == "/abs/path.yaml"
