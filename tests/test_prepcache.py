"""Incremental prepare (opensim_tpu/engine/prepcache.py): encode-cache
hit/miss/invalidation behavior, and the correctness bar of the delta
re-encoders — placements byte-identical to a full re-encode, fuzz-corpus
included."""

import copy
import json
import random
import threading
import urllib.request
from contextlib import contextmanager

import numpy as np
import pytest

from opensim_tpu.engine import prepcache
from opensim_tpu.engine.simulator import AppResource, prepare, simulate
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.models.expand import new_fake_nodes
from opensim_tpu.utils.trace import PREP_STATS


def _cluster(n_nodes=8, with_ds=False):
    rt = ResourceTypes()
    for i in range(n_nodes):
        rt.nodes.append(
            fx.make_fake_node(
                f"n{i:03d}", "16", "64Gi", "110",
                fx.with_labels(
                    {
                        "topology.kubernetes.io/zone": f"z{i % 3}",
                        "disk": "ssd" if i % 2 else "hdd",
                    }
                ),
            )
        )
    if with_ds:
        rt.daemon_sets.append(fx.make_fake_daemon_set("logd", "100m", "128Mi"))
    rt.pods.append(fx.make_fake_pod("pinned", "100m", "128Mi", fx.with_node_name("n000")))
    return rt


def _apps():
    rt = ResourceTypes()
    rt.deployments.append(
        fx.make_fake_deployment("web", 10, "500m", "1Gi", fx.with_node_selector({"disk": "ssd"}))
    )
    rt.deployments.append(
        fx.make_fake_deployment(
            "db", 4, "1", "2Gi",
            fx.with_topology_spread(
                [
                    {
                        "maxSkew": 1,
                        "topologyKey": "topology.kubernetes.io/zone",
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": "db"}},
                    }
                ]
            ),
        )
    )
    return [AppResource("a", rt)]


def _placements(prep):
    """(stream-position → node name, sorted reasons) after a simulate —
    pod names are randomized per expansion, so positionwise node names are
    the strongest comparable signal."""
    return [p.spec.node_name for p in prep.ordered]


def _result_shape(res):
    return (
        [(ns.node.metadata.name, len(ns.pods)) for ns in res.node_status],
        sorted(u.reason for u in res.unscheduled_pods),
    )


# ---------------------------------------------------------------------------
# cache behavior
# ---------------------------------------------------------------------------


def test_cache_hit_miss_eviction_invalidation():
    cache = prepcache.PrepareCache(capacity=2)
    assert cache.get("a") is None
    cache.put("a", prepcache.CacheEntry("a", None))
    cache.put("b", prepcache.CacheEntry("b", None))
    assert cache.get("a") is not None and cache.get("b") is not None
    cache.put("c", prepcache.CacheEntry("c", None))  # evicts LRU ("a")
    assert cache.get("a") is None
    assert cache.stats.evictions == 1
    assert cache.invalidate("b") == 1
    assert cache.get("b") is None
    assert cache.stats.hits == 2 and cache.stats.invalidations == 1


def test_fingerprint_tracks_cluster_content():
    rt = _cluster()
    fp0 = prepcache.fingerprint_cluster(rt)
    assert fp0 == prepcache.fingerprint_cluster(rt)  # stable
    rt2 = copy.copy(rt)
    rt2.nodes = rt.nodes + [fx.make_fake_node("extra", "8", "16Gi")]
    assert prepcache.fingerprint_cluster(rt2) != fp0
    rt3 = copy.copy(rt)
    rt3.pods = rt.pods + [fx.make_fake_pod("p2", "100m", "128Mi")]
    assert prepcache.fingerprint_cluster(rt3) != fp0
    assert prepcache.fingerprint_apps(_apps()) == prepcache.fingerprint_apps(_apps())


def test_simulate_cached_second_call_is_a_hit():
    cluster, apps = _cluster(), _apps()
    cache = prepcache.PrepareCache()
    r1 = prepcache.simulate_cached(cluster, apps, cache)
    PREP_STATS.reset()
    r2 = prepcache.simulate_cached(cluster, apps, cache)
    snap = PREP_STATS.snapshot()
    assert snap["counts"].get("hit") == 1 and "full" not in snap["counts"]
    assert cache.stats.hits == 1 and cache.stats.misses == 1
    assert _result_shape(r1) == _result_shape(r2)
    # third call still pristine (bind-state restored between uses)
    r3 = prepcache.simulate_cached(cluster, apps, cache)
    assert _result_shape(r1) == _result_shape(r3)


# ---------------------------------------------------------------------------
# delta re-encode == full re-encode
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("with_ds", [False, True])
def test_derive_with_apps_matches_full_prepare(with_ds):
    cluster, apps = _cluster(with_ds=with_ds), _apps()
    full = prepare(cluster, apps)
    r_full = simulate(cluster, apps, prep=full)

    base = prepare(cluster, [])
    entry = prepcache.CacheEntry("base", base)
    derived = prepcache.derive_with_apps(base, cluster, apps, base_entry=entry)
    assert len(derived.ordered) == len(full.ordered)
    r_delta = simulate(cluster, apps, prep=derived)
    assert _result_shape(r_full) == _result_shape(r_delta)
    assert _placements(full) == _placements(derived)


@pytest.mark.parametrize("with_ds", [False, True])
def test_extend_with_nodes_matches_full_prepare(with_ds):
    cluster, apps = _cluster(n_nodes=6, with_ds=with_ds), _apps()
    template = fx.make_fake_node(
        "tpl", "32", "128Gi", "110",
        fx.with_labels({"topology.kubernetes.io/zone": "z9", "disk": "ssd"}),
    )
    candidates = new_fake_nodes(template, 4)
    full_cluster = copy.copy(cluster)
    full_cluster.nodes = list(cluster.nodes) + candidates

    prep_fresh = prepare(full_cluster, apps)
    prep_base = prepare(cluster, apps)
    prep_ext = prepcache.extend_with_nodes(prep_base, candidates, cluster, apps)
    assert prep_ext is not None
    assert len(prep_ext.ordered) == len(prep_fresh.ordered)
    assert prep_ext.ds_target == prep_fresh.ds_target

    r1 = simulate(full_cluster, apps, prep=prep_fresh)
    r2 = simulate(full_cluster, apps, prep=prep_ext)
    assert _result_shape(r1) == _result_shape(r2)
    assert _placements(prep_fresh) == _placements(prep_ext)

    # masked re-simulation (the planner's final step) must agree too
    N = int(np.asarray(prep_ext.ec_np.node_valid).shape[0])
    sub = copy.copy(cluster)
    sub.nodes = list(cluster.nodes) + candidates[:2]
    mask = np.zeros(N, dtype=bool)
    mask[: len(sub.nodes)] = True
    m1 = simulate(sub, apps, prep=prep_fresh, node_valid=mask[: np.asarray(prep_fresh.ec_np.node_valid).shape[0]])
    m2 = simulate(sub, apps, prep=prep_ext, node_valid=mask)
    assert _result_shape(m1) == _result_shape(m2)


def test_extend_declines_greed_and_app_daemonsets():
    cluster, apps = _cluster(n_nodes=4), _apps()
    template = fx.make_fake_node("tpl", "8", "16Gi")
    prep_base = prepare(cluster, apps)
    assert prepcache.extend_with_nodes(prep_base, new_fake_nodes(template, 2), cluster, apps, use_greed=True) is None
    ds_app = ResourceTypes()
    ds_app.daemon_sets.append(fx.make_fake_daemon_set("agent", "50m", "64Mi"))
    assert (
        prepcache.extend_with_nodes(
            prep_base, new_fake_nodes(template, 2), cluster, [AppResource("d", ds_app)]
        )
        is None
    )


def test_drop_mask_matches_filtered_cluster():
    """scale-apps as a valid-mask flip: masking the scaled workload's bare
    pods out of a cached prep == re-preparing the filtered cluster."""
    cluster = _cluster()
    owned = fx.make_fake_pod("web-1", "500m", "1Gi", fx.with_node_name("n001"))
    from opensim_tpu.models.objects import OwnerReference

    owned.metadata.owner_references = [
        OwnerReference(kind="Deployment", name="web", uid="u1", controller=True)
    ]
    cluster.pods.append(owned)
    apps = _apps()
    scaled = {("Deployment", "default", "web")}

    from opensim_tpu.server.rest import _owned_by

    filtered = copy.copy(cluster)
    filtered.pods = [p for p in cluster.pods if not _owned_by(p, scaled)]
    r_fresh = simulate(filtered, apps)

    base = prepare(cluster, [])
    derived = prepcache.derive_with_apps(base, filtered, apps)
    drop = prepcache.drop_mask_for_scaled(derived, _owned_by, scaled)
    assert drop.sum() == 1
    r_masked = simulate(filtered, apps, prep=derived, drop_pods=drop)
    assert _result_shape(r_fresh)[1] == _result_shape(r_masked)[1]
    # node pod COUNTS: fresh result has no row for the dropped pod at all
    assert {n: c for n, c in _result_shape(r_fresh)[0]} == {
        n: c for n, c in _result_shape(r_masked)[0]
    }


def test_delta_vs_full_on_fuzz_corpus():
    """The fastpath-fuzz generators (every supported feature mixed) through
    both delta paths: placements must match a full re-encode exactly."""
    from test_fastpath_fuzz import random_app, random_cluster

    for seed in (3, 11, 42):
        rng = random.Random(seed)
        cluster = random_cluster(rng, rng.randrange(6, 12))
        apps = [AppResource("fuzz", random_app(rng, rng.randrange(2, 5)))]

        full = prepare(cluster, apps, node_pad=8)
        r_full = simulate(cluster, apps, prep=full)
        base = prepare(cluster, [], node_pad=8)
        if base is None:
            continue
        derived = prepcache.derive_with_apps(base, cluster, apps)
        r_delta = simulate(cluster, apps, prep=derived)
        assert _result_shape(r_full) == _result_shape(r_delta), f"seed {seed}"
        assert _placements(full) == _placements(derived), f"seed {seed}"

        template = fx.make_fake_node(
            "tpl", "16", "64Gi", "110",
            fx.with_labels({"topology.kubernetes.io/zone": "z0"}),
        )
        candidates = new_fake_nodes(template, 3)
        full_cluster = copy.copy(cluster)
        full_cluster.nodes = list(cluster.nodes) + candidates
        prep_fresh = prepare(full_cluster, apps, node_pad=8)
        prep_ext = prepcache.extend_with_nodes(full, candidates, cluster, apps)
        assert prep_ext is not None
        rf = simulate(full_cluster, apps, prep=prep_fresh)
        re_ = simulate(full_cluster, apps, prep=prep_ext)
        assert _result_shape(rf) == _result_shape(re_), f"seed {seed}"
        assert _placements(prep_fresh) == _placements(prep_ext), f"seed {seed}"


# ---------------------------------------------------------------------------
# REST: the second identical request skips re-encoding
# ---------------------------------------------------------------------------


@contextmanager
def _serve(server):
    from http.server import ThreadingHTTPServer

    from opensim_tpu.server.rest import make_handler

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield httpd.server_address[1]
    finally:
        httpd.shutdown()


def _metric(text, name):
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[-1])
    return None


def test_rest_second_identical_deploy_skips_reencode():
    from opensim_tpu.server.rest import SimonServer

    cluster = _cluster()
    server = SimonServer(base_cluster=cluster)
    assert server.prep_cache is not None
    body = json.dumps(
        {"deployments": [fx.make_fake_deployment("m", 3, "100m", "128Mi").raw]}
    ).encode()
    with _serve(server) as port:
        results = []
        for _ in range(2):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST"
            )
            with urllib.request.urlopen(req) as r:
                results.append(json.loads(r.read()))
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
            text = r.read().decode()
    # identical placements, and the second request hit the full-key entry
    assert results[0] == results[1]
    assert _metric(text, "simon_prep_cache_hits_total") >= 1
    assert _metric(text, "simon_prepare_seconds_total") > 0
    # the whole-request cache state: base entry + derived entry, one hit
    assert server.prep_cache.stats.hits >= 1


def test_rest_scale_apps_uses_drop_mask_and_matches_legacy(monkeypatch):
    """The cached scale-apps path must answer exactly like the legacy
    (full-prepare) path."""
    from opensim_tpu.server.rest import SimonServer

    cluster = _cluster()
    owned = fx.make_fake_pod("web-1", "500m", "1Gi", fx.with_node_name("n001"))
    from opensim_tpu.models.objects import OwnerReference

    owned.metadata.owner_references = [
        OwnerReference(kind="Deployment", name="web", uid="u1", controller=True)
    ]
    cluster.pods.append(owned)
    payload = {"deployments": [fx.make_fake_deployment("web", 4, "200m", "256Mi").raw]}

    cached = SimonServer(base_cluster=cluster)
    code1, resp1 = cached.scale_apps(payload)
    code1b, resp1b = cached.scale_apps(payload)  # second: full-key hit
    legacy = SimonServer(base_cluster=cluster, prep_cache=False)
    assert legacy.prep_cache is None
    code2, resp2 = legacy.scale_apps(payload)
    assert code1 == code1b == code2 == 200

    def shape(resp):
        return (
            sorted((e["node"], len(e["pods"])) for e in resp["nodeStatus"]),
            sorted(u["reason"] for u in resp["unscheduledPods"]),
        )

    assert shape(resp1) == shape(resp2)
    assert shape(resp1b) == shape(resp2)


# ---------------------------------------------------------------------------
# stale-fingerprint guard (VersionedObject / invalidate(obj))
# ---------------------------------------------------------------------------


def test_touch_without_invalidate_raises_stale_error():
    cluster, apps = _cluster(), _apps()
    cache = prepcache.PrepareCache()
    prepcache.simulate_cached(cluster, apps, cache)
    cluster.nodes[0].touch()  # in-place mutation marker, no invalidation
    with pytest.raises(prepcache.StaleFingerprintError, match="n000"):
        prepcache.simulate_cached(cluster, apps, cache)


def test_invalidate_object_drops_watching_entries():
    cluster, apps = _cluster(), _apps()
    cache = prepcache.PrepareCache()
    prepcache.simulate_cached(cluster, apps, cache)
    node = cluster.nodes[0]
    node.unschedulable = True
    node.touch()
    assert cache.invalidate(node) == 1
    assert len(cache) == 0
    # rebuild is clean and records the new version
    res = prepcache.simulate_cached(cluster, apps, cache)
    assert res is not None
    prepcache.simulate_cached(cluster, apps, cache)  # hit, no raise


def test_invalidate_object_covers_app_objects_and_misses_strangers():
    cluster, apps = _cluster(), _apps()
    cache = prepcache.PrepareCache()
    prepcache.simulate_cached(cluster, apps, cache)
    stranger = fx.make_fake_node("stranger", "8", "16Gi")
    assert cache.invalidate(stranger) == 0  # identity-keyed: not watched
    dep = apps[0].resources.deployments[0]
    dep.replicas += 1
    dep.touch()
    assert cache.invalidate(dep) == 1


def test_invalidate_prefix_still_works():
    cache = prepcache.PrepareCache()
    cache.put("abc|1", prepcache.CacheEntry("abc|1", None))
    cache.put("abd|2", prepcache.CacheEntry("abd|2", None))
    assert cache.invalidate("abc") == 1
    assert cache.invalidate() == 1  # '' drops the rest


def test_stale_entry_is_evicted_so_next_call_recovers():
    cluster, apps = _cluster(), _apps()
    cache = prepcache.PrepareCache()
    prepcache.simulate_cached(cluster, apps, cache)
    cluster.nodes[0].touch()
    with pytest.raises(prepcache.StaleFingerprintError):
        prepcache.simulate_cached(cluster, apps, cache)
    # the proven-stale entry was dropped: the same call now rebuilds
    res = prepcache.simulate_cached(cluster, apps, cache)
    assert res is not None
    prepcache.simulate_cached(cluster, apps, cache)  # and hits cleanly


def test_derived_entry_inherits_base_watch_list():
    base = prepcache.CacheEntry("base", None)
    base.watched = [(object(), 0)]
    derived = prepcache.CacheEntry("derived", None, base=base)
    assert derived.watched is base.watched


def test_invalidate_object_reaches_derived_entries():
    # REST-style topology: base entry watches the snapshot; the derived
    # full-key entry shares the watch list, so invalidate(obj) drops both
    cluster = _cluster()
    cache = prepcache.PrepareCache()
    base = cache.put(
        "fp|base",
        prepcache.CacheEntry("fp|base", None, watch=prepcache.watch_snapshot(cluster, [])),
    )
    cache.put("fp|deploy|x", prepcache.CacheEntry("fp|deploy|x", None, base=base))
    assert cache.invalidate(cluster.nodes[0]) == 2


def test_watch_snapshot_is_captured_before_build():
    # a touch() landing between fingerprint and entry creation (i.e. while
    # prepare() runs) must leave the entry provably stale, not fresh
    cluster, apps = _cluster(), _apps()
    snap = prepcache.watch_snapshot(cluster, apps)
    cluster.nodes[0].touch()  # races "during the build"
    entry = prepcache.CacheEntry("k", None, watch=snap)
    with pytest.raises(prepcache.StaleFingerprintError):
        entry.check_fresh()


def test_raw_objects_are_watched_too():
    from opensim_tpu.models.objects import RawObject

    cluster, apps = _cluster(), _apps()
    pdb = RawObject.from_dict({"kind": "PodDisruptionBudget", "metadata": {"name": "pdb1"}})
    cluster.pdbs.append(pdb)
    cache = prepcache.PrepareCache()
    prepcache.simulate_cached(cluster, apps, cache)
    pdb.touch()
    assert cache.invalidate(pdb) == 1  # the protocol covers RawObject kinds


def test_concurrent_requests_stale_self_eviction_one_failure_then_recovery():
    """ISSUE 3 satellite: a touch() landing mid-flight under concurrent
    requests. The first check_fresh to see the bumped version raises AND
    evicts everything the object taints, so concurrent peers either fail the
    same way (they raced the eviction) or rebuild cleanly — and the system
    always recovers: the next sequential call succeeds with the same
    placements as the pristine baseline."""
    import threading as _threading

    cluster, apps = _cluster(), _apps()
    cache = prepcache.PrepareCache()
    baseline = prepcache.simulate_cached(cluster, apps, cache)

    def shape(res):
        return (
            sorted((ns.node.metadata.name, len(ns.pods)) for ns in res.node_status),
            sorted(u.reason for u in res.unscheduled_pods),
        )

    cluster.nodes[0].touch()  # mid-flight mutation marker, no invalidation

    n_threads = 4
    barrier = _threading.Barrier(n_threads)
    outcomes = [None] * n_threads

    def request(i):
        barrier.wait()
        try:
            outcomes[i] = ("ok", prepcache.simulate_cached(cluster, apps, cache))
        except prepcache.StaleFingerprintError as e:
            outcomes[i] = ("stale", e)

    threads = [_threading.Thread(target=request, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    kinds = [k for k, _ in outcomes]
    # the entry was provably stale: at least one request failed loudly...
    assert kinds.count("stale") >= 1
    # ...and every request either failed typed or returned a correct result
    for kind, payload in outcomes:
        if kind == "ok":
            assert shape(payload) == shape(baseline)

    # recovery: the eviction happened exactly once, the rebuilt entry serves
    res = prepcache.simulate_cached(cluster, apps, cache)
    assert shape(res) == shape(baseline)
    prepcache.simulate_cached(cluster, apps, cache)  # and hits cleanly
