"""Observability layer (ISSUE 5, docs/observability.md): request-scoped
span trees, request-id propagation, the flight recorder and its debug
endpoints, phase latency histograms, Prometheus label escaping, structured
access logs, Chrome-trace export — and the satellite acceptance bar: under
fault injection the recorded span tree marks the failing phase with error
status and carries demotion spans matching ``EngineDecision.skipped``."""

import json
import re
import logging
import threading
import urllib.error
import urllib.request
from contextlib import contextmanager

import pytest

from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.obs import trace as tracing
from opensim_tpu.obs.metrics import RECORDER, escape_label_value, parse_metrics
from opensim_tpu.obs.recorder import FLIGHT_RECORDER, FlightRecorder
from opensim_tpu.resilience import breaker as breaker_mod
from opensim_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_obs_state(monkeypatch):
    monkeypatch.delenv("OPENSIM_TRACE", raising=False)
    monkeypatch.delenv("OPENSIM_ACCESS_LOG", raising=False)
    monkeypatch.delenv("OPENSIM_FAULTS", raising=False)
    monkeypatch.setenv("OPENSIM_SNAPSHOT_BACKOFF_S", "0.001")
    faults.clear_faults()
    breaker_mod.reset_breakers()
    FLIGHT_RECORDER.clear()
    RECORDER.reset()
    yield
    faults.clear_faults()
    breaker_mod.reset_breakers()
    FLIGHT_RECORDER.clear()
    RECORDER.reset()


def _cluster(n_nodes=6):
    rt = ResourceTypes()
    for i in range(n_nodes):
        rt.nodes.append(
            fx.make_fake_node(
                f"n{i:03d}", "16", "64Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 3}"}),
            )
        )
    # a bound snapshot pod so the prep cache's base entry engages
    rt.pods.append(fx.make_fake_pod("pinned", "100m", "128Mi", fx.with_node_name("n000")))
    return rt


def _payload():
    return {"deployments": [fx.make_fake_deployment("web", 6, "500m", "1Gi").raw]}


@contextmanager
def _serve(server):
    from http.server import ThreadingHTTPServer

    from opensim_tpu.server.rest import make_handler

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield httpd.server_address[1]
    finally:
        httpd.shutdown()


def _span_names(trace):
    return [sp.name for sp in trace.walk()]


def _find_spans(trace, name):
    return [sp for sp in trace.walk() if sp.name == name]


# ---------------------------------------------------------------------------
# span trees on the serving path
# ---------------------------------------------------------------------------


def test_deploy_records_span_tree_with_phases_and_engine():
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster())
    code, _ = server.deploy_apps(_payload())
    assert code == 200
    tr = FLIGHT_RECORDER.latest()
    assert tr is not None and tr.finished
    names = _span_names(tr)
    for phase in ("prepare", "encode", "schedule", "decode"):
        assert phase in names, f"missing {phase} in {names}"
    # at least one engine rung actually ran under the schedule span
    sched = _find_spans(tr, "schedule")[0]
    assert any(c.name.startswith("engine.") for c in sched.children)
    # encode nests under prepare; device upload nests under encode
    prep = _find_spans(tr, "prepare")[0]
    assert any(c.name == "encode" for c in prep.children)
    assert tr.root.status == "ok" and tr.http_status == 200
    assert tr.summary()["engine"]


def test_engine_decision_stamped_with_request_id(monkeypatch):
    from opensim_tpu.server import rest

    captured = []
    orig = rest._response
    monkeypatch.setattr(rest, "_response", lambda r, **kw: (captured.append(r), orig(r, **kw))[1])
    server = rest.SimonServer(base_cluster=_cluster())
    code, _ = server.deploy_apps(_payload(), request_id="my-req-7")
    assert code == 200
    assert captured[0].engine is not None
    assert captured[0].engine.request_id == "my-req-7"
    assert FLIGHT_RECORDER.get("my-req-7") is not None


def test_trace_disabled_is_dormant_but_request_id_still_flows(monkeypatch):
    from opensim_tpu.server import rest

    monkeypatch.setenv("OPENSIM_TRACE", "0")
    server = rest.SimonServer(base_cluster=_cluster())
    code, _ = server.deploy_apps(_payload())
    assert code == 200
    assert len(FLIGHT_RECORDER) == 0  # no traces recorded
    assert rest.last_request_id()  # id generated regardless
    # instrumentation points are no-ops without an ambient trace
    assert tracing.span("x") is tracing.NOOP_SPAN
    tracing.event("x")  # must not raise
    tracing.record_span("x", 0.1)
    # the request histogram still observes (metrics must not go dark)
    text = rest.METRICS.render()
    assert 'simon_request_seconds_bucket{endpoint="deploy-apps",status="ok",le="+Inf"} 1' in text


def test_prep_stats_attach_as_child_spans():
    """PREP_STATS timings (full prepare / cache hit) land in the span tree."""
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster())
    assert server.deploy_apps(_payload())[0] == 200
    assert server.deploy_apps(_payload())[0] == 200  # warm: full-key hit
    warm = FLIGHT_RECORDER.latest()
    names = _span_names(warm)
    assert "prep.hit" in names, names


# ---------------------------------------------------------------------------
# request-id propagation + flight-recorder HTTP endpoints
# ---------------------------------------------------------------------------


def test_request_id_honored_and_echoed_over_http():
    from opensim_tpu.server.rest import SimonServer

    with _serve(SimonServer(base_cluster=_cluster())) as port:
        body = json.dumps(_payload()).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST",
            headers={"X-Simon-Request-Id": "client-id-1"},
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.headers.get("X-Simon-Request-Id") == "client-id-1"

        # no header -> generated id, still echoed
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST"
        )
        with urllib.request.urlopen(req) as resp:
            rid = resp.headers.get("X-Simon-Request-Id")
        assert rid and rid != "client-id-1"

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/debug/requests"
        ) as resp:
            summaries = json.load(resp)["requests"]
        assert [s["request_id"] for s in summaries][0] == rid  # newest first
        assert {s["request_id"] for s in summaries} == {"client-id-1", rid}
        assert all(s["endpoint"] == "deploy-apps" for s in summaries)

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/debug/requests/client-id-1"
        ) as resp:
            tree = json.load(resp)
        assert tree["request_id"] == "client-id-1"
        assert tree["spans"]["name"] == "deploy-apps"
        child_names = {c["name"] for c in tree["spans"]["children"]}
        assert "schedule" in child_names and "decode" in child_names

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/debug/requests/nope"
            )
        assert ei.value.code == 404


def test_hostile_request_id_is_sanitized():
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster())
    code, _ = server.deploy_apps(_payload(), request_id="evil\r\nX-Injected: 1")
    assert code == 200
    from opensim_tpu.server.rest import last_request_id

    rid = last_request_id()
    assert "\r" not in rid and "\n" not in rid and " " not in rid
    assert rid == "evilX-Injected:1"


def test_flight_recorder_ring_is_bounded():
    fr = FlightRecorder(capacity=2)
    for i in range(3):
        tr = tracing.TraceContext("ep", request_id=f"r{i}")
        tr.finish()
        fr.record(tr)
    assert len(fr) == 2
    assert fr.get("r0") is None
    assert fr.get("r2") is not None
    assert [s["request_id"] for s in fr.summaries()] == ["r2", "r1"]


# ---------------------------------------------------------------------------
# /metrics: histograms + exposition-format hardening
# ---------------------------------------------------------------------------


def test_phase_histograms_rendered_and_cumulative():
    from opensim_tpu.server.rest import METRICS, SimonServer

    server = SimonServer(base_cluster=_cluster())
    assert server.deploy_apps(_payload())[0] == 200
    text = METRICS.render(prep_cache=server.prep_cache)
    assert "# TYPE simon_phase_seconds histogram" in text
    rows = [
        line for line in text.splitlines()
        if line.startswith('simon_phase_seconds_bucket{phase="schedule"')
    ]
    assert rows and rows[-1].split('le="')[1].startswith("+Inf")
    counts = [int(line.rsplit(" ", 1)[1]) for line in rows]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert counts[-1] == 1
    assert 'simon_phase_seconds_sum{phase="schedule",endpoint="deploy-apps"}' in text
    assert 'simon_phase_seconds_count{phase="schedule",endpoint="deploy-apps"} 1' in text
    # the legacy total is now derived from the request histogram
    assert "simon_simulate_seconds_total" in text


def test_hostile_label_values_cannot_corrupt_the_scrape():
    """A hostile endpoint name must not break the exposition format
    (satellite: Prometheus text-format hardening)."""
    from opensim_tpu.engine.simulator import SimulateResult
    from opensim_tpu.server.rest import METRICS

    evil = 'evil"} 1\nsimon_pwned_total{x="y'
    METRICS.record(evil, SimulateResult())
    RECORDER.observe_request(evil, 0.001)
    try:
        text = METRICS.render()
    finally:
        # METRICS is process-global: drop the hostile key for later tests
        with METRICS.lock:
            METRICS.requests.pop(evil, None)
            METRICS.simulations -= 1
    assert "simon_pwned_total" not in [
        line.split("{")[0] for line in text.splitlines()
    ]
    assert escape_label_value(evil) in text
    for line in text.splitlines():
        # every non-comment line must still parse as name{labels} value
        if not line or line.startswith("#"):
            continue
        name = line.split("{")[0].split(" ")[0]
        assert name.startswith("simon_"), f"corrupted scrape line: {line!r}"


def test_escape_label_value():
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"


def test_metrics_share_one_recorder_lock():
    from opensim_tpu.server.rest import METRICS

    assert METRICS.lock is RECORDER.lock


# ---------------------------------------------------------------------------
# satellite: span trees under fault injection
# ---------------------------------------------------------------------------


def test_prep_encode_fault_marks_encode_span_error():
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster())
    faults.inject("prep.encode", 1, "fault")
    code, body = server.deploy_apps(_payload())
    assert code == 500
    tr = FLIGHT_RECORDER.latest()
    assert tr.root.status == "error" and tr.http_status == 500
    enc = _find_spans(tr, "encode")
    assert enc and enc[0].status == "error"
    injected = _find_spans(tr, "fault.injected")
    assert injected and injected[0].attrs["point"] == "prep.encode"


def test_engine_compile_fault_demotion_spans_match_engine_decision(monkeypatch):
    """The demotion spans recorded in the trace must carry exactly the
    attribution EngineDecision.skipped reports — for every skipped rung,
    whatever this host's engine availability is."""
    from opensim_tpu.server import rest

    captured = []
    orig = rest._response
    monkeypatch.setattr(rest, "_response", lambda r, **kw: (captured.append(r), orig(r, **kw))[1])
    server = rest.SimonServer(base_cluster=_cluster())
    faults.inject("engine.compile", 1, "runtime")
    code, _ = server.deploy_apps(_payload())
    assert code == 200  # the ladder absorbs the engine failure
    engine = captured[0].engine
    tr = FLIGHT_RECORDER.latest()
    demotions = {
        sp.attrs["engine"]: sp.attrs["reason"]
        for sp in tr.walk()
        if sp.name.endswith(".skipped") and sp.status == "demoted"
    }
    assert demotions == engine.skipped
    # if the fault actually landed in an attempted engine, its span errored
    if faults.fault_stats().get("engine.compile"):
        errored = [
            sp for sp in tr.walk()
            if sp.name.startswith("engine.") and sp.status == "error"
        ]
        assert errored, "attempted engine rung should carry an error span"


def test_snapshot_fault_spans_retry_then_error(monkeypatch):
    from opensim_tpu.server import rest

    monkeypatch.setattr(
        rest, "cluster_from_kubeconfig", lambda kubeconfig, master=None: _cluster()
    )
    server = rest.SimonServer(kubeconfig="/tmp/kc", snapshot_ttl_s=3600.0)
    faults.inject("snapshot.http", 5, "fetch")  # outlasts the 3 attempts
    code, body = server.deploy_apps(_payload())
    assert code == 503 and body.get("retryable") is True
    tr = FLIGHT_RECORDER.latest()
    snap = _find_spans(tr, "snapshot")
    assert snap and snap[0].status == "error"
    retries = _find_spans(tr, "snapshot.retry")
    assert len(retries) == 2  # attempts-1 backoffs before failing closed
    assert tr.root.status == "error"

    # recovery: next request fetches clean and the snapshot span is ok
    code, _ = server.deploy_apps(_payload())
    assert code == 200
    assert _find_spans(FLIGHT_RECORDER.latest(), "snapshot")[0].status == "ok"


def test_deadline_exhaustion_marks_phase_span():
    from opensim_tpu.resilience.deadline import Deadline

    server_cluster = _cluster()
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=server_cluster)
    dead = Deadline.after(-1.0)  # already expired
    code, body = server.deploy_apps(_payload(), deadline=dead)
    assert code == 504
    tr = FLIGHT_RECORDER.latest()
    assert tr.root.status == "deadline-exceeded" and tr.http_status == 504
    events = _find_spans(tr, "deadline.exceeded")
    assert events and events[0].attrs["phase"] == body["phase"]
    # the failed request lands in its own histogram series and must NOT
    # inflate the success-only simulate_seconds_total continuity counter
    from opensim_tpu.server.rest import METRICS

    text = METRICS.render()
    assert "simon_simulate_seconds_total 0.000000" in text
    assert (
        'simon_request_seconds_count{endpoint="deploy-apps",status="deadline-exceeded"} 1'
        in text
    )


# ---------------------------------------------------------------------------
# access logging (satellite)
# ---------------------------------------------------------------------------


def test_access_log_opt_in_json(monkeypatch, caplog):
    from opensim_tpu.server.rest import SimonServer

    monkeypatch.setenv("OPENSIM_ACCESS_LOG", "1")
    with caplog.at_level(logging.INFO, logger="opensim_tpu.access"):
        with _serve(SimonServer(base_cluster=_cluster())) as port:
            body = json.dumps(_payload()).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST",
                headers={"X-Simon-Request-Id": "log-me"},
            )
            urllib.request.urlopen(req).read()
    records = [json.loads(r.message) for r in caplog.records if r.name == "opensim_tpu.access"]
    assert len(records) == 1
    rec = records[0]
    assert rec["endpoint"] == "/api/deploy-apps"
    assert rec["status"] == 200
    assert rec["request_id"] == "log-me"
    assert rec["method"] == "POST"
    assert rec["duration_s"] >= 0


def test_access_log_quiet_by_default(caplog):
    from opensim_tpu.server.rest import SimonServer

    with caplog.at_level(logging.INFO, logger="opensim_tpu.access"):
        with _serve(SimonServer(base_cluster=_cluster())) as port:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz").read()
    assert not [r for r in caplog.records if r.name == "opensim_tpu.access"]


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


def test_chrome_trace_export_round_trip(tmp_path):
    tr = tracing.start_trace("bench", force=True)
    with tracing.trace_scope(tr):
        with tracing.span("prepare", pods=3):
            with tracing.span("encode"):
                pass
        with tracing.span("schedule") as sp:
            sp.child_from_seconds("native.delta", 0.25, steps=10)
            sp.child_from_seconds("native.bind", 0.05, steps=10)
    tr.finish()

    out = tmp_path / "trace.json"
    tracing.write_chrome(tr, str(out))
    doc = json.loads(out.read_text())
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    by_name = {e["name"]: e for e in events}
    assert {"bench", "prepare", "encode", "schedule", "native.delta", "native.bind"} <= set(by_name)
    root = by_name["bench"]
    # every span fits inside the root's window and synthetic children are
    # laid out sequentially
    assert all(e["ts"] >= 0 for e in events)
    assert by_name["native.bind"]["ts"] >= by_name["native.delta"]["ts"] + by_name["native.delta"]["dur"] - 1e-3
    assert root["dur"] >= by_name["prepare"]["dur"]
    assert by_name["schedule"]["args"]["status"] == "ok"


def test_simulate_direct_call_with_ambient_trace():
    """Library callers compose: an ambient trace picks up simulate()'s
    spans without the REST layer."""
    rt = _cluster()
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("lib", 3, "100m", "128Mi"))
    tr = tracing.start_trace("lib-call", force=True)
    with tracing.trace_scope(tr):
        res = simulate(rt, [AppResource("lib", app)])
    tr.finish()
    assert res.engine is not None
    names = _span_names(tr)
    assert "schedule" in names and "decode" in names
    # total span time ~ wall time of the traced region (the bench --trace
    # acceptance bar, asserted structurally here): the DISJOINT phase spans
    # must fit in the root window ("prep.full" intentionally overlaps
    # "prepare" — it is attribution, not a phase)
    phase_total = sum(
        c.duration_s for c in tr.root.children
        if c.name in ("snapshot", "prepare", "schedule", "decode")
    )
    assert phase_total <= tr.root.duration_s * 1.01


def test_unclosed_spans_are_force_closed_on_finish():
    tr = tracing.TraceContext("ep")
    scope = tr.span("stuck", None)
    scope.__enter__()
    tr.finish(status="error", http_status=500)
    stuck = [sp for sp in tr.walk() if sp.name == "stuck"][0]
    assert stuck.end is not None and stuck.status == "error"
    assert tr.current_span() is tr.root


def test_native_profile_attaches_child_spans():
    from opensim_tpu import native

    if not native.available():
        pytest.skip("C++ engine not built on this host")
    import os

    rt = _cluster()
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("prof", 4, "100m", "128Mi"))
    os.environ["OPENSIM_NATIVE_PROFILE"] = "1"
    try:
        tr = tracing.start_trace("profiled", force=True)
        with tracing.trace_scope(tr):
            res = simulate(rt, [AppResource("prof", app)])
        tr.finish()
    finally:
        del os.environ["OPENSIM_NATIVE_PROFILE"]
    if res.engine is None or res.engine.name != "native":
        pytest.skip(f"native engine did not serve this run ({res.engine})")
    native_spans = _find_spans(tr, "engine.native")
    assert native_spans, _span_names(tr)
    children = {c.name for c in native_spans[0].children}
    assert any(n.startswith("native.") for n in children), children
    assert native_spans[0].attrs.get("native_path")


def test_native_bail_attribution_reaches_metrics_and_profile(monkeypatch):
    """Bail-reason attribution (abi v5): a forced-generic run must surface
    as ``simon_native_bail_total{reason="force_generic"}`` in /metrics and
    in the cumulative native snapshot served by /api/debug/profile."""
    from opensim_tpu import native
    from opensim_tpu.server import rest

    if not native.available():
        pytest.skip("C++ engine not built on this host")
    monkeypatch.setenv("OPENSIM_NATIVE_FORCE_GENERIC", "1")
    server = rest.SimonServer(base_cluster=_cluster())
    try:
        code, _body = server.deploy_apps(_payload())
        assert code == 200
        snap = rest.METRICS.native_snapshot()
        if not any(snap["steps"].values()):
            pytest.skip("native engine did not serve this run")
        text = rest.METRICS.render()
        m = re.search(r'simon_native_bail_total\{reason="force_generic"\} (\d+)', text)
        assert m and int(m.group(1)) > 0, text
        assert snap["bails"].get("force_generic", 0) > 0
        assert snap["steps"].get("generic", 0) > 0
    finally:
        # METRICS is process-global: unwind this test's contribution
        with rest.METRICS.lock:
            rest.METRICS.native_bails.clear()
            rest.METRICS.native_classes.clear()


@pytest.mark.slow
def test_bench_trace_flag_emits_chrome_json(tmp_path):
    """`bench.py --trace out.json` (acceptance bar): one JSON result line
    whose trace_span_s is within 10% of the reported wall time, plus a
    loadable Chrome-trace file covering the phases."""
    import os
    import subprocess
    import sys

    out = tmp_path / "trace.json"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--pods", "400",
         "--nodes", "40", "--no-warmup", "--trace", str(out)],
        capture_output=True, text=True, timeout=560, env=env, cwd=repo,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["trace_file"] == str(out)
    assert abs(rec["trace_span_s"] - rec["value"]) <= 0.1 * rec["value"] + 0.05
    doc = json.loads(out.read_text())
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert {"bench", "schedule", "decode"} <= names


def test_busy_rejection_lands_in_request_histogram():
    from opensim_tpu.server import rest

    # single-flight mode (admission=False): the TryLock busy path is the
    # OPENSIM_ADMISSION=off configuration (ISSUE 8)
    server = rest.SimonServer(base_cluster=_cluster(), admission=False)
    assert rest._deploy_lock.acquire(blocking=False)
    try:
        code, body = server.deploy_apps(_payload())
    finally:
        rest._deploy_lock.release()
    assert code == 503 and "busy" in body["error"]
    text = rest.METRICS.render()
    assert 'simon_request_seconds_count{endpoint="deploy-apps",status="busy"} 1' in text


# ---------------------------------------------------------------------------
# metrics-exposition conformance (ISSUE 7 satellite)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s(-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf)|NaN|[+-]Inf)$"
)
_LABEL_RE = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"$')


def _split_labels(body: str):
    """Split the inside of {...} into label assignments (quotes-aware)."""
    out, cur, depth, in_q, esc = [], "", 0, False, False
    for ch in body:
        if esc:
            cur += ch
            esc = False
            continue
        if ch == "\\":
            cur += ch
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
            cur += ch
            continue
        if ch == "," and not in_q:
            out.append(cur)
            cur = ""
            continue
        cur += ch
    if cur:
        out.append(cur)
    return out


def _assert_exposition_conformant(text):
    """The exposition contract every scrape surface must meet: one
    # HELP/# TYPE per family, every sample matches the Prometheus
    grammar, no series emitted twice. Returns the families that rendered
    at least one sample."""
    helped, typed, seen_series = set(), {}, set()
    families_with_samples = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in helped, f"duplicate HELP for {name}"
            helped.add(name)
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            assert name not in typed, f"duplicate TYPE for {name}"
            assert kind in ("counter", "gauge", "histogram", "summary"), line
            typed[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment line: {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"sample line fails the exposition grammar: {line!r}"
        name, _, labels_body, _value = m.groups()
        series_key = (name, labels_body or "")
        assert series_key not in seen_series, f"duplicate series: {line!r}"
        seen_series.add(series_key)
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and typed.get(base) == "histogram":
                family = base
        families_with_samples.add(family)
        assert family in typed, f"sample {name!r} has no # TYPE header"
        assert family in helped, f"sample {name!r} has no # HELP header"
        for part in _split_labels(labels_body or ""):
            assert _LABEL_RE.match(part), f"bad label in {line!r}: {part!r}"
    return families_with_samples


def test_metrics_exposition_conformance(tmp_path):
    """Every series in /metrics has # HELP/# TYPE, names and labels match
    the Prometheus grammar, and no series is emitted twice — regression-
    proofing the growing registry."""
    from opensim_tpu.server import rest
    from opensim_tpu.server.journal import Journal

    server = rest.SimonServer(base_cluster=_cluster())
    # traffic covering success + unschedulable so the decision counters,
    # request histograms, and per-endpoint series all render
    code, _ = server.deploy_apps(_payload())
    assert code == 200
    bad = {"deployments": [fx.make_fake_deployment("nope", 1, "640", "1Gi").raw]}
    code, _ = server.deploy_apps(bad)
    assert code == 200
    # capacity families (ISSUE 9) render once the report has bootstrapped
    # the observatory (headroom probes included)
    server.cluster_report()
    # watch-apply histogram (ISSUE 9 satellite) joins via the recorder
    RECORDER.observe_watch_apply(0.0002)
    # journal families (ISSUE 11): records of every type, an fsync, and a
    # recovery so each family renders populated
    journal = Journal(str(tmp_path / "journal"), policy={"fsync": "always"})
    journal.record_checkpoint({"pods": []}, generation=1, why="test")
    journal.record_event(
        "pods", "ADDED",
        {"metadata": {"name": "p", "namespace": "default", "resourceVersion": "2"}}, 2,
    )
    journal.record_rebase("pods", [], 3, rv="3", why="test")
    assert journal.flush(timeout=10.0)
    assert journal.recover() is not None
    journal.close()
    # admission families (ISSUE 8) and the memory observatory (ISSUE 12)
    # join the same conformance contract
    text = rest.METRICS.render(
        prep_cache=server.prep_cache, admission=server.admission,
        capacity=server.capacity, journal=journal, memory=server.memory,
    )
    families_with_samples = _assert_exposition_conformant(text)
    # the families this PR added are present and populated
    for required in (
        "simon_filter_reject_total",
        "simon_unschedulable_total",
        "simon_request_seconds",
        "simon_admission_queue_depth",
        "simon_queue_wait_seconds",
        "simon_batches_total",
        # capacity observatory (ISSUE 9)
        "simon_cluster_utilization",
        "simon_cluster_utilization_ratio",
        "simon_cluster_node_utilization",
        "simon_cluster_allocatable",
        "simon_cluster_requested",
        "simon_cluster_spread",
        "simon_cluster_fragmentation",
        "simon_cluster_headroom",
        "simon_cluster_nodes",
        "simon_cluster_pods_bound",
        "simon_cluster_pods_pending",
        "simon_watch_apply_seconds",
        # watch-event journal (ISSUE 11)
        "simon_journal_records_total",
        "simon_journal_bytes_total",
        "simon_journal_dropped_total",
        "simon_journal_fsync_seconds",
        "simon_journal_recoveries_total",
        # memory observatory + compile telemetry + phase profiles (ISSUE 12)
        "simon_mem_rss_bytes",
        "simon_mem_rss_peak_bytes",
        "simon_mem_prepcache_bytes",
        "simon_mem_prepcache_entries",
        "simon_mem_prepcache_evictions_total",
        "simon_mem_prepcache_compactions_total",
        "simon_mem_arena_bytes",
        "simon_mem_ring_entries",
        "simon_mem_ring_capacity",
        "simon_backend_compile_total",
        "simon_backend_compile_seconds_total",
        "simon_phase_profile_calls_total",
        "simon_phase_profile_seconds_total",
        "simon_phase_profile_exclusive_seconds_total",
    ):
        assert required in families_with_samples, f"{required} missing from /metrics"


def test_aggregated_metrics_exposition_conformance(tmp_path):
    """The fleet admin's aggregated /metrics (ISSUE 20 satellite) meets
    the SAME exposition contract as a single process: one header per
    family even when every worker ships it, summed series next to
    ``{worker="i"}``-labeled breakdowns with zero duplicates, and
    max-not-sum for the generation gauge."""
    from opensim_tpu.server import rest
    from opensim_tpu.server.fleet import render_aggregated

    server = rest.SimonServer(base_cluster=_cluster())
    code, _ = server.deploy_apps(_payload())
    assert code == 200
    server.cluster_report()
    worker_text = server.metrics_text()
    # two workers with identical traffic plus the owner's own exposition
    # (the owner ships watch/journal families, not request histograms)
    agg = render_aggregated([worker_text, worker_text], owner_text="")
    _assert_exposition_conformant(agg)
    single = parse_metrics(worker_text)
    merged = parse_metrics(agg)
    key = ("simon_request_seconds_count",
           (("endpoint", "deploy-apps"), ("status", "ok")))
    # backward compat: the summed family keeps its unlabeled shape...
    assert merged[key] == 2 * single[key]
    # ...and the per-worker breakdown rides next to it, same family
    for worker in ("0", "1"):
        labeled = (key[0], key[1] + (("worker", worker),))
        assert merged[labeled] == single[key]
    # the per-worker allowlist is a fence: unlisted families never grow
    # worker-labeled copies (cardinality × fleet size otherwise)
    assert not any(
        "worker" in dict(labels) and not name.startswith((
            "simon_request_seconds", "simon_requests_total", "simon_lane_depth",
            "simon_fleet_",
        ))
        for name, labels in merged
    )
    # a dead worker (failed scrape) degrades to the survivors' sum
    one = parse_metrics(render_aggregated([worker_text, None]))
    assert one[key] == single[key]
    # gauges in the max-set aggregate as max, not a meaningless sum
    gen_text = (
        "# TYPE simon_fleet_attach_generation gauge\n"
        "simon_fleet_attach_generation 7\n"
    )
    gen_text2 = gen_text.replace("7", "9")
    merged_gen = parse_metrics(render_aggregated([gen_text, gen_text2]))
    assert merged_gen[("simon_fleet_attach_generation", ())] == 9.0


def test_capacity_node_series_capped_under_1k_node_twin():
    """The per-node family stays cardinality-capped: a 1k-node cluster
    renders exactly top-K node series per resource (ISSUE 9 acceptance),
    and the whole capacity block stays exposition-conformant."""
    from opensim_tpu.obs.capacity import RESOURCES, CapacityEngine

    rt = ResourceTypes()
    for i in range(1000):
        rt.nodes.append(fx.make_fake_node(f"big{i:04d}", "16", "64Gi"))
    for i in range(200):
        rt.pods.append(
            fx.make_fake_pod(f"p{i}", "500m", "1Gi", fx.with_node_name(f"big{i:04d}"))
        )
    engine = CapacityEngine(topk=10)
    engine.bootstrap(rt, 1)
    lines = engine.metrics_lines()
    node_series = [l for l in lines if l.startswith("simon_cluster_node_utilization{")]
    assert len(node_series) == 10 * len(RESOURCES)
    # the cap keeps the HOTTEST nodes: every rendered node carries load
    assert all("big0" in l for l in node_series)
    for line in lines:
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE ")), line
        else:
            assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"


def test_watch_metrics_lines_conform(tmp_path):
    """The live twin's labeled counters join the same conformance contract
    (resource-labeled events and drift series)."""
    from opensim_tpu.server.watch import ClusterTwin, WatchSupervisor

    sup = WatchSupervisor.__new__(WatchSupervisor)
    sup.watched = ("pods", "nodes")
    sup.events_total = {("ADDED", "pods"): 3, ("BOOKMARK", "nodes"): 1}
    sup.reconnects_total = sup.relists_total = sup.gone_total = 0
    sup.drift_total = 2
    sup.drift_by_resource = {"pods": 2}
    sup.resyncs_total = 1
    sup._state = "live"
    sup._state_lock = threading.Lock()
    sup.twin = ClusterTwin()
    lines = sup.metrics_lines()
    text = "\n".join(lines)
    assert 'simon_watch_events_total{kind="ADDED",resource="pods"} 3' in text
    assert 'simon_twin_drift_total{resource="pods"} 2' in text
    assert 'simon_twin_drift_total{resource="nodes"} 0' in text
    assert "# HELP simon_twin_drift_total" in text
    assert "simon_twin_generation 0" in text
