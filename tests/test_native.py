"""The C++ scan engine must produce IDENTICAL placements, failure
attribution, and final state to the XLA scan on EVERY workload (it has no
feature envelope — only out-of-tree extra_plugins force the XLA path).
Covers the incremental same-template cache (long runs, failures, forced
interleavings) and the scheduler-config weight/disable handling."""

import os
import random
import sys

import numpy as np
import pytest

from opensim_tpu import native
from opensim_tpu.engine import nativepath
from opensim_tpu.engine.schedconfig import SchedulerConfig
from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
from opensim_tpu.engine.simulator import AppResource, prepare, simulate
from opensim_tpu.models import ResourceTypes, fixtures as fx

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native engine unavailable: {native.load_error()}"
)


def _xla_out(prep, config=None):
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(
        prep.ec, prep.st0, t, v, f, features=prep.features, config=config
    )
    return out, P


def _assert_match(prep, config=None):
    out, P = _xla_out(prep, config)
    nout = nativepath.schedule(prep, np.ones(P, bool), config=config)
    want = np.asarray(out.chosen)[:P]
    mism = np.nonzero(want != nout.chosen)[0]
    assert mism.size == 0, (
        f"{mism.size}/{P} placement mismatches at {mism[:10]}: "
        f"xla={want[mism[:10]]} native={nout.chosen[mism[:10]]}"
    )
    np.testing.assert_array_equal(np.asarray(out.fail_counts)[:P], nout.fail_counts)
    np.testing.assert_array_equal(np.asarray(out.insufficient)[:P], nout.insufficient)
    np.testing.assert_array_equal(np.asarray(out.final_state.used), nout.final_state.used)
    np.testing.assert_array_equal(
        np.asarray(out.final_state.port_used), nout.final_state.port_used
    )
    np.testing.assert_array_equal(
        np.asarray(out.final_state.dom_sel), nout.final_state.dom_sel
    )
    np.testing.assert_array_equal(
        np.asarray(out.final_state.gpu_free), nout.final_state.gpu_free
    )
    np.testing.assert_array_equal(
        np.asarray(out.final_state.vg_free), nout.final_state.vg_free
    )
    return nout


def _run_cluster(n_nodes=24):
    cluster = ResourceTypes()
    for i in range(n_nodes):
        labels = {"topology.kubernetes.io/zone": f"z{i % 3}"}
        cluster.nodes.append(
            fx.make_fake_node(f"n{i:03d}", "8", "16Gi", "110", fx.with_labels(labels))
        )
    return cluster


def test_incremental_long_run_with_failures():
    """One workload far over capacity: exercises the same-template cache
    through hundreds of binds, then the exact memoized-failure tail."""
    cluster = _run_cluster()
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("big", 600, "500m", "1Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    nout = _assert_match(prep)
    assert (nout.chosen >= 0).sum() > 300 and (nout.chosen < 0).sum() > 100


def test_incremental_with_soft_spread():
    cluster = _run_cluster()
    app = ResourceTypes()
    app.deployments.append(
        fx.make_fake_deployment(
            "spr", 200, "250m", "512Mi",
            fx.with_topology_spread(
                [
                    {
                        "maxSkew": 2,
                        "topologyKey": "topology.kubernetes.io/zone",
                        "whenUnsatisfiable": "ScheduleAnyway",
                        "labelSelector": {"matchLabels": {"app": "spr"}},
                    }
                ]
            ),
        )
    )
    app.deployments.append(fx.make_fake_deployment("other", 150, "100m", "256Mi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    _assert_match(prep)


def test_incremental_forced_interleaving():
    """Pre-bound pods interleave foreign binds into a template run — the
    cache must fold them in (or drop) without placement drift."""
    cluster = _run_cluster(8)
    for i in range(40):
        cluster.pods.append(
            fx.make_fake_pod(f"bound-{i:02d}", "250m", "512Mi",
                             fx.with_node_name(f"n{i % 8:03d}"))
        )
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("run", 120, "500m", "1Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert prep.forced.sum() == 40
    _assert_match(prep)


def test_sched_config_weights_and_disables():
    cluster = _run_cluster(12)
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("w", 80, "500m", "1Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    cfg = SchedulerConfig(w_least=3.0, w_balanced=0.0, w_spread=5.0, f_ports=False)
    _assert_match(prep, config=cfg)


def test_fit_disabled_zeroes_insufficient():
    """With NodeResourcesFit disabled the XLA scan reports zero per-resource
    shortfalls even when a later filter fails; the native engine must too."""
    cluster = ResourceTypes()
    for i in range(2):
        cluster.nodes.append(fx.make_fake_node(f"n{i:03d}", "2", "4Gi", "110"))
    app = ResourceTypes()
    app.deployments.append(
        fx.make_fake_deployment(
            "blocked", 2, "3", "1Gi",
            fx.with_affinity(
                {
                    "podAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {"matchLabels": {"app": "absent"}},
                                "topologyKey": "kubernetes.io/hostname",
                            }
                        ]
                    }
                }
            ),
        )
    )
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    nout = _assert_match(prep, config=SchedulerConfig(f_fit=False))
    assert nout.insufficient.sum() == 0


def test_native_engages_through_simulate(monkeypatch):
    """On a CPU backend simulate() must route through the native engine."""
    calls = []
    orig = nativepath.schedule

    def spy(*args, **kwargs):
        calls.append(1)
        return orig(*args, **kwargs)

    monkeypatch.setattr(nativepath, "schedule", spy)
    cluster = _run_cluster(8)
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("d", 30, "500m", "1Gi"))
    res = simulate(cluster, [AppResource("a", app)])
    assert calls, "native engine was not used on the CPU backend"
    assert sum(len(ns.pods) for ns in res.node_status) == 30


def test_disable_env_falls_back(monkeypatch):
    monkeypatch.setenv("OPENSIM_DISABLE_NATIVE", "1")
    cluster = _run_cluster(8)
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("d", 10, "500m", "1Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert not nativepath.applicable(prep)


def test_failure_reasons_identical_through_simulate(monkeypatch):
    """Reason strings from the native in-stream attribution must equal the
    XLA scan's (same '0/N nodes are available: …' reconstruction)."""
    cluster = _run_cluster(6)
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("fat", 4, "32", "64Gi"))
    app.deployments.append(fx.make_fake_deployment("fine", 6, "500m", "1Gi"))

    def reasons():
        # pod names carry per-expansion random suffixes; compare reasons only
        res = simulate(_run_cluster(6), [AppResource("a", app)])
        return sorted(u.reason for u in res.unscheduled_pods)

    native_reasons = reasons()
    monkeypatch.setenv("OPENSIM_DISABLE_NATIVE", "1")
    xla_reasons = reasons()
    assert native_reasons == xla_reasons
    assert native_reasons and "Insufficient" in native_reasons[0]


@pytest.mark.parametrize("seed", [3, 11, 31, 77, 1234])
@pytest.mark.slow
def test_native_fuzz_vs_xla(seed):
    """Differential fuzz over the full feature mix (gpu/local/interpod/
    ports/namespaces) — the generic non-incremental C++ path."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent))
    from test_fastpath_fuzz import random_app, random_cluster

    rng = random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(8, 20))
    app = random_app(rng, rng.randrange(3, 8))
    prep = prepare(cluster, [AppResource("fuzz", app)], node_pad=128)
    if prep is None:
        pytest.skip("empty workload")
    _assert_match(prep)


def test_precompute_np_bitwise_matches_jit():
    """The numpy static tables (native path, zero XLA compiles) must be
    BITWISE equal to the jitted ones — any drift between the two
    implementations silently desynchronizes the engines."""
    import random

    import jax
    import numpy as np

    from opensim_tpu.engine.simulator import AppResource, prepare
    from opensim_tpu.ops import kernels
    from test_fastpath_fuzz import random_app, random_cluster
    from test_k8s_oracle import ext_app, ext_cluster

    cases = []
    for seed in (1, 23, 99):
        rng = random.Random(seed)
        cases.append((random_cluster(rng, rng.randrange(6, 14)),
                      random_app(rng, rng.randrange(3, 7))))
    rng = random.Random(42)
    cases.append((ext_cluster(rng, 6), ext_app(rng, 15)))

    for cluster, app in cases:
        prep = prepare(cluster, [AppResource("x", app)], node_pad=8)
        if prep is None:
            continue
        jit_stat = jax.device_get(
            jax.jit(kernels.precompute_static)(prep.ec)
        )
        np_stat = kernels.precompute_static_np(prep.ec_np)
        for name in kernels.StaticTables._fields:
            a = np.asarray(getattr(jit_stat, name))
            b = np.asarray(getattr(np_stat, name))
            assert a.shape == b.shape, name
            mism = (a != b).sum()
            assert mism == 0, f"{name}: {mism} bitwise mismatches"


def test_native_scenario_sweep_matches_xla_sweep():
    """sweep_auto's C++ branch must return the same scenarios verdicts as
    the XLA sweep (unscheduled counts, placements, usage)."""
    import numpy as np

    from opensim_tpu.engine.simulator import AppResource, prepare
    from opensim_tpu.models import ResourceTypes, fixtures as fx
    from opensim_tpu.parallel import scenarios

    cluster = ResourceTypes()
    for i in range(6):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi", "20"))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("w", 30, "1", "2Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=8)
    P = len(prep.ordered)
    N = prep.ec.node_valid.shape[0]
    S = 5
    node_valid = np.zeros((S, N), bool)
    for s in range(S):
        node_valid[s, : s + 2] = True  # 2..6 nodes available
    pod_valid = np.ones((S, P), bool)

    res_native = scenarios.sweep_auto(prep, node_valid, pod_valid)

    import os

    os.environ["OPENSIM_DISABLE_NATIVE"] = "1"
    try:
        res_xla = scenarios.sweep_auto(prep, node_valid, pod_valid)
    finally:
        del os.environ["OPENSIM_DISABLE_NATIVE"]

    np.testing.assert_array_equal(
        np.asarray(res_native.unscheduled), np.asarray(res_xla.unscheduled)
    )
    np.testing.assert_array_equal(
        np.asarray(res_native.chosen), np.asarray(res_xla.chosen)
    )
    np.testing.assert_allclose(
        np.asarray(res_native.used), np.asarray(res_xla.used), rtol=0, atol=0
    )


# ---------------------------------------------------------------------------
# sampled tie-break in the C++ engine (VERDICT r4 #6)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_native_sampled_tie_break_distribution_parity():
    """The C++ engine's seeded sampled select must (a) keep structural
    results identical to deterministic runs, (b) only ever pick members of
    the XLA scan's tie set, and (c) cover the tie set over seeds with
    near-uniform frequencies — the distribution the XLA path (and the
    reference's selectHost reservoir) produces."""
    from opensim_tpu.engine import nativepath
    from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
    from opensim_tpu.engine.simulator import prepare

    from opensim_tpu import native

    if not native.available():
        pytest.skip(f"native engine unavailable: {native.load_error()}")

    cluster = ResourceTypes()
    for i in range(6):  # identical nodes -> every score ties
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))
    apps = [AppResource("a", app)]
    prep = prepare(cluster, apps, node_pad=8)
    P = len(prep.ordered)
    pv = np.ones(P, bool)

    # the XLA tie set for the first bind: every valid identical node
    t, v, f = pad_pod_stream(prep.tmpl_ids, pv, prep.forced)
    xla_landed = set()
    for seed in range(60):
        out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features, tie_seed=seed)
        xla_landed.add(int(np.asarray(out.chosen)[0]))

    counts = {}
    for seed in range(240):
        out = nativepath.schedule(prep, pv, tie_seed=seed)
        c = int(out.chosen[0])
        assert c >= 0  # structural parity: still scheduled
        counts[c] = counts.get(c, 0) + 1
    # (b) cross-engine tie-set parity: both engines sample exactly the
    # same equal-score set (60 XLA seeds make a coverage miss ~0.01%)
    assert set(counts) == xla_landed, (counts, xla_landed)
    # (c) covers the whole 6-node tie set, roughly uniformly (each node
    # expects 40 hits; tolerate 3-sigma binomial noise)
    assert set(counts) == set(range(6)), counts
    for node, n_hits in counts.items():
        assert 15 <= n_hits <= 70, (node, counts)

    # deterministic run unchanged by the new plumbing
    det = nativepath.schedule(prep, pv)
    assert int(det.chosen[0]) == 0


def test_native_sampled_matches_deterministic_structure_on_fuzz():
    """On a feature-rich fuzz workload, sampled C++ runs keep the same
    scheduled/unscheduled structure as the deterministic engine (sampling
    permutes only within equal-score sets)."""
    import random as _random

    from opensim_tpu.engine import nativepath
    from opensim_tpu.engine.simulator import prepare

    from opensim_tpu import native

    if not native.available():
        pytest.skip(f"native engine unavailable: {native.load_error()}")
    sys.path.insert(0, os.path.dirname(__file__))
    from test_k8s_oracle import random_app, random_cluster

    rng = _random.Random(97)
    cluster = random_cluster(rng, 8)
    app = random_app(rng, 6)
    apps = [AppResource("a", app)]
    prep = prepare(cluster, apps, node_pad=8)
    pv = np.ones(len(prep.ordered), bool)
    det = nativepath.schedule(prep, pv)
    det_sched = int((det.chosen >= 0).sum())
    for seed in (0, 1, 7):
        out = nativepath.schedule(prep, pv, tie_seed=seed)
        assert int((out.chosen >= 0).sum()) == det_sched


def test_native_default_spread_with_unlabeled_nodes():
    """Hier-mode edge: a node WITHOUT the zone label is spread-ignored but
    still schedulable, and its per-host pod count can exceed every scored
    zone's level range — the select must never index the (zone, level) LUT
    for it. Placements must match the XLA scan exactly."""
    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(
            fx.make_fake_node(
                f"z{i}", "4", "8Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"zone-{i % 2}"}),
            )
        )
    # zone-less big node: attracts many pods once the labeled ones fill
    cluster.nodes.append(fx.make_fake_node("plain", "64", "128Gi"))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("web", 60, "500m", "512Mi"))
    apps = [AppResource("a", app)]

    prep = prepare(cluster, apps, node_pad=8)
    pv = np.ones(len(prep.ordered), bool)
    out_native = nativepath.schedule(prep, pv)
    t, v, f = pad_pod_stream(prep.tmpl_ids, pv, prep.forced)
    out_xla = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    assert np.array_equal(
        np.asarray(out_native.chosen), np.asarray(out_xla.chosen)[: len(prep.ordered)]
    )
    # the unlabeled node really did absorb a level beyond the zoned hosts
    plain_count = int((np.asarray(out_native.chosen) == 4).sum())
    assert plain_count > 15, plain_count


def _assert_native_parity(cluster, apps):
    """Full-strength parity (placements + failure attribution + final
    state) via the module's _assert_match; returns the chosen array."""
    prep = prepare(cluster, apps, node_pad=8)
    return np.asarray(_assert_match(prep).chosen)


def test_native_hier_mode_reversed_constraint_order():
    """Explicit soft spread [zone, hostname] puts the FINE (singleton)
    constraint second — hier_fine_first=False: the cc-order float sum must
    still match the XLA scan bit-for-bit."""
    cluster = ResourceTypes()
    for i in range(6):
        cluster.nodes.append(
            fx.make_fake_node(
                f"n{i}", "8", "16Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 3}"}),
            )
        )
    app = ResourceTypes()
    app.deployments.append(
        fx.make_fake_deployment(
            "rev", 24, "200m", "256Mi",
            fx.with_topology_spread([
                {"maxSkew": 2, "topologyKey": "topology.kubernetes.io/zone",
                 "whenUnsatisfiable": "ScheduleAnyway",
                 "labelSelector": {"matchLabels": {"app": "rev"}}},
                {"maxSkew": 1, "topologyKey": "kubernetes.io/hostname",
                 "whenUnsatisfiable": "ScheduleAnyway",
                 "labelSelector": {"matchLabels": {"app": "rev"}}},
            ]),
        )
    )
    chosen = _assert_native_parity(cluster, [AppResource("a", app)])
    assert (chosen >= 0).all()
    # hostname (fine) really is the SECOND constraint in cc order
    prep = prepare(cluster, [AppResource("a", app)], node_pad=8)
    topo = np.asarray(prep.ec_np.spr_topo)[int(prep.tmpl_ids[0])]
    keys = list(prep.meta.vocab.topo_keys.items())
    active = [keys[t] for t in topo if t >= 0]
    assert active and active[-1] == "kubernetes.io/hostname", active


def test_native_dom_mode_with_hard_constraint_mix():
    """One soft + one hard spread constraint: dom mode handles the soft
    term while the hard constraint keeps filtering; placements match XLA
    including the hard-skew failures."""
    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(
            fx.make_fake_node(
                f"n{i}", "8", "16Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 2}"}),
            )
        )
    app = ResourceTypes()
    app.deployments.append(
        fx.make_fake_deployment(
            "mix", 20, "1", "1Gi",
            fx.with_topology_spread([
                {"maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
                 "whenUnsatisfiable": "DoNotSchedule",
                 "labelSelector": {"matchLabels": {"app": "mix"}}},
                {"maxSkew": 3, "topologyKey": "topology.kubernetes.io/zone",
                 "whenUnsatisfiable": "ScheduleAnyway",
                 "labelSelector": {"matchLabels": {"app": "mix"}}},
            ]),
        )
    )
    # zone z0 has 2 nodes (16 cpu), z1 has 2 (16 cpu); 20 one-cpu pods fit
    # numerically but the DoNotSchedule maxSkew=1 caps the zone imbalance;
    # shrink z1 to one node so capacity forces skew and the hard filter
    # actually rejects the tail
    cluster.nodes.pop()  # drop n3 (z1)
    chosen = _assert_native_parity(cluster, [AppResource("a", app)])
    assert (chosen == -1).sum() > 0  # the hard-skew failure path ran


def test_native_hier_mode_feasibility_flip_rebuild():
    """Default-spread pods that FILL nodes mid-run flip feasibility, which
    must invalidate the per-domain cache (apply_deltas bails, full_eval
    rebuilds histograms) — placements must match XLA through the flip,
    including the final failures."""
    cluster = ResourceTypes()
    for i in range(3):
        cluster.nodes.append(
            fx.make_fake_node(
                f"n{i}", "4", "8Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 2}"}),
            )
        )
    app = ResourceTypes()
    # 4-cpu nodes, 1-cpu pods: every 4th bind on a node flips it infeasible
    app.deployments.append(fx.make_fake_deployment("fill", 15, "1", "512Mi"))
    chosen = _assert_native_parity(cluster, [AppResource("a", app)])
    assert (chosen == -1).sum() == 3  # 12 fit, 3 fail


# ---------------------------------------------------------------------------
# interpod-aware incremental cache (ISSUE 4): the same-template envelope now
# covers the interpod filter + score and hard topology spread; these tests
# pin placements to the generic C++ path (via OPENSIM_NATIVE_FORCE_GENERIC),
# the XLA scan, and the independent kube oracle.
# ---------------------------------------------------------------------------


def _ip_cluster(n_nodes=18, unlabeled_every=6):
    """Zoned nodes plus a few zone-LESS ones (trash-domain members: their
    interpod/spread reads must stay vacuous through the delta path)."""
    cluster = ResourceTypes()
    for i in range(n_nodes):
        labels = {}
        if unlabeled_every == 0 or i % unlabeled_every != unlabeled_every - 1:
            labels["topology.kubernetes.io/zone"] = f"z{i % 3}"
        cluster.nodes.append(
            fx.make_fake_node(f"n{i:03d}", "8", "16Gi", "110", fx.with_labels(labels))
        )
    return cluster


def _ip_apps():
    """Required + preferred + anti-affinity terms MIXED with hard and soft
    spread — the full surface the widened envelope must keep bit-exact."""
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("base", 30, "250m", "512Mi"))
    # required affinity to base (zone) + preferred anti on itself (hostname):
    # negative symmetric weights — the score raw SHRINKS as copies land
    app.deployments.append(
        fx.make_fake_deployment(
            "follow", 40, "200m", "256Mi",
            fx.with_affinity({
                "podAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {"labelSelector": {"matchLabels": {"app": "base"}},
                         "topologyKey": "topology.kubernetes.io/zone"}
                    ]
                },
                "podAntiAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {"weight": 100, "podAffinityTerm": {
                            "labelSelector": {"matchLabels": {"app": "follow"}},
                            "topologyKey": "kubernetes.io/hostname"}}
                    ]
                },
            }),
        )
    )
    # required anti on ITSELF per hostname: every bind flips the bound
    # node's filter verdict — the bail-heavy worst case for the cache
    app.deployments.append(
        fx.make_fake_deployment(
            "excl", 12, "100m", "128Mi",
            fx.with_affinity({
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {"labelSelector": {"matchLabels": {"app": "excl"}},
                         "topologyKey": "kubernetes.io/hostname"}
                    ]
                }
            }),
        )
    )
    # hard spread + preferred affinity (positive weights) + soft spread mix
    app.deployments.append(
        fx.make_fake_deployment(
            "spread", 30, "150m", "256Mi",
            fx.with_topology_spread([
                {"maxSkew": 2, "topologyKey": "topology.kubernetes.io/zone",
                 "whenUnsatisfiable": "DoNotSchedule",
                 "labelSelector": {"matchLabels": {"app": "spread"}}},
                {"maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
                 "whenUnsatisfiable": "ScheduleAnyway",
                 "labelSelector": {"matchLabels": {"app": "spread"}}},
            ]),
            fx.with_affinity({
                "podAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {"weight": 50, "podAffinityTerm": {
                            "labelSelector": {"matchLabels": {"app": "base"}},
                            "topologyKey": "topology.kubernetes.io/zone"}}
                    ]
                }
            }),
        )
    )
    return app


def _force_generic(monkeypatch):
    monkeypatch.setenv("OPENSIM_NATIVE_FORCE_GENERIC", "1")


def _assert_same_output(a, b):
    np.testing.assert_array_equal(a.chosen, b.chosen)
    np.testing.assert_array_equal(a.fail_counts, b.fail_counts)
    np.testing.assert_array_equal(a.insufficient, b.insufficient)
    np.testing.assert_array_equal(
        np.asarray(a.final_state.used), np.asarray(b.final_state.used)
    )
    np.testing.assert_array_equal(
        np.asarray(a.final_state.dom_sel), np.asarray(b.final_state.dom_sel)
    )
    np.testing.assert_array_equal(
        np.asarray(a.final_state.dom_anti), np.asarray(b.final_state.dom_anti)
    )
    np.testing.assert_array_equal(
        np.asarray(a.final_state.dom_prefw), np.asarray(b.final_state.dom_prefw)
    )


def test_incremental_interpod_mixed_terms(monkeypatch):
    """Required + preferred + anti terms mixed with hard/soft spread: the
    incremental path must engage AND match the XLA scan and the forced
    generic C++ path bit-for-bit (placements, attribution, final counts)."""
    prep = prepare(_ip_cluster(), [AppResource("a", _ip_apps())], node_pad=128)
    nout = _assert_match(prep)  # XLA parity (placements + state + attribution)
    assert nout.native_stats is not None
    assert nout.native_stats["path"] == "incremental"
    assert nout.native_stats["steps"]["generic"] == 0
    pv = np.ones(len(prep.ordered), bool)
    _force_generic(monkeypatch)
    gout = nativepath.schedule(prep, pv)
    assert gout.native_stats["path"] == "generic"
    _assert_same_output(nout, gout)


def test_incremental_interpod_oracle_cross_check():
    """Every incremental-path bind must be feasible per the independent
    kube oracle (and every failure must have no oracle-feasible node) on
    the mixed required+preferred+anti workload."""
    sys.path.insert(0, os.path.dirname(__file__))
    from test_k8s_oracle import Oracle

    cluster = _ip_cluster()
    prep = prepare(cluster, [AppResource("a", _ip_apps())], node_pad=128)
    pv = np.ones(len(prep.ordered), bool)
    out = nativepath.schedule(prep, pv)
    assert out.native_stats["path"] == "incremental"
    oracle = Oracle(cluster.nodes)
    node_names = prep.meta.node_names
    for i, pod in enumerate(prep.ordered):
        c = int(out.chosen[i])
        if c >= 0:
            node = oracle.by_name[node_names[c]]
            assert oracle.feasible(pod, node), (
                f"incremental path bound {pod.metadata.name} to "
                f"{node.metadata.name}; oracle says infeasible "
                f"(interpod={oracle.interpod_ok(pod, node)} "
                f"spread={oracle.spread_ok(pod, node)})"
            )
            oracle.bind(pod, node)
        else:
            feasible = [n.metadata.name for n in cluster.nodes if oracle.feasible(pod, n)]
            assert not feasible, (
                f"{pod.metadata.name} unscheduled but oracle finds {feasible}"
            )


def test_incremental_interpod_bind_heavy_segments(tmp_path, monkeypatch):
    """Bind-heavy domain invalidation ACROSS SEGMENTS: two scheduler
    profiles chain the carry through consecutive incremental scans; the
    second segment's cache starts from the first segment's dom_sel/dom_anti
    state. Placements must match the XLA segmented path exactly."""
    from opensim_tpu.engine.schedconfig import load_scheduler_config

    cfg_path = tmp_path / "profiles.yaml"
    cfg_path.write_text(
        "kind: KubeSchedulerConfiguration\n"
        "profiles:\n"
        "  - schedulerName: default-scheduler\n"
        "  - schedulerName: lean\n"
        "    plugins:\n"
        "      score:\n"
        "        disabled:\n"
        "          - name: \"*\"\n"
    )
    cfg = load_scheduler_config(cfg_path)

    def patch(app_name, pods):
        # route the second workload's pods onto the lean profile
        for p in pods:
            if p.metadata.labels.get("app") == "excl":
                p.spec.scheduler_name = "lean"
                p.raw.setdefault("spec", {})["schedulerName"] = "lean"

    def run():
        return simulate(
            _ip_cluster(12), [AppResource("a", _ip_apps())],
            sched_config=cfg, patch_pods_fn=patch,
        )

    res_native = run()
    assert res_native.engine.name == "native"
    assert res_native.engine.native_path in ("incremental", "mixed")
    shape_native = sorted(
        (ns.node.metadata.name, len(ns.pods)) for ns in res_native.node_status
    )
    monkeypatch.setenv("OPENSIM_DISABLE_NATIVE", "1")
    res_xla = run()
    shape_xla = sorted(
        (ns.node.metadata.name, len(ns.pods)) for ns in res_xla.node_status
    )
    assert shape_native == shape_xla
    assert len(res_native.unscheduled_pods) == len(res_xla.unscheduled_pods)


def test_force_generic_knob_and_attribution(monkeypatch):
    """OPENSIM_NATIVE_FORCE_GENERIC=1 must disable the envelope and the
    attribution must say so — through simulate() into EngineDecision."""
    cluster = _run_cluster(8)
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("d", 30, "500m", "1Gi"))
    res = simulate(cluster, [AppResource("a", app)])
    assert res.engine.name == "native"
    assert res.engine.native_path == "incremental"
    assert res.engine.native_steps["incremental"] == 30
    assert "incremental" in res.engine.describe()
    monkeypatch.setenv("OPENSIM_NATIVE_FORCE_GENERIC", "1")
    res2 = simulate(_run_cluster(8), [AppResource("a", app)])
    assert res2.engine.native_path == "generic"
    assert sum(len(ns.pods) for ns in res2.node_status) == sum(
        len(ns.pods) for ns in res.node_status
    )


def test_incremental_interpod_forced_foreign_interleaving(monkeypatch):
    """Forced pins spliced INTO an interpod template run (patch_pods_fn sets
    spec.nodeName on every 7th pod → a distinct pinned template): the cache
    must fold the FOREIGN binder's selector matches through the pending
    (node, binder) entries — dom_sel/dom_anti moved by a template that is
    not the cached one. Incremental must equal forced-generic and XLA."""
    cluster = _ip_cluster(12)

    def patch(app_name, pods):
        for i, p in enumerate(pods):
            if i % 7 == 3:
                p.spec.node_name = f"n{i % 12:03d}"

    prep = prepare(
        cluster, [AppResource("a", _ip_apps())], node_pad=128, patch_pods_fn=patch
    )
    assert prep.forced.sum() > 5
    nout = _assert_match(prep)  # XLA parity incl. forced pins
    pv = np.ones(len(prep.ordered), bool)
    _force_generic(monkeypatch)
    gout = nativepath.schedule(prep, pv)
    _assert_same_output(nout, gout)


def _ip_fuzz_case(rng):
    """Interpod-rich random workloads that stay INSIDE the incremental
    envelope (no gpu/local/ports): required/preferred affinity and anti
    terms over zone/hostname/rack, mixed with hard/soft spread."""
    cluster = ResourceTypes()
    n_nodes = rng.randrange(10, 18)
    for i in range(n_nodes):
        labels = {}
        if rng.random() < 0.85:
            labels["topology.kubernetes.io/zone"] = f"z{rng.randrange(3)}"
        if rng.random() < 0.4:
            labels["topology.rack"] = f"k{rng.randrange(4)}"
        cluster.nodes.append(
            fx.make_fake_node(
                f"n{i:03d}", str(rng.choice([8, 16])), "32Gi", "110",
                fx.with_labels(labels),
            )
        )
    app = ResourceTypes()
    n_workloads = rng.randrange(3, 7)
    for w in range(n_workloads):
        opts = []
        aff = {}
        target = f"w{max(w - 1, 0)}" if rng.random() < 0.6 else f"w{w}"
        key = rng.choice(
            ["kubernetes.io/hostname", "topology.kubernetes.io/zone", "topology.rack"]
        )
        if rng.random() < 0.4:
            kind = rng.choice(["podAffinity", "podAntiAffinity"])
            aff.setdefault(kind, {})[
                "requiredDuringSchedulingIgnoredDuringExecution"
            ] = [{"labelSelector": {"matchLabels": {"app": target}}, "topologyKey": key}]
        if rng.random() < 0.5:
            kind = rng.choice(["podAffinity", "podAntiAffinity"])
            aff.setdefault(kind, {})[
                "preferredDuringSchedulingIgnoredDuringExecution"
            ] = [
                {"weight": rng.choice([10, 50, 100]), "podAffinityTerm": {
                    "labelSelector": {"matchLabels": {"app": target}},
                    "topologyKey": key}}
            ]
        if aff:
            opts.append(fx.with_affinity(aff))
        if rng.random() < 0.4:
            opts.append(
                fx.with_topology_spread([
                    {"maxSkew": rng.choice([1, 2, 4]),
                     "topologyKey": "topology.kubernetes.io/zone",
                     "whenUnsatisfiable": rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                     "labelSelector": {"matchLabels": {"app": f"w{w}"}}},
                ])
            )
        app.deployments.append(
            fx.make_fake_deployment(
                f"w{w}", rng.randrange(5, 16),
                f"{rng.choice([100, 250, 500])}m",
                f"{rng.choice([128, 256, 512])}Mi", *opts,
            )
        )
    return cluster, app


@pytest.mark.parametrize("seed", [211, 223, 251])
def test_incremental_vs_generic_interpod_fuzz(seed, monkeypatch):
    """Differential fuzz: the incremental path forced against the generic
    path (via the knob) AND the XLA scan on interpod-bearing templates."""
    rng = random.Random(seed)
    cluster, app = _ip_fuzz_case(rng)
    prep = prepare(cluster, [AppResource("fuzz", app)], node_pad=128)
    if prep is None:
        pytest.skip("empty workload")
    nout = _assert_match(prep)  # incremental vs XLA
    assert nout.native_stats["steps"]["generic"] == 0
    pv = np.ones(len(prep.ordered), bool)
    _force_generic(monkeypatch)
    gout = nativepath.schedule(prep, pv)
    assert gout.native_stats["path"] == "generic"
    _assert_same_output(nout, gout)


# --- abi v5 per-resource-class carry: ports / gpu-share / local-PV fuzz ----
#
# Each class gets a 3-seed differential sweep INSIDE the widened incremental
# envelope: the incremental path must (a) actually engage on its carry class
# (native_steps classes attribution), (b) match the forced-generic C++ path
# and the XLA scan bit-for-bit, and (c) replay clean against the independent
# kube oracle. A mixed storm with forced foreign binds closes the loop.


def _tmpl_annotate(deploy, anno):
    """Pod-TEMPLATE annotations on a workload (gpu-share / open-local pod
    requests live on the pod, not the controller)."""
    deploy.template_metadata.annotations.update(anno)
    deploy.template_raw.setdefault("metadata", {}).setdefault(
        "annotations", {}
    ).update(anno)


def _ports_fuzz_case(rng):
    """Host-port-bearing workloads over-subscribed enough to conflict: every
    template carries ports, so each incremental step exercises the per-node
    port-bitmap carry and binds flip verdicts (bail class B_PORTS)."""
    cluster = ResourceTypes()
    n_nodes = rng.randrange(6, 14)
    for i in range(n_nodes):
        cluster.nodes.append(
            fx.make_fake_node(
                f"n{i:03d}", "16", "32Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{rng.randrange(3)}"}),
            )
        )
    app = ResourceTypes()
    for w in range(rng.randrange(2, 5)):
        opts = [fx.with_host_ports(
            rng.sample([8080, 9090, 9443, 5000], rng.randrange(1, 3))
        )]
        if rng.random() < 0.4:
            opts.append(
                fx.with_topology_spread([
                    {"maxSkew": rng.choice([1, 2]),
                     "topologyKey": "topology.kubernetes.io/zone",
                     "whenUnsatisfiable": rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                     "labelSelector": {"matchLabels": {"app": f"w{w}"}}},
                ])
            )
        app.deployments.append(
            fx.make_fake_deployment(
                f"w{w}", rng.randrange(4, n_nodes + 5), "250m", "512Mi", *opts
            )
        )
    return cluster, app


def _gpu_fuzz_case(rng):
    """GPU-share templates (gpu-mem annotations) mixed with whole-GPU pods
    (gpu-count spec requests → the gc_dyn dynamic allocatable): per-GPU-index
    headroom carry + the dynamic share score term."""
    cluster = ResourceTypes()
    n_nodes = rng.randrange(5, 10)
    for i in range(n_nodes):
        opts = [fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 2}"})]
        if rng.random() < 0.8:
            opts.append(fx.with_allocatable(
                {"alibabacloud.com/gpu-mem": rng.choice(["16Gi", "32Gi"]),
                 "alibabacloud.com/gpu-count": rng.choice(["2", "4"])}))
        cluster.nodes.append(fx.make_fake_node(f"n{i:03d}", "16", "64Gi", "110", *opts))
    app = ResourceTypes()
    for w in range(rng.randrange(2, 5)):
        d = fx.make_fake_deployment(
            f"w{w}", rng.randrange(6, 20),
            f"{rng.choice([250, 500])}m", "512Mi",
        )
        if rng.random() < 0.6:
            _tmpl_annotate(d, {
                "alibabacloud.com/gpu-mem": rng.choice(["2Gi", "4Gi", "8Gi"]),
                "alibabacloud.com/gpu-count": rng.choice(["1", "1", "2"]),
            })
        else:
            # whole-GPU: gc_dyn fit + dynamic share (Reserve rewrite)
            d = fx.make_fake_deployment(
                f"w{w}", rng.randrange(3, 8), "250m", "512Mi",
                fx.with_requests(
                    {"alibabacloud.com/gpu-count": rng.choice(["1", "1", "2"])}),
            )
        app.deployments.append(d)
    return cluster, app


def _local_fuzz_case(rng):
    """open-local LVM + exclusive-device volumes: per-disk allocation carry
    for the local filter AND the w_local score term (use_loc now rides the
    incremental path)."""
    import json as _json

    cluster = ResourceTypes()
    n_nodes = rng.randrange(5, 10)
    for i in range(n_nodes):
        opts = [fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 2}"})]
        if rng.random() < 0.85:
            opts.append(fx.with_node_local_storage(
                vgs=[{"name": "pool0",
                      "capacity": rng.choice([50, 100, 200]) * 1024**3}],
                devices=[
                    {"device": "/dev/vdb",
                     "capacity": rng.choice([40, 80]) * 1024**3,
                     "mediaType": rng.choice(["ssd", "hdd"])},
                    {"device": "/dev/vdc", "capacity": 60 * 1024**3,
                     "mediaType": rng.choice(["ssd", "hdd"])},
                ]))
        cluster.nodes.append(fx.make_fake_node(f"n{i:03d}", "16", "64Gi", "110", *opts))
    app = ResourceTypes()
    for w in range(rng.randrange(2, 5)):
        vols = [{"size": str(rng.choice([5, 10, 20]) * 1024**3), "kind": "LVM",
                 "scName": "open-local-lvm"}]
        if rng.random() < 0.5:
            vols.append({"size": str(rng.choice([10, 30]) * 1024**3),
                         "kind": rng.choice(["SSD", "HDD"]),
                         "scName": "open-local-device"})
        d = fx.make_fake_deployment(
            f"w{w}", rng.randrange(4, 12), "250m", "512Mi",
        )
        _tmpl_annotate(d, {"simon/pod-local-storage": _json.dumps({"volumes": vols})})
        app.deployments.append(d)
    return cluster, app


def _storm_fuzz_case(rng):
    """Everything at once — ports + gpu-share + gc_dyn + local-PV + interpod
    + spread — with forced foreign binds spliced into the stream: every carry
    class must fold foreign deltas or bail, never drift."""
    c1, a1 = _ports_fuzz_case(rng)
    _c2, a2 = _gpu_fuzz_case(rng)
    _c3, a3 = _local_fuzz_case(rng)
    cluster = ResourceTypes()
    # gpu + local capable node set, zoned, sized to fit all three node shapes
    n_nodes = max(len(c1.nodes), 8)
    for i in range(n_nodes):
        opts = [fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 3}"})]
        if rng.random() < 0.6:
            opts.append(fx.with_allocatable(
                {"alibabacloud.com/gpu-mem": "16Gi",
                 "alibabacloud.com/gpu-count": "2"}))
        if rng.random() < 0.6:
            opts.append(fx.with_node_local_storage(
                vgs=[{"name": "pool0", "capacity": 100 * 1024**3}],
                devices=[{"device": "/dev/vdb", "capacity": 80 * 1024**3,
                          "mediaType": "ssd"}]))
        cluster.nodes.append(fx.make_fake_node(f"n{i:03d}", "32", "64Gi", "110", *opts))
    app = ResourceTypes()
    for src, tag in ((a1, "p"), (a2, "g"), (a3, "l")):
        for d in src.deployments:
            d.metadata.name = f"{tag}-{d.metadata.name}"
            app.deployments.append(d)
    if rng.random() < 0.6:
        app.deployments.append(
            fx.make_fake_deployment(
                "aff", rng.randrange(4, 10), "250m", "512Mi",
                fx.with_affinity({
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"labelSelector": {"matchLabels": {"app": "aff"}},
                             "topologyKey": "kubernetes.io/hostname"}]}}),
            )
        )
    return cluster, app


def _oracle_replay(cluster, prep, chosen, oracle):
    """Replay a placement stream against an independent kube-semantics
    oracle: every scheduler-made bind must be oracle-feasible, every failure
    must have no oracle-feasible node. Forced pods bypass the scheduler (but
    still drain oracle state)."""
    node_names = prep.meta.node_names
    lenient = False
    for i, pod in enumerate(prep.ordered):
        c = int(chosen[i])
        forced = bool(prep.forced[i])
        if c >= 0:
            node = oracle.by_name[node_names[c]]
            if not forced:
                assert oracle.feasible(pod, node), (
                    f"engine bound {pod.metadata.name} to {node.metadata.name}; "
                    "oracle says infeasible"
                )
            try:
                oracle.bind(pod, node)
            except (TypeError, ValueError, IndexError):
                # a FORCED pin outside the oracle's allocation model (e.g. a
                # device volume pinned onto a node with no free device): the
                # oracle state now under-counts usage, so stop asserting the
                # unscheduled side (feasible-bind asserts only get laxer)
                assert forced, "oracle.bind failed on a scheduler-made bind"
                lenient = True
        elif not forced and not lenient:
            feas = [n.metadata.name for n in cluster.nodes if oracle.feasible(pod, n)]
            assert not feas, (
                f"{pod.metadata.name} unscheduled but oracle finds {feas}"
            )


def _class_fuzz(monkeypatch, cluster, app, klass, ext_oracle):
    """Shared body: incremental vs XLA (_assert_match) vs forced-generic,
    engagement attribution on `klass`, then the oracle replay."""
    sys.path.insert(0, os.path.dirname(__file__))
    from test_k8s_oracle import ExtOracle, Oracle

    prep = prepare(cluster, [AppResource("fuzz", app)], node_pad=128)
    if prep is None:
        pytest.skip("empty workload")
    nout = _assert_match(prep)  # incremental vs XLA scan
    steps = nout.native_stats["steps"]
    assert steps["generic"] == 0, steps
    assert steps.get("classes", {}).get(klass, 0) > 0, (
        f"incremental path never engaged the {klass} carry: {steps}"
    )
    pv = np.ones(len(prep.ordered), bool)
    _force_generic(monkeypatch)
    gout = nativepath.schedule(prep, pv)
    assert gout.native_stats["path"] == "generic"
    assert gout.native_stats["steps"].get("bails", {}).get("force_generic", 0) > 0
    _assert_same_output(nout, gout)
    monkeypatch.delenv("OPENSIM_NATIVE_FORCE_GENERIC")
    oracle = (ExtOracle if ext_oracle else Oracle)(cluster.nodes)
    _oracle_replay(cluster, prep, nout.chosen, oracle)
    return nout


@pytest.mark.parametrize("seed", [211, 223, 251])
def test_incremental_vs_generic_ports_fuzz(seed, monkeypatch):
    rng = random.Random(seed)
    cluster, app = _ports_fuzz_case(rng)
    _class_fuzz(monkeypatch, cluster, app, "ports", ext_oracle=False)


@pytest.mark.parametrize("seed", [307, 311, 331])
def test_incremental_vs_generic_gpu_share_fuzz(seed, monkeypatch):
    rng = random.Random(seed)
    cluster, app = _gpu_fuzz_case(rng)
    _class_fuzz(monkeypatch, cluster, app, "gpu", ext_oracle=True)


@pytest.mark.parametrize("seed", [401, 409, 419])
def test_incremental_vs_generic_local_pv_fuzz(seed, monkeypatch):
    rng = random.Random(seed)
    cluster, app = _local_fuzz_case(rng)
    nout = _class_fuzz(monkeypatch, cluster, app, "local", ext_oracle=True)
    # the w_local SCORE term must ride the incremental path too
    assert nout.native_stats["steps"]["classes"].get("score", 0) > 0


@pytest.mark.parametrize("seed", [503, 509, 521])
def test_incremental_mixed_storm_forced_binds_fuzz(seed, monkeypatch):
    """All carry classes at once with forced foreign binds spliced every 7th
    pod: incremental vs generic vs XLA vs the extension oracle."""
    sys.path.insert(0, os.path.dirname(__file__))
    from test_k8s_oracle import ExtOracle

    rng = random.Random(seed)
    cluster, app = _storm_fuzz_case(rng)
    n_nodes = len(cluster.nodes)

    def patch(app_name, pods):
        for i, p in enumerate(pods):
            if i % 7 == 3:
                p.spec.node_name = f"n{i % n_nodes:03d}"

    prep = prepare(
        cluster, [AppResource("fuzz", app)], node_pad=128, patch_pods_fn=patch
    )
    if prep is None:
        pytest.skip("empty workload")
    assert prep.forced.sum() > 3
    nout = _assert_match(prep)  # incremental vs XLA, forced pins included
    classes = nout.native_stats["steps"].get("classes", {})
    assert classes, nout.native_stats["steps"]
    pv = np.ones(len(prep.ordered), bool)
    _force_generic(monkeypatch)
    gout = nativepath.schedule(prep, pv)
    _assert_same_output(nout, gout)
    monkeypatch.delenv("OPENSIM_NATIVE_FORCE_GENERIC")
    _oracle_replay(cluster, prep, nout.chosen, ExtOracle(cluster.nodes))


def test_class_failure_reasons_parity_through_simulate(monkeypatch):
    """Explanation parity on the new carry classes: unscheduled reason
    strings from the incremental native path must equal the XLA scan's for
    over-capacity ports, gpu-share, and local-PV workloads."""
    import json as _json

    def build():
        cluster = ResourceTypes()
        for i in range(3):
            cluster.nodes.append(
                fx.make_fake_node(
                    f"n{i:03d}", "16", "32Gi", "110",
                    fx.with_allocatable({"alibabacloud.com/gpu-mem": "8Gi",
                                         "alibabacloud.com/gpu-count": "2"}),
                    fx.with_node_local_storage(
                        vgs=[{"name": "pool0", "capacity": 20 * 1024**3}]),
                )
            )
        app = ResourceTypes()
        app.deployments.append(
            fx.make_fake_deployment("ports", 5, "100m", "128Mi",
                                    fx.with_host_ports([8080])))
        gpu = fx.make_fake_deployment("gpu", 6, "100m", "128Mi")
        _tmpl_annotate(gpu, {"alibabacloud.com/gpu-mem": "4Gi",
                             "alibabacloud.com/gpu-count": "1"})
        app.deployments.append(gpu)
        loc = fx.make_fake_deployment("loc", 4, "100m", "128Mi")
        _tmpl_annotate(loc, {"simon/pod-local-storage": _json.dumps(
            {"volumes": [{"size": str(15 * 1024**3), "kind": "LVM",
                          "scName": "open-local-lvm"}]})})
        app.deployments.append(loc)
        return cluster, [AppResource("a", app)]

    def reasons():
        res = simulate(*build())
        return res, sorted(u.reason for u in res.unscheduled_pods)

    res_native, native_reasons = reasons()
    assert res_native.engine.name == "native"
    assert res_native.engine.native_path == "incremental"
    monkeypatch.setenv("OPENSIM_DISABLE_NATIVE", "1")
    _res_xla, xla_reasons = reasons()
    assert native_reasons == xla_reasons
    assert native_reasons, "expected over-capacity failures in every class"


def test_scanargs_struct_lockstep():
    """The C++ ScanArgs struct and the ctypes mirror must agree FIELD BY
    COUNT (ISSUE 4 satellite): opensim_args_size() catches size drift at
    load time, this catches a same-size swap (e.g. one added + one removed)
    and names the section that drifted."""
    import re
    from pathlib import Path

    src = (Path(native.__file__).parent / "scan_engine.cc").read_text()
    m = re.search(r"struct ScanArgs \{(.*?)\n\};", src, re.S)
    assert m, "ScanArgs struct not found in scan_engine.cc"
    body = re.sub(r"//[^\n]*", "", m.group(1))
    n_int = n_dbl = n_ptr = 0
    for decl in body.split(";"):
        decl = decl.strip()
        if not decl:
            continue
        if "*" in decl:
            n_ptr += decl.count("*")
        elif decl.startswith("int64_t"):
            n_int += len(decl[len("int64_t"):].split(","))
        elif decl.startswith("double"):
            n_dbl += len(decl[len("double"):].split(","))
    from opensim_tpu.native import (
        _BUFFERS, _DIMS, _FEATURES, _FILTER_ENABLES, _SELECT, _WEIGHTS,
    )

    want_int = len(_DIMS) + len(_FEATURES) + len(_FILTER_ENABLES) + len(_SELECT)
    assert n_int == want_int, f"int64 dims/flags: C++ {n_int} vs Python {want_int}"
    assert n_dbl == len(_WEIGHTS), f"double weights: C++ {n_dbl} vs Python {len(_WEIGHTS)}"
    assert n_ptr == len(_BUFFERS), f"buffer pointers: C++ {n_ptr} vs Python {len(_BUFFERS)}"


@pytest.mark.slow
@pytest.mark.parametrize("seed", [500001, 500007, 500013, 500021, 500033])
def test_native_fuzz_random_configs(seed):
    """Config-surface fuzz: random plugin weights and filter disables must
    produce identical placements on the C++ engine and the XLA scan (a
    708-run randomized soak of this generator ran clean in round 5)."""
    import random as _random

    from opensim_tpu.engine.schedconfig import DEFAULT_CONFIG

    sys.path.insert(0, os.path.dirname(__file__))
    from test_k8s_oracle import random_app, random_cluster

    rng = _random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(4, 10))
    app = random_app(rng, rng.randrange(3, 8))
    kw = {}
    for w in ("w_balanced", "w_least", "w_node_affinity", "w_taint_toleration",
              "w_interpod", "w_spread", "w_prefer_avoid", "w_simon",
              "w_gpu_share", "w_local"):
        kw[w] = float(rng.choice([0.0, 0.5, 1.0, 2.0, 5.0]))
    for f in ("f_ports", "f_fit", "f_spread", "f_interpod", "f_gpu", "f_local",
              "f_taints", "f_node_affinity", "f_unschedulable"):
        kw[f] = rng.random() > 0.15
    cfg = DEFAULT_CONFIG._replace(**kw)  # raises on any unknown field name

    prep = prepare(cluster, [AppResource("s", app)], node_pad=8)
    assert prep is not None
    _assert_match(prep, config=cfg)
