"""The C++ scan engine must produce IDENTICAL placements, failure
attribution, and final state to the XLA scan on EVERY workload (it has no
feature envelope — only out-of-tree extra_plugins force the XLA path).
Covers the incremental same-template cache (long runs, failures, forced
interleavings) and the scheduler-config weight/disable handling."""

import os
import random
import sys

import numpy as np
import pytest

from opensim_tpu import native
from opensim_tpu.engine import nativepath
from opensim_tpu.engine.schedconfig import SchedulerConfig
from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
from opensim_tpu.engine.simulator import AppResource, prepare, simulate
from opensim_tpu.models import ResourceTypes, fixtures as fx

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native engine unavailable: {native.load_error()}"
)


def _xla_out(prep, config=None):
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(
        prep.ec, prep.st0, t, v, f, features=prep.features, config=config
    )
    return out, P


def _assert_match(prep, config=None):
    out, P = _xla_out(prep, config)
    nout = nativepath.schedule(prep, np.ones(P, bool), config=config)
    want = np.asarray(out.chosen)[:P]
    mism = np.nonzero(want != nout.chosen)[0]
    assert mism.size == 0, (
        f"{mism.size}/{P} placement mismatches at {mism[:10]}: "
        f"xla={want[mism[:10]]} native={nout.chosen[mism[:10]]}"
    )
    np.testing.assert_array_equal(np.asarray(out.fail_counts)[:P], nout.fail_counts)
    np.testing.assert_array_equal(np.asarray(out.insufficient)[:P], nout.insufficient)
    np.testing.assert_array_equal(np.asarray(out.final_state.used), nout.final_state.used)
    np.testing.assert_array_equal(
        np.asarray(out.final_state.port_used), nout.final_state.port_used
    )
    np.testing.assert_array_equal(
        np.asarray(out.final_state.dom_sel), nout.final_state.dom_sel
    )
    np.testing.assert_array_equal(
        np.asarray(out.final_state.gpu_free), nout.final_state.gpu_free
    )
    np.testing.assert_array_equal(
        np.asarray(out.final_state.vg_free), nout.final_state.vg_free
    )
    return nout


def _run_cluster(n_nodes=24):
    cluster = ResourceTypes()
    for i in range(n_nodes):
        labels = {"topology.kubernetes.io/zone": f"z{i % 3}"}
        cluster.nodes.append(
            fx.make_fake_node(f"n{i:03d}", "8", "16Gi", "110", fx.with_labels(labels))
        )
    return cluster


def test_incremental_long_run_with_failures():
    """One workload far over capacity: exercises the same-template cache
    through hundreds of binds, then the exact memoized-failure tail."""
    cluster = _run_cluster()
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("big", 600, "500m", "1Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    nout = _assert_match(prep)
    assert (nout.chosen >= 0).sum() > 300 and (nout.chosen < 0).sum() > 100


def test_incremental_with_soft_spread():
    cluster = _run_cluster()
    app = ResourceTypes()
    app.deployments.append(
        fx.make_fake_deployment(
            "spr", 200, "250m", "512Mi",
            fx.with_topology_spread(
                [
                    {
                        "maxSkew": 2,
                        "topologyKey": "topology.kubernetes.io/zone",
                        "whenUnsatisfiable": "ScheduleAnyway",
                        "labelSelector": {"matchLabels": {"app": "spr"}},
                    }
                ]
            ),
        )
    )
    app.deployments.append(fx.make_fake_deployment("other", 150, "100m", "256Mi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    _assert_match(prep)


def test_incremental_forced_interleaving():
    """Pre-bound pods interleave foreign binds into a template run — the
    cache must fold them in (or drop) without placement drift."""
    cluster = _run_cluster(8)
    for i in range(40):
        cluster.pods.append(
            fx.make_fake_pod(f"bound-{i:02d}", "250m", "512Mi",
                             fx.with_node_name(f"n{i % 8:03d}"))
        )
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("run", 120, "500m", "1Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert prep.forced.sum() == 40
    _assert_match(prep)


def test_sched_config_weights_and_disables():
    cluster = _run_cluster(12)
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("w", 80, "500m", "1Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    cfg = SchedulerConfig(w_least=3.0, w_balanced=0.0, w_spread=5.0, f_ports=False)
    _assert_match(prep, config=cfg)


def test_fit_disabled_zeroes_insufficient():
    """With NodeResourcesFit disabled the XLA scan reports zero per-resource
    shortfalls even when a later filter fails; the native engine must too."""
    cluster = ResourceTypes()
    for i in range(2):
        cluster.nodes.append(fx.make_fake_node(f"n{i:03d}", "2", "4Gi", "110"))
    app = ResourceTypes()
    app.deployments.append(
        fx.make_fake_deployment(
            "blocked", 2, "3", "1Gi",
            fx.with_affinity(
                {
                    "podAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {"matchLabels": {"app": "absent"}},
                                "topologyKey": "kubernetes.io/hostname",
                            }
                        ]
                    }
                }
            ),
        )
    )
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    nout = _assert_match(prep, config=SchedulerConfig(f_fit=False))
    assert nout.insufficient.sum() == 0


def test_native_engages_through_simulate(monkeypatch):
    """On a CPU backend simulate() must route through the native engine."""
    calls = []
    orig = nativepath.schedule

    def spy(*args, **kwargs):
        calls.append(1)
        return orig(*args, **kwargs)

    monkeypatch.setattr(nativepath, "schedule", spy)
    cluster = _run_cluster(8)
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("d", 30, "500m", "1Gi"))
    res = simulate(cluster, [AppResource("a", app)])
    assert calls, "native engine was not used on the CPU backend"
    assert sum(len(ns.pods) for ns in res.node_status) == 30


def test_disable_env_falls_back(monkeypatch):
    monkeypatch.setenv("OPENSIM_DISABLE_NATIVE", "1")
    cluster = _run_cluster(8)
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("d", 10, "500m", "1Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert not nativepath.applicable(prep)


def test_failure_reasons_identical_through_simulate(monkeypatch):
    """Reason strings from the native in-stream attribution must equal the
    XLA scan's (same '0/N nodes are available: …' reconstruction)."""
    cluster = _run_cluster(6)
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("fat", 4, "32", "64Gi"))
    app.deployments.append(fx.make_fake_deployment("fine", 6, "500m", "1Gi"))

    def reasons():
        # pod names carry per-expansion random suffixes; compare reasons only
        res = simulate(_run_cluster(6), [AppResource("a", app)])
        return sorted(u.reason for u in res.unscheduled_pods)

    native_reasons = reasons()
    monkeypatch.setenv("OPENSIM_DISABLE_NATIVE", "1")
    xla_reasons = reasons()
    assert native_reasons == xla_reasons
    assert native_reasons and "Insufficient" in native_reasons[0]


@pytest.mark.parametrize("seed", [3, 11, 31, 77, 1234])
@pytest.mark.slow
def test_native_fuzz_vs_xla(seed):
    """Differential fuzz over the full feature mix (gpu/local/interpod/
    ports/namespaces) — the generic non-incremental C++ path."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent))
    from test_fastpath_fuzz import random_app, random_cluster

    rng = random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(8, 20))
    app = random_app(rng, rng.randrange(3, 8))
    prep = prepare(cluster, [AppResource("fuzz", app)], node_pad=128)
    if prep is None:
        pytest.skip("empty workload")
    _assert_match(prep)


def test_precompute_np_bitwise_matches_jit():
    """The numpy static tables (native path, zero XLA compiles) must be
    BITWISE equal to the jitted ones — any drift between the two
    implementations silently desynchronizes the engines."""
    import random

    import jax
    import numpy as np

    from opensim_tpu.engine.simulator import AppResource, prepare
    from opensim_tpu.ops import kernels
    from test_fastpath_fuzz import random_app, random_cluster
    from test_k8s_oracle import ext_app, ext_cluster

    cases = []
    for seed in (1, 23, 99):
        rng = random.Random(seed)
        cases.append((random_cluster(rng, rng.randrange(6, 14)),
                      random_app(rng, rng.randrange(3, 7))))
    rng = random.Random(42)
    cases.append((ext_cluster(rng, 6), ext_app(rng, 15)))

    for cluster, app in cases:
        prep = prepare(cluster, [AppResource("x", app)], node_pad=8)
        if prep is None:
            continue
        jit_stat = jax.device_get(
            jax.jit(kernels.precompute_static)(prep.ec)
        )
        np_stat = kernels.precompute_static_np(prep.ec_np)
        for name in kernels.StaticTables._fields:
            a = np.asarray(getattr(jit_stat, name))
            b = np.asarray(getattr(np_stat, name))
            assert a.shape == b.shape, name
            mism = (a != b).sum()
            assert mism == 0, f"{name}: {mism} bitwise mismatches"


def test_native_scenario_sweep_matches_xla_sweep():
    """sweep_auto's C++ branch must return the same scenarios verdicts as
    the XLA sweep (unscheduled counts, placements, usage)."""
    import numpy as np

    from opensim_tpu.engine.simulator import AppResource, prepare
    from opensim_tpu.models import ResourceTypes, fixtures as fx
    from opensim_tpu.parallel import scenarios

    cluster = ResourceTypes()
    for i in range(6):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi", "20"))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("w", 30, "1", "2Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=8)
    P = len(prep.ordered)
    N = prep.ec.node_valid.shape[0]
    S = 5
    node_valid = np.zeros((S, N), bool)
    for s in range(S):
        node_valid[s, : s + 2] = True  # 2..6 nodes available
    pod_valid = np.ones((S, P), bool)

    res_native = scenarios.sweep_auto(prep, node_valid, pod_valid)

    import os

    os.environ["OPENSIM_DISABLE_NATIVE"] = "1"
    try:
        res_xla = scenarios.sweep_auto(prep, node_valid, pod_valid)
    finally:
        del os.environ["OPENSIM_DISABLE_NATIVE"]

    np.testing.assert_array_equal(
        np.asarray(res_native.unscheduled), np.asarray(res_xla.unscheduled)
    )
    np.testing.assert_array_equal(
        np.asarray(res_native.chosen), np.asarray(res_xla.chosen)
    )
    np.testing.assert_allclose(
        np.asarray(res_native.used), np.asarray(res_xla.used), rtol=0, atol=0
    )


# ---------------------------------------------------------------------------
# sampled tie-break in the C++ engine (VERDICT r4 #6)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_native_sampled_tie_break_distribution_parity():
    """The C++ engine's seeded sampled select must (a) keep structural
    results identical to deterministic runs, (b) only ever pick members of
    the XLA scan's tie set, and (c) cover the tie set over seeds with
    near-uniform frequencies — the distribution the XLA path (and the
    reference's selectHost reservoir) produces."""
    from opensim_tpu.engine import nativepath
    from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
    from opensim_tpu.engine.simulator import prepare

    from opensim_tpu import native

    if not native.available():
        pytest.skip(f"native engine unavailable: {native.load_error()}")

    cluster = ResourceTypes()
    for i in range(6):  # identical nodes -> every score ties
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))
    apps = [AppResource("a", app)]
    prep = prepare(cluster, apps, node_pad=8)
    P = len(prep.ordered)
    pv = np.ones(P, bool)

    # the XLA tie set for the first bind: every valid identical node
    t, v, f = pad_pod_stream(prep.tmpl_ids, pv, prep.forced)
    xla_landed = set()
    for seed in range(60):
        out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features, tie_seed=seed)
        xla_landed.add(int(np.asarray(out.chosen)[0]))

    counts = {}
    for seed in range(240):
        out = nativepath.schedule(prep, pv, tie_seed=seed)
        c = int(out.chosen[0])
        assert c >= 0  # structural parity: still scheduled
        counts[c] = counts.get(c, 0) + 1
    # (b) cross-engine tie-set parity: both engines sample exactly the
    # same equal-score set (60 XLA seeds make a coverage miss ~0.01%)
    assert set(counts) == xla_landed, (counts, xla_landed)
    # (c) covers the whole 6-node tie set, roughly uniformly (each node
    # expects 40 hits; tolerate 3-sigma binomial noise)
    assert set(counts) == set(range(6)), counts
    for node, n_hits in counts.items():
        assert 15 <= n_hits <= 70, (node, counts)

    # deterministic run unchanged by the new plumbing
    det = nativepath.schedule(prep, pv)
    assert int(det.chosen[0]) == 0


def test_native_sampled_matches_deterministic_structure_on_fuzz():
    """On a feature-rich fuzz workload, sampled C++ runs keep the same
    scheduled/unscheduled structure as the deterministic engine (sampling
    permutes only within equal-score sets)."""
    import random as _random

    from opensim_tpu.engine import nativepath
    from opensim_tpu.engine.simulator import prepare

    from opensim_tpu import native

    if not native.available():
        pytest.skip(f"native engine unavailable: {native.load_error()}")
    sys.path.insert(0, os.path.dirname(__file__))
    from test_k8s_oracle import random_app, random_cluster

    rng = _random.Random(97)
    cluster = random_cluster(rng, 8)
    app = random_app(rng, 6)
    apps = [AppResource("a", app)]
    prep = prepare(cluster, apps, node_pad=8)
    pv = np.ones(len(prep.ordered), bool)
    det = nativepath.schedule(prep, pv)
    det_sched = int((det.chosen >= 0).sum())
    for seed in (0, 1, 7):
        out = nativepath.schedule(prep, pv, tie_seed=seed)
        assert int((out.chosen >= 0).sum()) == det_sched


def test_native_default_spread_with_unlabeled_nodes():
    """Hier-mode edge: a node WITHOUT the zone label is spread-ignored but
    still schedulable, and its per-host pod count can exceed every scored
    zone's level range — the select must never index the (zone, level) LUT
    for it. Placements must match the XLA scan exactly."""
    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(
            fx.make_fake_node(
                f"z{i}", "4", "8Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"zone-{i % 2}"}),
            )
        )
    # zone-less big node: attracts many pods once the labeled ones fill
    cluster.nodes.append(fx.make_fake_node("plain", "64", "128Gi"))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("web", 60, "500m", "512Mi"))
    apps = [AppResource("a", app)]

    prep = prepare(cluster, apps, node_pad=8)
    pv = np.ones(len(prep.ordered), bool)
    out_native = nativepath.schedule(prep, pv)
    t, v, f = pad_pod_stream(prep.tmpl_ids, pv, prep.forced)
    out_xla = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    assert np.array_equal(
        np.asarray(out_native.chosen), np.asarray(out_xla.chosen)[: len(prep.ordered)]
    )
    # the unlabeled node really did absorb a level beyond the zoned hosts
    plain_count = int((np.asarray(out_native.chosen) == 4).sum())
    assert plain_count > 15, plain_count


def _assert_native_parity(cluster, apps):
    """Full-strength parity (placements + failure attribution + final
    state) via the module's _assert_match; returns the chosen array."""
    prep = prepare(cluster, apps, node_pad=8)
    return np.asarray(_assert_match(prep).chosen)


def test_native_hier_mode_reversed_constraint_order():
    """Explicit soft spread [zone, hostname] puts the FINE (singleton)
    constraint second — hier_fine_first=False: the cc-order float sum must
    still match the XLA scan bit-for-bit."""
    cluster = ResourceTypes()
    for i in range(6):
        cluster.nodes.append(
            fx.make_fake_node(
                f"n{i}", "8", "16Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 3}"}),
            )
        )
    app = ResourceTypes()
    app.deployments.append(
        fx.make_fake_deployment(
            "rev", 24, "200m", "256Mi",
            fx.with_topology_spread([
                {"maxSkew": 2, "topologyKey": "topology.kubernetes.io/zone",
                 "whenUnsatisfiable": "ScheduleAnyway",
                 "labelSelector": {"matchLabels": {"app": "rev"}}},
                {"maxSkew": 1, "topologyKey": "kubernetes.io/hostname",
                 "whenUnsatisfiable": "ScheduleAnyway",
                 "labelSelector": {"matchLabels": {"app": "rev"}}},
            ]),
        )
    )
    chosen = _assert_native_parity(cluster, [AppResource("a", app)])
    assert (chosen >= 0).all()
    # hostname (fine) really is the SECOND constraint in cc order
    prep = prepare(cluster, [AppResource("a", app)], node_pad=8)
    topo = np.asarray(prep.ec_np.spr_topo)[int(prep.tmpl_ids[0])]
    keys = list(prep.meta.vocab.topo_keys.items())
    active = [keys[t] for t in topo if t >= 0]
    assert active and active[-1] == "kubernetes.io/hostname", active


def test_native_dom_mode_with_hard_constraint_mix():
    """One soft + one hard spread constraint: dom mode handles the soft
    term while the hard constraint keeps filtering; placements match XLA
    including the hard-skew failures."""
    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(
            fx.make_fake_node(
                f"n{i}", "8", "16Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 2}"}),
            )
        )
    app = ResourceTypes()
    app.deployments.append(
        fx.make_fake_deployment(
            "mix", 20, "1", "1Gi",
            fx.with_topology_spread([
                {"maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
                 "whenUnsatisfiable": "DoNotSchedule",
                 "labelSelector": {"matchLabels": {"app": "mix"}}},
                {"maxSkew": 3, "topologyKey": "topology.kubernetes.io/zone",
                 "whenUnsatisfiable": "ScheduleAnyway",
                 "labelSelector": {"matchLabels": {"app": "mix"}}},
            ]),
        )
    )
    # zone z0 has 2 nodes (16 cpu), z1 has 2 (16 cpu); 20 one-cpu pods fit
    # numerically but the DoNotSchedule maxSkew=1 caps the zone imbalance;
    # shrink z1 to one node so capacity forces skew and the hard filter
    # actually rejects the tail
    cluster.nodes.pop()  # drop n3 (z1)
    chosen = _assert_native_parity(cluster, [AppResource("a", app)])
    assert (chosen == -1).sum() > 0  # the hard-skew failure path ran


def test_native_hier_mode_feasibility_flip_rebuild():
    """Default-spread pods that FILL nodes mid-run flip feasibility, which
    must invalidate the per-domain cache (apply_deltas bails, full_eval
    rebuilds histograms) — placements must match XLA through the flip,
    including the final failures."""
    cluster = ResourceTypes()
    for i in range(3):
        cluster.nodes.append(
            fx.make_fake_node(
                f"n{i}", "4", "8Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 2}"}),
            )
        )
    app = ResourceTypes()
    # 4-cpu nodes, 1-cpu pods: every 4th bind on a node flips it infeasible
    app.deployments.append(fx.make_fake_deployment("fill", 15, "1", "512Mi"))
    chosen = _assert_native_parity(cluster, [AppResource("a", app)])
    assert (chosen == -1).sum() == 3  # 12 fit, 3 fail


@pytest.mark.slow
@pytest.mark.parametrize("seed", [500001, 500007, 500013, 500021, 500033])
def test_native_fuzz_random_configs(seed):
    """Config-surface fuzz: random plugin weights and filter disables must
    produce identical placements on the C++ engine and the XLA scan (a
    708-run randomized soak of this generator ran clean in round 5)."""
    import random as _random

    from opensim_tpu.engine.schedconfig import DEFAULT_CONFIG

    sys.path.insert(0, os.path.dirname(__file__))
    from test_k8s_oracle import random_app, random_cluster

    rng = _random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(4, 10))
    app = random_app(rng, rng.randrange(3, 8))
    kw = {}
    for w in ("w_balanced", "w_least", "w_node_affinity", "w_taint_toleration",
              "w_interpod", "w_spread", "w_prefer_avoid", "w_simon",
              "w_gpu_share", "w_local"):
        kw[w] = float(rng.choice([0.0, 0.5, 1.0, 2.0, 5.0]))
    for f in ("f_ports", "f_fit", "f_spread", "f_interpod", "f_gpu", "f_local",
              "f_taints", "f_node_affinity", "f_unschedulable"):
        kw[f] = rng.random() > 0.15
    cfg = DEFAULT_CONFIG._replace(**kw)  # raises on any unknown field name

    prep = prepare(cluster, [AppResource("s", app)], node_pad=8)
    assert prep is not None
    _assert_match(prep, config=cfg)
