"""The driver contract: __graft_entry__.entry() must jit-compile and run,
and dryrun_multichip must execute on the virtual device mesh. Signature
drift in the engine internals it touches has broken it before — keep it
under test."""

import sys

sys.path.insert(0, "/root/repo")

import jax
import numpy as np


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    chosen, used = jax.jit(fn)(*args)
    chosen = np.asarray(chosen)
    assert chosen.ndim == 1 and (chosen >= -1).all()
    assert np.asarray(used).ndim == 2


def test_dryrun_multichip():
    import __graft_entry__ as g

    n = len(jax.devices())
    g.dryrun_multichip(n)
