"""Unit coverage for the resilience primitives (opensim_tpu/resilience):
deadlines, jittered-backoff retry, circuit breakers, fault injection — plus
the bench.py failure contract (one JSON line, nonzero exit) and the
jit-cache degradation log."""

import json
import os
import random
import subprocess
import sys

import pytest

from opensim_tpu.resilience import breaker as breaker_mod
from opensim_tpu.resilience import faults
from opensim_tpu.resilience.breaker import CircuitBreaker
from opensim_tpu.resilience.deadline import (
    Deadline,
    DeadlineExceeded,
    check_deadline,
    current_deadline,
    deadline_scope,
)
from opensim_tpu.resilience.retry import retry_call

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    faults.clear_faults()
    breaker_mod.reset_breakers()
    yield
    faults.clear_faults()
    breaker_mod.reset_breakers()


# ---------------------------------------------------------------------------
# deadline
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_deadline_expiry_and_phase():
    clock = FakeClock()
    dl = Deadline.after(5.0, clock=clock)
    assert dl.remaining() == 5.0 and not dl.expired()
    dl.check("prepare")  # plenty of budget: no raise
    clock.t = 6.0
    assert dl.expired()
    with pytest.raises(DeadlineExceeded) as ei:
        dl.check("schedule")
    assert ei.value.phase == "schedule"
    assert "schedule" in str(ei.value) and "budget 5.000s" in str(ei.value)


def test_deadline_scope_is_ambient_and_restores():
    assert current_deadline() is None
    check_deadline("anything")  # no ambient deadline: no-op
    clock = FakeClock()
    dl = Deadline.after(1.0, clock=clock)
    with deadline_scope(dl):
        assert current_deadline() is dl
        clock.t = 2.0
        with pytest.raises(DeadlineExceeded) as ei:
            check_deadline("encode")
        assert ei.value.phase == "encode"
        # deadline_scope(None) keeps the ambient scope (simulate(deadline=
        # None) inside a server-installed scope must still be bounded)
        with deadline_scope(None):
            assert current_deadline() is dl
    assert current_deadline() is None


def test_simulate_honors_deadline_at_prepare_boundary():
    from opensim_tpu.engine.simulator import AppResource, simulate
    from opensim_tpu.models import ResourceTypes, fixtures as fx

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p1", "500m", "1Gi"))
    clock = FakeClock()
    expired = Deadline.after(1.0, clock=clock)
    clock.t = 2.0
    with pytest.raises(DeadlineExceeded) as ei:
        simulate(cluster, [AppResource("a", app)], deadline=expired)
    assert ei.value.phase == "prepare"
    # and a generous deadline changes nothing
    res = simulate(cluster, [AppResource("a", app)], deadline=Deadline.after(3600.0))
    assert not res.unscheduled_pods


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------


def test_retry_recovers_within_attempts():
    calls = []
    sleeps = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    out = retry_call(
        flaky, attempts=3, base_delay=0.1, max_delay=2.0,
        retry_on=(OSError,), sleep=sleeps.append, rng=random.Random(0),
    )
    assert out == "ok" and len(calls) == 3
    # full-jitter: attempt k sleeps uniform[0, min(max, base*2^k)]
    assert len(sleeps) == 2
    assert 0.0 <= sleeps[0] <= 0.1 and 0.0 <= sleeps[1] <= 0.2


def test_retry_exhaustion_reraises_last_error():
    sleeps = []
    with pytest.raises(OSError, match="always"):
        retry_call(
            lambda: (_ for _ in ()).throw(OSError("always")),
            attempts=4, base_delay=0.05, retry_on=(OSError,),
            sleep=sleeps.append, rng=random.Random(1),
        )
    assert len(sleeps) == 3  # attempts-1 backoffs, bounded


def test_retry_does_not_retry_foreign_exceptions():
    calls = []

    def auth_error():
        calls.append(1)
        raise ValueError("bad kubeconfig")

    with pytest.raises(ValueError):
        retry_call(auth_error, attempts=5, retry_on=(OSError,), sleep=lambda s: None)
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


def test_breaker_opens_after_threshold_and_half_open_probes():
    clock = FakeClock()
    br = CircuitBreaker("native", threshold=3, cooldown_s=30.0, clock=clock)
    assert br.state() == "closed" and br.allow()
    for _ in range(2):
        br.record_failure(RuntimeError("boom"))
    assert br.state() == "closed" and br.allow() and br.trips_total == 0
    br.record_failure(RuntimeError("boom"))
    assert br.state() == "open" and not br.allow() and br.trips_total == 1
    assert "circuit breaker open" in br.describe_block()
    assert "RuntimeError: boom" in br.describe_block()

    # cooldown elapses: half-open allows exactly one probe
    clock.t = 31.0
    assert br.state() == "half-open"
    assert br.allow()       # the probe
    assert not br.allow()   # concurrent request during the probe: skipped
    br.record_failure(RuntimeError("still broken"))
    assert br.state() == "open" and br.trips_total == 2

    # next probe succeeds: breaker closes fully
    clock.t = 62.0
    assert br.allow()
    br.record_success()
    assert br.state() == "closed" and br.allow() and br.consecutive_failures == 0


def test_breaker_success_resets_consecutive_failures():
    br = CircuitBreaker("x", threshold=3, cooldown_s=1.0, clock=FakeClock())
    br.record_failure(RuntimeError("a"))
    br.record_failure(RuntimeError("b"))
    br.record_success()
    br.record_failure(RuntimeError("c"))
    assert br.state() == "closed" and br.failures_total == 3 and br.trips_total == 0


def test_engine_breaker_registry_env_config(monkeypatch):
    monkeypatch.setenv("OPENSIM_BREAKER_THRESHOLD", "1")
    breaker_mod.reset_breakers()
    br = breaker_mod.engine_breaker("native")
    assert br is breaker_mod.engine_breaker("native")  # one per engine
    br.record_failure(RuntimeError("x"))
    assert br.state() == "open"  # threshold 1 from env
    assert "native" in breaker_mod.all_breakers()


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------


def test_fault_point_fires_count_times_then_goes_inert():
    faults.inject("prep.encode", count=2, exc="runtime")
    for _ in range(2):
        with pytest.raises(RuntimeError, match="injected fault at prep.encode"):
            faults.fault_point("prep.encode")
    faults.fault_point("prep.encode")  # armed count exhausted: inert
    assert faults.fault_stats() == {"prep.encode": 2}


def test_fault_env_activation_and_reparse(monkeypatch):
    monkeypatch.setenv("OPENSIM_FAULTS", "engine.compile:1:oserror")
    with pytest.raises(OSError):
        faults.fault_point("engine.compile")
    faults.fault_point("engine.compile")  # consumed
    # changing the env raw value re-arms without any import dance
    monkeypatch.setenv("OPENSIM_FAULTS", "engine.compile:1:timeout")
    with pytest.raises(TimeoutError):
        faults.fault_point("engine.compile")


def test_fault_spec_validation():
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.inject("no.such.point")
    with pytest.raises(ValueError, match="unknown fault exception"):
        faults.inject("cache.stale", exc="nonsense")
    with pytest.raises(ValueError, match="bad fault count"):
        faults.parse_spec("cache.stale:xyz")


def test_fault_stale_exception_is_the_real_type():
    from opensim_tpu.engine.prepcache import StaleFingerprintError

    faults.inject("cache.stale", exc="stale")
    with pytest.raises(StaleFingerprintError):
        faults.fault_point("cache.stale")


# ---------------------------------------------------------------------------
# jit cache degradation
# ---------------------------------------------------------------------------


def test_jitcache_unwritable_dir_logs_and_disables(monkeypatch, caplog, tmp_path):
    import logging

    from opensim_tpu.utils import jitcache

    blocked = tmp_path / "blocked" / "jit"

    def deny(path, exist_ok=False):
        raise OSError(13, "Permission denied")

    monkeypatch.setattr(os, "makedirs", deny)
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    with caplog.at_level(logging.WARNING, logger="opensim_tpu"):
        assert jitcache.maybe_enable(path=str(blocked)) is None
    assert any("persistent jit cache disabled" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# bench.py failure contract (NOTES invariant: exactly one JSON line)
# ---------------------------------------------------------------------------


def test_bench_failure_emits_single_json_line_and_nonzero_exit():
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "OPENSIM_FAULTS": "prep.encode:1:runtime",
        "OPENSIM_JIT_CACHE": "0",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--pods", "20", "--nodes", "4", "--no-warmup"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert proc.returncode != 0
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout + proc.stderr
    rec = json.loads(lines[0])
    assert "injected fault at prep.encode" in rec["error"]
    assert rec["stage"] == "measure"
    # no traceback leaked to stdout (stderr is the driver's to ignore)
    assert "Traceback" not in proc.stdout
