"""The measured serial baseline (tools/serial_baseline.py) must agree with
the engines and the independent kube oracle: same scheduled/unscheduled
structure as the XLA scan, and every serial decision accepted by the
oracle. This guards the baseline's incremental memoization (CarrierCounts/
MatchCounts/NodeInfo) against drift from the recompute-from-scratch oracle
semantics — a wrong baseline would corrupt every speedup claim built on it."""

import os
import random
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from tools.serial_baseline import run_serial  # noqa: E402

from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods  # noqa: E402
from opensim_tpu.engine.simulator import AppResource, prepare  # noqa: E402

from test_k8s_oracle import (  # noqa: E402
    ExtOracle,
    Oracle,
    _replay_with_scores,
    ext_app,
    ext_cluster,
    random_app,
    random_cluster,
)

pytestmark = pytest.mark.slow  # nightly tier (README: test tiering)


@pytest.mark.parametrize("seed", [3, 17, 29, 61, 97])
def test_serial_baseline_matches_oracle_and_engine(seed):
    rng = random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(4, 10))
    app = random_app(rng, rng.randrange(3, 7))
    apps = [AppResource("oracle", app)]

    scheduled, unscheduled, _es, _ss, chosen = run_serial(cluster, apps)

    # oracle replay: every serial bind feasible, every failure total
    prep = prepare(cluster, apps, node_pad=8)
    if prep is None:
        pytest.skip("empty workload")
    oracle = Oracle(cluster.nodes)
    for pod, name in zip(prep.ordered, chosen):
        if name is not None:
            node = oracle.by_name[name]
            assert oracle.feasible(pod, node), (
                f"seed={seed}: serial bound {pod.metadata.name} to {name}, "
                "oracle says infeasible"
            )
            oracle.bind(pod, node)
        else:
            feas = [n.metadata.name for n in cluster.nodes if oracle.feasible(pod, n)]
            assert not feas, (
                f"seed={seed}: serial left {pod.metadata.name} unscheduled "
                f"but {feas} are feasible"
            )

    # every serial bind must also be score-optimal per the score oracle
    idx_of = {name: i for i, name in enumerate(prep.meta.node_names)}
    serial_idx = np.array([idx_of[n] if n is not None else -1 for n in chosen])
    assert _replay_with_scores(prep, cluster, serial_idx) == 0

    # structural parity with the XLA scan
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    eng = np.asarray(out.chosen)[:P]
    assert scheduled == int((eng >= 0).sum())
    assert unscheduled == int((eng < 0).sum())


@pytest.mark.parametrize("seed", [11, 42, 123, 777])
def test_serial_baseline_matches_ext_oracle(seed):
    """GPU-share (incl. the Reserve-updated gpu-count allocatable) and
    open-local decisions replayed against the extension oracle."""
    rng = random.Random(seed)
    cluster = ext_cluster(rng, rng.randrange(3, 8))
    app = ext_app(rng, rng.randrange(8, 25))
    apps = [AppResource("ext", app)]
    _s, _u, _es, _ss, chosen = run_serial(cluster, apps)

    prep = prepare(cluster, apps, node_pad=8)
    if prep is None:
        pytest.skip("empty workload")
    oracle = ExtOracle(cluster.nodes)
    for pod, name in zip(prep.ordered, chosen):
        if name is not None:
            node = oracle.by_name[name]
            assert oracle.feasible(pod, node), (
                f"seed={seed}: serial bound {pod.metadata.name} to {name}, "
                f"ext oracle says infeasible (gpu={oracle.gpu_ok(pod, node)} "
                f"local={oracle.local_ok(pod, node)})"
            )
            oracle.bind(pod, node)
        else:
            feas = [n.metadata.name for n in cluster.nodes if oracle.feasible(pod, n)]
            assert not feas, (
                f"seed={seed}: serial left {pod.metadata.name} unscheduled "
                f"but {feas} are feasible"
            )


# ---------------------------------------------------------------------------
# C++ serial engine (native/serial_engine.cc): the measured Go-cost stand-in
# must place every pod exactly where the python pipeline does.
# ---------------------------------------------------------------------------

def _native_serial():
    from opensim_tpu.native import serial

    if not serial.available():
        pytest.skip(f"serial engine unavailable: {serial.load_error()}")
    return serial.run_serial_native


@pytest.mark.parametrize("seed", [3, 17, 29, 61, 97, 123, 250])
def test_cxx_serial_matches_python_serial(seed):
    run_native = _native_serial()
    rng = random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(4, 10))
    app = random_app(rng, rng.randrange(3, 7))
    apps = [AppResource("x", app)]
    s1, u1, _, _, c1 = run_serial(cluster, apps)
    s2, u2, _, _, c2 = run_native(cluster, apps)
    assert (s1, u1) == (s2, u2)
    assert c1 == c2, f"seed={seed}: placements diverge"


@pytest.mark.parametrize("seed", [501, 502, 77, 1234, 31, 999])
def test_cxx_serial_matches_python_serial_ext(seed):
    """GPU-share + open-local workloads: device binpack and VG/exclusive
    device choices must agree bind-for-bind."""
    run_native = _native_serial()
    rng = random.Random(seed)
    cluster = ext_cluster(rng, rng.randrange(4, 9))
    app = ext_app(rng, rng.randrange(3, 7))
    apps = [AppResource("x", app)]
    _, _, _, _, c1 = run_serial(cluster, apps)
    _, _, _, _, c2 = run_native(cluster, apps)
    assert c1 == c2, f"seed={seed}: ext placements diverge"


def test_cxx_serial_matches_python_on_examples():
    from tools.serial_baseline import _REPO, _example

    run_native = _native_serial()
    for name in ("simon-config.yaml", "simon-gpushare-config.yaml"):
        path = os.path.join(_REPO, "example", name)
        cluster, apps = _example(path)
        s1, u1, _, _, c1 = run_serial(cluster, apps)
        s2, u2, _, _, c2 = run_native(cluster, apps)
        assert (s1, u1, c1) == (s2, u2, c2), path
