"""Randomized parity tests: the vectorized device kernels must agree with
the host-side golden implementations in ``opensim_tpu/models/selectors.py``
on every (template, node) pair. This is the per-kernel unit layer the
reference lacks (SURVEY.md §4)."""

import pytest
import random

import numpy as np

from opensim_tpu.encoding.state import ClusterEncoder
from opensim_tpu.models import ResourceTypes, fixtures as fx, selectors
from opensim_tpu.models.objects import Node, Pod
from opensim_tpu.ops import kernels

KEYS = ["zone", "disk", "role", "tier"]
VALUES = ["a", "b", "c", "1", "2", "10"]
EFFECTS = ["NoSchedule", "PreferNoSchedule", "NoExecute"]
OPS = ["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"]


def random_node(rng: random.Random, i: int) -> Node:
    labels = {k: rng.choice(VALUES) for k in KEYS if rng.random() < 0.6}
    taints = [
        {"key": rng.choice(KEYS), "value": rng.choice(VALUES), "effect": rng.choice(EFFECTS)}
        for _ in range(rng.randrange(0, 3))
    ]
    return fx.make_fake_node(f"n{i}", "8", "16Gi", "110", fx.with_labels(labels), fx.with_taints(taints))


def random_pod(rng: random.Random, i: int) -> Pod:
    opts = []
    if rng.random() < 0.5:
        opts.append(fx.with_node_selector({rng.choice(KEYS): rng.choice(VALUES)}))
    if rng.random() < 0.6:
        terms = []
        for _ in range(rng.randrange(1, 3)):
            exprs = []
            for _ in range(rng.randrange(1, 3)):
                op = rng.choice(OPS)
                expr = {"key": rng.choice(KEYS), "operator": op}
                if op in ("In", "NotIn"):
                    expr["values"] = rng.sample(VALUES, rng.randrange(1, 3))
                elif op in ("Gt", "Lt"):
                    expr["values"] = [rng.choice(["1", "5", "10"])]
                exprs.append(expr)
            terms.append({"matchExpressions": exprs})
        opts.append(
            fx.with_affinity(
                {"nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": {"nodeSelectorTerms": terms}}}
            )
        )
    if rng.random() < 0.6:
        tols = []
        for _ in range(rng.randrange(1, 3)):
            op = rng.choice(["Equal", "Exists"])
            tol = {"key": rng.choice(KEYS), "operator": op}
            if op == "Equal":
                tol["value"] = rng.choice(VALUES)
            if rng.random() < 0.7:
                tol["effect"] = rng.choice(EFFECTS)
            tols.append(tol)
        opts.append(fx.with_tolerations(tols))
    return fx.make_fake_pod(f"p{i}", "100m", "128Mi", *opts)


@pytest.mark.slow
def test_static_filter_kernels_match_host_golden():
    rng = random.Random(42)
    nodes = [random_node(rng, i) for i in range(24)]
    pods = [random_pod(rng, i) for i in range(40)]

    enc = ClusterEncoder()
    enc.add_nodes(nodes)
    tmpl_ids = [enc.add_pod(p) for p in pods]
    ec, st0, meta = enc.build()
    from opensim_tpu.engine.scheduler import to_device

    ec, st0 = to_device(ec, st0)
    stat = kernels.precompute_static(ec)
    taint_mask = np.asarray(stat.taint_mask) if hasattr(stat, "taint_mask") else None
    aff_mask = np.asarray(stat.aff_mask)
    static_pass = np.asarray(stat.static_pass)
    tt_raw = np.asarray(stat.tt_raw)

    for p, u in zip(pods, tmpl_ids):
        for i, node in enumerate(nodes):
            want_aff = selectors.pod_matches_node_selector_and_affinity(p, node)
            assert bool(aff_mask[u, i]) == want_aff, (
                f"affinity mismatch pod={p.metadata.name} node={node.metadata.name}: "
                f"kernel={bool(aff_mask[u, i])} host={want_aff}"
            )
            want_taint = (
                selectors.find_untolerated_taint(node.taints, p.spec.tolerations) is None
            )
            want_pass = want_aff and want_taint
            assert bool(static_pass[u, i]) == want_pass, (
                f"static_pass mismatch pod={p.metadata.name} node={node.metadata.name}"
            )
            want_tt = selectors.count_intolerable_prefer_no_schedule(p, node)
            assert int(tt_raw[u, i]) == want_tt, (
                f"PreferNoSchedule count mismatch pod={p.metadata.name} node={node.metadata.name}"
            )


@pytest.mark.slow
def test_share_score_matches_reference_formula():
    """share_raw must equal the Simon plugin formula (plugin/simon.go:57-68
    + algo.Share) computed by hand."""
    nodes = [fx.make_fake_node("n0", "4", "8Gi", "110")]
    pods = [fx.make_fake_pod("p0", "1", "2Gi")]
    enc = ClusterEncoder()
    enc.add_nodes(nodes)
    u = enc.add_pod(pods[0])
    ec, st0, meta = enc.build()
    from opensim_tpu.engine.scheduler import to_device

    ec, st0 = to_device(ec, st0)
    stat = kernels.precompute_static(ec)
    raw = float(np.asarray(stat.share_raw)[u, 0])
    # shares: cpu 1000m/(4000-1000)=1/3; mem 2Gi/(8-2)Gi=1/3; pods 0
    assert abs(raw - (1 / 3) * 100) < 1e-3


def test_daemonset_eligibility_matches_engine():
    """node_should_run_pod (host) and the engine must agree on where DS pods
    land — mirrored from checkResult's recomputation (core_test.go:472-479)."""
    rng = random.Random(7)
    nodes = [random_node(rng, i) for i in range(10)]
    ds = fx.make_fake_daemon_set(
        "agent", "10m", "16Mi", fx.with_node_selector({"disk": "a"}), fx.with_tolerations([{"operator": "Exists"}])
    )
    from opensim_tpu.engine.simulator import AppResource, simulate

    cluster = ResourceTypes()
    cluster.nodes = nodes
    app = ResourceTypes()
    app.daemon_sets.append(ds)
    res = simulate(cluster, [AppResource("a", app)])
    from opensim_tpu.models.expand import _daemon_pod_for_node

    expected = {
        n.metadata.name
        for n in nodes
        if selectors.node_should_run_pod(n, _daemon_pod_for_node(ds, n.metadata.name))
    }
    got = {ns.node.metadata.name for ns in res.node_status if ns.pods}
    assert got == expected
    assert not res.unscheduled_pods


# ---------------------------------------------------------------------------
# dynamic gpu-count allocatable (PARITY divergence #3, now closed): the
# reference rewrites a device-bearing node's gpu-count allocatable to the
# count of not-fully-used devices at gpushare Reserve
# (open-gpu-share.go:147-188 -> gpunodeinfo.go:354-369), feeding later
# NodeResourcesFit checks and Simon/GpuShare share scores for pods that
# request alibabacloud.com/gpu-count as a SPEC resource.
# ---------------------------------------------------------------------------


def test_gpu_count_allocatable_decrements_for_fit():
    """A whole-GPU pod requesting gpu-count=2 must NOT fit once a sharing
    pod has fully used one of the node's two devices (static allocatable
    would wrongly admit it)."""
    from opensim_tpu.engine.simulator import AppResource, simulate

    rt = ResourceTypes()
    rt.nodes.append(fx.make_fake_node(
        "g0", "32", "64Gi", "110",
        fx.with_allocatable({"alibabacloud.com/gpu-mem": "16Gi",
                             "alibabacloud.com/gpu-count": "2"}),
    ))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod(
        "share", "100m", "128Mi",
        fx.with_annotations({"alibabacloud.com/gpu-mem": "8Gi",
                             "alibabacloud.com/gpu-count": "1"}),
    ))
    app.pods.append(fx.make_fake_pod(
        "whole", "100m", "128Mi",
        fx.with_requests({"alibabacloud.com/gpu-count": "2"}),
    ))
    result = simulate(rt, [AppResource("a", app)], node_pad=8)
    assert "share" in [p.metadata.name for p in result.pods_on("g0")]
    unsched = {up.pod.metadata.name: up.reason for up in result.unscheduled_pods}
    assert "whole" in unsched, "static allocatable would wrongly admit the pod"
    assert "Insufficient alibabacloud.com/gpu-count" in unsched["whole"]


def test_gpu_count_decrement_feeds_share_score():
    """Binpack placement must follow the Reserve-updated allocatable: with
    g0 (4 devices, 2 filled -> dyn 2) and g1 (3 free devices), a whole-GPU
    pod requesting gpu-count=1 shares 1/(2-1)=1.0 on g0 vs 1/(3-1)=0.5 on
    g1 and must land on g0; the static view (1/3 vs 1/2) would pick g1."""
    from opensim_tpu.engine.simulator import AppResource, simulate

    rt = ResourceTypes()
    rt.nodes.append(fx.make_fake_node(
        "g0", "32", "64Gi", "110",
        fx.with_allocatable({"alibabacloud.com/gpu-mem": "32Gi",
                             "alibabacloud.com/gpu-count": "4"}),
    ))
    rt.nodes.append(fx.make_fake_node(
        "g1", "32", "64Gi", "110",
        fx.with_allocatable({"alibabacloud.com/gpu-mem": "24Gi",
                             "alibabacloud.com/gpu-count": "3"}),
    ))
    app = ResourceTypes()
    for k in range(2):  # fill two of g0's four 8Gi devices exactly
        app.pods.append(fx.make_fake_pod(
            f"fill-{k}", "0", "0",
            fx.with_node_name("g0"),
            fx.with_annotations({"alibabacloud.com/gpu-mem": "8Gi",
                                 "alibabacloud.com/gpu-count": "1"}),
        ))
    app.pods.append(fx.make_fake_pod(
        "whole", "0", "0",
        fx.with_requests({"alibabacloud.com/gpu-count": "1"}),
    ))
    result = simulate(rt, [AppResource("a", app)], node_pad=8)
    assert not result.unscheduled_pods
    assert "whole" in [p.metadata.name for p in result.pods_on("g0")], (
        "share score must use the Reserve-updated gpu-count allocatable"
    )


def test_whole_gpu_only_workload_keeps_static_share():
    """With NO gpushare-annotation pods, devices never fill and the
    reference's Reserve never rewrites allocatable — the gpu-count share
    must be the plain static share (regression: the column exclusion in
    share_raw must mirror Features.gc_dyn exactly, or whole-GPU-only
    workloads lose the term and binpack degenerates to lowest-index)."""
    from opensim_tpu.engine.simulator import AppResource, simulate

    rt = ResourceTypes()
    rt.nodes.append(fx.make_fake_node(
        "g0", "32", "64Gi", "110",
        fx.with_allocatable({"alibabacloud.com/gpu-mem": "32Gi",
                             "alibabacloud.com/gpu-count": "4"}),
    ))
    rt.nodes.append(fx.make_fake_node(
        "g1", "32", "64Gi", "110",
        fx.with_allocatable({"alibabacloud.com/gpu-mem": "16Gi",
                             "alibabacloud.com/gpu-count": "2"}),
    ))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod(
        "whole", "0", "0",
        fx.with_requests({"alibabacloud.com/gpu-count": "1"}),
    ))
    result = simulate(rt, [AppResource("a", app)], node_pad=8)
    assert not result.unscheduled_pods
    # static shares: 1/(4-1) on g0 vs 1/(2-1) on g1 -> binpack picks g1
    assert "whole" in [p.metadata.name for p in result.pods_on("g1")]
