"""Live-cluster snapshot coverage with a stub `kubernetes` client module —
the filtering rules of CreateClusterResourceFromClient
(pkg/simulator/simulator.go:503-601) and the server's informer-style
snapshot caching (pkg/server/server.go:97-137), testable without a real
cluster or the kubernetes package."""

import sys
import types

import pytest

from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx


def _pod(name, phase="Running", owners=None, deleting=False, node=""):
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "100m"}}}]},
        "status": {"phase": phase},
    }
    if node:
        d["spec"]["nodeName"] = node
    if owners:
        d["metadata"]["ownerReferences"] = owners
    if deleting:
        d["metadata"]["deletionTimestamp"] = "2026-01-01T00:00:00Z"
    return d


class _L:
    def __init__(self, items):
        self.items = items


def _install_fake_kubernetes(monkeypatch, store, calls):
    def _note_rv(field, resource_version):
        # every list passes resourceVersion=0 (serve-from-cache), not just
        # pods — recorded per field so the test can assert the full set
        calls.setdefault("resource_versions", {})[field] = resource_version
        calls["resource_version"] = resource_version

    class CoreV1Api:
        def list_node(self, resource_version=None):
            _note_rv("nodes", resource_version)
            return _L(store.get("nodes", []))

        def list_pod_for_all_namespaces(self, resource_version=None):
            _note_rv("pods", resource_version)
            return _L(store.get("pods", []))

        def list_service_for_all_namespaces(self, resource_version=None):
            _note_rv("services", resource_version)
            return _L(store.get("services", []))

        def list_persistent_volume_claim_for_all_namespaces(self, resource_version=None):
            _note_rv("pvcs", resource_version)
            return _L(store.get("pvcs", []))

        def list_config_map_for_all_namespaces(self, resource_version=None):
            _note_rv("config_maps", resource_version)
            return _L(store.get("config_maps", []))

    class AppsV1Api:
        def list_daemon_set_for_all_namespaces(self, resource_version=None):
            _note_rv("daemon_sets", resource_version)
            return _L(store.get("daemon_sets", []))

    class PolicyV1Api:
        def list_pod_disruption_budget_for_all_namespaces(self, resource_version=None):
            calls["policy_api"] = "v1"
            _note_rv("pdbs", resource_version)
            return _L(store.get("pdbs", []))

    class StorageV1Api:
        def list_storage_class(self, resource_version=None):
            _note_rv("storage_classes", resource_version)
            return _L(store.get("storage_classes", []))

    class ApiClient:
        def sanitize_for_serialization(self, obj):
            return obj

    client = types.ModuleType("kubernetes.client")
    client.CoreV1Api = CoreV1Api
    client.AppsV1Api = AppsV1Api
    client.PolicyV1Api = PolicyV1Api
    client.StorageV1Api = StorageV1Api
    client.ApiClient = ApiClient

    config = types.ModuleType("kubernetes.config")

    def load_kube_config(config_file=None):
        calls["kubeconfig"] = config_file

    config.load_kube_config = load_kube_config

    kubernetes = types.ModuleType("kubernetes")
    kubernetes.client = client
    kubernetes.config = config
    monkeypatch.setitem(sys.modules, "kubernetes", kubernetes)
    monkeypatch.setitem(sys.modules, "kubernetes.client", client)
    monkeypatch.setitem(sys.modules, "kubernetes.config", config)


def test_snapshot_filters_match_reference(monkeypatch):
    """Running + Pending pods only; skip DaemonSet-owned and deleting pods;
    pods listed with ResourceVersion=0 (simulator.go:524-540)."""
    store = {
        "nodes": [
            {"apiVersion": "v1", "kind": "Node", "metadata": {"name": "n1"},
             "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}}},
        ],
        "pods": [
            _pod("keep-running", "Running", node="n1"),
            _pod("keep-pending", "Pending"),
            _pod("skip-succeeded", "Succeeded"),
            _pod("skip-failed", "Failed"),
            _pod("skip-ds-owned", "Running",
                 owners=[{"kind": "DaemonSet", "name": "agent", "controller": True}]),
            _pod("keep-rs-owned", "Running",
                 owners=[{"kind": "ReplicaSet", "name": "web-abc", "controller": True}]),
            _pod("skip-deleting", "Running", deleting=True),
        ],
        "daemon_sets": [
            {"apiVersion": "apps/v1", "kind": "DaemonSet",
             "metadata": {"name": "agent", "namespace": "default"},
             "spec": {"selector": {"matchLabels": {"a": "b"}},
                      "template": {"metadata": {"labels": {"a": "b"}},
                                   "spec": {"containers": [{"name": "c"}]}}}},
        ],
        "services": [{"kind": "Service", "metadata": {"name": "svc"}}],
        "storage_classes": [{"kind": "StorageClass", "metadata": {"name": "open-local-lvm"}}],
        "pvcs": [{"kind": "PersistentVolumeClaim", "metadata": {"name": "pvc-1"}}],
        "config_maps": [{"kind": "ConfigMap", "metadata": {"name": "cm-1"}}],
        "pdbs": [{"kind": "PodDisruptionBudget", "metadata": {"name": "pdb-1"}}],
    }
    calls = {}
    _install_fake_kubernetes(monkeypatch, store, calls)
    from opensim_tpu.server.snapshot import cluster_from_kubeconfig

    rt = cluster_from_kubeconfig("/tmp/kubeconfig")
    assert calls["kubeconfig"] == "/tmp/kubeconfig"
    assert calls["resource_version"] == "0"
    # consistent list semantics: EVERY endpoint listed with resourceVersion=0
    assert set(calls["resource_versions"]) == {
        "nodes", "pods", "daemon_sets", "pdbs", "services",
        "storage_classes", "pvcs", "config_maps",
    }
    assert all(v == "0" for v in calls["resource_versions"].values())
    assert calls["policy_api"] == "v1"
    assert [n.metadata.name for n in rt.nodes] == ["n1"]
    assert sorted(p.metadata.name for p in rt.pods) == [
        "keep-pending", "keep-rs-owned", "keep-running",
    ]
    assert rt.pods[0].phase in ("Running", "Pending")
    assert [d.metadata.name for d in rt.daemon_sets] == ["agent"]
    assert len(rt.services) == 1 and len(rt.storage_classes) == 1
    assert len(rt.pvcs) == 1 and len(rt.config_maps) == 1 and len(rt.pdbs) == 1


def test_snapshot_missing_client_falls_back_to_rest(monkeypatch, tmp_path):
    """Without the kubernetes package the stdlib REST fallback takes over
    (round 5); a kubeconfig with no reachable server still fails clearly."""
    for mod in ("kubernetes", "kubernetes.client", "kubernetes.config"):
        monkeypatch.delitem(sys.modules, mod, raising=False)
    monkeypatch.setitem(sys.modules, "kubernetes", None)  # force ImportError
    from opensim_tpu.server.snapshot import cluster_from_kubeconfig

    empty = tmp_path / "kubeconfig"
    empty.write_text("apiVersion: v1\nkind: Config\n")
    with pytest.raises(RuntimeError, match="no cluster server"):
        cluster_from_kubeconfig(str(empty))


def test_server_caches_snapshot_between_requests(monkeypatch):
    """The reference serves requests from its warm informer cache
    (server.go:97-137); SimonServer caches the snapshot with a TTL instead
    of re-listing the cluster per request."""
    from opensim_tpu.server import rest

    fetches = []

    def fake_fetch(kubeconfig, master=None):
        fetches.append(kubeconfig)
        rt = ResourceTypes()
        rt.nodes.append(fx.make_fake_node("n1", "8", "16Gi"))
        return rt

    monkeypatch.setattr(rest, "cluster_from_kubeconfig", fake_fetch)
    srv = rest.SimonServer(kubeconfig="/tmp/kc", snapshot_ttl_s=3600.0)
    a = srv.current_cluster()
    b = srv.current_cluster()
    # one cluster list serves both requests, but each request gets its OWN
    # copy — simulate() mutates pods in place and must not taint the cache
    assert fetches == ["/tmp/kc"]
    assert a is not b
    a.nodes[0].metadata.labels["tainted-by-request"] = "yes"
    assert "tainted-by-request" not in srv.current_cluster().nodes[0].metadata.labels

    # TTL expiry forces a refresh
    srv._snapshot_at -= 7200.0
    srv.current_cluster()
    assert len(fetches) == 2

    # ttl<=0 disables caching: every call re-lists
    srv2 = rest.SimonServer(kubeconfig="/tmp/kc", snapshot_ttl_s=0.0)
    srv2.current_cluster()
    srv2.current_cluster()
    assert len(fetches) == 4


def test_recorded_snapshot_round_trip(monkeypatch):
    """Recorded apiserver JSON → cluster_from_kubeconfig → ResourceTypes →
    simulate: the kubeconfig path exercised end-to-end past decode
    (simulator.go:503-601 + the deploy-apps flow). Pre-bound pods replay as
    forced binds on their recorded nodes, the recorded pending pod and a
    new app schedule onto untainted workers, and daemonset expansion covers
    every eligible node."""
    import json
    import os

    with open(os.path.join(os.path.dirname(__file__), "fixtures", "live_snapshot.json")) as f:
        store = json.load(f)
    calls = {}
    _install_fake_kubernetes(monkeypatch, store, calls)
    from opensim_tpu.server.snapshot import cluster_from_kubeconfig

    rt = cluster_from_kubeconfig("/tmp/kubeconfig")

    # decode-level checks: filters applied, objects landed in their slots
    assert [n.metadata.name for n in rt.nodes] == [
        "prod-worker-1", "prod-worker-2", "prod-master-1",
    ]
    assert sorted(p.metadata.name for p in rt.pods) == [
        "batch-import-1", "web-7d4b9c-k2xzq", "web-7d4b9c-m8trw",
    ]  # ds-owned, deleting, and Succeeded pods all filtered
    assert rt.nodes[2].taints[0].key == "node-role.kubernetes.io/master"
    assert rt.nodes[0].allocatable["cpu"] == 15.6  # 15600m
    assert len(rt.daemon_sets) == 1 and len(rt.pdbs) == 1
    assert len(rt.services) == len(rt.storage_classes) == 1
    assert len(rt.pvcs) == len(rt.config_maps) == 1

    # round-trip: simulate the snapshot plus a new deployment (deploy-apps)
    from opensim_tpu.engine.simulator import AppResource, simulate

    app = ResourceTypes()
    app.deployments.append(
        fx.make_fake_deployment("rollout", 4, "1", "2Gi",
                                fx.with_namespace("shop"))
    )
    res = simulate(rt, [AppResource("rollout", app)])
    assert not res.unscheduled_pods, [
        (u.pod.metadata.name, u.reason) for u in res.unscheduled_pods
    ]
    placed = {p.metadata.name: ns.node.metadata.name
              for ns in res.node_status for p in ns.pods}
    # recorded bindings replay exactly
    assert placed["web-7d4b9c-k2xzq"] == "prod-worker-1"
    assert placed["web-7d4b9c-m8trw"] == "prod-worker-2"
    # the recorded pending pod lands on an untainted worker
    assert placed["batch-import-1"].startswith("prod-worker")
    # daemonset pods expand onto every node (tolerates the master taint)
    ds_pods = [n for n in placed if n.startswith("node-agent")]
    assert len(ds_pods) == 3
    # the new deployment spreads over the two schedulable workers only
    rollout_nodes = {placed[n] for n in placed if n.startswith("rollout")}
    assert rollout_nodes == {"prod-worker-1", "prod-worker-2"}


# ---------------------------------------------------------------------------
# stub-apiserver e2e (VERDICT r4 #8): the stdlib REST fallback drives the
# full kubeConfig-mode `simon apply` pipeline against a canned HTTP server
# ---------------------------------------------------------------------------


def _stub_apiserver(payloads):
    """~40-line fake apiserver: GET the kube list endpoints, serve canned
    kind: List JSON; everything else 404."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path not in payloads:
                self.send_response(404)
                self.end_headers()
                return
            body = json.dumps({"kind": "List", "items": payloads[path]}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def test_apply_against_stub_apiserver(tmp_path):
    """End-to-end: kubeConfig-mode Applier.run() lists the cluster from a
    stub apiserver over HTTP (no kubernetes package in this image), binds
    the snapshot's Running pod as forced, schedules the app, and reports."""
    import sys as _sys

    assert "kubernetes" not in _sys.modules or not getattr(
        _sys.modules.get("kubernetes"), "__file__", None
    )

    node = fx.make_fake_node("live-1", "8", "16Gi").raw
    node2 = fx.make_fake_node("live-2", "8", "16Gi").raw
    payloads = {
        "/api/v1/nodes": [node, node2],
        "/api/v1/pods": [
            _pod("bound", phase="Running", node="live-1"),
            _pod("finished", phase="Succeeded", node="live-1"),  # filtered
            _pod(
                "ds-owned",
                phase="Running",
                node="live-2",
                owners=[{"kind": "DaemonSet", "name": "d", "uid": "u1"}],
            ),  # filtered (re-expanded from the DS)
        ],
        "/apis/apps/v1/daemonsets": [],
        "/apis/policy/v1/poddisruptionbudgets": [],
        "/api/v1/services": [],
        "/apis/storage.k8s.io/v1/storageclasses": [],
        "/api/v1/persistentvolumeclaims": [],
        "/api/v1/configmaps": [],
    }
    httpd = _stub_apiserver(payloads)
    try:
        port = httpd.server_address[1]
        kubeconfig = tmp_path / "kubeconfig"
        kubeconfig.write_text(
            "apiVersion: v1\nkind: Config\ncurrent-context: stub\n"
            "contexts:\n  - name: stub\n    context: {cluster: stub, user: stub}\n"
            f"clusters:\n  - name: stub\n    cluster: {{server: 'http://127.0.0.1:{port}'}}\n"
            "users:\n  - name: stub\n    user: {token: stub-token}\n"
        )
        appdir = tmp_path / "app"
        appdir.mkdir()
        import yaml as _yaml

        (appdir / "deploy.yaml").write_text(
            _yaml.safe_dump(fx.make_fake_deployment("web", 3, "500m", "512Mi").raw)
        )
        cfg = tmp_path / "simon-config.yaml"
        cfg.write_text(
            "apiVersion: simon/v1alpha1\nkind: Config\nmetadata: {name: live}\n"
            "spec:\n"
            f"  cluster: {{kubeConfig: '{kubeconfig}'}}\n"
            "  appList:\n"
            f"    - {{name: webapp, path: '{appdir}'}}\n"
        )
        from opensim_tpu.planner.apply import Applier, Options

        out = tmp_path / "report.txt"
        rc = Applier(Options(simon_config=str(cfg), output_file=str(out))).run()
        text = out.read_text()
        assert rc == 0, text
        assert "Simulation success!" in text
        assert "live-1" in text and "live-2" in text
        # the snapshot's Running pod re-bound as forced onto live-1: its
        # 100m shows in live-1's requests alongside any app pods
        assert "webapp" in text
    finally:
        httpd.shutdown()


# ---------------------------------------------------------------------------
# ISSUE 6 satellites: minimal-RBAC tolerance, shared list path, timeout knob
# ---------------------------------------------------------------------------


def _minimal_stub(tmp_path, forbidden=()):
    from opensim_tpu.server.stubapi import StubApiServer

    stub = StubApiServer().start()
    stub.seed("/api/v1/nodes", [fx.make_fake_node("n1", "8", "16Gi").raw])
    stub.seed("/api/v1/pods", [_pod("p1", "Running", node="n1")])
    for path in (
        "/apis/apps/v1/daemonsets",
        "/apis/policy/v1/poddisruptionbudgets",
        "/api/v1/services",
        "/apis/storage.k8s.io/v1/storageclasses",
        "/api/v1/persistentvolumeclaims",
        "/api/v1/configmaps",
    ):
        stub.seed(path, [])
    stub.forbidden_paths.update(forbidden)
    return stub, stub.kubeconfig(tmp_path)


def test_minimal_rbac_403_on_optional_endpoints_tolerated(tmp_path):
    """A read-only nodes+pods ServiceAccount 403s services and config maps
    too — the whole optional-endpoint set yields empty lists instead of
    failing the snapshot."""
    from opensim_tpu.server.snapshot import _cluster_via_rest

    stub, kc = _minimal_stub(
        tmp_path,
        forbidden=(
            "/api/v1/services",
            "/api/v1/configmaps",
            "/apis/policy/v1/poddisruptionbudgets",
            "/apis/storage.k8s.io/v1/storageclasses",
            "/api/v1/persistentvolumeclaims",
        ),
    )
    try:
        rt, rvs = _cluster_via_rest(kc, None)
        assert [n.metadata.name for n in rt.nodes] == ["n1"]
        assert [p.metadata.name for p in rt.pods] == ["p1"]
        assert rt.services == [] and rt.config_maps == []
        assert rt.pdbs == [] and rt.storage_classes == [] and rt.pvcs == []
        # forbidden endpoints record no list resourceVersion
        assert "services" not in rvs and "config_maps" not in rvs
        assert rvs["nodes"] and rvs["pods"]
    finally:
        stub.stop()


def test_required_endpoint_403_still_fails(tmp_path):
    """Only the OPTIONAL set is 403-tolerant: nodes/pods are load-bearing
    and an RBAC hole there must surface, not serve an empty cluster."""
    import pytest as _pytest

    from opensim_tpu.server.snapshot import _cluster_via_rest

    stub, kc = _minimal_stub(tmp_path, forbidden=("/api/v1/pods",))
    try:
        with _pytest.raises(RuntimeError, match="HTTP 403"):
            _cluster_via_rest(kc, None)
    finally:
        stub.stop()


def test_every_rest_list_passes_resource_version_zero(tmp_path):
    """Consistent list semantics (one code path for polling and watch
    bootstrap): every list endpoint is queried with resourceVersion=0 and
    its list-level resourceVersion is captured."""
    from opensim_tpu.server.snapshot import RESOURCES, _cluster_via_rest

    stub, kc = _minimal_stub(tmp_path)
    try:
        rt, rvs = _cluster_via_rest(kc, None)
        lists = [(p, q) for p, q in stub.requests_seen if "watch" not in q]
        assert {p for p, _q in lists} == {spec.path for spec in RESOURCES}
        assert all(q.get("resourceVersion") == ["0"] for _p, q in lists)
        assert set(rvs) == {spec.field for spec in RESOURCES}
        assert all(v.isdigit() for v in rvs.values())
    finally:
        stub.stop()


def test_snapshot_timeout_knob_validated_and_plumbed(monkeypatch):
    from opensim_tpu.server import snapshot as snap

    assert snap.snapshot_timeout_s() == 60.0  # the old hardcoded default
    monkeypatch.setenv("OPENSIM_SNAPSHOT_TIMEOUT_S", "7.5")
    assert snap.snapshot_timeout_s() == 7.5

    seen = {}

    class _Resp:
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

        def read(self):
            return b'{"items": [], "metadata": {"resourceVersion": "1"}}'

    def fake_urlopen(req, timeout=None, context=None):
        seen["timeout"] = timeout
        return _Resp()

    monkeypatch.setattr(snap.urllib.request, "urlopen", fake_urlopen)
    got = snap.list_resource("http://x", {}, None, snap.RESOURCE_BY_FIELD["nodes"])
    assert got == ([], "1")
    assert seen["timeout"] == 7.5

    monkeypatch.setenv("OPENSIM_SNAPSHOT_TIMEOUT_S", "a minute")
    with pytest.raises(ValueError, match="OPENSIM_SNAPSHOT_TIMEOUT_S"):
        snap.snapshot_timeout_s()
    monkeypatch.setenv("OPENSIM_SNAPSHOT_TIMEOUT_S", "-1")
    with pytest.raises(ValueError, match="positive"):
        snap.snapshot_timeout_s()
