"""Engine attribution (VERDICT r4 #3): SimulateResult.engine records which
scheduling engine ran and why the others were skipped; envelope misses are
logged, never silent; bench.py and the apply report surface the decision."""

import logging

import pytest

from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx


def _mini_cluster(n=4):
    rt = ResourceTypes()
    for i in range(n):
        rt.nodes.append(
            fx.make_fake_node(
                f"n{i}", "8", "16Gi", "110", fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 2}"})
            )
        )
    return rt


def _apps(n_pods=6, opts=()):
    rt = ResourceTypes()
    rt.deployments.append(fx.make_fake_deployment("app", n_pods, "100m", "128Mi", *opts))
    return [AppResource("app", rt)]


def test_engine_recorded_on_cpu_host():
    """On an accelerator-less host the C++ engine owns the run; the
    megakernel skip reason names the missing TPU backend."""
    res = simulate(_mini_cluster(), _apps())
    assert res.engine is not None
    assert res.engine.name in ("native", "xla")
    assert "megakernel" in res.engine.skipped
    assert "no TPU backend" in res.engine.skipped["megakernel"]
    if res.engine.name == "xla":  # native engine failed to build on this host
        assert "native" in res.engine.skipped
    # the decision renders as one human-readable line (report footer)
    line = res.engine.describe()
    assert res.engine.name in line and "megakernel" in line


def test_extra_plugins_force_xla_with_reasons():
    import jax.numpy as jnp

    noop = ("filter", lambda ec, st, u: jnp.ones((ec.node_valid.shape[0],), bool))
    res = simulate(_mini_cluster(), _apps(), extra_plugins=(noop,))
    assert res.engine.name == "xla"
    assert "extra_plugins" in res.engine.skipped["megakernel"]
    assert "extra_plugins" in res.engine.skipped["native"]


def test_envelope_miss_is_logged(monkeypatch, caplog):
    """A workload outside the megakernel envelope (5 non-hostname topology
    keys) must log the miss and record it in the skip map."""
    monkeypatch.setenv("OPENSIM_FASTPATH", "interpret")
    cluster = ResourceTypes()
    keys = [f"example.com/tier-{k}" for k in range(5)]
    for i in range(4):
        labels = {k: f"v{i % 2}" for k in keys}
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi", "110", fx.with_labels(labels)))
    apps = ResourceTypes()
    for w, key in enumerate(keys):
        apps.deployments.append(
            fx.make_fake_deployment(
                f"w{w}",
                2,
                "100m",
                "128Mi",
                fx.with_topology_spread(
                    [
                        {
                            "maxSkew": 3,
                            "topologyKey": key,
                            "whenUnsatisfiable": "ScheduleAnyway",
                            "labelSelector": {"matchLabels": {"app": f"w{w}"}},
                        }
                    ]
                ),
            )
        )
    with caplog.at_level(logging.INFO, logger="opensim_tpu"):
        res = simulate(cluster, [AppResource("a", apps)])
    assert res.engine.name in ("native", "xla")
    assert "topology keys" in res.engine.skipped["megakernel"]
    assert any("envelope miss" in r.message for r in caplog.records)


def test_megakernel_attributed_in_interpret_mode(monkeypatch):
    monkeypatch.setenv("OPENSIM_FASTPATH", "interpret")
    res = simulate(_mini_cluster(), _apps())
    assert res.engine.name == "megakernel"
    assert "megakernel" not in res.engine.skipped


def test_require_tpu_makes_kernel_failure_fatal(monkeypatch):
    """--backend tpu (OPENSIM_REQUIRE_TPU=1) turns a megakernel failure into
    a hard error instead of a silent fallback."""
    from opensim_tpu.engine import fastpath

    monkeypatch.setenv("OPENSIM_REQUIRE_TPU", "1")
    monkeypatch.delenv("OPENSIM_FASTPATH", raising=False)

    # make the megakernel "applicable" then blow up inside it, as a Mosaic
    # compile failure on real silicon would
    monkeypatch.setattr(fastpath, "why_not", lambda prep, config=None: None)

    def boom(*a, **k):
        raise ValueError("mosaic says no")

    monkeypatch.setattr(fastpath, "schedule", boom)
    # pretending to be a TPU backend is what arms the fastpath branch
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with pytest.raises(RuntimeError, match="refusing to silently fall back"):
        simulate(_mini_cluster(), _apps())
