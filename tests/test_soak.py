"""Soak / leak tripwire (ISSUE 12 satellite).

Drives repeated watch-storm + serve iterations against one process and
asserts every bounded structure actually stays bounded — prep-cache
entries (LRU capacity), the flight-recorder/timeline rings, the journal's
segment set (checkpoint pruning) — and that the process RSS delta over
the soak stays inside a generous envelope (a real per-iteration leak of
even a few MB would blow it; allocator noise and warm jit caches do not).

Tier-1 runs the small-N variant; the slow tier runs a longer soak with
the same assertions.
"""

import os

import pytest

from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx
from opensim_tpu.obs.capacity import CapacityEngine
from opensim_tpu.obs.footprint import process_memory
from opensim_tpu.server import rest
from opensim_tpu.server.journal import Journal
from opensim_tpu.server.watch import ClusterTwin


def _cluster(nodes=6, bound=10):
    rt = ResourceTypes()
    for i in range(nodes):
        rt.nodes.append(fx.make_fake_node(f"n{i}", "16", "64Gi"))
    for i in range(bound):
        rt.pods.append(
            fx.make_fake_pod(f"b{i:02d}", "250m", "512Mi", fx.with_node_name(f"n{i % nodes}"))
        )
    return rt


def _storm_iteration(i, twin, capacity, journal, rv):
    """One watch-storm wave: pod adds, node-bound adds, deletes (tombstones
    included) through the twin's apply path, the capacity feed, and the
    journal — the live dispatch pipeline without sockets."""
    for j in range(20):
        rv += 1
        name = f"storm-{i:04d}-{j:02d}"
        obj = {
            "metadata": {"name": name, "namespace": "soak", "resourceVersion": str(rv)},
            "spec": {
                "containers": [
                    {"name": "c", "resources": {"requests": {"cpu": "100m", "memory": "128Mi"}}}
                ],
                "nodeName": f"n{j % 4}" if j % 2 else "",
            },
            "status": {"phase": "Running" if j % 2 else "Pending"},
        }
        change = twin.apply_event("pods", "ADDED", obj)
        if change is not None:
            capacity.on_twin_change("pods", "ADDED", obj, change, twin.generation)
        journal.record_event("pods", "ADDED", obj, twin.generation)
    for j in range(20):  # delete the whole wave: net-zero state per iteration
        rv += 1
        name = f"storm-{i:04d}-{j:02d}"
        obj = {"metadata": {"name": name, "namespace": "soak", "resourceVersion": str(rv)}}
        change = twin.apply_event("pods", "DELETED", obj)
        if change is not None:
            capacity.on_twin_change("pods", "DELETED", obj, change, twin.generation)
        journal.record_event("pods", "DELETED", obj, twin.generation)
    capacity.sample()  # fold the timeline ring like the supervisor tick
    return rv


def _soak(tmp_path, iterations, rss_budget_mb):
    server = rest.SimonServer(base_cluster=_cluster())
    twin = ClusterTwin()
    capacity = CapacityEngine(timeline=None)
    capacity.claim_event_fed()
    capacity.bootstrap(_cluster(), 0)
    journal = Journal(
        str(tmp_path / "journal"),
        policy={"fsync": "off", "segment_mb": 0.05, "checkpoint_every": 64, "keep": 2},
    )
    journal.checkpoint_source = lambda: ({"pods": []}, twin.generation, [])
    rv = 100

    def one(i):
        nonlocal rv
        rv = _storm_iteration(i, twin, capacity, journal, rv)
        # serve: alternating payloads exercise full-key + base-entry churn
        code, _ = server.deploy_apps(
            {"deployments": [
                fx.make_fake_deployment(f"soak-{i % 3}", 2 + (i % 2), "100m", "128Mi").raw
            ]}
        )
        assert code == 200

    try:
        one(0)  # warmup: first-compile + first-prepare allocations are not a leak
        journal.flush(timeout=30.0)
        rss0 = process_memory()["rss_bytes"]
        cache_cap = server.prep_cache.capacity
        for i in range(1, iterations + 1):
            one(i)
        journal.flush(timeout=30.0)
        rss1 = process_memory()["rss_bytes"]

        # bounded structures stayed bounded
        assert len(server.prep_cache) <= cache_cap
        from opensim_tpu.obs.recorder import FLIGHT_RECORDER

        assert len(FLIGHT_RECORDER) <= FLIGHT_RECORDER.capacity
        assert len(capacity.timeline) <= capacity.timeline.capacity
        # journal pruning holds the segment set down despite constant churn
        segments = [n for n in os.listdir(journal.path) if n.endswith(".seg")]
        assert len(segments) <= 8, f"journal segments unbounded: {segments}"
        # net-zero churn must not accumulate twin state (tombstones are a
        # capped LRU; the materialized view must be empty again)
        mat = twin.materialize()
        assert len(mat.pods) == 0, "twin leaked storm pods past their deletes"

        delta_mb = (rss1 - rss0) / (1 << 20)
        assert delta_mb < rss_budget_mb, (
            f"RSS grew {delta_mb:.1f} MiB over {iterations} iterations "
            f"(budget {rss_budget_mb} MiB) — leak tripwire"
        )
    finally:
        journal.close()
        server.close()


def test_soak_small_bounded_growth(tmp_path):
    """Tier-1 tripwire: a handful of storm+serve iterations must not grow
    the bounded structures or the RSS envelope."""
    _soak(tmp_path, iterations=8, rss_budget_mb=256)


@pytest.mark.slow
def test_soak_long_bounded_growth(tmp_path):
    """Nightly tier: a longer soak with the same budget — a real
    per-iteration leak scales with N and trips here even if the small run
    hides under allocator noise."""
    _soak(tmp_path, iterations=60, rss_budget_mb=320)
