"""HA control plane (ISSUE 18): journal-tailing hot standby, fenced
failover, zero-downtime fleet upgrades. Part of ``make chaos``.

The load-bearing gates:

- fencing: a deposed owner (lease stolen at a higher epoch) can never
  publish a generation a worker attaches — the publish raises
  :class:`FencedWrite` (counted), and a worker that has seen the newer
  lease epoch refuses any stale-epoch payload;
- tailing: the standby's segment-follow reader survives rotation, torn
  tails, and injected gaps (``journal.tail_gap``) — the next checkpoint
  rebases its twin back to truth bit-for-bit;
- takeover: SIGKILL the owner mid event-storm — the standby acquires the
  lease, adopts the surviving workers (zero respawns), resumes the watch
  at the recorded rvs (zero relists), and its twin fingerprint equals a
  fresh full relist;
- handover: a standby started with ``--handover`` asks the live owner to
  drain; the owner exits 0 leaving its workers running, and the standby
  publishes at a continuous generation
  (``simon_fleet_takeovers_total{reason="handover"}``).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from opensim_tpu.engine.prepcache import fingerprint_cluster
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.resilience import faults
from opensim_tpu.server.fleet import (
    FencedWrite,
    FleetLease,
    FleetReader,
    FleetTwinClient,
    TwinPublisher,
    lease_path,
)
from opensim_tpu.server.journal import Journal, JournalTailer, apply_record
from opensim_tpu.server.snapshot import _cluster_via_rest
from opensim_tpu.server.stubapi import StubApiServer
from opensim_tpu.server.watch import ClusterTwin


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("OPENSIM_FAULTS", raising=False)
    faults.clear_faults()
    yield
    faults.clear_faults()


def _cluster(n_nodes: int = 4) -> ResourceTypes:
    rt = ResourceTypes()
    for i in range(n_nodes):
        rt.nodes.append(fx.make_fake_node(f"n{i:03d}", "16", "64Gi", "110"))
    return rt


def _pod_dict(name, rv, phase="Pending", node="", cpu="100m"):
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name, "namespace": "default", "resourceVersion": str(rv),
        },
        "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": cpu}}}]},
        "status": {"phase": phase},
    }
    if node:
        d["spec"]["nodeName"] = node
    return d


def _wait(pred, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# the lease
# ---------------------------------------------------------------------------


def test_lease_acquire_renew_steal_epochs(tmp_path):
    path = lease_path(str(tmp_path))
    a = FleetLease(path, lease_s=5.0, holder="owner-a")
    assert a.acquire({"port": 1234}) == 1
    assert a.check() and a.renew(control="ctrl-a")
    assert a.read()["control"] == "ctrl-a"

    # a fresh, live lease is NOT claimable by a second holder
    b = FleetLease(path, lease_s=5.0, holder="standby-b")
    assert b.acquire() is None and not b.check()

    # expiry makes it claimable; the steal bumps the epoch and fences A
    doc = a.read()
    doc["renewed_at"] = time.time() - 60.0
    b._write(doc)  # backdate: deterministic expiry
    assert b.acquire() == 2
    assert b.check()
    assert not a.check(), "the deposed holder must observe the fence"
    assert not a.renew(), "renew under a moved epoch must refuse"


def test_lease_release_handover_is_immediately_claimable(tmp_path):
    path = lease_path(str(tmp_path))
    a = FleetLease(path, lease_s=600.0, holder="owner-a")  # would never expire
    assert a.acquire() == 1
    a.release(handover=True)
    doc = a.read()
    assert doc["released"] and doc["handover"]
    b = FleetLease(path, lease_s=600.0, holder="standby-b")
    assert b.claimable(doc)
    assert b.acquire() == 2


# ---------------------------------------------------------------------------
# fencing: the deposed owner can never reach a worker
# ---------------------------------------------------------------------------


def test_stale_epoch_publish_raises_fenced_write(tmp_path):
    """Owner A holds the lease and publishes; the lease is stolen (epoch
    moves); A's next publish must refuse with FencedWrite, leave the
    seqlock untouched, and count the fence."""
    path = lease_path(str(tmp_path))
    a = FleetLease(path, lease_s=5.0, holder="owner-a")
    assert a.acquire() == 1
    pub = TwinPublisher(epoch=a.epoch, lease=a)
    cluster = _cluster()
    try:
        pub.publish(1, cluster, None)
        reader = FleetReader(pub.control.name)
        assert reader.poll() == 1

        # steal the lease (expiry + second acquire)
        doc = a.read()
        doc["renewed_at"] = time.time() - 60.0
        a._write(doc)
        b = FleetLease(path, lease_s=5.0, holder="standby-b")
        assert b.acquire() == 2

        with pytest.raises(FencedWrite):
            pub.publish(2, cluster, None)
        assert pub.footprint()["fenced_writes"] >= 1
        # the control block never swapped: a worker still attaches gen 1
        gen, payload, _obj = reader.attach()
        assert gen == 1 and payload["epoch"] == 1
        reader.close()
    finally:
        pub.close()


def test_worker_refuses_stale_epoch_payload(tmp_path):
    """The reader-side fence: a worker that has seen lease epoch 2 must
    refuse a payload published at epoch 1 even if it lands in shared
    memory (the deposed owner's in-flight publish window), and keep
    serving its previously attached generation."""
    path = lease_path(str(tmp_path))
    a = FleetLease(path, lease_s=600.0, holder="owner-a")
    assert a.acquire() == 1
    # lease=None mimics the doomed in-flight publish: the write happens
    # without the owner-side gate, so only the worker-side fence is left
    pub = TwinPublisher(epoch=1, lease=None)
    cluster = _cluster()
    client = None
    try:
        pub.publish(1, cluster, None)
        client = FleetTwinClient(pub.control.name, lease_file=path)
        client.LEASE_CHECK_S = 0.0  # re-read the lease every snapshot
        assert client.start(wait_s=10.0)
        _cl, key, _stale = client.serving_snapshot()
        assert key == "fleet|1"

        # epoch moves to 2, lease still names A's control (the window
        # before the new owner publishes)
        doc = a.read()
        doc["epoch"] = 2
        doc["control"] = pub.control.name
        a._write(doc)
        client.serving_snapshot()  # absorb the new lease epoch
        assert client._lease_epoch == 2

        pub.publish(5, cluster, None)  # the deposed owner's late write
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            got = client.serving_snapshot()
            assert got is not None
            _cl, key, _stale = got
            assert key == "fleet|1", "stale-epoch generation must never serve"
            time.sleep(0.02)
    finally:
        if client is not None:
            client.stop()
        pub.close()


def test_worker_follows_lease_to_new_owner(tmp_path):
    """Failover discovery: once the lease names the new owner's control
    block AND the new owner has published, the worker swaps readers and
    serves the new epoch's generation — without ever dropping its old
    snapshot in between."""
    path = lease_path(str(tmp_path))
    a = FleetLease(path, lease_s=5.0, holder="owner-a")
    assert a.acquire() == 1
    pub_a = TwinPublisher(epoch=1, lease=a)
    cluster = _cluster()
    pub_b = None
    client = None
    try:
        pub_a.publish(3, cluster, None)
        a.renew(control=pub_a.control.name)
        client = FleetTwinClient(pub_a.control.name, lease_file=path)
        client.LEASE_CHECK_S = 0.0
        assert client.start(wait_s=10.0)
        assert client.serving_snapshot()[1] == "fleet|3"

        # takeover: B steals the expired lease, publishes the NEXT
        # generation under its own (epoch-named) control block
        doc = a.read()
        doc["renewed_at"] = time.time() - 60.0
        a._write(doc)
        b = FleetLease(path, lease_s=5.0, holder="standby-b")
        assert b.acquire() == 2
        pub_b = TwinPublisher(epoch=2, lease=b)
        b.renew(control=pub_b.control.name)

        # lease names B but B has not published yet: the worker must keep
        # serving A's generation (no dropped requests mid-failover)
        assert client.serving_snapshot()[1] == "fleet|3"
        assert client.owner_switches_total == 0

        pub_b.publish(4, cluster, None)

        def swapped():
            got = client.serving_snapshot()
            return got is not None and got[1] == "fleet|4"

        _wait(swapped, timeout=10.0, msg="worker to follow the lease")
        assert client.owner_switches_total == 1
        assert client.control_name == pub_b.control.name
    finally:
        if client is not None:
            client.stop()
        pub_a.close()
        if pub_b is not None:
            pub_b.close()


# ---------------------------------------------------------------------------
# the journal tailer
# ---------------------------------------------------------------------------


def _tail_journal(tmp_path, name="tail"):
    jd = str(tmp_path / name)
    return jd, Journal(jd, policy={"fsync": "always"})


def test_tailer_follows_live_writes_and_rotation(tmp_path):
    jd, jr = _tail_journal(tmp_path)
    src = ClusterTwin()
    dst = ClusterTwin()
    tailer = JournalTailer(jd)
    try:
        stores, gen = src.snapshot_raw()
        jr.record_checkpoint(stores, gen, why="bootstrap")
        for i in range(5):
            obj = _pod_dict(f"p{i}", rv=10 + i)
            src.apply_event("pods", "ADDED", obj)
            jr.record_event("pods", "ADDED", obj, src.generation)
        jr.flush(timeout=10.0)
        for rec in tailer.poll():
            apply_record(dst, rec)
        assert dst.fingerprint() == src.fingerprint()
        assert tailer.last_lag_records == 0 or tailer.poll() == []

        # cadence checkpoint rotates to a new segment; the tailer crosses
        # it and keeps applying in order
        jr.checkpoint_source = lambda: ({}, src.generation, [])
        jr.policy["checkpoint_every"] = 1
        obj = _pod_dict("rotor", rv=15)
        src.apply_event("pods", "ADDED", obj)
        jr.record_event("pods", "ADDED", obj, src.generation)
        jr.flush(timeout=10.0)
        jr.checkpoint_source = None
        for i in range(5, 8):
            obj = _pod_dict(f"p{i}", rv=10 + i)
            src.apply_event("pods", "ADDED", obj)
            jr.record_event("pods", "ADDED", obj, src.generation)
        jr.flush(timeout=10.0)
        segs = [f for f in os.listdir(jd) if f.endswith(".seg")]
        assert len(segs) >= 2, "checkpoint should have rotated a new segment"
        for rec in tailer.poll():
            apply_record(dst, rec)
        assert dst.fingerprint() == src.fingerprint()
        assert tailer.gaps_total == 0
    finally:
        jr.close()


def test_tailer_waits_at_torn_tail_then_resumes(tmp_path):
    """A torn (half-written) frame at the live tail is 'incomplete': the
    tailer returns what precedes it and waits — and once the writer's
    next complete frame lands (takeover truncation path re-reads), the
    stream continues without a gap."""
    jd, jr = _tail_journal(tmp_path)
    src = ClusterTwin()
    dst = ClusterTwin()
    tailer = JournalTailer(jd)
    try:
        stores, gen = src.snapshot_raw()
        jr.record_checkpoint(stores, gen, why="bootstrap")
        obj = _pod_dict("before-tear", rv=5)
        src.apply_event("pods", "ADDED", obj)
        jr.record_event("pods", "ADDED", obj, src.generation)
        jr.flush(timeout=10.0)
        for rec in tailer.poll():
            apply_record(dst, rec)
        assert dst.fingerprint() == src.fingerprint()

        # tear the tail: a frame header promising more bytes than exist
        seg = sorted(f for f in os.listdir(jd) if f.endswith(".seg"))[-1]
        seg_path = os.path.join(jd, seg)
        with open(seg_path, "ab") as f:
            f.write((1000).to_bytes(4, "little") + b"\x00\x00\x00\x00" + b"xx")
        assert tailer.poll() == []
        assert tailer.last_stop == "incomplete"

        # the takeover path truncates the torn bytes (writable reopen);
        # the tailer detects the shrink, re-reads, and stays consistent
        jr.close()
        jr2 = Journal(jd, policy={"fsync": "always"})
        obj2 = _pod_dict("after-tear", rv=6)
        src.apply_event("pods", "ADDED", obj2)
        jr2.record_event("pods", "ADDED", obj2, src.generation)
        jr2.flush(timeout=10.0)
        got = []
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not got:
            got = tailer.poll()
        for rec in got:
            apply_record(dst, rec)
        names = {p.metadata.name for p in dst.materialize().pods}
        assert "after-tear" in names
        assert dst.fingerprint() == src.fingerprint()
        jr2.close()
    finally:
        jr.close()


def test_tail_gap_fault_heals_at_next_checkpoint(tmp_path):
    """Chaos ``journal.tail_gap``: one drained batch is dropped on the
    floor (counted); the stream's next checkpoint rebases the consumer
    twin back to bit-equality with the source."""
    jd, jr = _tail_journal(tmp_path)
    src = ClusterTwin()
    dst = ClusterTwin()
    state_holder = None
    tailer = JournalTailer(jd)
    try:
        stores, gen = src.snapshot_raw()
        jr.record_checkpoint(stores, gen, why="bootstrap")
        jr.flush(timeout=10.0)
        for rec in tailer.poll():
            apply_record(dst, rec, state_holder)

        for i in range(4):
            obj = _pod_dict(f"lost-{i}", rv=20 + i)
            src.apply_event("pods", "ADDED", obj)
            jr.record_event("pods", "ADDED", obj, src.generation)
        jr.flush(timeout=10.0)
        faults.inject("journal.tail_gap", count=1, exc="runtime")
        assert tailer.poll() == [], "the injected gap must drop the batch"
        assert tailer.gaps_total == 1
        assert dst.fingerprint() != src.fingerprint(), "the twin is now behind"

        # the healing checkpoint: an authoritative full snapshot
        stores, gen = src.snapshot_raw()
        jr.record_checkpoint(stores, gen, why="heal")
        jr.flush(timeout=10.0)
        for rec in tailer.poll():
            apply_record(dst, rec, state_holder)
        assert dst.fingerprint() == src.fingerprint()
    finally:
        jr.close()


def test_lease_steal_fault_forces_fenced_publish(tmp_path):
    """Chaos ``fleet.lease_steal``: the injected steal makes check() fence
    even though the file still names us; the publish refuses."""
    path = lease_path(str(tmp_path))
    lease = FleetLease(path, lease_s=600.0, holder="owner-a")
    assert lease.acquire() == 1
    pub = TwinPublisher(epoch=1, lease=lease)
    try:
        pub.publish(1, _cluster(), None)
        faults.inject("fleet.lease_steal", count=1, exc="runtime")
        with pytest.raises(FencedWrite):
            pub.publish(2, _cluster(), None)
        assert pub.footprint()["fenced_writes"] == 1
        # the injection consumed itself: the owner is healthy again
        pub.publish(2, _cluster(), None)
        reader = FleetReader(pub.control.name)
        assert reader.poll() == 2
        reader.close()
    finally:
        pub.close()


def test_shm_republish_fault_keeps_previous_generation(tmp_path):
    """Chaos ``shm.republish``: a publish dying between the segment writes
    and the seqlock swap leaves readers on the previous stable
    generation; the next publish succeeds."""
    pub = TwinPublisher()
    cluster = _cluster()
    try:
        pub.publish(1, cluster, None)
        reader = FleetReader(pub.control.name)
        assert reader.poll() == 1
        faults.inject("shm.republish", count=1, exc="runtime")
        with pytest.raises(Exception):
            pub.publish(2, cluster, None)
        gen, payload, _obj = reader.attach()
        assert gen == 1, "a torn publish must never surface to readers"
        pub.publish(3, cluster, None)
        assert reader.poll() == 3
        reader.close()
    finally:
        pub.close()


# ---------------------------------------------------------------------------
# end to end: SIGKILL the owner mid-storm; the standby takes over
# ---------------------------------------------------------------------------

LIST_PATHS = (
    "/api/v1/nodes",
    "/api/v1/pods",
    "/apis/apps/v1/daemonsets",
    "/apis/policy/v1/poddisruptionbudgets",
    "/api/v1/services",
    "/apis/storage.k8s.io/v1/storageclasses",
    "/api/v1/persistentvolumeclaims",
    "/api/v1/configmaps",
)


def _seed(stub, n_nodes=4):
    stub.seed(
        "/api/v1/nodes",
        [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(n_nodes)],
    )
    stub.seed("/api/v1/pods", [])
    for p in LIST_PATHS[2:]:
        stub.seed(p, [])


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http_json(url, timeout=3.0, method="GET"):
    req = urllib.request.Request(url, method=method, data=b"" if method == "POST" else None)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _http_text(url, timeout=3.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def _ha_env(repo, lease_s="1.5"):
    return dict(
        os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu",
        OPENSIM_HA="1", OPENSIM_HA_LEASE_S=lease_s,
        OPENSIM_HA_TAIL_POLL_MS="25", OPENSIM_FLEET_PUBLISH_MS="50",
        OPENSIM_JOURNAL_FSYNC="always", OPENSIM_JOURNAL_CHECKPOINT_EVERY="64",
    )


def _spawn_owner(repo, kc, jd, port, env, logfile):
    # stdout goes to a FILE, not a pipe: the workers inherit the fd and
    # outlive the owner on handover/takeover — a pipe would never EOF
    return subprocess.Popen(
        [
            sys.executable, "-m", "opensim_tpu", "server",
            "--kubeconfig", kc, "--watch", "on", "--journal", jd,
            "--port", str(port), "--workers", "2", "--backend", "cpu",
        ],
        stdout=open(logfile, "w"), stderr=subprocess.STDOUT,
        env=env, cwd=repo, text=True,
    )


def _spawn_standby(repo, kc, jd, port, env, logfile, handover=False):
    argv = [
        sys.executable, "-m", "opensim_tpu", "server", "--standby",
        "--kubeconfig", kc, "--watch", "auto", "--journal", jd,
        "--port", str(port), "--workers", "2", "--backend", "cpu",
    ]
    if handover:
        argv.append("--handover")
    return subprocess.Popen(
        argv, stdout=open(logfile, "w"), stderr=subprocess.STDOUT,
        env=env, cwd=repo, text=True,
    )


def _log(path):
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return ""


def _owner_up(admin_port, proc, logfile, want_workers=2):
    def pred():
        if proc.poll() is not None:
            raise AssertionError(f"process died early: {_log(logfile)[-3000:]}")
        try:
            body = _http_json(f"http://127.0.0.1:{admin_port}/healthz", timeout=2.0)
            return body.get("workers", 0) >= want_workers
        except OSError:
            return False

    return pred


def _metric_value(text, needle):
    for line in text.splitlines():
        if line.startswith(needle):
            return float(line.rsplit(" ", 1)[-1])
    return None


def _drain_kill(*procs):
    for p in procs:
        if p is not None and p.poll() is None:
            p.kill()
    for p in procs:
        if p is not None:
            with open(os.devnull, "w"):
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass


def test_sigkill_owner_standby_takes_over_bit_equal(tmp_path):
    """The tentpole acceptance run: SIGKILL the HA owner mid event-storm.
    The tailing standby must take over at the recorded rvs — twin
    fingerprint equal to a fresh full relist, ZERO relists, the surviving
    workers adopted (zero respawns of live pids), the publication
    generation monotonic, and ``takeovers_total{reason="expired"} == 1``."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    stub = StubApiServer(bookmark_interval_s=0.1).start()
    _seed(stub)
    kc = stub.kubeconfig(tmp_path)
    jd = str(tmp_path / "journal")
    port = _free_port()
    env = _ha_env(repo)
    owner_log = str(tmp_path / "owner.log")
    sb_log = str(tmp_path / "standby.log")
    owner = standby = None
    try:
        owner = _spawn_owner(repo, kc, jd, port, env, owner_log)
        _wait(
            _owner_up(port + 1, owner, owner_log),
            timeout=120.0, msg="HA owner fleet up",
        )
        status = _http_json(f"http://127.0.0.1:{port + 1}/api/fleet/status")
        assert status["role"] == "owner" and status["epoch"] == 1
        worker_pids = {w["pid"] for w in status["workers"] if w["alive"]}
        assert len(worker_pids) == 2
        gen_before = status["generation"]

        standby = _spawn_standby(repo, kc, jd, port, env, sb_log)
        sb_admin = port + 16

        def standby_tailing():
            if standby.poll() is not None:
                raise AssertionError(f"standby died early: {_log(sb_log)[-3000:]}")
            try:
                body = _http_json(f"http://127.0.0.1:{sb_admin}/api/fleet/status")
                return body["role"] == "standby" and body["at_parity"]
            except OSError:
                return False

        _wait(standby_tailing, timeout=60.0, msg="standby to tail to parity")

        # event storm, then SIGKILL the owner mid-stream
        for i in range(30):
            stub.upsert("/api/v1/pods", _pod_dict(f"storm-{i}", rv=1000 + i))
            if i == 20:
                owner.kill()  # SIGKILL: no flush, no release, no goodbye
        owner.wait(timeout=10)
        stub.delete("/api/v1/pods", "storm-3")  # churn only the watch can see

        def promoted():
            try:
                body = _http_json(f"http://127.0.0.1:{sb_admin}/api/fleet/status")
                return body["role"] == "owner"
            except OSError:
                return False

        _wait(promoted, timeout=60.0, msg="standby to take over")
        status = _http_json(f"http://127.0.0.1:{sb_admin}/api/fleet/status")
        assert status["epoch"] == 2

        # the surviving workers were adopted, not respawned
        adopted = {w["pid"] for w in status["workers"] if w["adopted"]}
        assert adopted == worker_pids, f"{adopted} != {worker_pids}"

        # resumed reflectors absorb everything the crash lost; the twin
        # lands bit-equal to a fresh relist
        def caught_up():
            s = _http_json(f"http://127.0.0.1:{sb_admin}/api/fleet/status")
            fresh, _rvs = _cluster_via_rest(kc, None)
            return s["fingerprint"] == fingerprint_cluster(fresh)

        _wait(caught_up, timeout=60.0, msg="new owner twin to equal a fresh relist")

        # generation continuity + zero relists + exactly one takeover
        metrics = _http_text(f"http://127.0.0.1:{sb_admin}/metrics")
        assert (
            _metric_value(metrics, 'simon_fleet_takeovers_total{reason="expired"}')
            == 1.0
        )
        relists = _metric_value(metrics, "simon_watch_relists_total")
        assert relists in (None, 0.0), f"takeover must not relist (saw {relists})"
        gen_after = _http_json(
            f"http://127.0.0.1:{sb_admin}/api/fleet/status"
        )["generation"]
        assert gen_after >= gen_before, "generations must stay monotonic"
    finally:
        # the standby-turned-owner owns the adopted workers; SIGTERM it
        # first so it reaps them, then sweep whatever is left
        if standby is not None and standby.poll() is None:
            standby.send_signal(signal.SIGTERM)
            try:
                standby.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
        _drain_kill(owner, standby)
        stub.stop()


def test_rolling_upgrade_handover_drains_cleanly(tmp_path):
    """Zero-downtime upgrade: a standby started with ``--handover`` tails
    to parity, asks the owner to drain, the owner exits 0 WITHOUT killing
    its workers, and the standby owns the fleet at the next epoch with
    ``takeovers_total{reason="handover"} == 1``."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    stub = StubApiServer(bookmark_interval_s=0.1).start()
    _seed(stub)
    kc = stub.kubeconfig(tmp_path)
    jd = str(tmp_path / "journal")
    port = _free_port()
    env = _ha_env(repo, lease_s="4")
    owner_log = str(tmp_path / "owner.log")
    sb_log = str(tmp_path / "standby.log")
    owner = standby = None
    try:
        owner = _spawn_owner(repo, kc, jd, port, env, owner_log)
        _wait(
            _owner_up(port + 1, owner, owner_log),
            timeout=120.0, msg="HA owner fleet up",
        )
        status = _http_json(f"http://127.0.0.1:{port + 1}/api/fleet/status")
        worker_pids = {w["pid"] for w in status["workers"] if w["alive"]}

        standby = _spawn_standby(repo, kc, jd, port, env, sb_log, handover=True)
        owner.wait(timeout=120)
        out = _log(owner_log)
        assert owner.returncode == 0, f"owner exit {owner.returncode}: {out[-3000:]}"
        assert "handed over" in out
        for pid in worker_pids:
            os.kill(pid, 0)  # the old owner must NOT have killed its workers

        sb_admin = port + 16

        def promoted():
            if standby.poll() is not None:
                raise AssertionError(f"standby died early: {_log(sb_log)[-3000:]}")
            try:
                body = _http_json(f"http://127.0.0.1:{sb_admin}/api/fleet/status")
                return body["role"] == "owner"
            except OSError:
                return False

        _wait(promoted, timeout=60.0, msg="standby promotion after handover")
        status = _http_json(f"http://127.0.0.1:{sb_admin}/api/fleet/status")
        assert status["epoch"] == 2
        assert {w["pid"] for w in status["workers"] if w["adopted"]} == worker_pids
        metrics = _http_text(f"http://127.0.0.1:{sb_admin}/metrics")
        assert (
            _metric_value(metrics, 'simon_fleet_takeovers_total{reason="handover"}')
            == 1.0
        )
    finally:
        if standby is not None and standby.poll() is None:
            standby.send_signal(signal.SIGTERM)
            try:
                standby.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
        _drain_kill(owner, standby)
        stub.stop()
