"""Kube-semantics oracle: an INDEPENDENT pure-Python implementation of the
vendored kube-scheduler filter semantics (noderesources/fit.go, nodeports.go,
interpodaffinity/filtering.go, podtopologyspread/filtering.go) replays the
engine's placements pod by pod and checks every decision:

- a pod the engine bound to node n must be feasible on n per the oracle;
- a pod the engine left unscheduled must be infeasible on EVERY node.

Engine-vs-engine fuzzing (test_fastpath_fuzz.py) cannot catch a semantics
bug both engines share; this oracle can — it derives feasibility from the
Go sources directly, not from the tensor encodings."""

import random

import numpy as np
import pytest

from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
from opensim_tpu.engine.simulator import AppResource, prepare
from opensim_tpu.models import ResourceTypes, fixtures as fx, selectors
from opensim_tpu.models.objects import Node, Pod

HOSTNAME = "kubernetes.io/hostname"


# ---------------------------------------------------------------------------
# the oracle
# ---------------------------------------------------------------------------

def _match_term(term: dict, ns: str, pod: Pod) -> bool:
    """PodMatchesTermsNamespaceAndSelector: pod's namespace must be in the
    term's namespace set (default: the incoming pod's ns) and its labels
    must match the term's labelSelector (nil selector matches nothing)."""
    namespaces = term.get("namespaces") or [ns]
    if pod.metadata.namespace not in namespaces:
        return False
    sel = term.get("labelSelector")
    if sel is None:
        return False
    return selectors.match_label_selector(sel, pod.metadata.labels)


def _terms(pod: Pod, kind: str, mode: str):
    aff = (pod.spec.affinity or {}).get(kind) or {}
    return aff.get(f"{mode}DuringSchedulingIgnoredDuringExecution") or []


class Oracle:
    """Tracks bound pods and answers feasibility per the vendored sources."""

    def __init__(self, nodes):
        self.nodes = list(nodes)
        self.by_name = {n.metadata.name: n for n in nodes}
        self.bound = []  # (pod, node)

    def bind(self, pod: Pod, node: Node):
        self.bound.append((pod, node))

    # -- individual filters --------------------------------------------------

    def static_ok(self, pod: Pod, node: Node) -> bool:
        if node.unschedulable:
            return False
        if pod.spec.node_name and pod.spec.node_name != node.metadata.name:
            return False
        if not selectors.pod_matches_node_selector_and_affinity(pod, node):
            return False
        taints = [t for t in node.taints if t.effect in ("NoSchedule", "NoExecute")]
        return selectors.find_untolerated_taint(taints, pod.spec.tolerations) is None

    def fit_ok(self, pod: Pod, node: Node) -> bool:
        used = {"pods": 0.0}
        for p, n in self.bound:
            if n is node:
                used["pods"] += 1
                for k, v in p.resource_requests().items():
                    used[k] = used.get(k, 0.0) + v
        req = dict(pod.resource_requests())
        req["pods"] = req.get("pods", 0.0) + 1
        for k, v in req.items():
            if v > 0 and used.get(k, 0.0) + v > node.allocatable.get(k, 0.0):
                return False
        return True

    def ports_ok(self, pod: Pod, node: Node) -> bool:
        def conflict(a, b):
            if a.protocol != b.protocol or a.host_port != b.host_port:
                return False
            ia = "" if a.host_ip in ("", "0.0.0.0") else a.host_ip
            ib = "" if b.host_ip in ("", "0.0.0.0") else b.host_ip
            return ia == ib or ia == "" or ib == ""

        mine = pod.host_ports()
        for p, n in self.bound:
            if n is not node:
                continue
            for theirs in p.host_ports():
                if any(conflict(m, theirs) for m in mine):
                    return False
        return True

    def interpod_ok(self, pod: Pod, node: Node) -> bool:
        ns = pod.metadata.namespace
        # (1) existing pods' required anti-affinity vs the incoming pod
        # (satisfyExistingPodsAntiAffinity): violating when an existing pod
        # has a required anti term matching the incoming pod AND the
        # candidate node shares that term's topology (key, value) with the
        # existing pod's node
        for p, n in self.bound:
            for term in _terms(p, "podAntiAffinity", "required"):
                if not _match_term(term, p.metadata.namespace, pod):
                    continue
                key = term.get("topologyKey", "")
                val = n.metadata.labels.get(key)
                if val is not None and node.metadata.labels.get(key) == val:
                    return False
        # (2) incoming pod's required anti-affinity (satisfyPodAntiAffinity):
        # node missing the key → vacuously fine
        for term in _terms(pod, "podAntiAffinity", "required"):
            key = term.get("topologyKey", "")
            my_val = node.metadata.labels.get(key)
            if my_val is None:
                continue
            for p, n in self.bound:
                if n.metadata.labels.get(key) == my_val and _match_term(term, ns, p):
                    return False
        # (3) incoming pod's required affinity (satisfyPodAffinity): counts
        # come from pods matching ALL terms; every term needs its key on the
        # node and a positive count; bootstrap when the global map is empty
        # and the pod matches all its own terms
        terms = _terms(pod, "podAffinity", "required")
        if terms:
            all_matching = [
                (p, n) for p, n in self.bound if all(_match_term(t, ns, p) for t in terms)
            ]
            labels_ok = all(node.metadata.labels.get(t.get("topologyKey", "")) is not None for t in terms)
            per_term_ok = labels_ok and all(
                any(
                    n.metadata.labels.get(t.get("topologyKey", ""))
                    == node.metadata.labels.get(t.get("topologyKey", ""))
                    for _p, n in all_matching
                    if n.metadata.labels.get(t.get("topologyKey", "")) is not None
                )
                for t in terms
            )
            if not per_term_ok:
                map_empty = not any(
                    n.metadata.labels.get(t.get("topologyKey", "")) is not None
                    for _p, n in all_matching
                    for t in terms
                )
                self_match = all(_match_term(t, ns, pod) for t in terms)
                if not (labels_ok and map_empty and self_match):
                    return False
        return True

    def spread_ok(self, pod: Pod, node: Node) -> bool:
        ns = pod.metadata.namespace
        for c in pod.spec.topology_spread_constraints:
            if c.get("whenUnsatisfiable", "DoNotSchedule") != "DoNotSchedule":
                continue
            key = c.get("topologyKey", "")
            skew = int(c.get("maxSkew", 1))
            sel = c.get("labelSelector")
            my_val = node.metadata.labels.get(key)
            if my_val is None:
                return False  # node missing the label fails the constraint
            def matches(p):
                return p.metadata.namespace == ns and sel is not None and selectors.match_label_selector(
                    sel, p.metadata.labels
                )
            counts = {}
            for p, n in self.bound:
                val = n.metadata.labels.get(key)
                if val is not None and matches(p):
                    counts[val] = counts.get(val, 0) + 1
            # min over eligible domains: nodes passing the incoming pod's
            # node affinity/selector that carry the label
            eligible_vals = {
                n.metadata.labels.get(key)
                for n in self.nodes
                if n.metadata.labels.get(key) is not None
                and selectors.pod_matches_node_selector_and_affinity(pod, n)
            }
            if not eligible_vals:
                return False
            min_cnt = min(counts.get(v, 0) for v in eligible_vals)
            self_match = 1 if matches(pod) else 0
            if counts.get(my_val, 0) + self_match - min_cnt > skew:
                return False
        return True

    def feasible(self, pod: Pod, node: Node) -> bool:
        return (
            self.static_ok(pod, node)
            and self.fit_ok(pod, node)
            and self.ports_ok(pod, node)
            and self.interpod_ok(pod, node)
            and self.spread_ok(pod, node)
        )


# ---------------------------------------------------------------------------
# generators (no GPU/local storage — out of the oracle's scope)
# ---------------------------------------------------------------------------

def random_cluster(rng, n):
    rt = ResourceTypes()
    for i in range(n):
        labels = {}
        if rng.random() < 0.8:
            labels["topology.kubernetes.io/zone"] = f"z{rng.randrange(3)}"
        if rng.random() < 0.5:
            labels["topology.kubernetes.io/region"] = f"r{rng.randrange(2)}"
        if rng.random() < 0.4:
            labels["disk"] = rng.choice(["ssd", "hdd"])
        opts = [fx.with_labels(labels)]
        if rng.random() < 0.25:
            opts.append(fx.with_taints([{"key": "dedicated", "value": "x",
                                         "effect": rng.choice(["NoSchedule", "PreferNoSchedule"])}]))
        rt.nodes.append(fx.make_fake_node(f"n{i:03d}", str(rng.choice([4, 8])), "16Gi", "20", *opts))
    return rt


def random_app(rng, n_workloads):
    rt = ResourceTypes()
    for w in range(n_workloads):
        opts = []
        if rng.random() < 0.3:
            opts.append(fx.with_node_selector({"disk": rng.choice(["ssd", "hdd"])}))
        if rng.random() < 0.3:
            opts.append(fx.with_tolerations(
                [{"key": "dedicated", "operator": "Equal", "value": "x", "effect": "NoSchedule"}]))
        if rng.random() < 0.35:
            opts.append(fx.with_topology_spread([{
                "maxSkew": rng.choice([1, 2]),
                "topologyKey": rng.choice(
                    [HOSTNAME, "topology.kubernetes.io/zone", "topology.kubernetes.io/region"]),
                "whenUnsatisfiable": rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                "labelSelector": {"matchLabels": {"app": f"w{w}"}},
            }]))
        if rng.random() < 0.35:
            kind = rng.choice(["podAffinity", "podAntiAffinity"])
            n_terms = rng.randrange(1, 3) if kind == "podAffinity" else 1
            terms = []
            for _ in range(n_terms):
                term = {
                    "labelSelector": {"matchLabels": {"app": f"w{rng.randrange(max(w, 1))}" if w else f"w{w}"}},
                    "topologyKey": rng.choice(
                        [HOSTNAME, "topology.kubernetes.io/zone", "topology.kubernetes.io/region"]),
                }
                if rng.random() < 0.4:  # explicit multi-namespace scoping
                    term["namespaces"] = rng.sample(["ns-a", "ns-b", "default"], rng.randrange(1, 3))
                terms.append(term)
            opts.append(fx.with_affinity(
                {kind: {"requiredDuringSchedulingIgnoredDuringExecution": terms}}))
        if rng.random() < 0.25:
            opts.append(fx.with_host_ports([rng.choice([8080, 9090])]))
        if rng.random() < 0.5:
            opts.append(fx.with_namespace(rng.choice(["ns-a", "ns-b"])))
        rt.deployments.append(fx.make_fake_deployment(
            f"w{w}", rng.randrange(2, 7),
            f"{rng.choice([250, 500, 1000, 2000])}m", f"{rng.choice([256, 512, 2048])}Mi", *opts))
    return rt


@pytest.mark.parametrize("seed", [3, 17, 29, 61, 97])
def test_engine_matches_k8s_oracle(seed):
    rng = random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(4, 10))
    app = random_app(rng, rng.randrange(3, 7))
    prep = prepare(cluster, [AppResource("oracle", app)], node_pad=8)
    if prep is None:
        pytest.skip("empty workload")
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    chosen = np.asarray(out.chosen)[:P]

    oracle = Oracle(cluster.nodes)
    node_names = prep.meta.node_names
    for i, pod in enumerate(prep.ordered):
        c = int(chosen[i])
        if c >= 0:
            node = oracle.by_name[node_names[c]]
            assert oracle.feasible(pod, node), (
                f"seed={seed}: engine bound {pod.metadata.name} to {node.metadata.name}, "
                f"oracle says infeasible (static={oracle.static_ok(pod, node)} "
                f"fit={oracle.fit_ok(pod, node)} ports={oracle.ports_ok(pod, node)} "
                f"interpod={oracle.interpod_ok(pod, node)} spread={oracle.spread_ok(pod, node)})"
            )
            oracle.bind(pod, node)
        else:
            feasible_nodes = [n.metadata.name for n in cluster.nodes if oracle.feasible(pod, n)]
            assert not feasible_nodes, (
                f"seed={seed}: engine left {pod.metadata.name} unscheduled but the oracle "
                f"finds feasible nodes {feasible_nodes}"
            )
