"""Kube-semantics oracle: an INDEPENDENT pure-Python implementation of the
vendored kube-scheduler filter semantics (noderesources/fit.go, nodeports.go,
interpodaffinity/filtering.go, podtopologyspread/filtering.go) replays the
engine's placements pod by pod and checks every decision:

- a pod the engine bound to node n must be feasible on n per the oracle;
- a pod the engine left unscheduled must be infeasible on EVERY node.

Engine-vs-engine fuzzing (test_fastpath_fuzz.py) cannot catch a semantics
bug both engines share; this oracle can — it derives feasibility from the
Go sources directly, not from the tensor encodings."""

import random

import numpy as np
import pytest

from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
from opensim_tpu.engine.simulator import AppResource, prepare
from opensim_tpu.models import ResourceTypes, fixtures as fx, selectors
from opensim_tpu.models.objects import Node, Pod

pytestmark = pytest.mark.slow  # nightly tier (README: test tiering)

HOSTNAME = "kubernetes.io/hostname"


# ---------------------------------------------------------------------------
# the oracle
# ---------------------------------------------------------------------------

def _match_term(term: dict, ns: str, pod: Pod) -> bool:
    """PodMatchesTermsNamespaceAndSelector: pod's namespace must be in the
    term's namespace set (default: the incoming pod's ns) and its labels
    must match the term's labelSelector (nil selector matches nothing)."""
    namespaces = term.get("namespaces") or [ns]
    if pod.metadata.namespace not in namespaces:
        return False
    sel = term.get("labelSelector")
    if sel is None:
        return False
    return selectors.match_label_selector(sel, pod.metadata.labels)


def _terms(pod: Pod, kind: str, mode: str):
    aff = (pod.spec.affinity or {}).get(kind) or {}
    return aff.get(f"{mode}DuringSchedulingIgnoredDuringExecution") or []


class Oracle:
    """Tracks bound pods and answers feasibility per the vendored sources."""

    def __init__(self, nodes):
        self.nodes = list(nodes)
        self.by_name = {n.metadata.name: n for n in nodes}
        self.bound = []  # (pod, node)

    def bind(self, pod: Pod, node: Node):
        self.bound.append((pod, node))

    def alloc_view(self, node: Node) -> dict:
        """Scheduler-visible allocatable. The base oracle has no gpushare
        devices, so it is the static node object; ExtOracle overrides with
        the Reserve-updated gpu-count (open-gpu-share.go:177-182)."""
        return node.allocatable

    # -- individual filters --------------------------------------------------

    def static_ok(self, pod: Pod, node: Node) -> bool:
        if node.unschedulable:
            return False
        if pod.spec.node_name and pod.spec.node_name != node.metadata.name:
            return False
        if not selectors.pod_matches_node_selector_and_affinity(pod, node):
            return False
        taints = [t for t in node.taints if t.effect in ("NoSchedule", "NoExecute")]
        return selectors.find_untolerated_taint(taints, pod.spec.tolerations) is None

    def fit_ok(self, pod: Pod, node: Node) -> bool:
        used = {"pods": 0.0}
        for p, n in self.bound:
            if n is node:
                used["pods"] += 1
                for k, v in p.resource_requests().items():
                    used[k] = used.get(k, 0.0) + v
        req = dict(pod.resource_requests())
        req["pods"] = req.get("pods", 0.0) + 1
        alloc = self.alloc_view(node)
        for k, v in req.items():
            if v > 0 and used.get(k, 0.0) + v > alloc.get(k, 0.0):
                return False
        return True

    def ports_ok(self, pod: Pod, node: Node) -> bool:
        def conflict(a, b):
            if a.protocol != b.protocol or a.host_port != b.host_port:
                return False
            ia = "" if a.host_ip in ("", "0.0.0.0") else a.host_ip
            ib = "" if b.host_ip in ("", "0.0.0.0") else b.host_ip
            return ia == ib or ia == "" or ib == ""

        mine = pod.host_ports()
        for p, n in self.bound:
            if n is not node:
                continue
            for theirs in p.host_ports():
                if any(conflict(m, theirs) for m in mine):
                    return False
        return True

    def interpod_ok(self, pod: Pod, node: Node) -> bool:
        ns = pod.metadata.namespace
        # (1) existing pods' required anti-affinity vs the incoming pod
        # (satisfyExistingPodsAntiAffinity): violating when an existing pod
        # has a required anti term matching the incoming pod AND the
        # candidate node shares that term's topology (key, value) with the
        # existing pod's node
        for p, n in self.bound:
            for term in _terms(p, "podAntiAffinity", "required"):
                if not _match_term(term, p.metadata.namespace, pod):
                    continue
                key = term.get("topologyKey", "")
                val = n.metadata.labels.get(key)
                if val is not None and node.metadata.labels.get(key) == val:
                    return False
        # (2) incoming pod's required anti-affinity (satisfyPodAntiAffinity):
        # node missing the key → vacuously fine
        for term in _terms(pod, "podAntiAffinity", "required"):
            key = term.get("topologyKey", "")
            my_val = node.metadata.labels.get(key)
            if my_val is None:
                continue
            for p, n in self.bound:
                if n.metadata.labels.get(key) == my_val and _match_term(term, ns, p):
                    return False
        # (3) incoming pod's required affinity (satisfyPodAffinity): counts
        # come from pods matching ALL terms; every term needs its key on the
        # node and a positive count; bootstrap when the global map is empty
        # and the pod matches all its own terms
        terms = _terms(pod, "podAffinity", "required")
        if terms:
            all_matching = [
                (p, n) for p, n in self.bound if all(_match_term(t, ns, p) for t in terms)
            ]
            labels_ok = all(node.metadata.labels.get(t.get("topologyKey", "")) is not None for t in terms)
            per_term_ok = labels_ok and all(
                any(
                    n.metadata.labels.get(t.get("topologyKey", ""))
                    == node.metadata.labels.get(t.get("topologyKey", ""))
                    for _p, n in all_matching
                    if n.metadata.labels.get(t.get("topologyKey", "")) is not None
                )
                for t in terms
            )
            if not per_term_ok:
                map_empty = not any(
                    n.metadata.labels.get(t.get("topologyKey", "")) is not None
                    for _p, n in all_matching
                    for t in terms
                )
                self_match = all(_match_term(t, ns, pod) for t in terms)
                if not (labels_ok and map_empty and self_match):
                    return False
        return True

    def spread_ok(self, pod: Pod, node: Node) -> bool:
        ns = pod.metadata.namespace
        for c in pod.spec.topology_spread_constraints:
            if c.get("whenUnsatisfiable", "DoNotSchedule") != "DoNotSchedule":
                continue
            key = c.get("topologyKey", "")
            skew = int(c.get("maxSkew", 1))
            sel = c.get("labelSelector")
            my_val = node.metadata.labels.get(key)
            if my_val is None:
                return False  # node missing the label fails the constraint
            def matches(p):
                return p.metadata.namespace == ns and sel is not None and selectors.match_label_selector(
                    sel, p.metadata.labels
                )
            counts = {}
            for p, n in self.bound:
                val = n.metadata.labels.get(key)
                if val is not None and matches(p):
                    counts[val] = counts.get(val, 0) + 1
            # min over eligible domains: nodes passing the incoming pod's
            # node affinity/selector that carry the label
            eligible_vals = {
                n.metadata.labels.get(key)
                for n in self.nodes
                if n.metadata.labels.get(key) is not None
                and selectors.pod_matches_node_selector_and_affinity(pod, n)
            }
            if not eligible_vals:
                return False
            min_cnt = min(counts.get(v, 0) for v in eligible_vals)
            self_match = 1 if matches(pod) else 0
            if counts.get(my_val, 0) + self_match - min_cnt > skew:
                return False
        return True

    def feasible(self, pod: Pod, node: Node) -> bool:
        return (
            self.static_ok(pod, node)
            and self.fit_ok(pod, node)
            and self.ports_ok(pod, node)
            and self.interpod_ok(pod, node)
            and self.spread_ok(pod, node)
        )


# ---------------------------------------------------------------------------
# generators (no GPU/local storage — out of the oracle's scope)
# ---------------------------------------------------------------------------

def random_cluster(rng, n):
    rt = ResourceTypes()
    for i in range(n):
        labels = {}
        if rng.random() < 0.8:
            labels["topology.kubernetes.io/zone"] = f"z{rng.randrange(3)}"
        if rng.random() < 0.5:
            labels["topology.kubernetes.io/region"] = f"r{rng.randrange(2)}"
        if rng.random() < 0.4:
            labels["disk"] = rng.choice(["ssd", "hdd"])
        opts = [fx.with_labels(labels)]
        if rng.random() < 0.25:
            opts.append(fx.with_taints([{"key": "dedicated", "value": "x",
                                         "effect": rng.choice(["NoSchedule", "PreferNoSchedule"])}]))
        if rng.random() < 0.15:
            # NodePreferAvoidPods annotation naming one of the bare-pod RS
            # controllers the app generator can emit
            import json as _json

            opts.append(fx.with_annotations({
                "scheduler.alpha.kubernetes.io/preferAvoidPods": _json.dumps(
                    {"preferAvoidPods": [{"podSignature": {"podController": {
                        "kind": "ReplicaSet", "uid": f"rs-oracle-{rng.randrange(2)}"}}}]}
                )
            }))
        rt.nodes.append(fx.make_fake_node(f"n{i:03d}", str(rng.choice([4, 8])), "16Gi", "20", *opts))
    return rt


def random_app(rng, n_workloads):
    rt = ResourceTypes()
    for w in range(n_workloads):
        opts = []
        if rng.random() < 0.3:
            opts.append(fx.with_node_selector({"disk": rng.choice(["ssd", "hdd"])}))
        if rng.random() < 0.3:
            opts.append(fx.with_tolerations(
                [{"key": "dedicated", "operator": "Equal", "value": "x", "effect": "NoSchedule"}]))
        if rng.random() < 0.35:
            opts.append(fx.with_topology_spread([{
                "maxSkew": rng.choice([1, 2]),
                "topologyKey": rng.choice(
                    [HOSTNAME, "topology.kubernetes.io/zone", "topology.kubernetes.io/region"]),
                "whenUnsatisfiable": rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                "labelSelector": {"matchLabels": {"app": f"w{w}"}},
            }]))
        if rng.random() < 0.35:
            kind = rng.choice(["podAffinity", "podAntiAffinity"])
            mode = "preferred" if rng.random() < 0.4 else "required"
            n_terms = rng.randrange(1, 3) if (kind == "podAffinity" and mode == "required") else 1
            terms = []
            for _ in range(n_terms):
                term = {
                    "labelSelector": {"matchLabels": {"app": f"w{rng.randrange(max(w, 1))}" if w else f"w{w}"}},
                    "topologyKey": rng.choice(
                        [HOSTNAME, "topology.kubernetes.io/zone", "topology.kubernetes.io/region"]),
                }
                if rng.random() < 0.4:  # explicit multi-namespace scoping
                    term["namespaces"] = rng.sample(["ns-a", "ns-b", "default"], rng.randrange(1, 3))
                terms.append(term)
            if mode == "required":
                aff = {kind: {"requiredDuringSchedulingIgnoredDuringExecution": terms}}
            else:
                aff = {kind: {"preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": rng.choice([10, 50, 100]), "podAffinityTerm": t} for t in terms
                ]}}
            opts.append(fx.with_affinity(aff))
        if rng.random() < 0.25:
            # preferred node affinity (NodeAffinity score plugin)
            opts.append(fx.with_affinity({
                "nodeAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": rng.choice([5, 20, 100]),
                     "preference": {"matchExpressions": [
                         {"key": "disk", "operator": "In",
                          "values": [rng.choice(["ssd", "hdd"])]}]}}
                ]}
            }))
        if rng.random() < 0.25:
            opts.append(fx.with_host_ports([rng.choice([8080, 9090])]))
        if rng.random() < 0.5:
            opts.append(fx.with_namespace(rng.choice(["ns-a", "ns-b"])))
        rt.deployments.append(fx.make_fake_deployment(
            f"w{w}", rng.randrange(2, 7),
            f"{rng.choice([250, 500, 1000, 2000])}m", f"{rng.choice([256, 512, 2048])}Mi", *opts))
    if rng.random() < 0.3:
        # bare pods owned by the RS controllers the avoid annotations name
        from opensim_tpu.models.objects import OwnerReference

        rs = rng.randrange(2)
        for k in range(rng.randrange(1, 4)):
            p = fx.make_fake_pod(f"avoided-{rs}-{k}", "250m", "256Mi")
            p.metadata.owner_references = [
                OwnerReference(kind="ReplicaSet", name=f"rs-oracle-{rs}",
                               uid=f"rs-oracle-{rs}", controller=True)
            ]
            rt.pods.append(p)
    return rt


@pytest.mark.parametrize("seed", [3, 17, 29, 61, 97])
def test_engine_matches_k8s_oracle(seed):
    rng = random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(4, 10))
    app = random_app(rng, rng.randrange(3, 7))
    prep = prepare(cluster, [AppResource("oracle", app)], node_pad=8)
    if prep is None:
        pytest.skip("empty workload")
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    chosen = np.asarray(out.chosen)[:P]

    oracle = Oracle(cluster.nodes)
    node_names = prep.meta.node_names
    for i, pod in enumerate(prep.ordered):
        c = int(chosen[i])
        if c >= 0:
            node = oracle.by_name[node_names[c]]
            assert oracle.feasible(pod, node), (
                f"seed={seed}: engine bound {pod.metadata.name} to {node.metadata.name}, "
                f"oracle says infeasible (static={oracle.static_ok(pod, node)} "
                f"fit={oracle.fit_ok(pod, node)} ports={oracle.ports_ok(pod, node)} "
                f"interpod={oracle.interpod_ok(pod, node)} spread={oracle.spread_ok(pod, node)})"
            )
            oracle.bind(pod, node)
        else:
            feasible_nodes = [n.metadata.name for n in cluster.nodes if oracle.feasible(pod, n)]
            assert not feasible_nodes, (
                f"seed={seed}: engine left {pod.metadata.name} unscheduled but the oracle "
                f"finds feasible nodes {feasible_nodes}"
            )


# ---------------------------------------------------------------------------
# scoring oracle — independent implementation of the default score plugins,
# weights, and normalization (registry.go:119-132 + Simon/Open-Gpu-Share at
# weight 1 each, pkg/simulator/utils.go:321-368; per-plugin normalization
# over the filtered-node list, framework.go:635). Works on Pod/Node objects
# and the oracle's bound list, never on the tensor encodings.
# ---------------------------------------------------------------------------

import math

NONZERO_CPU = 0.1  # GetNonzeroRequests defaults: 100m
NONZERO_MEM = 200.0 * 1024 * 1024  # 200MB


def _nonzero(pod: Pod):
    req = pod.resource_requests()
    return (req.get("cpu") or NONZERO_CPU, req.get("memory") or NONZERO_MEM)


class ScoreOracle:
    """Given the filter oracle's bound state, computes each feasible node's
    total weighted score for the incoming pod. Float arithmetic: the engine
    scores in f32 while kube rounds to int64 at each step — the assertion's
    epsilon absorbs both (documented divergence, like the lowest-index
    tie-break)."""

    W_BALANCED = 1.0
    W_LEAST = 1.0
    W_NODE_AFFINITY = 1.0
    W_TAINT = 1.0
    W_INTERPOD = 1.0
    W_SPREAD = 2.0
    W_SHARE = 2.0  # Simon (1) + Open-Gpu-Share (1): same formula, same norm
    W_AVOID = 10000.0

    def __init__(self, oracle: Oracle):
        self.o = oracle

    def totals(self, pod: Pod, feasible, owner_selector=None):
        """node name → total score over the feasible node list.
        `owner_selector` feeds the system-default spread constraints (the
        k8s 1.21 DefaultPodTopologySpread scoring defaults applied when the
        pod carries none of its own)."""
        out = {n.metadata.name: 0.0 for n in feasible}
        self._least_balanced(pod, feasible, out)
        self._node_affinity(pod, feasible, out)
        self._taints(pod, feasible, out)
        self._interpod(pod, feasible, out)
        self._spread(pod, feasible, out, owner_selector)
        self._share(pod, feasible, out)
        self._avoid(pod, feasible, out)
        return out

    def _used_nonzero(self, node):
        cpu = mem = 0.0
        for p, n in self.o.bound:
            if n is node:
                c, m = _nonzero(p)
                cpu += c
                mem += m
        return cpu, mem

    def _least_balanced(self, pod, feasible, out):
        # least_allocated.go:93 leastRequestedScore; balanced_allocation.go:82
        pc, pm = _nonzero(pod)
        for n in feasible:
            uc, um = self._used_nonzero(n)
            ac = n.allocatable.get("cpu", 0.0)
            am = n.allocatable.get("memory", 0.0)
            rc, rm = uc + pc, um + pm

            def least(req, cap):
                if cap == 0 or req > cap:
                    return 0.0
                return (cap - req) * 100.0 / cap

            out[n.metadata.name] += self.W_LEAST * (least(rc, ac) + least(rm, am)) / 2.0
            cf = rc / ac if ac else 0.0
            mf = rm / am if am else 0.0
            bal = 0.0 if (cf >= 1 or mf >= 1) else (1.0 - abs(cf - mf)) * 100.0
            out[n.metadata.name] += self.W_BALANCED * bal

    def _node_affinity(self, pod, feasible, out):
        # node_affinity.go Score + DefaultNormalizeScore(100, reverse=false)
        raw = {n.metadata.name: float(selectors.node_affinity_preferred_score(pod, n))
               for n in feasible}
        mx = max(raw.values(), default=0.0)
        for k, v in raw.items():
            out[k] += self.W_NODE_AFFINITY * (v * 100.0 / mx if mx > 0 else v)

    def _taints(self, pod, feasible, out):
        # taint_toleration.go CountIntolerableTaintsOfNode + reverse norm
        raw = {n.metadata.name: float(selectors.count_intolerable_prefer_no_schedule(pod, n))
               for n in feasible}
        mx = max(raw.values(), default=0.0)
        for k, v in raw.items():
            out[k] += self.W_TAINT * (100.0 - v * 100.0 / mx if mx > 0 else 100.0)

    def _interpod(self, pod, feasible, out):
        # interpodaffinity/scoring.go: incoming preferred terms (anti
        # negative), symmetric existing preferred terms, and existing
        # REQUIRED affinity terms at HardPodAffinityWeight=1
        ns = pod.metadata.namespace
        raw = {n.metadata.name: 0.0 for n in feasible}

        def domain_match(node, other_node, key):
            v = other_node.metadata.labels.get(key)
            return v is not None and node.metadata.labels.get(key) == v

        for n in feasible:
            s = 0.0
            for term_w in _terms(pod, "podAffinity", "preferred"):
                t, w = term_w.get("podAffinityTerm") or {}, float(term_w.get("weight", 0))
                for p, pn in self.o.bound:
                    if _match_term(t, ns, p) and domain_match(n, pn, t.get("topologyKey", "")):
                        s += w
            for term_w in _terms(pod, "podAntiAffinity", "preferred"):
                t, w = term_w.get("podAffinityTerm") or {}, float(term_w.get("weight", 0))
                for p, pn in self.o.bound:
                    if _match_term(t, ns, p) and domain_match(n, pn, t.get("topologyKey", "")):
                        s -= w
            for p, pn in self.o.bound:
                pns = p.metadata.namespace
                for term_w in _terms(p, "podAffinity", "preferred"):
                    t, w = term_w.get("podAffinityTerm") or {}, float(term_w.get("weight", 0))
                    if _match_term(t, pns, pod) and domain_match(n, pn, t.get("topologyKey", "")):
                        s += w
                for term_w in _terms(p, "podAntiAffinity", "preferred"):
                    t, w = term_w.get("podAffinityTerm") or {}, float(term_w.get("weight", 0))
                    if _match_term(t, pns, pod) and domain_match(n, pn, t.get("topologyKey", "")):
                        s -= w
                for t in _terms(p, "podAffinity", "required"):
                    if _match_term(t, pns, pod) and domain_match(n, pn, t.get("topologyKey", "")):
                        s += 1.0  # HardPodAffinityWeight
            raw[n.metadata.name] = s
        hi = max(max(raw.values(), default=0.0), 0.0)
        lo = min(min(raw.values(), default=0.0), 0.0)
        rng = hi - lo
        for k, v in raw.items():
            out[k] += self.W_INTERPOD * (100.0 * (v - lo) / rng if rng > 0 else 0.0)

    def _spread(self, pod, feasible, out, owner_selector=None):
        # podtopologyspread/scoring.go: soft constraints only; raw =
        # Σ count·log(size+2) + (maxSkew-1); nodes missing a key are
        # "ignored" (score 0); normalize 100·(max+min-raw)/max. Pods with no
        # explicit constraints get the system defaults (maxSkew 3 hostname,
        # maxSkew 5 zone, ScheduleAnyway) with the owning workload's selector
        ns = pod.metadata.namespace
        explicit = pod.spec.topology_spread_constraints
        if explicit:
            soft = [c for c in explicit
                    if c.get("whenUnsatisfiable", "DoNotSchedule") == "ScheduleAnyway"]
        elif owner_selector is not None:
            soft = [
                {"topologyKey": HOSTNAME, "maxSkew": 3,
                 "whenUnsatisfiable": "ScheduleAnyway", "labelSelector": owner_selector},
                {"topologyKey": "topology.kubernetes.io/zone", "maxSkew": 5,
                 "whenUnsatisfiable": "ScheduleAnyway", "labelSelector": owner_selector},
            ]
        else:
            soft = []
        if not soft:
            return
        raw, ignored = {}, set()
        for n in feasible:
            s = 0.0
            for c in soft:
                key = c.get("topologyKey", "")
                my = n.metadata.labels.get(key)
                if my is None:
                    ignored.add(n.metadata.name)
                    continue
                sel = c.get("labelSelector")
                cnt = sum(
                    1 for p, pn in self.o.bound
                    if p.metadata.namespace == ns and sel is not None
                    and selectors.match_label_selector(sel, p.metadata.labels)
                    and pn.metadata.labels.get(key) == my
                )
                size = len({x.metadata.labels.get(key) for x in self.o.nodes
                            if x.metadata.labels.get(key) is not None})
                s += cnt * math.log(size + 2.0) + (int(c.get("maxSkew", 1)) - 1)
            raw[n.metadata.name] = s
        scored = [v for k, v in raw.items() if k not in ignored]
        mx = max(scored, default=0.0)
        mn = min(scored, default=0.0)
        for k, v in raw.items():
            if k in ignored:
                continue  # normalized score 0
            out[k] += self.W_SPREAD * (100.0 if mx <= 0 else 100.0 * (mx + mn - v) / mx)

    def _share(self, pod, feasible, out):
        # plugin/simon.go:45-101 + algo.Share (greed.go:70-83): max over the
        # node's declared allocatable resources of req/(alloc - req), static
        # allocatable; no requests → MaxNodeScore; then min-max normalize
        req = pod.resource_requests()
        raw = {}
        for n in feasible:
            if not req:
                raw[n.metadata.name] = 100.0
                continue
            best = 0.0
            for r, alloc in self.o.alloc_view(n).items():
                pr = req.get(r, 0.0)
                avail = alloc - pr
                share = (1.0 if pr else 0.0) if avail == 0 else pr / avail
                best = max(best, share)
            raw[n.metadata.name] = best * 100.0
        hi = max(raw.values(), default=0.0)
        lo = min(raw.values(), default=0.0)
        rng = hi - lo
        for k, v in raw.items():
            out[k] += self.W_SHARE * ((v - lo) * 100.0 / rng if rng > 0 else 0.0)

    def _avoid(self, pod, feasible, out):
        # node_prefer_avoid_pods.go:47-82: controller (RS/RC) listed in the
        # node's preferAvoidPods annotation → 0, else 100; no normalization
        import json

        ctrl = None
        for ref in pod.metadata.owner_references:
            if ref.controller and ref.kind in ("ReplicaSet", "ReplicationController"):
                ctrl = (ref.kind, ref.uid)
                break
        for n in feasible:
            score = 100.0
            anno = n.metadata.annotations.get("scheduler.alpha.kubernetes.io/preferAvoidPods")
            if anno and ctrl is not None:
                try:
                    entries = json.loads(anno).get("preferAvoidPods") or []
                except (ValueError, AttributeError):
                    entries = []
                for e in entries:
                    pc = ((e.get("podSignature") or {}).get("podController") or {})
                    if (str(pc.get("kind", "")), str(pc.get("uid", ""))) == ctrl:
                        score = 0.0
                        break
            out[n.metadata.name] += self.W_AVOID * score


def _score_eps(totals) -> float:
    """Tolerance for engine-f32 vs oracle-f64 score comparison. Scaled to
    the f32 resolution at the TOTAL's magnitude (a 1e6 NodePreferAvoidPods
    baseline costs ~0.06 of f32 ulp; a few accumulation steps multiply
    that), NOT to a fraction of the magnitude — 1e-4·mag would exceed an
    entire 0-100 plugin range once avoid's constant 1e6 is present, making
    single-plugin assertions vacuous."""
    mag = max((abs(v) for v in totals.values()), default=1.0)
    return max(1e-3, 4e-6 * mag)


def _replay_with_scores(prep, cluster, chosen):
    """Replays the engine's placements through both oracles; returns the
    number of score-suboptimal binds (engine chose a node more than EPS
    below the oracle's best over the feasible set)."""
    from opensim_tpu.engine.simulator import _owner_selector

    oracle = Oracle(cluster.nodes)
    scorer = ScoreOracle(oracle)
    node_names = prep.meta.node_names
    violations = 0
    for i, pod in enumerate(prep.ordered):
        c = int(chosen[i])
        feasible = [n for n in cluster.nodes if oracle.feasible(pod, n)]
        if c >= 0:
            node = oracle.by_name[node_names[c]]
            totals = scorer.totals(pod, feasible, _owner_selector(pod))
            best = max(totals.values())
            mine = totals[node.metadata.name]
            eps = _score_eps(totals)
            if mine < best - eps:
                violations += 1
            oracle.bind(pod, node)
    return violations


SCORE_SEEDS = [3, 17, 29, 61, 97, 131, 151] + list(range(500, 523))  # 30 seeds


@pytest.mark.parametrize("seed", SCORE_SEEDS)
def test_engine_scores_match_k8s_oracle(seed):
    """Every bind must land on a score-optimal feasible node per the
    independent score oracle (weights, formulas, and normalization from the
    Go sources)."""
    rng = random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(4, 10))
    app = random_app(rng, rng.randrange(3, 7))
    prep = prepare(cluster, [AppResource("oracle", app)], node_pad=8)
    if prep is None:
        pytest.skip("empty workload")
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    chosen = np.asarray(out.chosen)[:P]
    violations = _replay_with_scores(prep, cluster, chosen)
    assert violations == 0


def test_score_oracle_rejects_misweighted_engine():
    """Sensitivity check: an engine running with deliberately wrong score
    weights must produce binds the oracle flags as suboptimal — otherwise
    the oracle is vacuous."""
    from opensim_tpu.engine.schedconfig import DEFAULT_CONFIG

    bad = DEFAULT_CONFIG._replace(w_least=0.0, w_balanced=0.0, w_simon=20.0)
    caught = 0
    for seed in SCORE_SEEDS:
        rng = random.Random(seed)
        cluster = random_cluster(rng, rng.randrange(4, 10))
        app = random_app(rng, rng.randrange(3, 7))
        prep = prepare(cluster, [AppResource("oracle", app)], node_pad=8)
        if prep is None:
            continue
        P = len(prep.ordered)
        t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
        out = schedule_pods(
            prep.ec, prep.st0, t, v, f, features=prep.features, config=bad
        )
        caught += _replay_with_scores(prep, cluster, np.asarray(out.chosen)[:P])
    assert caught > 0, "oracle failed to flag a mis-weighted engine"


# ---------------------------------------------------------------------------
# extension oracle — GPU-share devices and open-local storage, from the
# plugin sources (open-gpu-share.go:51-81, AllocateGpuId
# gpunodeinfo.go:232-290; open-local common.go predicates/scores with the
# documented coalesced-LVM divergence, PARITY.md #4). State lives in plain
# dicts over Node objects; annotations are parsed here, not via the
# encoder.
# ---------------------------------------------------------------------------

import json as _json

from opensim_tpu.models.quantity import parse_quantity as _pq


def _pod_gpu(pod):
    mem = pod.metadata.annotations.get("alibabacloud.com/gpu-mem")
    try:
        mem = float(_pq(mem)) if mem else 0.0
    except ValueError:
        mem = 0.0
    try:
        cnt = max(int(pod.metadata.annotations.get("alibabacloud.com/gpu-count", "0") or 0), 0)
    except ValueError:
        cnt = 0
    return mem, (cnt if mem > 0 else 0)


def _pod_local(pod):
    raw = pod.metadata.annotations.get("simon/pod-local-storage")
    lvm, devs = 0.0, []
    if raw:
        try:
            vols = (_json.loads(raw) or {}).get("volumes") or []
        except ValueError:
            vols = []
        for v in vols:
            kind = str(v.get("kind", ""))
            size = float(_pq(v.get("size", 0)))
            if kind == "LVM":
                lvm += size
            elif kind in ("SSD", "HDD"):
                devs.append((size, kind))
    return lvm, devs


class ExtOracle(Oracle):
    """Filter oracle extended with fractional-GPU devices and open-local
    VG/exclusive-device storage, tracking its own allocation state."""

    def __init__(self, nodes):
        super().__init__(nodes)
        self.gpu_free = {}
        self.vg = {}  # name -> [(vg_name, free, cap)]
        self.devs = {}  # name -> [(dev_name, free, media, cap)]
        for n in nodes:
            total = n.allocatable.get("alibabacloud.com/gpu-mem", 0.0)
            cnt = int(n.allocatable.get("alibabacloud.com/gpu-count", 0))
            self.gpu_free[n.metadata.name] = (
                [total / cnt] * cnt if cnt > 0 and total > 0 else []
            )
            raw = n.metadata.annotations.get("simon/node-local-storage")
            vgs, devs = [], []
            if raw:
                try:
                    data = _json.loads(raw)
                except ValueError:
                    data = {}
                for vg in data.get("vgs") or []:
                    cap = float(_pq(vg.get("capacity", 0)))
                    vgs.append([str(vg.get("name", "")), cap, cap])
                for d in data.get("devices") or []:
                    cap = float(_pq(d.get("capacity", 0)))
                    media = "SSD" if str(d.get("mediaType", "")).lower() == "ssd" else "HDD"
                    devs.append([str(d.get("device", "")), cap, media, cap])
            self.vg[n.metadata.name] = vgs
            self.devs[n.metadata.name] = devs

    def alloc_view(self, node: Node) -> dict:
        """Reserve-updated allocatable (open-gpu-share.go:147-188 →
        gpunodeinfo.go:354-369): on device-bearing nodes gpu-count is the
        number of not-fully-used devices; everything else stays static."""
        free = self.gpu_free.get(node.metadata.name) or []
        if not free:
            return node.allocatable
        alloc = dict(node.allocatable)
        alloc["alibabacloud.com/gpu-count"] = float(sum(1 for f in free if f > 0))
        return alloc

    def gpu_ok(self, pod: Pod, node: Node) -> bool:
        mem, cnt = _pod_gpu(pod)
        if mem <= 0:
            return True
        free = self.gpu_free[node.metadata.name]
        return cnt > 0 and sum(int(f // mem) for f in free) >= cnt

    def local_ok(self, pod: Pod, node: Node) -> bool:
        lvm, devs = _pod_local(pod)
        name = node.metadata.name
        if lvm > 0 and not any(free >= lvm for _vg, free, _cap in self.vg[name]):
            return False
        # one exclusive device per volume (common.go:290-349): simulate the
        # smallest-volume-first matching on a scratch copy
        taken = set()
        for media in ("SSD", "HDD"):
            for size, _m in sorted(v for v in devs if v[1] == media):
                pick = None
                for idx, (dn, free, m, cap) in enumerate(self.devs[name]):
                    if idx in taken or m != media or free < size or free <= 0:
                        continue
                    if pick is None or cap < self.devs[name][pick][3]:
                        pick = idx
                if pick is None:
                    return False
                taken.add(pick)
        return True

    def feasible(self, pod: Pod, node: Node) -> bool:
        return (
            super().feasible(pod, node)
            and self.gpu_ok(pod, node)
            and self.local_ok(pod, node)
        )

    def bind(self, pod: Pod, node: Node):
        super().bind(pod, node)
        name = node.metadata.name
        mem, cnt = _pod_gpu(pod)
        free = self.gpu_free[name]
        if mem > 0 and cnt > 0:
            if cnt == 1:
                # tightest fit (AllocateGpuId single-GPU binpack)
                fitting = [i for i, f in enumerate(free) if f >= mem]
                tight = min(fitting, key=lambda i: (free[i], i))
                free[tight] -= mem
            else:
                # greedy multi-GPU packing in device order
                left = cnt
                for i, f in enumerate(free):
                    take = min(int(f // mem), left)
                    free[i] -= take * mem
                    left -= take
                    if left == 0:
                        break
        lvm, devs = _pod_local(pod)
        if lvm > 0:
            # tightest-fitting VG
            cands = [v for v in self.vg[name] if v[1] >= lvm]
            choice = min(cands, key=lambda v: v[1])
            choice[1] -= lvm
        taken = set()
        for media in ("SSD", "HDD"):
            for size, _m in sorted(v for v in devs if v[1] == media):
                pick = None
                for idx, (dn, dfree, m, cap) in enumerate(self.devs[name]):
                    if idx in taken or m != media or dfree < size or dfree <= 0:
                        continue
                    if pick is None or cap < self.devs[name][pick][3]:
                        pick = idx
                taken.add(pick)
                self.devs[name][pick][1] = 0.0  # exclusive: whole device


class ExtScoreOracle(ScoreOracle):
    """Adds the Open-Local capacity-match score (ScoreLVM/ScoreDevice,
    common.go:660-690,:753-762, StrategyBinpack, MaxScore 10), min-max
    normalized with weight 1. GPU-share's Score is the same share formula
    as Simon's (open-gpu-share.go:85-110) and is already inside W_SHARE."""

    W_LOCAL = 1.0

    def totals(self, pod, feasible, owner_selector=None):
        out = super().totals(pod, feasible, owner_selector)
        self._local(pod, feasible, out)
        return out

    def _local(self, pod, feasible, out):
        lvm, devs = _pod_local(pod)
        if lvm <= 0 and not devs:
            return
        o = self.o  # an ExtOracle
        raw = {}
        for n in feasible:
            name = n.metadata.name
            parts, count = 0.0, 0
            if lvm > 0:
                cands = [v for v in o.vg[name] if v[1] >= lvm]
                if cands:
                    choice = min(cands, key=lambda v: v[1])
                    parts += lvm / choice[2]
                count += 1
            for media in ("SSD", "HDD"):
                sizes = [s for s, m in devs if m == media]
                if not sizes:
                    continue
                size = max(sizes)  # score proxy: max volume size per media
                fitting = [d for d in o.devs[name] if d[2] == media and d[1] >= size and d[1] > 0]
                if fitting:
                    first_cap = min(d[3] for d in fitting)
                    parts += len(sizes) * size / first_cap
                count += len(sizes)
            raw[name] = parts / count * 10.0 if count else 0.0
        hi = max(raw.values(), default=0.0)
        lo = min(raw.values(), default=0.0)
        rng = hi - lo
        for k, v in raw.items():
            out[k] += self.W_LOCAL * ((v - lo) * 100.0 / rng if rng > 0 else 0.0)


def ext_cluster(rng, n):
    rt = ResourceTypes()
    for i in range(n):
        opts = [fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 2}"})]
        if rng.random() < 0.6:
            opts.append(fx.with_allocatable(
                {"alibabacloud.com/gpu-mem": rng.choice(["16Gi", "32Gi"]),
                 "alibabacloud.com/gpu-count": rng.choice(["2", "4"])}))
        if rng.random() < 0.6:
            opts.append(fx.with_node_local_storage(
                vgs=[{"name": "pool0", "capacity": rng.choice([50, 100]) * 1024**3}],
                devices=[
                    {"device": "/dev/vdb", "capacity": rng.choice([40, 80]) * 1024**3,
                     "mediaType": rng.choice(["ssd", "hdd"])},
                    {"device": "/dev/vdc", "capacity": 60 * 1024**3,
                     "mediaType": rng.choice(["ssd", "hdd"])},
                ]))
        rt.nodes.append(fx.make_fake_node(f"n{i:03d}", "16", "64Gi", "110", *opts))
    return rt


def ext_app(rng, n_pods):
    rt = ResourceTypes()
    for k in range(n_pods):
        opts = []
        roll = rng.random()
        if roll < 0.35:
            opts.append(fx.with_annotations(
                {"alibabacloud.com/gpu-mem": rng.choice(["2Gi", "4Gi", "8Gi"]),
                 "alibabacloud.com/gpu-count": rng.choice(["1", "1", "2"])}))
        elif roll < 0.5:
            # whole-GPU pod: gpu-count as a SPEC resource — exercises the
            # dynamic allocatable (Reserve rewrite) in fit and share
            opts.append(fx.with_requests(
                {"alibabacloud.com/gpu-count": rng.choice(["1", "1", "2"])}))
        elif roll < 0.8:
            vols = [{"size": str(rng.choice([5, 10, 20]) * 1024**3), "kind": "LVM",
                     "scName": "open-local-lvm"}]
            if rng.random() < 0.5:
                vols.append({"size": str(rng.choice([10, 30]) * 1024**3),
                             "kind": rng.choice(["SSD", "HDD"]),
                             "scName": "open-local-device"})
            opts.append(fx.with_pod_local_storage(_json.dumps({"volumes": vols})))
        rt.pods.append(fx.make_fake_pod(
            f"ext-{k}", f"{rng.choice([250, 500, 1000])}m",
            f"{rng.choice([512, 1024])}Mi", *opts))
    return rt


@pytest.mark.parametrize("seed", [11, 42, 77, 123, 202, 307, 501, 777])
def test_engine_matches_ext_oracle_gpu_local(seed):
    """GPU-share and open-local decisions — filter feasibility AND score
    optimality — replayed against the extension oracle."""
    from opensim_tpu.engine.simulator import _owner_selector

    rng = random.Random(seed)
    cluster = ext_cluster(rng, rng.randrange(3, 8))
    app = ext_app(rng, rng.randrange(8, 25))
    prep = prepare(cluster, [AppResource("ext", app)], node_pad=8)
    if prep is None:
        pytest.skip("empty workload")
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    chosen = np.asarray(out.chosen)[:P]

    oracle = ExtOracle(cluster.nodes)
    scorer = ExtScoreOracle(oracle)
    node_names = prep.meta.node_names
    for i, pod in enumerate(prep.ordered):
        c = int(chosen[i])
        feasible = [n for n in cluster.nodes if oracle.feasible(pod, n)]
        if c >= 0:
            node = oracle.by_name[node_names[c]]
            assert oracle.feasible(pod, node), (
                f"seed={seed}: engine bound {pod.metadata.name} to "
                f"{node.metadata.name}, ext oracle says infeasible "
                f"(gpu={oracle.gpu_ok(pod, node)} local={oracle.local_ok(pod, node)})"
            )
            totals = scorer.totals(pod, feasible, _owner_selector(pod))
            best = max(totals.values())
            mine = totals[node.metadata.name]
            assert mine >= best - _score_eps(totals), (
                f"seed={seed}: {pod.metadata.name} on {node.metadata.name} "
                f"scored {mine:.3f} < best {best:.3f}; totals={totals}"
            )
            oracle.bind(pod, node)
        else:
            feas = [n.metadata.name for n in feasible]
            assert not feas, (
                f"seed={seed}: engine left {pod.metadata.name} unscheduled "
                f"but ext oracle finds {feas}"
            )
