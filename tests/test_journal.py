"""Twin time machine coverage (ISSUE 11): crash-safe watch-event journal —
CRC32 framing and torn-tail truncation, checkpoint + suffix recovery,
segment rotation/pruning, the off-dispatch bounded writer, deterministic
replay (``simon replay`` / ``rebuild_twin``), ``journal.*`` fault points,
a true SIGKILL-mid-storm subprocess crash with same-journal restart, and
graceful SIGTERM shutdown of ``simon server``. Part of ``make chaos``."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from opensim_tpu.engine.prepcache import fingerprint_cluster
from opensim_tpu.models import fixtures as fx
from opensim_tpu.resilience import faults
from opensim_tpu.server.journal import (
    Journal,
    JournalError,
    iter_records,
    journal_policy,
    rebuild_twin,
    replay_events,
)
from opensim_tpu.server.snapshot import _cluster_via_rest
from opensim_tpu.server.stubapi import StubApiServer
from opensim_tpu.server.watch import RestWatchSource, WatchSupervisor

FAST = {"stale_s": 5.0, "resync_s": 0.0, "reconnects": 3, "backoff_s": 0.02}

LIST_PATHS = (
    "/api/v1/nodes",
    "/api/v1/pods",
    "/apis/apps/v1/daemonsets",
    "/apis/policy/v1/poddisruptionbudgets",
    "/api/v1/services",
    "/apis/storage.k8s.io/v1/storageclasses",
    "/api/v1/persistentvolumeclaims",
    "/api/v1/configmaps",
)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("OPENSIM_FAULTS", raising=False)
    faults.clear_faults()
    yield
    faults.clear_faults()


def _pod_dict(name, phase="Pending", node="", cpu="100m", rv=None):
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": cpu}}}]},
        "status": {"phase": phase},
    }
    if node:
        d["spec"]["nodeName"] = node
    if rv is not None:
        d["metadata"]["resourceVersion"] = str(rv)
    return d


def _seed(stub, n_nodes=4, pods=()):
    stub.seed("/api/v1/nodes", [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(n_nodes)])
    stub.seed("/api/v1/pods", list(pods))
    for p in LIST_PATHS[2:]:
        stub.seed(p, [])


def _wait(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _write_basic_journal(path, events=5):
    """A checkpoint (2 nodes) + ``events`` pod ADDEDs, cleanly closed."""
    j = Journal(path, policy={"fsync": "always"})
    j.record_checkpoint(
        {"nodes": [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(2)]},
        generation=1,
        resume_rvs={"nodes": "100", "pods": "100"},
        why="test",
    )
    for i in range(events):
        j.record_event("pods", "ADDED", _pod_dict(f"p{i}", rv=101 + i), 2 + i)
    j.close()
    return j


# ---------------------------------------------------------------------------
# framing, torn tails, corruption
# ---------------------------------------------------------------------------


def test_roundtrip_records_in_order(tmp_path):
    jd = str(tmp_path / "j")
    _write_basic_journal(jd, events=3)
    recs = list(iter_records(jd))
    assert [r["t"] for r in recs] == ["ck", "ev", "ev", "ev"]
    assert [r["gen"] for r in recs] == [1, 2, 3, 4]
    assert recs[0]["rvs"] == {"nodes": "100", "pods": "100"}
    assert recs[1]["o"]["metadata"]["name"] == "p0"


def test_torn_tail_truncated_loudly_on_reopen(tmp_path, caplog):
    jd = str(tmp_path / "j")
    _write_basic_journal(jd, events=3)
    seg = sorted(p for p in os.listdir(jd) if p.endswith(".seg"))[-1]
    with open(os.path.join(jd, seg), "ab") as f:
        f.write(b"\x40\x00\x00\x00\xde\xad")  # frame header promises 64 bytes
    with caplog.at_level("WARNING", logger="opensim_tpu.server.journal"):
        j = Journal(jd)
    assert any("torn tail" in r.message for r in caplog.records)
    # the truncation healed the file: all real records intact, and new
    # appends land after them
    j.record_event("pods", "ADDED", _pod_dict("late", rv=200), 10)
    j.close()
    assert [r["t"] for r in iter_records(jd)] == ["ck", "ev", "ev", "ev", "ev"]


def test_corruption_mid_stream_stops_replay_at_last_good_frame(tmp_path):
    jd = str(tmp_path / "j")
    _write_basic_journal(jd, events=4)
    seg = os.path.join(jd, sorted(p for p in os.listdir(jd) if p.endswith(".seg"))[-1])
    # flip one byte inside the LAST record's payload: its crc fails, the
    # walk stops there, and everything before it stays reachable
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.seek(size - 20)
        b = f.read(1)
        f.seek(size - 20)
        f.write(bytes([b[0] ^ 0xFF]))
    recs = list(iter_records(jd))
    assert [r["t"] for r in recs] == ["ck", "ev", "ev", "ev"]
    # recovery degrades to the surviving prefix, never raises
    state = Journal(jd, readonly=True).recover()
    assert state is not None and state.outcome == "restored"
    assert sorted(p["metadata"]["name"] for p in state.stores["pods"]) == ["p0", "p1", "p2"]


def test_recover_is_checkpoint_plus_suffix(tmp_path):
    jd = str(tmp_path / "j")
    _write_basic_journal(jd, events=3)
    state = Journal(jd, readonly=True).recover()
    assert state is not None
    assert state.checkpoint_generation == 1
    assert state.generation == 4
    assert state.records_replayed == 3
    assert sorted(p["metadata"]["name"] for p in state.stores["pods"]) == ["p0", "p1", "p2"]
    assert len(state.stores["nodes"]) == 2
    # resume rvs: the checkpoint's listing rvs advanced by the suffix events
    assert state.resume_rvs["pods"] == "103"
    assert state.resume_rvs["nodes"] == "100"


def test_recover_empty_journal_is_none(tmp_path):
    jd = str(tmp_path / "j")
    j = Journal(jd)
    j.close()
    assert Journal(jd, readonly=True).recover() is None


def test_events_without_checkpoint_degrade_to_relist(tmp_path, caplog):
    jd = str(tmp_path / "j")
    j = Journal(jd, policy={"fsync": "always"})
    j.record_event("pods", "ADDED", _pod_dict("p0", rv=1), 1)
    j.close()
    with caplog.at_level("WARNING", logger="opensim_tpu.server.journal"):
        state = Journal(jd, readonly=True).recover()
    assert state is None
    assert any("no checkpoint" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# rotation, checkpoint cadence, pruning
# ---------------------------------------------------------------------------


def test_checkpoint_cadence_rotates_and_prunes_old_segments(tmp_path):
    class _Obj:  # the checkpoint source hands the writer objects with .raw
        def __init__(self, raw):
            self.raw = raw

    jd = str(tmp_path / "j")
    nodes = [_Obj(fx.make_fake_node("n0", "8", "16Gi").raw)]
    pods = {}

    def source():
        return ({"nodes": nodes, "pods": list(pods.values())}, max(pods) if pods else 1, [])

    j = Journal(jd, policy={"fsync": "always", "checkpoint_every": 5, "keep": 2})
    j.checkpoint_source = source
    for i in range(30):
        gen = 2 + i
        raw = _pod_dict(f"p{i}", rv=100 + i)
        pods[gen] = _Obj(raw)
        j.record_event("pods", "ADDED", raw, gen)
        j.flush(timeout=10.0)
    j.close()
    segs = sorted(p for p in os.listdir(jd) if p.endswith(".seg"))
    # 30 events at a 5-event cadence rotated several times, and pruning
    # kept only the newest `keep` checkpoint segments (+ any trailing one)
    assert 2 <= len(segs) <= 3
    # the retained history is complete and self-contained: recovery works
    state = Journal(jd, readonly=True).recover()
    assert state is not None and state.outcome == "restored"
    assert state.generation == 31


def test_writer_queue_bound_drops_and_counts(tmp_path):
    jd = str(tmp_path / "j")
    j = Journal(jd, policy={"queue": 4, "fsync": "off"})
    # stall the writer so the queue genuinely fills: first record carries a
    # fault that makes the writer sleep? simpler — enqueue before the writer
    # thread can drain by holding its condition is racy; instead shrink the
    # bound and flood faster than one drain cycle
    for i in range(5000):
        j.record_event("pods", "ADDED", _pod_dict(f"p{i}", rv=i + 1), i + 1)
    from opensim_tpu.obs.metrics import RECORDER

    with RECORDER.lock:
        dropped = j.dropped_total
    j.close()
    written = sum(1 for r in iter_records(jd) if r["t"] == "ev")
    assert written + dropped == 5000
    # the journal stays structurally valid regardless of drops
    assert all(r["t"] in ("ev", "rb", "ck") for r in iter_records(jd))


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------


def test_rebuild_twin_full_and_at_generation(tmp_path):
    jd = str(tmp_path / "j")
    _write_basic_journal(jd, events=4)
    twin, meta = rebuild_twin(jd)
    assert meta["events"] == 4 and meta["checkpoints"] == 1
    assert sorted(p.metadata.name for p in twin.materialize().pods) == ["p0", "p1", "p2", "p3"]
    assert twin.generation == 5
    # the time machine: generation 3 = checkpoint + first two events
    twin3, meta3 = rebuild_twin(jd, at_generation=3)
    assert sorted(p.metadata.name for p in twin3.materialize().pods) == ["p0", "p1"]
    assert twin3.generation == 3


def test_rebuild_twin_target_before_pruned_history_is_loud(tmp_path):
    """A target generation older than the oldest surviving checkpoint must
    raise, not return a valid-shaped empty twin (review regression)."""

    class _Obj:
        def __init__(self, raw):
            self.raw = raw

    jd = str(tmp_path / "j")
    pods = {}

    def source():
        return ({"pods": list(pods.values())}, max(pods) if pods else 1, [])

    j = Journal(jd, policy={"fsync": "off", "checkpoint_every": 5, "keep": 1})
    j.checkpoint_source = source
    for i in range(30):
        gen = 2 + i
        raw = _pod_dict(f"p{i}", rv=100 + i)
        pods[gen] = _Obj(raw)
        j.record_event("pods", "ADDED", raw, gen)
        j.flush(timeout=10.0)
    j.close()
    # pruning dropped the early segments: generation 2 is gone
    oldest_ck = min(
        int(r.get("gen") or 0) for r in iter_records(jd) if r["t"] == "ck"
    )
    assert oldest_ck > 2
    with pytest.raises(JournalError, match="predates the retained history"):
        rebuild_twin(jd, at_generation=2)
    # the newest state is still fully reachable
    twin, _meta = rebuild_twin(jd)
    assert twin.generation == 31


def test_replay_events_streams_and_matches_rebuild(tmp_path):
    jd = str(tmp_path / "j")
    _write_basic_journal(jd, events=4)
    twins = [t for _r, t, _c in replay_events(jd)]
    assert twins  # same live twin object threaded through
    final = twins[-1]
    full, _ = rebuild_twin(jd)
    assert final.fingerprint() == full.fingerprint()


def test_replay_paced_respects_recorded_gaps(tmp_path, monkeypatch):
    """speed=N sleeps the recorded inter-event gaps divided by N; speed=0
    streams as fast as possible. Recording runs under a shimmed clock so
    the gaps are exact."""
    from opensim_tpu.server import journal as journal_mod

    class _Shim:
        monotonic = staticmethod(time.monotonic)
        sleep = staticmethod(time.sleep)
        _now = [1000.0]

        @classmethod
        def time(cls):
            return cls._now[0]

    monkeypatch.setattr(journal_mod, "time", _Shim)
    jd = str(tmp_path / "j")
    j = Journal(jd, policy={"fsync": "always"})
    j.record_checkpoint({"nodes": []}, generation=1, why="test")
    for i, name in enumerate(("a", "b", "c")):
        _Shim._now[0] = 1000.0 + i * 2.0  # 2s recorded gaps
        j.record_event("pods", "ADDED", _pod_dict(name, rv=i + 1), 2 + i)
        j.flush(timeout=10.0)
    j.close()
    monkeypatch.undo()  # replay paces against the real clock

    t0 = time.monotonic()
    assert sum(1 for _ in replay_events(jd, speed=20.0)) == 4
    paced = time.monotonic() - t0
    # two 2s gaps at 20x = 0.2s of pacing (the ck->first-ev hop is free)
    assert 0.15 <= paced <= 2.0
    t0 = time.monotonic()
    assert sum(1 for _ in replay_events(jd, speed=0.0)) == 4
    assert time.monotonic() - t0 < 0.5


def test_flush_fsyncs_promptly_even_with_fsync_off(tmp_path):
    """Review regression: with ``OPENSIM_JOURNAL_FSYNC=off`` a flush used to
    park for its whole timeout (the waiter deregistered before the
    dirty-wait, so the writer was never forced to sync). A flush is the
    graceful-shutdown barrier: it must force the fsync and return fast."""
    jd = str(tmp_path / "j")
    j = Journal(jd, policy={"fsync": "off"})
    j.record_event("pods", "ADDED", _pod_dict("a", rv=1), 1)
    t0 = time.monotonic()
    assert j.flush(timeout=10.0) is True
    assert time.monotonic() - t0 < 5.0
    j.close()
    assert [r["t"] for r in iter_records(jd)] == ["ev"]


def test_replay_applies_mid_history_reanchor_checkpoints(tmp_path):
    """Review regression: a checkpoint written mid-history (the re-anchor
    after a writer-queue drop lost an event) is authoritative state — the
    streamed replay must rebase on it, or it faithfully replays the gap the
    journal already healed. Stream and random-access rebuild must agree."""
    jd = str(tmp_path / "j")
    j = Journal(jd, policy={"fsync": "always"})
    j.record_checkpoint({"pods": [_pod_dict("a", rv=1)]}, generation=1, why="bootstrap")
    j.record_event("pods", "ADDED", _pod_dict("b", rv=2), 2)
    # pod "c"'s event was dropped at the queue; the re-anchor checkpoint
    # carries the repaired store
    j.record_checkpoint(
        {"pods": [_pod_dict("a", rv=1), _pod_dict("b", rv=2), _pod_dict("c", rv=3)]},
        generation=4, why="reanchor",
    )
    j.record_event("pods", "ADDED", _pod_dict("d", rv=4), 5)
    j.close()
    final = None
    for _rec, twin, _change in replay_events(jd):
        final = twin
    assert sorted(p.metadata.name for p in final.materialize().pods) == ["a", "b", "c", "d"]
    rebuilt, _meta = rebuild_twin(jd)
    assert rebuilt.fingerprint() == final.fingerprint()


def test_explicit_checkpoint_resets_cadence_no_back_to_back_duplicate(tmp_path):
    """Review regression: reopening a journal pre-arms the cadence counter
    (the re-anchor-on-restart contract); the explicit recovered/bootstrap
    checkpoint must reset it, or every restart writes TWO full snapshots."""
    class _Obj:
        def __init__(self, raw):
            self.raw = raw

    jd = str(tmp_path / "j")
    _write_basic_journal(jd, events=2)
    j = Journal(jd, policy={"fsync": "always"})
    j.checkpoint_source = lambda: ({"pods": [_Obj(_pod_dict("x", rv=50))]}, 9, [])
    # the restart's explicit re-anchor (what _restore_from_journal writes)
    j.record_checkpoint({"pods": [_pod_dict("x", rv=50)]}, generation=9, why="recovered")
    assert j.flush(timeout=10.0)
    j.close()
    cks = [r["why"] for r in iter_records(jd) if r["t"] == "ck"]
    assert cks.count("cadence") == 0, f"duplicate cadence checkpoint after explicit one: {cks}"


# ---------------------------------------------------------------------------
# fault points (make chaos)
# ---------------------------------------------------------------------------


def test_journal_write_fault_degrades_loudly_without_crashing(tmp_path, caplog):
    jd = str(tmp_path / "j")
    j = Journal(jd, policy={"fsync": "always"})
    faults.inject("journal.write", count=1, exc="fault")
    with caplog.at_level("WARNING", logger="opensim_tpu.server.journal"):
        j.record_event("pods", "ADDED", _pod_dict("a", rv=1), 1)
        _wait(
            lambda: any("degraded" in r.message for r in caplog.records),
            msg="writer degradation warning",
        )
    # the producer side never throws — recording just stops
    j.record_event("pods", "ADDED", _pod_dict("b", rv=2), 2)
    j.close()
    assert faults.fault_stats().get("journal.write") == 1


def test_journal_fsync_fault_degrades_loudly(tmp_path, caplog):
    jd = str(tmp_path / "j")
    j = Journal(jd, policy={"fsync": "always"})
    faults.inject("journal.fsync", count=1, exc="fault")
    with caplog.at_level("WARNING", logger="opensim_tpu.server.journal"):
        j.record_event("pods", "ADDED", _pod_dict("a", rv=1), 1)
        _wait(
            lambda: any("degraded" in r.message for r in caplog.records),
            msg="writer degradation warning",
        )
    j.close()
    assert faults.fault_stats().get("journal.fsync") == 1


def test_journal_corrupt_fault_degrades_recovery_to_relist(tmp_path, caplog):
    jd = str(tmp_path / "j")
    _write_basic_journal(jd, events=2)
    j = Journal(jd, readonly=True)
    faults.inject("journal.corrupt", count=1, exc="fault")
    with caplog.at_level("WARNING", logger="opensim_tpu.server.journal"):
        state = j.recover()
    assert state is None  # degraded to relist, no exception escaped
    assert any("degrading to a full relist" in r.message for r in caplog.records)
    lines = j.metrics_lines()
    assert any('simon_journal_recoveries_total{outcome="corrupt"} 1' in ln for ln in lines)


def test_policy_validation_is_loud(monkeypatch):
    monkeypatch.setenv("OPENSIM_JOURNAL_FSYNC", "sometimes")
    with pytest.raises(ValueError, match="OPENSIM_JOURNAL_FSYNC"):
        journal_policy()
    monkeypatch.setenv("OPENSIM_JOURNAL_FSYNC", "interval")
    monkeypatch.setenv("OPENSIM_JOURNAL_KEEP", "0")
    with pytest.raises(ValueError, match="OPENSIM_JOURNAL_KEEP"):
        journal_policy()
    monkeypatch.setenv("OPENSIM_JOURNAL_KEEP", "2")
    monkeypatch.setenv("OPENSIM_JOURNAL_FSYNC_S", "nope")
    with pytest.raises(ValueError, match="OPENSIM_JOURNAL_FSYNC_S"):
        journal_policy()


# ---------------------------------------------------------------------------
# timeline restore (obs/timeline.py satellite)
# ---------------------------------------------------------------------------


def test_timeline_restore_never_rewinds(tmp_path):
    from opensim_tpu.obs.timeline import Sample, Timeline

    tl = Timeline(capacity=8)
    live = Sample(generation=10)
    tl.append(live)
    stale = [Sample(generation=g) for g in (5, 9, 10, 12)]
    tl.restore(stale)
    gens = [s.generation for s in tl.snapshot()]
    assert gens == [10, 12]  # only fresher-than-tail samples appended
    # round-trip through the checkpoint dict form
    s = Sample(generation=13)
    s.utilization = {"cpu": 0.5}
    s.hottest = [("n0", {"cpu": 0.5, "memory": 0.1, "pods": 0.0})]
    d = Sample.from_dict(s.to_dict())
    assert d.generation == 13
    assert d.utilization["cpu"] == 0.5
    assert d.hottest[0][0] == "n0"


# ---------------------------------------------------------------------------
# crash recovery, end to end: SIGKILL mid-storm, restart on the same journal
# ---------------------------------------------------------------------------

_CHILD = """
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
from opensim_tpu.obs.capacity import CapacityEngine
from opensim_tpu.server.journal import Journal
from opensim_tpu.server.watch import RestWatchSource, WatchSupervisor
policy = {{"stale_s": 5.0, "resync_s": 0.0, "reconnects": 3, "backoff_s": 0.02}}
sup = WatchSupervisor(
    RestWatchSource({kc!r}, read_timeout_s=5.0), policy=policy,
    journal=Journal({jd!r}, policy={{"fsync": "always"}}),
)
sup.capacity = CapacityEngine()
assert sup.start(wait_s=30.0), "child twin failed to sync"
sup.capacity.sample()
sup._checkpoint_now("samples")
sup.journal.flush(timeout=10.0)
while True:
    time.sleep(0.05)
    sup.capacity.sample()
"""


def test_sigkill_mid_storm_restart_restores_bit_equal(tmp_path):
    """The ISSUE 11 acceptance run: a journaled twin in a real subprocess is
    SIGKILLed mid event-storm; a restart on the same journal restores from
    checkpoint + suffix, the resumed reflectors absorb the records the crash
    lost, and the twin lands bit-equal (content fingerprint) to a fresh full
    relist with the capacity timeline resuming monotonic generations."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    stub = StubApiServer(bookmark_interval_s=0.1).start()
    _seed(stub, pods=[_pod_dict("seed", phase="Running", node="n0")])
    kc = stub.kubeconfig(tmp_path)
    jd = str(tmp_path / "journal")
    child = subprocess.Popen(
        [sys.executable, "-c", _CHILD.format(repo=repo, kc=kc, jd=jd)],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        env=dict(os.environ, PYTHONPATH=repo), cwd=repo, text=True,
    )
    sup2 = None
    try:
        # synced when the bootstrap + post-sample checkpoints hit the disk
        _wait(
            lambda: child.poll() is not None
            or sum(1 for r in iter_records(jd) if r["t"] == "ck") >= 2,
            timeout=90.0, msg="child twin to sync and checkpoint",
        )
        if child.poll() is not None:
            raise AssertionError(f"child died early: {child.stderr.read()[-2000:]}")

        # storm; kill the child once a decent suffix is on disk (mid-storm)
        for i in range(40):
            stub.upsert("/api/v1/pods", _pod_dict(f"storm-{i}", cpu="150m"))
            if i == 30:
                _wait(
                    lambda: sum(1 for r in iter_records(jd) if r["t"] == "ev") >= 10,
                    msg="journal to absorb part of the storm",
                )
                child.kill()  # SIGKILL: no flush, no close, no goodbye
        child.wait(timeout=10)
        stub.delete("/api/v1/pods", "storm-2")  # churn the crash missed

        on_disk = sum(1 for r in iter_records(jd) if r["t"] == "ev")
        assert on_disk >= 10, "the crash should have left a replayable suffix"

        # restart on the same journal
        from opensim_tpu.obs.capacity import CapacityEngine

        jr2 = Journal(jd, policy={"fsync": "always"})
        sup2 = WatchSupervisor(RestWatchSource(kc, read_timeout_s=5.0), policy=FAST, journal=jr2)
        sup2.capacity = CapacityEngine()
        assert sup2.start(wait_s=20.0), "restart did not come up from the journal"
        lines = jr2.metrics_lines()
        assert any(
            'simon_journal_recoveries_total{outcome="restored"} 1' in ln for ln in lines
        ), "restart must recover from the journal, not relist cold"

        # the resumed reflectors deliver everything the crash lost
        want = {f"storm-{i}" for i in range(40)} - {"storm-2"} | {"seed"}
        _wait(
            lambda: {p.metadata.name for p in sup2.twin.materialize().pods} == want,
            timeout=20.0, msg="restored twin to absorb the missed suffix",
        )
        fresh, _rvs = _cluster_via_rest(kc, None)
        assert sup2.twin.fingerprint() == fingerprint_cluster(fresh)

        # capacity timeline: restored checkpoint samples + fresh post-restart
        # samples form one strictly monotonic generation sequence
        sup2.capacity.sample()
        gens = [s.generation for s in sup2.capacity.timeline.snapshot()]
        assert gens == sorted(set(gens)), f"timeline generations not monotonic: {gens}"
        assert len(gens) >= 1
    finally:
        if child.poll() is None:
            child.kill()
        if sup2 is not None:
            sup2.stop()
            sup2.journal.close()
        stub.stop()


# ---------------------------------------------------------------------------
# graceful shutdown (SIGTERM drains, flushes, exits 0)
# ---------------------------------------------------------------------------


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_sigterm_drains_flushes_journal_and_exits_zero(tmp_path):
    """``simon server`` on SIGTERM: stop admitting, drain, stop reflectors,
    flush + fsync the journal, exit 0 — and a restart on the same journal
    recovers instead of relisting."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    stub = StubApiServer(bookmark_interval_s=0.1).start()
    _seed(stub, pods=[_pod_dict("seed", phase="Running", node="n0")])
    kc = stub.kubeconfig(tmp_path)
    jd = str(tmp_path / "journal")
    port = _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "opensim_tpu", "server",
            "--kubeconfig", kc, "--watch", "on", "--journal", jd,
            "--port", str(port), "--backend", "cpu",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=dict(
            os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu",
            OPENSIM_JOURNAL_FSYNC="always",
        ),
        cwd=repo, text=True,
    )
    try:
        def up():
            if proc.poll() is not None:
                raise AssertionError(f"server died early: {proc.stdout.read()[-2000:]}")
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=2
                ) as resp:
                    return resp.status == 200
            except OSError:
                return False

        _wait(up, timeout=120.0, msg="journaled server to come up")
        stub.upsert("/api/v1/pods", _pod_dict("while-up"))
        _wait(
            lambda: any(
                r["t"] == "ev" and r["o"]["metadata"]["name"] == "while-up"
                for r in iter_records(jd)
            ),
            msg="event to reach the journal",
        )
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0, f"SIGTERM exit code {proc.returncode}: {out[-2000:]}"
        assert "shutdown complete" in out
        # the on-disk history recovers cleanly after the clean stop
        state = Journal(jd, readonly=True).recover()
        assert state is not None and state.outcome == "restored"
        names = {p["metadata"]["name"] for p in state.stores.get("pods", [])}
        assert "while-up" in names
    finally:
        if proc.poll() is None:
            proc.kill()
        stub.stop()


def test_admission_stop_sheds_shutting_down_with_metric():
    """Graceful drain semantics at the unit level: queued tickets shed a
    typed 503 whose reason is ``shutting_down`` (not ``queue_full``), and
    the shed counter carries the same reason label."""
    from opensim_tpu.obs.metrics import RECORDER
    from opensim_tpu.server import admission as admission_mod

    ctrl = admission_mod.AdmissionController(
        solo_fn=lambda t: None, batch_fn=lambda ts: None, window_s=5.0
    )
    t1 = admission_mod.Ticket(kind="deploy", payload={})
    ctrl.submit(t1)
    ctrl.stop()
    with pytest.raises(admission_mod.QueueFull) as ei:
        ctrl.wait(t1)
    assert ei.value.reason == "shutting_down"
    with pytest.raises(admission_mod.QueueFull) as ei2:
        ctrl.submit(admission_mod.Ticket(kind="deploy", payload={}))
    assert ei2.value.reason == "shutting_down"
    with RECORDER.lock:
        lines = ctrl.shed.render_lines()
    assert any('reason="shutting_down"' in ln and ln.rstrip().endswith(" 2") for ln in lines)
