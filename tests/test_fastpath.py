"""The Pallas megakernel must produce IDENTICAL placements to the XLA scan
on its supported feature subset. Runs in interpret mode on CPU;
OPENSIM_TEST_BACKEND=tpu compiles the kernel through Mosaic for real."""

import os

import numpy as np
import pytest

from opensim_tpu.engine import fastpath
from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
from opensim_tpu.engine.simulator import AppResource, prepare

pytestmark = pytest.mark.slow  # nightly tier: full megakernel-vs-XLA parity matrix (README: test tiering)
from opensim_tpu.models import ResourceTypes, fixtures as fx

_INTERPRET = os.environ.get("OPENSIM_TEST_BACKEND") != "tpu"


@pytest.fixture(autouse=True)
def _enable_interpret_fastpath(monkeypatch):
    """applicable() requires a TPU backend unless interpret mode is forced
    (the rest of the suite intentionally exercises the XLA path on CPU)."""
    monkeypatch.setenv("OPENSIM_FASTPATH", "interpret")


def _prep(n_nodes=16, with_spread=True, with_zone=True, replicas=64):
    cluster = ResourceTypes()
    for i in range(n_nodes):
        labels = {}
        if with_zone and i % 4 != 3:  # some nodes lack the zone label
            labels["topology.kubernetes.io/zone"] = f"z{i % 3}"
        cluster.nodes.append(
            fx.make_fake_node(f"n{i:03d}", "16", "32Gi", "110", fx.with_labels(labels))
        )
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("plain", replicas, "500m", "1Gi"))
    app.deployments.append(fx.make_fake_deployment("tiny", replicas // 2, "100m", "128Mi"))
    if with_spread:
        app.deployments.append(
            fx.make_fake_deployment(
                "spread",
                replicas // 2,
                "250m",
                "512Mi",
                fx.with_topology_spread(
                    [
                        {
                            "maxSkew": 2,
                            "topologyKey": "kubernetes.io/hostname",
                            "whenUnsatisfiable": "DoNotSchedule",
                            "labelSelector": {"matchLabels": {"app": "spread"}},
                        },
                        {
                            "maxSkew": 3,
                            "topologyKey": "topology.kubernetes.io/zone",
                            "whenUnsatisfiable": "ScheduleAnyway",
                            "labelSelector": {"matchLabels": {"app": "spread"}},
                        },
                    ]
                ),
            )
        )
    # overload so some pods genuinely fail
    app.deployments.append(fx.make_fake_deployment("fat", 8, "8", "16Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert prep is not None
    return prep


def _xla_chosen(prep):
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    return np.asarray(out.chosen)[:P], np.asarray(out.final_state.used)


def test_fastpath_applicable():
    prep = _prep()
    assert fastpath.applicable(prep)


def test_fastpath_rejects_unsupported():
    from opensim_tpu.engine.schedconfig import DEFAULT_CONFIG

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "1", "1Gi"))

    # non-default scheduler config stays on the XLA path
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert fastpath.applicable(prep)
    assert not fastpath.applicable(prep, DEFAULT_CONFIG._replace(w_least=3.0))

    # two non-hostname topology keys are in scope; a third is not
    def spread_app(keys):
        rt = ResourceTypes()
        rt.pods.append(
            fx.make_fake_pod(
                "spread", "1", "1Gi",
                fx.with_topology_spread(
                    [
                        {"maxSkew": 1, "topologyKey": k, "whenUnsatisfiable": "ScheduleAnyway",
                         "labelSelector": {"matchLabels": {"x": "y"}}}
                        for k in keys
                    ]
                ),
            )
        )
        return rt

    prep2 = prepare(
        cluster,
        [AppResource("a", spread_app(["topology.kubernetes.io/zone", "topology.kubernetes.io/region"]))],
        node_pad=128,
    )
    assert fastpath.applicable(prep2)
    # up to four non-hostname keys are in scope; a fifth is not
    prep2b = prepare(
        cluster,
        [AppResource("a", spread_app([
            "topology.kubernetes.io/zone", "topology.kubernetes.io/region",
            "topology.rack", "topology.row",
        ]))],
        node_pad=128,
    )
    assert fastpath.applicable(prep2b)
    prep2c = prepare(
        cluster,
        [AppResource("a", spread_app([
            "topology.kubernetes.io/zone", "topology.kubernetes.io/region",
            "topology.rack", "topology.row", "topology.cell",
        ]))],
        node_pad=128,
    )
    assert not fastpath.applicable(prep2c)

    # non-128-multiple node padding is padded at marshalling time, not rejected
    prep3 = prepare(cluster, [AppResource("a", app)], node_pad=8)
    assert fastpath.applicable(prep3)


def test_fastpath_matches_xla_gpu():
    """GPU device packing through the megakernel must match the XLA scan:
    placements, device assignments (gpu_take), and final device state."""
    cluster = ResourceTypes()
    for i in range(6):
        cluster.nodes.append(
            fx.make_fake_node(
                f"g{i}", "64", "128Gi", "110",
                fx.with_allocatable({"alibabacloud.com/gpu-mem": "32Gi", "alibabacloud.com/gpu-count": "4"}),
            )
        )
    app = ResourceTypes()
    for j, (mem, cnt, n) in enumerate([("4Gi", "1", 10), ("10Gi", "1", 6), ("6Gi", "2", 4), ("8Gi", "3", 3)]):
        for k in range(n):
            app.pods.append(
                fx.make_fake_pod(
                    f"gpu-{j}-{k}", "1", "1Gi",
                    fx.with_annotations({"alibabacloud.com/gpu-mem": mem, "alibabacloud.com/gpu-count": cnt}),
                )
            )
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert prep.features.gpu
    assert fastpath.applicable(prep)
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    want_chosen = np.asarray(out.chosen)[:P]
    want_take = np.asarray(out.gpu_take)[:P]
    want_gpu = np.asarray(out.final_state.gpu_free)
    got_chosen, got_used, _sf, got_take, got_gpu, _vg, _dv = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    np.testing.assert_array_equal(got_chosen, want_chosen)
    np.testing.assert_allclose(got_take, want_take, rtol=1e-6)
    np.testing.assert_allclose(got_gpu, want_gpu, rtol=1e-6)


def test_fastpath_matches_xla_ports_na_tt():
    """Host ports, preferred node affinity, and PreferNoSchedule scoring
    through the megakernel must match the XLA scan."""
    cluster = ResourceTypes()
    for i in range(6):
        opts = [fx.with_labels({"disk": "ssd" if i % 2 else "hdd"})]
        if i < 2:
            opts.append(fx.with_taints([{"key": "soft", "value": "x", "effect": "PreferNoSchedule"}]))
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "16", "32Gi", "110", *opts))
    app = ResourceTypes()
    for k in range(5):
        app.pods.append(fx.make_fake_pod(f"web-{k}", "500m", "1Gi", fx.with_host_ports([8080])))
    app.deployments.append(
        fx.make_fake_deployment(
            "pref", 6, "250m", "512Mi",
            fx.with_affinity(
                {
                    "nodeAffinity": {
                        "preferredDuringSchedulingIgnoredDuringExecution": [
                            {"weight": 50, "preference": {"matchExpressions": [{"key": "disk", "operator": "In", "values": ["ssd"]}]}}
                        ]
                    }
                }
            ),
        )
    )
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert prep.features.ports and prep.features.pref_node_affinity and prep.features.prefer_taints
    assert fastpath.applicable(prep)
    P = len(prep.ordered)
    want_chosen, want_used = _xla_chosen(prep)
    got_chosen, got_used, *_rest = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    np.testing.assert_array_equal(got_chosen, want_chosen)
    np.testing.assert_allclose(got_used, want_used, rtol=1e-5)


def test_fastpath_matches_xla_local_storage():
    """Open-local VG + exclusive-device packing through the megakernel must
    match the XLA scan: placements, VG free, and device occupancy."""
    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(
            fx.make_fake_node(
                f"s{i}", "32", "64Gi", "110",
                fx.with_node_local_storage(
                    vgs=[
                        {"name": "pool0", "capacity": 100 * 1024**3},
                        {"name": "pool1", "capacity": 50 * 1024**3},
                    ],
                    devices=[
                        {"device": "/dev/vdb", "capacity": 80 * 1024**3, "mediaType": "ssd"},
                        {"device": "/dev/vdd", "capacity": 30 * 1024**3, "mediaType": "ssd"},
                        {"device": "/dev/vdc", "capacity": 120 * 1024**3, "mediaType": "hdd"},
                    ],
                ),
            )
        )
    app = ResourceTypes()
    sts = fx.make_fake_stateful_set("db", 6, "500m", "1Gi")
    sts.volume_claim_templates = [
        {"metadata": {"name": "data"}, "spec": {"storageClassName": "open-local-lvm", "resources": {"requests": {"storage": "30Gi"}}}},
    ]
    app.stateful_sets.append(sts)
    sts2 = fx.make_fake_stateful_set("disk", 3, "250m", "512Mi")
    sts2.volume_claim_templates = [
        {"metadata": {"name": "d"}, "spec": {"storageClassName": "open-local-device-hdd", "resources": {"requests": {"storage": "100Gi"}}}},
    ]
    app.stateful_sets.append(sts2)
    # mixed-size device volumes of one media: per-volume matching, not
    # count × max-size (common.go:290-349)
    sts3 = fx.make_fake_stateful_set("mixed", 2, "250m", "512Mi")
    sts3.volume_claim_templates = [
        {"metadata": {"name": "small"}, "spec": {"storageClassName": "open-local-device-ssd", "resources": {"requests": {"storage": "10Gi"}}}},
        {"metadata": {"name": "big"}, "spec": {"storageClassName": "open-local-device-ssd", "resources": {"requests": {"storage": "60Gi"}}}},
    ]
    app.stateful_sets.append(sts3)
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert prep.features.local
    assert fastpath.applicable(prep)
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(prep.ec, prep.st0, t, v, f, features=prep.features)
    want_chosen = np.asarray(out.chosen)[:P]
    got_chosen, got_used, _sf, _gt, _gf, got_vg, got_dev = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    np.testing.assert_array_equal(got_chosen, want_chosen)
    np.testing.assert_allclose(got_vg, np.asarray(out.final_state.vg_free), rtol=1e-6)
    np.testing.assert_allclose(got_dev, np.asarray(out.final_state.dev_free), rtol=1e-6)


@pytest.mark.parametrize("with_spread,with_zone", [(False, False), (True, True), (True, False)])
def test_fastpath_matches_xla(with_spread, with_zone):
    prep = _prep(with_spread=with_spread, with_zone=with_zone)
    assert fastpath.applicable(prep)
    P = len(prep.ordered)
    want_chosen, want_used = _xla_chosen(prep)
    got_chosen, got_used, *_rest = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    mismatches = np.nonzero(want_chosen != got_chosen)[0]
    assert mismatches.size == 0, (
        f"{mismatches.size} placement mismatches, first at {mismatches[:5]}: "
        f"xla={want_chosen[mismatches[:5]]} pallas={got_chosen[mismatches[:5]]}"
    )
    np.testing.assert_allclose(got_used, want_used, rtol=1e-5)


def test_fastpath_matches_xla_interpod():
    """Inter-pod affinity / anti-affinity / preferred terms through the
    megakernel must match the XLA scan exactly."""
    cluster = ResourceTypes()
    for i in range(12):
        # every 4th node lacks the zone label: k8s gives label-less nodes no
        # topology contribution, and both paths must agree on that
        labels = {} if i % 4 == 3 else {"topology.kubernetes.io/zone": f"z{i % 3}"}
        cluster.nodes.append(fx.make_fake_node(f"n{i:02d}", "16", "32Gi", "110", fx.with_labels(labels)))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("anchor", "100m", "128Mi", fx.with_labels({"role": "anchor"})))
    app.pods.append(
        fx.make_fake_pod("anchor-b", "100m", "128Mi", fx.with_labels({"role": "anchor", "grade": "gold"}))
    )
    app.deployments.append(
        fx.make_fake_deployment(
            "followers", 6, "200m", "256Mi",
            fx.with_affinity(
                {
                    "podAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"labelSelector": {"matchLabels": {"role": "anchor"}}, "topologyKey": "topology.kubernetes.io/zone"}
                        ]
                    }
                }
            ),
        )
    )
    # multi-term required affinity: only a pod matching BOTH terms counts
    # (filtering.go:113-127) — anchor-b satisfies, anchor alone must not
    app.deployments.append(
        fx.make_fake_deployment(
            "picky", 4, "200m", "256Mi",
            fx.with_affinity(
                {
                    "podAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"labelSelector": {"matchLabels": {"role": "anchor"}}, "topologyKey": "topology.kubernetes.io/zone"},
                            {"labelSelector": {"matchLabels": {"grade": "gold"}}, "topologyKey": "kubernetes.io/hostname"},
                        ]
                    }
                }
            ),
        )
    )
    app.stateful_sets.append(
        fx.make_fake_stateful_set(
            "spread-db", 8, "500m", "1Gi",
            fx.with_affinity(
                {
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"labelSelector": {"matchLabels": {"app": "spread-db"}}, "topologyKey": "kubernetes.io/hostname"}
                        ],
                        "preferredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "weight": 100,
                                "podAffinityTerm": {
                                    "labelSelector": {"matchLabels": {"app": "spread-db"}},
                                    "topologyKey": "topology.kubernetes.io/zone",
                                },
                            }
                        ],
                    }
                }
            ),
        )
    )
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert prep.features.interpod and prep.features.prefg
    assert fastpath.applicable(prep)
    P = len(prep.ordered)
    want_chosen, want_used = _xla_chosen(prep)
    got_chosen, got_used, *_rest = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    mism = np.nonzero(want_chosen != got_chosen)[0]
    assert mism.size == 0, (
        f"{mism.size} mismatches at {mism[:5]}: xla={want_chosen[mism[:5]]} fast={got_chosen[mism[:5]]}"
    )
    np.testing.assert_allclose(got_used, want_used, rtol=1e-5)


def test_fastpath_two_zone_keys_matches_xla():
    """Workloads spanning hostname + TWO zone-like topology keys (zone and
    region) run on the megakernel's stacked per-key count blocks; placements
    must match the XLA scan exactly across spread and inter-pod terms on
    either key."""
    cluster = ResourceTypes()
    for i in range(12):
        labels = {}
        if i % 4 != 3:  # some nodes lack the zone label
            labels["topology.kubernetes.io/zone"] = f"z{i % 3}"
        if i % 5 != 4:  # and some lack the region label — independently
            labels["topology.kubernetes.io/region"] = f"r{i % 2}"
        cluster.nodes.append(fx.make_fake_node(f"n{i:02d}", "16", "32Gi", "110", fx.with_labels(labels)))
    app = ResourceTypes()
    app.deployments.append(
        fx.make_fake_deployment(
            "zonal", 9, "250m", "512Mi",
            fx.with_topology_spread(
                [
                    {"maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
                     "whenUnsatisfiable": "DoNotSchedule",
                     "labelSelector": {"matchLabels": {"app": "zonal"}}},
                    {"maxSkew": 2, "topologyKey": "topology.kubernetes.io/region",
                     "whenUnsatisfiable": "ScheduleAnyway",
                     "labelSelector": {"matchLabels": {"app": "zonal"}}},
                ]
            ),
        )
    )
    app.pods.append(fx.make_fake_pod("anchor", "100m", "128Mi", fx.with_labels({"role": "anchor"})))
    app.deployments.append(
        fx.make_fake_deployment(
            "regional", 4, "200m", "256Mi",
            fx.with_affinity(
                {
                    "podAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"labelSelector": {"matchLabels": {"role": "anchor"}},
                             "topologyKey": "topology.kubernetes.io/region"}
                        ]
                    }
                }
            ),
        )
    )
    app.stateful_sets.append(
        fx.make_fake_stateful_set(
            "iso", 4, "500m", "1Gi",
            fx.with_affinity(
                {
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"labelSelector": {"matchLabels": {"app": "iso"}},
                             "topologyKey": "topology.kubernetes.io/zone"}
                        ],
                        "preferredDuringSchedulingIgnoredDuringExecution": [
                            {"weight": 50, "podAffinityTerm": {
                                "labelSelector": {"matchLabels": {"app": "iso"}},
                                "topologyKey": "topology.kubernetes.io/region"}},
                        ],
                    }
                }
            ),
        )
    )
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert fastpath.applicable(prep)
    P = len(prep.ordered)
    want_chosen, want_used = _xla_chosen(prep)
    got_chosen, got_used, *_rest = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    mism = np.nonzero(want_chosen != got_chosen)[0]
    assert mism.size == 0, (
        f"{mism.size} mismatches at {mism[:5]}: xla={want_chosen[mism[:5]]} fast={got_chosen[mism[:5]]}"
    )
    np.testing.assert_allclose(got_used, want_used, rtol=1e-5)


def test_fastpath_big_u_matches_xla():
    """>512 distinct templates switch the kernel to big-U mode (template
    tables in HBM, per-step DMA); placements must still match the XLA scan
    exactly — including inter-pod and port features whose tables move."""
    cluster = ResourceTypes()
    for i in range(8):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "64", "128Gi", "110"))
    app = ResourceTypes()
    # 515 unique specs (distinct cpu requests) → >512 templates
    for i in range(515):
        app.pods.append(fx.make_fake_pod(f"p{i:04d}", f"{100 + i}m", "64Mi"))
    app.pods.append(fx.make_fake_pod("anchor", "100m", "64Mi", fx.with_labels({"role": "anchor"})))
    app.deployments.append(
        fx.make_fake_deployment(
            "followers", 4, "200m", "128Mi",
            fx.with_affinity(
                {
                    "podAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"labelSelector": {"matchLabels": {"role": "anchor"}}, "topologyKey": "kubernetes.io/hostname"}
                        ]
                    }
                }
            ),
        )
    )
    app.pods.append(
        fx.make_fake_pod("gateway", "100m", "64Mi", fx.with_host_ports([31080]))
    )
    app.pods.append(
        fx.make_fake_pod("gateway-2", "100m", "64Mi", fx.with_host_ports([31080]))
    )
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert int(prep.ec_np.req.shape[0]) > 512
    # the VMEM-aware heuristic keeps this small-N case resident, engaging
    # only when the resident tables would crowd VMEM (headline-N cases)
    assert not fastpath.use_big_u(int(prep.ec_np.req.shape[0]), 128)
    assert fastpath.use_big_u(513, 5120) and fastpath.use_big_u(1000, 5120)
    assert fastpath.applicable(prep)
    P = len(prep.ordered)
    want_chosen, want_used = _xla_chosen(prep)
    # force big_u to exercise the HBM template-table DMA path at small N
    got_chosen, got_used, *_rest = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET, big_u=True
    )
    mism = np.nonzero(want_chosen != got_chosen)[0]
    assert mism.size == 0, (
        f"{mism.size} mismatches at {mism[:5]}: xla={want_chosen[mism[:5]]} fast={got_chosen[mism[:5]]}"
    )
    np.testing.assert_allclose(got_used, want_used, rtol=1e-5)


def test_fastpath_failure_reasons_without_rescan(monkeypatch):
    """Unschedulable pods through the fast path get kube-style reasons from
    a per-template evaluation against the final carry — NOT a second full
    XLA scan — and the reasons match the XLA path exactly (exactness holds
    because nothing binds after the first failure)."""
    from opensim_tpu.engine import fastpath as fp
    from opensim_tpu.engine import simulator as sim_mod
    from opensim_tpu.engine.simulator import simulate

    monkeypatch.setenv("OPENSIM_FASTPATH", "interpret")
    scans = []
    orig_scan = sim_mod.schedule_pods

    def spy_scan(*args, **kwargs):
        scans.append(1)
        return orig_scan(*args, **kwargs)

    monkeypatch.setattr(sim_mod, "schedule_pods", spy_scan)

    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    # 12 × 3cpu on 4 × 8cpu nodes: 8 bind (2/node), 4 fail on cpu
    app.deployments.append(fx.make_fake_deployment("web", 12, "3", "1Gi"))
    res = simulate(cluster, [AppResource("a", app)])
    assert not scans, "fast path fell back to a full XLA re-scan"
    assert len(res.unscheduled_pods) == 4
    fast_reasons = sorted(u.reason for u in res.unscheduled_pods)

    monkeypatch.delenv("OPENSIM_FASTPATH")
    res2 = simulate(cluster, [AppResource("a", app)])
    assert sorted(u.reason for u in res2.unscheduled_pods) == fast_reasons
    assert "Insufficient cpu" in fast_reasons[0]


def test_fastpath_engages_through_simulate(monkeypatch):
    """End-to-end: simulate() must take the fast branch (interpret mode on
    CPU via OPENSIM_FASTPATH) and produce the same placements as the XLA
    path."""
    from opensim_tpu.engine import fastpath as fp
    from opensim_tpu.engine.simulator import simulate

    monkeypatch.setenv("OPENSIM_FASTPATH", "interpret")
    calls = []
    orig = fp.schedule

    def spy(*args, **kwargs):
        calls.append(1)
        return orig(*args, **kwargs)

    monkeypatch.setattr(fp, "schedule", spy)

    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("web", 8, "1", "1Gi"))
    res = simulate(cluster, [AppResource("a", app)])
    assert calls, "fast path did not engage"
    assert not res.unscheduled_pods
    per_node = sorted(len(ns.pods) for ns in res.node_status)
    assert sum(per_node) == 8

    # same workload through the XLA path gives identical placement (pod
    # names get fresh suffixes per expansion; compare in name order)
    monkeypatch.delenv("OPENSIM_FASTPATH")
    res2 = simulate(cluster, [AppResource("a", app)])

    def placement_seq(r):
        pairs = [(p.metadata.name, ns.node.metadata.name) for ns in r.node_status for p in ns.pods]
        return [node for _name, node in sorted(pairs)]

    assert placement_seq(res) == placement_seq(res2)


def test_fastpath_forced_pods():
    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    cluster.pods.append(fx.make_fake_pod("pinned", "1", "1Gi", fx.with_node_name("n2")))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("d", 6, "1", "1Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert fastpath.applicable(prep)
    P = len(prep.ordered)
    want_chosen, want_used = _xla_chosen(prep)
    got_chosen, got_used, *_rest = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    np.testing.assert_array_equal(got_chosen, want_chosen)
    np.testing.assert_allclose(got_used, want_used, rtol=1e-5)


def test_fastpath_matches_xla_prefer_avoid():
    """NodePreferAvoidPods (w=10000 raw 0/100 table) through the megakernel
    must match the XLA scan — including the avoided node winning when it is
    the only feasible one."""
    import json

    cluster = ResourceTypes()
    avoid = json.dumps(
        {"preferAvoidPods": [
            {"podSignature": {"podController": {"kind": "ReplicaSet", "uid": "rs-avoid"}}}
        ]}
    )
    for i in range(6):
        opts = [fx.with_labels({"disk": "ssd" if i < 4 else "hdd"})]
        if i < 4:  # the four best-fit nodes all carry the avoid annotation
            opts.append(
                fx.with_annotations({"scheduler.alpha.kubernetes.io/preferAvoidPods": avoid})
            )
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi", "110", *opts))
    app = ResourceTypes()
    for k in range(12):
        p = fx.make_fake_pod(f"av-{k}", "1", "1Gi")
        from opensim_tpu.models.objects import OwnerReference

        p.metadata.owner_references = [
            OwnerReference(kind="ReplicaSet", name="rs-avoid", uid="rs-avoid", controller=True)
        ]
        app.pods.append(p)
    for k in range(4):
        app.pods.append(fx.make_fake_pod(f"plain-{k}", "1", "1Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert prep.features.prefer_avoid, "fixture must trigger the avoid table"
    assert fastpath.applicable(prep)
    want_chosen, want_used = _xla_chosen(prep)
    P = len(prep.ordered)
    got_chosen, got_used, *_ = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    np.testing.assert_array_equal(got_chosen, want_chosen)
    np.testing.assert_allclose(got_used, want_used, rtol=1e-6)


def test_fastpath_matches_xla_unpadded_nodes():
    """node_pad=8 encodings (N not a multiple of 128) are lane-padded at
    marshalling time; placements and final state must still match the XLA
    scan bit-for-bit."""
    cluster = ResourceTypes()
    for i in range(21):  # pads to 24 under node_pad=8
        labels = {"topology.kubernetes.io/zone": f"z{i % 3}"} if i % 5 else {}
        cluster.nodes.append(
            fx.make_fake_node(f"n{i:03d}", "16", "32Gi", "110", fx.with_labels(labels))
        )
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("plain", 48, "500m", "1Gi"))
    app.deployments.append(
        fx.make_fake_deployment(
            "spread", 24, "250m", "512Mi",
            fx.with_topology_spread(
                [{"maxSkew": 2, "topologyKey": "topology.kubernetes.io/zone",
                  "whenUnsatisfiable": "DoNotSchedule",
                  "labelSelector": {"matchLabels": {"app": "spread"}}}]
            ),
        )
    )
    app.deployments.append(fx.make_fake_deployment("fat", 6, "9", "20Gi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=8)
    assert int(prep.ec_np.node_valid.shape[0]) % 128 != 0
    assert fastpath.applicable(prep)
    want_chosen, want_used = _xla_chosen(prep)
    P = len(prep.ordered)
    got_chosen, got_used, *_ = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    np.testing.assert_array_equal(got_chosen, want_chosen)
    np.testing.assert_allclose(got_used, want_used, rtol=1e-6)


def test_fastpath_matches_xla_four_zone_keys():
    """Four non-hostname topology keys (the new cap) must match the XLA
    scan, mixing hard and soft constraints across keys."""
    keys = ["topology.kubernetes.io/zone", "topology.kubernetes.io/region",
            "topology.rack", "topology.row"]
    cluster = ResourceTypes()
    for i in range(16):
        labels = {
            keys[0]: f"z{i % 3}", keys[1]: f"r{i % 2}",
            keys[2]: f"k{i % 4}", keys[3]: f"w{i % 5}",
        }
        if i % 7 == 6:
            labels.pop(keys[2])  # some nodes lack a key
        cluster.nodes.append(
            fx.make_fake_node(f"n{i:03d}", "16", "32Gi", "110", fx.with_labels(labels))
        )
    app = ResourceTypes()
    constraints = [
        {"maxSkew": 2, "topologyKey": keys[0], "whenUnsatisfiable": "DoNotSchedule",
         "labelSelector": {"matchLabels": {"app": "multi"}}},
        {"maxSkew": 1, "topologyKey": keys[1], "whenUnsatisfiable": "ScheduleAnyway",
         "labelSelector": {"matchLabels": {"app": "multi"}}},
        {"maxSkew": 3, "topologyKey": keys[2], "whenUnsatisfiable": "ScheduleAnyway",
         "labelSelector": {"matchLabels": {"app": "multi"}}},
        {"maxSkew": 2, "topologyKey": keys[3], "whenUnsatisfiable": "DoNotSchedule",
         "labelSelector": {"matchLabels": {"app": "multi"}}},
    ]
    app.deployments.append(
        fx.make_fake_deployment("multi", 40, "500m", "1Gi",
                                fx.with_topology_spread(constraints))
    )
    app.deployments.append(fx.make_fake_deployment("plain", 24, "250m", "512Mi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=128)
    assert fastpath.applicable(prep)
    want_chosen, want_used = _xla_chosen(prep)
    P = len(prep.ordered)
    got_chosen, got_used, *_ = fastpath.schedule(
        prep, prep.tmpl_ids, np.ones(P, bool), prep.forced, interpret=_INTERPRET
    )
    np.testing.assert_array_equal(got_chosen, want_chosen)
    np.testing.assert_allclose(got_used, want_used, rtol=1e-6)


def test_megakernel_failure_degrades_to_xla(monkeypatch, caplog):
    """A Mosaic compile failure (constructs that pass interpret mode can
    fail the real compiler) must degrade to the XLA scan with a warning,
    never kill the simulation — placements are identical either way."""
    import logging

    from opensim_tpu.engine import fastpath
    from opensim_tpu.engine.simulator import AppResource, simulate
    from opensim_tpu.models import ResourceTypes, fixtures as fx

    import jax

    # simulate a REAL-hardware failure: tpu backend, no interpret mode (in
    # interpret/test mode the exception re-raises so CI can't silently
    # validate the fallback engine instead of the kernel)
    monkeypatch.delenv("OPENSIM_FASTPATH", raising=False)
    monkeypatch.setenv("OPENSIM_DISABLE_NATIVE", "1")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def boom(*a, **k):
        raise RuntimeError("Mosaic lowering failed (simulated)")

    monkeypatch.setattr(fastpath, "schedule", boom)
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))
    with caplog.at_level(logging.WARNING, logger="opensim_tpu"):
        res = simulate(cluster, [AppResource("a", app)], node_pad=8)
    assert not res.unscheduled_pods
    assert any("falling back to a slower engine" in r.message for r in caplog.records)
