"""Two-process jax.distributed test: a scenario sweep sharded across a
DCN-spanning mesh (2 processes × 4 virtual CPU devices) must agree with the
single-process result — the backing for PARITY.md §2.3's multi-host claim.
Each child joins via multihost.initialize()'s env-var path."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow  # nightly tier (README: test tiering)

_CHILD = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from opensim_tpu.parallel import multihost
from opensim_tpu.parallel.scenarios import sweep
from opensim_tpu.engine.simulator import AppResource, prepare
from opensim_tpu.models import ResourceTypes, fixtures as fx

assert multihost.initialize(), "JAX_COORDINATOR env not picked up"
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

cluster = ResourceTypes()
for i in range(6):
    cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
app = ResourceTypes()
app.deployments.append(fx.make_fake_deployment("web", 10, "2", "2Gi"))
prep = prepare(cluster, [AppResource("a", app)], node_pad=8)

N = int(np.asarray(prep.ec_np.node_valid).shape[0])
P = len(prep.ordered)
# scenarios: first k nodes enabled, k = 1..8 (padded count)
S = 8
node_masks = np.zeros((S, N), bool)
for s in range(S):
    node_masks[s, : min(s + 1, 6)] = True
pod_masks = np.ones((S, P), bool)

res = sweep(
    prep.ec, prep.st0, prep.tmpl_ids, prep.forced,
    node_masks, pod_masks,
    mesh=multihost.global_mesh(), features=prep.features,
)
if jax.process_index() == 0:
    print("UNSCHED:" + ",".join(str(int(x)) for x in np.asarray(res.unscheduled)))
"""


@pytest.mark.skipif(os.environ.get("OPENSIM_SKIP_MULTIHOST") == "1", reason="opt-out")
def test_two_process_dcn_sweep(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=REPO + (
                os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
            ),
        )
        env.pop("JAX_PLATFORMS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process sweep timed out")
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out[-3000:]}"
    line = [ln for ln in outs[0].splitlines() if ln.startswith("UNSCHED:")]
    assert line, outs[0][-2000:]
    got = [int(x) for x in line[0][len("UNSCHED:"):].split(",")]

    # closed-form reference for the same scenarios: 10 pods × 2cpu on
    # k × 8cpu nodes (k capped at the 6 real nodes) → min(4k, 10) bind
    want = [10 - min(4 * min(s + 1, 6), 10) for s in range(8)]
    assert got == want, (got, want)


_PLANNER_CHILD = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")

from opensim_tpu.parallel import multihost

# the planner calls initialize() itself, but asserting here catches env rot
assert multihost.initialize(), "JAX_COORDINATOR env not picked up"
assert jax.process_count() == 2, jax.process_count()

import yaml
base = sys.argv[1]  # per-process scratch dir (same content both sides)
os.makedirs(f"{base}/cluster", exist_ok=True)
os.makedirs(f"{base}/app", exist_ok=True)
os.makedirs(f"{base}/newnode", exist_ok=True)

def node(name):
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name, "labels": {"kubernetes.io/hostname": name}},
        "status": {"allocatable": {"cpu": "8", "memory": "32Gi", "pods": "110"},
                   "capacity": {"cpu": "8", "memory": "32Gi", "pods": "110"}},
    }

for i in range(2):
    open(f"{base}/cluster/n{i}.yaml", "w").write(yaml.safe_dump(node(f"n{i}")))
open(f"{base}/newnode/tmpl.yaml", "w").write(yaml.safe_dump(node("tmpl")))
open(f"{base}/app/d.yaml", "w").write(yaml.safe_dump({
    "apiVersion": "apps/v1", "kind": "Deployment",
    "metadata": {"name": "web"},
    "spec": {"replicas": 20, "selector": {"matchLabels": {"app": "web"}},
             "template": {"metadata": {"labels": {"app": "web"}},
                          "spec": {"containers": [{"name": "c", "image": "x",
                                   "resources": {"requests": {"cpu": "2", "memory": "2Gi"}}}]}}},
}))
open(f"{base}/config.yaml", "w").write(yaml.safe_dump({
    "apiVersion": "simon/v1alpha1", "kind": "Config",
    "metadata": {"name": "mh"},
    "spec": {"cluster": {"customConfig": f"{base}/cluster"},
             "appList": [{"name": "a", "path": f"{base}/app"}],
             "newNode": f"{base}/newnode"},
}))

from opensim_tpu.planner.apply import Applier, Options

rc = Applier(Options(simon_config=f"{base}/config.yaml",
                     output_file=f"{base}/report.txt",
                     max_new_nodes=16)).run()
assert rc == 0, rc
report = open(f"{base}/report.txt").read()
if jax.process_index() == 0:
    added = [ln for ln in report.splitlines() if "new node(s)" in ln]
    print("ADDED:" + (added[0] if added else "none"))
"""


@pytest.mark.skipif(os.environ.get("OPENSIM_SKIP_MULTIHOST") == "1", reason="opt-out")
def test_two_process_capacity_planner(tmp_path):
    """End-to-end `simon apply` capacity sweep across a 2-process DCN mesh:
    the candidate-count scenarios shard over both hosts and the minimal
    feasible count matches the closed form (40 cpu needed, 16 present,
    8 cpu per new node -> 3 new nodes)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "planner_child.py"
    script.write_text(_PLANNER_CHILD)
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=REPO + (
                os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
            ),
        )
        env.pop("JAX_PLATFORMS", None)
        scratch = tmp_path / f"p{pid}"
        scratch.mkdir()
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script), str(scratch)],
                env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process planner timed out")
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out[-3000:]}"
    line = [ln for ln in outs[0].splitlines() if ln.startswith("ADDED:")]
    assert line, outs[0][-2000:]
    assert "added 3 new node(s)" in line[0], line[0]
