"""Two-process jax.distributed test: a scenario sweep sharded across a
DCN-spanning mesh (2 processes × 4 virtual CPU devices) must agree with the
single-process result — the backing for PARITY.md §2.3's multi-host claim.
Each child joins via multihost.initialize()'s env-var path."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from opensim_tpu.parallel import multihost
from opensim_tpu.parallel.scenarios import sweep
from opensim_tpu.engine.simulator import AppResource, prepare
from opensim_tpu.models import ResourceTypes, fixtures as fx

assert multihost.initialize(), "JAX_COORDINATOR env not picked up"
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

cluster = ResourceTypes()
for i in range(6):
    cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
app = ResourceTypes()
app.deployments.append(fx.make_fake_deployment("web", 10, "2", "2Gi"))
prep = prepare(cluster, [AppResource("a", app)], node_pad=8)

N = int(np.asarray(prep.ec_np.node_valid).shape[0])
P = len(prep.ordered)
# scenarios: first k nodes enabled, k = 1..8 (padded count)
S = 8
node_masks = np.zeros((S, N), bool)
for s in range(S):
    node_masks[s, : min(s + 1, 6)] = True
pod_masks = np.ones((S, P), bool)

res = sweep(
    prep.ec, prep.st0, prep.tmpl_ids, prep.forced,
    node_masks, pod_masks,
    mesh=multihost.global_mesh(), features=prep.features,
)
if jax.process_index() == 0:
    print("UNSCHED:" + ",".join(str(int(x)) for x in np.asarray(res.unscheduled)))
"""


@pytest.mark.skipif(os.environ.get("OPENSIM_SKIP_MULTIHOST") == "1", reason="opt-out")
def test_two_process_dcn_sweep(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=REPO + (
                os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
            ),
        )
        env.pop("JAX_PLATFORMS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process sweep timed out")
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out[-3000:]}"
    line = [ln for ln in outs[0].splitlines() if ln.startswith("UNSCHED:")]
    assert line, outs[0][-2000:]
    got = [int(x) for x in line[0][len("UNSCHED:"):].split(",")]

    # closed-form reference for the same scenarios: 10 pods × 2cpu on
    # k × 8cpu nodes (k capped at the 6 real nodes) → min(4k, 10) bind
    want = [10 - min(4 * min(s + 1, 6), 10) for s in range(8)]
    assert got == want, (got, want)
