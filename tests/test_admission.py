"""Concurrent serving core (ISSUE 8): admission queue, cross-request
batching, worker pool, load-shedding.

The load-bearing gate is bit-identity: N parallel clients (mixed
deploy/scale, batched and unbatched paths, twin events mid-storm) must
produce placements identical to the same requests run serially through the
seed's proven path. Pod names embed a process-global expansion counter
(NOTES invariant) and are not stable across re-expansions, so identity is
compared on suffix-normalized names — everything else (node assignment,
counts, reasons) must match exactly.
"""

import re
import threading
import time

import pytest

from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.models.objects import OwnerReference
from opensim_tpu.obs.metrics import RECORDER
from opensim_tpu.obs.recorder import FLIGHT_RECORDER
from opensim_tpu.resilience.deadline import Deadline


@pytest.fixture(autouse=True)
def _clean_recorders():
    FLIGHT_RECORDER.clear()
    RECORDER.reset()
    yield
    FLIGHT_RECORDER.clear()
    RECORDER.reset()


def _cluster():
    rt = ResourceTypes()
    for i in range(6):
        rt.nodes.append(
            fx.make_fake_node(
                f"n{i:03d}", "16", "64Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 3}"}),
            )
        )
    rt.pods.append(fx.make_fake_pod("pinned", "100m", "128Mi", fx.with_node_name("n000")))
    # a deployment-owned snapshot pod so scale-apps has something to remove
    owned = fx.make_fake_pod("web-1", "500m", "1Gi", fx.with_node_name("n001"))
    owned.metadata.owner_references = [
        OwnerReference(kind="Deployment", name="web", uid="u1", controller=True)
    ]
    rt.pods.append(owned)
    return rt


def _requests():
    """Mixed request set: distinct deploys, a scale, and an unschedulable
    workload (reason rendering must survive batching)."""
    reqs = []
    for i in range(5):
        reqs.append(
            ("deploy", {"deployments": [
                fx.make_fake_deployment(f"app-{i}", 2 + i % 3, "500m", "1Gi").raw
            ]})
        )
    reqs.append(
        ("scale", {"deployments": [fx.make_fake_deployment("web", 3, "200m", "256Mi").raw]})
    )
    reqs.append(
        ("deploy", {"deployments": [fx.make_fake_deployment("huge", 1, "640", "1Gi").raw]})
    )
    return reqs


def _workloads_of(payloads) -> list:
    """Deployment names in a request set — the stable identity pod names
    are canonicalized onto."""
    names = []
    for p in payloads:
        for d in p.get("deployments") or []:
            names.append(d["metadata"]["name"])
    return names


def _canon_pod(ref: str, workloads) -> str:
    """``ns/name`` → ``ns/<owning workload>``: expansion counters make the
    raw names unstable across re-expansions (NOTES invariant), but every
    expanded pod name starts with its workload's name. Longest prefix wins
    (``app-1`` vs ``app-10``)."""
    ns, _, name = ref.partition("/")
    best = ""
    for w in workloads:
        if name.startswith(w) and len(w) > len(best):
            best = w
    return f"{ns}/{best or name}"


def _canon(body: dict, workloads):
    return (
        sorted(
            (_canon_pod(u["pod"], workloads), u["reason"])
            for u in body["unscheduledPods"]
        ),
        sorted(
            (e["node"], sorted(_canon_pod(p, workloads) for p in e["pods"]))
            for e in body["nodeStatus"]
        ),
    )


def _make_server(window_s=None, pipelined=True, **kwargs):
    from opensim_tpu.server import admission as admission_mod
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster(), **kwargs)
    if window_s is not None and server.admission is not None:
        server.admission.stop()
        stage_fns = (
            dict(
                prep_fn=server._batch_prep, dispatch_fn=server._batch_dispatch,
                decode_fn=server._batch_decode,
            )
            if pipelined
            else {}
        )
        server.admission = admission_mod.AdmissionController(
            solo_fn=server._admitted_solo, batch_fn=server._admitted_batch,
            window_s=window_s, **stage_fns,
        )
    return server


# ---------------------------------------------------------------------------
# bit-identity: batched concurrent == serial
# ---------------------------------------------------------------------------


def test_concurrent_batched_bitidentical_to_serial():
    """N parallel mixed deploy/scale requests through the admission queue
    (wide window → guaranteed coalescing) produce placements identical to
    the same requests run serially on the single-flight path."""
    reqs = _requests()
    wl = _workloads_of([p for _, p in reqs])

    serial = _make_server(admission=False)
    expected = []
    for kind, payload in reqs:
        code, body = (
            serial.deploy_apps if kind == "deploy" else serial.scale_apps
        )(payload)
        assert code == 200, body
        expected.append(_canon(body, wl))

    batched = _make_server(window_s=0.25)
    results = [None] * len(reqs)

    def run(i, kind, payload):
        results[i] = (
            batched.deploy_apps if kind == "deploy" else batched.scale_apps
        )(payload)

    threads = [
        threading.Thread(target=run, args=(i, k, p))
        for i, (k, p) in enumerate(reqs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        for i, (code, body) in enumerate(results):
            assert code == 200, (i, body)
            assert _canon(body, wl) == expected[i], f"request {i} diverged"
        # the run actually batched (a test that silently went solo would
        # gate nothing)
        assert batched.admission.batches_total >= 1
    finally:
        batched.close()
        serial.close()


def test_unbatchable_newnodes_rides_worker_pool_alongside_batch():
    """newnodes requests (randomized fake node names) must not join a
    batch — they run solo through the pool, concurrently with a batch, and
    still answer exactly."""
    server = _make_server(window_s=0.25)
    # the nn workload REQUIRES the fake node (simon/new-node marker), so
    # its placement deterministically proves the newnodes path ran
    new_node_payload = {
        "deployments": [
            fx.make_fake_deployment(
                "nn", 2, "500m", "1Gi",
                fx.with_node_selector({"simon/new-node": ""}),
            ).raw
        ],
        "newnodes": [fx.make_fake_node("template", "8", "16Gi").raw],
    }
    plain = {"deployments": [fx.make_fake_deployment("plain-a", 2, "250m", "512Mi").raw]}
    plain2 = {"deployments": [fx.make_fake_deployment("plain-b", 2, "250m", "512Mi").raw]}
    results = [None] * 3

    def run(i, payload):
        results[i] = server.deploy_apps(payload)

    threads = [
        threading.Thread(target=run, args=(i, p))
        for i, p in enumerate((new_node_payload, plain, plain2))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        for code, body in results:
            assert code == 200, body
        # the newnodes request bound its pods onto fresh simon-* fake nodes
        nn_nodes = {e["node"] for e in results[0][1]["nodeStatus"]}
        assert any(n.startswith("simon-") for n in nn_nodes)
    finally:
        server.close()


def test_batched_and_solo_paths_expose_queue_metrics():
    server = _make_server(window_s=0.15)
    try:
        payloads = [
            {"deployments": [fx.make_fake_deployment(f"m-{i}", 2, "250m", "256Mi").raw]}
            for i in range(4)
        ]
        results = [None] * 4

        def run(i):
            results[i] = server.deploy_apps(payloads[i])

        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(code == 200 for code, _ in results)
        from opensim_tpu.server.rest import METRICS

        text = METRICS.render(prep_cache=server.prep_cache, admission=server.admission)
        for needle in (
            "# TYPE simon_admission_queue_depth gauge",
            "# TYPE simon_batch_size histogram",
            "# TYPE simon_shed_total counter",
            "# TYPE simon_queue_wait_seconds histogram",
            "simon_batches_total",
        ):
            assert needle in text, needle
        # real time-in-queue recorded for every admitted request
        m = re.search(r"simon_queue_wait_seconds_count (\d+)", text)
        assert m and int(m.group(1)) >= 4
        m = re.search(r"simon_batch_size_count (\d+)", text)
        assert m and int(m.group(1)) >= 1
    finally:
        server.close()


# ---------------------------------------------------------------------------
# shed / deadline paths: typed errors, never partial results
# ---------------------------------------------------------------------------


def test_queue_full_sheds_typed_503_with_retry_after():
    from opensim_tpu.server import admission as admission_mod
    from opensim_tpu.server import rest as rest_mod
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster())
    server.admission.stop()
    server.admission = admission_mod.AdmissionController(
        solo_fn=server._admitted_solo, batch_fn=server._admitted_batch,
        window_s=0.6, bound=1,
    )
    try:
        first = {}

        def hold():
            first["resp"] = server.deploy_apps(
                {"deployments": [fx.make_fake_deployment("hold", 2, "250m", "256Mi").raw]}
            )

        t = threading.Thread(target=hold)
        t.start()
        # the first ticket sits in the 0.6s coalescing window; the queue
        # (bound 1) is full, so this request must shed NOW with a typed 503
        time.sleep(0.1)
        code, body = server.deploy_apps(
            {"deployments": [fx.make_fake_deployment("shed-me", 2, "250m", "256Mi").raw]}
        )
        assert code == 503
        assert body["reason"] == "queue_full" and body["retryable"] is True
        assert "Retry-After" in rest_mod.response_extra_headers()
        t.join()
        assert first["resp"][0] == 200
        text = rest_mod.METRICS.render(admission=server.admission)
        assert 'simon_shed_total{reason="queue_full"} 1' in text
        # the shed's latency is real elapsed time, not a fake 0.0 —
        # observed while the request waited, so the series must exist with
        # the shed status
        assert 'status="shed"' in text
    finally:
        server.close()


def test_deadline_expiring_in_queue_sheds_504_queue_phase():
    from opensim_tpu.server import admission as admission_mod
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster())
    server.admission.stop()
    server.admission = admission_mod.AdmissionController(
        solo_fn=server._admitted_solo, batch_fn=server._admitted_batch,
        window_s=0.5,
    )
    try:
        # alive at admission, dead by the time the window closes
        dl = Deadline.after(0.1)
        code, body = server.deploy_apps(
            {"deployments": [fx.make_fake_deployment("late", 2, "250m", "256Mi").raw]},
            deadline=dl,
        )
        assert code == 504
        assert body["phase"] == "queue"
        from opensim_tpu.server.rest import METRICS

        text = METRICS.render(admission=server.admission)
        assert 'simon_shed_total{reason="deadline"} 1' in text
    finally:
        server.close()


def test_pre_expired_deadline_keeps_legacy_phase_contract():
    """A deadline already expired at admission executes and 504s at the
    first phase boundary (snapshot/prepare/...), exactly like the seed —
    the resilience tests' contract must survive the queue."""
    from opensim_tpu.server.rest import SimonServer

    server = SimonServer(base_cluster=_cluster())
    try:
        dl = Deadline.after(1e-9)
        time.sleep(0.01)
        code, body = server.deploy_apps(
            {"deployments": [fx.make_fake_deployment("dead", 2, "250m", "256Mi").raw]},
            deadline=dl,
        )
        assert code == 504
        assert body["phase"] in ("snapshot", "prepare", "encode", "schedule", "decode")
    finally:
        server.close()


def test_shutdown_resolves_queued_tickets_with_typed_shed():
    from opensim_tpu.server import admission as admission_mod

    resolved = []

    def never_solo(t):
        pass  # dispatcher never reaches it: stop() races first

    ctrl = admission_mod.AdmissionController(
        solo_fn=never_solo, batch_fn=lambda ts: None, window_s=5.0
    )
    t1 = admission_mod.Ticket(kind="deploy", payload={})
    ctrl.submit(t1)
    ctrl.stop()
    with pytest.raises(admission_mod.QueueFull):
        ctrl.wait(t1)
    # post-stop submission sheds immediately
    with pytest.raises(admission_mod.QueueFull):
        ctrl.submit(admission_mod.Ticket(kind="deploy", payload={}))
    assert not resolved


# ---------------------------------------------------------------------------
# twin events mid-storm
# ---------------------------------------------------------------------------


def test_batched_requests_with_twin_events_mid_storm(tmp_path):
    """Concurrent batched requests while the live twin absorbs watch events
    still answer 200 with placements consistent with a fresh serial run of
    the post-storm state."""
    from opensim_tpu.server import rest
    from opensim_tpu.server.stubapi import StubApiServer
    from opensim_tpu.server.watch import RestWatchSource, WatchSupervisor

    stub = StubApiServer(bookmark_interval_s=0.1).start()
    stub.seed(
        "/api/v1/nodes",
        [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(4)],
    )
    stub.seed("/api/v1/pods", [
        {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "seed-0", "namespace": "default"},
            "spec": {"nodeName": "n0", "containers": [
                {"name": "c", "resources": {"requests": {"cpu": "100m"}}}
            ]},
            "status": {"phase": "Running"},
        }
    ])
    for p in (
        "/apis/apps/v1/daemonsets", "/apis/policy/v1/poddisruptionbudgets",
        "/api/v1/services", "/apis/storage.k8s.io/v1/storageclasses",
        "/api/v1/persistentvolumeclaims", "/api/v1/configmaps",
    ):
        stub.seed(p, [])
    kc = stub.kubeconfig(str(tmp_path))
    policy = {"stale_s": 5.0, "resync_s": 0.0, "reconnects": 3, "backoff_s": 0.02}
    sup = WatchSupervisor(RestWatchSource(kc, read_timeout_s=5.0), policy=policy)
    server = rest.SimonServer(kubeconfig=kc, watch=sup)
    sup.prep_cache = server.prep_cache
    assert sup.start(wait_s=15.0)
    try:
        results = [None] * 6

        def run(i):
            results[i] = server.deploy_apps(
                {"deployments": [
                    fx.make_fake_deployment(f"storm-{i}", 2, "500m", "1Gi").raw
                ]}
            )

        threads = [threading.Thread(target=run, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        # a twin event lands mid-storm
        stub.upsert("/api/v1/pods", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "mid-storm", "namespace": "default"},
            "spec": {"nodeName": "n1", "containers": [
                {"name": "c", "resources": {"requests": {"cpu": "200m"}}}
            ]},
            "status": {"phase": "Running"},
        })
        for t in threads:
            t.join()
        for code, body in results:
            assert code == 200, body
            # typed shape, never partial: every response carries both keys
            assert set(body) >= {"unscheduledPods", "nodeStatus"}
        # quiesce, then a fresh request equals a polling server's answer on
        # the SAME post-storm cluster (the twin_smoke convergence contract)
        gen = sup.twin.generation

        def _settled():
            return "mid-storm" in {
                p.metadata.name for p in sup.twin.materialize().pods
            }

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not _settled():
            time.sleep(0.05)
        assert _settled()
        code, twin_body = server.deploy_apps(
            {"deployments": [fx.make_fake_deployment("after", 3, "500m", "1Gi").raw]}
        )
        assert code == 200
        polling = rest.SimonServer(kubeconfig=kc, admission=False)
        code2, poll_body = polling.deploy_apps(
            {"deployments": [fx.make_fake_deployment("after", 3, "500m", "1Gi").raw]}
        )
        assert code2 == 200
        assert _canon(twin_body, ["after"]) == _canon(poll_body, ["after"])
    finally:
        server.close()
        sup.stop()
        stub.stop()


# ---------------------------------------------------------------------------
# review regressions: poisoned batches, pre-expired riders, process pool
# ---------------------------------------------------------------------------


def test_malformed_payload_fails_only_its_own_batch_rider():
    """One undecodable payload in a coalesced batch 500s that request
    alone; every other rider still answers 200 (never a poisoned group)."""
    server = _make_server(window_s=0.25)
    try:
        payloads = [
            {"deployments": [fx.make_fake_deployment(f"ok-{i}", 2, "250m", "256Mi").raw]}
            for i in range(3)
        ] + [{"deployments": ["garbage - not an object"]}]
        results = [None] * len(payloads)

        def run(i):
            results[i] = server.deploy_apps(payloads[i])

        threads = [threading.Thread(target=run, args=(i,)) for i in range(len(payloads))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for code, body in results[:3]:
            assert code == 200, body
        code, body = results[3]
        assert code == 500 and "error" in body and "type" in body
    finally:
        server.close()


def test_pre_expired_rider_takes_solo_path_even_in_a_storm():
    """A pre-expired deadline must 504 with a legacy phase even when it
    arrives alongside batchable traffic (the batch installs no deadline
    scope, so dead tickets are routed solo at dispatch)."""
    server = _make_server(window_s=0.25)
    try:
        results = {}

        def ok_run(i):
            results[i] = server.deploy_apps(
                {"deployments": [fx.make_fake_deployment(f"live-{i}", 2, "250m", "256Mi").raw]}
            )

        def dead_run():
            dl = Deadline.after(1e-9)
            time.sleep(0.01)
            results["dead"] = server.deploy_apps(
                {"deployments": [fx.make_fake_deployment("dead", 2, "250m", "256Mi").raw]},
                deadline=dl,
            )

        threads = [threading.Thread(target=ok_run, args=(i,)) for i in range(3)]
        threads.append(threading.Thread(target=dead_run))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(3):
            assert results[i][0] == 200
        code, body = results["dead"]
        assert code == 504
        assert body["phase"] in ("snapshot", "prepare", "encode", "schedule", "decode")
    finally:
        server.close()


def test_process_pool_runs_unpicklable_tasks_on_threads():
    """OPENSIM_WORKERS_MODE=process must never hang admission work: bound
    methods / Tickets (threading primitives) are unpicklable, so they run
    on the thread fallback — picklable tasks may genuinely fork."""
    from opensim_tpu.server.pool import WorkerPool

    pool = WorkerPool(workers=2, mode="process")
    try:
        ev = threading.Event()

        class Holder:
            def poke(self, e):
                e.set()
                return "threaded"

        # unpicklable (bound method + Event): must execute via threads and
        # actually set OUR event (a forked child could never do that)
        fut = pool.submit(Holder().poke, ev)
        assert fut.result(timeout=10) == "threaded"
        assert ev.is_set()
        if pool.mode == "process":
            assert pool.submit(len, (1, 2, 3)).result(timeout=30) == 3
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# batched explain + vmapped-batch deadline shedding (ISSUE 15 satellites)
# ---------------------------------------------------------------------------


def test_explain_requests_batch_and_match_solo_audit():
    """``?explain=1`` deploys ride the shared batch (count_all fail rows
    over the shared derive) and the audit — per-pod explanations and the
    per-filter reject totals — is bit-identical to the solo explain path.
    The unschedulable workload is the load-bearing case: its reason
    breakdown comes entirely from the audited fail rows."""
    payloads = [
        {"deployments": [fx.make_fake_deployment(f"xp-{i}", 2, "500m", "1Gi").raw]}
        for i in range(3)
    ]
    payloads.append(
        {"deployments": [fx.make_fake_deployment("xhuge", 1, "640", "1Gi").raw]}
    )
    wl = _workloads_of(payloads)

    serial = _make_server(admission=False)
    expected = []
    for p in payloads:
        code, body = serial.deploy_apps(p, explain=True)
        assert code == 200, body
        expected.append(body)

    batched = _make_server(window_s=0.25)
    results = [None] * len(payloads)

    def run(i):
        results[i] = batched.deploy_apps(payloads[i], explain=True)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(len(payloads))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        for i, (code, body) in enumerate(results):
            assert code == 200, (i, body)
            assert _canon(body, wl) == _canon(expected[i], wl)
            # the audit payloads match too: reject totals and, for the
            # unschedulable rider, the per-pod explanation breakdown
            assert body.get("filterRejects") == expected[i].get("filterRejects"), i
            got_expl = {
                _canon_pod(u["pod"], wl): u.get("explanation")
                for u in body["unscheduledPods"]
            }
            want_expl = {
                _canon_pod(u["pod"], wl): u.get("explanation")
                for u in expected[i]["unscheduledPods"]
            }
            def _strip(e):
                if not isinstance(e, dict):
                    return e
                return {k: v for k, v in e.items() if k != "pod"}
            assert {k: _strip(v) for k, v in got_expl.items()} == {
                k: _strip(v) for k, v in want_expl.items()
            }, i
        assert batched.admission.batches_total >= 1, "explain traffic never batched"
    finally:
        batched.close()
        serial.close()


def test_xla_batch_sheds_expired_riders_before_dispatch(monkeypatch):
    """Pre-dispatch deadline shedding on the vmapped path: a rider whose
    deadline is already dead gets the typed 504 (phase=schedule) and its
    lane is masked out of the compiled dispatch; the live riders' results
    are untouched (their masks never included the shed rider's pods)."""
    from opensim_tpu.engine import prepcache, reqbatch
    from opensim_tpu.engine.simulator import AppResource, prepare
    from opensim_tpu.resilience.deadline import DeadlineExceeded

    monkeypatch.setenv("OPENSIM_BATCH_ENGINE", "xla")
    cluster = _cluster()
    base = prepcache.CacheEntry("b|base", prepare(cluster, []))
    apps = []
    for i in range(3):
        rt = ResourceTypes()
        rt.add(fx.make_fake_deployment(f"dl-{i}", 2, "500m", "1Gi"))
        apps.append(AppResource("deploy", rt))

    def run(deadlines):
        with base.lock:
            base.restore()
            derived, slices = prepcache.derive_with_app_slices(
                base.prep, cluster, apps, base_entry=base
            )
            items = [
                reqbatch.BatchItem(
                    cluster=cluster, apps=[apps[s]],
                    lo=slices[s][0], hi=slices[s][1],
                    deadline=deadlines[s],
                )
                for s in range(len(apps))
            ]
            try:
                return reqbatch.run_request_batch(derived, items)
            finally:
                base.restore()

    clean = run([None, None, None])
    dead = Deadline.after(0.0)
    assert dead.expired()
    mixed = run([None, dead, None])

    assert isinstance(mixed[1], DeadlineExceeded)
    assert mixed[1].phase == "schedule"
    for s in (0, 2):
        assert not isinstance(mixed[s], BaseException)
        want = sorted(
            (ns.node.metadata.name, len(ns.pods))
            for ns in clean[s].node_status if ns.pods
        )
        got = sorted(
            (ns.node.metadata.name, len(ns.pods))
            for ns in mixed[s].node_status if ns.pods
        )
        assert want == got, f"live rider {s} perturbed by the shed rider"


# ---------------------------------------------------------------------------
# pipelined admission + priority lanes (ISSUE 16)
# ---------------------------------------------------------------------------


def _storm(server, reqs):
    results = [None] * len(reqs)

    def run(i, kind, payload):
        results[i] = (
            server.deploy_apps if kind == "deploy" else server.scale_apps
        )(payload)

    threads = [
        threading.Thread(target=run, args=(i, k, p))
        for i, (k, p) in enumerate(reqs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def test_pipelined_matches_nonpipelined_under_mixed_storm():
    """The tentpole gate: the same mixed deploy/scale storm through the
    staged prep/dispatch/decode pipeline and through the serial inline
    batch path produces identical placements — and the pipeline
    demonstrably engaged (all three stage aggregates recorded)."""
    reqs = _requests()
    wl = _workloads_of([p for _, p in reqs])
    serial = _make_server(window_s=0.25, pipelined=False)
    piped = _make_server(window_s=0.25)
    assert piped.admission.pipelined and not serial.admission.pipelined
    try:
        want = _storm(serial, reqs)
        got = _storm(piped, reqs)
        for i in range(len(reqs)):
            assert want[i][0] == 200, (i, want[i][1])
            assert got[i][0] == 200, (i, got[i][1])
            assert _canon(got[i][1], wl) == _canon(want[i][1], wl), (
                f"request {i} diverged between pipelined and serial"
            )
        snap = piped.admission.pipeline_snapshot()
        assert snap["enabled"] and snap["batches"] >= 1
        for stage in ("prep", "dispatch", "decode"):
            assert snap["stages"].get(stage, {}).get("count", 0) >= 1, stage
        # pipeline + lane telemetry families are live on /metrics
        from opensim_tpu.server.rest import METRICS

        text = METRICS.render(admission=piped.admission)
        for needle in (
            "# TYPE simon_pipeline_stage_seconds histogram",
            "# TYPE simon_pipeline_prep_overlap_seconds_total counter",
            "# TYPE simon_pipeline_overlapped_batches_total counter",
            "# TYPE simon_lane_depth gauge",
            "# TYPE simon_lane_admitted_total counter",
            "# TYPE simon_lane_shed_total counter",
            "# TYPE simon_lane_starvation_promotions_total counter",
            'simon_pipeline_stage_seconds_count{stage="prep"}',
        ):
            assert needle in text, needle
    finally:
        piped.close()
        serial.close()


def test_generation_swap_mid_prep_retries_once_bitidentical():
    """A stale fingerprint surfacing at the prep stage (the cache.stale
    fault point — what a twin generation swap mid-prep looks like to the
    pipeline) retries exactly once INSIDE prep; the storm still answers
    bit-identically to the serial single-flight path."""
    from opensim_tpu.resilience import faults
    from opensim_tpu.server.rest import METRICS

    reqs = _requests()
    wl = _workloads_of([p for _, p in reqs])
    serial = _make_server(admission=False)
    expected = []
    for kind, payload in reqs:
        code, body = (
            serial.deploy_apps if kind == "deploy" else serial.scale_apps
        )(payload)
        assert code == 200, body
        expected.append(_canon(body, wl))

    piped = _make_server(window_s=0.25)
    retries0 = METRICS.stale_prep_retries
    faults.inject("cache.stale", count=1, exc="stale")
    try:
        results = _storm(piped, reqs)
        for i, (code, body) in enumerate(results):
            assert code == 200, (i, body)
            assert _canon(body, wl) == expected[i], f"request {i} diverged"
        assert METRICS.stale_prep_retries - retries0 >= 1
        assert piped.admission.batches_total >= 1
    finally:
        faults.clear_faults()
        piped.close()
        serial.close()


def _lane_ticket(name, reps, explain=False):
    from opensim_tpu.server import admission as admission_mod

    return admission_mod.Ticket(
        kind="deploy",
        payload={
            "deployments": [
                fx.make_fake_deployment(name, reps, "100m", "128Mi").raw
            ]
        },
        explain=explain,
    )


def _lane_controller(batch_fn, window_s=0.4, **kwargs):
    from opensim_tpu.server import admission as admission_mod

    return admission_mod.AdmissionController(
        solo_fn=lambda t: t.resolve(result=None), batch_fn=batch_fn,
        window_s=window_s, **kwargs,
    )


def test_interactive_lane_overtakes_bulk_within_weight(monkeypatch):
    """Weighted pickup: small requests submitted AFTER large ones are
    still drained first (interactive lane wins up to the lane weight),
    while FIFO order is preserved within each lane."""
    monkeypatch.setenv("OPENSIM_LANE_STARVATION_S", "30")  # isolate the weight
    order = []
    done = threading.Event()

    def batch_fn(tickets):
        order.extend((t.lane, t.payload["deployments"][0]["metadata"]["name"]) for t in tickets)
        for t in tickets:
            t.resolve(result=None)
        done.set()

    ctrl = _lane_controller(batch_fn)
    try:
        tickets = [
            ctrl.submit(_lane_ticket("big-0", 50)),
            ctrl.submit(_lane_ticket("big-1", 50)),
            ctrl.submit(_lane_ticket("small-0", 1)),
            ctrl.submit(_lane_ticket("small-1", 1, explain=True)),
        ]
        assert done.wait(timeout=30)
        for t in tickets:
            ctrl.wait(t)
        assert order == [
            ("interactive", "small-0"), ("interactive", "small-1"),
            ("bulk", "big-0"), ("bulk", "big-1"),
        ]
        assert ctrl.lane_admitted == {"interactive": 2, "bulk": 2}
    finally:
        ctrl.stop()


def test_bulk_starvation_bound_promotes_past_weight(monkeypatch):
    """The starvation bound: a bulk head older than the bound is picked
    BEFORE waiting interactive requests regardless of lane weight, and the
    promotion is counted."""
    monkeypatch.setenv("OPENSIM_LANE_STARVATION_S", "0")
    order = []
    done = threading.Event()

    def batch_fn(tickets):
        order.extend(t.lane for t in tickets)
        for t in tickets:
            t.resolve(result=None)
        done.set()

    ctrl = _lane_controller(batch_fn, window_s=0.3)
    try:
        b = ctrl.submit(_lane_ticket("big", 50))
        i1 = ctrl.submit(_lane_ticket("small-0", 1))
        i2 = ctrl.submit(_lane_ticket("small-1", 1))
        assert done.wait(timeout=30)
        for t in (b, i1, i2):
            ctrl.wait(t)
        assert order[0] == "bulk", order
        assert ctrl.starvation_promotions >= 1
        from opensim_tpu.server.rest import METRICS

        text = METRICS.render(admission=ctrl)
        assert re.search(r"simon_lane_starvation_promotions_total [1-9]", text)
    finally:
        ctrl.stop()


def test_queue_full_shed_is_lane_attributed():
    """Sheds carry their lane: a bulk request shed at the bound lands in
    ``simon_lane_shed_total{lane="bulk",reason="queue_full"}`` alongside
    the existing un-laned ``simon_shed_total``."""
    from opensim_tpu.server import admission as admission_mod
    from opensim_tpu.server.rest import METRICS

    ctrl = _lane_controller(lambda ts: None, window_s=5.0, bound=1)
    try:
        held = ctrl.submit(_lane_ticket("held", 1))  # parks in the window
        with pytest.raises(admission_mod.QueueFull):
            ctrl.submit(_lane_ticket("shed-bulk", 50))
        text = METRICS.render(admission=ctrl)
        assert 'simon_lane_shed_total{lane="bulk",reason="queue_full"} 1' in text
        assert 'simon_shed_total{reason="queue_full"} 1' in text
        assert 'simon_lane_admitted_total{lane="interactive"} 1' in text
        assert held is not None
    finally:
        ctrl.stop()


def test_pipeline_off_knob_restores_serial_batch_path(monkeypatch):
    """OPENSIM_PIPELINE=off must construct a non-pipelined controller even
    when the staged executors are wired (the serial inline path is the
    fallback, and the storm still answers correctly)."""
    monkeypatch.setenv("OPENSIM_PIPELINE", "off")
    server = _make_server(window_s=0.2)
    try:
        assert server.admission.pipelined is False
        reqs = _requests()[:3]
        results = _storm(server, reqs)
        for code, body in results:
            assert code == 200, body
        assert server.admission.pipeline_snapshot()["enabled"] is False
    finally:
        server.close()
