"""OSL18xx array-contract engine: promotion-table parity with numpy/jax,
firing and precision of the off-policy/upcast/shape rules, and the
anchoring contract (creation site / promotion site / binding site)."""

import numpy as np
import pytest

from opensim_tpu.analysis import lint_source
from opensim_tpu.analysis.arrays import npname_to_tag, promote, promote_weak

ENC_PATH = "opensim_tpu/encoding/fixture_arrays.py"

_TAG_TO_NP = {
    "bool": np.bool_,
    "u8": np.uint8,
    "i32": np.int32,
    "i64": np.int64,
    "f32": np.float32,
    "f64": np.float64,
}


# -- promotion tables vs the real libraries --------------------------------


@pytest.mark.parametrize("a", sorted(_TAG_TO_NP))
@pytest.mark.parametrize("b", sorted(_TAG_TO_NP))
def test_numpy_promotion_table_matches_result_type(a, b):
    want = npname_to_tag(np.result_type(_TAG_TO_NP[a], _TAG_TO_NP[b]).name)
    assert promote(a, b, jax_sem=False) == want


@pytest.mark.parametrize("a", sorted(_TAG_TO_NP))
@pytest.mark.parametrize("b", sorted(_TAG_TO_NP))
def test_jax_promotion_table_matches_promote_types(a, b):
    jnp = pytest.importorskip("jax.numpy")
    want = npname_to_tag(np.dtype(jnp.promote_types(_TAG_TO_NP[a], _TAG_TO_NP[b])).name)
    assert promote(a, b, jax_sem=True) == want


def test_weak_scalar_promotion():
    # NEP-50: an int scalar never widens; a float scalar widens integer
    # arrays to the default float (f64 numpy, f32 jax) and leaves floats
    for tag in ("bool", "u8", "i32", "i64", "f32", "f64"):
        assert promote_weak(tag, "int", jax_sem=False) == tag
        assert promote_weak(tag, "int", jax_sem=True) == tag
    assert promote_weak("i32", "float", jax_sem=False) == "f64"
    assert promote_weak("i32", "float", jax_sem=True) == "f32"
    assert promote_weak("f32", "float", jax_sem=False) == "f32"
    assert promote_weak("f64", "float", jax_sem=True) == "f64"


# -- firing / precision / anchoring ----------------------------------------


def _codes(src, rules=("array-off-policy", "silent-upcast", "shape-contract")):
    return [(f.code, f.line) for f in lint_source(src, path=ENC_PATH, rules=rules)]


def test_off_policy_creation_fires_at_creation_site():
    src = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(n, r):\n"
        "    alloc = np.zeros((n, r))\n"  # line 4: f64 by default
        "    return EncodedCluster(alloc=alloc)\n"
    )
    assert _codes(src) == [("OSL1801", 4)]


def test_policy_dtype_is_clean():
    src = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.dtypes import FLOAT_DTYPE\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(n, r):\n"
        "    return EncodedCluster(alloc=np.zeros((n, r), dtype=FLOAT_DTYPE))\n"
    )
    assert _codes(src) == []


def test_off_policy_kernel_argument_fires():
    # np.arange defaults to i64; tmpl_ids is contracted INT_DTYPE (i32)
    src = (
        "import numpy as np\n"
        "from opensim_tpu.ops.kernels import schedule_pods\n"
        "def drive(ec, st0, p):\n"
        "    ids = np.arange(p)\n"  # line 4
        "    return schedule_pods(ec, st0, tmpl_ids=ids)\n"
    )
    assert _codes(src, rules=("array-off-policy",)) == [("OSL1801", 4)]


def test_silent_upcast_fires_at_promotion_site_interprocedurally():
    src = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.dtypes import FLOAT_DTYPE\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def mix(n, r):\n"
        "    a = np.zeros((n, r), dtype=FLOAT_DTYPE)\n"
        "    idx = np.arange(n)\n"
        "    return a * idx.reshape((n, 1))\n"  # line 7: f32 x i64 -> f64
        "def build(n, r):\n"
        "    return EncodedCluster(alloc=mix(n, r))\n"
    )
    findings = lint_source(src, path=ENC_PATH, rules=("silent-upcast",))
    assert [(f.code, f.line) for f in findings] == [("OSL1802", 7)]
    assert "f32 x i64 -> f64" in findings[0].message
    assert "EncodedCluster.alloc" in findings[0].message


def test_jax_semantics_do_not_flag_numpy_only_promotions():
    # under jax.numpy, i-array x f32-array stays f32: no upcast to report
    src = (
        "import jax.numpy as jnp\n"
        "from opensim_tpu.encoding.dtypes import FLOAT_DTYPE\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def mix(n, r):\n"
        "    a = jnp.zeros((n, r), dtype=FLOAT_DTYPE)\n"
        "    idx = jnp.arange(n)\n"
        "    return a * idx.reshape((n, 1))\n"
        "def build(n, r):\n"
        "    return EncodedCluster(alloc=mix(n, r))\n"
    )
    assert _codes(src, rules=("silent-upcast",)) == []


def test_rank_mismatch_fires_at_binding():
    src = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.dtypes import FLOAT_DTYPE\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(n):\n"
        "    alloc = np.zeros((n,), dtype=FLOAT_DTYPE)\n"
        "    return EncodedCluster(alloc=alloc)\n"  # line 6: rank 1 vs (N, R)
    )
    assert _codes(src, rules=("shape-contract",)) == [("OSL1803", 6)]


def test_axis_order_mismatch_fires():
    src = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.dtypes import FLOAT_DTYPE\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(n, r):\n"
        "    alloc = np.zeros((r, n), dtype=FLOAT_DTYPE)\n"
        "    return EncodedCluster(alloc=alloc)\n"  # (R, N) vs contract (N, R)
    )
    assert _codes(src, rules=("shape-contract",)) == [("OSL1803", 6)]


def test_matching_symbolic_axes_are_clean():
    src = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.dtypes import FLOAT_DTYPE\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(n, r):\n"
        "    alloc = np.zeros((n, r), dtype=FLOAT_DTYPE)\n"
        "    return EncodedCluster(alloc=alloc)\n"
    )
    assert _codes(src) == []


def test_unknown_dtype_and_shape_never_fire():
    # precision over recall: a raw parameter has no known dtype or rank
    src = (
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(some_array):\n"
        "    return EncodedCluster(alloc=some_array)\n"
    )
    assert _codes(src) == []


def test_scope_excludes_non_pipeline_files():
    # same defect under cli/: outside the arena pipeline scope, no finding
    src = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(n, r):\n"
        "    return EncodedCluster(alloc=np.zeros((n, r)))\n"
    )
    assert lint_source(src, path="opensim_tpu/cli/fixture_arrays.py",
                       rules=("array-off-policy",)) == []


def test_where_promotes_branches_and_fires_silent_upcast():
    # np.where(mask, f32, i64) promotes to f64 under numpy semantics
    src = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.dtypes import FLOAT_DTYPE\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(n, r, mask):\n"
        "    a = np.zeros((n, r), dtype=FLOAT_DTYPE)\n"
        "    b = np.arange(n).reshape((n, 1))\n"
        "    alloc = np.where(mask, a, b)\n"  # line 7: f32 x i64 -> f64
        "    return EncodedCluster(alloc=alloc)\n"
    )
    findings = lint_source(src, path=ENC_PATH, rules=("silent-upcast",))
    assert [(f.code, f.line) for f in findings] == [("OSL1802", 7)]
    assert "f32 x i64 -> f64" in findings[0].message


def test_frombuffer_view_tracks_through_chained_reshape():
    # frombuffer defaults to f64; the chained .reshape must not launder it
    src = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(buf, n, r):\n"
        "    alloc = np.frombuffer(buf).reshape((n, r))\n"  # line 4
        "    return EncodedCluster(alloc=alloc)\n"
    )
    assert _codes(src, rules=("array-off-policy",)) == [("OSL1801", 4)]
    # an explicit astype to the policy dtype sanctions the same chain
    clean = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.dtypes import FLOAT_DTYPE\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(buf, n, r):\n"
        "    alloc = np.frombuffer(buf).astype(FLOAT_DTYPE).reshape((n, r))\n"
        "    return EncodedCluster(alloc=alloc)\n"
    )
    assert _codes(clean) == []


def test_integer_index_drops_leading_axis():
    # big[(K, N, R)][0] -> (N, R): matches the alloc contract, stays clean
    src = (
        "import numpy as np\n"
        "from opensim_tpu.encoding.dtypes import FLOAT_DTYPE\n"
        "from opensim_tpu.encoding.state import EncodedCluster\n"
        "def build(k, n, r):\n"
        "    big = np.zeros((k, n, r), dtype=FLOAT_DTYPE)\n"
        "    return EncodedCluster(alloc=big[0])\n"
    )
    assert _codes(src, rules=("shape-contract",)) == []
    # without the index the rank-3 value violates the (N, R) contract
    fire = src.replace("big[0]", "big")
    assert _codes(fire, rules=("shape-contract",)) == [("OSL1803", 6)]
