"""Contract registry integrity: the (dtype, axes) declarations in
``encoding/dtypes.py`` must cover the arena structs key-for-key, resolve
to real policy constants, and — the ground truth — agree with the arrays
a real ``prepare()`` actually builds, field by field, dtype AND rank."""

import numpy as np
import pytest

from opensim_tpu.encoding import dtypes as D
from opensim_tpu.encoding.state import EncodedCluster, ScanState


def _policy(name):
    return np.dtype(getattr(D, name))


def test_arena_contract_keys_match_encoded_cluster_fields():
    assert set(D.ARENA_CONTRACTS) == set(EncodedCluster._fields)


def test_state_contract_keys_match_scan_state_fields():
    assert set(D.STATE_CONTRACTS) == set(ScanState._fields)


def test_every_contract_names_a_policy_constant():
    for table in (D.ARENA_CONTRACTS, D.STATE_CONTRACTS,
                  *D.KERNEL_ARG_CONTRACTS.values()):
        for fname, (policy, axes) in table.items():
            assert policy.endswith("_DTYPE") and hasattr(D, policy), (
                f"{fname}: contract names {policy!r}, not a policy constant")
            assert isinstance(axes, tuple), f"{fname}: axes must be a tuple"


def test_buffer_aliases_point_at_contracted_fields():
    for buf, fname in D.BUFFER_FIELD_ALIASES.items():
        assert fname in D.ARENA_CONTRACTS or fname in D.STATE_CONTRACTS, (
            f"alias {buf} -> {fname} names no contracted field")


@pytest.fixture(scope="module")
def prepared():
    from opensim_tpu.engine.simulator import AppResource, prepare
    from opensim_tpu.models import ResourceTypes, fixtures as fx

    rt = ResourceTypes()
    for i in range(8):
        rt.nodes.append(fx.make_fake_node(
            f"n{i:03d}", "16", "64Gi", "110",
            fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 3}"})))
    apps_rt = ResourceTypes()
    apps_rt.deployments.append(fx.make_fake_deployment("web", 4, "500m", "1Gi"))
    return prepare(rt, [AppResource(name="web", resources=apps_rt)])


def test_runtime_cluster_arrays_honor_arena_contracts(prepared):
    bad = []
    for fname, (policy, axes) in D.ARENA_CONTRACTS.items():
        arr = np.asarray(getattr(prepared.ec, fname))
        if arr.dtype != _policy(policy):
            bad.append(f"ec.{fname}: dtype {arr.dtype} != {policy}")
        if arr.ndim != len(axes):
            bad.append(f"ec.{fname}: rank {arr.ndim} != {axes}")
    assert not bad, "\n".join(bad)


def test_runtime_state_arrays_honor_state_contracts(prepared):
    bad = []
    for fname, (policy, axes) in D.STATE_CONTRACTS.items():
        arr = np.asarray(getattr(prepared.st0, fname))
        if arr.dtype != _policy(policy):
            bad.append(f"st0.{fname}: dtype {arr.dtype} != {policy}")
        if arr.ndim != len(axes):
            bad.append(f"st0.{fname}: rank {arr.ndim} != {axes}")
    assert not bad, "\n".join(bad)


def test_runtime_kernel_entry_arrays_honor_boundary_contracts(prepared):
    contracts = D.KERNEL_ARG_CONTRACTS["schedule_pods"]
    for name, attr in (("tmpl_ids", "tmpl_ids"), ("forced", "forced")):
        policy, axes = contracts[name]
        arr = np.asarray(getattr(prepared, attr))
        assert arr.dtype == _policy(policy), f"{name}: {arr.dtype} != {policy}"
        assert arr.ndim == len(axes), f"{name}: rank {arr.ndim} != {axes}"
