"""Capacity observatory (ISSUE 9, docs/observability.md "Watching cluster
capacity"): incremental per-node accounting vs a from-scratch bootstrap,
headroom probes bit-consistent with a fresh ``simulate``, report parity
between the JSON endpoint and the text renderer, the timeline ring, the
watch-apply histogram, in-flight batch deadline shedding, and OSL1101."""

import json
import threading
import urllib.request

import pytest

from opensim_tpu.engine.simulator import AppResource, prepare, simulate
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.obs.capacity import (
    CapacityEngine,
    WorkloadProfile,
    build_report,
    format_top,
    headroom_probe,
    headroom_profiles,
    snapshot_result,
)
from opensim_tpu.obs.metrics import RECORDER
from opensim_tpu.obs.timeline import Sample, Timeline


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    monkeypatch.delenv("OPENSIM_HEADROOM_PROFILES", raising=False)
    monkeypatch.delenv("OPENSIM_CAPACITY_TOPK", raising=False)
    monkeypatch.delenv("OPENSIM_BATCH_ENGINE", raising=False)
    RECORDER.reset()
    yield
    RECORDER.reset()


def _pod_dict(name, node="", cpu="500m", mem="1Gi", phase="Running", rv=None):
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": mem}}}
            ]
        },
        "status": {"phase": phase},
    }
    if node:
        d["spec"]["nodeName"] = node
    if rv is not None:
        d["metadata"]["resourceVersion"] = str(rv)
    return d


def _cluster(n_nodes=4, n_pods=6):
    rt = ResourceTypes()
    for i in range(n_nodes):
        rt.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    for i in range(n_pods):
        rt.pods.append(
            fx.make_fake_pod(f"p{i}", "500m", "1Gi", fx.with_node_name(f"n{i % n_nodes}"))
        )
    return rt


def _assert_engines_agree(a: CapacityEngine, b: CapacityEngine):
    sa, sb = a.sample(), b.sample()
    assert sa.nodes == sb.nodes
    assert sa.pods_bound == sb.pods_bound
    assert sa.pods_pending == sb.pods_pending
    for res in ("cpu", "memory", "pods"):
        assert sa.allocatable[res] == pytest.approx(sb.allocatable[res])
        assert sa.requested[res] == pytest.approx(sb.requested[res])
        assert sa.utilization[res] == pytest.approx(sb.utilization[res])
        assert sa.spread[res] == pytest.approx(sb.spread[res], abs=1e-9)
        assert sa.fragmentation[res] == pytest.approx(sb.fragmentation[res])
    assert [n for n, _ in sa.hottest] == [n for n, _ in sb.hottest]
    # the incrementally-maintained distribution equals the rebuilt one
    assert a._dist == b._dist
    assert a._n_util == b._n_util


# ---------------------------------------------------------------------------
# incremental accounting == from-scratch bootstrap
# ---------------------------------------------------------------------------


def test_event_fed_engine_matches_fresh_bootstrap():
    """Drive a storm of pod/node events through a real WatchSupervisor
    dispatch; the event-fed aggregates must equal a fresh O(cluster)
    bootstrap of the final twin state (the observatory's analogue of the
    twin's fingerprint-equality proof)."""
    from opensim_tpu.server.watch import WatchSupervisor

    policy = {"stale_s": 30.0, "resync_s": 0.0, "reconnects": 1, "backoff_s": 0.0}
    sup = WatchSupervisor(source=None, policy=policy)
    engine = CapacityEngine(topk=5)
    sup.capacity = engine
    # bootstrap: 3 nodes, 2 bound pods, 1 pending
    nodes = [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(3)]
    pods = [
        _pod_dict("a", node="n0", rv=1),
        _pod_dict("b", node="n1", cpu="2", mem="4Gi", rv=2),
        _pod_dict("pending", rv=3),
    ]
    sup.twin.rebase("nodes", nodes)
    sup.twin.rebase("pods", pods)
    sup._capacity_rebase()
    assert engine.event_fed

    rv = 10
    # storm: adds, a modify (rebind), deletes, a node add, a terminal pod
    sup.dispatch("pods", "ADDED", _pod_dict("c", node="n2", cpu="1", rv=rv))
    sup.dispatch("pods", "ADDED", _pod_dict("d", rv=rv + 1))  # pending
    sup.dispatch("pods", "MODIFIED", _pod_dict("pending", node="n1", rv=rv + 2))
    sup.dispatch("pods", "DELETED", _pod_dict("a", node="n0", rv=rv + 3))
    sup.dispatch("nodes", "ADDED", fx.make_fake_node("n3", "4", "8Gi").raw)
    sup.dispatch("pods", "MODIFIED", _pod_dict("b", node="n1", cpu="2", mem="4Gi", phase="Succeeded", rv=rv + 4))
    # duplicate delivery must be a no-op for the aggregates too
    sup.dispatch("pods", "ADDED", _pod_dict("c", node="n2", cpu="1", rv=rv))

    fresh = CapacityEngine(topk=5)
    fresh.bootstrap(sup.twin.materialize(), sup.twin.generation)
    assert engine.generation == sup.twin.generation
    _assert_engines_agree(engine, fresh)
    # the watch-apply histogram saw every applied dispatch
    lines = "\n".join(RECORDER.render_lines())
    assert "simon_watch_apply_seconds_count 7" in lines


def test_node_flap_and_modify_accounting():
    """Node MODIFIED (allocatable resize) and DELETED/re-ADDED keep the
    aggregates equal to a fresh bootstrap (bound-pod requests survive the
    flap and fold back in)."""
    from opensim_tpu.server.watch import WatchSupervisor

    policy = {"stale_s": 30.0, "resync_s": 0.0, "reconnects": 1, "backoff_s": 0.0}
    sup = WatchSupervisor(source=None, policy=policy)
    engine = CapacityEngine()
    sup.capacity = engine
    sup.twin.rebase("nodes", [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(2)])
    sup.twin.rebase("pods", [_pod_dict("a", node="n0", rv=1)])
    sup._capacity_rebase()

    bigger = fx.make_fake_node("n0", "32", "64Gi").raw
    bigger["metadata"]["resourceVersion"] = "20"
    sup.dispatch("nodes", "MODIFIED", bigger)
    gone = fx.make_fake_node("n1", "8", "16Gi").raw
    gone["metadata"]["resourceVersion"] = "21"
    sup.dispatch("nodes", "DELETED", gone)
    back = fx.make_fake_node("n1", "8", "16Gi").raw
    back["metadata"]["resourceVersion"] = "22"
    sup.dispatch("nodes", "ADDED", back)

    fresh = CapacityEngine()
    fresh.bootstrap(sup.twin.materialize(), sup.twin.generation)
    _assert_engines_agree(engine, fresh)


def test_ensure_bootstrap_is_keyed_and_event_fed_wins():
    engine = CapacityEngine()
    cluster = _cluster()
    engine.ensure_bootstrap(cluster, "fp1")
    gen = engine.generation
    engine.ensure_bootstrap(cluster, "fp1")  # same key: no-op
    assert engine.generation == gen
    engine.ensure_bootstrap(cluster, "fp2")  # key moved: rebuild
    assert engine.generation == gen + 1
    engine.event_fed = True
    engine.ensure_bootstrap(cluster, "fp3")  # supervisor owns the view
    assert engine.generation == gen + 1


# ---------------------------------------------------------------------------
# headroom: probe == fresh simulate frontier
# ---------------------------------------------------------------------------


def test_headroom_bit_consistent_with_fresh_simulate():
    cluster = _cluster(n_nodes=3, n_pods=4)
    profile = WorkloadProfile("t", "1500m", "3Gi", max_replicas=64)
    engine = CapacityEngine()
    engine.bootstrap(cluster, 1)
    k = headroom_probe(cluster, profile, kmax=engine.fit_upper_bound(profile))

    def fits(n):
        rt = ResourceTypes()
        rt.add(fx.make_fake_deployment("probe", n, profile.cpu, profile.memory))
        return not simulate(cluster, [AppResource("probe", rt)]).unscheduled_pods

    assert k > 0
    assert fits(k), f"probe said {k} replicas fit but simulate disagrees"
    assert not fits(k + 1), f"probe said {k} is the max but {k + 1} also fits"


def test_headroom_zero_when_cluster_is_full():
    cluster = _cluster(n_nodes=1, n_pods=0)
    # fill the single 8-cpu node almost completely
    cluster.pods.append(fx.make_fake_pod("hog", "7500m", "12Gi", fx.with_node_name("n0")))
    profile = WorkloadProfile("big", "2", "4Gi", max_replicas=16)
    engine = CapacityEngine()
    engine.bootstrap(cluster, 1)
    assert engine.fit_upper_bound(profile) == 0
    assert headroom_probe(cluster, profile, kmax=engine.fit_upper_bound(profile)) == 0


def test_headroom_through_warm_base_entry_skips_full_prepare():
    """The server-path probe derives over the cached base entry: after the
    base exists, probing costs delta re-encodes only (the capacity-smoke
    acceptance in miniature) and agrees with the cold probe."""
    from opensim_tpu.engine import prepcache
    from opensim_tpu.utils.trace import PREP_STATS

    cluster = _cluster()
    profile = WorkloadProfile("t", "1", "2Gi", max_replicas=32)
    base_key = "test|base"
    watch = prepcache.watch_snapshot(cluster, [])
    base = prepcache.CacheEntry(base_key, prepare(cluster, []), watch=watch)

    cold = headroom_probe(cluster, profile, kmax=32)
    full_before = PREP_STATS.counts.get("full", 0)
    warm = headroom_probe(cluster, profile, base=base, kmax=32)
    assert warm == cold
    assert PREP_STATS.counts.get("full", 0) == full_before, (
        "warm-base probe paid a full O(cluster) prepare"
    )


def test_headroom_regrows_ladder_when_bound_undershoots():
    """A too-small kmax must not under-report: the probe doubles the ladder
    when everything fits (profile.max_replicas is the only hard ceiling)."""
    cluster = _cluster(n_nodes=2, n_pods=0)
    profile = WorkloadProfile("t", "1", "2Gi", max_replicas=64)
    honest = headroom_probe(cluster, profile, kmax=None)
    lowball = headroom_probe(cluster, profile, kmax=2)
    assert lowball == honest


def test_headroom_profiles_env_parsing(monkeypatch):
    monkeypatch.setenv("OPENSIM_HEADROOM_PROFILES", "web=250m:512Mi,batch=2:4Gi:128")
    profiles = headroom_profiles()
    assert [(p.name, p.max_replicas) for p in profiles] == [("web", 256), ("batch", 128)]
    assert profiles[0].cpu_cores == pytest.approx(0.25)
    for bad in ("oops", "a=1", "a=0:0", "a=1:1Gi:x", "a=1:1Gi,a=2:2Gi", "b ad=1:1Gi"):
        monkeypatch.setenv("OPENSIM_HEADROOM_PROFILES", bad)
        with pytest.raises(ValueError):
            headroom_profiles()


# ---------------------------------------------------------------------------
# report parity: JSON cells byte-equal to the text table cells
# ---------------------------------------------------------------------------


def _text_section(text, title):
    lines = text.splitlines()
    start = lines.index(title) + 1
    out = []
    for line in lines[start:]:
        if not line.strip():
            break
        out.append(line)
    return out


def _rendered(rows):
    import io

    from opensim_tpu.planner.report import _table

    out = io.StringIO()
    _table(rows, out)
    return out.getvalue().splitlines()


def test_report_json_byte_equal_to_text_renderer():
    import io

    from opensim_tpu.planner import report as report_mod

    cluster = _cluster()
    cluster.pods[0].metadata.labels["simon/app-name"] = "demo"
    result = snapshot_result(cluster)
    engine = CapacityEngine()
    engine.bootstrap(cluster, 1)
    report = build_report(engine, cluster, state="test")

    out = io.StringIO()
    report_mod.report_cluster_info(result, [], out)
    report_mod.report_app_info(result, ["demo"], out)
    text = out.getvalue()

    # byte-equality: rendering the JSON rows reproduces the text renderer's
    # table exactly — the two surfaces share ONE computation path
    json_rows = [report["nodeInfo"]["header"]] + report["nodeInfo"]["rows"]
    assert _text_section(text, "Node Info") == _rendered(json_rows)
    app_rows = [report["appInfo"]["header"]] + report["appInfo"]["rows"]
    assert _text_section(text, "App Info") == _rendered(app_rows)
    # the JSON round-trips (the endpoint serializes this dict verbatim)
    assert json.loads(json.dumps(report))["nodeInfo"]["rows"] == report["nodeInfo"]["rows"]


def test_rest_report_endpoint_and_timeline_export():
    from http.server import ThreadingHTTPServer

    from opensim_tpu.server import rest

    server = rest.SimonServer(base_cluster=_cluster())
    try:
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), rest.make_handler(server))
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            with urllib.request.urlopen(f"{base}/api/cluster/report", timeout=30) as resp:
                body = json.load(resp)
            assert body["capacity"]["nodes"] == 4
            assert body["capacity"]["headroom"], "headroom probes missing from the report"
            assert body["nodeInfo"]["rows"], "node table missing"
            # the same numbers the CLI renders (smoke the formatter too)
            rendered = format_top(body)
            assert "Utilization" in rendered and "Headroom" in rendered
            with urllib.request.urlopen(f"{base}/api/debug/capacity", timeout=30) as resp:
                tl = json.load(resp)
            assert tl["samples"], "timeline export is empty"
            assert tl["samples"][-1]["generation"] == body["capacity"]["generation"]
            # headroom=0 skips the probes but still reports utilization
            with urllib.request.urlopen(
                f"{base}/api/cluster/report?headroom=0", timeout=30
            ) as resp:
                assert json.load(resp)["capacity"]["nodes"] == 4
        finally:
            httpd.shutdown()
    finally:
        server.close()


def test_simon_top_cli_one_shot(capsys):
    from http.server import ThreadingHTTPServer

    from opensim_tpu.cli.main import main as cli_main
    from opensim_tpu.server import rest

    server = rest.SimonServer(base_cluster=_cluster())
    try:
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), rest.make_handler(server))
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            assert cli_main(["top", "--url", base]) == 0
            out = capsys.readouterr().out
            assert "Resource" in out and "cpu" in out
            assert cli_main(["top", "--url", base, "--json", "--no-headroom"]) == 0
            body = json.loads(capsys.readouterr().out)
            assert body["capacity"]["nodes"] == 4
        finally:
            httpd.shutdown()
    finally:
        server.close()


def test_report_lists_pods_bound_to_absent_nodes():
    """A pod bound to a node missing from the view (node-flap window) has
    no table row, but the report reconciles: it appears in `orphaned` so
    pods_bound never silently disagrees with the tables."""
    cluster = _cluster(n_nodes=2, n_pods=2)
    cluster.pods.append(
        fx.make_fake_pod("ghost", "1", "1Gi", fx.with_node_name("gone-node"))
    )
    engine = CapacityEngine()
    engine.bootstrap(cluster, 1)
    rep = build_report(engine, cluster, state="test")
    assert rep["capacity"]["pods_bound"] == 3  # the aggregates still count it
    assert rep["orphaned"] == ["default/ghost (on gone-node)"]
    assert "absent nodes" in format_top(rep)


# ---------------------------------------------------------------------------
# timeline ring
# ---------------------------------------------------------------------------


def test_timeline_ring_bounds_and_generation_replacement():
    tl = Timeline(capacity=4)
    for g in range(10):
        tl.append(Sample(generation=g))
    assert len(tl) == 4
    assert [s.generation for s in tl.snapshot()] == [6, 7, 8, 9]
    enriched = Sample(generation=9)
    enriched.headroom = {"small": 3}
    tl.append(enriched)  # same generation: replace, don't append
    assert len(tl) == 4
    assert tl.latest().headroom == {"small": 3}


def test_sampling_is_generation_keyed():
    engine = CapacityEngine()
    cluster = _cluster()
    engine.bootstrap(cluster, 1)
    s1 = engine.sample()
    assert engine.sample() is s1  # memoized: no second fold, no new row
    assert len(engine.timeline) == 1
    engine.bootstrap(cluster, 2)
    s2 = engine.sample()
    assert s2 is not s1 and s2.generation == 2
    assert len(engine.timeline) == 2


# ---------------------------------------------------------------------------
# in-flight batch deadline shedding (NOTES.md rough edge)
# ---------------------------------------------------------------------------


def test_batch_sheds_expired_rider_between_native_scans(monkeypatch):
    from opensim_tpu import native
    from opensim_tpu.engine import reqbatch
    from opensim_tpu.resilience.deadline import Deadline, DeadlineExceeded

    if not native.available():
        pytest.skip("C++ engine unavailable (sequential-scan path only)")
    monkeypatch.setenv("OPENSIM_BATCH_ENGINE", "native")

    cluster = _cluster()
    apps = []
    for name in ("app-a", "app-b", "app-c"):
        rt = ResourceTypes()
        rt.add(fx.make_fake_deployment(name, 2, "250m", "512Mi"))
        apps.append(AppResource(name, rt))
    prep = prepare(cluster, apps)
    assert prep is not None and prep.app_slices is not None

    clock = lambda: 100.0
    live = Deadline(expires_at=10_000.0, budget_s=10_000.0, clock=clock)
    dead = Deadline(expires_at=50.0, budget_s=1.0, clock=clock)
    items = [
        reqbatch.BatchItem(
            cluster=cluster, apps=[apps[i]],
            lo=prep.app_slices[i][0], hi=prep.app_slices[i][1],
            deadline=[live, dead, live][i],
        )
        for i in range(3)
    ]
    results = reqbatch.run_request_batch(prep, items)
    assert isinstance(results[1], DeadlineExceeded)
    assert results[1].phase == "schedule"
    # survivors ran to completion with their pods placed
    for s in (0, 2):
        assert not isinstance(results[s], BaseException)
        placed = sum(len(ns.pods) for ns in results[s].node_status)
        assert placed >= 2  # its own 2 replicas landed (plus base pods)

    # bit-identity of a surviving rider vs a solo run of the same app
    solo = simulate(cluster, [apps[0]])
    def shape(res):
        return sorted(
            (ns.node.metadata.name, len(ns.pods)) for ns in res.node_status
        )
    assert shape(results[0]) == shape(solo)


def test_rest_batch_transports_rider_shed_as_504(monkeypatch):
    """End-to-end through the admission batch executor: a rider whose
    deadline dies in flight resolves as the typed 504, the others as 200s."""
    from opensim_tpu import native

    if not native.available():
        pytest.skip("C++ engine unavailable (sequential-scan path only)")
    monkeypatch.setenv("OPENSIM_BATCH_ENGINE", "native")
    from opensim_tpu.resilience.deadline import Deadline, DeadlineExceeded
    from opensim_tpu.server import admission as admission_mod
    from opensim_tpu.server import rest

    server = rest.SimonServer(base_cluster=_cluster(), admission=False)
    clock = lambda: 100.0
    tickets = []
    for i, name in enumerate(("w-a", "w-b")):
        payload = {"deployments": [fx.make_fake_deployment(name, 2, "250m", "512Mi").raw]}
        tickets.append(
            admission_mod.Ticket(
                kind="deploy", payload=payload,
                deadline=Deadline(expires_at=50.0, budget_s=1.0, clock=clock)
                if i == 1
                else None,
            )
        )
    # mark the dead ticket as NOT pre-expired so it reaches the batch (the
    # in-flight case: alive at admission, dead between scans)
    tickets[1]._expired_at_admission = False
    server._admitted_batch(tickets)
    assert tickets[0].error is None and tickets[0].result is not None
    assert isinstance(tickets[1].error, DeadlineExceeded)
    assert tickets[1].error.phase == "schedule"
    server.close()


# ---------------------------------------------------------------------------
# OSL1101 metric-registry
# ---------------------------------------------------------------------------


def test_osl1101_flags_registration_outside_metrics():
    from opensim_tpu.analysis import lint_source

    src = (
        "from opensim_tpu.obs.metrics import CounterVec, exposition_headers\n"
        "c = CounterVec('simon_x_total', ('a',), help='x')\n"
        "h = exposition_headers('simon_x_total', 'x')\n"
    )
    findings = lint_source(src, path="opensim_tpu/server/somewhere.py",
                           rules=["metric-registry"])
    assert [f.code for f in findings] == ["OSL1101", "OSL1101"]
    # the registry module itself and tests are exempt
    assert not lint_source(src, path="opensim_tpu/obs/metrics.py",
                           rules=["metric-registry"])
    assert not lint_source(src, path="tests/test_x.py", rules=["metric-registry"])


def test_osl1101_allows_registry_helpers():
    from opensim_tpu.analysis import lint_source

    src = (
        "from opensim_tpu.obs.metrics import family_header, make_counter\n"
        "c = make_counter('simon_shed_total', ('reason',))\n"
        "lines = family_header('simon_watch_state')\n"
    )
    assert not lint_source(src, path="opensim_tpu/server/somewhere.py",
                           rules=["metric-registry"])


def test_family_header_rejects_unregistered_family():
    from opensim_tpu.obs.metrics import family_header, make_counter, make_histogram

    with pytest.raises(KeyError):
        family_header("simon_never_registered_total")
    with pytest.raises(KeyError):
        make_counter("simon_never_registered_total", ())
    with pytest.raises(ValueError):
        make_histogram("simon_shed_total", ())  # registered as a counter
