"""Helm renderer coverage for the full-template-language constructs
(VERDICT r4 #4): define/include/template, with, range over lists and maps,
variables, toYaml|nindent pipelines, sprig string/logic functions, subchart
value coalescing with condition gating. The golden expectations are written
to helm v3 semantics (`helm template` output); when a `helm` binary is on
PATH process_chart prefers it, so these goldens keep both paths identical.
Reference behavior: pkg/chart/chart.go:18-41 renders via the real helm v3
library."""

import os
import textwrap

import pytest
import yaml

from opensim_tpu.chart.render import ChartError, process_chart, render_template


def _write_chart(root, files):
    for rel, content in files.items():
        p = os.path.join(root, rel)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "w") as f:
            f.write(textwrap.dedent(content))


def _loop_chart_files():
    """A chart exercising range + include + define + toYaml/nindent + with
    + variables + else branches — the constructs VERDICT r4 flagged."""
    return {
        "Chart.yaml": """\
            apiVersion: v2
            name: loopy
            version: 1.0.0
            appVersion: "2.0"
        """,
        "values.yaml": """\
            tiers:
              - name: web
                replicas: 2
                cpu: 100m
              - name: worker
                replicas: 1
                cpu: 200m
            flags:
              beta: "on"
              alpha: "off"
            common:
              labels:
                team: obs
                dept: infra
            sidecar: {}
        """,
        "templates/_helpers.tpl": """\
            {{- define "loopy.fullname" -}}
            {{ printf "%s-%s" .Release.Name .Chart.Name | trunc 63 | trimSuffix "-" }}
            {{- end -}}
            {{- define "loopy.labels" -}}
            app.kubernetes.io/name: {{ .Chart.Name }}
            app.kubernetes.io/instance: {{ .Release.Name }}
            {{- end }}
        """,
        "templates/deployments.yaml": """\
            {{- $root := . -}}
            {{- range .Values.tiers }}
            ---
            apiVersion: apps/v1
            kind: Deployment
            metadata:
              name: {{ include "loopy.fullname" $root }}-{{ .name }}
              labels:
                {{- include "loopy.labels" $root | nindent 4 }}
                {{- toYaml $root.Values.common.labels | nindent 4 }}
            spec:
              replicas: {{ .replicas }}
              selector:
                matchLabels:
                  app: {{ .name }}
              template:
                metadata:
                  labels:
                    app: {{ .name }}
                spec:
                  containers:
                    - name: {{ .name }}
                      image: registry.example.com/{{ .name }}:latest
                      resources:
                        requests:
                          cpu: {{ .cpu }}
                          memory: 128Mi
            {{- end }}
        """,
        "templates/flags-config.yaml": """\
            apiVersion: v1
            kind: ConfigMap
            metadata:
              name: {{ include "loopy.fullname" . }}-flags
            data:
            {{- range $k, $v := .Values.flags }}
              {{ $k }}: {{ $v | quote }}
            {{- end }}
        """,
        "templates/sidecar.yaml": """\
            {{- with .Values.sidecar.image }}
            apiVersion: v1
            kind: Pod
            metadata:
              name: sidecar
            spec:
              containers:
                - name: sidecar
                  image: {{ . }}
            {{- else }}
            apiVersion: v1
            kind: ConfigMap
            metadata:
              name: {{ include "loopy.fullname" . }}-no-sidecar
            data:
              enabled: "false"
            {{- end }}
        """,
    }


def test_loop_chart_renders_like_helm(tmp_path):
    _write_chart(tmp_path, _loop_chart_files())
    docs = [yaml.safe_load(d) for d in process_chart("rel", str(tmp_path))]
    by_kind_name = {(d["kind"], d["metadata"]["name"]): d for d in docs}

    web = by_kind_name[("Deployment", "rel-loopy-web")]
    worker = by_kind_name[("Deployment", "rel-loopy-worker")]
    assert web["spec"]["replicas"] == 2
    assert worker["spec"]["replicas"] == 1
    assert (
        web["spec"]["template"]["spec"]["containers"][0]["resources"]["requests"]["cpu"]
        == "100m"
    )
    # include + nindent merged the helper labels AND the toYaml block
    assert web["metadata"]["labels"] == {
        "app.kubernetes.io/name": "loopy",
        "app.kubernetes.io/instance": "rel",
        "team": "obs",
        "dept": "infra",
    }
    # map range is key-sorted (Go template map iteration order)
    flags = by_kind_name[("ConfigMap", "rel-loopy-flags")]
    assert flags["data"] == {"alpha": "off", "beta": "on"}
    # with-else: absent sidecar image takes the else branch
    assert ("ConfigMap", "rel-loopy-no-sidecar") in by_kind_name
    assert ("Pod", "sidecar") not in by_kind_name


def test_loop_chart_with_branch_flips(tmp_path):
    files = _loop_chart_files()
    files["values.yaml"] = files["values.yaml"].replace(
        "sidecar: {}",
        'sidecar:\n              image: "registry.example.com/sc:1"',
    )  # replacement indentation matches the dedent-stripped block prefix
    _write_chart(tmp_path, files)
    docs = [yaml.safe_load(d) for d in process_chart("rel", str(tmp_path))]
    kinds = {(d["kind"], d["metadata"]["name"]) for d in docs}
    assert ("Pod", "sidecar") in kinds
    assert ("ConfigMap", "rel-loopy-no-sidecar") not in kinds


def test_subchart_values_coalescing_and_condition(tmp_path):
    _write_chart(
        tmp_path,
        {
            "Chart.yaml": """\
                apiVersion: v2
                name: parent
                version: 1.0.0
                dependencies:
                  - name: childa
                    version: 0.1.0
                    condition: childa.enabled
                  - name: childb
                    version: 0.1.0
                    condition: childb.enabled
            """,
            "values.yaml": """\
                global:
                  registry: registry.example.com
                childa:
                  enabled: true
                  tag: "9.9"
                childb:
                  enabled: false
            """,
            "templates/own.yaml": """\
                apiVersion: v1
                kind: ConfigMap
                metadata:
                  name: {{ .Release.Name }}-parent
                data:
                  registry: {{ .Values.global.registry }}
            """,
            "charts/childa/Chart.yaml": """\
                apiVersion: v2
                name: childa
                version: 0.1.0
            """,
            "charts/childa/values.yaml": """\
                tag: "1.0"
                port: 8080
            """,
            "charts/childa/templates/cm.yaml": """\
                apiVersion: v1
                kind: ConfigMap
                metadata:
                  name: {{ .Release.Name }}-childa
                data:
                  image: {{ .Values.global.registry }}/childa:{{ .Values.tag }}
                  port: {{ .Values.port | quote }}
                  chart: {{ .Chart.Name }}
            """,
            "charts/childb/Chart.yaml": """\
                apiVersion: v2
                name: childb
                version: 0.1.0
            """,
            "charts/childb/templates/cm.yaml": """\
                apiVersion: v1
                kind: ConfigMap
                metadata:
                  name: {{ .Release.Name }}-childb
            """,
        },
    )
    docs = [yaml.safe_load(d) for d in process_chart("r", str(tmp_path))]
    names = {d["metadata"]["name"] for d in docs}
    assert names == {"r-parent", "r-childa"}  # childb gated off by condition
    child = next(d for d in docs if d["metadata"]["name"] == "r-childa")
    # parent override beats subchart default; global flows down; subchart
    # keeps its own Chart metadata and un-overridden values
    assert child["data"]["image"] == "registry.example.com/childa:9.9"
    assert child["data"]["port"] == "8080"  # quote renders the int as "8080"
    assert child["data"]["chart"] == "childa"


def test_parent_helper_visible_in_subchart(tmp_path):
    """helm's template namespace is global: a subchart template can include
    a helper defined by the parent."""
    _write_chart(
        tmp_path,
        {
            "Chart.yaml": "apiVersion: v2\nname: parent\nversion: 1.0.0\n",
            "values.yaml": "",
            "templates/_helpers.tpl": (
                '{{- define "shared.note" -}}from-parent{{- end -}}\n'
            ),
            "charts/kid/Chart.yaml": "apiVersion: v2\nname: kid\nversion: 0.1.0\n",
            "charts/kid/templates/cm.yaml": (
                "apiVersion: v1\nkind: ConfigMap\nmetadata:\n"
                "  name: kid-cm\ndata:\n"
                '  note: {{ include "shared.note" . }}\n'
            ),
        },
    )
    docs = [yaml.safe_load(d) for d in process_chart("r", str(tmp_path))]
    kid = next(d for d in docs if d["metadata"]["name"] == "kid-cm")
    assert kid["data"]["note"] == "from-parent"


def test_falsy_branches_never_evaluate(tmp_path):
    """required/include inside a false if/with body must not run — helm
    only evaluates taken branches."""
    out = render_template(
        '{{- if .Values.on }}{{ required "boom" .Values.missing }}{{ end -}}ok',
        {"Values": {"on": False}},
    )
    assert out == "ok"
    out = render_template(
        "{{- with .Values.absent }}{{ .nope.deep }}{{ end -}}ok",
        {"Values": {}},
    )
    assert out == "ok"


def test_unsupported_constructs_fail_loudly(tmp_path):
    with pytest.raises(ChartError, match="unsupported template construct"):
        render_template('{{ block "b" . }}x{{ end }}', {"Values": {}})
    with pytest.raises(ChartError, match="unsupported template function"):
        render_template("{{ lookup \"v1\" \"Pod\" \"ns\" \"x\" }}", {"Values": {}})
    with pytest.raises(ChartError, match='undefined template'):
        render_template('{{ include "nope" . }}', {"Values": {}})
    with pytest.raises(ChartError, match="boom"):
        render_template('{{ required "boom" .Values.missing }}', {"Values": {}})


def test_sprig_function_semantics():
    ctx = {"Values": {"name": "Simon-Chart-", "n": 3, "items": ["a", "b"]}}
    cases = [
        ('{{ .Values.name | lower | trimSuffix "-" }}', "simon-chart"),
        ('{{ printf "%s/%d" "x" 7 }}', "x/7"),
        ('{{ if eq .Values.n 3 }}y{{ else }}n{{ end }}', "y"),
        ('{{ if and (gt .Values.n 1) (lt .Values.n 5) }}in{{ end }}', "in"),
        ('{{ ternary "a" "b" (eq .Values.n 3) }}', "a"),
        ('{{ join "," .Values.items }}', "a,b"),
        ('{{ add 1 2 3 }}', "6"),
        ('{{ .Values.absent | default "fb" }}', "fb"),
        ('{{ $x := 5 }}{{ $x }}', "5"),
        ('{{ indent 2 "a\nb" }}', "  a\n  b"),
        ('{{ "keep" | upper }}', "KEEP"),
        ('{{ len .Values.items }}', "2"),
        ('{{ index .Values.items 1 }}', "b"),
    ]
    for tpl, want in cases:
        assert render_template(tpl, dict(ctx)) == want, tpl


def test_variable_scoping_go_semantics():
    """`:=` declares block-scoped; `=` assigns the enclosing declaration
    (the range-accumulator idiom); `=` on an undeclared name fails."""
    out = render_template(
        "{{ $found := false }}{{ range .Values.l }}{{ $found = true }}{{ end }}"
        "{{ if $found }}YES{{ else }}NO{{ end }}",
        {"Values": {"l": [1]}},
    )
    assert out == "YES"
    out = render_template(
        '{{ if .Values.a }}A{{ else if .Values.b }}B{{ end }}TAIL',
        {"Values": {"a": False, "b": True}},
    )
    assert out == "BTAIL"  # else-if must not re-render trailing content
    with pytest.raises(ChartError, match="undeclared"):
        render_template("{{ $nope = 1 }}", {"Values": {}})


def test_range_scoped_values_follows_helm_scoping():
    """Inside a {{ range }} or {{ with }} body the dot is the item/pivot
    (Go scoping): `.Values` resolves against it — NOT silently against the
    chart root — and a non-map dot fails loudly, exactly where helm
    refuses the chart. `$.Values` stays the sanctioned route to the root
    (round-5 rough edge in NOTES.md, now closed)."""
    ctx = {"Values": {"l": [1, 2], "maps": [{"Values": {"x": "inner"}}], "tag": "root"}}
    # non-map item: Go template execution errors — we must too
    with pytest.raises(ChartError, match="range/with body"):
        render_template(
            "{{ range .Values.l }}{{ .Values.tag }}{{ end }}", dict(ctx)
        )
    # map item carrying its own Values key: plain map lookup on the item
    assert (
        render_template(
            "{{ range .Values.maps }}{{ .Values.x }}{{ end }}", dict(ctx)
        )
        == "inner"
    )
    # $.Values reaches the root from inside the body (the helm idiom)
    assert (
        render_template(
            "{{ range .Values.l }}{{ $.Values.tag }}{{ end }}", dict(ctx)
        )
        == "rootroot"
    )
    # with rebinds the dot the same way (a with nested in a range behaves
    # identically to a top-level with — one rule, no nesting surprises)
    with pytest.raises(ChartError, match="range/with body"):
        render_template("{{ with .Values.tag }}{{ .Values.tag }}{{ end }}", dict(ctx))
    assert (
        render_template(
            "{{ with .Values.maps }}{{ $.Values.tag }}{{ end }}", dict(ctx)
        )
        == "root"
    )
    # the with ELSE branch keeps the OUTER dot (Go): .Values still roots
    assert (
        render_template(
            "{{ with .Values.absent }}x{{ else }}{{ .Values.tag }}{{ end }}", dict(ctx)
        )
        == "root"
    )
    # outside any range/with, .Values still resolves from the root as before
    assert render_template("{{ .Values.tag }}", dict(ctx)) == "root"


def test_checksum_and_secret_idioms():
    """The checksum/config and Secret-encoding idioms real charts rely on."""
    import hashlib

    ctx = {"Values": {"conf": "a: 1\n", "pw": "s3cret", "m": {"x": 1}}}
    out = render_template('{{ .Values.conf | sha256sum }}', dict(ctx))
    assert out == hashlib.sha256(b"a: 1\n").hexdigest()
    assert render_template('{{ .Values.pw | b64enc }}', dict(ctx)) == "czNjcmV0"
    assert render_template('{{ "czNjcmV0" | b64dec }}', dict(ctx)) == "s3cret"
    assert render_template('{{ if hasKey .Values.m "x" }}y{{ end }}', dict(ctx)) == "y"
    assert render_template('{{ keys .Values.m | sortAlpha | join "," }}', dict(ctx)) == "x"
    assert render_template('{{ range until 3 }}{{ . }}{{ end }}', dict(ctx)) == "012"
    assert render_template('{{ repeat 3 "ab" }}', dict(ctx)) == "ababab"
